"""Linearizability search as a TPU frontier BFS (the north-star kernel).

Replaces Knossos' CPU Wing-Gong/Lowe search (reference binding at
``register.clj:110-112``) for versioned-register histories. The key
insight making the search TPU-shaped: in a history with bounded
concurrency, sort the must-linearize (:ok) ops by invocation; then any
reachable "linearized set" consists of a *forced prefix* plus a bitmask
over a sliding window of at most W undecided ops (W auto-selects 32,
64, or 128 — one, two, or four uint32 words — per history). A search
state packs to

    (depth d, window mask words, info class-count words, model value id)

and a BFS wave is a dense [F, W + C] tensor expansion:
- required candidates: window bit clear ∧ precomputed predecessor-mask
  bits set, model step table-driven (version is *derived*: forced-prefix
  update count + popcount of update bits in the window + the sum of the
  info counts — no per-state version storage), window slide by
  (lo[d+1]-lo[d]) with shifted-out-bits-must-be-set pruning;
- info (indefinite) candidates: a crashed/timed-out update may linearize
  at any point after all :ok ops that returned before its invoke, or
  never (Knossos semantics, checkers/linearizable.py). Interchangeable
  crashed ops (identical effect after dead-value merging) form symmetry
  classes fired in canonical order, so the reachable info states are
  per-class prefix COUNTS — each class owns a fixed bit field in the
  count words, and capacity is the bit budget (NI_MAX words), not one
  bit per op. Firing a class's next member keeps d, increments its
  count, bumps the derived version, and moves the value. Info *reads*
  and info ops invoked after the last required return are dropped up
  front — they can never influence a required op's verdict.
- dedup = multi-key lax.sort + neighbor-compare + scatter compaction.
  Every successor's (d + total info count) is exactly one greater than
  its parent's, so waves are strict BFS levels and no state recurs
  across waves — dedup within a wave is complete dedup.

The wave loop is a lax.while_loop; all shapes are static (F_MAX x (W+I)),
so one compile serves all histories of a bucketed length. On frontier
overflow the kernel freezes the pre-expansion frontier and returns it;
the host driver resumes with a chunked BFS (spill mode) using the same
single-wave expand kernel at full output capacity, so no successor is
ever lost — the TPU path stays *sound and complete* far past F_MAX, and
falls back to the CPU oracle only past an explicit state budget.
"""

from __future__ import annotations

import functools
import logging
import os
from dataclasses import dataclass
from typing import Any, Optional

import numpy as np

logger = logging.getLogger("jepsen_etcd_tpu.ops")

#: set after the fused MXU kernel fails once: a broken toolchain
#: disables the fast path for the rest of the process
_mxu_broken = [False]


def _run_fused(broken: list, name: str, call):
    """Shared fused-kernel dispatch guard: TPU-backend + kill-switch
    env + broken-flag checks, and degrade-don't-crash on Mosaic
    failures (disabling the engine for the process)."""
    import jax
    if jax.default_backend() != "tpu" or broken[0] or \
            os.environ.get("JEPSEN_ETCD_TPU_NO_PALLAS_WGL"):
        return None
    try:
        return call()
    except Exception as e:  # a compile failure must degrade, not crash
        logger.warning("%s kernel unavailable (%r); disabling it for "
                       "this process", name, e)
        broken[0] = True
        return None

from ..checkers.linearizable import Entry, history_entries
from ..runner import telemetry
from .common import UnsupportedValue, ValueIds, as_version

W = 32          # single-word window width (fast path)
W_MAX = 128     # widest window the kernel packs (4 uint32 words).
                # High-overlap histories — long blocked ops (e.g. lock
                # acquires) spanning many completions, or 8n+
                # concurrency — push the undecided window past 32;
                # width auto-selects 32/64/128 per history.
NI_MAX = 4      # count words per state (bit budget for info
                # class fields; 128 bits)
I_TABLE_MAX = 256  # member-table width cap ([R, I, NW] memory)
F_MAX = 512     # frontier capacity per wave (in-kernel mode)
F_MAX_BIG = 4096  # top of the in-kernel retry ladder; past this the
                # host-driven spill BFS takes over
# per-wave cost is dominated by the dedup sort of F*(w+classes)
# candidates, so running above the needed capacity wastes time
# proportionally. The ladder ascends geometrically and the search
# settles at the smallest rung that fits its peak frontier; the
# frontier-resume makes an extra rung nearly free for histories that
# overflow past it. Profiled on the r4 deep 4n/2000 register bench
# (the r2 profile's peak-954 history no longer exists — the r4
# simulator rework changed generated histories): peak 252, so rung 512
# pays double the needed per-wave sort (measured 1.59 s vs 1.00 s at
# the 256 rung). Healthy single-key searches peak in the tens, so the
# ladder bottoms at 32 — the 10k-op headline bench runs 1.8x faster
# there than at 128.
LADDER = [32, 128, 256, F_MAX, 1024, 2048, F_MAX_BIG]
SENTINEL_D = np.int32(2 ** 31 - 1)
SENTINEL_W = np.uint32(0xFFFFFFFF)
SENTINEL_V = np.int32(2 ** 31 - 1)

READ, WRITE, CAS = 0, 1, 2
NO_ASSERT = -(2 ** 30)  # distinct from any real (possibly corrupted) version
NONE_VAL = 0     # value id for "key unset"
WILDCARD = -1    # read asserted nothing

# spill-mode limits: chunk size per expand launch, frontier cap (a
# frontier growing past this is combinatorial blowup — BFS cannot win;
# hand the history to the CPU DFS oracle), and total-state budget
SPILL_CHUNK = 4096
SPILL_FRONTIER_LIMIT = 400_000
SPILL_STATE_BUDGET = 3_000_000
# at/above this many kept info ops the POTENTIAL space is >= 2^24 info
# subsets (symmetry + infeasibility may prune it far smaller, so the
# spill still runs — it can deliver definitive verdicts when the
# reachable space is modest), but its state budget shrinks so hopeless
# cases exit in seconds rather than minutes
SPILL_I_LIMIT = 24
SPILL_STATE_BUDGET_HIGH_I = 1_000_000


def pack_bits(bits: np.ndarray, nw: int) -> np.ndarray:
    """Pack a trailing bool axis of width w = 32*nw into nw
    little-endian uint32 words (new trailing axis replaces it).
    np.packbits + a little-endian uint32 view is ~10x the widen-
    multiply-reduce formulation on the packer's (R, W, W) tensors."""
    w = bits.shape[-1]
    assert w <= 32 * nw
    if w < 32 * nw:
        padded = np.zeros(bits.shape[:-1] + (32 * nw,), dtype=bool)
        padded[..., :w] = bits
        bits = padded
    by = np.packbits(np.ascontiguousarray(bits), axis=-1,
                     bitorder="little")
    return by.view(np.uint32).reshape(bits.shape[:-1] + (nw,)) \
        if by.dtype.byteorder in "|=" and np.little_endian \
        else (by.astype(np.uint32).reshape(bits.shape[:-1] + (nw, 4))
              * (np.uint32(1) << (8 * np.arange(4, dtype=np.uint32)))
              ).sum(-1, dtype=np.uint32)


@dataclass
class Packed:
    """Host-packed tables for one key's history."""

    ok: bool
    reason: str = ""
    blowup: bool = False  # structurally past kernel capacity (count
                          # bits / member tables); DFS gets a trimmed
                          # budget there
    R: int = 0
    I: int = 0
    n_values: int = 0
    w: int = W      # window width (32 / 64 / 128 = 1, 2, 4 words)
    # required tables ([R, W] unless noted; NW = w // 32 little-endian
    # uint32 mask words on the trailing axis)
    shift: Any = None         # [R] int32
    static_ok: Any = None     # [R, W] bool
    f_code: Any = None        # [R, W] int8
    a1: Any = None            # [R, W] int32 (read: rval / write: wval / cas: old)
    a2: Any = None            # [R, W] int32 (cas: new)
    ver: Any = None           # [R, W] int32 (version assertion or -1)
    pred_frame: Any = None    # [R, W, NW] uint32
    upd_mask: Any = None      # [R, NW] uint32
    u_forced: Any = None      # [R] int32
    ceil_frame: Any = None    # [R, W] int32 (version ceiling / CEIL_INF)
    ceil_beyond: Any = None   # [R] int32 (min ceiling past the window)
    # info tables. Canonical-order symmetry means the reachable info
    # states are exactly per-class prefix-count vectors, so the kernel
    # stores counts packed into NI uint32 words (each class owns a
    # fixed bit field, never straddling a word) instead of a
    # one-bit-per-op mask — crashed ops pack while the count bits fit
    # the budget (NI <= 4 words) and members fit the per-depth tables
    # (I <= I_TABLE_MAX).
    C: int = 0                # number of info symmetry classes
    ni: int = 0               # count words per state
    c_f: Any = None           # [C] int8 (WRITE or CAS)
    c_a1: Any = None          # [C] int32 (write val / cas old)
    c_a2: Any = None          # [C] int32 (cas new)
    c_size: Any = None        # [C] int32 (members per class)
    c_off: Any = None         # [C] int32 (first member index, class-major)
    c_word: Any = None        # [C] int32 (count word index)
    c_shift: Any = None       # [C] int32 (bit offset within the word)
    c_mask: Any = None        # [C] uint32 (count field mask)
    i_static_ok: Any = None   # [R, I] bool, class-major member order
    ipred_frame: Any = None   # [R, I, NW] uint32, class-major member order
    # per-op vectors (the compact source the [R, W] frames are gathered
    # from) — retained so device-side frame builders (ops/wgl_mxu.py)
    # can ship ~32 B/op instead of ~512 B/op over the host->device link
    op_a1: Any = None         # [R] int32 (raw, WILDCARD = -1)
    op_a2: Any = None         # [R] int32
    op_ver: Any = None        # [R] int32 (NO_ASSERT sentinel)
    op_f: Any = None          # [R] int8
    op_pred_rank: Any = None  # [R] int32 (# required preds by ret<inv)
    op_ceiling: Any = None    # [R] int32 (version ceiling, INF 2**30)
    inv_rank: Any = None      # [R] int32 (invoke-time rank)
    ret_rank: Any = None      # [R] int32 (return-time rank)
    lo: Any = None            # [R+1] int64 (window base per depth)
    _i_inv_rank: Any = None   # [I] int64 (info invokes on the rank scale;
                              # ensure_frames ingredient)


MUTEX_LOCKED = "locked"


def mutex_adapter(f: str, value):
    """Express mutex ops as CAS register ops: a mutex IS a two-value CAS
    register (acquire = cas(None->locked), release = cas(locked->None),
    no version assertions) — so the lock workloads' Knossos mutex check
    (lock.clj:244) runs on the same TPU kernel as the register."""
    if f == "acquire":
        return "cas", [None, (None, MUTEX_LOCKED)]
    if f == "release":
        return "cas", [None, (MUTEX_LOCKED, None)]
    return None


def pack_mutex_history(history) -> Packed:
    """Pack a mutex (acquire/release) history for the kernel."""
    return pack_register_history(history, adapter=mutex_adapter)


def pack_register_history(history, adapter=None) -> Packed:
    """Build the per-depth tables for the kernel. Returns ok=False with a
    reason when the history needs the CPU path. ``adapter`` (optional)
    maps each entry's (f, value) into register-language (f, value) —
    models expressible as CAS registers (e.g. Mutex) reuse the whole
    kernel this way.

    Adapter-less histories route through the columnar fast path of
    ``pack_register_histories_batched`` (one merged extraction pass,
    numpy for everything downstream — measured ~5x the reference on the
    headline shapes); ``_pack_register_history`` remains the bit-level
    reference the fast path is differentially tested against."""
    if adapter is not None:
        return _pack_reference(history, adapter=adapter)
    return pack_register_histories_batched({0: history})[0]


def _pack_reference(history, adapter=None) -> Packed:
    """The reference packer with the UnsupportedValue guard: the
    semantics ``pack_register_history`` always had, and the delegation
    target for keys the batched fast path can't express."""
    try:
        return _pack_register_history(history, adapter=adapter)
    except UnsupportedValue as e:
        # a value/version whose == semantics the dense id encoding can't
        # carry: sound fallback to the Python oracle
        return Packed(ok=False, reason=f"unsupported value: {e}")


def _pack_register_history(history, adapter) -> Packed:
    entries = history_entries(history)
    if adapter is not None:
        adapted = {}
        for e in entries:
            m = adapter(e.f, e.value)
            if m is None:
                return Packed(ok=False,
                              reason=f"op f={e.f!r} not supported by adapter")
            adapted[e.i] = m

        def fv(e):
            return adapted[e.i]
    else:
        def fv(e):
            return e.f, e.value
    req = sorted([e for e in entries if e.required], key=lambda e: e.invoke)
    R = len(req)
    if R == 0:
        # with no required ops every history linearizes trivially (info
        # ops may simply never have happened)
        return Packed(ok=True, R=0)

    # value id mapping: 0 = None (unset); concrete values from 1, with
    # id-equality iff Python == (ops/common.ValueIds)
    vids = ValueIds()
    val_id = vids.id

    inv = np.array([e.invoke for e in req], dtype=np.int64)
    ret = np.array([e.ret for e in req], dtype=np.int64)
    # build as Python lists (one numpy scalar-assignment per op costs
    # more than the whole list); convert once below
    f_l = [0] * R
    a1_l = [0] * R
    a2_l = [0] * R
    ver_l = [NO_ASSERT] * R
    for i, e in enumerate(req):
        ef, ev = fv(e)
        if ef == "read":
            rv, rval = ev if ev is not None else (None, None)
            if rv is not None:
                ver_l[i] = as_version(rv)
            # A None read value asserts nothing (VersionedRegister.step
            # treats nil op-value as unchecked REGARDLESS of version —
            # an unset-key read [0, None] is constrained via version 0).
            a1_l[i] = WILDCARD if rval is None else val_id(rval)
        elif ef == "write":
            f_l[i] = WRITE
            wv, wval = ev
            if wv is not None:
                ver_l[i] = as_version(wv)
            a1_l[i] = val_id(wval)
        elif ef == "cas":
            f_l[i] = CAS
            cv, (old, new) = ev
            if cv is not None:
                ver_l[i] = as_version(cv)
            a1_l[i] = val_id(old)
            a2_l[i] = val_id(new)
        else:
            return Packed(ok=False, reason=f"op f={ef!r} not supported")
    f = np.array(f_l, dtype=np.int8)
    a1 = np.array(a1_l, dtype=np.int32)
    a2 = np.array(a2_l, dtype=np.int32)
    ver = np.array(ver_l, dtype=np.int32)

    # --- info (indefinite) ops: may linearize any time after their
    # required predecessors, or never. Reads are droppable (invoke value
    # asserts nothing, model unchanged); so are ops whose invoke follows
    # every required return (they could only linearize after d == R).
    sorted_ret = np.sort(ret)
    infos = []
    for e in entries:
        if e.required or fv(e)[0] == "read":
            continue
        npred = int(np.searchsorted(sorted_ret, e.invoke, side="left"))
        if npred >= R:
            continue
        infos.append((e, npred))
    I = len(infos)
    i_f = np.zeros(I, dtype=np.int8)
    i_a1 = np.zeros(I, dtype=np.int32)
    i_a2 = np.zeros(I, dtype=np.int32)
    i_inv = np.zeros(I, dtype=np.int64)
    i_npred = np.zeros(I, dtype=np.int64)
    for j, (e, npred) in enumerate(infos):
        i_inv[j] = e.invoke
        i_npred[j] = npred
        ef, ev = fv(e)
        val = ev if ev is not None else (None, None)
        if val[0] is not None:
            # the kernel's info tables carry no version assertion;
            # honoring one needs the CPU oracle (real histories never
            # produce these — invocations haven't learned a version)
            return Packed(ok=False,
                          reason="info op with version assertion")
        if ef == "write":
            i_f[j] = WRITE
            i_a1[j] = val_id(val[1])
        elif ef == "cas" and isinstance(val[1], (list, tuple)) \
                and len(val[1]) == 2:
            i_f[j] = CAS
            old, new = val[1]
            i_a1[j] = val_id(old)
            i_a2[j] = val_id(new)
        else:
            return Packed(ok=False, reason=f"info op f={ef!r} not supported")

    # --- value-space reductions (ops/common.register_value_sets):
    # merge dead values (producible, never asserted) into one id, and
    # drop info cas ops whose old value has no producer — they can
    # never fire. Crashed writes of distinct never-observed values
    # collapse from 2^I subsets to one symmetry class.
    from .common import register_value_sets
    triples = list(zip(f.tolist(), a1.tolist(), a2.tolist())) + \
        list(zip(i_f.tolist(), i_a1.tolist(), i_a2.tolist()))
    asserted, producible = register_value_sets(triples)
    dead = producible - asserted - {NONE_VAL}
    if len(dead) > 1:
        dead_id = min(dead)
        for i in range(R):
            if f[i] == WRITE and a1[i] in dead:
                a1[i] = dead_id
            elif f[i] == CAS and a2[i] in dead:
                a2[i] = dead_id
        for j in range(I):
            if i_f[j] == WRITE and i_a1[j] in dead:
                i_a1[j] = dead_id
            elif i_f[j] == CAS and i_a2[j] in dead:
                i_a2[j] = dead_id
    keep = [j for j in range(I)
            if not (i_f[j] == CAS and i_a1[j] != NONE_VAL
                    and int(i_a1[j]) not in producible)]
    if len(keep) < I:
        i_f, i_a1, i_a2 = i_f[keep], i_a1[keep], i_a2[keep]
        i_inv, i_npred = i_inv[keep], i_npred[keep]
        I = len(keep)
    # symmetry classes: info ops with identical (f, a1, a2) are
    # interchangeable, and a lower-(npred, invoke) member is enabled
    # whenever a higher one is, so any linearization can be rewritten
    # to fire each class in canonical order. The reachable info states
    # are therefore exactly per-class prefix COUNTS — the kernel packs
    # them into fixed bit fields (never straddling a word), so capacity
    # is the bit budget (NI_MAX words), not one bit per op.
    order = sorted(range(I), key=lambda j: ((int(i_f[j]), int(i_a1[j]),
                                             int(i_a2[j])),
                                            (int(i_npred[j]),
                                             int(i_inv[j]), j)))
    i_f, i_a1, i_a2 = i_f[order], i_a1[order], i_a2[order]
    i_inv, i_npred = i_inv[order], i_npred[order]
    class_runs: list = []  # (start, size)
    for j in range(I):
        key_j = (int(i_f[j]), int(i_a1[j]), int(i_a2[j]))
        if class_runs and class_runs[-1][0] == key_j:
            class_runs[-1][2] += 1
        else:
            class_runs.append([key_j, j, 1])
    C = len(class_runs)
    c_f = np.array([k[0] for k, _, _ in class_runs], dtype=np.int8)
    c_a1 = np.array([k[1] for k, _, _ in class_runs], dtype=np.int32)
    c_a2 = np.array([k[2] for k, _, _ in class_runs], dtype=np.int32)
    c_off = np.array([off for _, off, _ in class_runs], dtype=np.int32)
    c_size = np.array([sz for _, _, sz in class_runs], dtype=np.int32)
    # bit layout: each class's count field is ceil(log2(size+1)) bits,
    # placed in the first word with room (fields never cross words)
    c_word = np.zeros(C, dtype=np.int32)
    c_shift = np.zeros(C, dtype=np.int32)
    c_mask = np.zeros(C, dtype=np.uint32)
    word, used = 0, 0
    for ci in range(C):
        bits = max(1, int(c_size[ci]).bit_length())
        if used + bits > 32:
            word, used = word + 1, 0
        c_word[ci] = word
        c_shift[ci] = used
        c_mask[ci] = (1 << bits) - 1
        used += bits
    ni = (word + 1) if C else 0
    if ni > NI_MAX:
        return Packed(ok=False, blowup=True,
                      reason=f"{I} info updates in {C} classes need "
                             f"{ni} count words > {NI_MAX}")
    if I > I_TABLE_MAX:
        return Packed(ok=False, blowup=True,
                      reason=f"{I} info updates > member-table cap "
                             f"{I_TABLE_MAX}")

    pred = np.searchsorted(sorted_ret, inv, side="left")  # ret[j] < inv[i]
    cap = np.searchsorted(inv, ret, side="left") - 1      # inv[j] < ret[i], j != i

    # lo[d] = first rank that can still be absent from a depth-d prefix
    # = length of the longest prefix with cap < d, i.e. the insertion
    # point of d in the (non-decreasing) running prefix max of cap
    lo = np.searchsorted(np.maximum.accumulate(cap), np.arange(R + 1),
                         side="left").astype(np.int64) if R \
        else np.zeros(1, dtype=np.int64)
    # feasibility: window must hold all set bits and all enabled
    # candidates. Histories needing >32 bits get the wider multi-word
    # kernel variants (W=64/128); >128 is beyond the kernel.
    width_bits = np.max(np.arange(R + 1) - lo) if R else 0
    first_lo = lo[np.minimum(pred, R)]
    width_cand = np.max(np.arange(R) - first_lo) + 1 if R else 0
    width = max(width_bits, width_cand)
    if width > W_MAX:
        return Packed(ok=False,
                      reason=f"window {width} > {W_MAX} "
                             f"(concurrency too high for kernel)")
    w = next(c for c in (W, 64, W_MAX) if width <= c)
    nw = w // 32

    is_upd = (f == WRITE) | (f == CAS)
    cum_upd = np.concatenate([[0], np.cumsum(is_upd)])
    u_forced = cum_upd[lo[:R]].astype(np.int32)

    # version ceilings (the native oracle's dead-state prune, on
    # device): op e with a version assertion can only fire while the
    # register version is <= its ceiling (read: ver, update: ver-1);
    # version never decreases, so a state whose version exceeds the
    # min ceiling among unlinearized required ops is dead. The static
    # suffix min covers ranks beyond the window; the per-window-lane
    # table is a frame (lazy).
    CEIL_INF = np.int32(2 ** 30)
    ceiling = np.where(ver == NO_ASSERT, CEIL_INF,
                       np.where(f == READ, ver, ver - 1)).astype(np.int32)
    suffix_min = np.full(R + 1, CEIL_INF, dtype=np.int32)
    suffix_min[:R] = np.minimum.accumulate(ceiling[::-1])[::-1]
    ceil_beyond = suffix_min[np.minimum(lo[:R] + w, R)]       # [R]

    # rank-compress the int64 invoke/return times jointly: pairwise
    # comparisons (all the frames need) are order-preserved, and ranks
    # fit int32 for device-side frame building
    all_times = np.concatenate([inv, ret])
    order = np.argsort(all_times, kind="stable")
    ranks = np.empty(2 * R, dtype=np.int32)
    ranks[order] = np.arange(2 * R, dtype=np.int32)

    p = Packed(
        ok=True, R=R, I=I, n_values=len(vids.rev), w=w,
        shift=(lo[1:] - lo[:-1]).astype(np.int32),
        u_forced=u_forced, ceil_beyond=ceil_beyond,
        C=C, ni=ni, c_f=c_f, c_a1=c_a1, c_a2=c_a2, c_size=c_size,
        c_off=c_off, c_word=c_word, c_shift=c_shift, c_mask=c_mask,
        op_a1=a1, op_a2=a2, op_ver=ver, op_f=f,
        op_pred_rank=pred.astype(np.int32), op_ceiling=ceiling,
        inv_rank=ranks[:R], ret_rank=ranks[R:], lo=lo,
    )
    # frame ingredients for ensure_frames (the [R, W(, W|I)] frames are
    # LAZY: the fused device path rebuilds them on-chip from the per-op
    # vectors, so materializing ~R*W^2 host bits up front would charge
    # every production check for tables only the jnp path reads)
    p._i_inv_rank = (np.searchsorted(
        np.sort(all_times), i_inv, side="left").astype(np.int64)
        if I else np.zeros(0, dtype=np.int64))
    return p


def ensure_frames(p: Packed) -> None:
    """Materialize the [R, W] / [R, W, W] / [R, I] frame tables on the
    Packed (idempotent). Consumers: pad_tables (the jnp kernel path)
    and wgl_mxu.pack_tables (the host reference for the device-builder
    contract test)."""
    if p.static_ok is not None or not p.ok or p.R == 0:
        return
    R, w, I = p.R, p.w, p.I
    nw = w // 32
    lo = p.lo
    pred = p.op_pred_rank.astype(np.int64)
    inv = p.inv_rank.astype(np.int64)
    ret = p.ret_rank.astype(np.int64)
    f = p.op_f
    d_idx = np.arange(R)[:, None]                       # [R, 1]
    b_idx = np.arange(w)[None, :]                       # [1, W]
    idx = np.minimum(lo[:R][:, None] + b_idx, R - 1)    # [R, W] clamped
    in_range = (lo[:R][:, None] + b_idx) < R
    p.static_ok = in_range & (pred[idx] <= d_idx)

    # predecessor bits within the frame: bit c <-> rank lo[d]+c. Masks
    # pack into nw little-endian uint32 words (trailing axis) — TPUs
    # have no native 64-bit ints, and W=128 exceeds uint64 anyway.
    ret_frame = ret[idx]                                      # [R, W]
    inv_cand = inv[idx]                                       # [R, W]
    is_pred = (ret_frame[:, None, :] < inv_cand[:, :, None])  # [R, W, W]
    in_range_c = in_range[:, None, :]                         # [R, 1, W]
    p.pred_frame = pack_bits(is_pred & in_range_c, nw)

    is_upd = (f == WRITE) | (f == CAS)
    p.upd_mask = pack_bits(is_upd[idx] & in_range, nw)

    CEIL_INF = np.int32(2 ** 30)
    p.ceil_frame = np.where(in_range, p.op_ceiling[idx], CEIL_INF)
    p.f_code = f[idx].astype(np.int8)
    p.a1 = p.op_a1[idx]
    p.a2 = p.op_a2[idx]
    p.ver = p.op_ver[idx]

    # info predecessor tables: info j enabled at depth d iff every
    # required op with ret < inv_j is linearized — ranks < lo[d] are
    # forced; ranks in [lo[d], lo[d]+W) must have their window bit set;
    # any pred rank >= lo[d]+W cannot be linearized yet -> disabled.
    if I:
        i_inv = p._i_inv_rank
        pred_in_win = in_range[:, :, None] & \
            (ret_frame[:, :, None] < i_inv[None, None, :])    # [R, W, I]
        p.ipred_frame = pack_bits(
            np.swapaxes(pred_in_win, 1, 2), nw)               # [R, I, NW]
        pf = (ret[:, None] < i_inv[None, :])                  # [R, I]
        cum_pf = np.concatenate([np.zeros((1, I), dtype=np.int64),
                                 np.cumsum(pf, axis=0)])      # [R+1, I]
        hi = np.minimum(lo[:R] + w, R)                        # [R]
        p.i_static_ok = cum_pf[hi] == cum_pf[R][None, :]      # [R, I]
    else:
        p.ipred_frame = np.zeros((R, 0, nw), dtype=np.uint32)
        p.i_static_ok = np.zeros((R, 0), dtype=bool)


# ---------------------------------------------------------------------------
# Packed wire format (pack-once, serialize-packed)
#
# The checker-service protocol (runner/checker_service.py) ships
# host-packed histories between processes: the runner packs ONCE, the
# service deserializes and dispatches. Only the compact per-op vectors
# travel (~32 B/op) — the [R, W(, W|I)] frame tables are exactly the
# lazy fields ensure_frames rebuilds deterministically from them, so
# re-deriving on the receiving side is both cheaper than shipping
# (~512 B/op) and bit-identical (pinned by tests/test_checker_service).

#: the lazy frame tables ensure_frames materializes — never serialized
FRAME_FIELDS = frozenset((
    "static_ok", "f_code", "a1", "a2", "ver", "pred_frame", "upd_mask",
    "ceil_frame", "i_static_ok", "ipred_frame",
))


def serialize_packed(p: Packed) -> bytes:
    """One Packed -> bytes: a JSON header (scalars + array manifest)
    followed by the raw C-contiguous array payloads, no pickle."""
    import dataclasses
    import json as _json
    scalars: dict = {}
    arrays: list = []
    blobs: list = []
    for f in dataclasses.fields(Packed):
        if f.name in FRAME_FIELDS:
            continue
        v = getattr(p, f.name)
        if v is None or isinstance(v, (bool, int, str)):
            scalars[f.name] = v
        else:
            a = np.ascontiguousarray(v)
            arrays.append([f.name, a.dtype.str, list(a.shape)])
            blobs.append(a.tobytes())
    head = _json.dumps({"v": 1, "scalars": scalars,
                        "arrays": arrays}).encode()
    return head + b"\n" + b"".join(blobs)


def deserialize_packed(buf: bytes) -> Packed:
    """Inverse of serialize_packed. The frame tables stay lazy; any
    consumer that needs them calls ensure_frames (pad_tables does).

    The manifest is validated against the actual payload BEFORE any
    array is materialized: field names must be real (non-frame) Packed
    fields — a hostile header cannot setattr arbitrary attributes —
    shapes must be non-negative, and the manifest's summed byte length
    must equal the payload exactly. The service answers a ValueError
    from here with a structured error reply and keeps the connection
    (a malformed request is not a dead peer)."""
    import dataclasses
    import json as _json
    nl = buf.index(b"\n")
    head = _json.loads(buf[:nl].decode())
    if head.get("v") != 1:
        raise ValueError(f"unknown Packed wire version {head.get('v')}")
    wire_fields = {f.name for f in dataclasses.fields(Packed)} \
        - FRAME_FIELDS
    scalars = head.get("scalars")
    arrays = head.get("arrays")
    if not isinstance(scalars, dict) or not isinstance(arrays, list):
        raise ValueError("malformed Packed header")
    off = nl + 1
    manifest = []
    for entry in arrays:
        name, dtype, shape = entry
        if name not in wire_fields:
            raise ValueError(f"unknown Packed field {name!r}")
        if not isinstance(shape, list) \
                or any(not isinstance(d, int) or d < 0 for d in shape):
            raise ValueError(f"bad shape for {name!r}: {shape!r}")
        dt = np.dtype(dtype)
        n = int(np.prod(shape, dtype=np.int64)) if shape else 1
        manifest.append((name, dt, shape, n, off))
        off += n * dt.itemsize
    if off != len(buf):
        raise ValueError(
            f"Packed payload length mismatch: manifest claims "
            f"{off - nl - 1} bytes, got {len(buf) - nl - 1}")
    p = Packed(ok=False)
    for name, v in scalars.items():
        if name not in wire_fields:
            raise ValueError(f"unknown Packed field {name!r}")
        setattr(p, name, v)
    for name, dt, shape, n, at in manifest:
        a = np.frombuffer(buf, dtype=dt, count=n,
                          offset=at).reshape(shape).copy()
        setattr(p, name, a)
    return p


# ---------------------------------------------------------------------------
# batched SoA packing (the key-DP axis' host-side hot path)


class _Delegate(Exception):
    """A key's history needs semantics the columnar fast path doesn't
    carry; re-pack it through the per-key reference packer."""


def _classify_info(pos, f, ev, ilists):
    """Classify one indefinite update (info completion or still-open
    invoke; value is the INVOCATION's, per history_entries) into the
    columnar info lists. Raises _Delegate whenever only the reference's
    handling applies — its ok=False rejections (version-asserting
    infos, unsupported fs, bad cas shapes) and its own exceptions on
    malformed values — so delegation reproduces them exactly."""
    if ev is None:
        va = payload = None
    elif (type(ev) is tuple or type(ev) is list) and len(ev) == 2:
        va, payload = ev
    else:
        raise _Delegate
    if va is not None:
        raise _Delegate           # "info op with version assertion"
    if f == "write":
        fc = WRITE
        if payload is None:
            t1 = x1 = 0
        elif type(payload) is int:
            t1, x1 = 2, payload
        else:
            raise _Delegate       # non-int payload: interning needs ==
        t2 = x2 = 0
    elif f == "cas":
        if not (isinstance(payload, (list, tuple)) and len(payload) == 2):
            raise _Delegate       # "info op f='cas' not supported"
        fc = CAS
        old, new = payload
        if old is None:
            t1 = x1 = 0
        elif type(old) is int:
            t1, x1 = 2, old
        else:
            raise _Delegate
        if new is None:
            t2 = x2 = 0
        elif type(new) is int:
            t2, x2 = 2, new
        else:
            raise _Delegate
    else:
        raise _Delegate           # "info op f=... not supported"
    ipos_l, if_l, i1t_l, i1v_l, i2t_l, i2v_l = ilists
    ipos_l.append(pos)
    if_l.append(fc)
    i1t_l.append(t1)
    i1v_l.append(x1)
    i2t_l.append(t2)
    i2v_l.append(x2)


def _rows_from_ops(ops):
    """Parallel (procs, types, fs, vals) row lists from dict ops — the
    dict front-end of _extract_key_columns. One pass of dict lookups,
    the same count the fused loop used to pay inline."""
    procs, types, fs, vals = [], [], [], []
    for op in ops:
        procs.append(op.get("process"))
        types.append(op.get("type"))
        fs.append(op.get("f"))
        vals.append(op.get("value"))
    return procs, types, fs, vals


def _rows_from_columns(cols):
    """Parallel row lists straight from SoA columns (core/history.py
    OpColumns) — zero per-op dict access: type/f decode through their
    intern tables, non-int processes decode from proc_table, and the
    values list is shared by reference (per-key sub-columns already
    hold unwrapped payloads, exactly what the dict subhistory path
    feeds the extraction loop)."""
    from ..core.history import TYPE_NAMES
    types = [TYPE_NAMES[c] for c in cols.type_code.tolist()]
    pt = cols.proc_table
    procs = [p if p >= 0 else pt[-1 - p] for p in cols.proc.tolist()]
    ft = cols.f_table
    fs = [ft[c] for c in cols.f_code.tolist()]
    return procs, types, fs, cols.values


class _KeyExtract:
    """Resumable form of the merged extraction pass over ONE key's raw
    ops: invoke/completion pairing (history_entries), required-op
    classification, and register-language field extraction fused into a
    single loop, feedable in row chunks (the streaming packer's
    per-chunk drain) or in one shot (:func:`_extract_key_columns`).

    The state carried between ``feed`` calls — open invocations (each
    held as ``(pos, f, value)`` so the completion may land in a later
    chunk), the running int-process row position, and the required-op
    count — is exactly the loop state of the one-shot pass, so chunked
    feeding is bit-identical to one pass over the concatenation.
    ``finish`` runs the history-end sweep (still-open ops become
    indefinite updates) and must be called exactly once."""

    __slots__ = ("lists", "ilists", "open_by", "pos", "n_req")

    def __init__(self, lists=None, ilists=None):
        self.lists = lists if lists is not None \
            else tuple([] for _ in range(8))
        self.ilists = ilists if ilists is not None \
            else tuple([] for _ in range(6))
        self.open_by: dict = {}
        self.pos = 0
        self.n_req = 0

    def feed(self, rows) -> None:
        """Consume one (procs, types, fs, vals) row-chunk. Raises
        _Delegate / TypeError / ValueError exactly where the one-shot
        pass would; the caller owns rollback of shared lists."""
        procs, types, fs, vals = rows
        inv_l, ret_l, f_l, ver_l, v1t_l, v1v_l, v2t_l, v2v_l = self.lists
        ilists = self.ilists
        open_by = self.open_by
        pos = self.pos
        n_req = self.n_req
        lo_ver, hi_ver = -(2 ** 29), 2 ** 29
        try:
            for i, proc in enumerate(procs):
                if not isinstance(proc, int):
                    continue
                pos += 1
                t = types[i]
                if t == "invoke":
                    open_by[proc] = (pos, fs[i], vals[i])
                    continue
                got = open_by.pop(proc, None)
                if got is None or t == "fail":
                    continue
                if t == "ok":
                    f = got[1]
                    ev = vals[i]
                    # 2-unpacks mirror the reference exactly (it
                    # unpacks any 2-iterable); failures surface as
                    # TypeError/ValueError, which the caller converts
                    # to delegation — and the reference then re-raises
                    # the identical error
                    if f == "read":
                        fc = READ
                        if ev is None:
                            rv = rval = None
                        else:
                            rv, rval = ev
                        if rval is None:
                            t1, x1 = 0, 0  # wildcard: asserts nothing
                        elif type(rval) is int:
                            t1, x1 = 2, rval
                        else:
                            raise _Delegate
                        t2 = x2 = 0
                    elif f == "write":
                        fc = WRITE
                        rv, wval = ev
                        if wval is None:
                            t1, x1 = 1, 0
                        elif type(wval) is int:
                            t1, x1 = 2, wval
                        else:
                            raise _Delegate
                        t2 = x2 = 0
                    elif f == "cas":
                        fc = CAS
                        rv, (old, new) = ev
                        if old is None:
                            t1, x1 = 1, 0
                        elif type(old) is int:
                            t1, x1 = 2, old
                        else:
                            raise _Delegate
                        if new is None:
                            t2, x2 = 1, 0
                        elif type(new) is int:
                            t2, x2 = 2, new
                        else:
                            raise _Delegate
                    else:
                        raise _Delegate  # unsupported f: per-key msg
                    if rv is None:
                        ver = NO_ASSERT
                    elif type(rv) is int and lo_ver < rv < hi_ver:
                        ver = rv
                    else:
                        raise _Delegate  # as_version semantics / range
                    inv_l.append(got[0])
                    ret_l.append(pos)
                    f_l.append(fc)
                    ver_l.append(ver)
                    v1t_l.append(t1)
                    v1v_l.append(x1)
                    v2t_l.append(t2)
                    v2v_l.append(x2)
                    n_req += 1
                elif t == "info":
                    f = got[1]
                    if f != "read":    # indefinite update
                        _classify_info(got[0], f, got[2], ilists)
                    # info reads are dropped up front (assert nothing)
                else:
                    open_by[proc] = got  # ad-hoc type: leave op open
        finally:
            self.pos = pos
            self.n_req = n_req

    def finish(self) -> int:
        """History end: ops still open are indefinite, like :info
        completions. Returns the key's required-op count."""
        for ppos, f, val in self.open_by.values():
            if f != "read":
                _classify_info(ppos, f, val, self.ilists)
        return self.n_req


def _extract_key_columns(rows, lists, ilists):
    """ONE merged pass over a key's raw ops — the one-shot form of
    :class:`_KeyExtract`. ``rows`` is the (procs, types, fs, vals)
    parallel-list form of the ops — built by _rows_from_ops (dict
    histories) or _rows_from_columns (SoA-backed histories, no dict
    round-trip).
    Appends required-op columns to the shared flat ``lists`` (and
    indefinite updates to ``ilists``); returns the number of required
    ops appended. Raises _Delegate on anything the vectorized phase
    can't express bit-identically: non-int payload values (interning
    needs Python == semantics), non-int or out-of-range version
    assertions, unsupported fs, and malformed value shapes."""
    st = _KeyExtract(lists, ilists)
    st.feed(rows)
    return st.finish()


def _intern_values_batched(key_of, ridx, v1t, v1v, v2t, v2v,
                           ikey, i1t, i1v, i2t, i2v, n_keys):
    """Vectorized per-key value-id interning with ValueIds' exact
    semantics restricted to int payloads: id 0 is None, concrete values
    get dense ids in FIRST-APPEARANCE order of the per-key interning
    stream — required ops by invoke, then indefinite updates in entry
    order, a1 before a2 within an op. Returns (a1, a2, ia1, ia2,
    n_values) with WILDCARD = -1 reads preserved."""
    N = len(key_of)
    m1 = v1t == 2
    m2 = v2t == 2
    j1 = i1t == 2
    j2 = i2t == 2
    n1 = int(np.count_nonzero(m1))
    n2 = int(np.count_nonzero(m2))
    n3 = int(np.count_nonzero(j1))
    iidx = np.arange(len(ikey), dtype=np.int64)
    ibase = np.int64(2 * N)       # infos intern after every required op
    s_key = np.concatenate([key_of[m1], key_of[m2], ikey[j1], ikey[j2]])
    s_val = np.concatenate([v1v[m1], v2v[m2], i1v[j1], i2v[j2]])
    s_seq = np.concatenate([2 * ridx[m1], 2 * ridx[m2] + 1,
                            ibase + 2 * iidx[j1],
                            ibase + 2 * iidx[j2] + 1])
    ids = np.empty(len(s_key), dtype=np.int64)
    if len(s_key):
        order = np.lexsort((s_seq, s_val, s_key))
        sk, sv = s_key[order], s_val[order]
        newg = np.ones(len(sk), dtype=bool)
        newg[1:] = (sk[1:] != sk[:-1]) | (sv[1:] != sv[:-1])
        heads = np.flatnonzero(newg)
        hk, hs = sk[heads], s_seq[order][heads]
        horder = np.lexsort((hs, hk))          # first-appearance order
        hk_s = hk[horder]
        firstk = np.ones(len(heads), dtype=bool)
        firstk[1:] = hk_s[1:] != hk_s[:-1]
        hpos = np.arange(len(heads), dtype=np.int64)
        kstart = np.maximum.accumulate(np.where(firstk, hpos, 0))
        gid = np.empty(len(heads), dtype=np.int64)
        gid[horder] = hpos - kstart + 1
        ids[order] = gid[np.cumsum(newg) - 1]
        n_values = np.bincount(hk, minlength=n_keys) + 1
    else:
        n_values = np.ones(n_keys, dtype=np.int64)
    a1 = np.where(v1t == 0, np.int64(WILDCARD), np.int64(0))
    a1[m1] = ids[:n1]
    a2 = np.zeros(len(v2t), dtype=np.int64)
    a2[m2] = ids[n1:n1 + n2]
    ia1 = np.zeros(len(ikey), dtype=np.int64)
    ia1[j1] = ids[n1 + n2:n1 + n2 + n3]
    ia2 = np.zeros(len(ikey), dtype=np.int64)
    ia2[j2] = ids[n1 + n2 + n3:]
    return a1, a2, ia1, ia2, n_values


def _merge_dead_values_batched(key_of, fcode, a1, a2, n_values):
    """Vectorized dead-value merge (register_value_sets semantics over
    required AND indefinite ops): per key, producible-but-never-
    asserted ids collapse to the smallest such id when there is more
    than one. Mutates a1/a2 in place; returns (vbase, prod_mask) — the
    per-key id-space offsets and the PRE-merge producible mask the
    reference uses for its never-fires info-cas drop."""
    n_keys = len(n_values)
    vbase = np.zeros(n_keys, dtype=np.int64)
    np.cumsum(n_values[:-1], out=vbase[1:])
    V = int(vbase[-1] + n_values[-1]) if n_keys else 0
    isread = fcode == READ
    iswrite = fcode == WRITE
    iscas = fcode == CAS
    kb = vbase[key_of]
    ga1 = a1 + kb
    ga2 = a2 + kb
    assert_mask = np.zeros(V, dtype=bool)
    assert_mask[ga1[(isread & (a1 != WILDCARD)) | iscas]] = True
    prod_mask = np.zeros(V, dtype=bool)
    prod_mask[ga1[iswrite]] = True
    prod_mask[ga2[iscas]] = True
    dead = prod_mask & ~assert_mask
    dead[vbase] = False                       # id 0 (None) never merges
    vkey = np.repeat(np.arange(n_keys), n_values)
    dead_counts = np.bincount(vkey[dead], minlength=n_keys)
    if not np.any(dead_counts > 1):
        return vbase, prod_mask
    didx = np.flatnonzero(dead)
    dk = vkey[didx]
    firstd = np.ones(len(didx), dtype=bool)
    firstd[1:] = dk[1:] != dk[:-1]
    dead_min = np.zeros(n_keys, dtype=np.int64)
    dead_min[dk[firstd]] = didx[firstd] - vbase[dk[firstd]]
    rem = (dead_counts > 1)[key_of]
    hit1 = rem & iswrite & dead[np.where(iswrite, ga1, 0)]
    a1[hit1] = dead_min[key_of[hit1]]
    hit2 = rem & iscas & dead[ga2]
    a2[hit2] = dead_min[key_of[hit2]]
    return vbase, prod_mask


def pack_register_histories_batched(subhistories: dict,
                                    adapter=None) -> dict:
    """Batched structure-of-arrays form of ``pack_register_history``
    over a keyed dict of subhistories — the host side of the key-DP
    axis. One merged Python pass per op does pairing + classification +
    field extraction; everything downstream (value-id interning, dead-
    value merge, predecessor/window geometry, version ceilings, time
    rank compression) runs as single numpy calls vectorized ACROSS all
    keys, using per-key segment offsets so every per-key searchsorted /
    prefix-scan becomes one global operation on globally-sorted data.

    Per-key results are bit-identical to ``pack_register_history``
    (differentially tested in tests/test_wgl_batch_pack.py), including
    indefinite updates (info/crashed writes and cas, their symmetry
    classes and count-word layout). Keys the columnar path can't
    express (adapters, non-int payload values, non-int/out-of-range
    version assertions, malformed shapes, version-asserting infos)
    silently delegate to the per-key packer, so only the constant
    factor ever changes. Returns ``{key: Packed}``."""
    from ..core.history import History

    out: dict = {}
    fast_keys: list = []
    seg_R_l: list = []
    seg_I_l: list = []
    lists = tuple([] for _ in range(8))
    ilists = tuple([] for _ in range(6))
    alllists = lists + ilists
    (inv_l, ret_l, f_l, ver_l, v1t_l, v1v_l, v2t_l, v2v_l) = lists
    (ipos_l, if_l, i1t_l, i1v_l, i2t_l, i2v_l) = ilists
    for key, h in subhistories.items():
        if adapter is not None:
            out[key] = _pack_reference(h, adapter=adapter)
            continue
        # column-backed per-key histories (Independent's split of a
        # recorded run) extract straight from the SoA arrays — the
        # dict op stream is never materialized on this path
        cols = getattr(h, "columns", None) if isinstance(h, History) \
            else None
        if cols is not None:
            rows = _rows_from_columns(cols)
        else:
            # graftlint: ignore[COL001] explicit non-columnar delegation: raw op lists land here
            rows = _rows_from_ops(h.ops if isinstance(h, History) else h)
        marks = [len(c) for c in alllists]
        imark = len(ipos_l)
        try:
            n_req = _extract_key_columns(rows, lists, ilists)
        except (_Delegate, TypeError, ValueError):
            # TypeError/ValueError: a value didn't 2-unpack the way the
            # op's ``f`` demands — the reference raises the identical
            # error (or returns its Packed) for the same history
            for c, m in zip(alllists, marks):
                del c[m:]
            out[key] = _pack_reference(h)
            continue
        if n_req == 0:
            # with no required ops every history linearizes trivially,
            # before any indefinite op is even considered
            for c, m in zip(alllists, marks):
                del c[m:]
            out[key] = Packed(ok=True, R=0)
            continue
        fast_keys.append(key)
        seg_R_l.append(n_req)
        seg_I_l.append(len(ipos_l) - imark)
    if not fast_keys:
        return out
    return _pack_batched_tail(fast_keys, seg_R_l, seg_I_l,
                              lists, ilists, out)


def _pack_batched_tail(fast_keys, seg_R_l, seg_I_l, lists, ilists,
                       out: dict) -> dict:
    """The vectorized phase of :func:`pack_register_histories_batched`:
    given the flat per-key-contiguous extraction lists (keys in the
    order of ``fast_keys``, required ops sorted by invoke within each
    key's segment), run interning, dead-value merge, window geometry,
    ceilings, rank compression and per-key Packed assembly. Shared by
    the one-shot batched packer and the streaming packer
    (:class:`PackStream`), which must feed IDENTICAL flat lists for the
    same history — that is the whole bit-identity argument."""
    (inv_l, ret_l, f_l, ver_l, v1t_l, v1v_l, v2t_l, v2v_l) = lists
    (ipos_l, if_l, i1t_l, i1v_l, i2t_l, i2v_l) = ilists

    Kf = len(fast_keys)
    seg_R = np.array(seg_R_l, dtype=np.int64)
    starts = np.zeros(Kf, dtype=np.int64)
    np.cumsum(seg_R[:-1], out=starts[1:])
    N = int(starts[-1] + seg_R[-1])
    key_of = np.repeat(np.arange(Kf), seg_R)
    ridx = np.arange(N, dtype=np.int64)
    i_within = ridx - starts[key_of]

    inv64 = np.array(inv_l, dtype=np.int64)
    ret64 = np.array(ret_l, dtype=np.int64)
    fcode = np.array(f_l, dtype=np.int8)
    ver = np.array(ver_l, dtype=np.int32)
    v1t = np.array(v1t_l, dtype=np.int8)
    v1v = np.array(v1v_l, dtype=np.int64)
    v2t = np.array(v2t_l, dtype=np.int8)
    v2v = np.array(v2v_l, dtype=np.int64)

    # required ops sort by invoke within each key (stable; invokes are
    # distinct per key, so this matches the per-key sorted())
    perm = np.lexsort((inv64, key_of))
    inv64, ret64 = inv64[perm], ret64[perm]
    fcode, ver = fcode[perm], ver[perm]
    v1t, v1v, v2t, v2v = v1t[perm], v1v[perm], v2t[perm], v2v[perm]

    # per-key searchsorted via segment time offsets: key k's times move
    # to a disjoint band k * T_OFF, so ONE global searchsorted against
    # the concatenation of per-key-sorted arrays answers all keys
    T_OFF = np.int64(2) ** 32
    tbase = key_of * T_OFF
    ginv = inv64 + tbase                     # sorted (invoke order)
    gret_sorted = np.sort(ret64 + tbase)
    pred = np.searchsorted(gret_sorted, ginv, side="left") - starts[key_of]
    cap = np.searchsorted(ginv, ret64 + tbase, side="left") \
        - starts[key_of] - 1

    # indefinite updates: npred = count of required rets before the
    # invoke; ops that could only linearize after depth R are dropped
    # BEFORE interning (the reference never interns their values)
    seg_I = np.array(seg_I_l, dtype=np.int64)
    ikey = np.repeat(np.arange(Kf), seg_I)
    ipos = np.array(ipos_l, dtype=np.int64)
    if8 = np.array(if_l, dtype=np.int8)
    i1t = np.array(i1t_l, dtype=np.int8)
    i1v = np.array(i1v_l, dtype=np.int64)
    i2t = np.array(i2t_l, dtype=np.int8)
    i2v = np.array(i2v_l, dtype=np.int64)
    npred = np.searchsorted(gret_sorted, ipos + ikey * T_OFF,
                            side="left") - starts[ikey]
    keep = npred < seg_R[ikey]
    if not np.all(keep):
        ikey, ipos, npred = ikey[keep], ipos[keep], npred[keep]
        if8, i1t, i1v = if8[keep], i1t[keep], i1v[keep]
        i2t, i2v = i2t[keep], i2v[keep]

    a1, a2, ia1, ia2, n_values = _intern_values_batched(
        key_of, ridx, v1t, v1v, v2t, v2v, ikey, i1t, i1v, i2t, i2v, Kf)
    # dead-value merge over required + indefinite triples jointly, then
    # the reference's never-fires drop: an info cas whose old value has
    # no producer (pre-merge producible set) can never linearize
    gkeys = np.concatenate([key_of, ikey])
    gfc = np.concatenate([fcode, if8])
    ga1 = np.concatenate([a1, ia1])
    ga2 = np.concatenate([a2, ia2])
    vbase, producible = _merge_dead_values_batched(
        gkeys, gfc, ga1, ga2, n_values)
    a1, ia1 = ga1[:N], ga1[N:]
    a2, ia2 = ga2[:N], ga2[N:]
    keep = ~((if8 == CAS) & (ia1 != NONE_VAL)
             & ~producible[vbase[ikey] + ia1])
    if not np.all(keep):
        ikey, ipos, npred = ikey[keep], ipos[keep], npred[keep]
        if8, ia1, ia2 = if8[keep], ia1[keep], ia2[keep]
    seg_I = np.bincount(ikey, minlength=Kf).astype(np.int64)

    # lo[d] per depth d in 0..R_k: insertion of d into the running
    # prefix max of cap — the ragged [R_k + 1] query axis flattens to
    # one M-array with per-key offsets (qstart_k = starts_k + k)
    gpm = np.maximum.accumulate(cap + tbase)
    M = N + Kf
    qstarts = starts + np.arange(Kf)
    qkey = np.repeat(np.arange(Kf), seg_R + 1)
    qd = np.arange(M, dtype=np.int64) - qstarts[qkey]
    glo = (np.searchsorted(gpm, qd + qkey * T_OFF, side="left")
           - starts[qkey]).astype(np.int64)

    # window feasibility / width selection (per-key maxima via reduceat)
    width_bits = np.maximum.reduceat(qd - glo, qstarts)
    lo_R = glo[ridx + key_of]                # lo[:R] rows, N-aligned
    first_lo = glo[qstarts[key_of] + np.minimum(pred, seg_R[key_of])]
    width_cand = np.maximum.reduceat(i_within - first_lo, starts) + 1
    width = np.maximum(width_bits, width_cand)
    w_key = np.where(width <= W, W, np.where(width <= 64, 64, W_MAX))

    # forced update counts: per-key exclusive prefix sums of update ops
    is_upd = (fcode == WRITE) | (fcode == CAS)
    pcs = np.zeros(N + 1, dtype=np.int64)
    np.cumsum(is_upd, out=pcs[1:])
    u_forced = (pcs[starts[key_of] + lo_R]
                - pcs[starts[key_of]]).astype(np.int32)

    # version ceilings + per-key suffix min (offset-banded accumulate)
    CEIL_INF = np.int32(2 ** 30)
    ceiling = np.where(ver == NO_ASSERT, CEIL_INF,
                       np.where(fcode == READ, ver, ver - 1)) \
        .astype(np.int32)
    gsuf = np.minimum.accumulate(
        (ceiling.astype(np.int64) + tbase)[::-1])[::-1] - tbase
    tgt = lo_R + w_key[key_of]
    ceil_beyond = np.where(
        tgt >= seg_R[key_of], np.int64(CEIL_INF),
        gsuf[np.clip(starts[key_of] + tgt, 0, N - 1)]).astype(np.int32)

    # joint rank compression of invoke/return times per key: one stable
    # global lexsort, ranks rebased to each key's 2R block
    t_all = np.concatenate([inv64, ret64])
    tk = np.concatenate([key_of, key_of])
    tpos = np.concatenate([i_within, i_within + seg_R[key_of]])
    sorder = np.lexsort((tpos, t_all, tk))
    ranks_flat = np.empty(2 * N, dtype=np.int64)
    ranks_flat[sorder] = np.arange(2 * N, dtype=np.int64) \
        - 2 * starts[tk[sorder]]
    inv_rank = ranks_flat[:N].astype(np.int32)
    ret_rank = ranks_flat[N:].astype(np.int32)

    shift = (glo[ridx + key_of + 1] - glo[ridx + key_of]) \
        .astype(np.int32)
    a1_32 = a1.astype(np.int32)
    a2_32 = a2.astype(np.int32)
    pred_32 = pred.astype(np.int32)

    # info symmetry classes: per key, sort members by ((f, a1, a2),
    # (npred, invoke)) — stable, so ties keep entry order like the
    # reference's explicit j tiebreak — and take run boundaries as
    # class heads. _i_inv_rank ranks each member's invoke among the
    # key's 2R required times, in this class-sorted member order.
    istarts = np.zeros(Kf, dtype=np.int64)
    np.cumsum(seg_I[:-1], out=istarts[1:])
    NI_tot = len(ikey)
    if NI_tot:
        corder = np.lexsort((ipos, npred, ia2, ia1, if8, ikey))
        sk, sf = ikey[corder], if8[corder]
        sa1, sa2 = ia1[corder], ia2[corder]
        sip = ipos[corder]
        newc = np.ones(NI_tot, dtype=bool)
        newc[1:] = (sk[1:] != sk[:-1]) | (sf[1:] != sf[:-1]) \
            | (sa1[1:] != sa1[:-1]) | (sa2[1:] != sa2[:-1])
        rstarts = np.flatnonzero(newc)
        rsizes = np.diff(np.append(rstarts, NI_tot))
        ckey = sk[rstarts]
        c_f_all = sf[rstarts]
        c_a1_all = sa1[rstarts].astype(np.int32)
        c_a2_all = sa2[rstarts].astype(np.int32)
        c_off_all = (rstarts - istarts[ckey]).astype(np.int32)
        c_size_all = rsizes.astype(np.int32)
        cstarts = np.searchsorted(ckey, np.arange(Kf), side="left")
        cends = np.searchsorted(ckey, np.arange(Kf), side="right")
        g_all_sorted = t_all[sorder] + tk[sorder] * T_OFF
        i_inv_rank_all = (np.searchsorted(g_all_sorted,
                                          sip + sk * T_OFF, side="left")
                          - 2 * starts[sk]).astype(np.int64)

    empty8 = np.zeros(0, dtype=np.int8)
    empty32 = np.zeros(0, dtype=np.int32)
    emptyu32 = np.zeros(0, dtype=np.uint32)
    empty64 = np.zeros(0, dtype=np.int64)
    for j, key in enumerate(fast_keys):
        R = int(seg_R[j])
        I = int(seg_I[j])
        if I:
            cs, ce = int(cstarts[j]), int(cends[j])
            C = ce - cs
            c_size = c_size_all[cs:ce]
            # bit layout: each class's count field is
            # ceil(log2(size+1)) bits, placed in the first word with
            # room (fields never cross words) — the C-length greedy
            # scan is the reference's, verbatim (C is tiny)
            c_word = np.zeros(C, dtype=np.int32)
            c_shift = np.zeros(C, dtype=np.int32)
            c_mask = np.zeros(C, dtype=np.uint32)
            word, used = 0, 0
            for ci in range(C):
                bits = max(1, int(c_size[ci]).bit_length())
                if used + bits > 32:
                    word, used = word + 1, 0
                c_word[ci] = word
                c_shift[ci] = used
                c_mask[ci] = (1 << bits) - 1
                used += bits
            ni = word + 1
            if ni > NI_MAX:
                out[key] = Packed(
                    ok=False, blowup=True,
                    reason=f"{I} info updates in {C} classes need "
                           f"{ni} count words > {NI_MAX}")
                continue
            if I > I_TABLE_MAX:
                out[key] = Packed(
                    ok=False, blowup=True,
                    reason=f"{I} info updates > member-table cap "
                           f"{I_TABLE_MAX}")
                continue
        else:
            C, ni = 0, 0
        if width[j] > W_MAX:
            out[key] = Packed(
                ok=False,
                reason=f"window {int(width[j])} > {W_MAX} "
                       f"(concurrency too high for kernel)")
            continue
        s, e = int(starts[j]), int(starts[j] + R)
        qs = int(qstarts[j])
        p = Packed(
            ok=True, R=R, I=I, n_values=int(n_values[j]),
            w=int(w_key[j]),
            shift=shift[s:e], u_forced=u_forced[s:e],
            ceil_beyond=ceil_beyond[s:e],
            C=C, ni=ni,
            c_f=c_f_all[cs:ce] if I else empty8,
            c_a1=c_a1_all[cs:ce] if I else empty32,
            c_a2=c_a2_all[cs:ce] if I else empty32,
            c_size=c_size if I else empty32,
            c_off=c_off_all[cs:ce] if I else empty32,
            c_word=c_word if I else empty32,
            c_shift=c_shift if I else empty32,
            c_mask=c_mask if I else emptyu32,
            op_a1=a1_32[s:e], op_a2=a2_32[s:e], op_ver=ver[s:e],
            op_f=fcode[s:e], op_pred_rank=pred_32[s:e],
            op_ceiling=ceiling[s:e],
            inv_rank=inv_rank[s:e], ret_rank=ret_rank[s:e],
            lo=glo[qs:qs + R + 1],
        )
        iis = int(istarts[j])
        p._i_inv_rank = i_inv_rank_all[iis:iis + I] if I else empty64
        out[key] = p
    return out


class PackStream:
    """Streaming front-end of the batched register packer: ``feed``
    columnar op-stream chunks (core/history.py OpColumns — e.g. the
    ``ColumnsBuilder.take_chunk`` drain) while generation proceeds;
    ``finish()`` returns the same ``{key: Packed}`` dict
    :func:`pack_register_histories_batched` produces over the completed
    history's per-key split.

    Bit-identity argument: the per-op extraction pass is chunk-resumable
    (:class:`_KeyExtract` carries the one-shot loop's exact state), keys
    accumulate in first-seen order (matching ``split_by_key``'s group
    order), and ``finish`` concatenates each key's lists into the same
    per-key-contiguous flat arrays before running the SAME vectorized
    tail (:func:`_pack_batched_tail`). The tail itself cannot run per
    chunk — suffix-min version ceilings, dead-value merges and info
    symmetry classes all depend on the history's future — so only the
    per-op Python pass overlaps generation; that is the host-packing
    half the cost model in PERF.md §2 attributes to extraction.

    Any key the columnar path can't express (reference-delegation
    semantics, malformed shapes) invalidates the whole stream: ``ok``
    flips False, further feeds no-op, and ``finish`` returns None — the
    checker then packs post-hoc exactly as before. Streaming is a pure
    reuse hint, never a correctness dependency."""

    def __init__(self):
        self._keys: list = []
        self._st: dict = {}
        self.ok = True
        #: total column rows consumed (ALL events, keyed or not) — the
        #: consumer's guard that the stream saw the complete history
        self.n_rows = 0
        self.chunks = 0

    def feed(self, cols) -> None:
        if cols is None or not self.ok:
            return
        self.n_rows += len(cols)
        self.chunks += 1
        try:
            for key, sub in cols.split_by_key().items():
                st = self._st.get(key)
                if st is None:
                    st = self._st[key] = _KeyExtract()
                    self._keys.append(key)
                st.feed(_rows_from_columns(sub))
        except (_Delegate, TypeError, ValueError):
            self.ok = False

    def finish(self) -> Optional[dict]:
        if not self.ok:
            return None
        out: dict = {}
        fast_keys: list = []
        seg_R_l: list = []
        seg_I_l: list = []
        lists = tuple([] for _ in range(8))
        ilists = tuple([] for _ in range(6))
        try:
            for key in self._keys:
                st = self._st[key]
                n_req = st.finish()
                if n_req == 0:
                    # no required ops: trivially linearizable, before
                    # any indefinite op is even considered (mirrors the
                    # batched packer's early out)
                    out[key] = Packed(ok=True, R=0)
                    continue
                fast_keys.append(key)
                seg_R_l.append(n_req)
                seg_I_l.append(len(st.ilists[0]))
                for dst, src in zip(lists, st.lists):
                    dst.extend(src)
                for dst, src in zip(ilists, st.ilists):
                    dst.extend(src)
        except (_Delegate, TypeError, ValueError):
            self.ok = False
            return None
        if fast_keys:
            _pack_batched_tail(fast_keys, seg_R_l, seg_I_l,
                               lists, ilists, out)
        return out


# ---------------------------------------------------------------------------
# the kernel


def _expand(dvec, wvec, ivec, vvec, tables, R, I,
            w: int, f_out: int):
    """One BFS wave: expand a frontier into its deduped successor set.

    Pure jax; works standalone (spill mode) and inside the while_loop.
    Returns (out_d, out_w, out_i, out_v, n_new, accepted). accepted is
    computed on the *full* candidate set before truncation, so a reached
    goal is never lost to overflow.
    """
    import jax.numpy as jnp
    from jax import lax

    f_in = dvec.shape[0]
    nw = wvec.shape[1]                 # mask words (1: W<=32, 2: W<=64)
    # static one-hot candidate-bit table: B[b, wi] = bit (b%32) of word
    # b//32 — little-endian words, same layout pack_bits produces
    B_np = np.zeros((w, nw), dtype=np.uint32)
    for b in range(w):
        B_np[b, b // 32] = np.uint32(1) << np.uint32(b % 32)
    B = jnp.asarray(B_np)                                  # [W, NW]

    alive = (dvec != SENTINEL_D) & (dvec < R)              # [F]
    d_cl = jnp.clip(dvec, 0, tables["shift"].shape[0] - 1)
    row = lambda t: jnp.take(t, d_cl, axis=0)              # [F, ...]

    s_ok = row(tables["static_ok"])                        # [F, W]
    fc = row(tables["f_code"])
    ra1 = row(tables["a1"])
    ra2 = row(tables["a2"])
    rver = row(tables["ver"])
    rpred = row(tables["pred_frame"])                      # [F, W, NW]
    rupd = row(tables["upd_mask"])                         # [F, NW]
    ruf = row(tables["u_forced"])                          # [F]
    rshift = row(tables["shift"]).astype(jnp.uint32)       # [F]

    wm = wvec[:, None, :]                                  # [F, 1, NW]
    not_set = ~jnp.any((wm & B[None]) != 0, axis=-1)       # [F, W]
    preds_in = jnp.all((wm & rpred) == rpred, axis=-1)     # [F, W]
    # per-class info counts, unpacked from the [F, NI] count words
    # (classes own fixed bit fields; padding classes have mask 0)
    ni = ivec.shape[1]
    c_pad = tables["c_size"].shape[-1] if ni else 0
    if c_pad:
        cw = jnp.clip(tables["c_word"], 0, ni - 1)          # [C]
        ivw = jnp.take(ivec, cw, axis=1)                    # [F, C]
        counts = (ivw >> tables["c_shift"].astype(jnp.uint32)[None, :]) \
            & tables["c_mask"][None, :]                     # [F, C]
        info_total = counts.sum(axis=1).astype(jnp.int32)   # [F]
    else:
        info_total = jnp.int32(0)
    version = (ruf
               + lax.population_count(wvec & rupd)
               .sum(axis=-1).astype(jnp.int32)
               + info_total)                                # [F]
    # dead-state prune: version never decreases, so a state whose
    # version exceeds the min ceiling among unlinearized required ops
    # (window lanes with clear bits, plus everything past the window)
    # can never linearize them — drop it from the frontier
    min_ceil = jnp.minimum(
        jnp.min(jnp.where(not_set, row(tables["ceil_frame"]),
                          jnp.int32(2 ** 30)), axis=1),
        row(tables["ceil_beyond"]))                        # [F]
    alive = alive & (version <= min_ceil)
    ver_b = version[:, None]
    v = vvec[:, None]                                      # [F, 1]

    is_read = fc == READ
    is_write = fc == WRITE
    is_cas = fc == CAS
    no_assert = rver == NO_ASSERT
    ver_ok = jnp.where(is_read,
                       no_assert | (rver == ver_b),
                       no_assert | (rver == ver_b + 1))
    read_ok = is_read & ((ra1 == WILDCARD) | (ra1 == v))
    cas_ok = is_cas & (ra1 == v)
    model_ok = read_ok | is_write | cas_ok
    req_valid = alive[:, None] & s_ok & not_set & preds_in & ver_ok & model_ok

    new_w = wm | B[None]                                   # [F, W, NW]
    # slide feasibility: the rshift lowest bits (which fall off the
    # window) must all be set. Per-word low masks; shift amounts are
    # clamped before any << / >> so no lane shifts by >= 32 (UB).
    s_amt = rshift[:, None]                                # [F, 1]

    def low_mask_word(wi):
        k = jnp.clip(s_amt.astype(jnp.int32) - 32 * wi, 0, 32)
        ksafe = jnp.minimum(k, 31).astype(jnp.uint32)
        return jnp.where(k >= 32, jnp.uint32(0xFFFFFFFF),
                         (jnp.uint32(1) << ksafe) - jnp.uint32(1))

    low = jnp.stack([low_mask_word(wi) for wi in range(nw)],
                    axis=-1)                               # [F, 1, NW]
    slide_ok = jnp.all((new_w & low) == low, axis=-1)      # [F, W]
    req_valid = req_valid & slide_ok

    def rshift_words(words, s):
        """words: list of NW [..., ] uint32 planes; s broadcastable
        shift in [0, 32*nw]. Returns the shifted planes. Generic over
        nw: decompose s = 32*k + r, select source planes i+k / i+k+1
        by a where-chain over the (static, <= nw) possible k values,
        and combine with clamped lane shifts (no lane ever shifts by
        >= 32, which would be UB)."""
        s32 = s.astype(jnp.uint32)
        k = s32 >> 5                          # word offset, 0..nw
        r = s32 & jnp.uint32(31)              # bit offset within word
        rsafe = jnp.minimum(r, jnp.uint32(31))
        carry_amt = jnp.minimum(jnp.uint32(32) - rsafe, jnp.uint32(31))
        zero = jnp.zeros_like(words[0])
        padded = list(words) + [zero] * (nw + 1)
        out = []
        for i in range(nw):
            lo_w = zero
            hi_w = zero
            for kk in range(nw + 1):
                lo_w = jnp.where(k == kk, padded[i + kk], lo_w)
                hi_w = jnp.where(k == kk, padded[i + kk + 1], hi_w)
            carry = jnp.where(r == 0, jnp.uint32(0), hi_w << carry_amt)
            out.append((lo_w >> rsafe) | carry)
        return out

    shifted = rshift_words([new_w[:, :, wi] for wi in range(nw)], s_amt)
    new_w = jnp.stack(shifted, axis=-1)                    # [F, W, NW]
    req_d = jnp.broadcast_to(dvec[:, None] + 1, (f_in, w))
    req_i = jnp.broadcast_to(ivec[:, None, :], (f_in, w, ni))
    req_v = jnp.where(is_read, v,
                      jnp.where(is_write, ra1, ra2)).astype(jnp.int32)
    accepted = jnp.any(req_valid & (req_d == R))

    rv3 = req_valid[:, :, None]
    cand_d = [jnp.where(req_valid, req_d, SENTINEL_D)]
    cand_w = [jnp.where(rv3, new_w, jnp.uint32(SENTINEL_W))]
    cand_i = [req_i]
    cand_v = [jnp.where(req_valid, req_v, SENTINEL_V)]

    if c_pad:
        # class candidates: fire each class's NEXT member (the
        # count-th in canonical order); the count field increments in
        # place (fields never overflow: can_more gates at class size)
        can_more = counts < tables["c_size"][None, :].astype(jnp.uint32)
        i_tab = tables["i_static_ok"].shape[-1]
        member = jnp.clip(tables["c_off"][None, :]
                          + counts.astype(jnp.int32), 0, i_tab - 1)
        # single advanced-index gather straight to [F, C(, NW)]: a
        # row() gather first would materialize the full [F, i_tab, NW]
        # slab (i_tab up to 256) every wave
        istat = tables["i_static_ok"][d_cl[:, None], member]  # [F, C]
        ipredf = tables["ipred_frame"][d_cl[:, None], member]
        ipred_in = jnp.all((wm & ipredf) == ipredf, axis=-1)  # [F, C]
        cfc = tables["c_f"][None, :]
        ca1 = tables["c_a1"][None, :]
        ca2 = tables["c_a2"][None, :]
        c_is_w = cfc == WRITE
        i_model_ok = c_is_w | ((cfc == CAS) & (ca1 == v))
        i_valid = (alive[:, None] & can_more & istat & ipred_in
                   & i_model_ok
                   # child (version+1, same required set) would be
                   # ceiling-dead: don't spend a frontier slot on it
                   & ((version + 1) <= min_ceil)[:, None])
        i_new_i = ivec[:, None, :] + tables["c_inc"][None, :, :]
        i_new_v = jnp.broadcast_to(
            jnp.where(c_is_w, ca1, ca2).astype(jnp.int32),
            (f_in, c_pad))
        cand_d.append(jnp.where(i_valid, jnp.broadcast_to(
            dvec[:, None], (f_in, c_pad)), SENTINEL_D))
        cand_w.append(jnp.where(
            i_valid[:, :, None],
            jnp.broadcast_to(wvec[:, None, :], (f_in, c_pad, nw)),
            jnp.uint32(SENTINEL_W)))
        cand_i.append(jnp.where(i_valid[:, :, None], i_new_i,
                                jnp.broadcast_to(ivec[:, None, :],
                                                 (f_in, c_pad, ni))))
        cand_v.append(jnp.where(i_valid, i_new_v, SENTINEL_V))

    flat_d = jnp.concatenate(cand_d, axis=1).reshape(-1)
    flat_w = jnp.concatenate(cand_w, axis=1).reshape(-1, nw)
    flat_i = (jnp.concatenate(cand_i, axis=1).reshape(-1, ni) if ni
              else jnp.zeros((flat_d.shape[0], 0), dtype=jnp.uint32))
    flat_v = jnp.concatenate(cand_v, axis=1).reshape(-1)

    ops = (flat_d, *[flat_w[:, wi] for wi in range(nw)],
           *[flat_i[:, iw] for iw in range(ni)], flat_v)
    sorted_ = lax.sort(ops, num_keys=len(ops))
    sd = sorted_[0]
    sw = list(sorted_[1:1 + nw])
    si = list(sorted_[1 + nw:1 + nw + ni])
    sv = sorted_[1 + nw + ni]
    is_real = sd != SENTINEL_D
    change = (sd[1:] != sd[:-1]) | (sv[1:] != sv[:-1])
    for wi in range(nw):
        change = change | (sw[wi][1:] != sw[wi][:-1])
    for iw in range(ni):
        change = change | (si[iw][1:] != si[iw][:-1])
    first = jnp.concatenate([jnp.array([True]), change])
    uniq = is_real & first
    pos = jnp.cumsum(uniq.astype(jnp.int32)) - 1
    n_new = jnp.sum(uniq.astype(jnp.int32))
    pos = jnp.where(uniq & (pos < f_out), pos, f_out)      # drop overflowed
    out_d = jnp.full((f_out + 1,), SENTINEL_D, dtype=jnp.int32)
    out_w = jnp.full((f_out + 1, nw), SENTINEL_W, dtype=jnp.uint32)
    out_i = jnp.zeros((f_out + 1, ni), dtype=jnp.uint32)
    out_v = jnp.full((f_out + 1,), SENTINEL_V, dtype=jnp.int32)
    out_d = out_d.at[pos].set(sd, mode="drop")[:f_out]
    out_w = out_w.at[pos].set(jnp.stack(sw, axis=-1), mode="drop")[:f_out]
    if ni:
        out_i = out_i.at[pos].set(jnp.stack(si, axis=-1),
                                  mode="drop")
    out_i = out_i[:f_out]
    out_v = out_v.at[pos].set(sv, mode="drop")[:f_out]
    return out_d, out_w, out_i, out_v, n_new, accepted


@functools.lru_cache(maxsize=None)
def _kernel_resume_jitted(f_max: int, w: int):
    """The ONE jitted wave-loop form per rung. Fresh searches seed the
    initial frontier on the host and enter through the same resume
    signature, so each (f_max, w) rung compiles once per table shape —
    wide-window (W=128) compiles are expensive enough that a separate
    fresh-start compile per rung would double a multi-minute bill."""
    import jax

    def run(tables, R, I, k0, d0, w0, i0, v0, n0):
        return _wgl_loop(tables, R, I, f_max, w,
                         (k0, d0, w0, i0, v0, n0))

    return jax.jit(run)


@functools.lru_cache(maxsize=None)
def _kernel_budget_jitted(f_max: int, w: int):
    """Wave-budgeted twin of :func:`_kernel_resume_jitted`: same resume
    signature plus a traced ``k_stop`` wave ceiling, so one compile per
    (f_max, w) rung serves every chunk size the streaming driver picks
    (the budget is data, not shape)."""
    import jax

    def run(tables, R, I, k_stop, k0, d0, w0, i0, v0, n0):
        return _wgl_loop(tables, R, I, f_max, w,
                         (k0, d0, w0, i0, v0, n0), k_stop=k_stop)

    return jax.jit(run)


def _wgl_kernel(tables: dict, R, I, f_max: int = F_MAX, w: int = W):
    """Run the wave loop from the initial state. tables hold the
    [R_pad, ...] arrays; R (number of required ops) and I (number of
    info ops) are dynamic. Returns (valid, overflow, waves_done,
    frontier_size_max, frontier) where frontier = (dvec, wvec, ivec,
    vvec, n_alive) is the pre-expansion frontier at exit — on overflow
    the host driver RESUMES from it at a higher capacity (the retry
    ladder) or in spill mode, without redoing earlier waves.
    """
    return _wgl_loop(tables, R, I, f_max, w, None)


def _wgl_loop(tables: dict, R, I, f_max: int, w: int, init0,
              k_stop=None):
    import jax.numpy as jnp
    from jax import lax

    # k_stop (traced) budgets the waves run THIS call — the streaming
    # check_prefix API pauses there and resumes later with identical
    # semantics; None (every non-streaming caller) keeps the exact
    # R + I + 1 exhaustion bound and compiles the same trace as before
    lim = (R + I + 1) if k_stop is None \
        else jnp.minimum(k_stop, R + I + 1)

    def body(carry):
        k, dvec, wvec, ivec, vvec, n_alive, overflow, accepted, peak = carry
        # vmap-safety guard: under vmap, while_loop runs until ALL batch
        # elements finish; finished elements must be no-ops.
        active = (~accepted) & (n_alive > 0) & (~overflow) & (k < lim)
        out_d, out_w, out_i, out_v, n_new, acc_now = _expand(
            dvec, wvec, ivec, vvec, tables, R, I, w, f_max)
        ovf_now = (n_new > f_max) & (~acc_now)
        # on overflow, freeze the pre-expansion frontier for spill resume
        advance = active & (~ovf_now)
        return (jnp.where(advance, k + 1, k),
                jnp.where(advance, out_d, dvec),
                jnp.where(advance, out_w, wvec),
                jnp.where(advance, out_i, ivec),
                jnp.where(advance, out_v, vvec),
                jnp.where(advance, jnp.minimum(n_new, f_max), n_alive),
                jnp.where(active, overflow | ovf_now, overflow),
                jnp.where(active, accepted | acc_now, accepted),
                jnp.where(active, jnp.maximum(peak, n_new), peak))

    def cond(carry):
        k, _, _, _, _, n_alive, overflow, accepted, _ = carry
        return (~accepted) & (n_alive > 0) & (~overflow) & (k < lim)

    nw = w // 32
    ni = tables["c_inc"].shape[-1] if "c_inc" in tables else 0
    if init0 is None:
        d0 = jnp.full((f_max,), SENTINEL_D, dtype=jnp.int32)
        d0 = d0.at[0].set(0)
        w0 = jnp.full((f_max, nw), SENTINEL_W, dtype=jnp.uint32)
        w0 = w0.at[0].set(0)
        i0 = jnp.zeros((f_max, ni), dtype=jnp.uint32)
        v0 = jnp.full((f_max,), SENTINEL_V, dtype=jnp.int32)
        v0 = v0.at[0].set(NONE_VAL)
        k0, n0, peak0 = jnp.int32(0), jnp.int32(1), jnp.int32(1)
    else:
        k0, d0, w0, i0, v0, n0 = init0
        peak0 = n0
    init = (k0, d0, w0, i0, v0, n0, jnp.bool_(False), R == 0, peak0)
    k, dvec, wvec, ivec, vvec, n_alive, overflow, accepted, peak = \
        lax.while_loop(cond, body, init)
    return (accepted, overflow, k, peak,
            (dvec, wvec, ivec, vvec, n_alive))


def bucket(n: int) -> int:
    """Pad R to a power-of-two bucket so jit caches stay warm."""
    b = 16
    while b < n:
        b *= 2
    return b


def info_dims(p: Packed) -> tuple[int, int, int]:
    """Bucketed (c_pad, ni_pad, i_tab) so jit caches stay warm: padded
    class count, count words, and member-table width. All zero for
    info-free histories (keeps them on the info-free compile)."""
    if p.C == 0:
        return 0, 0, 0
    c_pad = 8
    while c_pad < p.C:
        c_pad *= 2
    ni_pad = 1
    while ni_pad < p.ni:
        ni_pad *= 2
    i_tab = 8
    while i_tab < p.I:
        i_tab *= 2
    return c_pad, ni_pad, i_tab


def pad_tables(p: Packed, r_pad: int, info: tuple = None):
    """Pad the per-depth tables to bucketed lengths (shared by
    check_packed and the __graft_entry__ paths)."""
    ensure_frames(p)   # frames are lazy; this path reads them
    if info is None:
        info = info_dims(p)
    c_pad, ni_pad, i_tab = info

    def padded(a, rows=r_pad):
        out = np.zeros((rows,) + a.shape[1:], dtype=a.dtype)
        out[:a.shape[0]] = a
        return out

    def padded_c(a):
        out = np.zeros((c_pad,), dtype=a.dtype)
        out[:p.C] = a
        return out

    def padded_ri(a):
        out = np.zeros((r_pad, i_tab) + a.shape[2:], dtype=a.dtype)
        out[:a.shape[0], :p.I] = a
        return out

    t = {
        "shift": padded(p.shift), "static_ok": padded(p.static_ok),
        "f_code": padded(p.f_code), "a1": padded(p.a1), "a2": padded(p.a2),
        "ver": padded(p.ver), "pred_frame": padded(p.pred_frame),
        "upd_mask": padded(p.upd_mask), "u_forced": padded(p.u_forced),
        "ceil_frame": padded(p.ceil_frame),
        "ceil_beyond": padded(p.ceil_beyond),
    }
    # ceiling padding must be +inf, not 0 (a zero ceiling would prune
    # clamped-gather rows)
    t["ceil_frame"][p.ceil_frame.shape[0]:] = 2 ** 30
    t["ceil_beyond"][p.ceil_beyond.shape[0]:] = 2 ** 30
    if c_pad:
        inc = np.zeros((c_pad, ni_pad), dtype=np.uint32)
        inc[np.arange(p.C), p.c_word] = \
            np.uint32(1) << p.c_shift.astype(np.uint32)
        t.update({
            "c_f": padded_c(p.c_f), "c_a1": padded_c(p.c_a1),
            "c_a2": padded_c(p.c_a2), "c_size": padded_c(p.c_size),
            "c_off": padded_c(p.c_off), "c_word": padded_c(p.c_word),
            "c_shift": padded_c(p.c_shift), "c_mask": padded_c(p.c_mask),
            "c_inc": inc,
            "i_static_ok": padded_ri(p.i_static_ok),
            "ipred_frame": padded_ri(p.ipred_frame),
        })
    return t


@functools.lru_cache(maxsize=None)
def _expand_jitted(f_in: int, w: int, f_out: int):
    import jax

    def run(dvec, wvec, ivec, vvec, tables, R, I):
        return _expand(dvec, wvec, ivec, vvec, tables, R, I, w, f_out)

    return jax.jit(run)


SPILL_WALL_BUDGET_S = 60.0  # hopeless-width searches must fail fast


def spill_packed(p: Packed, tables, frontier, waves_done: int) -> dict:
    """Budgeted host-spill continuation from a frozen frontier — the
    entry point for resuming a ``check_packed(..., spill=False)``
    overflow (its ``_resume`` payload) without re-climbing the ladder."""
    tel = telemetry.current()
    tel.counter("wgl.host-spill")
    with tel.span("wgl.spill", ops=p.R, w=p.w) as sp:
        out = _spill_bfs(p, tables, frontier, waves_done,
                         state_budget=SPILL_STATE_BUDGET
                         if p.I < SPILL_I_LIMIT
                         else SPILL_STATE_BUDGET_HIGH_I)
        sp.set(valid=out.get("valid?"),
               peak_frontier=out.get("peak-frontier"),
               states=out.get("states"))
    if out.get("peak-frontier"):
        tel.counter("wgl.max-frontier", out["peak-frontier"], mode="max")
    return out


def _spill_bfs(p: Packed, tables, frontier, waves_done: int,
               state_budget: int = SPILL_STATE_BUDGET,
               wall_budget_s: float = SPILL_WALL_BUDGET_S) -> dict:
    """Host-driven chunked BFS after in-kernel frontier overflow.

    The frontier lives on host as numpy arrays; each wave expands it in
    SPILL_CHUNK-sized chunks through the single-wave expand kernel at
    full output capacity (SPILL_CHUNK * (W + classes) slots can hold every
    possible successor of a chunk, so nothing is dropped), then merges
    across chunks with np.unique. Sound *and* complete: the only exit
    without a verdict is the explicit state budget.

    This is the "capacity-overflow spill logic" SURVEY §7 names as a hard
    part; the reference's Knossos equivalent is its unbounded JVM heap
    (project.clj:21-23 sizes it at 24 GB).
    """
    import jax.numpy as jnp

    c_pad, ni, _i_tab = info_dims(p)
    nw = p.w // 32
    # W=128: a full-size chunk would make the lossless-output sort
    # (f_in * 129 slots) prohibitively slow to compile; spill there is
    # a last resort behind the DFS anyway
    f_in = SPILL_CHUNK if p.w < W_MAX else 1024
    f_out = f_in * (p.w + max(c_pad, 1))
    expand = _expand_jitted(f_in, p.w, f_out)
    dvec, wvec, ivec, vvec, n_alive = [np.asarray(x) for x in frontier]
    n = int(n_alive)
    fr = np.concatenate(
        [dvec[:n, None].astype(np.int64),
         wvec[:n].astype(np.int64).reshape(n, nw),
         ivec[:n].astype(np.int64).reshape(n, ni),
         vvec[:n, None].astype(np.int64)], axis=1)  # [n, 2 + nw + ni]
    import time as _time
    # compile warmup outside the wall budget: an all-sentinel chunk is
    # a no-op wave, but it forces the (expensive, possibly minutes for
    # W=128) expand compile so the budget measures search, not XLA
    expand(jnp.full((f_in,), SENTINEL_D, dtype=jnp.int32),
           jnp.full((f_in, nw), SENTINEL_W, dtype=jnp.uint32),
           jnp.zeros((f_in, ni), dtype=jnp.uint32),
           jnp.full((f_in,), SENTINEL_V, dtype=jnp.int32),
           tables, jnp.int32(p.R), jnp.int32(p.I))
    # graftlint: ignore[DET001] explicit wall budget: returns valid?=unknown (never flips a verdict), the Knossos-timeout analog
    t_start = _time.monotonic()
    states_total = n
    peak = n
    waves = waves_done
    max_waves = p.R + p.I + 1
    while fr.shape[0] and waves < max_waves:
        # graftlint: ignore[DET001] explicit wall budget: returns valid?=unknown (never flips a verdict), the Knossos-timeout analog
        if _time.monotonic() - t_start > wall_budget_s:
            return {"valid?": "unknown", "blowup": True,
                    "reason": f"spill wall budget {wall_budget_s:.0f}s "
                              "exceeded",
                    "peak-frontier": peak, "spilled": True}
        succs = []
        for s in range(0, fr.shape[0], f_in):
            chunk = fr[s:s + f_in]
            cn = chunk.shape[0]
            cd = np.full(f_in, SENTINEL_D, dtype=np.int32)
            cw = np.full((f_in, nw), SENTINEL_W, dtype=np.uint32)
            ci = np.zeros((f_in, ni), dtype=np.uint32)
            cv = np.full(f_in, SENTINEL_V, dtype=np.int32)
            cd[:cn] = chunk[:, 0]
            cw[:cn] = chunk[:, 1:1 + nw].astype(np.uint32)
            ci[:cn] = chunk[:, 1 + nw:1 + nw + ni].astype(np.uint32)
            cv[:cn] = chunk[:, 1 + nw + ni]
            out_d, out_w, out_i, out_v, n_new, accepted = expand(
                # graftlint: ignore[JAX001] spill engine: one dispatch per host chunk is its design
                jnp.asarray(cd), jnp.asarray(cw), jnp.asarray(ci),
                # graftlint: ignore[JAX001] spill engine: one dispatch per host chunk is its design
                jnp.asarray(cv), tables, jnp.int32(p.R), jnp.int32(p.I))
            if bool(accepted):
                return {"valid?": True, "waves": waves + 1,
                        "peak-frontier": peak, "ops": p.R,
                        "info-ops": p.I, "spilled": True,
                        "states": states_total}
            m = int(n_new)
            if m:
                succs.append(np.concatenate(
                    # graftlint: ignore[JAX002] spill engine: host merge per chunk is its design
                    [np.asarray(out_d)[:m, None].astype(np.int64),
                     # graftlint: ignore[JAX002] spill engine: host merge per chunk is its design
                     np.asarray(out_w)[:m].astype(np.int64),
                     # graftlint: ignore[JAX002] spill engine: host merge per chunk is its design
                     np.asarray(out_i)[:m].astype(np.int64),
                     # graftlint: ignore[JAX002] spill engine: host merge per chunk is its design
                     np.asarray(out_v)[:m, None].astype(np.int64)], axis=1))
        if not succs:
            fr = np.zeros((0, 2 + nw + ni), dtype=np.int64)
            break
        fr = np.unique(np.concatenate(succs, axis=0), axis=0)
        waves += 1
        states_total += fr.shape[0]
        peak = max(peak, fr.shape[0])
        if fr.shape[0] > SPILL_FRONTIER_LIMIT:
            return {"valid?": "unknown", "blowup": True,
                    "reason": f"spill frontier {fr.shape[0]} > "
                              f"{SPILL_FRONTIER_LIMIT}",
                    "peak-frontier": peak, "spilled": True}
        if states_total > state_budget:
            return {"valid?": "unknown", "blowup": True,
                    "reason": f"spill budget exceeded "
                              f"({states_total} states)",
                    "peak-frontier": peak, "spilled": True}
    if fr.shape[0]:
        # wave-budget backstop tripped with work remaining: cannot happen
        # for a well-formed pack (levels are bounded by R+I), so answer
        # soundly rather than guess
        return {"valid?": "unknown", "reason": "spill wave budget exceeded",
                "peak-frontier": peak, "spilled": True}
    return {"valid?": False, "waves": waves, "peak-frontier": peak,
            "ops": p.R, "info-ops": p.I, "spilled": True,
            "states": states_total, "stuck-at-depth": waves}


@functools.lru_cache(maxsize=None)
def _batched_kernel_jitted(f_max: int, w: int, donate: bool = False):
    import jax
    kernel = functools.partial(_wgl_kernel, f_max=f_max, w=w)
    if donate:
        # donated table/R/I buffers let XLA reuse their device memory
        # for the wave ladder's working set — safe because every tick
        # device_puts fresh inputs (nothing aliases across ticks).
        # Callers gate this to the TPU backend: the CPU runtime warns
        # and ignores donation.
        return jax.jit(jax.vmap(kernel), donate_argnums=(0, 1, 2))
    return jax.jit(jax.vmap(kernel))


@functools.lru_cache(maxsize=None)
def _batched_kernel_sharded(f_max: int, w: int, n_dev: int,
                            devs_key: tuple):
    """shard_map form of the vmapped wave ladder for ONE oversized
    (bucket, width) group: the key axis splits over a ("key",) device
    mesh and each shard runs its own vmapped while_loop — unlike the
    GSPMD scatter, a shard whose keys all die early is NOT held in
    lockstep wave steps until the slowest shard finishes (the host +
    device + sharded dispatch split ops/closure.py proved for the
    closure op). Keys are independent, so nothing rides the ICI.
    ``devs_key`` pins the cache entry to the device set by string
    identity (the same aliasing rule as closure._closure_sharded_jitted
    — ``id()`` of device objects is NOT stable)."""
    import jax
    from jax.sharding import Mesh, PartitionSpec as P
    from .wgl_mxu import _shard_map

    del devs_key  # cache key only
    kernel = jax.vmap(functools.partial(_wgl_kernel, f_max=f_max, w=w))
    mesh = Mesh(np.array(jax.devices()[:n_dev]), ("key",))
    shard_map, vma_kw = _shard_map()
    sharded = shard_map(kernel, mesh=mesh,
                        in_specs=(P("key"), P("key"), P("key")),
                        out_specs=P("key"), **vma_kw)
    return jax.jit(sharded)


def group_key(p: Packed) -> tuple:
    """The (R-bucket, info dims, window width) dispatch-group key: keys
    sharing it ride one vmapped launch, and the sharded checker service
    (runner/checker_service.py) uses it as the unit of sticky
    group→device placement."""
    return (bucket(p.R), info_dims(p), p.w)


class PreparedGroup:
    """The host half of one bucket-group dispatch: padded + stacked
    numpy tables for a same-``group_key`` key group. Splitting this off
    ``_check_bucket_group`` lets the checker service double-buffer —
    pack tick N+1's groups on the dispatcher thread while tick N's jobs
    still run on their chips. ``lanes`` is the device-lane count the
    key axis was padded for (1 for a single committed device, n_dev for
    the mesh paths)."""

    __slots__ = ("key", "n", "lanes", "k_pad", "stacked", "Rs", "Is")

    def __init__(self, key, n, lanes, k_pad, stacked, Rs, Is):
        self.key = key
        self.n = n
        self.lanes = lanes
        self.k_pad = k_pad
        self.stacked = stacked
        self.Rs = Rs
        self.Is = Is


def prepare_bucket_group(packs: list, idxs: list, r_pad: int,
                         info: tuple, lanes: int = 1) -> PreparedGroup:
    """Pad and stack a key group's tables on the host (no jax touched).

    The key axis pads to a power-of-two per-lane count times ``lanes``
    so jit caches stay warm across varying group sizes (the campaign
    checker service coalesces packs from many runs per tick, so K
    varies tick to tick); padding keys have R=0 and their lanes are
    dropped at decode — verdicts never see the pad."""
    K = len(idxs)
    per_lane = 1
    while per_lane * lanes < K:
        per_lane *= 2
    k_pad = per_lane * lanes
    per_key = [pad_tables(packs[i], r_pad, info) for i in idxs]
    stacked = {}
    for name in per_key[0]:
        arrs = [t[name] for t in per_key]
        out = np.zeros((k_pad,) + arrs[0].shape, dtype=arrs[0].dtype)
        for j, a in enumerate(arrs):
            out[j] = a
        stacked[name] = out
    Rs = np.zeros(k_pad, dtype=np.int32)  # padding keys: R=0 -> accepted
    Is = np.zeros(k_pad, dtype=np.int32)
    for j, i in enumerate(idxs):
        Rs[j] = packs[i].R
        Is[j] = packs[i].I
    return PreparedGroup((r_pad, info, packs[idxs[0]].w), K, lanes,
                         k_pad, stacked, Rs, Is)


def check_packed_batch(packs: list, f_max: Optional[int] = None,
                       try_fused: bool = True, device=None,
                       shard: bool = False, prepared=None,
                       device_for=None) -> list:
    """Check K per-key packed histories in vmapped kernel launches.

    This is the production key-level data-parallel axis (SURVEY §2.3; the
    per-key decomposition of ``register.clj:108-119``): tables are padded
    to a shared (K_pad, R_pad, ...) batch, sharded over the device mesh
    along the key axis when more than one device is present (ICI carries
    nothing — keys are independent, so the "collective" layout is a pure
    scatter), and expanded wave-parallel on device. Keys are grouped by
    (R-bucket, I-bucket) — one launch per group — so a single long-history
    key neither inflates every key's padded tables nor forces cold keys
    through its wave count (while_loop under vmap runs until the slowest
    batch element finishes). Per-key overflow falls out of the batch and
    climbs the remaining ladder rungs through ``check_packed``; spill is
    deferred (``{"overflow": True}`` result) so the calling checker can
    interpose its cheaper DFS first.

    Placement (ISSUE 15, the sharded checker service): ``device``
    commits every launch to one chip; ``device_for`` is a per-group
    callback ``group_key -> device | None`` (the service-down fallback
    routes through the service's sticky round-robin map with it);
    ``shard=True`` splits each group's key axis over the whole device
    mesh with shard_map instead of the GSPMD scatter (one oversized
    group); ``prepared`` maps group keys to PreparedGroup host tables
    built ahead by :func:`prepare_bucket_group` (the service's
    double-buffered packing). All default to the historical behavior.

    Returns one result dict per pack, aligned with the input order.
    """
    results: list = [None] * len(packs)
    # MXU wave kernel first: ONE pallas dispatch per R-bucket for every
    # supported key (the tunnel round trip is the dominant device cost,
    # so a single launch for the whole batch is the only device path
    # that competes with the in-process native sweep). Unsupported or
    # overflowing keys fall through to the vmapped jnp path / ladder.
    # f_max set means the caller chose a rung past the fused capacity
    # 32 — the kernel would only overflow again (same guard as
    # check_packed's single-history path). try_fused=False means the
    # caller already ran the fused batch itself (the overlapped
    # pack-and-launch path in TPULinearizableChecker.check_batch) and
    # these packs are its leftovers.
    if f_max is None and try_fused:
        from . import wgl_mxu
        mxu_out = _run_fused(
            _mxu_broken, "mxu batch",
            lambda: wgl_mxu.check_packed_batch_mxu(packs, device=device))
        if mxu_out is not None:
            for i, out in enumerate(mxu_out):
                if out is not None and not out.get("overflow"):
                    results[i] = out
    groups: dict = {}
    for i, p in enumerate(packs):
        if results[i] is not None:
            continue
        if not p.ok:
            results[i] = {"valid?": "unknown", "reason": p.reason,
                          "blowup": p.blowup}
        elif p.R == 0:
            results[i] = {"valid?": True, "waves": 0}
        else:
            groups.setdefault((bucket(p.R), info_dims(p), p.w),
                              []).append(i)
    for (r_pad, info, w), idxs in groups.items():
        dev = device
        if dev is None and device_for is not None:
            dev = device_for((r_pad, info, w))
        prep = None if prepared is None else prepared.get((r_pad, info, w))
        _check_bucket_group(packs, results, idxs, r_pad, info, w, f_max,
                            device=dev, shard=shard, prepared=prep)
    return results


def _check_bucket_group(packs: list, results: list, idxs: list,
                        r_pad: int, info: tuple, w: int,
                        f_max: Optional[int], device=None,
                        shard: bool = False, prepared=None) -> None:
    """One vmapped launch for a same-bucket key group; results written
    in place. ``device`` commits the launch to one chip (the sharded
    checker service's per-group placement); ``shard=True`` splits the
    key axis over the device mesh with shard_map (one oversized group);
    the default keeps the historical behavior — a GSPMD scatter over
    every visible device when more than one exists. ``prepared`` is an
    optional :class:`PreparedGroup` built ahead on the host; it is
    validated against the group and silently rebuilt on any mismatch
    (e.g. the fused MXU path already claimed part of the group)."""
    import jax
    import jax.numpy as jnp

    if len(idxs) == 1 and not shard:
        # a lone pack rides the rung ladder (early exit beats the
        # fixed-f batched kernel) — unless the caller asked to shard,
        # where even one pack pads across the mesh to keep chips warm
        results[idxs[0]] = check_packed(packs[idxs[0]], f_max=f_max,
                                        spill=False, device=device)
        return
    if f_max is None:
        f_max = 128
    K = len(idxs)
    devs = jax.devices()
    if device is not None:
        lanes = 1
    elif shard:
        # always the FULL mesh: the key axis pads up to the lane count,
        # so even a lone pack spreads over every chip (and every chip's
        # executable stays warm for the next single-group tick). The
        # explicit shard_map kernel is reserved for genuinely oversized
        # groups; smaller ones ride the same GSPMD scatter as mixed
        # groups (identical placement, shared compile cache)
        lanes = len(devs)
        shard = lanes > 1 and K >= 2 * lanes
    else:
        lanes = len(devs)
    if prepared is not None and not (
            prepared.n == K and prepared.lanes == lanes
            and prepared.key == (r_pad, info, w)
            and all(packs[i].R == int(prepared.Rs[j])
                    for j, i in enumerate(idxs))):
        prepared = None
    if prepared is None:
        prepared = prepare_bucket_group(packs, idxs, r_pad, info,
                                        lanes=lanes)
    stacked, Rs, Is = prepared.stacked, prepared.Rs, prepared.Is

    if device is not None:
        def put(x):
            return jax.device_put(x, device)
        # committed inputs pin the jit executable to this chip; donated
        # buffers free their memory for the ladder's working set
        # (TPU-only — the CPU runtime warns and ignores donation)
        kern = _batched_kernel_jitted(
            f_max, w,
            donate=(getattr(device, "platform", "") == "tpu"))
    elif lanes > 1:
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
        mesh = Mesh(np.array(devs[:lanes]), ("dp",))

        def put(x):
            s = NamedSharding(mesh, P("dp", *([None] * (x.ndim - 1))))
            return jax.device_put(jnp.asarray(x), s)
        if shard:
            kern = _batched_kernel_sharded(
                f_max, w, lanes,
                tuple(str(d) for d in devs[:lanes]))
        else:
            kern = _batched_kernel_jitted(f_max, w)
    else:
        put = jnp.asarray
        kern = _batched_kernel_jitted(f_max, w)
    tables_dev = {k: put(v) for k, v in stacked.items()}
    tel = telemetry.current()
    for _ in idxs:
        # every key in the group attempts the batch rung (overflowing
        # keys then add their per-key ladder climb via check_packed)
        tel.hist("wgl.rung_waves", f_max)
    with tel.span("wgl.batch-dispatch", keys=K, w=w, f_max=f_max):
        valid, overflow, waves, peak, _frontier = kern(
            tables_dev, put(Rs), put(Is))
        valid = np.asarray(valid)
    overflow = np.asarray(overflow)
    waves = np.asarray(waves)
    peak = np.asarray(peak)
    tel.counter("wgl.dispatches")
    if peak.size:
        tel.counter("wgl.max-frontier", int(peak.max()), mode="max")
    if waves.size:
        tel.counter("wgl.waves", int(waves.max()), mode="max")
    for j, i in enumerate(idxs):
        p = packs[i]
        if overflow[j]:
            # climb the remaining ladder rungs — per key, off the
            # batch; spill is deferred so the checker can interpose
            # its cheaper DFS on top-rung overflow (see
            # TPULinearizableChecker._overflow)
            results[i] = check_packed(p, f_max=F_MAX, spill=False,
                                      device=device)
        else:
            v = bool(valid[j])
            results[i] = {
                "valid?": v, "waves": int(waves[j]),
                "peak-frontier": int(peak[j]), "ops": p.R,
                "info-ops": p.I, "batched": True,
                **({} if v else {"stuck-at-depth": int(waves[j])})}


def check_packed(p: Packed, f_max: Optional[int] = None,
                 spill: bool = True, device=None) -> dict:
    """Telemetry shell around :func:`_check_packed_impl`: one span per
    dispatch (per-dispatch wall time), plus the routing counters a run's
    results.json surfaces (dispatch count, rung total, peak frontier
    width across the run). ``device`` commits the launch to one chip
    (the checker service's per-group placement)."""
    tel = telemetry.current()
    with tel.span("wgl.check_packed", ops=getattr(p, "R", None),
                  w=getattr(p, "w", None)) as sp:
        out = _check_packed_impl(p, f_max=f_max, spill=spill,
                                 device=device)
        sp.set(engine=out.get("engine"), valid=out.get("valid?"),
               rungs=out.get("rungs"), waves=out.get("waves"),
               peak_frontier=out.get("peak-frontier"))
    tel.counter("wgl.dispatches")
    if out.get("rungs"):
        tel.counter("wgl.rungs", out["rungs"])
    if out.get("waves"):
        tel.counter("wgl.waves", out["waves"], mode="max")
    if out.get("peak-frontier"):
        tel.counter("wgl.max-frontier", out["peak-frontier"], mode="max")
    return out


def _check_packed_impl(p: Packed, f_max: Optional[int] = None,
                       spill: bool = True, device=None) -> dict:
    """Run the kernel on one packed history (host->device->host).

    f_max defaults small (tiny sorts, fast waves — healthy frontiers
    peak in the tens). On overflow the frozen pre-expansion frontier
    RESUMES at the next LADDER rung (32 -> ... -> 4096) — earlier waves
    are never redone, and the search settles at the smallest rung that
    fits its peak frontier. Past the top rung the host-driven chunked
    spill BFS takes over from the same frontier — unless ``spill=False``,
    which instead returns ``{"valid?": "unknown", "overflow": True}``
    so the caller can try a cheaper engine first (a DFS needs one
    witness path where this BFS carries the whole frontier; see
    TPULinearizableChecker's fallback ordering).
    """
    import jax.numpy as jnp

    if not p.ok:
        return {"valid?": "unknown", "reason": p.reason,
                "blowup": p.blowup}
    if p.R == 0:
        return {"valid?": True, "waves": 0}
    if f_max is None and \
            not os.environ.get("JEPSEN_ETCD_TPU_NO_PALLAS_WGL"):
        # f_max set means an overflow-retry path chose a rung past the
        # fused kernels' capacity 32 — launching them would only
        # overflow again.
        # Engine order on real TPU: the MXU wave kernel (ops/wgl_mxu.py
        # — one table stream, matmul compaction, ~6x the r3 fused
        # kernel end-to-end at 50k scale), then the complete jnp
        # ladder. A Mosaic failure in the kernel degrades to the
        # ladder and disables the kernel for the process. (The r3
        # pick-loop kernel was retired in r5: its supported shapes were
        # a strict subset of the MXU kernel's, and both are Mosaic
        # kernels, so it could only ever run in the vanishing window
        # where one Mosaic compile fails and the other succeeds — the
        # jnp ladder is the real backstop either way.)
        # Real-chip only: in interpret mode (CPU CI) the fused kernel
        # is python-slow, and its correctness is pinned directly by
        # tests/test_wgl_mxu.py
        from . import wgl_mxu
        out = _run_fused(_mxu_broken, "mxu wave",
                         lambda: wgl_mxu.check_packed_mxu(p,
                                                          device=device))
        if out is not None and not out.get("overflow"):
            return out
    # f_max (when given) is the STARTING rung; the ladder still
    # escalates past it on overflow before spilling
    if f_max is None:
        ladder = LADDER
    else:
        ladder = [f_max] + [f for f in LADDER if f > f_max]
    if p.w == W_MAX:
        # W=128 kernels compile slowly and their overflows are almost
        # always combinatorial blowup: cap the in-kernel ladder (and
        # skip the 256 rung — one fewer multi-minute w=128 compile on a
        # path that nearly always ends at the DFS anyway) and let the
        # DFS-first overflow path (TPULinearizableChecker._overflow)
        # take it from there
        ladder = [f for f in ladder
                  if f <= F_MAX and f != 256] or [ladder[0]]
    _c_pad, ni, _i_tab = info_dims(p)
    if device is not None:
        import jax

        def _put(x):
            # committed inputs pin every ladder rung to this chip;
            # uncommitted scalars follow the committed operands
            return jax.device_put(x, device)
    else:
        _put = jnp.asarray
    tables = {k: _put(np.asarray(v))
              for k, v in pad_tables(p, bucket(p.R)).items()}
    R_, I_ = jnp.int32(p.R), jnp.int32(p.I)
    peak_all = 1
    nw = p.w // 32
    d0 = np.full((ladder[0],), SENTINEL_D, dtype=np.int32)
    d0[0] = 0
    w0 = np.full((ladder[0], nw), SENTINEL_W, dtype=np.uint32)
    w0[0] = 0
    i0 = np.zeros((ladder[0], ni), dtype=np.uint32)
    v0 = np.full((ladder[0],), SENTINEL_V, dtype=np.int32)
    v0[0] = NONE_VAL
    # one histogram sample per rung ATTEMPT, value = the rung's
    # frontier budget: log2 buckets give each rung its own bucket, so
    # bucket counts read as "dispatches that reached this search
    # depth" (the guided coverage vector's wave-histogram feature)
    telemetry.current().hist("wgl.rung_waves", ladder[0])
    valid, overflow, k, peak, frontier = _kernel_resume_jitted(
        ladder[0], p.w)(tables, R_, I_, jnp.int32(0),
                        _put(d0), _put(w0),
                        _put(i0), _put(v0),
                        jnp.int32(1))
    peak_all = max(peak_all, int(peak))
    rungs = 1
    for f_next in ladder[1:]:
        if not bool(overflow):
            break
        rungs += 1
        telemetry.current().hist("wgl.rung_waves", f_next)
        # pad the frozen frontier to the next rung and resume in place
        dvec, wvec, ivec, vvec, n_alive = frontier
        f_cur = dvec.shape[0]
        grow = f_next - f_cur
        # graftlint: ignore[JAX001] rung ladder: pads at most len(ladder)-1 times per key
        d0 = jnp.concatenate([dvec, jnp.full((grow,), SENTINEL_D,
                                             dtype=jnp.int32)])
        # graftlint: ignore[JAX001] rung ladder: pads at most len(ladder)-1 times per key
        w0 = jnp.concatenate([wvec, jnp.full((grow, wvec.shape[1]),
                                             SENTINEL_W,
                                             dtype=jnp.uint32)])
        # graftlint: ignore[JAX001] rung ladder: pads at most len(ladder)-1 times per key
        i0 = jnp.concatenate([ivec, jnp.zeros((grow, ivec.shape[1]),
                                              dtype=jnp.uint32)])
        # graftlint: ignore[JAX001] rung ladder: pads at most len(ladder)-1 times per key
        v0 = jnp.concatenate([vvec, jnp.full((grow,), SENTINEL_V,
                                             dtype=jnp.int32)])
        valid, overflow, k, peak, frontier = _kernel_resume_jitted(
            f_next, p.w)(tables, R_, I_, k, d0, w0, i0, v0, n_alive)
        peak_all = max(peak_all, int(peak))
    valid = bool(valid)
    if bool(overflow):
        if not spill:
            # hand back the frozen frontier so the caller's eventual
            # spill RESUMES here instead of re-climbing the ladder
            # (earlier waves are never redone — module contract)
            return {"valid?": "unknown", "overflow": True,
                    "reason": "frontier overflow past the top rung",
                    "peak-frontier": peak_all, "ops": p.R,
                    "info-ops": p.I, "rungs": rungs,
                    "engine": "jnp-ladder",
                    "_resume": (tables, frontier, int(k))}
        out = spill_packed(p, tables, frontier, int(k))
        out["peak-frontier"] = max(peak_all, out.get("peak-frontier", 0))
        out["rungs"] = rungs
        out.setdefault("engine", "jnp-ladder")
        return out
    return {"valid?": valid, "waves": int(k), "peak-frontier": peak_all,
            "ops": p.R, "info-ops": p.I, "rungs": rungs,
            "engine": "jnp-ladder",
            **({} if valid else {"stuck-at-depth": int(k)})}


# ---------------------------------------------------------------------------
# chunked frontier resume (streaming / soak)


class FrontierState:
    """Opaque resumable cursor for :func:`check_prefix`: the device
    tables, the frozen pre-expansion frontier, the cumulative wave
    counter, the current ladder rung and the run accounting. ``done``
    flips once the search concludes; ``result`` then holds the same
    dict the one-shot ladder (:func:`check_packed`) produces."""

    __slots__ = ("p", "tables", "R_", "I_", "ladder", "rung_i", "k",
                 "frontier", "peak", "rungs", "waves_run", "done",
                 "result", "spill")

    def __init__(self):
        self.done = False
        self.result = None
        self.waves_run = 0


def check_prefix(p: Packed, state: Optional[FrontierState] = None,
                 max_waves: int = 64,
                 spill: bool = True) -> FrontierState:
    """Chunked form of the WGL ladder: advance the BFS by at most
    ``max_waves`` waves and return the (possibly finished) frontier
    state — ``check_prefix(packed, state) -> state``, the streaming /
    soak monitor API. Call with ``state=None`` to start; poll
    ``state.done`` / ``state.result``.

    Exactness: the wave budget only chooses WHERE the loop pauses —
    frontier contents, rung escalations (each counted on the
    ``stream.resume_rungs`` telemetry counter), spill hand-off and the
    final verdict dict are bit-identical to ``check_packed``'s jnp
    ladder for every budget, including ``max_waves`` larger than the
    whole search (tests/test_stream.py pins this across budgets).
    The MXU fused path is not attempted here — chunked pausing is a
    host-driven loop by construction; production one-shot checks keep
    their fused routing."""
    import jax.numpy as jnp

    if state is None:
        state = FrontierState()
        state.p = p
        state.spill = spill
        if not p.ok:
            state.done = True
            state.result = {"valid?": "unknown", "reason": p.reason,
                            "blowup": p.blowup}
            return state
        if p.R == 0:
            state.done = True
            state.result = {"valid?": True, "waves": 0}
            return state
        ladder = LADDER
        if p.w == W_MAX:
            # same rung cap as _check_packed_impl: W=128 compiles are
            # expensive and top out at the DFS/spill hand-off anyway
            ladder = [f for f in ladder
                      if f <= F_MAX and f != 256] or [ladder[0]]
        state.ladder = ladder
        _c_pad, ni, _i_tab = info_dims(p)
        state.tables = {k: jnp.asarray(v)
                        for k, v in pad_tables(p, bucket(p.R)).items()}
        state.R_, state.I_ = jnp.int32(p.R), jnp.int32(p.I)
        state.rung_i = 0
        nw = p.w // 32
        d0 = np.full((ladder[0],), SENTINEL_D, dtype=np.int32)
        d0[0] = 0
        w0 = np.full((ladder[0], nw), SENTINEL_W, dtype=np.uint32)
        w0[0] = 0
        i0 = np.zeros((ladder[0], ni), dtype=np.uint32)
        v0 = np.full((ladder[0],), SENTINEL_V, dtype=np.int32)
        v0[0] = NONE_VAL
        state.frontier = (jnp.asarray(d0), jnp.asarray(w0),
                          jnp.asarray(i0), jnp.asarray(v0),
                          jnp.int32(1))
        state.k = jnp.int32(0)
        state.peak = 1
        state.rungs = 1
        # rung ATTEMPT sample (not per budget chunk: a rung entered
        # once is one search-depth observation however often the wave
        # budget pauses inside it)
        telemetry.current().hist("wgl.rung_waves", ladder[0])
    if state.done:
        return state
    p = state.p
    dvec, wvec, ivec, vvec, n_alive = state.frontier
    k_before = int(state.k)
    k_stop = jnp.int32(k_before + max(1, max_waves))
    valid, overflow, k, peak, frontier = _kernel_budget_jitted(
        state.ladder[state.rung_i], p.w)(
            state.tables, state.R_, state.I_, k_stop,
            state.k, dvec, wvec, ivec, vvec, n_alive)
    state.peak = max(state.peak, int(peak))
    state.k, state.frontier = k, frontier
    state.waves_run += int(k) - k_before
    if bool(overflow):
        if state.rung_i + 1 < len(state.ladder):
            # climb one rung: pad the frozen pre-expansion frontier in
            # place, exactly like the one-shot ladder — earlier waves
            # are never redone (module contract)
            state.rung_i += 1
            state.rungs += 1
            telemetry.current().counter("stream.resume_rungs")
            f_next = state.ladder[state.rung_i]
            telemetry.current().hist("wgl.rung_waves", f_next)
            dvec, wvec, ivec, vvec, n_alive = frontier
            grow = f_next - dvec.shape[0]
            state.frontier = (
                jnp.concatenate([dvec, jnp.full(
                    (grow,), SENTINEL_D, dtype=jnp.int32)]),
                jnp.concatenate([wvec, jnp.full(
                    (grow, wvec.shape[1]), SENTINEL_W,
                    dtype=jnp.uint32)]),
                jnp.concatenate([ivec, jnp.zeros(
                    (grow, ivec.shape[1]), dtype=jnp.uint32)]),
                jnp.concatenate([vvec, jnp.full(
                    (grow,), SENTINEL_V, dtype=jnp.int32)]),
                n_alive)
            return state
        # past the top rung: spill (complete last resort) or hand the
        # frozen frontier back, mirroring check_packed's contract
        state.done = True
        if state.spill:
            out = spill_packed(p, state.tables, state.frontier,
                               int(state.k))
            out["peak-frontier"] = max(state.peak,
                                       out.get("peak-frontier", 0))
            out["rungs"] = state.rungs
            out.setdefault("engine", "jnp-ladder")
            state.result = out
        else:
            state.result = {
                "valid?": "unknown", "overflow": True,
                "reason": "frontier overflow past the top rung",
                "peak-frontier": state.peak, "ops": p.R,
                "info-ops": p.I, "rungs": state.rungs,
                "engine": "jnp-ladder",
                "_resume": (state.tables, state.frontier,
                            int(state.k))}
        return state
    valid = bool(valid)
    k_i = int(state.k)
    if valid or int(frontier[4]) == 0 or k_i >= p.R + p.I + 1:
        state.done = True
        state.result = {
            "valid?": valid, "waves": k_i, "peak-frontier": state.peak,
            "ops": p.R, "info-ops": p.I, "rungs": state.rungs,
            "engine": "jnp-ladder",
            **({} if valid else {"stuck-at-depth": k_i})}
    return state
