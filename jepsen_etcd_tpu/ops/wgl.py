"""Linearizability search as a TPU frontier BFS (the north-star kernel).

Replaces Knossos' CPU Wing-Gong/Lowe search (reference binding at
``register.clj:110-112``) for versioned-register histories. The key
insight making the search TPU-shaped: in a history with bounded
concurrency, sort the must-linearize (:ok) ops by invocation; then any
reachable "linearized set" consists of a *forced prefix* plus a bitmask
over a sliding window of at most W undecided ops. A search state packs to

    (depth d, uint32 window mask, model value id)

and a BFS wave over depth d is a dense [F, W] tensor expansion:
- enabled = window bit clear ∧ precomputed predecessor-mask bits set,
- model step = table-driven versioned-register transition
  (version is *derived*: forced-prefix update count + popcount of update
  bits in the window — no per-state version storage),
- window slide = shift by (lo[d+1]-lo[d]) with shifted-out-bits-must-be-
  set pruning,
- dedup = 2-key lax.sort + neighbor-compare + scatter compaction.

The wave loop is a lax.while_loop; all shapes are static (F_MAX x W), so
one compile serves all histories of a bucketed length. Overflow (frontier
beyond F_MAX) or window overflow (> W concurrent undecided ops) returns
UNKNOWN and the caller falls back to the CPU oracle
(checkers/linearizable.py) — the TPU fast path never *wrongly* answers.

Histories containing :info (indefinite) ops currently take the CPU path:
an info op may linearize at any point or never, which breaks the
forced-prefix invariant. (Planned: separate persistent info-bit words.)
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Any, Optional

import numpy as np

from ..checkers.linearizable import Entry, history_entries

W = 32          # window width (max undecided concurrent required ops)
F_MAX = 512     # frontier capacity per wave
SENTINEL_W = np.uint32(0xFFFFFFFF)
SENTINEL_V = np.int32(2 ** 31 - 1)

READ, WRITE, CAS = 0, 1, 2
NO_ASSERT = -(2 ** 30)  # distinct from any real (possibly corrupted) version
NONE_VAL = 0     # value id for "key unset"
WILDCARD = -1    # read asserted nothing


@dataclass
class Packed:
    """Host-packed tables for one key's history."""

    ok: bool
    reason: str = ""
    R: int = 0
    n_values: int = 0
    # all [R, W] unless noted
    shift: Any = None         # [R] int32
    static_ok: Any = None     # [R, W] bool
    f_code: Any = None        # [R, W] int8
    a1: Any = None            # [R, W] int32 (read: rval / write: wval / cas: old)
    a2: Any = None            # [R, W] int32 (cas: new)
    ver: Any = None           # [R, W] int32 (version assertion or -1)
    pred_frame: Any = None    # [R, W] uint32
    upd_mask: Any = None      # [R] uint32
    u_forced: Any = None      # [R] int32


def pack_register_history(history, value_ids: Optional[dict] = None,
                          w: int = W) -> Packed:
    """Build the per-depth tables for the kernel. Returns ok=False with a
    reason when the history needs the CPU path."""
    entries = history_entries(history)
    infos = [e for e in entries if not e.required]
    if infos:
        return Packed(ok=False, reason=f"{len(infos)} info ops (CPU path)")
    req = sorted([e for e in entries if e.required], key=lambda e: e.invoke)
    R = len(req)
    if R == 0:
        return Packed(ok=True, R=0)

    # value id mapping: 0 = None (unset); concrete values from 1
    vid = dict(value_ids or {})

    def val_id(v):
        if v is None:
            return NONE_VAL
        key = repr(v)
        if key not in vid:
            vid[key] = max(vid.values(), default=NONE_VAL) + 1
        return vid[key]

    inv = np.array([e.invoke for e in req], dtype=np.int64)
    ret = np.array([e.ret for e in req], dtype=np.int64)
    f = np.zeros(R, dtype=np.int8)
    a1 = np.zeros(R, dtype=np.int32)
    a2 = np.zeros(R, dtype=np.int32)
    ver = np.full(R, NO_ASSERT, dtype=np.int32)
    for i, e in enumerate(req):
        if e.f == "read":
            f[i] = READ
            rv, rval = e.value if e.value is not None else (None, None)
            ver[i] = NO_ASSERT if rv is None else int(rv)
            # A None read value asserts nothing (VersionedRegister.step
            # treats nil op-value as unchecked REGARDLESS of version —
            # an unset-key read [0, None] is constrained via version 0).
            a1[i] = WILDCARD if rval is None else val_id(rval)
        elif e.f == "write":
            f[i] = WRITE
            wv, wval = e.value
            ver[i] = NO_ASSERT if wv is None else int(wv)
            a1[i] = val_id(wval)
        elif e.f == "cas":
            f[i] = CAS
            cv, (old, new) = e.value
            ver[i] = NO_ASSERT if cv is None else int(cv)
            a1[i] = val_id(old)
            a2[i] = val_id(new)
        else:
            return Packed(ok=False, reason=f"op f={e.f!r} not supported")

    sorted_ret = np.sort(ret)
    pred = np.searchsorted(sorted_ret, inv, side="left")  # ret[j] < inv[i]
    cap = np.searchsorted(inv, ret, side="left") - 1      # inv[j] < ret[i], j != i

    # lo[d] = first rank that can still be absent from a depth-d prefix
    lo = np.zeros(R + 1, dtype=np.int64)
    p = 0
    for d in range(R + 1):
        while p < R and cap[p] < d:
            p += 1
        lo[d] = p
    # feasibility: window must hold all set bits and all enabled candidates
    width_bits = np.max(np.arange(R + 1) - lo) if R else 0
    first_lo = lo[np.minimum(pred, R)]
    width_cand = np.max(np.arange(R) - first_lo) + 1 if R else 0
    if max(width_bits, width_cand) > w:
        return Packed(ok=False,
                      reason=f"window {max(width_bits, width_cand)} > {w} "
                             f"(concurrency too high for kernel)")

    d_idx = np.arange(R)[:, None]                       # [R, 1]
    b_idx = np.arange(w)[None, :]                       # [1, W]
    idx = np.minimum(lo[:R][:, None] + b_idx, R - 1)    # [R, W] clamped
    in_range = (lo[:R][:, None] + b_idx) < R
    static_ok = in_range & (pred[idx] <= d_idx)

    # predecessor bits within the frame: bit c <-> rank lo[d]+c
    ret_frame = ret[idx]                                      # [R, W]
    inv_cand = inv[idx]                                       # [R, W]
    is_pred = (ret_frame[:, None, :] < inv_cand[:, :, None])  # [R, W, W]
    in_range_c = ((lo[:R][:, None] + b_idx) < R)[:, None, :]  # [R, 1, W]
    bits = (1 << np.arange(w, dtype=np.uint64))
    pred_frame = ((is_pred & in_range_c) * bits).sum(-1).astype(np.uint32)

    is_upd = (f == WRITE) | (f == CAS)
    upd_frame = is_upd[idx] & in_range
    upd_mask = (upd_frame * bits).sum(-1).astype(np.uint32)
    cum_upd = np.concatenate([[0], np.cumsum(is_upd)])
    u_forced = cum_upd[lo[:R]].astype(np.int32)

    return Packed(
        ok=True, R=R, n_values=len(vid) + 1,
        shift=(lo[1:] - lo[:-1]).astype(np.int32),
        static_ok=static_ok,
        f_code=f[idx].astype(np.int8),
        a1=a1[idx], a2=a2[idx], ver=ver[idx],
        pred_frame=pred_frame, upd_mask=upd_mask, u_forced=u_forced,
    )


# ---------------------------------------------------------------------------
# the kernel


@functools.lru_cache(maxsize=None)
def _kernel_jitted(f_max: int, w: int):
    import jax
    return jax.jit(functools.partial(_wgl_kernel, f_max=f_max, w=w))


def _wgl_kernel(tables: dict, R, f_max: int = F_MAX, w: int = W):
    """Run the wave loop. tables hold the [R_pad, W] arrays; R is the
    dynamic number of waves. Returns (valid, overflow, waves_done,
    frontier_size_max)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    shift = tables["shift"]
    static_ok = tables["static_ok"]
    f_code = tables["f_code"]
    a1 = tables["a1"]
    a2 = tables["a2"]
    ver = tables["ver"]
    pred_frame = tables["pred_frame"]
    upd_mask = tables["upd_mask"]
    u_forced = tables["u_forced"]

    bpos = jnp.arange(w, dtype=jnp.uint32)[None, :]        # [1, W]
    bit = (jnp.uint32(1) << bpos)

    def body(carry):
        d, wmask, val, n_alive, overflow, peak = carry
        # vmap-safety guard: under vmap, while_loop runs until ALL batch
        # elements finish; finished elements must be no-ops.
        active = (d < R) & (n_alive > 0) & (~overflow)
        # row d of each table
        row = lambda t: lax.dynamic_index_in_dim(t, d, 0, keepdims=False)
        s_ok = row(static_ok)[None, :]                      # [1, W]
        fc = row(f_code)[None, :]
        ra1 = row(a1)[None, :]
        ra2 = row(a2)[None, :]
        rver = row(ver)[None, :]
        rpred = row(pred_frame)[None, :]
        rupd = row(upd_mask)
        ruf = row(u_forced)
        rshift = row(shift).astype(jnp.uint32)

        alive = (jnp.arange(f_max) < n_alive)[:, None]      # [F, 1]
        wm = wmask[:, None]                                 # [F, 1]
        not_set = ((wm >> bpos) & 1) == 0
        preds_in = (wm & rpred) == rpred
        version = ruf + lax.population_count(wm & rupd).astype(jnp.int32)
        v = val[:, None]                                    # [F, 1]

        is_read = fc == READ
        is_write = fc == WRITE
        is_cas = fc == CAS
        no_assert = rver == NO_ASSERT
        ver_ok = jnp.where(is_read,
                           no_assert | (rver == version),
                           no_assert | (rver == version + 1))
        read_ok = is_read & ((ra1 == WILDCARD) | (ra1 == v))
        cas_ok = is_cas & (ra1 == v)
        model_ok = read_ok | is_write | cas_ok
        valid = alive & s_ok & not_set & preds_in & ver_ok & model_ok

        new_w = wm | bit                                    # [F, W]
        # shift may equal w (whole window forced at once); uint32 << 32
        # is implementation-defined, so saturate explicitly
        full_slide = rshift >= jnp.uint32(w)
        low_mask = jnp.where(full_slide, jnp.uint32(0xFFFFFFFF),
                             (jnp.uint32(1) << rshift) - jnp.uint32(1))
        slide_ok = (new_w & low_mask) == low_mask
        valid = valid & slide_ok
        new_w = jnp.where(full_slide, jnp.uint32(0), new_w >> rshift)
        new_v = jnp.where(is_read, v,
                          jnp.where(is_write, ra1, ra2)).astype(jnp.int32)

        # dedup: sort flattened (w, v) with sentinels for invalid slots
        flat_w = jnp.where(valid, new_w, jnp.uint32(SENTINEL_W)).reshape(-1)
        flat_v = jnp.where(valid, new_v, SENTINEL_V).reshape(-1)
        sw, sv = lax.sort((flat_w, flat_v), num_keys=2)
        is_real = sw != jnp.uint32(SENTINEL_W)
        first = jnp.concatenate([
            jnp.array([True]),
            (sw[1:] != sw[:-1]) | (sv[1:] != sv[:-1])])
        uniq = is_real & first
        pos = jnp.cumsum(uniq.astype(jnp.int32)) - 1
        n_new = jnp.sum(uniq.astype(jnp.int32))
        pos = jnp.where(uniq & (pos < f_max), pos, f_max)   # drop overflowed
        out_w = jnp.full((f_max + 1,), SENTINEL_W, dtype=jnp.uint32)
        out_v = jnp.full((f_max + 1,), SENTINEL_V, dtype=jnp.int32)
        out_w = out_w.at[pos].set(sw, mode="drop")
        out_v = out_v.at[pos].set(sv, mode="drop")
        out_w = out_w[:f_max]
        out_v = out_v[:f_max]
        return (jnp.where(active, d + 1, d),
                jnp.where(active, out_w, wmask),
                jnp.where(active, out_v, val),
                jnp.where(active, jnp.minimum(n_new, f_max), n_alive),
                jnp.where(active, overflow | (n_new > f_max), overflow),
                jnp.where(active, jnp.maximum(peak, n_new), peak))

    def cond(carry):
        d, _, _, n_alive, overflow, _ = carry
        return (d < R) & (n_alive > 0) & (~overflow)

    w0 = jnp.full((f_max,), SENTINEL_W, dtype=jnp.uint32)
    w0 = w0.at[0].set(0)
    v0 = jnp.full((f_max,), SENTINEL_V, dtype=jnp.int32)
    v0 = v0.at[0].set(NONE_VAL)
    init = (jnp.int32(0), w0, v0, jnp.int32(1), jnp.bool_(False),
            jnp.int32(1))
    d, _, _, n_alive, overflow, peak = lax.while_loop(cond, body, init)
    valid = (d >= R) & (n_alive > 0) & (~overflow)
    return valid, overflow, d, peak


def bucket(n: int) -> int:
    """Pad R to a power-of-two bucket so jit caches stay warm."""
    b = 16
    while b < n:
        b *= 2
    return b


def pad_tables(p: Packed, r_pad: int):
    """Pad the per-depth tables to a bucketed length (shared by
    check_packed and the __graft_entry__ paths)."""
    def padded(a, fill=0):
        out = np.full((r_pad,) + a.shape[1:], fill, dtype=a.dtype)
        out[:p.R] = a
        return out

    return {
        "shift": padded(p.shift), "static_ok": padded(p.static_ok),
        "f_code": padded(p.f_code), "a1": padded(p.a1), "a2": padded(p.a2),
        "ver": padded(p.ver), "pred_frame": padded(p.pred_frame),
        "upd_mask": padded(p.upd_mask), "u_forced": padded(p.u_forced),
    }


def check_packed(p: Packed, f_max: Optional[int] = None) -> dict:
    """Run the kernel on one packed history (host->device->host).

    f_max defaults small for short histories (tiny sorts, fast waves) —
    an overflow retries at full capacity before falling back to CPU.
    """
    import jax.numpy as jnp

    if not p.ok:
        return {"valid?": "unknown", "reason": p.reason}
    if p.R == 0:
        return {"valid?": True, "waves": 0}
    if f_max is None:
        # frontiers are tiny on healthy histories (peak ~tens); start
        # small — sorts are 4x cheaper — and retry at F_MAX on overflow
        f_max = 128
    tables = {k: jnp.asarray(v)
              for k, v in pad_tables(p, bucket(p.R)).items()}
    valid, overflow, d, peak = _kernel_jitted(f_max, W)(
        tables, jnp.int32(p.R))
    valid = bool(valid)
    overflow = bool(overflow)
    if overflow and f_max < F_MAX:
        return check_packed(p, f_max=F_MAX)  # retry at full capacity
    if overflow:
        return {"valid?": "unknown", "reason": "frontier overflow",
                "peak-frontier": int(peak)}
    return {"valid?": valid, "waves": int(d), "peak-frontier": int(peak),
            "ops": p.R,
            **({} if valid else {"stuck-at-depth": int(d)})}
