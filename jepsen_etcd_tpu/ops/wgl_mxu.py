"""MXU-compacted Pallas wave kernel for the WGL frontier BFS.

Second-generation fused kernel (supersedes ops/wgl_pallas.py on its
shape class: W <= 32 window, no info ops). The r3 kernel's cost was
measured to be dominated by vector->scalar round trips in its greedy
dedupe pick loop (~1.2 us per pick on a v5e through axon) plus one
DMA-visible stream per table; this kernel's wave body contains ZERO
vector->scalar reductions and one table stream:

- the frontier lives in packed (8, 128) int32 planes: candidate
  (op o, state s) sits at position (p, q) with s = 8*(q//32) + p and
  o = q % 32 — 32 states x 32 window ops = 1024 candidate slots in
  ONE vreg per payload plane;
- per-depth tables ship as ONE consolidated [R_pad, 256] int16 array
  (a1/a2 value ids biased +1, version and ceiling RELATIVE to the
  row's forced-update count so they fit int16, predecessor mask split
  16/16) — one HBM stream instead of eight, half the host->device
  bytes of the r3 layout (the axon tunnel moves ~0.5-1 GB/s, so
  transfer bytes are first-order);
- successor compaction is dedupe-FREE: candidates get dense ranks
  from a log-shift prefix sum (pltpu.roll — all vector domain), and
  an MXU one-hot matmul scatters payloads into frontier rows. The
  window mask rides two f32 matmuls (16 bits each — f32 holds <= 2^16
  exactly), value ids one (gated n_values < 2^16). Without dedupe,
  states converging to the same (window, value) occupy multiple rows;
  that only costs capacity (overflow -> the complete jnp ladder),
  never soundness — BFS acceptance is witness-based;
- acceptance / overflow / peak-frontier / waves are carried as VECTOR
  flag planes folded elementwise each wave and decoded on host from
  the final (32, 128) output block. The only scalar sync is a
  frontier-death check every DONE_EVERY waves, which lets finished
  (or padding) grid steps skip the body.

Measured on the 10k-op register history (v5e through axon): ~2.5 us
per wave vs ~7 us (r3 pick-loop kernel) vs ~100 us (jnp ladder), with
host->device bytes halved. The batched variant runs K keys in ONE
pallas dispatch (grid (K, R_pad)) — one tunnel round trip total,
which is what makes the TPU competitive with the in-process native
DFS sweep on the key-DP axis (SURVEY §2.3, register.clj:108-119).

Soundness contract: definitive answers only. accepted=True is
witnessed by a surviving path (valid even if earlier waves
overflowed); accepted=False is only reported when no wave overflowed;
anything else degrades to {"overflow": True} and the caller's
complete ladder. Differentially fuzzed against the jnp kernel and
both CPU oracles in tests/test_wgl_mxu.py.

Reference role: hot path of the Knossos-equivalent checker
(register.clj:110-112); the reference has no analog (Knossos is a JVM
heap search).
"""

from __future__ import annotations

import functools

import numpy as np

from .wgl import (CAS, NO_ASSERT, READ, WRITE, WILDCARD,
                  Packed, bucket)

F = 32            # frontier capacity (states; no-dedupe rows)
W = 32            # window width (one 32-bit mask)
SEG = 128 // W    # states per packed sublane row (4)
NP = 8 * 128      # packed candidate slots
TLANES = 128      # int32 table lanes: 4 segments of 32, two 16-bit
                  # attrs per lane (int16 memrefs can't take dynamic
                  # sublane loads, so attrs pair up inside int32 lanes)
TSUB = 8          # int32 block sublane tile
DONE_EVERY = 8    # waves between frontier-death scalar checks
V_SENT = np.int16(-32768)   # "never matches" relative version
C_INF = np.int16(32767)     # "no ceiling" relative ceiling
VAL_MAX = 2 ** 16 - 3       # value-id budget (uint16 biased +1)

# lane-segment layout: segment g (lanes 32g..32g+32) holds the attr
# pair (low 16 bits | high 16 bits)
G_A1A2, G_VERCEIL, G_PRED, G_FSK = range(4)
# 8-bit payload limbs through the compaction matmul
L_W0, L_W1, L_W2, L_W3, L_V0, L_V1, L_FILL = range(7)
PL = 7
# int32 SMEM scal columns
S_SHIFT, S_CEILB, S_UPD, S_R = range(4)
# output plane rows (each flag is an (8,128) plane in the (32,128) out)
O_ACC, O_OVF, O_PEAK, O_WAVES = range(4)


def supported(p: Packed) -> bool:
    """Preconditions: packed OK, one mask word, no info ops, value ids
    and history length within the uint16 shipping budget (others fall
    back to the jnp ladder)."""
    return (bool(p.ok) and p.w == W and p.I == 0 and p.R > 0
            and p.n_values < VAL_MAX and p.R < 65000)


def pack_tables(p: Packed, r_pad: int):
    """Consolidate a Packed's per-depth frames into the kernel's
    [r_pad, 256] int16 table + [r_pad, 4] int32 scal (see layout
    above). Relative encodings keep everything in int16 soundly:
    a row-d frame entry can only be satisfied while the state's
    version sits in [u_forced[d], u_forced[d] + W], so version
    assertions and ceilings are stored relative to u_forced[d] and
    out-of-range assertions become the never-matching sentinel."""
    R = p.R
    uf = p.u_forced.astype(np.int64)                      # [R]
    tab = np.zeros((r_pad, TLANES), dtype=np.int32)

    def pair(lo_u16, hi_u16):
        return (lo_u16.astype(np.uint32)
                | (hi_u16.astype(np.uint32) << 16)).view(np.int32)

    def seg(g):
        return tab[:R, 32 * g:32 * g + 32]

    a1u = np.where(p.a1 == WILDCARD, 0,
                   p.a1 + 1).astype(np.uint16)            # biased
    a2u = (p.a2 + 1).astype(np.uint16)
    seg(G_A1A2)[...] = pair(a1u, a2u)
    # CANONICAL relative encodings (shared with the device builder —
    # the bit-identity contract requires one rule, not two clippings):
    # a reachable relative version is 0..W+1, so any assertion outside
    # [-1, W+1] maps to the never-matching -32767; ceilings prune via
    # version <= ceil with version in [0, W], so values clamp into
    # [-1, W+1] (any value past W prunes nothing, any below 0 prunes
    # everything)
    rel = p.ver.astype(np.int64) - uf[:, None]
    rel = np.where((rel < -1) | (rel > W + 1), -32767, rel)
    rel = np.where(p.ver == NO_ASSERT, V_SENT, rel).astype(np.int16)
    relc = np.clip(p.ceil_frame.astype(np.int64) - uf[:, None],
                   -1, W + 1)
    relc = np.where(p.ceil_frame >= 2 ** 30, C_INF, relc).astype(np.int16)
    seg(G_VERCEIL)[...] = pair(rel.view(np.uint16), relc.view(np.uint16))
    pred = p.pred_frame[:, :, 0]                          # [R, W] uint32
    seg(G_PRED)[...] = pred.view(np.int32)                # full 32 bits
    fsk = np.where(p.static_ok, p.f_code.astype(np.uint16) + 1,
                   0).astype(np.uint16)
    seg(G_FSK)[...] = pair(fsk, np.zeros_like(fsk))

    scal = np.zeros((r_pad, 4), dtype=np.int32)
    scal[:R, S_SHIFT] = p.shift
    cb = np.clip(p.ceil_beyond.astype(np.int64) - uf, -1, W + 1)
    scal[:R, S_CEILB] = np.where(p.ceil_beyond >= 2 ** 30, 2 ** 30, cb)
    scal[:R, S_UPD] = p.upd_mask[:, 0].view(np.int32)
    scal[:, S_R] = R
    return tab, scal


# per-op compact shipping format (device-side frame building): the
# [R, W] frames are pure gathers over per-op vectors (see
# wgl._pack_register_history), so the host ships ~32 B/op and a jitted
# builder materializes the [r_pad, 128] table in HBM — the axon tunnel
# moves ~30-50 MB/s under honest sync, so shipping frames (~512 B/op)
# dominated every check
U16_NOASSERT = 65535
U16_INF = 65534
U16_NEVER = 65533   # version assertion that can never match
# uint16 col layout
C_A1, C_A2, C_VER, C_FSK1, C_PRED, C_CEIL, C_LO, C_SHIFT, C_CEILB, \
    C_UF, C_R, C_SPARE = range(12)


def pack_perop(p: Packed, r_pad: int):
    """Compact per-op arrays for the device frame builder: int32
    [r_pad, 4] (invoke/return time ranks) + uint16 [r_pad, 12]."""
    R = p.R
    i32 = np.zeros((r_pad, 4), dtype=np.int32)
    i32[:R, 0] = p.inv_rank
    i32[:R, 1] = p.ret_rank
    u16 = np.zeros((r_pad, 12), dtype=np.uint16)
    u16[:R, C_A1] = np.where(p.op_a1 == WILDCARD, 0, p.op_a1 + 1)
    u16[:R, C_A2] = p.op_a2 + 1
    # version assertions outside [0, 65000) (negative / huge — e.g. a
    # corrupted read version) can never match a reachable version;
    # ship the NEVER marker so the device builder emits the same
    # canonical -32767 as pack_tables
    u16[:R, C_VER] = np.where(
        p.op_ver == NO_ASSERT, U16_NOASSERT,
        np.where((p.op_ver < 0) | (p.op_ver >= 65000), U16_NEVER,
                 p.op_ver + 1))
    u16[:R, C_FSK1] = p.op_f.astype(np.uint16) + 1
    u16[:R, C_PRED] = np.clip(p.op_pred_rank, 0, 65533)
    # ceilings are >= -1 (version - 1 of a version-0 update): bias +1
    u16[:R, C_CEIL] = np.where(p.op_ceiling >= 2 ** 30, U16_INF,
                               np.clip(p.op_ceiling + 1, 0, U16_INF - 1))
    u16[:R, C_LO] = p.lo[:R]
    u16[:R, C_SHIFT] = np.clip(p.shift, 0, 65535)
    uf = p.u_forced.astype(np.int64)
    relb = np.where(p.ceil_beyond >= 2 ** 30, U16_INF - 1,
                    np.clip(p.ceil_beyond.astype(np.int64) - uf,
                            -1, W + 1) + 1)         # biased +1, -1 -> 0
    u16[:R, C_CEILB] = relb
    u16[:R, C_UF] = uf
    u16[:, C_R] = R
    return i32, u16


def _build_tables_one(jnp, lax, i32, u16, r_pad: int):
    """Device-side frame builder for ONE key: (r_pad, 4) int32 +
    (r_pad, 12) uint16 -> (r_pad, TLANES) int32 tab, (r_pad, 4) int32
    scal. Bit-identical to pack_tables (differentially tested)."""
    u = u16.astype(jnp.int32)
    invr = i32[:, 0]
    retr = i32[:, 1]
    R = u[0, C_R]
    kr = lax.broadcasted_iota(jnp.int32, (r_pad, 1), 0)
    o = lax.broadcasted_iota(jnp.int32, (r_pad, W), 1)
    lo = u[:, C_LO:C_LO + 1]
    pos = lo + o
    in_range = (pos < R) & (kr < R)
    idx = jnp.clip(pos, 0, jnp.maximum(R - 1, 0))

    def g(col):
        return jnp.take(u[:, col], idx, axis=0)      # (r_pad, W)

    fsk = jnp.where(in_range & (g(C_PRED) <= kr), g(C_FSK1), 0)
    a1p = g(C_A1)
    a2p = g(C_A2)
    uf = u[:, C_UF:C_UF + 1]
    verabs = g(C_VER)
    raw = (verabs - 1) - uf
    relver = jnp.where(
        verabs == U16_NOASSERT, -32768,
        jnp.where((verabs == U16_NEVER) | (raw < -1) | (raw > W + 1),
                  -32767, raw))
    ceilabs = g(C_CEIL)
    relceil = jnp.where((ceilabs == U16_INF) | ~in_range, 32767,
                        jnp.clip((ceilabs - 1) - uf, -1, W + 1))
    retg = jnp.take(retr, idx, axis=0)               # (r_pad, W)
    invg = jnp.take(invr, idx, axis=0)
    bits = ((retg[:, None, :] < invg[:, :, None])
            & in_range[:, None, :])                  # (r_pad, W, W) c-minor
    wts = (jnp.uint32(1) << jnp.arange(W, dtype=jnp.uint32))
    pm = (bits.astype(jnp.uint32) * wts[None, None, :]).sum(-1)
    isupd = (g(C_FSK1) >= 2) & in_range
    um = (isupd.astype(jnp.uint32) * wts[None, :]).sum(-1)  # (r_pad,)

    def pair(lo16, hi16):
        return (lo16 & 0xFFFF) | (hi16 << 16)

    tab = jnp.concatenate([
        pair(a1p, a2p),
        pair(relver, relceil),
        lax.bitcast_convert_type(pm, jnp.int32),
        pair(fsk, jnp.zeros_like(fsk)),
    ], axis=1)                                       # (r_pad, TLANES)
    tab = jnp.where(kr < R, tab, 0)
    # ceil_beyond decode: 65533 = INF, else biased by +1
    relb = jnp.where(u[:, C_CEILB] == U16_INF - 1, 2 ** 30,
                     u[:, C_CEILB] - 1)
    inrow = kr[:, 0] < R
    scal = jnp.stack([jnp.where(inrow, u[:, C_SHIFT], 0),
                      jnp.where(inrow, relb, 0),
                      jnp.where(inrow,
                                lax.bitcast_convert_type(um, jnp.int32), 0),
                      jnp.full((r_pad,), 1, jnp.int32) * R], axis=1)
    return tab, scal


def _wave_body(jnp, lax, pl, pltpu, row16, shift, ceilb, upd, kk, R,
               stw_p, stv_p, alive_p, xs, rs, acc_p, ovf_p, peak_p,
               wav_p):
    """One BFS wave on the packed planes. No vector->scalar syncs."""
    lane = lax.broadcasted_iota(jnp.int32, (8, 128), 1)
    o = lane % W                         # window op index per slot
    row = row16

    def seg(g):
        s = row[:, 32 * g:32 * g + 32]
        sp = jnp.pad(s, ((0, 0), (0, 96)))
        sp = sp | pltpu.roll(sp, 32, 1) | pltpu.roll(sp, 64, 1) \
            | pltpu.roll(sp, 96, 1)
        return jnp.broadcast_to(sp, (8, 128))

    g_av = seg(G_A1A2)
    g_vc = seg(G_VERCEIL)
    a1 = g_av & 0xFFFF                   # biased value ids (0 = wildcard)
    a2 = (g_av >> 16) & 0xFFFF
    rver = (g_vc << 16) >> 16            # sign-extended int16
    rceil = g_vc >> 16                   # arithmetic shift: signed
    pmask = seg(G_PRED).astype(jnp.uint32)
    fsk = seg(G_FSK) & 0xFFFF

    sw = stw_p[...].astype(jnp.uint32)
    sv = stv_p[...]                      # biased value ids (0 = unset? no:
    # sv stores value id + 1 with 1 == NONE_VAL's bias; init plane is 1)
    alive = alive_p[...] != 0

    not_set = ((sw >> o.astype(jnp.uint32)) & jnp.uint32(1)) == 0
    preds_in = (sw & pmask) == pmask
    version = lax.population_count(
        sw & jnp.uint32(upd)).astype(jnp.int32)   # relative to u_forced
    # per-STATE min ceiling among its not-yet-linearized window ops:
    # a state's 32 candidate lanes live in one 32-lane segment, so this
    # is a segment-local all-reduce — butterfly of wrapped rolls (the
    # wrap re-enters the same segment, so no cross-state mixing)
    mc = jnp.where(not_set, rceil, 2 ** 30)
    d = 1
    while d < W:
        wrapped = jnp.where(lane % W >= d, pltpu.roll(mc, d, 1),
                            pltpu.roll(mc, d - W + 128, 1))
        mc = jnp.minimum(mc, wrapped)
        d *= 2
    min_ceil = jnp.minimum(mc, ceilb)
    alive = alive & (version <= min_ceil)

    is_read = fsk == (1 + READ)
    is_write = fsk == (1 + WRITE)
    is_cas = fsk == (1 + CAS)
    no_assert = rver == jnp.int32(-32768)
    ver_ok = no_assert | (is_read & (rver == version)) | \
        ((is_write | is_cas) & (rver == version + 1))
    read_ok = is_read & ((a1 == 0) | (a1 == sv))
    model_ok = read_ok | is_write | (is_cas & (a1 == sv))

    bitb = jnp.uint32(1) << o.astype(jnp.uint32)
    new_w_full = sw | bitb
    ssafe = jnp.minimum(shift, 31).astype(jnp.uint32)
    low = jnp.where(shift >= 32, jnp.uint32(0xFFFFFFFF),
                    (jnp.uint32(1) << ssafe) - jnp.uint32(1))
    slide_ok = (new_w_full & low) == low
    new_w = jnp.where(shift >= 32, jnp.uint32(0), new_w_full >> ssafe)

    valid = (alive & (fsk > 0) & not_set & preds_in
             & ver_ok & model_ok & slide_ok)
    new_v = jnp.where(is_read, sv, jnp.where(is_write, a1, a2))

    # partial dedupe (soundness-free: only kills candidates identical
    # to a SURVIVING one). Duplicates arise when distinct states
    # converge on the same (window, value); without any dedupe their
    # multiplicity compounds every wave and saturates capacity
    # (measured: peak 110 vs true frontier 14). Two cheap passes:
    # within a column (same op, states in sublanes) and across
    # segments of a row. Compaction assigns surviving copies
    # CONSECUTIVE ranks, which places them in one column next wave —
    # so cross-position duplicates collapse within two waves and
    # multiplicity stays O(segments) instead of compounding.
    nwb = lax.bitcast_convert_type(new_w, jnp.int32)
    vld = valid.astype(jnp.int32)
    srow_f = lax.broadcasted_iota(jnp.int32, (8, 128), 0)
    # stack [w, v, valid] into one (24, 128) array so each compare
    # needs ONE roll (rolls dominated this pass: 30 -> 10)
    st24 = jnp.concatenate([nwb, new_v, vld], axis=0)
    dup = srow_f < 0             # all-false plane
    for d in range(1, 8):        # vs candidate d sublanes above
        r24 = pltpu.roll(st24, d, 0)
        same = ((nwb == r24[0:8]) & (new_v == r24[8:16])
                & (r24[16:24] != 0) & (srow_f >= d))
        dup = dup | same
    for g in range(1, SEG):      # vs candidate g segments to the left
        dd = 32 * g
        r24 = pltpu.roll(st24, dd, 1)
        same = ((nwb == r24[0:8]) & (new_v == r24[8:16])
                & (r24[16:24] != 0) & (lane >= dd))
        dup = dup | same
    valid = valid & ~dup

    # dense ranks via log-shift prefix sums (vector only)
    vi = valid.astype(jnp.int32)
    acc = vi
    d = 1
    while d < 128:
        acc = acc + jnp.where(lane >= d, pltpu.roll(acc, d, 1), 0)
        d *= 2
    rowtot = acc[:, 127:128]
    srow8 = lax.broadcasted_iota(jnp.int32, (8, 1), 0)
    racc = rowtot
    d = 1
    while d < 8:
        racc = racc + jnp.where(srow8 >= d, pltpu.roll(racc, d, 0), 0)
        d *= 2
    rank = acc - vi + (racc - rowtot)    # exclusive global rank

    # flags BEFORE compaction: acceptance is witness-based; overflow =
    # any candidate ranked past capacity
    last = jnp.where(kk + 1 == R, 1, 0)  # scalar 0/1
    acc_p[...] = acc_p[...] | (vi * last)
    ovf_p[...] = ovf_p[...] | (valid & (rank >= F)).astype(jnp.int32)
    peak_p[...] = jnp.maximum(peak_p[...], jnp.where(valid, rank + 1, 0))
    wav_p[...] = wav_p[...] + (alive_p[...] != 0).astype(jnp.int32)

    rank = jnp.where(valid, rank, NP + 7)
    rs[...] = rank
    r_flat = rs.reshape(1, NP)[...]
    rio = lax.broadcasted_iota(jnp.int32, (F, NP), 0)
    # bf16 one-hot: Mosaic's single-pass matmul feeds the MXU bf16
    # (8 mantissa bits), so payloads ride as 8-bit limbs — exact in
    # bf16 — and ALL limbs compact in ONE matmul via a (PL, NP) lhs
    A = (jnp.broadcast_to(r_flat, (F, NP)) == rio).astype(jnp.bfloat16)

    nwi = lax.bitcast_convert_type(new_w, jnp.int32)
    limbs = ((nwi & 0xFF), ((nwi >> 8) & 0xFF), ((nwi >> 16) & 0xFF),
             ((nwi >> 24) & 0xFF), (new_v & 0xFF), ((new_v >> 8) & 0xFF),
             vi)
    for i, pl_ in enumerate(limbs):
        xs[8 * i:8 * i + 8, :] = pl_
    lhs = xs.reshape(PL, NP)[...].astype(jnp.bfloat16)
    out7 = lax.dot_general(lhs, A, (((1,), (1,)), ((), ())),
                           preferred_element_type=jnp.float32)  # (PL, F)
    wl0 = out7[L_W0:L_W0 + 1]
    wl1 = out7[L_W1:L_W1 + 1]
    wl2 = out7[L_W2:L_W2 + 1]
    wl3 = out7[L_W3:L_W3 + 1]
    vl0 = out7[L_V0:L_V0 + 1]
    vl1 = out7[L_V1:L_V1 + 1]
    filled = out7[L_FILL:L_FILL + 1]

    # EXACT frontier dedupe on the compacted (1, F) rows: kill a row
    # identical to a lower-ranked filled row (F-1 roll-compares on one
    # tiny vector). Candidate-level dups are only partially removable
    # (cross-op convergences aren't roll-reachable), but deduping the
    # KEPT frontier stops multiplicity compounding across waves — each
    # wave's candidate count is then distinct successors plus that
    # wave's primordial convergences only (measured: peak 60 -> ~25 on
    # the repro class). Holes in the row space are harmless: ranks are
    # recomputed from scratch next wave.
    # combined int32 keys: one roll per compare instead of seven
    cw = (wl0.astype(jnp.int32) + (wl1.astype(jnp.int32) << 8)
          + (wl2.astype(jnp.int32) << 16) + (wl3.astype(jnp.int32) << 24))
    cv = vl0.astype(jnp.int32) + (vl1.astype(jnp.int32) << 8)
    fi = (filled > 0.5).astype(jnp.int32)
    key3 = jnp.concatenate([cw, cv, fi], axis=0)          # (3, F)
    lane_f = lax.broadcasted_iota(jnp.int32, (1, F), 1)
    dupr = lane_f < 0
    for d in range(1, F):
        r3 = pltpu.roll(key3, d, 1)
        eq = ((cw == r3[0:1]) & (cv == r3[1:2]) & (r3[2:3] != 0)
              & (lane_f >= d))
        dupr = dupr | eq
    filled = jnp.where(dupr, 0.0, filled)

    # pack all limb rows back into (8, 128) planes with two more
    # matmuls: expand (PL, F) -> (8*PL, F) sublane-replicated rows
    # masked to their residue, then scatter segments via D
    prow = lax.broadcasted_iota(jnp.int32, (PL, F), 0)
    out7d = jnp.where(prow == L_FILL,
                      jnp.broadcast_to(filled, (PL, F)), out7)
    jio = lax.broadcasted_iota(jnp.int32, (8 * PL, PL), 0)
    iio = lax.broadcasted_iota(jnp.int32, (8 * PL, PL), 1)
    E = ((jio // 8) == iio).astype(jnp.bfloat16)          # (8PL, PL)
    out56 = lax.dot_general(E, out7d.astype(jnp.bfloat16),
                            (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)
    jio2 = lax.broadcasted_iota(jnp.int32, (8 * PL, F), 0)
    rio2 = lax.broadcasted_iota(jnp.int32, (8 * PL, F), 1)
    M1t = ((rio2 % 8) == (jio2 % 8)).astype(jnp.float32)
    tmp = (out56 * M1t).astype(jnp.bfloat16)              # (8PL, F)
    rioD = lax.broadcasted_iota(jnp.int32, (F, 128), 0)
    lioD = lax.broadcasted_iota(jnp.int32, (F, 128), 1)
    D = ((rioD // 8) == (lioD // 32)).astype(jnp.bfloat16)
    plane56 = lax.dot_general(tmp, D, (((1,), (0,)), ((), ())),
                              preferred_element_type=jnp.float32)

    def limb_plane(i):
        return plane56[8 * i:8 * i + 8, :].astype(jnp.int32)

    fplane = limb_plane(L_FILL)
    stw_p[...] = jnp.where(
        fplane != 0,
        limb_plane(L_W0) + (limb_plane(L_W1) << 8)
        + (limb_plane(L_W2) << 16) + (limb_plane(L_W3) << 24), 0)
    stv_p[...] = jnp.where(
        fplane != 0, limb_plane(L_V0) + (limb_plane(L_V1) << 8), 0)
    alive_p[...] = fplane


def _make_kernel(batched: bool):
    def kernel(tab_ref, scal_ref, out_ref, stw_p, stv_p, alive_p, xs,
               rs, acc_p, ovf_p, peak_p, wav_p, sm):
        import jax
        import jax.numpy as jnp
        from jax import lax
        from jax.experimental import pallas as pl
        from jax.experimental.pallas import tpu as pltpu

        # batched refs have their leading key dim squeezed by the
        # BlockSpec (None, ...) — the body is identical either way
        kk = pl.program_id(1) if batched else pl.program_id(0)
        sub = kk % TSUB

        @pl.when(kk == 0)
        def _init():
            lane = lax.broadcasted_iota(jnp.int32, (8, 128), 1)
            srow = lax.broadcasted_iota(jnp.int32, (8, 128), 0)
            init = ((srow == 0) & (lane < W)).astype(jnp.int32)
            alive_p[...] = init
            stw_p[...] = jnp.zeros((8, 128), jnp.int32)
            stv_p[...] = init  # biased NONE_VAL = 0 + 1
            acc_p[...] = jnp.zeros((8, 128), jnp.int32)
            ovf_p[...] = jnp.zeros((8, 128), jnp.int32)
            peak_p[...] = init
            wav_p[...] = jnp.zeros((8, 128), jnp.int32)
            sm[0] = 0

        row16 = tab_ref[pl.ds(sub, 1), :]
        shift = scal_ref[sub, S_SHIFT]
        ceilb = scal_ref[sub, S_CEILB]
        upd = scal_ref[sub, S_UPD]
        R = scal_ref[sub, S_R]

        @pl.when(sm[0] == 0)
        def _wave():
            _wave_body(jnp, lax, pl, pltpu, row16, shift, ceilb, upd,
                       kk, R, stw_p, stv_p, alive_p, xs, rs, acc_p,
                       ovf_p, peak_p, wav_p)

        # frontier-death check: one vector->scalar sync every
        # DONE_EVERY waves lets dead/padding steps skip the body
        @pl.when((kk % DONE_EVERY == DONE_EVERY - 1) & (sm[0] == 0))
        def _check():
            sm[0] = jnp.where(jnp.any(alive_p[...] != 0), 0, 1)

        nprog = pl.num_programs(1) if batched else pl.num_programs(0)

        @pl.when(kk == nprog - 1)
        def _emit():
            out_ref[0:8, :] = acc_p[...]
            out_ref[8:16, :] = ovf_p[...]
            out_ref[16:24, :] = peak_p[...]
            out_ref[24:32, :] = wav_p[...]

    return kernel


@functools.lru_cache(maxsize=None)
def _call_single(r_pad: int, interpret: bool):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    call = pl.pallas_call(
        _make_kernel(False),
        grid=(r_pad,),
        in_specs=[
            pl.BlockSpec((TSUB, TLANES), lambda k: (k // TSUB, 0)),
            pl.BlockSpec((TSUB, 4), lambda k: (k // TSUB, 0),
                         memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec((32, 128), lambda k: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((32, 128), jnp.int32),
        scratch_shapes=[pltpu.VMEM((8, 128), jnp.int32)] * 3 +
                       [pltpu.VMEM((8 * PL, 128), jnp.int32)] +
                       [pltpu.VMEM((8, 128), jnp.int32)] * 5 +
                       [pltpu.SMEM((8,), jnp.int32)],
        interpret=interpret,
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",)),
    )

    def run(i32, u16):
        from jax import lax
        tab, scal = _build_tables_one(jnp, lax, i32, u16, r_pad)
        return call(tab, scal)

    return jax.jit(run)


@functools.lru_cache(maxsize=None)
def _call_batch(k_keys: int, r_pad: int, interpret: bool):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    call = pl.pallas_call(
        _make_kernel(True),
        grid=(k_keys, r_pad),
        in_specs=[
            pl.BlockSpec((None, TSUB, TLANES),
                         lambda key, k: (key, k // TSUB, 0)),
            pl.BlockSpec((None, TSUB, 4), lambda key, k: (key, k // TSUB, 0),
                         memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec((None, 32, 128), lambda key, k: (key, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((k_keys, 32, 128), jnp.int32),
        scratch_shapes=[pltpu.VMEM((8, 128), jnp.int32)] * 3 +
                       [pltpu.VMEM((8 * PL, 128), jnp.int32)] +
                       [pltpu.VMEM((8, 128), jnp.int32)] * 5 +
                       [pltpu.SMEM((8,), jnp.int32)],
        interpret=interpret,
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary")),
    )

    # inputs are compact per-op arrays shipped 2D (the tunnel moves 3D
    # arrays pathologically slowly); frames build on device — one
    # lax.map step per key bounds the (r_pad, W, W) pred-bit
    # intermediates to ~1 MB each
    def run(i32_2d, u16_2d):
        from jax import lax
        i32r = i32_2d.reshape(k_keys, r_pad, 4)
        u16r = u16_2d.reshape(k_keys, r_pad, 12)

        def one(args):
            return _build_tables_one(jnp, lax, args[0], args[1], r_pad)

        tabs, scals = lax.map(one, (i32r, u16r))
        return call(tabs, scals)

    return jax.jit(run)


def _decode(out: np.ndarray, p: Packed) -> dict:
    acc = out[0:8].any()
    ovf = out[8:16].any()
    peak = int(out[16:24].max())
    waves = int(out[24:32].max())
    if acc:
        res = {"valid?": True, "waves": waves, "peak-frontier": peak,
               "ops": p.R, "info-ops": 0, "engine": "mxu-wave"}
        if ovf:
            res["overflowed-en-route"] = True
        return res
    if ovf:
        return {"valid?": "unknown", "overflow": True,
                "reason": f"mxu frontier overflow (capacity {F})",
                "waves": waves, "peak-frontier": peak}
    return {"valid?": False, "waves": waves, "peak-frontier": peak,
            "ops": p.R, "info-ops": 0, "engine": "mxu-wave",
            "stuck-at-depth": waves}


def check_packed_mxu(p: Packed) -> dict | None:
    """Run the MXU wave kernel on one packed history; None when
    unsupported, an overflow-unknown when capacity was exceeded."""
    import jax
    import jax.numpy as jnp

    if not supported(p):
        return None
    r_pad = max(bucket(p.R), TSUB)
    i32, u16 = pack_perop(p, r_pad)
    interpret = jax.default_backend() != "tpu"
    out = np.asarray(_call_single(r_pad, interpret)(
        jnp.asarray(i32), jnp.asarray(u16)))
    return _decode(out, p)


def check_packed_batch_mxu(packs: list) -> list | None:
    """Check many packed histories in ONE pallas dispatch per R-bucket
    group. Returns per-pack results aligned with input order; packs the
    kernel can't take (wide window, info ops, id overflow) get None
    entries for the caller's per-key fallback. Returns None outright
    when NO pack is supported."""
    import jax
    import jax.numpy as jnp

    if not packs or not any(supported(p) for p in packs):
        return None
    interpret = jax.default_backend() != "tpu"
    results: list = [None] * len(packs)
    groups: dict = {}
    for i, p in enumerate(packs):
        if supported(p):
            groups.setdefault(max(bucket(p.R), TSUB), []).append(i)
    for r_pad, idxs in groups.items():
        # bucket the key count so the jit cache holds O(log K) variants
        # instead of one compile per distinct batch size; padding keys
        # are all-zero (R=0) rows whose grid steps die immediately
        K = len(idxs)
        k_pad = 1
        while k_pad < K:
            k_pad *= 2
        i32s = np.zeros((k_pad, r_pad, 4), dtype=np.int32)
        u16s = np.zeros((k_pad, r_pad, 12), dtype=np.uint16)
        for j, i in enumerate(idxs):
            a, b = pack_perop(packs[i], r_pad)
            i32s[j] = a
            u16s[j] = b
        out = np.asarray(_call_batch(k_pad, r_pad, interpret)(
            jnp.asarray(i32s.reshape(k_pad * r_pad, 4)),
            jnp.asarray(u16s.reshape(k_pad * r_pad, 12))))
        for j, i in enumerate(idxs):
            results[i] = _decode(out[j], packs[i])
    return results
