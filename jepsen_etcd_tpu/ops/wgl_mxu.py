"""MXU-compacted Pallas wave kernel for the WGL frontier BFS.

Second-generation fused kernel (supersedes the retired r3 pick-loop
kernel on its shape class: W <= 64 window, no info ops; the r3 kernel
lives in git history at tag r4 as ops/wgl_pallas.py). Its cost was
measured to be dominated by vector->scalar round trips in its greedy
dedupe pick loop (~1.2 us per pick on a v5e through axon) plus one
DMA-visible stream per table; and every device engine pays the axon
tunnel's measured ~100 ms round-trip latency per synchronized call and
~30-50 MB/s effective host->device bandwidth. This kernel's design
removes all three costs:

- the frontier lives in packed (NR, 128) int32 planes: candidate
  (op o, state s) sits at position (p, q) with s = NR*(q//wk) + p and
  o = q % wk, where wk is the window width (32 or 64) and NR = F*wk/128
  — F=32 states x wk window ops per wave, one vreg per payload plane
  (wk=32) or two (wk=64);
- per-op data ships as ~32 B/op compact vectors (the [R, W] frames are
  pure gathers over them — see wgl._pack_register_history) and a
  jitted device-side builder materializes the [R_pad, TLANES] frame
  table in HBM, bit-identical to the host packer (contract-tested);
- successor compaction is pick-free: candidates get dense ranks from
  log-shift prefix sums (pltpu.roll — all vector domain), and MXU
  one-hot matmuls scatter payloads into frontier rows. Payloads ride
  as 8-bit limbs — exact in bf16 (Mosaic's single-pass matmul feeds
  the MXU bf16, 8 mantissa bits) — all limbs in ONE matmul via a
  (PL, NP) lhs;
- the compacted frontier is deduped exactly (F-1 roll-compares on
  tiny row vectors) so duplicate multiplicity cannot compound across
  waves (no-dedupe peak measured 110 vs true frontier 14; with it 25);
- acceptance / overflow / peak-frontier / waves ride as VECTOR flag
  planes folded elementwise and decoded on host from the final
  (32, 128) output block. The only scalar sync is a frontier-death
  check every DONE_EVERY waves, which lets finished (or padding) grid
  steps skip the body;
- the batched variant runs K keys in ONE pallas dispatch
  (grid (K, R_pad)) — one tunnel round trip for the whole key batch,
  which is what makes the TPU competitive with the in-process native
  DFS sweep on the key-DP axis (SURVEY §2.3, register.clj:108-119).

Soundness contract: definitive answers only. accepted=True is
witnessed by a surviving path (valid even if earlier waves
overflowed); accepted=False is only reported when no wave overflowed;
anything else degrades to {"overflow": True} and the caller's complete
jnp ladder. Differentially fuzzed against the jnp kernel and both CPU
oracles in tests/test_wgl_mxu.py.

Reference role: hot path of the Knossos-equivalent checker
(register.clj:110-112); the reference has no analog (Knossos is a JVM
heap search).
"""

from __future__ import annotations

import functools

import numpy as np

from ..runner import telemetry
from .wgl import (CAS, NO_ASSERT, READ, WRITE, WILDCARD,
                  Packed, bucket)

F = 32            # frontier capacity (states)
W_SUPPORTED = (32, 64, 128)
TSUB = 8          # int32 table block sublane tile
DONE_EVERY = 8    # waves between frontier-death scalar checks
V_SENT = np.int16(-32768)   # "never matches" relative version
C_INF = np.int16(32767)     # "no ceiling" relative ceiling
VAL_MAX = 2 ** 16 - 3       # value-id budget (uint16 biased +1)

# table lane-segment layout (each segment is wk lanes):
# 0: a1|a2 pair, 1: ver|ceil pair, 2..2+NW-1: pred words, last: fsk
# int32 SMEM scal columns (S_UPD0..S_UPD0+NW-1 hold the update-mask
# words; NW <= 4 fits before S_R)
S_SHIFT, S_CEILB, S_UPD0, S_UPD1, S_UPD2, S_UPD3, S_R = range(7)
SCAL_COLS = 8
#: largest r_pad whose (r_pad*wk, r_pad) one-hot gather matrix fits
#: comfortably, BY WIDTH: the matrix is r_pad^2*wk*2 bytes and the
#: build vmaps 16 keys at once, so the budget halves as wk doubles
#: (w=64: <= ~34 MB/key, ~0.5 GB per chunk; w=128 at 256: the same)
OH_MAX_RPAD = {32: 1024, 64: 512, 128: 256}
#: keys per batched dispatch. Measured r5: each pallas launch carries
#: ~57 ms of fixed cost through the tunnel, which exceeds anything a
#: finer chunk overlap can hide — so chunks only bound the padded
#: k_pad blowup of truly huge batches, and a (bucket, width) group
#: normally launches ONCE
BATCH_CHUNK = 1024

U16_NOASSERT = 65535
U16_INF = 65534
U16_NEVER = 65533   # version assertion that can never match
# uint16 per-op col layout
C_A1, C_A2, C_VER, C_FSK1, C_PRED, C_CEIL, C_LO, C_SHIFT, C_CEILB, \
    C_UF, C_R, C_SPARE = range(12)


def _tpu_compiler_params(pltpu, dimension_semantics):
    """jax renamed ``TPUCompilerParams`` -> ``CompilerParams`` (and back
    again across 0.4.x/0.5.x); resolve whichever this jax ships."""
    cls = getattr(pltpu, "CompilerParams", None) \
        or getattr(pltpu, "TPUCompilerParams")
    return cls(dimension_semantics=dimension_semantics)


def _shard_map():
    """``shard_map`` moved from jax.experimental to the jax namespace;
    the keyword for replication checking renamed check_rep -> check_vma.
    Returns (shard_map, vma_kwargs) for whichever API this jax ships."""
    try:
        from jax import shard_map as sm
    except ImportError:
        from jax.experimental.shard_map import shard_map as sm
    import inspect
    params = inspect.signature(sm).parameters
    if "check_vma" in params:
        return sm, {"check_vma": False}
    return sm, {"check_rep": False}


def _dims(wk: int):
    """Derived layout constants for a window width."""
    nw = wk // 32            # mask words
    nr = F * wk // 128       # plane rows (candidate slots = F*wk)
    np_ = F * wk             # packed candidate slots
    segk = 128 // wk         # states per plane-row set
    pl = 4 * nw + 3          # payload limbs: w bytes + v lo/hi + filled
    tlanes = wk * (3 + nw)
    tlanes = -(-tlanes // 128) * 128    # lane-tile align
    return nw, nr, np_, segk, pl, tlanes


def supported(p: Packed) -> bool:
    """Preconditions: packed OK, one/two/four-word window, no info ops,
    value ids and history length within the uint16 shipping budget
    (others fall back to the jnp ladder). The shift bound guards the
    uint16 C_SHIFT column of the host/device bit-identity contract:
    shift <= w for every packing today, but a future packing that
    widened it must fall back rather than silently truncate."""
    return (bool(p.ok) and p.w in W_SUPPORTED and p.I == 0 and p.R > 0
            and p.n_values < VAL_MAX and p.R < 65000
            and int(np.max(p.shift, initial=0)) < 65536)


def pack_tables(p: Packed, r_pad: int):
    """Host reference packer: consolidate a Packed's per-depth frames
    into the kernel's [r_pad, TLANES] int32 table + [r_pad, SCAL_COLS]
    int32 scal. CANONICAL relative encodings (shared with the device
    builder — the bit-identity contract requires one rule): a reachable
    relative version is 0..wk+1, so any assertion outside [-1, wk+1]
    maps to the never-matching -32767; ceilings prune via
    version <= ceil with version in [0, wk], so values clamp into
    [-1, wk+1]."""
    from .wgl import ensure_frames
    ensure_frames(p)   # frames are lazy; this host reference reads them
    R, wk = p.R, p.w
    nw, nr, np_, segk, pl, tlanes = _dims(wk)
    uf = p.u_forced.astype(np.int64)                      # [R]
    tab = np.zeros((r_pad, tlanes), dtype=np.int32)

    def pair(lo_u16, hi_u16):
        return (lo_u16.astype(np.uint32)
                | (hi_u16.astype(np.uint32) << 16)).view(np.int32)

    def seg(j):
        return tab[:R, wk * j:wk * j + wk]

    a1u = np.where(p.a1 == WILDCARD, 0,
                   p.a1 + 1).astype(np.uint16)            # biased
    a2u = (p.a2 + 1).astype(np.uint16)
    seg(0)[...] = pair(a1u, a2u)
    rel = p.ver.astype(np.int64) - uf[:, None]
    rel = np.where((rel < -1) | (rel > wk + 1), -32767, rel)
    rel = np.where(p.ver == NO_ASSERT, V_SENT, rel).astype(np.int16)
    relc = np.clip(p.ceil_frame.astype(np.int64) - uf[:, None],
                   -1, wk + 1)
    relc = np.where(p.ceil_frame >= 2 ** 30, C_INF, relc).astype(np.int16)
    seg(1)[...] = pair(rel.view(np.uint16), relc.view(np.uint16))
    for wi in range(nw):
        seg(2 + wi)[...] = p.pred_frame[:, :, wi].view(np.int32)
    fsk = np.where(p.static_ok, p.f_code.astype(np.uint16) + 1,
                   0).astype(np.uint16)
    seg(2 + nw)[...] = pair(fsk, np.zeros_like(fsk))

    scal = np.zeros((r_pad, SCAL_COLS), dtype=np.int32)
    scal[:R, S_SHIFT] = p.shift
    cb = np.clip(p.ceil_beyond.astype(np.int64) - uf, -1, wk + 1)
    scal[:R, S_CEILB] = np.where(p.ceil_beyond >= 2 ** 30, 2 ** 30, cb)
    for wi in range(nw):
        scal[:R, S_UPD0 + wi] = p.upd_mask[:, wi].view(np.int32)
    scal[:, S_R] = R
    return tab, scal


def pack_perop(p: Packed, r_pad: int):
    """Compact per-op arrays for the device frame builder: int32
    [r_pad, 4] (invoke/return time ranks) + uint16 [r_pad, 12].
    Width-agnostic — the window geometry is carried by lo/shift."""
    R = p.R
    i32 = np.zeros((r_pad, 4), dtype=np.int32)
    i32[:R, 0] = p.inv_rank
    i32[:R, 1] = p.ret_rank
    u16 = np.zeros((r_pad, 12), dtype=np.uint16)
    u16[:R, C_A1] = np.where(p.op_a1 == WILDCARD, 0, p.op_a1 + 1)
    u16[:R, C_A2] = p.op_a2 + 1
    # version assertions outside [0, 65000) (negative / huge — e.g. a
    # corrupted read version) can never match a reachable version;
    # ship the NEVER marker so the device builder emits the same
    # canonical -32767 as pack_tables
    u16[:R, C_VER] = np.where(
        p.op_ver == NO_ASSERT, U16_NOASSERT,
        np.where((p.op_ver < 0) | (p.op_ver >= 65000), U16_NEVER,
                 p.op_ver + 1))
    u16[:R, C_FSK1] = p.op_f.astype(np.uint16) + 1
    u16[:R, C_PRED] = np.clip(p.op_pred_rank, 0, 65533)
    # ceilings are >= -1 (version - 1 of a version-0 update): bias +1
    u16[:R, C_CEIL] = np.where(p.op_ceiling >= 2 ** 30, U16_INF,
                               np.clip(p.op_ceiling + 1, 0, U16_INF - 1))
    u16[:R, C_LO] = p.lo[:R]
    u16[:R, C_SHIFT] = np.clip(p.shift, 0, 65535)
    uf = p.u_forced.astype(np.int64)
    relb = np.where(p.ceil_beyond >= 2 ** 30, U16_INF - 1,
                    np.clip(p.ceil_beyond.astype(np.int64) - uf,
                            -1, p.w + 1) + 1)   # biased +1, -1 -> 0
    u16[:R, C_CEILB] = relb
    u16[:R, C_UF] = uf
    u16[:, C_R] = R
    return i32, u16


def pack_perop_batch(packs: list, r_pad: int, k_pad: int | None = None):
    """Vectorized ``pack_perop`` over a whole launch chunk: ONE numpy
    pass over the concatenated per-op columns fills the [k_pad, r_pad,
    4] int32 and [k_pad, r_pad, 12] uint16 batch tensors, bit-identical
    to the per-key loop (differentially tested).

    The per-key loop was the last O(K) host floor on the batched key-DP
    axis: ~15 numpy dispatches per key at K=512 cost more in call
    overhead than the actual byte traffic (every column is [R] with R
    typically < 256). Concatenating first amortizes the dispatch over
    the whole chunk, and a single fancy-index row scatter lands every
    key at ``kid * r_pad + row`` in the padded batch tensor. Padding
    keys beyond ``len(packs)`` stay all-zero (R = 0) rows, exactly as
    the caller's preallocated tensors had them."""
    K = len(packs)
    kp = K if k_pad is None else k_pad
    i32 = np.zeros((kp, r_pad, 4), dtype=np.int32)
    u16 = np.zeros((kp, r_pad, 12), dtype=np.uint16)
    if K == 0:
        return i32, u16
    Rs = np.fromiter((p.R for p in packs), dtype=np.int64, count=K)
    # C_R rides every row (real and pad) of a real key
    u16[:K, :, C_R] = Rs[:, None].astype(np.uint16)
    N = int(Rs.sum())
    if N == 0:
        return i32, u16
    kid = np.repeat(np.arange(K), Rs)                  # [N] key per op
    offs = np.concatenate(([0], np.cumsum(Rs)[:-1]))
    row = np.arange(N, dtype=np.int64) - offs[kid]     # [N] in-key row

    live = [p for p in packs if p.R]

    def cat(get):
        return np.concatenate([np.asarray(get(p), dtype=np.int64)
                               for p in live])

    inv = cat(lambda p: p.inv_rank)
    ret = cat(lambda p: p.ret_rank)
    a1 = cat(lambda p: p.op_a1)
    a2 = cat(lambda p: p.op_a2)
    ver = cat(lambda p: p.op_ver)
    f = cat(lambda p: p.op_f)
    pred = cat(lambda p: p.op_pred_rank)
    ceil = cat(lambda p: p.op_ceiling)
    lo = cat(lambda p: p.lo[:p.R])
    shift = cat(lambda p: p.shift)
    uf = cat(lambda p: p.u_forced)
    ceilb = cat(lambda p: p.ceil_beyond)
    wv = np.repeat(np.fromiter((p.w for p in live), dtype=np.int64,
                               count=len(live)),
                   Rs[Rs > 0])                         # [N] window width

    i32f = np.zeros((N, 4), dtype=np.int32)
    i32f[:, 0] = inv
    i32f[:, 1] = ret
    u16f = np.zeros((N, 12), dtype=np.uint16)
    u16f[:, C_A1] = np.where(a1 == WILDCARD, 0, a1 + 1)
    u16f[:, C_A2] = a2 + 1
    u16f[:, C_VER] = np.where(
        ver == NO_ASSERT, U16_NOASSERT,
        np.where((ver < 0) | (ver >= 65000), U16_NEVER, ver + 1))
    u16f[:, C_FSK1] = f + 1
    u16f[:, C_PRED] = np.clip(pred, 0, 65533)
    u16f[:, C_CEIL] = np.where(ceil >= 2 ** 30, U16_INF,
                               np.clip(ceil + 1, 0, U16_INF - 1))
    u16f[:, C_LO] = lo
    u16f[:, C_SHIFT] = np.clip(shift, 0, 65535)
    u16f[:, C_CEILB] = np.where(ceilb >= 2 ** 30, U16_INF - 1,
                                np.clip(ceilb - uf, -1, wv + 1) + 1)
    u16f[:, C_UF] = uf
    u16f[:, C_R] = Rs[kid]
    i32[kid, row] = i32f
    u16[kid, row] = u16f
    return i32, u16


def _build_tables_one(jnp, lax, i32, u16, r_pad: int, wk: int):
    """Device-side frame builder for ONE key: -> (r_pad, TLANES) int32
    tab, (r_pad, SCAL_COLS) int32 scal. Bit-identical to pack_tables
    (differentially tested).

    All eight per-op columns are gathered at the SAME sliding-window
    index (lo_k + o), so instead of eight `jnp.take` gathers — which
    lower to the TPU's serial gather unit and dominated the r4 build
    (~0.2 s at 512 keys) — ONE one-hot matrix rides the MXU: each
    one-hot row selects exactly one source element, so the contraction
    has a single nonzero term and is exact whenever the operand is,
    and 8-bit limb decomposition keeps every operand bf16-exact.

    The one-hot matrix is O(r_pad^2 * wk) bytes, so it only pays (and
    only fits) on the short-history shapes the batched key-DP axis
    produces; past OH_MAX_RPAD deep single keys keep the serial-gather
    path, whose cost is amortized over one big search."""
    nw, nr, np_, segk, pl, tlanes = _dims(wk)
    u = u16.astype(jnp.int32)
    invr = i32[:, 0]
    retr = i32[:, 1]
    R = u[0, C_R]
    kr = lax.broadcasted_iota(jnp.int32, (r_pad, 1), 0)
    o = lax.broadcasted_iota(jnp.int32, (r_pad, wk), 1)
    lo = u[:, C_LO:C_LO + 1]
    pos = lo + o
    in_range = (pos < R) & (kr < R)
    idx = jnp.clip(pos, 0, jnp.maximum(R - 1, 0))

    # one-hot gather: limb columns (values 0..255, bf16-exact) for
    # the six u16 cols (2 limbs) and the two time-rank cols
    # (3 limbs: ranks < 65000 * 2 < 2^18). One-hot rows select exactly
    # one source element, so the contraction is exact whenever the
    # operand limbs are.
    gather_cols = (C_VER, C_A1, C_A2, C_FSK1, C_PRED, C_CEIL)
    limbs = []
    for c in gather_cols:
        limbs += [u[:, c] & 0xFF, (u[:, c] >> 8) & 0xFF]
    for arr in (invr, retr):
        limbs += [arr & 0xFF, (arr >> 8) & 0xFF, (arr >> 16) & 0xFF]
    V = jnp.stack(limbs, axis=1).astype(jnp.bfloat16)   # (r_pad, 18)
    L = len(limbs)
    if r_pad <= OH_MAX_RPAD[wk]:
        # short histories: ONE dense one-hot matmul
        flat = idx.reshape(r_pad * wk, 1)
        rr = lax.broadcasted_iota(jnp.int32, (r_pad * wk, r_pad), 1)
        OH = (flat == rr).astype(jnp.bfloat16)
        G = lax.dot_general(OH, V, (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)
        G = G.astype(jnp.int32).reshape(r_pad, wk, L)
    else:
        # deep histories: the dense (r_pad*wk, r_pad) one-hot is
        # O(r_pad^2), but the gather is BANDED — window packing
        # guarantees k - wk < lo_k <= idx[k, :] <= k + wk - 1 (clamped
        # lanes stay within [R-wk, R-1] of their row) — so each
        # CH-row chunk's sources live in a (CH + 2*wk)-row slice.
        # One dynamic_slice + one small one-hot matmul per chunk under
        # lax.scan replaces the serial per-element gather that
        # dominated deep single-key device time (~0.12 s of the 10k
        # cell's 0.16 s)
        ch = min(16384 // wk, r_pad)   # one-hot stays ~(16k, ch+2wk)
        src = ch + 2 * wk
        n_ch = r_pad // ch
        Vp = jnp.pad(V, ((0, 2 * wk), (0, 0)))          # slice safety
        idx_ch = idx.reshape(n_ch, ch, wk)

        def one_chunk(_, c):
            start = jnp.maximum(c * ch - wk, 0)
            vsl = lax.dynamic_slice(Vp, (start, 0), (src, L))
            offs = (idx_ch[c] - start).reshape(ch * wk, 1)
            rr = lax.broadcasted_iota(jnp.int32, (ch * wk, src), 1)
            OH = (offs == rr).astype(jnp.bfloat16)
            gc = lax.dot_general(OH, vsl, (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
            return None, gc.astype(jnp.int32).reshape(ch, wk, L)

        _, G = lax.scan(one_chunk, None,
                        jnp.arange(n_ch, dtype=jnp.int32))
        G = G.reshape(r_pad, wk, L)

    def g(col):
        ci = 2 * gather_cols.index(col)
        return G[:, :, ci] | (G[:, :, ci + 1] << 8)       # (r_pad, wk)

    base = 2 * len(gather_cols)
    invg = G[:, :, base] | (G[:, :, base + 1] << 8) \
        | (G[:, :, base + 2] << 16)                      # (r_pad, wk)
    retg = G[:, :, base + 3] | (G[:, :, base + 4] << 8) \
        | (G[:, :, base + 5] << 16)

    fsk = jnp.where(in_range & (g(C_PRED) <= kr), g(C_FSK1), 0)
    a1p = g(C_A1)
    a2p = g(C_A2)
    uf = u[:, C_UF:C_UF + 1]
    verabs = g(C_VER)
    raw = (verabs - 1) - uf
    relver = jnp.where(
        verabs == U16_NOASSERT, -32768,
        jnp.where((verabs == U16_NEVER) | (raw < -1) | (raw > wk + 1),
                  -32767, raw))
    ceilabs = g(C_CEIL)
    relceil = jnp.where((ceilabs == U16_INF) | ~in_range, 32767,
                        jnp.clip((ceilabs - 1) - uf, -1, wk + 1))
    bits = ((retg[:, None, :] < invg[:, :, None])
            & in_range[:, None, :])                  # (r_pad, wk, wk)
    wts32 = (jnp.uint32(1) << (jnp.arange(wk, dtype=jnp.uint32) % 32))
    pms = []
    ums = []
    isupd = (g(C_FSK1) >= 2) & in_range
    for wi in range(nw):
        sl = slice(32 * wi, 32 * wi + 32)
        pms.append((bits[:, :, sl].astype(jnp.uint32)
                    * wts32[None, None, sl]).sum(-1))
        ums.append((isupd[:, sl].astype(jnp.uint32)
                    * wts32[None, sl]).sum(-1))

    def pair(lo16, hi16):
        return (lo16 & 0xFFFF) | (hi16 << 16)

    parts = [pair(a1p, a2p), pair(relver, relceil)]
    parts += [lax.bitcast_convert_type(pm, jnp.int32) for pm in pms]
    parts += [pair(fsk, jnp.zeros_like(fsk))]
    tab = jnp.concatenate(parts, axis=1)
    if tab.shape[1] < tlanes:
        tab = jnp.pad(tab, ((0, 0), (0, tlanes - tab.shape[1])))
    tab = jnp.where(kr < R, tab, 0)
    # ceil_beyond decode: U16_INF-1 = INF marker, else biased by +1
    relb = jnp.where(u[:, C_CEILB] == U16_INF - 1, 2 ** 30,
                     u[:, C_CEILB] - 1)
    inrow = kr[:, 0] < R
    cols = [jnp.where(inrow, u[:, C_SHIFT], 0),
            jnp.where(inrow, relb, 0)]
    for wi in range(4):
        if wi < nw:
            cols.append(jnp.where(
                inrow, lax.bitcast_convert_type(ums[wi], jnp.int32), 0))
        else:
            cols.append(jnp.zeros((r_pad,), jnp.int32))
    cols.append(jnp.full((r_pad,), 1, jnp.int32) * R)
    cols += [jnp.zeros((r_pad,), jnp.int32)] * (SCAL_COLS - len(cols))
    scal = jnp.stack(cols, axis=1)
    return tab, scal


def _wave_body(jnp, lax, pl_mod, pltpu, wk, row_t, shift, ceilb, upds,
               kk, R, stw_p, stv_p, alive_p, xs, rs, acc_p,
               ovf_p, peak_p, wav_p, mseg_p, plane_p):
    """One BFS wave on the packed planes. No vector->scalar syncs.

    Reductions that the r4 body ran as pltpu.roll butterflies (per-state
    min-ceiling, global candidate ranks) ride the MXU here as matmuls
    against constant 0/1 matrices hoisted into VMEM scratch (mseg_p,
    plane_p, built once at kk==0): every operand is a small integer
    (indicators, counts <= NP), exactly representable in bf16 with f32
    accumulation, so the matmul reduction is bit-exact while replacing
    ~25 (min-ceil) and ~40 (ranks) serial vector ops with one MXU pass
    each — measured ~2x on the per-wave cost at w=64."""
    nw, nr, np_, segk, pl, tlanes = _dims(wk)
    lane = lax.broadcasted_iota(jnp.int32, (nr, 128), 1)
    o = lane % wk                        # window op index per slot
    obit = o % 32                        # bit within its mask word
    o_word = o // 32                     # which mask word holds the bit

    def seg(j):
        s = row_t[:, wk * j:wk * j + wk]
        if wk < 128:
            s = jnp.pad(s, ((0, 0), (0, 128 - wk)))
            d = wk
            while d < 128:
                s = s | pltpu.roll(s, d, 1)
                d += wk
            return jnp.broadcast_to(s, (nr, 128))
        # wk == 128: Mosaic rejects broadcasting a column-slice of the
        # dynamically-offset row (invalid input layout); replicate down
        # the sublanes with log2(nr) roll-ors instead
        buf = jnp.pad(s, ((0, nr - 1), (0, 0)))
        d = 1
        while d < nr:
            buf = buf | pltpu.roll(buf, d, 0)
            d *= 2
        return buf

    g_av = seg(0)
    g_vc = seg(1)
    a1 = g_av & 0xFFFF                   # biased value ids (0 = wildcard)
    a2 = (g_av >> 16) & 0xFFFF
    rver = (g_vc << 16) >> 16            # sign-extended int16
    rceil = g_vc >> 16                   # arithmetic shift: signed
    pmask = [seg(2 + wi).astype(jnp.uint32) for wi in range(nw)]
    fsk = seg(2 + nw) & 0xFFFF

    # window words: word wi lives at plane rows [wi*nr:(wi+1)*nr]
    sw = [stw_p[wi * nr:(wi + 1) * nr, :].astype(jnp.uint32)
          for wi in range(nw)]
    sv = stv_p[...]                      # biased value ids (1 = NONE)
    alive = alive_p[...] != 0

    osafe = obit.astype(jnp.uint32)
    mybits = sw[0] >> osafe
    for wi in range(1, nw):
        mybits = jnp.where(o_word == wi, sw[wi] >> osafe, mybits)
    not_set = (mybits & jnp.uint32(1)) == 0
    preds_in = (sw[0] & pmask[0]) == pmask[0]
    version = lax.population_count(
        sw[0] & jnp.uint32(upds[0])).astype(jnp.int32)
    for wi in range(1, nw):
        preds_in = preds_in & ((sw[wi] & pmask[wi]) == pmask[wi])
        version = version + lax.population_count(
            sw[wi] & jnp.uint32(upds[wi])).astype(jnp.int32)
    # per-STATE ceiling prune: a state dies when any not-yet-linearized
    # window op has rceil < version (equivalently version > the segment
    # min ceiling). version is constant across a state's wk-lane
    # segment, so the min-reduce collapses to a segment-OR of a
    # violation indicator — ONE matmul against the block-diagonal
    # segment-membership matrix (0/1 bf16, f32 accumulate: exact)
    bad = (not_set & (rceil < version)).astype(jnp.bfloat16)
    segbad = lax.dot_general(bad, mseg_p[...],
                             (((1,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32)
    alive = alive & (version <= ceilb) & (segbad < 0.5)

    is_read = fsk == (1 + READ)
    is_write = fsk == (1 + WRITE)
    is_cas = fsk == (1 + CAS)
    no_assert = rver == jnp.int32(-32768)
    ver_ok = no_assert | (is_read & (rver == version)) | \
        ((is_write | is_cas) & (rver == version + 1))
    read_ok = is_read & ((a1 == 0) | (a1 == sv))
    model_ok = read_ok | is_write | (is_cas & (a1 == sv))

    bitb = jnp.uint32(1) << osafe
    nwf = [sw[wi] | jnp.where(o_word == wi, bitb, jnp.uint32(0))
           for wi in range(nw)] if nw > 1 else [sw[0] | bitb]
    # slide: the `shift` lowest bits of the (nw*32)-bit window fall off
    # and must all be set; per-word low masks with clamped shifts
    sh = shift

    def low_mask(wi):
        k = jnp.clip(sh - 32 * wi, 0, 32)
        ks = jnp.minimum(k, 31).astype(jnp.uint32)
        return jnp.where(k >= 32, jnp.uint32(0xFFFFFFFF),
                         (jnp.uint32(1) << ks) - jnp.uint32(1))

    slide_ok = (nwf[0] & low_mask(0)) == low_mask(0)
    for wi in range(1, nw):
        slide_ok = slide_ok & ((nwf[wi] & low_mask(wi)) == low_mask(wi))
    # shifted window: (w_hi..w_lo) >> sh, word-wise. sh is a per-row
    # SCALAR, so decompose sh = 32*k_off + r_off with a where-chain
    # over the (static, <= nw) possible word offsets and clamped lane
    # shifts (no lane ever shifts by >= 32, which would be UB) — the
    # generic form of the old nw<=2 special cases
    zero_p = jnp.zeros_like(nwf[0])
    k_off = sh // 32                     # scalar word offset, 0..nw
    r_off = sh % 32
    rsafe = jnp.minimum(r_off, 31).astype(jnp.uint32)
    carry_amt = jnp.clip(32 - r_off, 1, 31).astype(jnp.uint32)
    padded = list(nwf) + [zero_p] * (nw + 1)
    new_w = []
    for i in range(nw):
        lo_w = zero_p
        hi_w = zero_p
        for ko in range(nw + 1):
            lo_w = jnp.where(k_off == ko, padded[i + ko], lo_w)
            hi_w = jnp.where(k_off == ko, padded[i + ko + 1], hi_w)
        carry = jnp.where(r_off == 0, jnp.uint32(0), hi_w << carry_amt)
        new_w.append((lo_w >> rsafe) | carry)

    valid = (alive & (fsk > 0) & not_set & preds_in
             & ver_ok & model_ok & slide_ok)
    new_v = jnp.where(is_read, sv, jnp.where(is_write, a1, a2))

    # partial candidate dedupe (soundness-free: only kills candidates
    # identical to a SURVIVING one); the exact frontier dedupe below is
    # what stops compounding, this pass just relieves capacity pressure
    # within a wave. Stack [w words, v, valid] so each compare needs
    # ONE roll.
    nwb = [lax.bitcast_convert_type(x, jnp.int32) for x in new_w]
    vld = valid.astype(jnp.int32)
    srow_f = lax.broadcasted_iota(jnp.int32, (nr, 128), 0)
    stk = jnp.concatenate(nwb + [new_v, vld], axis=0)

    def blocks(r):
        ws = [r[wi * nr:(wi + 1) * nr] for wi in range(nw)]
        return ws, r[nw * nr:(nw + 1) * nr], r[(nw + 1) * nr:]

    def same_mask(r, guard):
        ws, v2, vl2 = blocks(r)
        eq = (nwb[0] == ws[0])
        for wi in range(1, nw):
            eq = eq & (nwb[wi] == ws[wi])
        return eq & (new_v == v2) & (vl2 != 0) & guard

    dup = srow_f < 0             # all-false plane
    for d in range(1, min(nr, 8)):       # vs candidate d sublanes above
        dup = dup | same_mask(pltpu.roll(stk, d, 0), srow_f >= d)
    for gs in range(1, segk):            # vs segments to the left
        dd = wk * gs
        dup = dup | same_mask(pltpu.roll(stk, dd, 1), lane >= dd)
    valid = valid & ~dup

    # dense ranks: exclusive global prefix sum in row-major slot order,
    # as TWO matmul reductions (bf16 0/1 operands, f32 accumulate —
    # exact for counts <= NP): lanes-before via the strict-lower
    # triangular matrix, rows-above via a tiny (nr, nr) triangle
    vi = valid.astype(jnp.int32)
    vb = valid.astype(jnp.bfloat16)
    lanes_before = lax.dot_general(vb, plane_p[...],
                                   (((1,), (0,)), ((), ())),
                                   preferred_element_type=jnp.float32)
    rowtot_b = lax.dot_general(
        vb, jnp.ones((128, 128), jnp.bfloat16),
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)       # (nr, 128) row totals
    rio_t = lax.broadcasted_iota(jnp.int32, (nr, nr), 0)
    cio_t = lax.broadcasted_iota(jnp.int32, (nr, nr), 1)
    tri_r = (rio_t > cio_t).astype(jnp.bfloat16)  # strict lower (nr, nr)
    rows_above = lax.dot_general(
        tri_r, rowtot_b.astype(jnp.bfloat16),
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)       # (nr, 128)
    rank = (lanes_before + rows_above).astype(jnp.int32)

    # flags BEFORE compaction: acceptance is witness-based; overflow =
    # any candidate ranked past capacity
    last = jnp.where(kk + 1 == R, 1, 0)  # scalar 0/1
    acc_p[0:nr, :] = acc_p[0:nr, :] | (vi * last)
    ovf_p[0:nr, :] = ovf_p[0:nr, :] | (valid & (rank >= F)).astype(
        jnp.int32)
    peak_p[0:nr, :] = jnp.maximum(peak_p[0:nr, :],
                                  jnp.where(valid, rank + 1, 0))
    wav_p[...] = wav_p[...] + (alive_p[...] != 0).astype(jnp.int32)

    rank = jnp.where(valid, rank, np_ + 7)
    rs[...] = rank
    r_flat = rs.reshape(1, np_)[...]
    rio = lax.broadcasted_iota(jnp.int32, (F, np_), 0)
    # bf16 one-hot: payloads ride as 8-bit limbs — exact in bf16 — and
    # ALL limbs compact in ONE matmul via a (PL, NP) lhs
    A = (jnp.broadcast_to(r_flat, (F, np_)) == rio).astype(jnp.bfloat16)

    limbs = []
    for wi in range(nw):
        x = nwb[wi]
        limbs += [(x & 0xFF), ((x >> 8) & 0xFF), ((x >> 16) & 0xFF),
                  ((x >> 24) & 0xFF)]
    limbs += [(new_v & 0xFF), ((new_v >> 8) & 0xFF), vi]
    for i, pl_ in enumerate(limbs):
        xs[nr * i:nr * i + nr, :] = pl_
    lhs = xs.reshape(pl, np_)[...].astype(jnp.bfloat16)
    outp = lax.dot_general(lhs, A, (((1,), (1,)), ((), ())),
                           preferred_element_type=jnp.float32)  # (PL, F)
    l_fill = pl - 1
    filled = outp[l_fill:l_fill + 1]

    # EXACT frontier dedupe on the compacted (1, F) rows: kill a row
    # identical to a lower-ranked filled row. Combined int32 keys keep
    # it at one roll per compare.
    keys = []
    for wi in range(nw):
        base = 4 * wi
        keys.append(outp[base + 0:base + 1].astype(jnp.int32)
                    + (outp[base + 1:base + 2].astype(jnp.int32) << 8)
                    + (outp[base + 2:base + 3].astype(jnp.int32) << 16)
                    + (outp[base + 3:base + 4].astype(jnp.int32) << 24))
    keys.append(outp[4 * nw:4 * nw + 1].astype(jnp.int32)
                + (outp[4 * nw + 1:4 * nw + 2].astype(jnp.int32) << 8))
    fi = (filled > 0.5).astype(jnp.int32)
    keycat = jnp.concatenate(keys + [fi], axis=0)       # (nw+2, F)
    nk = len(keys)
    lane_f = lax.broadcasted_iota(jnp.int32, (1, F), 1)
    dupr = lane_f < 0
    for d in range(1, F):
        r3 = pltpu.roll(keycat, d, 1)
        eq = (keys[0] == r3[0:1])
        for j in range(1, nk):
            eq = eq & (keys[j] == r3[j:j + 1])
        dupr = dupr | (eq & (r3[nk:nk + 1] != 0) & (lane_f >= d))
    filled = jnp.where(dupr, 0.0, filled)

    # pack all limb rows back into (nr, 128) planes with two more
    # matmuls: expand (PL, F) -> (nr*PL, F) sublane-replicated rows
    # masked to their residue, then scatter segments via D
    prow = lax.broadcasted_iota(jnp.int32, (pl, F), 0)
    outd = jnp.where(prow == l_fill,
                     jnp.broadcast_to(filled, (pl, F)), outp)
    jio = lax.broadcasted_iota(jnp.int32, (nr * pl, pl), 0)
    iio = lax.broadcasted_iota(jnp.int32, (nr * pl, pl), 1)
    E = ((jio // nr) == iio).astype(jnp.bfloat16)       # (nr*PL, PL)
    oute = lax.dot_general(E, outd.astype(jnp.bfloat16),
                           (((1,), (0,)), ((), ())),
                           preferred_element_type=jnp.float32)
    jio2 = lax.broadcasted_iota(jnp.int32, (nr * pl, F), 0)
    rio2 = lax.broadcasted_iota(jnp.int32, (nr * pl, F), 1)
    M1t = ((rio2 % nr) == (jio2 % nr)).astype(jnp.float32)
    tmp = (oute * M1t).astype(jnp.bfloat16)             # (nr*PL, F)
    rioD = lax.broadcasted_iota(jnp.int32, (F, 128), 0)
    lioD = lax.broadcasted_iota(jnp.int32, (F, 128), 1)
    D = ((rioD // nr) == (lioD // wk)).astype(jnp.bfloat16)
    planes = lax.dot_general(tmp, D, (((1,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32)

    def limb_plane(i):
        return planes[nr * i:nr * i + nr, :].astype(jnp.int32)

    fplane = limb_plane(l_fill)
    for wi in range(nw):
        base = 4 * wi
        stw_p[wi * nr:(wi + 1) * nr, :] = jnp.where(
            fplane != 0,
            limb_plane(base) + (limb_plane(base + 1) << 8)
            + (limb_plane(base + 2) << 16) + (limb_plane(base + 3) << 24),
            0)
    stv_p[...] = jnp.where(
        fplane != 0,
        limb_plane(4 * nw) + (limb_plane(4 * nw + 1) << 8), 0)
    alive_p[...] = fplane


def _make_kernel(batched: bool, wk: int):
    nw, nr, np_, segk, pl_n, tlanes = _dims(wk)

    def kernel(tab_ref, scal_ref, out_ref, stw_p, stv_p, alive_p, xs,
               rs, acc_p, ovf_p, peak_p, wav_p, mseg_p, plane_p, sm):
        import jax
        import jax.numpy as jnp
        from jax import lax
        from jax.experimental import pallas as pl
        from jax.experimental.pallas import tpu as pltpu

        # batched refs have their leading key dim squeezed by the
        # BlockSpec (None, ...) — the body is identical either way
        kk = pl.program_id(1) if batched else pl.program_id(0)
        sub = kk % TSUB

        @pl.when(kk == 0)
        def _init():
            lane = lax.broadcasted_iota(jnp.int32, (nr, 128), 1)
            srow = lax.broadcasted_iota(jnp.int32, (nr, 128), 0)
            init = ((srow == 0) & (lane < wk)).astype(jnp.int32)
            alive_p[...] = init
            stw_p[...] = jnp.zeros((nw * nr, 128), jnp.int32)
            stv_p[...] = init  # biased NONE_VAL = 0 + 1
            acc_p[...] = jnp.zeros((nr, 128), jnp.int32)
            ovf_p[...] = jnp.zeros((nr, 128), jnp.int32)
            peak_p[...] = init
            wav_p[...] = jnp.zeros((nr, 128), jnp.int32)
            # constant reduction matrices for the wave body's MXU
            # reductions (built once, reused every wave): segment
            # membership and strict-lower lane triangle
            l1 = lax.broadcasted_iota(jnp.int32, (128, 128), 0)
            l2 = lax.broadcasted_iota(jnp.int32, (128, 128), 1)
            mseg_p[...] = ((l1 // wk) == (l2 // wk)).astype(jnp.bfloat16)
            plane_p[...] = (l1 < l2).astype(jnp.bfloat16)
            sm[0] = 0

        row_t = tab_ref[pl.ds(sub, 1), :]
        shift = scal_ref[sub, S_SHIFT]
        ceilb = scal_ref[sub, S_CEILB]
        upds = [scal_ref[sub, S_UPD0 + wi] for wi in range(nw)]
        R = scal_ref[sub, S_R]

        @pl.when(sm[0] == 0)
        def _wave():
            _wave_body(jnp, lax, pl, pltpu, wk, row_t, shift, ceilb,
                       upds, kk, R, stw_p, stv_p, alive_p, xs,
                       rs, acc_p, ovf_p, peak_p, wav_p, mseg_p, plane_p)

        # frontier-death check: one vector->scalar sync every
        # DONE_EVERY waves lets dead/padding steps skip the body
        @pl.when((kk % DONE_EVERY == DONE_EVERY - 1) & (sm[0] == 0))
        def _check():
            sm[0] = jnp.where(jnp.any(alive_p[...] != 0), 0, 1)

        nprog = pl.num_programs(1) if batched else pl.num_programs(0)

        @pl.when(kk == nprog - 1)
        def _emit():
            out_ref[0:8, :] = _fold8(jnp, pltpu, acc_p[...], nr)
            out_ref[8:16, :] = _fold8(jnp, pltpu, ovf_p[...], nr)
            out_ref[16:24, :] = _fold8(jnp, pltpu, peak_p[...], nr)
            out_ref[24:32, :] = _fold8(jnp, pltpu, wav_p[...], nr)

    return kernel


def _fold8(jnp, pltpu, plane, nr: int):
    """Fold an (nr, 128) flag plane into (8, 128) by maximum — the out
    block stays (32, 128) for every window width."""
    if nr == 8:
        return plane
    out = plane[0:8, :]
    for b in range(1, nr // 8):
        out = jnp.maximum(out, plane[8 * b:8 * b + 8, :])
    return out


def _scratch_shapes(wk: int):
    from jax.experimental.pallas import tpu as pltpu
    import jax.numpy as jnp
    nw, nr, np_, segk, pl_n, tlanes = _dims(wk)
    return [
        pltpu.VMEM((nw * nr, 128), jnp.int32),   # stw_p (mask words)
        pltpu.VMEM((nr, 128), jnp.int32),        # stv_p
        pltpu.VMEM((nr, 128), jnp.int32),        # alive_p
        pltpu.VMEM((nr * pl_n, 128), jnp.int32),  # xs (limb stack)
        pltpu.VMEM((nr, 128), jnp.int32),        # rs (ranks)
        pltpu.VMEM((nr, 128), jnp.int32),        # acc_p
        pltpu.VMEM((nr, 128), jnp.int32),        # ovf_p
        pltpu.VMEM((nr, 128), jnp.int32),        # peak_p
        pltpu.VMEM((nr, 128), jnp.int32),        # wav_p
        pltpu.VMEM((128, 128), jnp.bfloat16),    # mseg_p (const)
        pltpu.VMEM((128, 128), jnp.bfloat16),    # plane_p (const)
        pltpu.SMEM((8,), jnp.int32),
    ]


@functools.lru_cache(maxsize=None)
def _call_single(r_pad: int, wk: int, interpret: bool):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    nw, nr, np_, segk, pl_n, tlanes = _dims(wk)
    call = pl.pallas_call(
        _make_kernel(False, wk),
        grid=(r_pad,),
        in_specs=[
            pl.BlockSpec((TSUB, tlanes), lambda k: (k // TSUB, 0)),
            pl.BlockSpec((TSUB, SCAL_COLS), lambda k: (k // TSUB, 0),
                         memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec((32, 128), lambda k: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((32, 128), jnp.int32),
        scratch_shapes=_scratch_shapes(wk),
        interpret=interpret,
        compiler_params=_tpu_compiler_params(
            pltpu, dimension_semantics=("arbitrary",)),
    )

    def run(i32, u16):
        from jax import lax
        tab, scal = _build_tables_one(jnp, lax, i32, u16, r_pad, wk)
        return _summarize(jnp, call(tab, scal))

    return jax.jit(run)


@functools.lru_cache(maxsize=None)
def _call_batch(k_keys: int, r_pad: int, wk: int, interpret: bool):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    nw, nr, np_, segk, pl_n, tlanes = _dims(wk)
    call = pl.pallas_call(
        _make_kernel(True, wk),
        grid=(k_keys, r_pad),
        in_specs=[
            pl.BlockSpec((None, TSUB, tlanes),
                         lambda key, k: (key, k // TSUB, 0)),
            pl.BlockSpec((None, TSUB, SCAL_COLS),
                         lambda key, k: (key, k // TSUB, 0),
                         memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec((None, 32, 128), lambda key, k: (key, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((k_keys, 32, 128), jnp.int32),
        scratch_shapes=_scratch_shapes(wk),
        interpret=interpret,
        compiler_params=_tpu_compiler_params(
            pltpu, dimension_semantics=("arbitrary", "arbitrary")),
    )

    # inputs are compact per-op arrays shipped 2D (the tunnel moves 3D
    # arrays pathologically slowly); frames build on device — chunked
    # vmap (batch_size) bounds the (chunk, r_pad, wk, wk) pred-bit
    # intermediates to ~30 MB while cutting the per-key sequential
    # scan that dominated the r4 build time (~0.1 s at 512 keys)
    def run(i32_2d, u16_2d):
        from jax import lax
        i32r = i32_2d.reshape(k_keys, r_pad, 4)
        u16r = u16_2d.reshape(k_keys, r_pad, 12)

        def one(args):
            return _build_tables_one(jnp, lax, args[0], args[1],
                                     r_pad, wk)

        tabs, scals = lax.map(one, (i32r, u16r),
                              batch_size=min(16, k_keys))
        return _summarize(jnp, call(tabs, scals))

    return jax.jit(run)


@functools.lru_cache(maxsize=None)
def _call_batch_sharded(k_pad: int, r_pad: int, wk: int, n_dev: int,
                        interpret: bool):
    """The multi-chip form of the fused batch: shard_map over a
    ("key",) device mesh, each device running the SAME one-dispatch
    pallas batch on its k_pad/n_dev key shard. Keys are independent,
    so the layout is a pure scatter — no collectives ride the ICI —
    which is exactly SURVEY §2.3's key-level DP axis for the
    production fast path (a v5e-8 runs 8 one-chip dispatches
    concurrently instead of queueing one)."""
    import jax
    from jax.sharding import Mesh, PartitionSpec as P

    assert k_pad % n_dev == 0
    per = _call_batch(k_pad // n_dev, r_pad, wk, interpret)
    mesh = Mesh(np.array(jax.devices()[:n_dev]), ("key",))
    shard_map, vma_kw = _shard_map()
    sharded = shard_map(
        per,
        mesh=mesh,
        in_specs=(P("key"), P("key")),
        out_specs=P("key"),
        # the pallas_call inside can't annotate varying-mesh-axes on
        # its out_shape; every output IS per-shard (key-varying)
        **vma_kw)
    return jax.jit(sharded)


def _batch_geometry(K: int):
    """(k_pad, n_dev) for a K-key chunk: with one device, bucket to the
    next power of two (bounds the jit cache at O(log K) variants);
    with a mesh, pad the key axis to pow2-bucketed keys PER DEVICE
    times every visible device — all devices shard, any device count,
    and padding keys are zero rows whose grid steps die at the first
    frontier-death check (the same layout rule as the jnp path's
    _check_bucket_group)."""
    import jax

    n_dev = min(len(jax.devices()), K)  # never pad a tiny chunk wider
    if n_dev > 1:
        per_dev = 1
        while per_dev * n_dev < K:
            per_dev *= 2
        return per_dev * n_dev, n_dev
    k_pad = 1
    while k_pad < K:
        k_pad *= 2
    return k_pad, 1


def _batch_call_for(k_pad: int, r_pad: int, wk: int, n_dev: int,
                    interpret: bool):
    """The single-device or mesh-sharded fused batch entry for a
    geometry from ``_batch_geometry`` (the flattened (k_pad * r_pad,
    ...) inputs are key-major, so an even axis-0 split IS a key
    split)."""
    if n_dev > 1:
        return _call_batch_sharded(k_pad, r_pad, wk, n_dev, interpret)
    return _call_batch(k_pad, r_pad, wk, interpret)


def _summarize(jnp, out):
    """Fold the per-key (32, 128) flag block into 4 per-key scalars
    [accepted, overflowed, peak, waves] ON DEVICE. The raw block is
    8.4 MB at 512 keys — ~0.2 s of readback through the tunnel's
    30-50 MB/s — where the summary is 8 KB."""
    acc = out[..., 0:8, :].max(axis=(-2, -1))
    ovf = out[..., 8:16, :].max(axis=(-2, -1))
    peak = out[..., 16:24, :].max(axis=(-2, -1))
    wav = out[..., 24:32, :].max(axis=(-2, -1))
    return jnp.stack([acc, ovf, peak, wav], axis=-1)


def _decode(out: np.ndarray, p: Packed) -> dict:
    acc = bool(out[0])
    ovf = bool(out[1])
    peak = int(out[2])
    waves = int(out[3])
    if acc:
        res = {"valid?": True, "waves": waves, "peak-frontier": peak,
               "ops": p.R, "info-ops": 0, "engine": "mxu-wave"}
        if ovf:
            res["overflowed-en-route"] = True
        return res
    if ovf:
        return {"valid?": "unknown", "overflow": True,
                "reason": f"mxu frontier overflow (capacity {F})",
                "waves": waves, "peak-frontier": peak}
    return {"valid?": False, "waves": waves, "peak-frontier": peak,
            "ops": p.R, "info-ops": 0, "engine": "mxu-wave",
            "stuck-at-depth": waves}


def check_packed_mxu(p: Packed, device=None) -> dict | None:
    """Run the MXU wave kernel on one packed history; None when
    unsupported, an overflow-unknown when capacity was exceeded.
    ``device`` commits the dispatch to one chip (the sharded checker
    service's per-group placement)."""
    import jax
    import jax.numpy as jnp

    if not supported(p):
        return None
    tel = telemetry.current()
    r_pad = max(bucket(p.R), TSUB)
    i32, u16 = pack_perop(p, r_pad)
    interpret = jax.default_backend() != "tpu"
    if device is not None:
        def _put(x):
            return jax.device_put(x, device)
    else:
        _put = jnp.asarray
    with tel.span("mxu.dispatch", ops=p.R, w=p.w) as sp:
        out = np.asarray(_call_single(r_pad, p.w, interpret)(
            _put(i32), _put(u16)))
        res = _decode(out, p)
        sp.set(valid=res.get("valid?"),
               peak_frontier=res.get("peak-frontier"))
    tel.counter("mxu.dispatches")
    return res


def launch_packed_batch_mxu(packs: list, device=None) -> list:
    """Stage + asynchronously launch the supported packs, one pallas
    dispatch per (R-bucket, window-width, BATCH_CHUNK) chunk. Returns a
    list of (index_chunk, device_future, pack_chunk) launch records for
    ``collect_packed_batch_mxu``: all launches go out before any
    readback, so a multi-group batch pays one synchronization total.
    ``device`` commits every chunk to one chip (single-device geometry
    — the sharded checker service owns cross-chip placement at the
    group level, so the fused batch must not scatter over the mesh
    behind its back)."""
    import jax
    import jax.numpy as jnp

    interpret = jax.default_backend() != "tpu"
    tel = telemetry.current()
    if device is not None:
        def _put(x):
            return jax.device_put(x, device)
    else:
        _put = jnp.asarray
    groups: dict = {}
    for i, p in enumerate(packs):
        if supported(p):
            groups.setdefault((max(bucket(p.R), TSUB), p.w), []).append(i)
    launched = []
    with tel.span("mxu.launch", keys=len(packs)) as sp:
        for (r_pad, wk), idxs in groups.items():
            for lo_i in range(0, len(idxs), BATCH_CHUNK):
                chunk = idxs[lo_i:lo_i + BATCH_CHUNK]
                # bucket the chunk count so the jit cache holds O(log K)
                # variants instead of one compile per distinct batch
                # size; padding keys are all-zero (R=0) rows whose grid
                # steps die at the first frontier-death check
                if device is not None:
                    k_pad = 1
                    while k_pad < len(chunk):
                        k_pad *= 2
                    n_dev = 1
                else:
                    k_pad, n_dev = _batch_geometry(len(chunk))
                i32s, u16s = pack_perop_batch([packs[i] for i in chunk],
                                              r_pad, k_pad)
                dev = _batch_call_for(k_pad, r_pad, wk, n_dev,
                                      interpret)(
                    _put(i32s.reshape(k_pad * r_pad, 4)),
                    _put(u16s.reshape(k_pad * r_pad, 12)))
                launched.append((chunk, dev,
                                 [packs[i] for i in chunk]))
        sp.set(chunks=len(launched),
               supported=sum(len(v) for v in groups.values()))
    tel.counter("mxu.dispatches", len(launched))
    return launched


def collect_packed_batch_mxu(launched: list, results: list) -> None:
    """Read back launch records from ``launch_packed_batch_mxu`` and
    decode into ``results`` (indexed as the original pack list)."""
    with telemetry.current().span("mxu.collect",
                                  chunks=len(launched)):
        for chunk, dev, chunk_packs in launched:
            # graftlint: ignore[JAX002] collect phase: one readback per launch record is its design
            out = np.asarray(dev)
            for j, (i, p) in enumerate(zip(chunk, chunk_packs)):
                results[i] = _decode(out[j], p)


def check_packed_batch_mxu(packs: list, device=None) -> list | None:
    """Check many packed histories in ONE pallas dispatch per
    (R-bucket, window-width) chunk, all launched before any readback.
    Returns per-pack results aligned with input order; packs the
    kernel can't take (wide window, info ops, id overflow) get None
    entries for the caller's per-key fallback. Returns None outright
    when NO pack is supported. ``device`` commits every chunk to one
    chip (see :func:`launch_packed_batch_mxu`)."""
    if not packs or not any(supported(p) for p in packs):
        return None
    results: list = [None] * len(packs)
    collect_packed_batch_mxu(launch_packed_batch_mxu(packs,
                                                     device=device),
                             results)
    return results
