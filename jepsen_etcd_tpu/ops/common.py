"""Shared kernel scaffolding: jit-cache bucketing and device dispatch.

Every TPU kernel in this package pads its inputs to bucketed power-of-two
shapes (so jit caches stay warm across histories) and falls back to a
host implementation below a size cutoff (device dispatch would dominate).
"""

from __future__ import annotations

import math

try:
    import jax  # noqa: F401
    HAVE_JAX = True
except Exception:  # pragma: no cover - jax is baked in
    HAVE_JAX = False


import os

_CACHE_ON = False


def enable_compile_cache(path: str | None = None) -> None:
    """Point XLA's persistent compilation cache at a repo-local dir so
    kernel compiles (W=128 wave loops run minutes of XLA time) amortize
    across processes — the CLI, bench, tests, and the graft entry all
    call this. No-op if jax is absent or
    JEPSEN_ETCD_TPU_NO_COMPILE_CACHE is set."""
    global _CACHE_ON
    if _CACHE_ON or os.environ.get("JEPSEN_ETCD_TPU_NO_COMPILE_CACHE") \
            or not HAVE_JAX:
        return
    try:
        import jax
        if jax.default_backend() == "cpu":
            # XLA:CPU AOT cache entries pin host machine features and
            # can SIGILL when reloaded under different flags; CPU
            # compiles are cheap, so cache only accelerator backends
            return
        if path is None:
            path = os.path.join(os.path.dirname(os.path.dirname(
                os.path.dirname(os.path.abspath(__file__)))),
                ".jax_cache")
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          0.5)
        _CACHE_ON = True
    except Exception:  # cache is an optimization, never a failure
        pass


class UnsupportedValue(Exception):
    """An op value the dense encodings can't represent faithfully;
    callers fall back to the Python oracle."""


_LIST, _TUPLE, _DICT, _SET = object(), object(), object(), object()


def _canon(v):
    """Hashable canonical form preserving Python == semantics (and the
    list/tuple/dict/set type distinctions the sequential models' ==
    sees). Unordered containers canonicalize to frozensets so == dicts
    (e.g. {True: 'x'} == {1: 'x'}) share a form regardless of order."""
    if isinstance(v, list):
        return (_LIST,) + tuple(_canon(x) for x in v)
    if isinstance(v, tuple):
        return (_TUPLE,) + tuple(_canon(x) for x in v)
    if isinstance(v, dict):
        return (_DICT, frozenset((k, _canon(x)) for k, x in v.items()))
    if isinstance(v, (set, frozenset)):
        return (_SET, frozenset(v))
    return v


class ValueIds:
    """Dense int ids for op values with id-equality iff value-equality
    under Python == — the comparison the sequential models use — so the
    packed encodings (TPU kernel, native oracle) can never disagree with
    the Python reference about whether two observed values match
    (1 == 1.0 == True share an id; '1' does not). None is id 0."""

    def __init__(self):
        self._map: dict = {}
        self.rev: dict = {0: None}

    def id(self, v) -> int:
        if v is None:
            return 0
        c = _canon(v)
        try:
            got = self._map.get(c)
        except TypeError as e:  # unhashable leaf (e.g. a set)
            raise UnsupportedValue(repr(v)) from e
        if got is None:
            got = len(self._map) + 1
            self._map[c] = got
            self.rev[got] = v
        return got


def register_value_sets(triples):
    """Classify register-language value ids over (f, a1, a2) triples
    (f: 0 read / 1 write / 2 cas; a1: read-expected|write-payload|
    cas-old; a2: cas-new; WILDCARD = -1 read asserts nothing).

    Returns (asserted, producible):
    - asserted: ids some step COMPARES against the register state
      (read expectations, cas olds);
    - producible: ids some step can MAKE the state (write payloads,
      cas news), plus 0 (the initial None).

    A producible id that is never asserted is a *dead value*: no guard
    distinguishes it from any other dead value, so all dead values can
    merge into one id without changing any verdict (the runs of the
    original and merged histories are in value-mapping bijection). And
    a cas whose old id is neither producible nor 0 can never fire.
    Both reductions collapse the otherwise-exponential space of
    crashed (:info) updates with distinct never-observed values —
    the dominant 'unknown' regime for faulted register histories."""
    asserted = set()
    producible = {0}
    for f, a1, a2 in triples:
        if f == 0:
            if a1 != -1:
                asserted.add(a1)
        elif f == 1:
            producible.add(a1)
        else:
            asserted.add(a1)
            producible.add(a2)
    return asserted, producible


def as_version(v) -> int:
    """An etcd version assertion as int, faithful to == against int
    model versions; raises UnsupportedValue for anything whose equality
    an int can't carry (non-integral or non-numeric)."""
    if isinstance(v, bool) or isinstance(v, int):
        iv = int(v)
    elif isinstance(v, float) and v.is_integer():
        iv = int(v)
    else:
        raise UnsupportedValue(f"version assertion {v!r}")
    if not -(2 ** 29) < iv < 2 ** 29:
        raise UnsupportedValue(f"version assertion {v!r} out of range")
    return iv


def bucket(n: int, minimum: int = 128) -> int:
    """Pad to the next power of two (min `minimum`)."""
    return max(minimum, 1 << max(0, math.ceil(math.log2(max(1, n)))))


def use_device(force_device: bool | None, n: int, cutoff: int,
               what: str) -> bool:
    """Resolve the force_device tri-state against availability and size.

    force_device=True demands the device (error without jax);
    False forces the host path; None picks by size.
    """
    if force_device and not HAVE_JAX:
        raise RuntimeError(f"{what}(force_device=True) but jax is "
                           "unavailable")
    return HAVE_JAX and force_device is not False \
        and (bool(force_device) or n >= cutoff)
