"""Shared kernel scaffolding: jit-cache bucketing and device dispatch.

Every TPU kernel in this package pads its inputs to bucketed power-of-two
shapes (so jit caches stay warm across histories) and falls back to a
host implementation below a size cutoff (device dispatch would dominate).
"""

from __future__ import annotations

import math

try:
    import jax  # noqa: F401
    HAVE_JAX = True
except Exception:  # pragma: no cover - jax is baked in
    HAVE_JAX = False


def bucket(n: int, minimum: int = 128) -> int:
    """Pad to the next power of two (min `minimum`)."""
    return max(minimum, 1 << max(0, math.ceil(math.log2(max(1, n)))))


def use_device(force_device: bool | None, n: int, cutoff: int,
               what: str) -> bool:
    """Resolve the force_device tri-state against availability and size.

    force_device=True demands the device (error without jax);
    False forces the host path; None picks by size.
    """
    if force_device and not HAVE_JAX:
        raise RuntimeError(f"{what}(force_device=True) but jax is "
                           "unavailable")
    return HAVE_JAX and force_device is not False \
        and (bool(force_device) or n >= cutoff)
