"""TPU edit-distance kernel: anti-diagonal wavefront DP.

The reference's watch checker measures per-thread log divergence with
clj-diff (Myers diff; ``watch.clj:328-357`` computes ``diff/edit-distance``
per thread against a canonical log). That distance is *indel* edit
distance (insertions + deletions, no substitution): ``ed = n + m - 2*LCS``.

The O(n*m) DP has a sequential dependency along rows but none along
anti-diagonals, so the TPU-native formulation sweeps diagonals: diag k
holds D[i, k-i] for all i, computed elementwise (VPU) from diags k-1 and
k-2 — a `lax.scan` over 2N steps of fully vectorized work, the classic
wavefront trick (the same shape as blockwise DP in sequence alignment).

Inputs are padded to bucketed sizes so jit caches stay warm; lengths are
runtime scalars, so one compiled kernel serves all logs in a bucket.
"""

from __future__ import annotations

import functools
from functools import partial

import numpy as np

from .common import HAVE_JAX, bucket as _bucket, use_device

if HAVE_JAX:
    import jax
    import jax.numpy as jnp

#: below this size the pure-python DP beats a device dispatch
CPU_CUTOFF = 128

INF = np.int32(2 ** 30)


if HAVE_JAX:

    @partial(jax.jit, static_argnames=("size",))
    def _indel_device_batch(a, b, n, m, size: int):
        """Batched wavefront: a, b int32[K, size]; n, m int32[K].
        Returns D[n_k, m_k] for every pair in ONE kernel — the diagonal
        sweep is inherently sequential (2*size tiny steps), so its cost
        is per-STEP latency; batching K pairs into the lanes makes the
        whole watch check pay it once instead of K times. The loop stops
        at max(n+m) rather than sweeping the padded tail."""
        K = a.shape[0]
        l = size + 1
        i_idx = jnp.arange(l, dtype=jnp.int32)[None, :]          # [1, l]
        gidx = jnp.clip(jnp.arange(l, dtype=jnp.int32) - 1, 0, size - 1)
        d0 = jnp.broadcast_to(
            jnp.where(i_idx == 0, 0, INF).astype(jnp.int32), (K, l))
        d1 = jnp.broadcast_to(
            jnp.where(i_idx <= 1, 1, INF).astype(jnp.int32), (K, l))
        nm = n + m
        res = jnp.where(nm == 0, 0,
                        jnp.where(nm == 1, 1, INF)).astype(jnp.int32)
        n_row = jnp.minimum(n, l - 1)[:, None]                   # [K, 1]
        kmax = jnp.max(nm)
        ai = jnp.take(a, gidx, axis=1)                           # [K, l]

        def cond(c):
            k = c[0]
            return k <= kmax

        def body(c):
            k, dm2, dm1, res = c
            j_idx = k - i_idx                                    # [1, l]
            bj = jnp.take(b, jnp.clip(k - 1 - jnp.arange(
                l, dtype=jnp.int32), 0, size - 1), axis=1)       # [K, l]
            match = ai == bj
            up = jnp.roll(dm1, 1, axis=1).at[:, 0].set(INF)
            diag = jnp.roll(dm2, 1, axis=1).at[:, 0].set(INF)
            dk = jnp.where(match, diag, jnp.minimum(up, dm1) + 1)
            dk = jnp.where(i_idx == 0, k, dk)
            dk = jnp.where(j_idx == 0, i_idx, dk)
            dk = jnp.where((j_idx < 0) | (i_idx > k), INF,
                           dk).astype(jnp.int32)
            at_n = jnp.take_along_axis(dk, n_row, axis=1)[:, 0]
            res = jnp.where(k == nm, at_n, res)
            return k + 1, dm1, dk, res

        _, _, _, res = jax.lax.while_loop(
            cond, body, (jnp.int32(2), d0, d1, res))
        return res


if HAVE_JAX:

    def _wavefront_pallas(size: int, K: int, interpret: bool = False):
        """Build the single-launch pallas wavefront for a (size, K)
        bucket: the ENTIRE 2*size-step diagonal sweep runs inside one
        kernel with the DP band held in VMEM, instead of 2*size XLA
        while-loop iterations each paying dispatch + HBM round trips
        for the loop carries. The hot state is three [K, LP] int32
        bands (current/previous diagonals and the sliding window of b)
        — a few hundred KB, far under the ~16 MB VMEM budget."""
        from jax.experimental import pallas as pl

        LP = size + 128  # lanes: holds l = size+1, multiple of 128
        KMAX = 2 * size + 1

        def kernel(a_ref, b_ref, nrow_ref, nm_ref, out_ref):
            i_idx = jax.lax.broadcasted_iota(jnp.int32, (K, LP), 1)
            nrow = nrow_ref[:]                         # [K, 1]
            nm = nm_ref[:]                             # [K, 1]
            ai = a_ref[:]                              # [K, LP]
            def concrete(x):
                # the loop body produces sublane-concrete layouts; inits
                # built purely from lane iota are sublane-replicated and
                # Mosaic rejects the back-edge relayout — blend in a
                # per-row loaded value (no-op condition) to pin the
                # concrete layout at entry
                return jnp.where(nrow < -(2 ** 30), 0, x)

            d0 = concrete(jnp.where(i_idx == 0, 0, INF))
            d1 = concrete(jnp.where(i_idx <= 1, 1, INF))
            # bj at k=2 holds b[k-1-i]: lane0 = b[1], lane1 = b[0]
            b0 = b_ref[:, 0:1]
            b1 = b_ref[:, 1:2]
            bj = concrete(jnp.where(i_idx == 0, b1,
                                    jnp.where(i_idx == 1, b0, -2)))
            res = concrete(jnp.where(nm + jnp.zeros_like(i_idx) == 0, 0,
                                     jnp.where(
                                         nm + jnp.zeros_like(i_idx) == 1,
                                         1, INF)))

            def step(k, carry):
                dm2, dm1, bj, res = carry
                j_idx = k - i_idx
                match = ai == bj
                up = jnp.where(i_idx == 0, INF,
                               jnp.roll(dm1, 1, axis=1))
                diag = jnp.where(i_idx == 0, INF,
                                 jnp.roll(dm2, 1, axis=1))
                dk = jnp.where(match, diag, jnp.minimum(up, dm1) + 1)
                dk = jnp.where(i_idx == 0, k, dk)
                dk = jnp.where(j_idx == 0, i_idx, dk)
                dk = jnp.where((j_idx < 0) | (i_idx > k), INF,
                               dk).astype(jnp.int32)
                sel = (i_idx == nrow) & (k == nm)
                res = jnp.where(sel, dk, res)
                # slide the b window: bj'[i] = b[k-i] = bj[i-1]. Lane-dim
                # dynamic loads must be 128-aligned on TPU, so read the
                # aligned block holding column k and mask-select the lane.
                kk = jnp.clip(k, 0, size - 1)
                start = pl.multiple_of((kk // 128) * 128, 128)
                block = b_ref[:, pl.ds(start, 128)]          # [K, 128]
                lane = jax.lax.broadcasted_iota(jnp.int32, (K, 128), 1)
                newcol = jnp.sum(
                    jnp.where(lane == kk % 128, block, 0), axis=1,
                    keepdims=True)
                bj = jnp.where(i_idx == 0, newcol,
                               jnp.roll(bj, 1, axis=1))
                return dm1, dk, bj, res

            _, _, _, res = jax.lax.fori_loop(2, KMAX, step,
                                             (d0, d1, bj, res))
            out_ref[:] = res

        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((K, LP), jnp.int32),
            interpret=interpret,
        )


    @functools.lru_cache(maxsize=None)
    def _wavefront_jitted(size: int, K: int, interpret: bool = False):
        call = _wavefront_pallas(size, K, interpret=interpret)

        def run(pa, pb, nrow, nm):
            res = call(pa, pb, nrow, nm)
            return jnp.take_along_axis(res, nrow, axis=1)[:, 0]

        return jax.jit(run)


def _use_pallas() -> bool:
    import os
    if os.environ.get("JEPSEN_ETCD_TPU_NO_PALLAS"):
        return False
    try:
        import jax
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def edit_distance_batch(canonical, logs: list,
                        force_device: bool | None = None,
                        force_pallas: bool | None = None) -> list[int]:
    """Indel edit distance of each log vs the canonical, in one device
    launch (the watch checker's per-thread divergence measure)."""
    lens = [len(l) for l in logs] + [len(canonical)]
    if not logs:
        return []
    if not use_device(force_device, max(lens), CPU_CUTOFF,
                      "edit_distance"):
        return [_indel_python(list(canonical), list(l)) for l in logs]
    enc = _encode([list(canonical)] + [list(l) for l in logs])
    ec, elogs = enc[0], enc[1:]
    size = _bucket(max(lens))
    K = len(logs)
    pa = np.full((K, size), -1, np.int32)
    pb = np.full((K, size), -2, np.int32)  # distinct pads never match
    n = np.full(K, len(ec), np.int32)
    m = np.zeros(K, np.int32)
    for k, el in enumerate(elogs):
        pa[k, :len(ec)] = ec
        pb[k, :len(el)] = el
        m[k] = len(el)
    pallas = _use_pallas() if force_pallas is None else force_pallas
    if pallas:
        Kp = -(-K // 8) * 8            # sublane-pad the batch
        LP = size + 128
        # the kernel holds ~6 [Kp, LP] int32 bands in VMEM (no grid
        # tiling over K); past the ~16 MB budget fall back to the XLA
        # wavefront rather than fail the Mosaic allocation
        if Kp * LP * 4 * 6 > 12 * 2 ** 20:
            pallas = False
    if pallas:
        pa_p = np.full((Kp, LP), -1, np.int32)
        pa_p[:K, 1:size + 1] = pa[:, :size]  # ai[i] = a[i-1] pre-gather
        pb_p = np.full((Kp, size), -2, np.int32)
        pb_p[:K] = pb
        nrow = np.zeros((Kp, 1), np.int32)
        nrow[:K, 0] = np.minimum(n, size)
        nm = np.full((Kp, 1), -1, np.int32)
        nm[:K, 0] = n + m
        # off-TPU (tests' CPU mesh) the kernel runs in interpret mode,
        # so the pallas path is exercised everywhere
        interpret = jax.default_backend() != "tpu"
        out = _wavefront_jitted(size, Kp, interpret)(
            jnp.asarray(pa_p), jnp.asarray(pb_p), jnp.asarray(nrow),
            jnp.asarray(nm))
        return [int(v) for v in np.asarray(out)[:K]]
    out = _indel_device_batch(jnp.asarray(pa), jnp.asarray(pb),
                              jnp.asarray(n), jnp.asarray(m), size)
    return [int(v) for v in np.asarray(out)]


def _indel_python(a, b) -> int:
    n, m = len(a), len(b)
    if n == 0 or m == 0:
        return n + m
    prev = list(range(m + 1))
    for i in range(1, n + 1):
        cur = [i] + [0] * m
        ai = a[i - 1]
        for j in range(1, m + 1):
            cur[j] = prev[j - 1] if ai == b[j - 1] else \
                min(prev[j], cur[j - 1]) + 1
        prev = cur
    return prev[m]


def _encode(seqs: list) -> list[np.ndarray]:
    """Map arbitrary hashable elements to dense int32 codes."""
    codes: dict = {}
    out = []
    for s in seqs:
        arr = np.empty(len(s), np.int32)
        for i, x in enumerate(s):
            arr[i] = codes.setdefault(x, len(codes))
        out.append(arr)
    return out


def edit_distance(a, b, force_device: bool | None = None) -> int:
    """Indel edit distance between two sequences of hashable elements
    (the K=1 case of the batched kernel)."""
    return edit_distance_batch(a, [b], force_device=force_device)[0]


def diff_report(canonical, log) -> dict:
    """Host-side insert/delete report (the clj-diff :diff analog),
    computed only for divergent logs."""
    import difflib
    sm = difflib.SequenceMatcher(a=list(canonical), b=list(log),
                                 autojunk=False)
    additions, deletions = [], []
    for tag, i1, i2, j1, j2 in sm.get_opcodes():
        if tag in ("replace", "delete"):
            deletions.append({"at": i1, "values": list(canonical[i1:i2])})
        if tag in ("replace", "insert"):
            additions.append({"at": i1, "values": list(log[j1:j2])})
    return {"additions": additions, "deletions": deletions}
