"""TPU edit-distance kernel: anti-diagonal wavefront DP.

The reference's watch checker measures per-thread log divergence with
clj-diff (Myers diff; ``watch.clj:328-357`` computes ``diff/edit-distance``
per thread against a canonical log). That distance is *indel* edit
distance (insertions + deletions, no substitution): ``ed = n + m - 2*LCS``.

The O(n*m) DP has a sequential dependency along rows but none along
anti-diagonals, so the TPU-native formulation sweeps diagonals: diag k
holds D[i, k-i] for all i, computed elementwise (VPU) from diags k-1 and
k-2 — a `lax.scan` over 2N steps of fully vectorized work, the classic
wavefront trick (the same shape as blockwise DP in sequence alignment).

Inputs are padded to bucketed sizes so jit caches stay warm; lengths are
runtime scalars, so one compiled kernel serves all logs in a bucket.
"""

from __future__ import annotations

from functools import partial

import numpy as np

from .common import HAVE_JAX, bucket as _bucket, use_device

if HAVE_JAX:
    import jax
    import jax.numpy as jnp

#: below this size the pure-python DP beats a device dispatch
CPU_CUTOFF = 128

INF = np.int32(2 ** 30)


if HAVE_JAX:

    @partial(jax.jit, static_argnames=("size",))
    def _indel_device(a, b, n, m, size: int):
        """a, b: int32[size] padded; n, m: actual lengths (traced).
        Returns D[n, m] where D[i,j] = i + j - 2 * LCS(a[:i], b[:j])."""
        l = size + 1  # diag vectors indexed by i in 0..size
        i_idx = jnp.arange(l, dtype=jnp.int32)

        # diag 0: D[0,0]=0 ; diag 1: D[0,1]=1, D[1,0]=1
        d0 = jnp.where(i_idx == 0, 0, INF).astype(jnp.int32)
        d1 = jnp.where(i_idx <= 1, 1, INF).astype(jnp.int32)

        def step(carry, k):
            dm2, dm1 = carry  # diags k-2 and k-1
            j_idx = k - i_idx  # j for each cell on diag k
            # gather compared elements (clip keeps gathers in-bounds;
            # out-of-range cells are masked below)
            ai = a[jnp.clip(i_idx - 1, 0, size - 1)]
            bj = b[jnp.clip(j_idx - 1, 0, size - 1)]
            match = ai == bj
            up = jnp.roll(dm1, 1).at[0].set(INF)      # D[i-1, j]
            left = dm1                                 # D[i, j-1]
            diag = jnp.roll(dm2, 1).at[0].set(INF)     # D[i-1, j-1]
            dk = jnp.where(match, diag,
                           jnp.minimum(up, left) + 1)
            # boundaries: i == 0 -> j ; j == 0 -> i
            dk = jnp.where(i_idx == 0, k, dk)
            dk = jnp.where(j_idx == 0, i_idx, dk)
            dk = jnp.where((j_idx < 0) | (i_idx > k), INF, dk).astype(
                jnp.int32)
            return (dm1, dk), dk[jnp.minimum(n, l - 1)]

        ks = jnp.arange(2, 2 * size + 1, dtype=jnp.int32)
        (_, _), at_n = jax.lax.scan(step, (d0, d1), ks)
        # at_n[t] = D[n, (t+2) - n]; we want D[n, m] -> t = n + m - 2
        full = jnp.concatenate([
            jnp.array([d0[jnp.minimum(n, l - 1)],
                       d1[jnp.minimum(n, l - 1)]], jnp.int32), at_n])
        return full[n + m]


def _indel_python(a, b) -> int:
    n, m = len(a), len(b)
    if n == 0 or m == 0:
        return n + m
    prev = list(range(m + 1))
    for i in range(1, n + 1):
        cur = [i] + [0] * m
        ai = a[i - 1]
        for j in range(1, m + 1):
            cur[j] = prev[j - 1] if ai == b[j - 1] else \
                min(prev[j], cur[j - 1]) + 1
        prev = cur
    return prev[m]


def _encode(seqs: list) -> list[np.ndarray]:
    """Map arbitrary hashable elements to dense int32 codes."""
    codes: dict = {}
    out = []
    for s in seqs:
        arr = np.empty(len(s), np.int32)
        for i, x in enumerate(s):
            arr[i] = codes.setdefault(x, len(codes))
        out.append(arr)
    return out


def edit_distance(a, b, force_device: bool | None = None) -> int:
    """Indel edit distance between two sequences of hashable elements."""
    n, m = len(a), len(b)
    if not use_device(force_device, max(n, m), CPU_CUTOFF,
                      "edit_distance"):
        return _indel_python(list(a), list(b))
    ea, eb = _encode([list(a), list(b)])
    size = _bucket(max(n, m))
    pa = np.full(size, -1, np.int32)
    pb = np.full(size, -2, np.int32)  # distinct pads can never match
    pa[:n] = ea
    pb[:m] = eb
    return int(_indel_device(jnp.asarray(pa), jnp.asarray(pb),
                             jnp.int32(n), jnp.int32(m), size))


def diff_report(canonical, log) -> dict:
    """Host-side insert/delete report (the clj-diff :diff analog),
    computed only for divergent logs."""
    import difflib
    sm = difflib.SequenceMatcher(a=list(canonical), b=list(log),
                                 autojunk=False)
    additions, deletions = [], []
    for tag, i1, i2, j1, j2 in sm.get_opcodes():
        if tag in ("replace", "delete"):
            deletions.append({"at": i1, "values": list(canonical[i1:i2])})
        if tag in ("replace", "insert"):
            additions.append({"at": i1, "values": list(log[j1:j2])})
    return {"additions": additions, "deletions": deletions}
