"""TPU edit-distance kernel: anti-diagonal wavefront DP.

The reference's watch checker measures per-thread log divergence with
clj-diff (Myers diff; ``watch.clj:328-357`` computes ``diff/edit-distance``
per thread against a canonical log). That distance is *indel* edit
distance (insertions + deletions, no substitution): ``ed = n + m - 2*LCS``.

The O(n*m) DP has a sequential dependency along rows but none along
anti-diagonals, so the TPU-native formulation sweeps diagonals: diag k
holds D[i, k-i] for all i, computed elementwise (VPU) from diags k-1 and
k-2 — a `lax.scan` over 2N steps of fully vectorized work, the classic
wavefront trick (the same shape as blockwise DP in sequence alignment).

Inputs are padded to bucketed sizes so jit caches stay warm; lengths are
runtime scalars, so one compiled kernel serves all logs in a bucket.
"""

from __future__ import annotations

from functools import partial

import numpy as np

from .common import HAVE_JAX, bucket as _bucket, use_device

if HAVE_JAX:
    import jax
    import jax.numpy as jnp

#: below this size the pure-python DP beats a device dispatch
CPU_CUTOFF = 128

INF = np.int32(2 ** 30)


if HAVE_JAX:

    @partial(jax.jit, static_argnames=("size",))
    def _indel_device_batch(a, b, n, m, size: int):
        """Batched wavefront: a, b int32[K, size]; n, m int32[K].
        Returns D[n_k, m_k] for every pair in ONE kernel — the diagonal
        sweep is inherently sequential (2*size tiny steps), so its cost
        is per-STEP latency; batching K pairs into the lanes makes the
        whole watch check pay it once instead of K times. The loop stops
        at max(n+m) rather than sweeping the padded tail."""
        K = a.shape[0]
        l = size + 1
        i_idx = jnp.arange(l, dtype=jnp.int32)[None, :]          # [1, l]
        gidx = jnp.clip(jnp.arange(l, dtype=jnp.int32) - 1, 0, size - 1)
        d0 = jnp.broadcast_to(
            jnp.where(i_idx == 0, 0, INF).astype(jnp.int32), (K, l))
        d1 = jnp.broadcast_to(
            jnp.where(i_idx <= 1, 1, INF).astype(jnp.int32), (K, l))
        nm = n + m
        res = jnp.where(nm == 0, 0,
                        jnp.where(nm == 1, 1, INF)).astype(jnp.int32)
        n_row = jnp.minimum(n, l - 1)[:, None]                   # [K, 1]
        kmax = jnp.max(nm)
        ai = jnp.take(a, gidx, axis=1)                           # [K, l]

        def cond(c):
            k = c[0]
            return k <= kmax

        def body(c):
            k, dm2, dm1, res = c
            j_idx = k - i_idx                                    # [1, l]
            bj = jnp.take(b, jnp.clip(k - 1 - jnp.arange(
                l, dtype=jnp.int32), 0, size - 1), axis=1)       # [K, l]
            match = ai == bj
            up = jnp.roll(dm1, 1, axis=1).at[:, 0].set(INF)
            diag = jnp.roll(dm2, 1, axis=1).at[:, 0].set(INF)
            dk = jnp.where(match, diag, jnp.minimum(up, dm1) + 1)
            dk = jnp.where(i_idx == 0, k, dk)
            dk = jnp.where(j_idx == 0, i_idx, dk)
            dk = jnp.where((j_idx < 0) | (i_idx > k), INF,
                           dk).astype(jnp.int32)
            at_n = jnp.take_along_axis(dk, n_row, axis=1)[:, 0]
            res = jnp.where(k == nm, at_n, res)
            return k + 1, dm1, dk, res

        _, _, _, res = jax.lax.while_loop(
            cond, body, (jnp.int32(2), d0, d1, res))
        return res


def edit_distance_batch(canonical, logs: list,
                        force_device: bool | None = None) -> list[int]:
    """Indel edit distance of each log vs the canonical, in one device
    launch (the watch checker's per-thread divergence measure)."""
    lens = [len(l) for l in logs] + [len(canonical)]
    if not logs:
        return []
    if not use_device(force_device, max(lens), CPU_CUTOFF,
                      "edit_distance"):
        return [_indel_python(list(canonical), list(l)) for l in logs]
    enc = _encode([list(canonical)] + [list(l) for l in logs])
    ec, elogs = enc[0], enc[1:]
    size = _bucket(max(lens))
    K = len(logs)
    pa = np.full((K, size), -1, np.int32)
    pb = np.full((K, size), -2, np.int32)  # distinct pads never match
    n = np.full(K, len(ec), np.int32)
    m = np.zeros(K, np.int32)
    for k, el in enumerate(elogs):
        pa[k, :len(ec)] = ec
        pb[k, :len(el)] = el
        m[k] = len(el)
    out = _indel_device_batch(jnp.asarray(pa), jnp.asarray(pb),
                              jnp.asarray(n), jnp.asarray(m), size)
    return [int(v) for v in np.asarray(out)]


def _indel_python(a, b) -> int:
    n, m = len(a), len(b)
    if n == 0 or m == 0:
        return n + m
    prev = list(range(m + 1))
    for i in range(1, n + 1):
        cur = [i] + [0] * m
        ai = a[i - 1]
        for j in range(1, m + 1):
            cur[j] = prev[j - 1] if ai == b[j - 1] else \
                min(prev[j], cur[j - 1]) + 1
        prev = cur
    return prev[m]


def _encode(seqs: list) -> list[np.ndarray]:
    """Map arbitrary hashable elements to dense int32 codes."""
    codes: dict = {}
    out = []
    for s in seqs:
        arr = np.empty(len(s), np.int32)
        for i, x in enumerate(s):
            arr[i] = codes.setdefault(x, len(codes))
        out.append(arr)
    return out


def edit_distance(a, b, force_device: bool | None = None) -> int:
    """Indel edit distance between two sequences of hashable elements
    (the K=1 case of the batched kernel)."""
    return edit_distance_batch(a, [b], force_device=force_device)[0]


def diff_report(canonical, log) -> dict:
    """Host-side insert/delete report (the clj-diff :diff analog),
    computed only for divergent logs."""
    import difflib
    sm = difflib.SequenceMatcher(a=list(canonical), b=list(log),
                                 autojunk=False)
    additions, deletions = [], []
    for tag, i1, i2, j1, j2 in sm.get_opcodes():
        if tag in ("replace", "delete"):
            deletions.append({"at": i1, "values": list(canonical[i1:i2])})
        if tag in ("replace", "insert"):
            additions.append({"at": i1, "values": list(log[j1:j2])})
    return {"additions": additions, "deletions": deletions}
