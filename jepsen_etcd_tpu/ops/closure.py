"""TPU transitive-closure kernel: the Elle cycle-detection engine.

The reference's Elle checkers (``append.clj:183-185``, ``wr.clj:87-92``
call into the Elle library) find cycles in a transaction dependency graph
with JVM graph traversals. The TPU-native re-design expresses cycle
detection as *boolean matrix closure by iterative squaring*: with
``R0 = A | I``, squaring k times covers all paths of length < 2^k, so
``ceil(log2 N)`` squarings reach the full transitive closure R*. Each
squaring is one big matmul — exactly what the MXU is for — and the
nested anomaly subgraphs Elle distinguishes (ww ⊂ ww|wr ⊂ ww|wr|rw, each
with/without realtime edges) batch into one ``[B, N, N]`` stack so all
levels close in a single vmapped kernel launch.

Matmuls run in bfloat16 with float32 accumulation (values are exactly
0/1, sums of positives cannot cancel, and the accumulator never
overflows at N ≤ ~1e6 — only zero/nonzero matters) and shapes are padded
to bucketed powers of two so jit caches stay warm across histories.

A node lies on a cycle iff some successor reaches back to it:
``on_cycle[i] = ∃j. A[i,j] ∧ R*[j,i]``.
"""

from __future__ import annotations

import math
from functools import partial

import numpy as np

from ..runner import telemetry
from .common import HAVE_JAX, bucket as _bucket, use_device

if HAVE_JAX:
    import jax
    import jax.numpy as jnp

#: below this node count, numpy squaring beats a device round-trip.
#: MEASURED (r4, 6 subgraphs of N nodes, iterative squaring, v5e
#: through axon): N=256 host 0.020 s vs device 0.149 s; N=512 host
#: 0.189 s vs 0.328 s; N=1024 host 1.53 s vs 0.68 s; N=2048 host
#: 13.2 s vs 1.95 s; N=4096 host 102 s vs 6.1 s. Crossover ~768 —
#: the device pays a ~0.1 s tunnel round trip, the host pays O(N^3).
CPU_CUTOFF = 768
#: at/above this node count (with >1 device), shard rows over the mesh
SHARD_CUTOFF = 1024


if HAVE_JAX:

    @partial(jax.jit, static_argnames=("iters",))
    def _closure_device(a: "jax.Array", iters: int):
        """a: [B, N, N] bool adjacency. Returns (reach [B,N,N] bool
        — reflexive-transitive closure — and on_cycle [B,N] bool).

        Squaring runs under a FIXPOINT EARLY-EXIT: R contains the
        identity, so R ⊆ R² and the reachable-pair popcount grows
        monotonically until the closure is reached; the while_loop
        stops at the first squaring that adds no pairs. ``iters``
        (ceil(log2 N)) stays the worst-case bound, but real dependency
        graphs converge in O(log diameter) squarings — typically 3-5
        at the append bench's shapes — and the O(B·N²) popcount is
        noise next to the O(B·N³) matmul it gates. int32 popcount is
        exact through B·N² < 2³¹ (N = 16384 at B = 6)."""
        n = a.shape[-1]
        eye = jnp.eye(n, dtype=bool)
        r0 = jnp.logical_or(a, eye[None, :, :]).astype(jnp.bfloat16)

        def cnt(r):
            return jnp.sum(r > 0, dtype=jnp.int32)

        def cond(c):
            i, _, grew = c
            return (i < iters) & grew

        def body(c):
            i, r, _ = c
            prod = jax.lax.dot_general(
                r, r, (((2,), (1,)), ((0,), (0,))),
                preferred_element_type=jnp.float32)
            r2 = (prod > 0).astype(jnp.bfloat16)
            return i + 1, r2, cnt(r2) > cnt(r)

        _, r, _ = jax.lax.while_loop(
            cond, body, (jnp.int32(0), r0, jnp.bool_(True)))
        reach = r > 0
        # A[i,j] & R*[j,i]: row-wise AND with the transpose, any over j
        on_cycle = jnp.any(
            jnp.logical_and(a, jnp.swapaxes(reach, -1, -2)), axis=-1)
        return reach, on_cycle

    from functools import lru_cache

    @lru_cache(maxsize=None)
    def _mesh(devs_key: tuple):
        from jax.sharding import Mesh
        return Mesh(np.array(jax.devices()), ("dp",))

    @lru_cache(maxsize=None)
    def _closure_sharded_jitted(iters: int, devs_key: tuple):
        """Row-sharded squaring: R is [B, N, N] with rows split over the
        mesh ('dp'); each R@R is a 1D-sharded matmul — XLA/GSPMD inserts
        the all-gather of the stationary operand over ICI (SURVEY §2.3
        "SCC via repeated boolean matmul under pjit sharding"). The
        sharding constraint in the loop body pins the layout so the
        gather happens once per squaring, not once per op."""
        from jax.sharding import NamedSharding, PartitionSpec as P
        mesh = _mesh(devs_key)
        sh = NamedSharding(mesh, P(None, "dp", None))

        @jax.jit
        def run(a):
            n = a.shape[-1]
            eye = jnp.eye(n, dtype=bool)
            r0 = jnp.logical_or(a, eye[None, :, :]).astype(jnp.bfloat16)
            r0 = jax.lax.with_sharding_constraint(r0, sh)

            def cnt(r):
                # cross-shard reduction; GSPMD inserts the all-reduce
                return jnp.sum(r > 0, dtype=jnp.int32)

            def cond(c):
                i, _, grew = c
                return (i < iters) & grew

            def body(c):
                i, r, _ = c
                prod = jax.lax.dot_general(
                    r, r, (((2,), (1,)), ((0,), (0,))),
                    preferred_element_type=jnp.float32)
                r2 = jax.lax.with_sharding_constraint(
                    (prod > 0).astype(jnp.bfloat16), sh)
                return i + 1, r2, cnt(r2) > cnt(r)

            _, r, _ = jax.lax.while_loop(
                cond, body, (jnp.int32(0), r0, jnp.bool_(True)))
            reach = r > 0
            on_cycle = jnp.any(
                jnp.logical_and(a, jnp.swapaxes(reach, -1, -2)), axis=-1)
            return reach, on_cycle

        return run, sh

    def _closure_device_sharded(pad: np.ndarray, iters: int):
        # str(device) is a stable platform identity ("TPU_0(...)");
        # id() is allocation order and can alias a fresh device list
        # after GC, silently reusing a jit built for dead devices
        devs_key = tuple(str(d) for d in jax.devices())
        run, sh = _closure_sharded_jitted(iters, devs_key)
        # single host->sharded transfer (device_put straight from numpy;
        # jnp.asarray first would commit to one device then reshard)
        return run(jax.device_put(pad, sh))


if HAVE_JAX:

    @partial(jax.jit, static_argnames=("b", "m", "iters", "n_types"))
    def _closure_from_edges(edges, lvl_mask, inv_v, comp_v, b, m,
                            iters, n_types):
        """Compact-input closure: build the [B, m, m] level stack ON
        DEVICE from typed edge lists plus the realtime vectors, then
        square. Ships O(E + N) bytes instead of O(B*N^2) — the dense
        bool stack was ~80 MB at the append bench's 3.7k txns, ~2 s of
        tunnel bandwidth (PERF.md).

        edges: [E, 3] int32 (type, i, j), padded rows filled with
        POSITIVE out-of-range indices (type = n_types, i = j = m) —
        negative indices would WRAP before mode="drop"'s bounds check
        and set a real spurious edge;
        lvl_mask: [B, n_types+1] bool (last column = include realtime);
        inv_v / comp_v: [m] f32 invoke/complete indices (+inf pad).
        """
        et = jnp.zeros((n_types, m, m), dtype=bool)
        et = et.at[edges[:, 0], edges[:, 1], edges[:, 2]].set(
            True, mode="drop")
        rt = comp_v[:, None] < inv_v[None, :]
        rt = rt & ~jnp.eye(m, dtype=bool)
        planes = []
        for bi in range(b):
            x = jnp.zeros((m, m), dtype=bool)
            for t in range(n_types):
                x = x | (et[t] & lvl_mask[bi, t])
            x = x | (rt & lvl_mask[bi, n_types])
            planes.append(x)
        a = jnp.stack(planes)
        return _closure_device(a, iters)


class EdgeAccumulator:
    """Incremental typed-edge accumulation for the Elle dependency
    graph: ``add(t, i, j)`` appends into per-type growing int32 chunk
    buffers (amortized O(1), no Python set-of-tuples — the set was the
    memory floor on long streamed histories), and ``finalize()``
    returns per-type sorted-unique ``[E, 2]`` int32 arrays, row-for-row
    identical to ``np.array(sorted(set_of_pairs))`` — exactly the
    compact edge lists :func:`closure_levels_lazy` ships to the device.
    Feeding may resume after a finalize (the streaming/soak path
    accumulates edges chunk by chunk and snapshots between windows);
    finalize is cached until the next add."""

    CHUNK = 4096

    def __init__(self, n_types: int):
        self.n_types = n_types
        self._bufs: list[list] = [[] for _ in range(n_types)]
        self._fill = [0] * n_types
        self._final = None

    def add(self, t: int, i: int, j: int) -> None:
        if i == j:
            return
        bufs = self._bufs[t]
        f = self._fill[t]
        if not bufs or f == len(bufs[-1]):
            bufs.append(np.empty((self.CHUNK, 2), dtype=np.int32))
            f = 0
        cur = bufs[-1]
        cur[f, 0] = i
        cur[f, 1] = j
        self._fill[t] = f + 1
        self._final = None

    def __len__(self) -> int:
        """Raw (pre-dedup) edge count across all types."""
        return sum((len(b) - 1) * self.CHUNK + self._fill[t]
                   if (b := self._bufs[t]) else 0
                   for t in range(self.n_types))

    def finalize(self) -> list:
        if self._final is None:
            out = []
            for t in range(self.n_types):
                bufs = self._bufs[t]
                if not bufs:
                    out.append(np.zeros((0, 2), dtype=np.int32))
                    continue
                rows = np.concatenate(bufs[:-1]
                                      + [bufs[-1][:self._fill[t]]])
                out.append(np.unique(rows, axis=0))
            self._final = out
        return self._final


def _closure_numpy(a: np.ndarray) -> tuple:
    n = a.shape[-1]
    r = a | np.eye(n, dtype=bool)[None]
    iters = max(1, math.ceil(math.log2(max(2, n))))
    prev = int(r.sum())
    for _ in range(iters):
        # int32 accumulator: uint8 would wrap at 256 paths and silently
        # drop reachability (and so miss real cycles) on long histories
        r = np.matmul(r.astype(np.int32), r.astype(np.int32)) > 0
        cur = int(r.sum())
        if cur == prev:   # fixpoint: squaring added no pairs
            break
        prev = cur
    on_cycle = np.any(a & np.swapaxes(r, -1, -2), axis=-1)
    return r, on_cycle


def closure_levels_lazy(et_edges: list, lvl_mask: np.ndarray, n: int,
                        rt_vecs, densify,
                        force_device: bool | None = None):
    """closure_batch_lazy with COMPACT device inputs: per-type edge
    lists + the realtime (invoke, complete) vectors; the [B, N, N]
    level stack is built on device (_closure_from_edges). densify() is
    only called on the host / multi-device-sharded paths, which keep
    the dense pipeline. Same return contract as closure_batch_lazy."""
    b, n_types = lvl_mask.shape[0], lvl_mask.shape[1] - 1
    n_dev = len(jax.devices()) if HAVE_JAX else 1
    m = _bucket(max(1, n))
    if m % max(1, n_dev):
        m = ((m + n_dev - 1) // n_dev) * n_dev
    if (n == 0
            or not use_device(force_device, n, CPU_CUTOFF,
                              "closure_batch")
            or (n_dev > 1 and m >= SHARD_CUTOFF)):
        # host / sharded / empty: the dense pipeline handles these —
        # one copy of that routing lives in closure_batch_lazy
        return closure_batch_lazy(densify() if n else
                                  np.zeros((b, 0, 0), bool),
                                  force_device=force_device)
    iters = max(1, math.ceil(math.log2(m)))
    rows = [np.column_stack([np.full(len(e), t, np.int32),
                             np.asarray(e, np.int32).reshape(-1, 2)])
            for t, e in enumerate(et_edges) if len(e)]
    edges = (np.concatenate(rows) if rows
             else np.zeros((0, 3), np.int32))
    e_pad = _bucket(max(1, len(edges)))
    # padding rows use positive OUT-OF-RANGE indices: negative ones
    # wrap before mode="drop"'s bounds check and plant a real edge
    epad = np.empty((e_pad, 3), dtype=np.int32)
    epad[:, 0] = n_types
    epad[:, 1] = epad[:, 2] = m
    epad[:len(edges)] = edges
    inv_v = np.full(m, np.inf, dtype=np.float32)
    comp_v = np.full(m, np.inf, dtype=np.float32)
    if rt_vecs is not None:
        inv_v[:n] = rt_vecs[0]
        comp_v[:n] = rt_vecs[1]
    with telemetry.current().span("closure.device", n=n, b=b,
                                  compact=True, edges=len(edges)):
        reach_dev, on_cycle = _closure_from_edges(
            jnp.asarray(epad), jnp.asarray(lvl_mask),
            jnp.asarray(inv_v), jnp.asarray(comp_v),
            b, m, iters, n_types)
        on_cycle = np.asarray(on_cycle)[:, :n]
    cache: list = []

    def reach_fn():
        if not cache:
            cache.append(np.asarray(reach_dev)[:, :n, :n])
        return cache[0]

    return reach_fn, on_cycle


def closure_batch_lazy(adj: np.ndarray, force_device: bool | None = None):
    """Close a [B, N, N] bool adjacency stack, deferring the reach
    transfer.

    Returns (reach_fn, on_cycle) where on_cycle is a numpy [B, N] bool
    and reach_fn() materializes the [B, N, N] closure on first call
    (cached). Cycle *detection* only needs on_cycle; the full reach
    matrix is consulted only for certificate recovery on INVALID
    histories — valid ones (the overwhelming case) skip the O(B*N^2)
    device->host transfer entirely, which dominated Elle wall-clock at
    device scale.
    """
    adj = np.asarray(adj, dtype=bool)
    if adj.ndim == 2:
        adj = adj[None]
    b, n, _ = adj.shape
    if n == 0:
        empty = np.zeros((b, 0, 0), bool)
        return (lambda: empty), np.zeros((b, 0), bool)
    if not use_device(force_device, n, CPU_CUTOFF, "closure_batch"):
        with telemetry.current().span("closure.host", n=n, b=b):
            reach, on_cycle = _closure_numpy(adj)
        return (lambda: reach), on_cycle
    m = _bucket(n)
    n_dev = len(jax.devices())
    if m % max(1, n_dev):  # row axis must split evenly over the mesh
        m = ((m + n_dev - 1) // n_dev) * n_dev
    pad = np.zeros((b, m, m), dtype=bool)
    pad[:, :n, :n] = adj
    iters = max(1, math.ceil(math.log2(m)))
    with telemetry.current().span("closure.device", n=n, b=b,
                                  sharded=(n_dev > 1
                                           and m >= SHARD_CUTOFF)):
        if n_dev > 1 and m >= SHARD_CUTOFF:
            reach_dev, on_cycle = _closure_device_sharded(pad, iters)
        else:
            reach_dev, on_cycle = _closure_device(jnp.asarray(pad),
                                                  iters)
        on_cycle = np.asarray(on_cycle)[:, :n]
    cache: list = []

    def reach_fn():
        if not cache:
            cache.append(np.asarray(reach_dev)[:, :n, :n])
        return cache[0]

    return reach_fn, on_cycle


def closure_batch(adj: np.ndarray, force_device: bool | None = None):
    """Close a [B, N, N] bool adjacency stack.

    Returns (reach [B, N, N], on_cycle [B, N]) as numpy bool arrays,
    trimmed back to the caller's N. Small problems run on host (device
    dispatch would dominate); large ones pad to a bucketed size and run
    the jitted squaring kernel. Prefer ``closure_batch_lazy`` when the
    reach matrix is only needed conditionally.
    """
    reach_fn, on_cycle = closure_batch_lazy(adj, force_device)
    return reach_fn(), on_cycle
