"""TPU transitive-closure kernel: the Elle cycle-detection engine.

The reference's Elle checkers (``append.clj:183-185``, ``wr.clj:87-92``
call into the Elle library) find cycles in a transaction dependency graph
with JVM graph traversals. The TPU-native re-design expresses cycle
detection as *boolean matrix closure by iterative squaring*: with
``R0 = A | I``, squaring k times covers all paths of length < 2^k, so
``ceil(log2 N)`` squarings reach the full transitive closure R*. Each
squaring is one big matmul — exactly what the MXU is for — and the
nested anomaly subgraphs Elle distinguishes (ww ⊂ ww|wr ⊂ ww|wr|rw, each
with/without realtime edges) batch into one ``[B, N, N]`` stack so all
levels close in a single vmapped kernel launch.

Matmuls run in bfloat16 with float32 accumulation (values are exactly
0/1, sums of positives cannot cancel, and the accumulator never
overflows at N ≤ ~1e6 — only zero/nonzero matters) and shapes are padded
to bucketed powers of two so jit caches stay warm across histories.

A node lies on a cycle iff some successor reaches back to it:
``on_cycle[i] = ∃j. A[i,j] ∧ R*[j,i]``.
"""

from __future__ import annotations

import math
from functools import partial

import numpy as np

from .common import HAVE_JAX, bucket as _bucket, use_device

if HAVE_JAX:
    import jax
    import jax.numpy as jnp

#: below this node count, numpy squaring beats a device round-trip.
#: MEASURED (r4, 6 subgraphs of N nodes, iterative squaring, v5e
#: through axon): N=256 host 0.020 s vs device 0.149 s; N=512 host
#: 0.189 s vs 0.328 s; N=1024 host 1.53 s vs 0.68 s; N=2048 host
#: 13.2 s vs 1.95 s; N=4096 host 102 s vs 6.1 s. Crossover ~768 —
#: the device pays a ~0.1 s tunnel round trip, the host pays O(N^3).
CPU_CUTOFF = 768
#: at/above this node count (with >1 device), shard rows over the mesh
SHARD_CUTOFF = 1024


if HAVE_JAX:

    @partial(jax.jit, static_argnames=("iters",))
    def _closure_device(a: "jax.Array", iters: int):
        """a: [B, N, N] bool adjacency. Returns (reach [B,N,N] bool
        — reflexive-transitive closure — and on_cycle [B,N] bool)."""
        n = a.shape[-1]
        eye = jnp.eye(n, dtype=bool)
        r = jnp.logical_or(a, eye[None, :, :]).astype(jnp.bfloat16)

        def body(_, r):
            prod = jax.lax.dot_general(
                r, r, (((2,), (1,)), ((0,), (0,))),
                preferred_element_type=jnp.float32)
            return (prod > 0).astype(jnp.bfloat16)

        r = jax.lax.fori_loop(0, iters, body, r)
        reach = r > 0
        # A[i,j] & R*[j,i]: row-wise AND with the transpose, any over j
        on_cycle = jnp.any(
            jnp.logical_and(a, jnp.swapaxes(reach, -1, -2)), axis=-1)
        return reach, on_cycle

    from functools import lru_cache

    @lru_cache(maxsize=None)
    def _mesh(devs_key: tuple):
        from jax.sharding import Mesh
        return Mesh(np.array(jax.devices()), ("dp",))

    @lru_cache(maxsize=None)
    def _closure_sharded_jitted(iters: int, devs_key: tuple):
        """Row-sharded squaring: R is [B, N, N] with rows split over the
        mesh ('dp'); each R@R is a 1D-sharded matmul — XLA/GSPMD inserts
        the all-gather of the stationary operand over ICI (SURVEY §2.3
        "SCC via repeated boolean matmul under pjit sharding"). The
        sharding constraint in the loop body pins the layout so the
        gather happens once per squaring, not once per op."""
        from jax.sharding import NamedSharding, PartitionSpec as P
        mesh = _mesh(devs_key)
        sh = NamedSharding(mesh, P(None, "dp", None))

        @jax.jit
        def run(a):
            n = a.shape[-1]
            eye = jnp.eye(n, dtype=bool)
            r = jnp.logical_or(a, eye[None, :, :]).astype(jnp.bfloat16)
            r = jax.lax.with_sharding_constraint(r, sh)

            def body(_, r):
                prod = jax.lax.dot_general(
                    r, r, (((2,), (1,)), ((0,), (0,))),
                    preferred_element_type=jnp.float32)
                return jax.lax.with_sharding_constraint(
                    (prod > 0).astype(jnp.bfloat16), sh)

            r = jax.lax.fori_loop(0, iters, body, r)
            reach = r > 0
            on_cycle = jnp.any(
                jnp.logical_and(a, jnp.swapaxes(reach, -1, -2)), axis=-1)
            return reach, on_cycle

        return run, sh

    def _closure_device_sharded(pad: np.ndarray, iters: int):
        devs_key = tuple(id(d) for d in jax.devices())
        run, sh = _closure_sharded_jitted(iters, devs_key)
        # single host->sharded transfer (device_put straight from numpy;
        # jnp.asarray first would commit to one device then reshard)
        return run(jax.device_put(pad, sh))


def _closure_numpy(a: np.ndarray) -> tuple:
    n = a.shape[-1]
    r = a | np.eye(n, dtype=bool)[None]
    iters = max(1, math.ceil(math.log2(max(2, n))))
    for _ in range(iters):
        # int32 accumulator: uint8 would wrap at 256 paths and silently
        # drop reachability (and so miss real cycles) on long histories
        r = np.matmul(r.astype(np.int32), r.astype(np.int32)) > 0
    on_cycle = np.any(a & np.swapaxes(r, -1, -2), axis=-1)
    return r, on_cycle


def closure_batch_lazy(adj: np.ndarray, force_device: bool | None = None):
    """Close a [B, N, N] bool adjacency stack, deferring the reach
    transfer.

    Returns (reach_fn, on_cycle) where on_cycle is a numpy [B, N] bool
    and reach_fn() materializes the [B, N, N] closure on first call
    (cached). Cycle *detection* only needs on_cycle; the full reach
    matrix is consulted only for certificate recovery on INVALID
    histories — valid ones (the overwhelming case) skip the O(B*N^2)
    device->host transfer entirely, which dominated Elle wall-clock at
    device scale.
    """
    adj = np.asarray(adj, dtype=bool)
    if adj.ndim == 2:
        adj = adj[None]
    b, n, _ = adj.shape
    if n == 0:
        empty = np.zeros((b, 0, 0), bool)
        return (lambda: empty), np.zeros((b, 0), bool)
    if not use_device(force_device, n, CPU_CUTOFF, "closure_batch"):
        reach, on_cycle = _closure_numpy(adj)
        return (lambda: reach), on_cycle
    m = _bucket(n)
    n_dev = len(jax.devices())
    if m % max(1, n_dev):  # row axis must split evenly over the mesh
        m = ((m + n_dev - 1) // n_dev) * n_dev
    pad = np.zeros((b, m, m), dtype=bool)
    pad[:, :n, :n] = adj
    iters = max(1, math.ceil(math.log2(m)))
    if n_dev > 1 and m >= SHARD_CUTOFF:
        reach_dev, on_cycle = _closure_device_sharded(pad, iters)
    else:
        reach_dev, on_cycle = _closure_device(jnp.asarray(pad), iters)
    on_cycle = np.asarray(on_cycle)[:, :n]
    cache: list = []

    def reach_fn():
        if not cache:
            cache.append(np.asarray(reach_dev)[:, :n, :n])
        return cache[0]

    return reach_fn, on_cycle


def closure_batch(adj: np.ndarray, force_device: bool | None = None):
    """Close a [B, N, N] bool adjacency stack.

    Returns (reach [B, N, N], on_cycle [B, N]) as numpy bool arrays,
    trimmed back to the caller's N. Small problems run on host (device
    dispatch would dominate); large ones pad to a bucketed size and run
    the jitted squaring kernel. Prefer ``closure_batch_lazy`` when the
    reach matrix is only needed conditionally.
    """
    reach_fn, on_cycle = closure_batch_lazy(adj, force_device)
    return reach_fn(), on_cycle
