"""DB protocol for a live (external) etcd cluster.

The reference's db.clj owns the whole node lifecycle over SSH —
install, start, kill, wipe. In live mode this harness drives an etcd it
did NOT start and has no shell on, so the DB layer shrinks to what the
wire offers: readiness barriers (client.clj:652-661) and member-status
primaries (db.clj:38-52). Process-level faults (kill/pause/wipe) need a
control plane this environment doesn't have; fault testing lives in the
simulated cluster, which models those faults at the byte level.
"""

from __future__ import annotations

import logging
from typing import Optional

from ..runner.sim import current_loop, gather
from ..sut.errors import SimError

logger = logging.getLogger("jepsen_etcd_tpu.db")


def _live_client_cls(opts: dict):
    """The live client class for this run's wire protocol (http = v3
    JSON gateway, grpc = native gRPC — the reference's protocol)."""
    if (opts or {}).get("client_type") == "grpc":
        from ..client.etcd_grpc import GrpcEtcdClient
        return GrpcEtcdClient
    from ..client.etcd_http import HttpEtcdClient
    return HttpEtcdClient


class LiveDb:
    """jepsen.db against an already-running cluster: setup is a
    readiness barrier, teardown leaves the cluster alone."""

    def __init__(self, opts: dict):
        self.opts = opts
        self.members: Optional[set] = None

    async def setup(self, test: dict) -> None:
        self.members = set(test["nodes"])
        loop = current_loop()
        cls = _live_client_cls(test)
        clients = [cls(ep) for ep in test["nodes"]]
        try:
            await gather(*[loop.spawn(c.await_node_ready())
                           for c in clients])
        finally:
            for c in clients:  # gRPC clients own channels/threads
                c.close()
        logger.info("live cluster ready: %s", test["nodes"])

    async def teardown(self, test: dict) -> None:
        pass  # not ours to stop

    def log_files(self, test: dict) -> dict:
        return {}  # no shell on the nodes; logs stay remote

    # ---- Process protocol: no control plane --------------------------------

    def _unsupported(self, what: str) -> str:
        raise SimError("unsupported",
                       f"live mode cannot {what}: no control plane on an "
                       f"external cluster (use --db local to spawn and "
                       f"fault local etcd processes, or the simulated "
                       f"cluster)", definite=True)

    def start(self, test: dict, node: str) -> str:
        return self._unsupported("start nodes")

    def kill(self, test: dict, node: str) -> str:
        return self._unsupported("kill nodes")

    def pause(self, test: dict, node: str) -> str:
        return self._unsupported("pause nodes")

    def resume(self, test: dict, node: str) -> str:
        return self._unsupported("resume nodes")

    def wipe(self, test: dict, node: str) -> str:
        return self._unsupported("wipe nodes")

    # ---- Primary protocol --------------------------------------------------

    async def primaries(self, test: dict) -> list[str]:
        """Highest-raft-term status answer wins (db.clj:38-52), mapped
        back to the endpoint whose member id is the reported leader."""
        loop = current_loop()
        cls = _live_client_cls(self.opts)

        async def ask(ep):
            c = cls(ep)
            try:
                return ep, await c.status()
            except (SimError, TimeoutError):
                return ep, None
            finally:
                c.close()

        answers = [a for a in await gather(
            *[loop.spawn(ask(ep)) for ep in sorted(self.members)])
            if a[1] is not None]
        if not answers:
            return []
        _, best = max(answers, key=lambda a: a[1].get("raft-term", 0))
        leader_id = best.get("leader")
        if not leader_id:
            return []
        # the endpoint whose own member id IS the leader id (its status
        # header carries its member_id); a term-leading follower that
        # merely *names* the leader is not the primary
        for ep, st in answers:
            member_id = int(st.get("header", {}).get("member_id", 0) or 0)
            if member_id == leader_id:
                return [ep]
        return []


def live_db(opts: dict) -> LiveDb:
    return LiveDb(opts)
