"""Cluster lifecycle automation (the db.clj analog).

Where the reference SSHes into nodes to install/start/kill/wipe real etcd
binaries, we drive the simulated cluster's fault API. The protocol surface
mirrors jepsen.db (DB/Process/Pause/Primary/LogFiles) as used by the
nemesis packages and test composition:

- setup/teardown with the initialized? barrier (db.clj:192-232): the first
  start bootstraps a fresh cluster ("--initial-cluster-state new"); later
  starts rejoin with existing data ("existing", db.clj:257-262);
- kill!/start! (with lazyfs lose-unfsynced-writes! on kill,
  db.clj:264-267), pause!/resume! (grepkill :stop/:cont, db.clj:269-271);
- grow!/shrink! membership changes (db.clj:128-190);
- primaries via the highest-raft-term fan-out (db.clj:38-52).
"""

from __future__ import annotations

import logging
from typing import Any, Optional

from ..runner.sim import current_loop, sleep, gather, SECOND
from ..sut.cluster import Cluster
from ..sut.errors import SimError
from ..client import DirectClient

logger = logging.getLogger("jepsen_etcd_tpu.db")


class Db:
    def __init__(self, opts: dict):
        self.opts = opts
        self.initialized = False          # db.clj:219-220 atom
        self.members: Optional[set] = None  # db.clj:107-112 atom
        self.next_node_id = 0
        self._corrupt_monitor = None

    # ---- DB protocol -------------------------------------------------------

    async def setup(self, test: dict) -> None:
        cluster: Cluster = test["cluster"]
        self.members = set(test["nodes"])
        self.next_node_id = len(test["nodes"])
        cluster.launch()
        # await-node-ready on every node (db.clj:212-215), in parallel
        loop = current_loop()
        clients = [DirectClient(cluster, n) for n in test["nodes"]]
        await gather(*[loop.spawn(c.await_node_ready())
                       for c in clients])
        self.initialized = True  # jepsen/synchronize barrier passed
        if test.get("lazyfs"):
            # pin the post-setup state (lazyfs checkpoint!, db.clj:222-223)
            for n in test["nodes"]:
                cluster.checkpoint_node(n)
        if test.get("corrupt_check"):
            # --corrupt-check (db.clj:97-99): initial check at boot, then
            # a periodic monitor every virtual minute, the
            # --experimental-corrupt-check-time 1m analog
            cluster.check_corruption()

            async def monitor():
                while cluster.running:
                    await sleep(60 * SECOND)
                    cluster.check_corruption()
            self._corrupt_monitor = loop.spawn(monitor(),
                                               "db-corrupt-monitor")

    async def teardown(self, test: dict) -> None:
        if test.get("corrupt_check"):
            # final sweep before shutdown freezes node state
            test["cluster"].check_corruption()
        test["cluster"].shutdown()

    def log_files(self, test: dict) -> dict:
        """node -> etcd.log lines (db.clj:234-242 collects logs + data)."""
        return {name: list(node.etcd_log)
                for name, node in test["cluster"].nodes.items()}

    # ---- Process protocol --------------------------------------------------

    def start(self, test: dict, node: str) -> str:
        cluster: Cluster = test["cluster"]
        try:
            cluster.start_node(node, fresh=not self.initialized)
            if test.get("corrupt_check"):
                # --experimental-initial-corrupt-check: verify at boot
                cluster.check_corruption()
            return "started"
        except SimError as e:
            if e.type == "corrupt":
                return "corrupt"  # node refuses to start; logged a panic
            raise

    def kill(self, test: dict, node: str) -> str:
        cluster: Cluster = test["cluster"]
        lose = bool(test.get("lazyfs"))
        cluster.kill_node(node, lose_unfsynced=lose)
        return "killed"

    def pause(self, test: dict, node: str) -> str:
        test["cluster"].pause_node(node)
        return "paused"

    def resume(self, test: dict, node: str) -> str:
        test["cluster"].resume_node(node)
        return "resumed"

    def wipe(self, test: dict, node: str) -> str:
        test["cluster"].wipe_node(node)
        return "wiped"

    # ---- Primary protocol --------------------------------------------------

    async def primaries(self, test: dict) -> list[str]:
        """Highest-raft-term answer wins (from-highest-term, db.clj:38-52)."""
        cluster: Cluster = test["cluster"]
        loop = current_loop()

        async def ask(n):
            try:
                c = DirectClient(cluster, n)
                return await c.status()
            except (SimError, TimeoutError):
                return None

        statuses = [s for s in await gather(
            *[loop.spawn(ask(n)) for n in sorted(self.members)])
            if s is not None]
        if not statuses:
            return []
        best = max(statuses, key=lambda s: s["raft-term"])
        return [best["leader"]] if best.get("leader") else []

    # ---- membership (db.clj:128-190) ---------------------------------------

    async def grow(self, test: dict) -> str:
        """Add a fresh node via a random current member and start it."""
        cluster: Cluster = test["cluster"]
        loop = current_loop()
        self.next_node_id += 1
        new = f"n{self.next_node_id}"
        via = loop.rng.choice(sorted(self.members))
        c = DirectClient(cluster, via)
        await c.add_member(new)
        members = sorted(self.members | {new})
        cluster.start_node(new, fresh=True, initial_membership=members)
        self.members.add(new)
        return new

    async def shrink(self, test: dict) -> str:
        """Remove a random member via another member; kill and wipe it."""
        cluster: Cluster = test["cluster"]
        loop = current_loop()
        if len(self.members) <= 1:
            raise SimError("unhealthy-cluster", "cannot shrink to zero")
        victim = loop.rng.choice(sorted(self.members))
        others = sorted(self.members - {victim})
        via = loop.rng.choice(others)
        c = DirectClient(cluster, via)
        await c.remove_member(victim)
        cluster.kill_node(victim)
        cluster.wipe_node(victim)
        self.members.discard(victim)
        return victim


def db(opts: dict) -> Db:
    return Db(opts)
