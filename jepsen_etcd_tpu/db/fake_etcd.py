"""fake-etcd: a standalone stub binary speaking enough of etcd's
surface for process-level fault testing without a real etcd.

The local control plane (db/local.py) spawns, signals, wipes, and
supervises OS processes; what those processes *serve* is secondary —
what matters is that every process-management path (spawn, readiness
polling, SIGKILL/SIGSTOP/SIGCONT delivery, data-dir wipe,
restart-after-kill, log capture, crash-loop detection, teardown of
leaked children) can be exercised end-to-end in tier-1 tests. This stub
provides that: it parses etcd's real flag set (the subset db.clj:79-100
passes), serves the v3 JSON gateway (sut/http_gateway.py) on its client
URL, persists its MVCC store to the data dir so kill→restart keeps
data and wipe visibly loses it, reports a member/status surface derived
from --initial-cluster, and writes etcd-shaped log lines to stderr.

NOT a distributed store: each fake node owns an independent Store (no
raft, no replication), so a multi-node fake cluster is N disjoint KVs
behind one member list. Checker validity across faults is a real-binary
concern (tests/test_live_etcd.py, gated on `shutil.which("etcd")`);
process-control correctness is this stub's concern. Leadership is
deterministic: every node reports leader = lowest member id.

Quorum awareness (the one distributed behavior the stub does model, so
userspace-proxy partitions are observable): in a multi-node roster each
node listens on its peer port and runs a prober that round-trips a
``FAKE-ETCD-PEER <name>\\n`` preamble through every roster peer URL —
which, under ``--net-proxy``, routes through the target's ingress proxy
where drop rules apply. A node that can see fewer than a majority of
the roster reports leader=0 and refuses linearizable reads and writes
with ``etcdserver: no leader`` (grpc code 14, the same wire shape real
etcd emits), so a partitioned minority fails ops while the majority
progresses, and healing restores it. Probe reads use short timeouts:
a SIGSTOP'd node's kernel still completes TCP handshakes via the
accept backlog, so only the reply round-trip distinguishes alive.

Runs both ways:
    python -m jepsen_etcd_tpu.db.fake_etcd --name n1 ...
    python /path/to/fake_etcd.py --name n1 ...   (db/local.py default)

Crash injection (for crash-loop tests): FAKE_ETCD_CRASH=1 in the
environment makes the process log a panic and exit 1 before serving.
"""

from __future__ import annotations

import argparse
import os
import pickle
import signal
import socket
import sys
import threading
import time

if __package__ in (None, ""):  # invoked as a file path, not a module
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))))

from jepsen_etcd_tpu.sut.http_gateway import (  # noqa: E402
    GatewayState, member_id_for_peer_urls, serve)
from jepsen_etcd_tpu.sut.store import Store  # noqa: E402

STORE_FILE = "member/snap/store.pickle"  # under the data dir

#: peer-visibility probe cadence / per-peer reply deadline (short: a
#: SIGSTOP'd peer still accepts via the kernel backlog, only the reply
#: times out)
PROBE_INTERVAL_S = 0.25
PROBE_TIMEOUT_S = 1.0
PEER_PREAMBLE = b"FAKE-ETCD-PEER "
PEER_REPLY = b"FAKE-ETCD-OK "


def _log(msg: str, level: str = "info") -> None:
    # etcd's zap console format, near enough for eyeballing run logs
    ts = time.strftime("%Y-%m-%dT%H:%M:%S")
    sys.stderr.write(f'{{"level":"{level}","ts":"{ts}","msg":"{msg}"}}\n')
    sys.stderr.flush()


def parse_args(argv: list[str]) -> argparse.Namespace:
    """etcd's flag surface, the subset the reference passes
    (db.clj:79-100). parse_known_args: unknown real-etcd flags must not
    kill the stub — a real binary would accept them."""
    p = argparse.ArgumentParser(prog="fake-etcd", add_help=False)
    p.add_argument("--name", required=True)
    p.add_argument("--data-dir", required=True)
    p.add_argument("--listen-client-urls", default="")
    p.add_argument("--advertise-client-urls", default="")
    p.add_argument("--listen-peer-urls", default="")
    p.add_argument("--initial-advertise-peer-urls", default="")
    p.add_argument("--initial-cluster", default="")
    p.add_argument("--initial-cluster-state", default="new",
                   choices=["new", "existing"])
    p.add_argument("--initial-cluster-token", default="etcd-cluster")
    p.add_argument("--snapshot-count", type=int, default=100000)
    p.add_argument("--unsafe-no-fsync", action="store_true")
    p.add_argument("--experimental-initial-corrupt-check",
                   default=None, nargs="?")
    p.add_argument("--experimental-corrupt-check-time", default=None)
    p.add_argument("--logger", default="zap")
    p.add_argument("--log-outputs", default="stderr")
    args, unknown = p.parse_known_args(argv)
    if unknown:
        _log(f"ignoring unrecognized flags: {unknown}", "warn")
    return args


def parse_initial_cluster(spec: str) -> dict[str, str]:
    """'n1=http://h:p1,n2=http://h:p2' -> {name: peer_url}."""
    out: dict[str, str] = {}
    for part in filter(None, (s.strip() for s in spec.split(","))):
        name, _, url = part.partition("=")
        out[name] = url
    return out


def _url_port(url: str) -> int:
    return int(url.rsplit(":", 1)[1].rstrip("/"))


def _url_host(url: str) -> str:
    hostport = url.split("//", 1)[-1]
    return hostport.rsplit(":", 1)[0] or "127.0.0.1"


class FakeEtcd:
    def __init__(self, args: argparse.Namespace):
        self.args = args
        self.data_dir = args.data_dir
        roster = parse_initial_cluster(args.initial_cluster)
        if args.name not in roster and args.initial_advertise_peer_urls:
            roster[args.name] = args.initial_advertise_peer_urls
        members = {
            member_id_for_peer_urls([url]): {
                "name": name, "peerURLs": [url],
                "clientURLs": ([args.advertise_client_urls]
                               if name == args.name else [])}
            for name, url in roster.items()}
        self.member_id = member_id_for_peer_urls(
            [roster.get(args.name, f"unix://{args.name}")])
        self.state = GatewayState(name=args.name,
                                  member_id=self.member_id,
                                  members=members)
        self._persist_lock = threading.Lock()
        self._stopping = threading.Event()
        self._srv = None
        # peer visibility: visible/total member counts published by the
        # prober (self included). The probe targets and majority size
        # come from the LIVE member list each round (_live_peers), not
        # this boot roster — roster only gates whether the peer plane
        # starts at all. Starts optimistic so a clean boot reports a
        # leader before the first probe round completes.
        self.roster = dict(roster)
        self._peer_lock = threading.Lock()
        self._visible_count = max(len(self.roster), 1)
        self._member_total = max(len(self.roster), 1)
        self._peer_srv: socket.socket = None
        if len(self.roster) > 1:
            self.state.quorum_check = self._has_quorum

    # ---- peer visibility / quorum ------------------------------------------

    def _has_quorum(self) -> bool:
        with self._peer_lock:
            return self._visible_count >= self._member_total // 2 + 1

    def _live_peers(self) -> tuple[list[str], int]:
        """Peer URLs of every *other* live member plus the live member
        count, from state.members — member add/remove faults move the
        real majority mid-run, so quorum must never judge against the
        boot-time --initial-cluster roster."""
        with self.state.lock:
            urls = [m["peerURLs"][0]
                    for mid, m in self.state.members.items()
                    if mid != self.member_id and m.get("peerURLs")]
            total = len(self.state.members)
        return urls, total

    def _peer_answer(self, conn: socket.socket) -> None:
        """Answer one probe: read the preamble, echo our name back.
        The round trip crosses both proxy legs, so a one-way drop in
        either direction degrades visibility correctly."""
        try:
            conn.settimeout(PROBE_TIMEOUT_S)
            buf = b""
            while b"\n" not in buf and len(buf) < 256:
                chunk = conn.recv(256)
                if not chunk:
                    break
                buf += chunk
            if buf.startswith(PEER_PREAMBLE):
                conn.sendall(PEER_REPLY
                             + self.args.name.encode("utf-8") + b"\n")
        except OSError:
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _peer_listen_loop(self) -> None:
        while not self._stopping.is_set():
            try:
                conn, _ = self._peer_srv.accept()
            except OSError:
                return  # listener closed on shutdown
            threading.Thread(target=self._peer_answer, args=(conn,),
                             daemon=True).start()

    def _probe_one(self, url: str) -> bool:
        try:
            with socket.create_connection(
                    (_url_host(url), _url_port(url)),
                    timeout=PROBE_TIMEOUT_S) as s:
                s.settimeout(PROBE_TIMEOUT_S)
                s.sendall(PEER_PREAMBLE
                          + self.args.name.encode("utf-8") + b"\n")
                buf = b""
                while b"\n" not in buf and len(buf) < 256:
                    chunk = s.recv(256)
                    if not chunk:
                        break
                    buf += chunk
                return buf.startswith(PEER_REPLY)
        except OSError:
            return False

    def _probe_loop(self) -> None:
        """Round-trip the preamble to every live member's peer URL
        (under --net-proxy these route through each target's ingress
        proxy, where drop rules apply) and publish visible/total
        counts. An added-but-unstarted member counts toward the
        majority size but never answers — the same fault-tolerance
        dent a real etcd takes from an unstarted learner."""
        while not self._stopping.wait(PROBE_INTERVAL_S):
            urls, total = self._live_peers()
            seen = 1  # self
            for url in sorted(urls):
                if self._probe_one(url):
                    seen += 1
            with self._peer_lock:
                self._visible_count = seen
                self._member_total = max(total, 1)

    def _start_peer_plane(self) -> None:
        port = _url_port(self.args.listen_peer_urls)
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind(("127.0.0.1", port))
        srv.listen(64)
        self._peer_srv = srv
        threading.Thread(target=self._peer_listen_loop,
                         daemon=True).start()
        threading.Thread(target=self._probe_loop, daemon=True).start()
        _log(f"peer visibility prober up on :{port} "
             f"(roster {sorted(self.roster)})")

    # ---- persistence -------------------------------------------------------

    @property
    def _store_path(self) -> str:
        return os.path.join(self.data_dir, STORE_FILE)

    def load(self) -> None:
        os.makedirs(os.path.dirname(self._store_path), exist_ok=True)
        if os.path.exists(self._store_path):
            with open(self._store_path, "rb") as f:
                payload = pickle.load(f)
            store = Store.__new__(Store)
            store.__dict__.update(payload)
            self.state.store = store
            _log(f"restored store from {self._store_path} at revision "
                 f"{store.revision}")
        elif self.args.initial_cluster_state == "existing":
            # rejoining with an empty data dir is how a wiped node comes
            # back; real etcd would stream a snapshot from the leader —
            # the stub just starts empty
            _log("existing-state start with empty data dir "
                 "(post-wipe rejoin)", "warn")

    def persist(self) -> None:
        """Snapshot the store to the data dir (atomic rename). Called
        after every committed txn: like a per-commit fsync, so SIGKILL
        at any instant loses nothing already acknowledged."""
        with self._persist_lock:
            payload = dict(self.state.store.__dict__)
            payload.pop("apply_txn", None)  # never pickle a wrapper
            tmp = self._store_path + ".tmp"
            with open(tmp, "wb") as f:
                pickle.dump(payload, f)
                if not self.args.unsafe_no_fsync:
                    f.flush()
                    os.fsync(f.fileno())
            os.replace(tmp, self._store_path)

    def _hook_persistence(self) -> None:
        store = self.state.store
        orig = store.apply_txn

        def persisting_apply(txn):
            result = orig(txn)
            self.persist()
            return result

        # instance attribute shadows the method; persist() strips it
        # before pickling
        store.apply_txn = persisting_apply

    # ---- lifecycle ---------------------------------------------------------

    def run(self) -> int:
        args = self.args
        _log(f"starting fake-etcd member {args.name} "
             f"(id {self.member_id:x}), data-dir {self.data_dir}, "
             f"snapshot-count {args.snapshot_count}, "
             f"unsafe-no-fsync {args.unsafe_no_fsync}")
        if os.environ.get("FAKE_ETCD_CRASH"):
            # injected startup failure for crash-loop detection tests
            _log("panic: injected crash (FAKE_ETCD_CRASH)", "panic")
            return 1
        self.load()
        self._hook_persistence()
        port = _url_port(args.listen_client_urls
                         or args.advertise_client_urls)
        self._srv, _ = serve(port=port, state=self.state)

        def on_term(signum, frame):
            _log(f"received signal {signum}; shutting down gracefully")
            self._stopping.set()

        signal.signal(signal.SIGTERM, on_term)
        signal.signal(signal.SIGINT, on_term)
        t = threading.Thread(target=self._srv.serve_forever,
                             daemon=True)
        t.start()
        if len(self.roster) > 1 and args.listen_peer_urls:
            self._start_peer_plane()
        _log(f"serving client requests on {args.listen_client_urls}")
        _log("ready to serve client requests")
        self._stopping.wait()
        if self._peer_srv is not None:
            try:
                self._peer_srv.close()
            except OSError:
                pass
        self._srv.shutdown()
        self._srv.server_close()
        self.persist()
        _log("closed fake-etcd; goodbye")
        return 0


def main(argv: list[str] = None) -> int:
    args = parse_args(sys.argv[1:] if argv is None else argv)
    return FakeEtcd(args).run()


if __name__ == "__main__":
    raise SystemExit(main())
