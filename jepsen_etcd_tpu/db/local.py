"""Local control plane: spawn, supervise, and fault N etcd processes.

The db.clj analog for processes on THIS machine instead of over SSH
(db.clj:72-105,192-271): where the reference runs `etcd` on five debian
nodes and faults it with grepkill, this driver owns the OS processes
directly — subprocess spawn with the reference's flag set (peer/client
URLs, --snapshot-count, --unsafe-no-fsync, corrupt-check flags,
db.clj:79-100), SIGKILL/SIGSTOP/SIGCONT delivery, data-dir wipes,
member grow/shrink via the real member API, readiness polling with
bounded exponential backoff, crash-loop detection, and per-node log
collection into the run store.

The binary is pluggable: a real `etcd` from PATH (or --etcd-binary)
when one exists, else the bundled fake-etcd stub (db/fake_etcd.py) so
every process-management path runs end-to-end without etcd installed.
Node identity is a NAME (n1..nN) everywhere — nemesis targets, members,
log dirs — and this driver owns the name -> client URL mapping
(client_url), which the client factory consults in local mode.

Fault support matrix (compose.py enforces it with specific refusals):
kill / pause / member / admin work directly; partition and latency ride
the userspace TCP proxy plane (net/plane.py, ``--net-proxy`` — auto-set
when those faults are requested): every advertised client and peer URL
points at a per-node ingress proxy while the process listens on its
real port, so drop/latency rules apply to all inter-node and client
traffic without netns/iptables privileges. Clock skew still needs
per-process time virtualization and stays refused.
"""

from __future__ import annotations

import logging
import os
import re
import shlex
import shutil
import signal
import socket
import subprocess
import sys
import tempfile
from typing import Optional

from ..runner.sim import current_loop, sleep, gather, SECOND
from ..sut.errors import SimError
from ..sut.http_gateway import member_id_for_peer_urls
from .live import _live_client_cls

logger = logging.getLogger("jepsen_etcd_tpu.db.local")

FAKE_ETCD = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "fake_etcd.py")

#: how many startup deaths count as a crash loop (db.clj restarts a
#: crashed node a few times before declaring it wedged)
MAX_START_RETRIES = 3


def resolve_binary(spec) -> list[str]:
    """--etcd-binary -> argv prefix. Accepts a list (tests pass
    [sys.executable, fake_etcd.py]), a shell-ish string, the literal
    "fake", or None (a real etcd from PATH if present, else the
    bundled fake stub)."""
    if isinstance(spec, (list, tuple)) and spec:
        return list(spec)
    if isinstance(spec, str) and spec.strip() and spec.strip() != "fake":
        return shlex.split(spec)
    if not (isinstance(spec, str) and spec.strip() == "fake"):
        real = shutil.which("etcd")
        if real:
            return [real]
        logger.warning("no etcd binary on PATH: using the bundled "
                       "fake-etcd stub (process control is real, the "
                       "store is per-node and non-replicated)")
    return [sys.executable, FAKE_ETCD]


def free_port() -> int:
    s = socket.socket()
    try:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]
    finally:
        s.close()


class LocalDb:
    """jepsen.db over locally-spawned etcd processes."""

    def __init__(self, opts: dict):
        self.opts = opts or {}
        self.binary = resolve_binary(self.opts.get("etcd_binary"))
        self.extra_env: dict = dict(self.opts.get("etcd_env") or {})
        self.members: Optional[set] = None
        self.next_node_id = 0
        self.initialized = False
        # node -> (client_port, peer_port); allocated lazily per node
        self.ports: dict[str, tuple[int, int]] = {}
        # userspace network fault plane (--net-proxy): advertised URLs
        # route through per-node ingress proxies; None = direct wiring
        self.plane = None
        # node -> (client_proxy_port, peer_proxy_port) when fronted
        self.proxy_ports: dict[str, tuple[int, int]] = {}
        if self.opts.get("net_proxy"):
            from ..net.plane import NetPlane
            self.plane = NetPlane(seed=int(self.opts.get("seed") or 0))
        # node -> live Popen (dead ones are reaped out on kill/start)
        self.procs: dict[str, subprocess.Popen] = {}
        # every Popen ever spawned, for teardown + leak accounting
        self.all_procs: list[subprocess.Popen] = []
        self._log_handles: dict[str, object] = {}
        root = self.opts.get("etcd_data_dir")
        if root:
            os.makedirs(root, exist_ok=True)
            self.root = root
            self._own_root = False
        else:
            self.root = tempfile.mkdtemp(prefix="jepsen-etcd-local-")
            self._own_root = True
        # the unique token a /proc cmdline scan can find leaked
        # children by: the absolute data root (every spawn's
        # --data-dir starts with it; a basename like "data" would
        # false-positive on unrelated processes)
        self.token = os.path.abspath(self.root)

    # ---- addressing --------------------------------------------------------

    def _ensure_ports(self, node: str) -> None:
        if node not in self.ports:
            self.ports[node] = (free_port(), free_port())
            if self.plane is not None:
                client_port, peer_port = self.ports[node]
                self.proxy_ports[node] = (
                    self.plane.front(node, "client", client_port),
                    self.plane.front(node, "peer", peer_port))

    def client_url(self, node: str) -> str:
        """What clients (and other nodes' member APIs) dial: the
        ingress proxy when the net plane is up, else the real port."""
        self._ensure_ports(node)
        if self.plane is not None:
            return f"http://127.0.0.1:{self.proxy_ports[node][0]}"
        return f"http://127.0.0.1:{self.ports[node][0]}"

    def peer_url(self, node: str) -> str:
        """The ADVERTISED peer URL (what --initial-cluster carries, so
        every peer dial crosses the target's ingress proxy)."""
        self._ensure_ports(node)
        if self.plane is not None:
            return f"http://127.0.0.1:{self.proxy_ports[node][1]}"
        return f"http://127.0.0.1:{self.ports[node][1]}"

    def listen_client_url(self, node: str) -> str:
        """The real port the process binds (proxied or not)."""
        self._ensure_ports(node)
        return f"http://127.0.0.1:{self.ports[node][0]}"

    def listen_peer_url(self, node: str) -> str:
        self._ensure_ports(node)
        return f"http://127.0.0.1:{self.ports[node][1]}"

    def data_dir(self, node: str) -> str:
        return os.path.join(self.root, node)

    def log_path(self, node: str) -> str:
        return os.path.join(self.root, f"{node}.log")

    def _client(self, test: dict, node: str):
        cls = _live_client_cls(test if isinstance(test, dict) else
                               self.opts)
        c = cls(self.client_url(node))
        c.node = node
        return c

    # ---- spawning ----------------------------------------------------------

    def _argv(self, node: str, state: str, roster: list[str]) -> list[str]:
        """The reference's etcd invocation (db.clj:79-100)."""
        o = self.opts
        argv = list(self.binary) + [
            "--name", node,
            "--data-dir", self.data_dir(node),
            "--listen-client-urls", self.listen_client_url(node),
            "--advertise-client-urls", self.client_url(node),
            "--listen-peer-urls", self.listen_peer_url(node),
            "--initial-advertise-peer-urls", self.peer_url(node),
            "--initial-cluster",
            ",".join(f"{n}={self.peer_url(n)}" for n in sorted(roster)),
            "--initial-cluster-state", state,
            "--initial-cluster-token",
            "jepsen-" + os.path.basename(self.root.rstrip("/")),
            "--snapshot-count", str(o.get("snapshot_count") or 100),
            "--logger", "zap",
            "--log-outputs", "stderr",
        ]
        if o.get("unsafe_no_fsync"):
            argv.append("--unsafe-no-fsync")
        if o.get("corrupt_check"):
            # db.clj:97-99: verify at boot, then sweep every minute
            argv += ["--experimental-initial-corrupt-check=true",
                     "--experimental-corrupt-check-time", "1m"]
        return argv

    def _spawn(self, node: str, state: str,
               roster: Optional[list[str]] = None) -> subprocess.Popen:
        roster = roster if roster is not None else sorted(
            self.members or [node])
        os.makedirs(self.data_dir(node), exist_ok=True)
        old = self._log_handles.pop(node, None)
        if old is not None:
            old.close()
        log = open(self.log_path(node), "ab")
        self._log_handles[node] = log
        env = dict(os.environ)
        env.update({k: str(v) for k, v in self.extra_env.items()})
        proc = subprocess.Popen(self._argv(node, state, roster),
                                stdout=log, stderr=log, env=env)
        self.procs[node] = proc
        self.all_procs.append(proc)
        logger.info("spawned %s (pid %d, state %s)", node, proc.pid,
                    state)
        return proc

    def _log_tail(self, node: str, n: int = 12) -> str:
        try:
            with open(self.log_path(node), "rb") as f:
                lines = f.read().decode("utf-8", "replace").splitlines()
            return "\n".join(lines[-n:])
        except OSError:
            return "<no log>"

    async def _await_node_ready(self, test: dict, node: str,
                                state: str = "existing",
                                max_wait_s: float = 30.0,
                                respawn: bool = True) -> None:
        """Poll status with bounded exponential backoff until the node
        reports a leader (client.clj:652-661). A process that dies
        during startup is respawned up to MAX_START_RETRIES times;
        past that it is a crash loop and setup fails with the log tail
        as evidence."""
        loop = current_loop()
        deadline = loop.now + int(max_wait_s * SECOND)
        delay, respawns = 0.05, 0
        while True:
            proc = self.procs.get(node)
            if proc is None or proc.poll() is not None:
                respawns += 1
                if not respawn or respawns > MAX_START_RETRIES:
                    rc = proc.returncode if proc is not None else "?"
                    raise SimError(
                        "crash-loop",
                        f"{node} died {respawns}x during startup "
                        f"(last exit {rc}); log tail:\n"
                        f"{self._log_tail(node)}")
                self._spawn(node, state)
            else:
                c = self._client(test, node)
                try:
                    st = await c.status()
                    if st.get("leader"):
                        return
                except (SimError, TimeoutError):
                    pass
                finally:
                    c.close()
            if loop.now > deadline:
                raise SimError(
                    "unavailable",
                    f"{node} never became ready in {max_wait_s:.0f}s; "
                    f"log tail:\n{self._log_tail(node)}")
            await sleep(int(delay * SECOND))
            delay = min(delay * 2, 2.0)

    # ---- DB protocol -------------------------------------------------------

    async def setup(self, test: dict) -> None:
        self.members = set(test["nodes"])
        ids = [int(m.group(1)) for n in test["nodes"]
               if (m := re.fullmatch(r"n(\d+)", n))]
        self.next_node_id = max(ids, default=len(test["nodes"]))
        for node in sorted(self.members):
            self._ensure_ports(node)  # full roster before any argv
        for node in sorted(self.members):
            self._spawn(node, "new")
        loop = current_loop()
        await gather(*[
            loop.spawn(self._await_node_ready(test, n, state="new"))
            for n in sorted(self.members)])
        self.initialized = True
        if self.plane is not None:
            await self._register_member_ids(test)
        logger.info("local cluster ready: %s (binary %s)",
                    sorted(self.members), self.binary[0])

    async def _register_member_ids(self, test: dict) -> None:
        """Teach the net plane real member-id -> name attribution: a
        real etcd's rafthttp dials carry X-Server-From: <member-id-hex>
        and the ids are only known once the cluster has formed."""
        c = self._client(test, sorted(self.members)[0])
        try:
            mapping = {}
            for m in await c.member_list():
                if m.get("name") and m.get("id") is not None:
                    mapping[f"{int(m['id']):x}"] = m["name"]
            self.plane.register_member_ids(mapping)
        except (SimError, TimeoutError):
            # attribution degrades gracefully: unattributed peer links
            # are never directionally dropped
            logger.warning("member-id attribution unavailable")
        finally:
            c.close()

    async def teardown(self, test: dict) -> None:
        self.stop_all()

    def stop_all(self) -> None:
        """SIGKILL every child ever spawned and reap it. SIGKILL lands
        on SIGSTOP'd processes too, so paused nodes cannot outlive the
        run."""
        for proc in self.all_procs:
            if proc.poll() is None:
                try:
                    proc.kill()
                except ProcessLookupError:
                    pass
        for proc in self.all_procs:
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:  # pragma: no cover
                logger.error("pid %d failed to die on SIGKILL",
                             proc.pid)
        for h in self._log_handles.values():
            h.close()
        self._log_handles.clear()
        self.procs.clear()
        if self.plane is not None:
            self.plane.close()

    def leaked_pids(self) -> list[int]:
        """Live children after teardown: tracked Popens still running,
        plus any /proc process whose cmdline carries this run's unique
        data-dir token (catches a child we lost track of)."""
        leaked = {p.pid for p in self.all_procs if p.poll() is None}
        try:
            for pid in os.listdir("/proc"):
                if not pid.isdigit():
                    continue
                try:
                    with open(f"/proc/{pid}/cmdline", "rb") as f:
                        cmd = f.read().decode("utf-8", "replace")
                except OSError:
                    continue
                if self.token in cmd and int(pid) != os.getpid():
                    leaked.add(int(pid))
        except OSError:  # pragma: no cover (no /proc: macOS etc.)
            pass
        return sorted(leaked)

    def log_files(self, test: dict) -> dict:
        """node -> etcd log lines (db.clj:234-242), read back from the
        per-node capture files for the run store."""
        out = {}
        for node in sorted(set(self.ports) | set(self.members or ())):
            try:
                with open(self.log_path(node), "rb") as f:
                    out[node] = f.read().decode(
                        "utf-8", "replace").splitlines()
            except OSError:
                pass
        return out

    # ---- Process protocol --------------------------------------------------

    def start(self, test: dict, node: str) -> str:
        proc = self.procs.get(node)
        if proc is not None and proc.poll() is None:
            return "already-running"
        self._spawn(node, "existing" if self.initialized else "new")
        return "started"

    def kill(self, test: dict, node: str) -> str:
        return self.kill_node(test, node,
                              wipe=bool(test.get("wipe_on_kill")))

    def kill_node(self, test: dict, node: str,
                  wipe: bool = False) -> str:
        """SIGKILL, optionally wiping the data dir while it's down
        (kill! + lazyfs lose-unfsynced-writes analog, db.clj:264-267)."""
        proc = self.procs.get(node)
        if proc is not None and proc.poll() is None:
            proc.kill()
            proc.wait(timeout=10)
        if wipe:
            self.wipe(test, node)
        return "killed"

    def _signal(self, node: str, sig: int) -> bool:
        proc = self.procs.get(node)
        if proc is None or proc.poll() is not None:
            return False
        try:
            proc.send_signal(sig)
            return True
        except ProcessLookupError:  # raced with its death
            return False

    def pause(self, test: dict, node: str) -> str:
        return ("paused" if self._signal(node, signal.SIGSTOP)
                else "not-running")

    def resume(self, test: dict, node: str) -> str:
        return ("resumed" if self._signal(node, signal.SIGCONT)
                else "not-running")

    def wipe(self, test: dict, node: str) -> str:
        """Destroy the data dir (wipe!, db.clj:247-255). Only sane on a
        dead node; the caller sequences kill before wipe."""
        shutil.rmtree(self.data_dir(node), ignore_errors=True)
        os.makedirs(self.data_dir(node), exist_ok=True)
        return "wiped"

    # ---- Primary protocol --------------------------------------------------

    async def primaries(self, test: dict) -> list[str]:
        """Highest-raft-term status answer wins (db.clj:38-52), mapped
        back to the node whose own member id IS the reported leader."""
        loop = current_loop()

        async def ask(node):
            c = self._client(test, node)
            try:
                return node, await c.status()
            except (SimError, TimeoutError):
                return node, None
            finally:
                c.close()

        answers = [a for a in await gather(
            *[loop.spawn(ask(n)) for n in sorted(self.members or ())])
            if a[1] is not None]
        if not answers:
            return []
        _, best = max(answers, key=lambda a: a[1].get("raft-term", 0))
        leader_id = best.get("leader")
        if not leader_id:
            return []
        for node, st in answers:
            mid = int(st.get("header", {}).get("member_id", 0) or 0)
            if mid == int(leader_id):
                return [node]
        return []

    # ---- membership (db.clj:128-190) ---------------------------------------

    async def grow(self, test: dict) -> str:
        """Add a member via the real member API on a random current
        node, then spawn and await the new process."""
        loop = current_loop()
        self.next_node_id += 1
        new = f"n{self.next_node_id}"
        self._ensure_ports(new)
        via = loop.rng.choice(sorted(self.members))
        c = self._client(test, via)
        try:
            await c.member_add_urls([self.peer_url(new)])
        finally:
            c.close()
        self.members.add(new)
        self._spawn(new, "existing")
        await self._await_node_ready(test, new, max_wait_s=15)
        return new

    async def shrink(self, test: dict) -> str:
        """Remove a random member via another member's API; kill and
        wipe the victim."""
        loop = current_loop()
        if len(self.members or ()) <= 1:
            raise SimError("unhealthy-cluster", "cannot shrink to zero")
        victim = loop.rng.choice(sorted(self.members))
        others = sorted(self.members - {victim})
        via = loop.rng.choice(others)
        c = self._client(test, via)
        try:
            mid = None
            victim_peer = self.peer_url(victim)
            for m in await c.member_list():
                if m["name"] == victim or \
                        victim_peer in m.get("peer-urls", ()):
                    mid = m["id"]
                    break
            if mid is None:
                # an added-but-renamed member: fall back to the shared
                # peer-URL id derivation
                mid = member_id_for_peer_urls([victim_peer])
            try:
                await c.remove_member_by_id(mid)
            except SimError as e:
                # "member not found" means the goal state — victim not
                # a member — already holds on this node (fake nodes
                # don't replicate membership; real etcd can race a
                # concurrent removal). Anything else is a real failure.
                if "member not found" not in str(e).lower():
                    raise
        finally:
            c.close()
        self.kill_node(test, victim, wipe=True)
        self.members.discard(victim)
        return victim


def local_db(opts: dict) -> LocalDb:
    return LocalDb(opts)
