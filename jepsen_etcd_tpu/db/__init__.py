from .etcd import Db, db

__all__ = ["Db", "db"]
