"""Revisioned MVCC model of the store, built columnar-natively.

The consistency-surface checkers (checkers/mvcc.py) all need the same
substrate: *when could version v of key k have been current?* This
module builds that model in one pass over an ``OpColumns`` view —
per-key **version chains** (version -> the acked write's invoke/ok
interval), the **global revision counter** (acked write revisions),
and the **compaction watermark** ledger (acked compactions) — plus the
run's nemesis fault windows, so checkers can attribute an anomaly to
an open fault instead of calling it definite.

Soundness conventions (every checker rule leans on these):

- A write acked with version ``v`` committed somewhere inside its
  ``[invoke, ok]`` interval, so version ``v`` is *possibly current*
  from its write's invoke until the ok of the write acked ``v+1``
  (missing successor => unbounded). Timed-out (info) writes may have
  committed, so they appear in ``write_invokes`` (lower bounds) but
  never in chains (upper bounds) — unknowns always *widen* intervals.
- Sessions are process incarnations (jepsen: a crashed process never
  returns), so grouping by the ``proc`` column is the session model,
  exactly as in checkers/session.py. The lease model leans on this
  HARDER than the read rules do: ``_lease_sessions`` closes a proc's
  held lease at that same proc's next release invoke, which is only
  sound while proc == session — one incarnation never holds two
  leases, because a second acquire would have come from a NEW proc
  (timeouts retire the incarnation). Both sim epochs guarantee this
  by construction (lease lanes strictly alternate acquire/release,
  and every timeout bumps the proc); live etcd lease ids carry NO
  such guarantee (a real client can re-acquire under one process id),
  so the walk asserts the assumption and raises a diagnostic instead
  of silently merging two leases into one session span.

Times are the history's own clock (virtual ns in both generator
epochs); nothing here reads a wall clock.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

#: sentinel for "no upper bound" interval ends
T_INF = np.iinfo(np.int64).max


def history_columns(history):
    """The columnar view of a history, rebuilding one from a dict
    stream only when no columns exist (hand-built fixtures)."""
    cols = getattr(history, "columns", None)
    if cols is not None:
        return cols
    from .history import History, columns_of
    if isinstance(history, History):
        # graftlint: ignore[COL001] dict-only fallback — no columns exist yet, this path builds them
        ops = history.ops
    else:
        ops = list(history)
    return columns_of(ops)


def _int(v) -> Optional[int]:
    return int(v) if isinstance(v, (int, np.integer)) else None


class MvccModel:
    """One history's MVCC surface: version chains, revision ledger,
    compaction watermark, lease sessions, watch observations, fault
    windows. Built once, shared by every consistency checker."""

    __slots__ = ("chains", "write_invokes", "reads", "ranges",
                 "sessions", "watches", "revisions", "compactions",
                 "windows", "writes", "events")

    def __init__(self):
        #: key -> {"ver": int64[], "inv": int64[], "ok": int64[]}
        #: sorted by version (acked writes only)
        self.chains: dict = {}
        #: key -> sorted int64[] of ALL write invoke times (any
        #: outcome: an info write may have committed)
        self.write_invokes: dict = {}
        #: read observations: (idx, proc, key, version, inv, ok)
        self.reads: list = []
        #: range observations: (idx, proc, inv, ok, [(key, ver), ...])
        self.ranges: list = []
        #: lease sessions: (idx, proc, acq_inv, acq_ok, rel_inv|None)
        self.sessions: list = []
        #: watch observations: (idx, proc, from_rev, revs, gaps)
        self.watches: list = []
        #: acked global revisions (the revision counter's observed
        #: points), sorted
        self.revisions: np.ndarray = np.zeros(0, np.int64)
        #: compaction watermark ledger: (ok_time, revision) acks
        self.compactions: list = []
        #: nemesis fault windows [(open, close)], close may be T_INF
        self.windows: list = []
        self.writes = 0
        self.events = 0

    # -- construction --------------------------------------------------------

    @classmethod
    def from_columns(cls, cols) -> "MvccModel":
        m = cls()
        m.events = len(cols)
        ft = list(cols.f_table)
        vals = cols.values
        tc = cols.type_code
        times = cols.time
        proc = cols.proc
        fc = cols.f_code
        chains: dict = {}
        # invoke -> completion pairing drives every interval below
        for inv_i, cmp_i in cols.client_pairs():
            f = ft[fc[inv_i]]
            inv_t = int(times[inv_i])
            if f == "write":
                v_inv = vals[inv_i]
                if (isinstance(v_inv, (list, tuple)) and len(v_inv) == 3
                        and _int(v_inv[0]) is not None):
                    m.write_invokes.setdefault(
                        _int(v_inv[0]), []).append(inv_t)
            if cmp_i < 0 or tc[cmp_i] != 1:     # never completed / not ok
                continue
            v = vals[cmp_i]
            ok_t = int(times[cmp_i])
            p = int(proc[cmp_i])
            if f == "write":
                if not isinstance(v, (list, tuple)):
                    continue
                if len(v) == 3 and _int(v[0]) is not None \
                        and _int(v[1]) is not None:
                    # [key, version, value]: a version-chain link
                    chains.setdefault(_int(v[0]), []).append(
                        (_int(v[1]), inv_t, ok_t))
                    m.writes += 1
                elif len(v) == 2 and _int(v[0]) is not None:
                    # [revision, value]: a revision-counter observation
                    m.revisions = np.append(m.revisions, _int(v[0]))
                    m.writes += 1
            elif f == "read":
                if (isinstance(v, (list, tuple)) and len(v) == 3
                        and _int(v[0]) is not None
                        and _int(v[1]) is not None):
                    m.reads.append((int(cols.index[cmp_i]), p,
                                    _int(v[0]), _int(v[1]), inv_t, ok_t))
            elif f == "range":
                if isinstance(v, (list, tuple)):
                    pairs = [( _int(e[0]), _int(e[1]))
                             for e in v
                             if isinstance(e, (list, tuple))
                             and len(e) >= 2 and _int(e[0]) is not None
                             and _int(e[1]) is not None]
                    if pairs:
                        m.ranges.append((int(cols.index[cmp_i]), p,
                                         inv_t, ok_t, pairs))
            elif f == "compact":
                r = _int(v)
                if r is not None:
                    m.compactions.append((ok_t, r))
            elif f == "watch":
                if isinstance(v, dict) and _int(v.get("from")) is not None:
                    revs = [r for r in (v.get("revs") or [])
                            if _int(r) is not None]
                    gaps = [(int(g[0]), int(g[1]))
                            for g in (v.get("gaps") or [])
                            if isinstance(g, (list, tuple))
                            and len(g) == 2]
                    m.watches.append((int(cols.index[cmp_i]), p,
                                      _int(v["from"]), revs, gaps))
        for k, links in chains.items():
            links.sort()
            # host-side numpy only: per-key chains are tiny and never
            # cross a device boundary
            arr = np.array(links, np.int64).reshape(len(links), 3)  # graftlint: ignore[JAX002] host numpy, no device transfer
            m.chains[k] = {"ver": arr[:, 0], "inv": arr[:, 1],
                           "ok": arr[:, 2]}
        for k in m.write_invokes:
            m.write_invokes[k] = np.sort(
                np.array(m.write_invokes[k], np.int64))  # graftlint: ignore[JAX002] host numpy, no device transfer
        m.revisions = np.unique(m.revisions)
        m.windows = _fault_windows(cols)
        # lease sessions: per-proc acquire/release state machine (one
        # ordered pass; rows are already in history order)
        m.sessions = _lease_sessions(cols)
        return m

    @classmethod
    def of_history(cls, history) -> Optional["MvccModel"]:
        cols = history_columns(history)
        return None if cols is None else cls.from_columns(cols)

    # -- version-chain queries ----------------------------------------------

    def chain_link(self, key: int, version: int):
        """``(inv, ok)`` of the acked write of ``version`` on ``key``,
        or None if that write never acked (unknown commit point)."""
        ch = self.chains.get(key)
        if ch is None:
            return None
        i = int(np.searchsorted(ch["ver"], version))
        if i >= len(ch["ver"]) or int(ch["ver"][i]) != version:
            return None
        return int(ch["inv"][i]), int(ch["ok"][i])

    def version_window(self, key: int, version: int) -> tuple:
        """The possibly-current interval of (key, version): from the
        version's write invoke (0 for version 0) to the ok of the
        acked successor write (T_INF when the successor is unknown) —
        unknowns widen, so intersecting these windows is sound."""
        if version <= 0:
            lo = 0
        else:
            link = self.chain_link(key, version)
            lo = 0 if link is None else link[0]
        nxt = self.chain_link(key, version + 1)
        hi = T_INF if nxt is None else nxt[1]
        return lo, hi

    def writes_invoked_before(self, key: int, t: int) -> int:
        """How many writes on ``key`` had invoked by time ``t`` (any
        outcome) — the ceiling on any version readable at ``t``."""
        w = self.write_invokes.get(key)
        if w is None:
            return 0
        return int(np.searchsorted(w, t, side="right"))

    # -- compaction / fault-window queries -----------------------------------

    def horizon(self) -> int:
        """Highest acked compaction revision (0 = never compacted)."""
        return max((r for _, r in self.compactions), default=0)

    def window_overlaps(self, lo: int, hi: int) -> bool:
        """Did any fault window intersect ``[lo, hi]``? Checkers use
        this to excuse anomalies a fault can legitimately cause."""
        return any(w_lo <= hi and lo <= w_hi
                   for w_lo, w_hi in self.windows)


#: epoch-v1 process-fault op names (nemesis/faults.py
#: _process_package): onset/heal pairs that don't follow the
#: start-<kind>/stop-<kind> convention the batched generator uses
_V1_ONSETS = {"kill": "kill", "pause": "pause"}
_V1_HEALS = {"start": "kill", "resume": "pause"}


def _fault_windows(cols) -> list:
    """Nemesis windows from fault onset/heal rows, widened to the
    whole burst (first onset .. last heal): wider windows only ever
    excuse more, which is the sound direction. Both generator epochs'
    vocabularies are recognized: ``start-<kind>``/``stop-<kind>``
    (epoch-v2, and epoch-v1 network faults) plus epoch-v1's
    ``kill``/``start`` and ``pause``/``resume`` process faults."""
    ft = list(cols.f_table)
    fc = cols.f_code
    times = cols.time
    by_kind: dict = {}
    for i in range(len(cols)):
        f = ft[fc[i]]
        if f.startswith("start-"):
            by_kind.setdefault(f[6:], []).append((int(times[i]), True))
        elif f.startswith("stop-"):
            by_kind.setdefault(f[5:], []).append((int(times[i]), False))
        elif f in _V1_ONSETS:
            by_kind.setdefault(_V1_ONSETS[f], []).append(
                (int(times[i]), True))
        elif f in _V1_HEALS:
            by_kind.setdefault(_V1_HEALS[f], []).append(
                (int(times[i]), False))
    windows = []
    for rows in by_kind.values():
        rows.sort()
        cur_open = None
        last_stop = None
        for t, is_start in rows:
            if is_start:
                if cur_open is not None and last_stop is not None:
                    windows.append((cur_open, last_stop))
                    cur_open, last_stop = t, None
                elif cur_open is None:
                    cur_open = t
            else:
                last_stop = t
        if cur_open is not None:
            windows.append((cur_open,
                            last_stop if last_stop is not None else T_INF))
    windows.sort()
    return windows


def _lease_sessions(cols) -> list:
    """Acquire/release spans per session: ``(idx, proc, acq_inv,
    acq_ok, rel_inv|None)`` for every acked acquire, closed by the
    same proc's next release *invoke* (the client stops claiming the
    lock the instant it asks to release — outcome irrelevant)."""
    ft = list(cols.f_table)
    if "acquire" not in ft:
        return []
    fc = cols.f_code
    tc = cols.type_code
    times = cols.time
    proc = cols.proc
    acq = ft.index("acquire")
    rel = ft.index("release") if "release" in ft else -1
    open_inv: dict = {}         # proc -> pending acquire invoke time
    held: dict = {}             # proc -> open session list ref
    out: list = []
    for i in range(len(cols)):
        p = int(proc[i])
        if p < 0:
            continue
        f = fc[i]
        t = int(times[i])
        if f == acq:
            if tc[i] == 0:
                open_inv[p] = t
            elif tc[i] == 1:
                if p in held:
                    # proc==session assumption violated: this proc
                    # acked a second acquire while its first lease was
                    # still open (no intervening release invoke). True
                    # in both sim epochs by construction; live etcd
                    # lease ids can re-acquire under one process id,
                    # which this model cannot attribute — refuse
                    # loudly rather than merge two leases into one
                    # session span (module docstring, soundness
                    # conventions).
                    raise ValueError(
                        "lease session model requires proc==session: "
                        f"proc {p} acked acquire at row "
                        f"{int(cols.index[i])} while already holding "
                        f"a lease (acquired at row {held[p][0]}) — "
                        "histories with per-process lease re-acquire "
                        "(live etcd lease ids) need fresh procs per "
                        "acquire before the MVCC lease checkers apply")
                inv_t = open_inv.pop(p, t)
                sess = [int(cols.index[i]), p, inv_t, t, None]
                held[p] = sess
                out.append(sess)
            else:
                open_inv.pop(p, None)
        elif f == rel and tc[i] == 0:
            sess = held.pop(p, None)
            if sess is not None:
                sess[4] = t
    return [tuple(s) for s in out]
