from .op import Op, invoke_op, ok, fail, info, NEMESIS
from .history import History, pair_index

__all__ = ["Op", "invoke_op", "ok", "fail", "info", "NEMESIS", "History", "pair_index"]
