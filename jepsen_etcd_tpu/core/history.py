"""Histories: ordered op sequences with invoke/completion pairing.

Mirrors jepsen.history semantics: a history is a vector of ops ordered by
real (here: virtual) time; each client process is sequential, so an
``invoke`` by process p pairs with the next completion (``ok``/``fail``/
``info``) by p.  Crashed ops surface as ``info`` completions; processes are
then retired and replaced with ``process + concurrency`` by the interpreter
(thread recovery via ``(mod process concurrency)``, cf. reference
``watch.clj:281-282``).
"""

from __future__ import annotations

import json
from typing import Any, Callable, Iterable, Iterator, Optional

import numpy as np

from .op import Op, INVOKE, COMPLETIONS


def pair_index(ops: list[Op]) -> dict[int, int | None]:
    """Map each op's ``index`` field -> its pair's ``index``.

    invoke -> completion index (or None if never completed);
    completion -> invoke index (or None for spontaneous completions, which
    should not occur in our histories).

    Keys are the ops' own ``index`` fields (not positions), so pairing
    survives filtering: a sub-history keeps the parent's indices.
    """
    out: dict[int, int | None] = {}
    open_by_process: dict[Any, int] = {}
    for op in ops:
        t = op.get("type")
        p = op.get("process")
        i = op["index"]
        if t == INVOKE:
            if p in open_by_process:
                raise ValueError(
                    f"process {p!r} invoked op {i} while op "
                    f"{open_by_process[p]} is still open"
                )
            open_by_process[p] = i
            out[i] = None
        elif t in COMPLETIONS:
            j = open_by_process.pop(p, None)
            out[i] = j
            if j is not None:
                out[j] = i
        else:
            raise ValueError(f"op {i} has unknown type {t!r}")
    return out


#: the op keys the typed columns carry; anything else rides in extras
_CORE_KEYS = frozenset(("type", "f", "value", "process", "time", "index"))
_CORE_ORDER = ("type", "f", "value", "process", "time", "index")
_TYPE_CODES = {"invoke": 0, "ok": 1, "fail": 2, "info": 3}
TYPE_NAMES = ("invoke", "ok", "fail", "info")


class OpColumns:
    """Typed structure-of-arrays view of an op stream (SoA columns).

    One row per history EVENT, in record order. The typed columns are
    numpy arrays; payloads stay as an aligned Python list (``values``),
    and rare non-core keys (error, debug, ...) ride in a sparse
    ``extras`` dict keyed by row. Checkers consume the arrays directly
    — see ops/wgl.py's batched packer, checkers/set_full.py,
    checkers/perf.py, checkers/timeline.py — so the per-op dict
    round-trip disappears from those paths; dict materialization is
    lazy (``History.ops``) and counted (``History.dict_materializations``).

    Column schema (pinned; OBSERVABILITY.md §columns documents it):

    - ``type_code``  int8   0 invoke / 1 ok / 2 fail / 3 info
    - ``f_code``     int32  index into ``f_table`` (op ``f`` values)
    - ``proc``       int64  the process when a non-negative int;
                            non-int processes (e.g. "nemesis") intern
                            into ``proc_table`` as ``-(i + 1)``
    - ``key_id``     int64  index into ``key_table`` when the value is
                            a 2-tuple ``(key, v)`` (independent
                            workloads), else ``-1``
    - ``time``       int64  virtual nanoseconds
    - ``index``      int64  global history index
    - ``values``     list   the payload per row — the unwrapped inner
                            value for keyed rows, the raw value
                            otherwise (shared by reference, no copy)
    - ``extras``     dict   row -> {non-core keys}
    - ``missing``    dict   row -> core keys absent from the source op
    """

    __slots__ = ("type_code", "f_code", "proc", "key_id", "time", "index",
                 "values", "extras", "missing",
                 "f_table", "key_table", "proc_table")

    def __init__(self, type_code, f_code, proc, key_id, time, index,
                 values, extras, missing, f_table, key_table, proc_table):
        self.type_code = type_code
        self.f_code = f_code
        self.proc = proc
        self.key_id = key_id
        self.time = time
        self.index = index
        self.values = values
        self.extras = extras
        self.missing = missing
        self.f_table = f_table
        self.key_table = key_table
        self.proc_table = proc_table

    def __len__(self) -> int:
        return len(self.values)

    # -- row accessors -------------------------------------------------------
    def process_at(self, i: int) -> Any:
        p = int(self.proc[i])
        return p if p >= 0 else self.proc_table[-1 - p]

    def value_at(self, i: int) -> Any:
        k = int(self.key_id[i])
        v = self.values[i]
        return v if k < 0 else (self.key_table[k], v)

    def op_at(self, i: int) -> Op:
        d = Op()
        d["type"] = TYPE_NAMES[self.type_code[i]]
        d["f"] = self.f_table[self.f_code[i]]
        d["value"] = self.value_at(i)
        d["process"] = self.process_at(i)
        d["time"] = int(self.time[i])
        d["index"] = int(self.index[i])
        miss = self.missing.get(i)
        if miss:
            for k in miss:
                del d[k]
        ex = self.extras.get(i)
        if ex:
            d.update(ex)
        return d

    def to_ops(self) -> list[Op]:
        return [self.op_at(i) for i in range(len(self.values))]

    # -- pairing / splitting -------------------------------------------------
    def client_pairs(self) -> list[list[int]]:
        """``[[invoke_row, completion_row | -1], ...]`` for client ops
        (int process), in invoke order — the columnar analog of
        iterating invokes and asking ``History.completion``."""
        tc = self.type_code.tolist()
        pr = self.proc.tolist()
        pt = self.proc_table
        out: list[list[int]] = []
        open_by: dict = {}
        for i, t in enumerate(tc):
            p = pr[i]
            if p < 0 and not isinstance(pt[-1 - p], int):
                continue
            if t == 0:
                open_by[p] = len(out)
                out.append([i, -1])
            else:
                j = open_by.pop(p, None)
                if j is not None:
                    out[j][1] = i
        return out

    def split_by_key(self) -> dict:
        """Per-key sub-columns in key first-seen order: the columnar
        analog of ``generators.independent.subhistories`` (values
        unwrapped, indices preserved) with no dict materialization."""
        kid = self.key_id
        order = np.argsort(kid, kind="stable")
        skid = kid[order]
        n = len(skid)
        start = int(np.searchsorted(skid, 0, side="left"))
        groups: dict[int, np.ndarray] = {}
        i = start
        while i < n:
            k = int(skid[i])
            j = int(np.searchsorted(skid, k, side="right"))
            groups[k] = order[i:j]  # stable sort: already in row order
            i = j
        sub_extras: dict[int, dict] = {k: {} for k in groups}
        sub_missing: dict[int, dict] = {k: {} for k in groups}
        for src, dst in ((self.extras, sub_extras),
                         (self.missing, sub_missing)):
            for r, ex in src.items():
                k = int(kid[r])
                if k >= 0:
                    dst[k][int(np.searchsorted(groups[k], r))] = ex
        vals = self.values
        neg1 = None
        out: dict = {}
        for k, rows in groups.items():
            if neg1 is None or len(neg1) != len(rows):
                neg1 = np.full(len(rows), -1, dtype=np.int64)
            out[self.key_table[k]] = OpColumns(
                self.type_code[rows], self.f_code[rows], self.proc[rows],
                neg1, self.time[rows], self.index[rows],
                [vals[r] for r in rows.tolist()],
                sub_extras[k], sub_missing[k],
                self.f_table, [], self.proc_table)
        return out


class ColumnsBuilder:
    """Accumulates SoA columns as the interpreter records ops.

    ``append`` is on the record() hot path: plain list appends plus
    dict-interning, no numpy until ``finish()``. Anything the column
    schema can't express (unhashable f/key, unknown op type, non-int
    time) marks the builder dead and ``finish()`` returns None — the
    run keeps its dict history and checkers take the dict paths.
    """

    __slots__ = ("_tc", "_fc", "_pr", "_kid", "_tm", "_ix",
                 "values", "extras", "missing",
                 "f_index", "f_table", "key_index", "key_table",
                 "proc_index", "proc_table", "dead", "_cursor")

    def __init__(self):
        self._cursor = 0
        self._tc: list = []
        self._fc: list = []
        self._pr: list = []
        self._kid: list = []
        self._tm: list = []
        self._ix: list = []
        self.values: list = []
        self.extras: dict = {}
        self.missing: dict = {}
        self.f_index: dict = {}
        self.f_table: list = []
        self.key_index: dict = {}
        self.key_table: list = []
        self.proc_index: dict = {}
        self.proc_table: list = []
        self.dead = False

    def append(self, op: Op) -> None:
        if self.dead:
            return
        try:
            self._tc.append(_TYPE_CODES[op.get("type")])
            f = op.get("f")
            fc = self.f_index.get(f)
            if fc is None:
                fc = self.f_index[f] = len(self.f_table)
                self.f_table.append(f)
            self._fc.append(fc)
            p = op.get("process")
            if type(p) is int and p >= 0:
                self._pr.append(p)
            else:
                pc = self.proc_index.get(p)
                if pc is None:
                    pc = self.proc_index[p] = len(self.proc_table)
                    self.proc_table.append(p)
                self._pr.append(-(pc + 1))
            v = op.get("value")
            if isinstance(v, tuple) and len(v) == 2:
                k = v[0]
                kc = self.key_index.get(k)
                if kc is None:
                    kc = self.key_index[k] = len(self.key_table)
                    self.key_table.append(k)
                self._kid.append(kc)
                self.values.append(v[1])
            else:
                self._kid.append(-1)
                self.values.append(v)
            self._tm.append(op["time"])
            self._ix.append(op["index"])
            row = len(self._tc) - 1
            n_core = 0
            ex = None
            for key, val in op.items():
                if key in _CORE_KEYS:
                    n_core += 1
                else:
                    if ex is None:
                        ex = {}
                    ex[key] = val
            if ex is not None:
                self.extras[row] = ex
            if n_core != 6:
                self.missing[row] = tuple(
                    k for k in _CORE_ORDER if k not in op)
        except Exception:
            self.dead = True

    def take_chunk(self) -> Optional[OpColumns]:
        """Drain rows recorded since the previous ``take_chunk`` as an
        OpColumns slice (the streaming-checker feed). Non-destructive: a
        cursor advances but the builder keeps every row, so ``finish()``
        still returns the complete columns; intern tables are shared by
        reference (chunk codes stay valid as the tables grow — tables
        only ever append). Returns None when the builder is dead or no
        new rows arrived."""
        if self.dead:
            return None
        start, end = self._cursor, len(self._tc)
        if end <= start:
            return None
        self._cursor = end
        try:
            extras = {r - start: ex for r, ex in self.extras.items()
                      if start <= r < end}
            missing = {r - start: m for r, m in self.missing.items()
                       if start <= r < end}
            return OpColumns(
                np.asarray(self._tc[start:end], dtype=np.int8),
                np.asarray(self._fc[start:end], dtype=np.int32),
                np.asarray(self._pr[start:end], dtype=np.int64),
                np.asarray(self._kid[start:end], dtype=np.int64),
                np.asarray(self._tm[start:end], dtype=np.int64),
                np.asarray(self._ix[start:end], dtype=np.int64),
                self.values[start:end], extras, missing,
                self.f_table, self.key_table, self.proc_table)
        except Exception:
            self.dead = True
            return None

    def finish(self) -> Optional[OpColumns]:
        if self.dead:
            return None
        try:
            return OpColumns(
                np.asarray(self._tc, dtype=np.int8),
                np.asarray(self._fc, dtype=np.int32),
                np.asarray(self._pr, dtype=np.int64),
                np.asarray(self._kid, dtype=np.int64),
                np.asarray(self._tm, dtype=np.int64),
                np.asarray(self._ix, dtype=np.int64),
                self.values, self.extras, self.missing,
                self.f_table, self.key_table, self.proc_table)
        except Exception:
            return None


def columns_of(ops: Iterable[Op]) -> Optional[OpColumns]:
    """Build SoA columns from an existing op stream (tests, reloaded
    histories); None when the stream doesn't fit the schema."""
    b = ColumnsBuilder()
    for op in ops:
        b.append(op)
    return b.finish()


class History:
    """An immutable-by-convention sequence of ops with pairing helpers.

    Backed by a dict op list, SoA columns (``from_columns``), or both
    (recorded histories: the interpreter emits columns alongside the
    dict stream). Column-only histories materialize their dicts lazily
    on first ``.ops`` touch; ``History.dict_materializations`` counts
    those events so perf guards can assert a checker path stayed
    columnar (tests/test_history.py)."""

    #: process-wide count of lazy column->dict materializations
    dict_materializations = 0

    def __init__(self, ops: Iterable[Op],
                 columns: Optional[OpColumns] = None):
        self._ops: list[Op] = [o if isinstance(o, Op) else Op(o)
                               for o in ops]
        self.columns = columns
        # Assign indices to ops missing one, starting past any explicit
        # indices (so synthesized ops appended to a recorded history can't
        # collide); copy rather than mutate the caller's op.
        explicit = [o["index"] for o in self._ops
                    if o.get("index") is not None]
        if len(explicit) != len(set(explicit)):
            raise ValueError("duplicate op indices in history")
        nxt = max(explicit, default=-1) + 1
        for i, o in enumerate(self._ops):
            if o.get("index") is None:
                self._ops[i] = o.evolve(index=nxt)
                nxt += 1
        self._pairs: dict[int, int | None] | None = None
        self._by_index: dict[int, Op] | None = None

    @classmethod
    def from_columns(cls, columns: OpColumns) -> "History":
        """A column-only history: dict ops materialize lazily (and bump
        ``dict_materializations``) only if some consumer asks."""
        h = cls.__new__(cls)
        h._ops = None
        h.columns = columns
        h._pairs = None
        h._by_index = None
        return h

    @property
    def ops(self) -> list[Op]:
        if self._ops is None:
            History.dict_materializations += 1
            self._ops = self.columns.to_ops()
        return self._ops

    def split_by_key(self) -> dict:
        """``{key: History}`` per-key decomposition (2-tuple values),
        keys in first-seen order, values unwrapped, indices preserved —
        columnar when columns are present (no dict work), else the
        one-pass dict split."""
        if self.columns is not None:
            return {k: History.from_columns(c)
                    for k, c in self.columns.split_by_key().items()}
        from ..generators.independent import subhistories
        return {k: History(ops) for k, ops in subhistories(self).items()}

    # -- sequence protocol --------------------------------------------------
    def __len__(self) -> int:
        if self._ops is None:
            return len(self.columns)
        return len(self._ops)

    def __iter__(self) -> Iterator[Op]:
        return iter(self.ops)

    def __getitem__(self, i):
        return self.ops[i]

    # -- pairing ------------------------------------------------------------
    @property
    def pairs(self) -> dict[int, int | None]:
        if self._pairs is None:
            self._pairs = pair_index(self.ops)
        return self._pairs

    def by_index(self, i: int) -> Op:
        if self._by_index is None:
            self._by_index = {o["index"]: o for o in self.ops}
        return self._by_index[i]

    def completion(self, op: Op) -> Op | None:
        """The completion for an invoke op (or None if it never completed)."""
        j = self.pairs.get(op["index"])
        return None if j is None else self.by_index(j)

    def invocation(self, op: Op) -> Op | None:
        j = self.pairs.get(op["index"])
        return None if j is None else self.by_index(j)

    # -- filters (jepsen.history-style) -------------------------------------
    def filter(self, pred: Callable[[Op], bool]) -> "History":
        return History([o for o in self.ops if pred(o)])

    def client_ops(self) -> "History":
        return self.filter(lambda o: isinstance(o.get("process"), int))

    def nemesis_ops(self) -> "History":
        return self.filter(lambda o: not isinstance(o.get("process"), int))

    def oks(self) -> "History":
        return self.filter(lambda o: o.get("type") == "ok")

    def invokes(self) -> "History":
        return self.filter(lambda o: o.get("type") == "invoke")

    def remove_f(self, fs: set) -> "History":
        return self.filter(lambda o: o.get("f") not in fs)

    # -- (de)serialization ---------------------------------------------------
    def to_jsonl(self) -> str:
        return "\n".join(json.dumps(_jsonable(o)) for o in self.ops)

    @classmethod
    def from_jsonl(cls, text: str) -> "History":
        ops = []
        for line in text.splitlines():
            line = line.strip()
            if line:
                ops.append(Op(_unjsonable(json.loads(line))))
        return cls(ops)

    def __repr__(self) -> str:
        return f"<History of {len(self)} ops>"


_SCALAR_TYPES = frozenset((str, int, float, bool, type(None)))


def _jsonable(x: Any) -> Any:
    """JSON encoding that round-trips tuples and sets (tagged).

    Op values use tuples structurally — e.g. the documented ``(key, value)``
    shape for independent workloads — so a plain list coercion would silently
    break tuple-equality in checkers over reloaded histories.
    """
    if isinstance(x, dict):
        if all(isinstance(k, str) for k in x) and not (
                set(x.keys()) & {"__tuple__", "__set__", "__dict__"}):
            return {k: _jsonable(v) for k, v in x.items()}
        # Non-string (or tag-colliding) keys: tagged pair-list encoding.
        return {"__dict__": [[_jsonable(k), _jsonable(v)]
                             for k, v in x.items()]}
    if isinstance(x, tuple):
        return {"__tuple__": [_jsonable(v) for v in x]}
    if isinstance(x, list):
        # fast path: a list of plain scalars is already JSON-clean and
        # json.dumps serializes it at C speed — recursing per element
        # made big read values (e.g. the set workload's full-set reads)
        # dominate history serialization. set(map(type, x)) runs the
        # whole scan in C
        if not set(map(type, x)) - _SCALAR_TYPES:
            return x
        return [_jsonable(v) for v in x]
    if isinstance(x, (set, frozenset)):
        return {"__set__": sorted((_jsonable(v) for v in x), key=repr)}
    if isinstance(x, (str, int, float, bool)) or x is None:
        return x
    return repr(x)  # lossy fallback for exotic values; documented


def _hashable(x: Any) -> Any:
    """Make a decoded key usable as a dict key (lists -> tuples)."""
    if isinstance(x, list):
        return tuple(_hashable(v) for v in x)
    if isinstance(x, set):
        return frozenset(x)
    return x


def _unjsonable(x: Any) -> Any:
    if isinstance(x, dict):
        if set(x.keys()) == {"__tuple__"}:
            return tuple(_unjsonable(v) for v in x["__tuple__"])
        if set(x.keys()) == {"__set__"}:
            return set(_unjsonable(v) for v in x["__set__"])
        if set(x.keys()) == {"__dict__"}:
            return {_hashable(_unjsonable(k)): _unjsonable(v)
                    for k, v in x["__dict__"]}
        return {k: _unjsonable(v) for k, v in x.items()}
    if isinstance(x, list):
        return [_unjsonable(v) for v in x]
    return x
