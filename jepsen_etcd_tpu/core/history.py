"""Histories: ordered op sequences with invoke/completion pairing.

Mirrors jepsen.history semantics: a history is a vector of ops ordered by
real (here: virtual) time; each client process is sequential, so an
``invoke`` by process p pairs with the next completion (``ok``/``fail``/
``info``) by p.  Crashed ops surface as ``info`` completions; processes are
then retired and replaced with ``process + concurrency`` by the interpreter
(thread recovery via ``(mod process concurrency)``, cf. reference
``watch.clj:281-282``).
"""

from __future__ import annotations

import json
from typing import Any, Callable, Iterable, Iterator

from .op import Op, INVOKE, COMPLETIONS


def pair_index(ops: list[Op]) -> dict[int, int | None]:
    """Map each op's ``index`` field -> its pair's ``index``.

    invoke -> completion index (or None if never completed);
    completion -> invoke index (or None for spontaneous completions, which
    should not occur in our histories).

    Keys are the ops' own ``index`` fields (not positions), so pairing
    survives filtering: a sub-history keeps the parent's indices.
    """
    out: dict[int, int | None] = {}
    open_by_process: dict[Any, int] = {}
    for op in ops:
        t = op.get("type")
        p = op.get("process")
        i = op["index"]
        if t == INVOKE:
            if p in open_by_process:
                raise ValueError(
                    f"process {p!r} invoked op {i} while op "
                    f"{open_by_process[p]} is still open"
                )
            open_by_process[p] = i
            out[i] = None
        elif t in COMPLETIONS:
            j = open_by_process.pop(p, None)
            out[i] = j
            if j is not None:
                out[j] = i
        else:
            raise ValueError(f"op {i} has unknown type {t!r}")
    return out


class History:
    """An immutable-by-convention sequence of ops with pairing helpers."""

    def __init__(self, ops: Iterable[Op]):
        self.ops: list[Op] = [o if isinstance(o, Op) else Op(o) for o in ops]
        # Assign indices to ops missing one, starting past any explicit
        # indices (so synthesized ops appended to a recorded history can't
        # collide); copy rather than mutate the caller's op.
        explicit = [o["index"] for o in self.ops if o.get("index") is not None]
        if len(explicit) != len(set(explicit)):
            raise ValueError("duplicate op indices in history")
        nxt = max(explicit, default=-1) + 1
        for i, o in enumerate(self.ops):
            if o.get("index") is None:
                self.ops[i] = o.evolve(index=nxt)
                nxt += 1
        self._pairs: dict[int, int | None] | None = None
        self._by_index: dict[int, Op] | None = None

    # -- sequence protocol --------------------------------------------------
    def __len__(self) -> int:
        return len(self.ops)

    def __iter__(self) -> Iterator[Op]:
        return iter(self.ops)

    def __getitem__(self, i):
        return self.ops[i]

    # -- pairing ------------------------------------------------------------
    @property
    def pairs(self) -> dict[int, int | None]:
        if self._pairs is None:
            self._pairs = pair_index(self.ops)
        return self._pairs

    def by_index(self, i: int) -> Op:
        if self._by_index is None:
            self._by_index = {o["index"]: o for o in self.ops}
        return self._by_index[i]

    def completion(self, op: Op) -> Op | None:
        """The completion for an invoke op (or None if it never completed)."""
        j = self.pairs.get(op["index"])
        return None if j is None else self.by_index(j)

    def invocation(self, op: Op) -> Op | None:
        j = self.pairs.get(op["index"])
        return None if j is None else self.by_index(j)

    # -- filters (jepsen.history-style) -------------------------------------
    def filter(self, pred: Callable[[Op], bool]) -> "History":
        return History([o for o in self.ops if pred(o)])

    def client_ops(self) -> "History":
        return self.filter(lambda o: isinstance(o.get("process"), int))

    def nemesis_ops(self) -> "History":
        return self.filter(lambda o: not isinstance(o.get("process"), int))

    def oks(self) -> "History":
        return self.filter(lambda o: o.get("type") == "ok")

    def invokes(self) -> "History":
        return self.filter(lambda o: o.get("type") == "invoke")

    def remove_f(self, fs: set) -> "History":
        return self.filter(lambda o: o.get("f") not in fs)

    # -- (de)serialization ---------------------------------------------------
    def to_jsonl(self) -> str:
        return "\n".join(json.dumps(_jsonable(o)) for o in self.ops)

    @classmethod
    def from_jsonl(cls, text: str) -> "History":
        ops = []
        for line in text.splitlines():
            line = line.strip()
            if line:
                ops.append(Op(_unjsonable(json.loads(line))))
        return cls(ops)

    def __repr__(self) -> str:
        return f"<History of {len(self.ops)} ops>"


_SCALAR_TYPES = frozenset((str, int, float, bool, type(None)))


def _jsonable(x: Any) -> Any:
    """JSON encoding that round-trips tuples and sets (tagged).

    Op values use tuples structurally — e.g. the documented ``(key, value)``
    shape for independent workloads — so a plain list coercion would silently
    break tuple-equality in checkers over reloaded histories.
    """
    if isinstance(x, dict):
        if all(isinstance(k, str) for k in x) and not (
                set(x.keys()) & {"__tuple__", "__set__", "__dict__"}):
            return {k: _jsonable(v) for k, v in x.items()}
        # Non-string (or tag-colliding) keys: tagged pair-list encoding.
        return {"__dict__": [[_jsonable(k), _jsonable(v)]
                             for k, v in x.items()]}
    if isinstance(x, tuple):
        return {"__tuple__": [_jsonable(v) for v in x]}
    if isinstance(x, list):
        # fast path: a list of plain scalars is already JSON-clean and
        # json.dumps serializes it at C speed — recursing per element
        # made big read values (e.g. the set workload's full-set reads)
        # dominate history serialization. set(map(type, x)) runs the
        # whole scan in C
        if not set(map(type, x)) - _SCALAR_TYPES:
            return x
        return [_jsonable(v) for v in x]
    if isinstance(x, (set, frozenset)):
        return {"__set__": sorted((_jsonable(v) for v in x), key=repr)}
    if isinstance(x, (str, int, float, bool)) or x is None:
        return x
    return repr(x)  # lossy fallback for exotic values; documented


def _hashable(x: Any) -> Any:
    """Make a decoded key usable as a dict key (lists -> tuples)."""
    if isinstance(x, list):
        return tuple(_hashable(v) for v in x)
    if isinstance(x, set):
        return frozenset(x)
    return x


def _unjsonable(x: Any) -> Any:
    if isinstance(x, dict):
        if set(x.keys()) == {"__tuple__"}:
            return tuple(_unjsonable(v) for v in x["__tuple__"])
        if set(x.keys()) == {"__set__"}:
            return set(_unjsonable(v) for v in x["__set__"])
        if set(x.keys()) == {"__dict__"}:
            return {_hashable(_unjsonable(k)): _unjsonable(v)
                    for k, v in x["__dict__"]}
        return {k: _unjsonable(v) for k, v in x.items()}
    if isinstance(x, list):
        return [_unjsonable(v) for v in x]
    return x
