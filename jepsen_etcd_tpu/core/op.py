"""The operation model.

Jepsen represents operations as Clojure maps with keys ``:type`` (one of
``:invoke``, ``:ok``, ``:fail``, ``:info``), ``:f``, ``:value``, ``:process``,
``:time`` (nanoseconds), ``:index``, plus ad-hoc extras (``:error``,
``:debug``, ...).  Checkers nil-pun missing keys heavily, so we model an op as
a thin ``dict`` subclass with attribute access that returns ``None`` for
missing keys.

Reference semantics: jepsen.etcd records histories through jepsen's generator
interpreter; op shape is visible throughout the reference, e.g.
``register.clj:98-100`` (op constructors ``r``/``w``/``cas``) and
``watch.clj:278-291`` (thread recovery via ``(mod process concurrency)``).
"""

from __future__ import annotations

from typing import Any, Iterable

#: the distinguished nemesis "process"; jepsen uses the keyword :nemesis.
NEMESIS = "nemesis"

INVOKE = "invoke"
OK = "ok"
FAIL = "fail"
INFO = "info"

COMPLETIONS = (OK, FAIL, INFO)


class Op(dict):
    """An operation: a dict with attribute access (missing keys -> None).

    ``op.type`` is one of "invoke", "ok", "fail", "info".
    ``op.f`` is the function tag (e.g. "read", "write", "cas", "txn").
    ``op.value`` is workload-specific; for independent (per-key) workloads it
    is a ``(key, value)`` tuple, mirroring jepsen.independent.
    ``op.process`` is an int worker process, or "nemesis".
    ``op.time`` is virtual nanoseconds since test start.
    ``op.index`` is the global history index (dense, 0-based).
    """

    __slots__ = ()

    def __getattr__(self, name: str) -> Any:
        if name.startswith("__"):
            raise AttributeError(name)
        return self.get(name)

    def __setattr__(self, name: str, value: Any) -> None:
        self[name] = value

    # -- predicates ---------------------------------------------------------
    @property
    def is_invoke(self) -> bool:
        return self.get("type") == INVOKE

    @property
    def is_ok(self) -> bool:
        return self.get("type") == OK

    @property
    def is_fail(self) -> bool:
        return self.get("type") == FAIL

    @property
    def is_info(self) -> bool:
        return self.get("type") == INFO

    @property
    def is_completion(self) -> bool:
        return self.get("type") in COMPLETIONS

    @property
    def is_client_op(self) -> bool:
        return isinstance(self.get("process"), int)

    def evolve(self, **kw: Any) -> "Op":
        """Copy with updates (the op analog of clojure's assoc)."""
        new = Op(self)
        new.update(kw)
        return new

    def __repr__(self) -> str:  # compact, jepsen-log-like
        base = f"{self.get('index')}\t{self.get('process')}\t{self.get('type')}\t{self.get('f')}\t{self.get('value')!r}"
        err = self.get("error")
        return base + (f"\t{err!r}" if err is not None else "")


def invoke_op(process: Any, f: str, value: Any = None, **extra: Any) -> Op:
    op = Op(type=INVOKE, f=f, value=value, process=process)
    op.update(extra)
    return op


def _complete(op: Op, type_: str, **extra: Any) -> Op:
    new = op.evolve(type=type_)
    new.update(extra)
    return new


def ok(op: Op, **extra: Any) -> Op:
    return _complete(op, OK, **extra)


def fail(op: Op, error: Any = None, **extra: Any) -> Op:
    return _complete(op, FAIL, error=error, **extra)


def info(op: Op, error: Any = None, **extra: Any) -> Op:
    return _complete(op, INFO, error=error, **extra)


def ops_by_f(ops: Iterable[Op], f: str) -> list[Op]:
    return [o for o in ops if o.get("f") == f]
