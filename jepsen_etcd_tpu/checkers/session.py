"""Session-guarantee checker: monotone reads and writes-follow-reads,
one vectorized pass over OpColumns.

The cheap slice of ROADMAP direction 2: register histories carry
``[version, value]`` payloads, so two of the classic session guarantees
(Terry et al., PDIS 1994) reduce to per-session version arithmetic —
no search, no state-machine replay:

- **monotone reads**: successive reads in one session must observe
  non-decreasing versions (a read below the session's running read-max
  is a stale read).
- **writes-follow-reads**: a write acknowledged at version ``v`` was
  ordered after every write the session had already read, i.e. ``v``
  must exceed the session's prior read-max.

A *session* is one process incarnation (jepsen semantics: a crashed
process never returns — its thread continues as a NEW process, which is
exactly a new session), so grouping by the ``proc`` column is the whole
session model. Both guarantees then fall out of one segmented
running-max over completion versions: sort rows by (session, history
order), offset each group's versions into a disjoint band
(``gid * BAND``), and ``np.maximum.accumulate`` yields every row's
prior-read-max in O(n log n) with no Python loop over ops.

Weaker than linearizability — a history can pass here and still fail
the linear checker — but the pass is cheap enough to run on every
history, and it localizes *which session* observed the anomaly, which
a global linearizability verdict does not.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from .core import Checker

#: per-group version band for the segmented running max; versions are
#: write counts per key and histories are far below this
_BAND = np.int64(2) ** 40

#: violations reported per run (the rest are counted, not listed)
_MAX_REPORT = 8


def _versions(cols) -> tuple:
    """Completion versions per row: ``(vers, is_read, is_write)`` with
    ``vers[i] = -1`` for rows that carry no version (invokes, infos,
    failed cas, non-register payloads)."""
    n = len(cols)
    vers = np.full(n, -1, np.int64)
    is_read = np.zeros(n, bool)
    is_write = np.zeros(n, bool)
    ft = list(cols.f_table)
    rd = ft.index("read") if "read" in ft else -1
    wr = ft.index("write") if "write" in ft else -1
    cs = ft.index("cas") if "cas" in ft else -1
    # version payloads exist only under the register schema ([version,
    # value] pairs); a history whose f table has reads but no
    # write/cas (e.g. the set workload, where a read's value is a
    # snapshot LIST) carries no versions to check
    if rd < 0 or (wr < 0 and cs < 0):
        return vers, is_read, is_write
    ok = cols.type_code == 1
    fc = cols.f_code
    cand = np.flatnonzero(ok & ((fc == rd) | (fc == wr) | (fc == cs)))
    vals = cols.values
    fcl = fc[cand].tolist()
    for i, f in zip(cand.tolist(), fcl):
        v = vals[i]
        if not isinstance(v, (list, tuple)) or not v:
            continue
        ver = v[0]
        if not isinstance(ver, (int, np.integer)):
            continue
        vers[i] = int(ver)
        if f == rd:
            is_read[i] = True
        else:
            is_write[i] = True
    return vers, is_read, is_write


class SessionGuarantees(Checker):
    """Monotone-reads + writes-follow-reads over version payloads."""

    def check(self, test, history, opts: Optional[dict] = None) -> dict:
        cols = getattr(history, "columns", None)
        if cols is None:
            # dict-only histories (hand-built test fixtures) have no
            # columnar view; the guarantees still apply, so rebuild one
            # from the dict stream rather than skipping the check
            from ..core.history import History, columns_of
            if isinstance(history, History):
                # graftlint: ignore[COL001] dict-only fallback — no columns exist yet, this path builds them
                ops = history.ops
            else:
                ops = list(history)
            cols = columns_of(ops)
            if cols is None:
                return {"valid?": "unknown",
                        "error": "history has no columnar view"}
        vers, is_read, is_write = _versions(cols)
        rows = np.flatnonzero(is_read | is_write)
        n_read = int(is_read.sum())
        if rows.size == 0 or n_read == 0:
            # no reads -> both guarantees hold vacuously: True, not
            # "unknown" (nothing was left unchecked)
            return {"valid?": True, "sessions": 0, "reads": n_read,
                    "writes": int(is_write.sum())}
        # sessions: (proc, key) groups — under the independent split
        # key_id is uniformly -1 and this degrades to proc alone
        proc = cols.proc[rows]
        kid = cols.key_id[rows]
        sess = np.unique(proc)
        pgid = np.searchsorted(sess, proc)
        kuniq = np.unique(kid)
        kgid = np.searchsorted(kuniq, kid)
        gid = pgid * len(kuniq) + kgid
        # segmented exclusive running max of READ versions, in history
        # order within each group: band-offset + maximum.accumulate
        order = np.argsort(gid, kind="stable")  # rows already time-sorted
        g = gid[order]
        v = vers[rows][order]
        r = is_read[rows][order]
        w = is_write[rows][order]
        banded = g * _BAND + np.where(r, v, -1)
        acc = np.maximum.accumulate(banded)
        prior = np.empty_like(acc)
        prior[0] = -1
        # acc[i-1] for a group's first row comes from an earlier group's
        # band, lands below g*_BAND, and clamps to "no prior read"
        prior[1:] = acc[:-1] - g[1:] * _BAND
        prior = np.maximum(prior, -1)
        mr_bad = r & (v < prior)
        wfr_bad = w & (v >= 0) & (v <= prior)
        bad = np.flatnonzero(mr_bad | wfr_bad)
        result = {
            "valid?": bad.size == 0,
            "sessions": int(len(sess)),
            "reads": n_read,
            "writes": int(is_write.sum()),
        }
        if bad.size:
            report = []
            for b in bad[:_MAX_REPORT].tolist():
                i = int(rows[order[b]])
                report.append({
                    "guarantee": ("monotone-reads" if mr_bad[b]
                                  else "writes-follow-reads"),
                    "index": int(cols.index[i]),
                    "process": cols.process_at(i),
                    "f": cols.f_table[cols.f_code[i]],
                    "version": int(v[b]),
                    "prior-read-max": int(prior[b]),
                })
            result["violation-count"] = int(bad.size)
            result["violations"] = report
        return result


def session_guarantees() -> SessionGuarantees:
    return SessionGuarantees()
