from .core import (Checker, Compose, compose, Stats, UnhandledExceptions,
                   LogFilePattern, ClockPlot, Noop)
from .independent import Independent, independent_checker
from .linearizable import LinearizableChecker, linearizable, check_history
from .mvcc import (BoundedStaleness, CompactionWatch, LeaseChurn,
                   SnapshotRanges)
from .perf import Perf
from .session import SessionGuarantees, session_guarantees
from .set_full import SetFull, set_full
from .timeline import TimelineHtml

__all__ = [
    "Checker", "Compose", "compose", "Stats", "UnhandledExceptions",
    "LogFilePattern", "ClockPlot", "Noop", "Independent",
    "independent_checker", "LinearizableChecker", "linearizable",
    "check_history", "BoundedStaleness", "CompactionWatch",
    "LeaseChurn", "SnapshotRanges", "Perf", "SessionGuarantees",
    "session_guarantees", "SetFull", "set_full", "TimelineHtml",
]
