"""Watch checker: all watchers saw the same values in the same order.

Re-design of the reference checker (watch.clj:274-357):

- group ok ``watch``/``final-watch`` ops by *thread* (``process mod
  concurrency`` — processes recycle onto threads, watch.clj:281-282) and
  concatenate their observed value logs;
- pick a canonical log: the most common log, else the longest
  (watch.clj:304-318);
- any thread whose log differs (nonzero edit distance, computed by the
  TPU wavefront kernel, ops/edit_distance.py) is a delta -> invalid;
- threads that recorded a compaction gap (final-watch restarted past the
  compact horizon, watch.clj:243-267 semantics) are held to a weaker but
  still sound standard: their log must be an in-order subsequence of
  canonical and every canonical value they missed must have a revision
  inside one of their recorded gap windows — omissions are forgiven
  only where compaction provably destroyed the events;
- any ``nonmonotonic-watch`` error in history -> invalid
  (watch.clj:320-326, 347-350);
- if threads' final revisions are unequal the test didn't converge, so
  missing entries prove nothing: verdict :unknown (watch.clj:348-351).
"""

from __future__ import annotations

from collections import Counter, defaultdict
from typing import Optional

from ..core.history import History
from ..ops.edit_distance import edit_distance_batch, diff_report
from .core import Checker


def per_thread_watches(test, history) -> dict:
    conc = test.get("concurrency", 1) if isinstance(test, dict) else 1
    h = history if isinstance(history, History) else History(history)
    out: dict = defaultdict(list)
    for op in h.client_ops():
        if op.is_ok and op.get("f") in ("watch", "final-watch"):
            out[op["process"] % conc].append(op)
    return dict(out)


def per_thread_logs(test, history) -> dict:
    return {thread: [v for op in ops
                     for v in ((op.value or {}).get("log") or [])]
            for thread, ops in per_thread_watches(test, history).items()}


def per_thread_revs(test, history) -> dict:
    """Per-thread event-revision logs (parallel to per_thread_logs)."""
    return {thread: [r for op in ops
                     for r in ((op.value or {}).get("revs") or [])]
            for thread, ops in per_thread_watches(test, history).items()}


def per_thread_gaps(test, history) -> dict:
    """Per-thread compaction-gap windows [(from_rev, to_rev], ...]: the
    unobservable window recorded when a final-watch restarted past the
    compact horizon (watch.clj:243-267 semantics)."""
    return {thread: [tuple(g) for op in ops
                     for g in ((op.value or {}).get("gaps") or [])]
            for thread, ops in per_thread_watches(test, history).items()}


def is_subsequence(sub: list, seq: list) -> bool:
    it = iter(seq)
    return all(any(x == y for y in it) for x in sub)


def per_thread_revisions(test, history) -> dict:
    return {thread: max([(op.value or {}).get("revision", 0)
                         for op in ops] + [0])
            for thread, ops in per_thread_watches(test, history).items()}


def canonical_log(logs: list) -> list:
    """The mode log if one repeats, else the longest (watch.clj:304-318)."""
    if not logs:
        return []
    counts = Counter(tuple(l) for l in logs)
    (top, freq), = counts.most_common(1)
    if freq > 1:
        return list(top)
    return max(logs, key=len)


class WatchChecker(Checker):
    def __init__(self, use_tpu: Optional[bool] = None):
        self.use_tpu = use_tpu

    def check(self, test, history, opts=None) -> dict:
        h = history if isinstance(history, History) else History(history)
        logs = per_thread_logs(test, h)
        revs = per_thread_revs(test, h)
        gaps = per_thread_gaps(test, h)
        revisions = per_thread_revisions(test, h)
        full = sorted(t for t in logs if not gaps.get(t))
        gapped = sorted(t for t in logs if gaps.get(t))
        # canonical from complete logs when any exist: a gapped log is
        # legitimately missing its compacted window and must not define
        # the consensus. With EVERY thread gapped, no single log can
        # serve (each may be missing values another saw outside its own
        # window) — merge all observations by server revision instead
        if full:
            canonical = canonical_log([logs[t] for t in full])
        else:
            by_rev: dict = {}
            for t in gapped:
                for v, r in zip(logs[t], revs.get(t, [])):
                    by_rev.setdefault(r, v)
            canonical = [v for _, v in sorted(by_rev.items())]
        deltas = []
        dists = edit_distance_batch(canonical, [logs[t] for t in full],
                                    force_device=self.use_tpu)
        for thread, ed in zip(full, dists):
            if ed:
                deltas.append({"thread": thread, "edit-distance": ed,
                               "diff": diff_report(canonical,
                                                   logs[thread])})
        # a gapped thread may omit exactly the values that fell inside a
        # recorded compaction window — everything it DID see must still
        # be in canonical order, and every canonical value it missed
        # must be attributable to a gap. Attribution is by OCCURRENCE
        # (value, revision), not a first-seen value->rev map: were the
        # same value ever written twice, a miss of the later occurrence
        # must be judged against ITS revision, not the earlier one's.
        from collections import Counter, defaultdict
        value_revs: dict = defaultdict(set)
        for t in logs:
            for v, r in zip(logs[t], revs.get(t, [])):
                value_revs[v].add(r)
        sorted_revs = {v: sorted(rs) for v, rs in value_revs.items()}

        def canonical_occurrence_revs():
            nth: Counter = Counter()
            out = []
            for v in canonical:
                rl = sorted_revs.get(v)
                k = nth[v]
                nth[v] += 1
                out.append(rl[min(k, len(rl) - 1)] if rl else None)
            return out

        crevs = canonical_occurrence_revs()
        ccount = Counter(canonical)
        dup_values = any(c > 1 for c in ccount.values())

        def greedy_missing(thread, reverse=False):
            have: Counter = Counter(logs[thread])
            taken: Counter = Counter()
            pairs = list(zip(canonical, crevs))
            if reverse:
                pairs = pairs[::-1]
            out = []
            for v, r in pairs:
                if taken[v] < have[v]:
                    taken[v] += 1
                else:
                    out.append((v, r))
            return out[::-1] if reverse else out

        def unattributed_of(thread, pairs):
            return [v for v, r in pairs
                    if r is None or not any(lo < r <= hi
                                            for lo, hi in gaps[thread])]

        for thread in gapped:
            trevs = revs.get(thread, [])
            missing_pairs = []
            indefinite = False
            if len(trevs) == len(logs[thread]):
                # match by the thread's OWN recorded (value, revision)
                # pairs: a thread that saw only the LATER of two writes
                # of the same value must have the EARLIER occurrence
                # marked missing (attributable to its gap), not the
                # later one
                avail: Counter = Counter(zip(logs[thread], trevs))
                for v, r in zip(canonical, crevs):
                    if avail[(v, r)] > 0:
                        avail[(v, r)] -= 1
                    else:
                        missing_pairs.append((v, r))
            else:
                # no per-event revisions recorded: greedy value-count
                # matching (exact while the workload writes unique
                # values). With duplicate values the start-anchored
                # assignment can hand a sighting to the wrong
                # occurrence, so also try the end-anchored one; if
                # neither attributes every miss to a gap, the evidence
                # is ambiguous — downgrade to indefinite rather than
                # report a possibly-false violation
                missing_pairs = greedy_missing(thread)
                if dup_values and unattributed_of(thread, missing_pairs):
                    alt = greedy_missing(thread, reverse=True)
                    if len(unattributed_of(thread, alt)) < \
                            len(unattributed_of(thread, missing_pairs)):
                        missing_pairs = alt
                    rest = unattributed_of(thread, missing_pairs)
                    # only assignment ambiguity is indefinite: a missed
                    # value is reassignable only when canonical repeats
                    # it AND the thread sighted it at least once —
                    # otherwise every occurrence is missing under every
                    # assignment and the miss is determined, so it
                    # stays a definite violation
                    have: Counter = Counter(logs[thread])
                    if rest and all(ccount[v] > 1 and have[v] > 0
                                    for v in rest):
                        indefinite = True
            unattributed = unattributed_of(thread, missing_pairs)
            if not is_subsequence(logs[thread], canonical) or unattributed:
                delta = {"thread": thread,
                         "edit-distance": len(unattributed) or 1,
                         "gaps": gaps[thread],
                         "unattributed-missing": unattributed[:32],
                         "diff": diff_report(canonical,
                                             logs[thread])}
                # out-of-order sightings stay definite violations even
                # under duplicate values; only pure attribution
                # ambiguity is indefinite
                if indefinite and is_subsequence(logs[thread], canonical):
                    delta["indefinite"] = True
                deltas.append(delta)
        deltas.sort(key=lambda d: -d["edit-distance"])
        nm_errors = [op["error"] for op in h
                     if isinstance(op.get("error"), (list, tuple))
                     and op["error"] and op["error"][0] == "nonmonotonic-watch"]
        definite_deltas = [d for d in deltas if not d.get("indefinite")]
        if nm_errors:
            valid = False
        elif len(set(revisions.values())) > 1:
            valid = "unknown"
        elif definite_deltas:
            valid = False
        elif deltas:
            valid = "unknown"
        else:
            valid = True
        out = {"valid?": valid, "revisions": revisions}
        if valid is not True:
            out.update({"logs": {t: l[:200] for t, l in logs.items()},
                        "canonical": canonical[:200],
                        "deltas": deltas[:8],
                        "nonmonotonic-errors": nm_errors[:8]})
        return out
