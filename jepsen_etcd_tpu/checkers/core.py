"""The checker protocol and the composite/stat checkers.

Mirrors the jepsen.checker stack the reference composes at
``etcd.clj:128-141``: compose{perf, clock, stats, exceptions,
crash(log-file-pattern), workload-checker}.
"""

from __future__ import annotations

import re
from collections import Counter, defaultdict
from typing import Any, Optional

from ..core.history import History
from ..runner import telemetry


class Checker:
    def check(self, test: Any, history, opts: Optional[dict] = None) -> dict:
        raise NotImplementedError


def stream_hint(test: Any, history, name: str):
    """Fetch a streaming-precomputed artifact (runner/stream.py installs
    ``test["_stream"]`` = {name: (artifact, n_rows), ...}) if — and only
    if — it provably covers THIS history: the feed consumed exactly
    ``len(history)`` rows and the history still carries the columns the
    artifact was extracted from. Returns the artifact or None; hints
    are pure reuse, never a correctness dependency — a None simply
    means the checker recomputes from scratch."""
    hint = test.get("_stream") if isinstance(test, dict) else None
    if not hint or getattr(history, "columns", None) is None:
        return None
    got = hint.get(name)
    if got is None or got[1] != len(history):
        return None
    telemetry.current().counter(f"stream.{name}_reuse")
    return got[0]


def _merge_valid(vals: list) -> Any:
    """jepsen merge-valid: false < unknown < true."""
    if any(v is False for v in vals):
        return False
    if any(v == "unknown" for v in vals):
        return "unknown"
    return True


class Compose(Checker):
    def __init__(self, checkers: dict):
        self.checkers = checkers

    def check(self, test, history, opts=None) -> dict:
        tel = telemetry.current()
        results = {}
        for name, c in self.checkers.items():
            with tel.span("checker:" + str(name)):
                results[name] = c.check(test, history, opts)
        return {"valid?": _merge_valid([r.get("valid?") for r in
                                        results.values()]),
                **results}

    def check_batch(self, test, subhistories: dict, opts=None) -> dict:
        """Per-key batch entry (called by checkers.Independent): children
        that are batch-aware (the TPU kernel) get the whole key batch in
        one call; the rest run per key."""
        tel = telemetry.current()
        per_key: dict = {k: {} for k in subhistories}
        for name, c in self.checkers.items():
            with tel.span("checker:" + str(name), keys=len(subhistories)):
                if hasattr(c, "check_batch"):
                    outs = c.check_batch(test, subhistories, opts)
                else:
                    outs = {k: c.check(test, sub, opts)
                            for k, sub in subhistories.items()}
            for k, r in outs.items():
                per_key[k][name] = r
        return {k: {"valid?": _merge_valid([r.get("valid?")
                                            for r in rs.values()]), **rs}
                for k, rs in per_key.items()}


def compose(checkers: dict) -> Compose:
    return Compose(checkers)


class Stats(Checker):
    """checker/stats: ok/fail/info counts, per f (etcd.clj:131)."""

    def check(self, test, history, opts=None) -> dict:
        h = history if isinstance(history, History) else History(history)
        by_f: dict = defaultdict(Counter)
        total = Counter()
        for op in h.client_ops():
            if op.is_completion:
                by_f[op.f][op["type"]] += 1
                total[op["type"]] += 1
        # valid if every f had at least one ok (jepsen's heuristic:
        # a workload where some op class never succeeds is suspicious)
        valid = all(c.get("ok", 0) > 0 for c in by_f.values()) \
            if by_f else True
        return {"valid?": True if valid else "unknown",
                "count": sum(total.values()),
                "ok-count": total.get("ok", 0),
                "fail-count": total.get("fail", 0),
                "info-count": total.get("info", 0),
                "by-f": {f: dict(c) for f, c in by_f.items()}}


class UnhandledExceptions(Checker):
    """checker/unhandled-exceptions: collect worker-crash errors
    (etcd.clj:133)."""

    def check(self, test, history, opts=None) -> dict:
        h = history if isinstance(history, History) else History(history)
        crashes = [dict(op) for op in h
                   if isinstance(op.get("error"), (list, tuple))
                   and len(op["error"]) == 2
                   and op["error"][0] == "worker-crash"]
        return {"valid?": True if not crashes else False,
                "exceptions": crashes[:16],
                "count": len(crashes)}


class LogFilePattern(Checker):
    """checker/log-file-pattern: scan SUT logs for crash signatures
    (etcd.clj:134-140), with the reference's false-positive carve-out for
    membership-change restarts ("couldn't find local name")."""

    def __init__(self,
                 pattern: str = r'"level":"(fatal|panic)"|panic:'
                                r'|^signal SIG',
                 exclude: str = r"couldn't find local name",
                 log_file: str = "etcd.log"):
        # default matches the reference's regex (etcd.clj:139): JSON
        # fatal/panic levels, literal "panic:", or a line-leading signal
        # — NOT bare substrings like "fatal"/"SIG", which false-match
        # fault-injection markers
        self.pattern = re.compile(pattern)
        self.exclude = re.compile(exclude)
        self.log_file = log_file

    def check(self, test, history, opts=None) -> dict:
        matches = []
        cluster = test.get("cluster") if isinstance(test, dict) else None
        if cluster is not None:
            for name, node in cluster.nodes.items():
                for line in node.etcd_log:
                    if self.pattern.search(line) and \
                            not self.exclude.search(line):
                        matches.append({"node": name, "line": line})
        return {"valid?": True if not matches else False,
                "matches": matches[:32],
                "count": len(matches)}


class ClockPlot(Checker):
    """checker/clock-plot: renders per-node clock offsets over time to
    clock.png (like the reference's plot, not data-only), reconstructed
    from the recorded clock-nemesis ops."""

    def check(self, test, history, opts=None) -> dict:
        h = history if isinstance(history, History) else History(history)
        points = [(op.time, op.f, op.value) for op in h.nemesis_ops()
                  if op.f in ("bump-clock", "strobe-clock", "reset-clock")
                  and op.is_completion]
        result = {"valid?": True,
                  "points": [(t, v) for t, _, v in points][:1000]}
        store_dir = (opts or {}).get("store_dir")
        if store_dir and points:
            try:
                self._plot(points, store_dir)
                result["plots"] = ["clock.png"]
            except Exception as e:  # plotting must never fail a test run
                result["plot-error"] = repr(e)
        return result

    def _plot(self, points, store_dir):
        import os
        import matplotlib
        matplotlib.use("Agg")
        import matplotlib.pyplot as plt
        from collections import defaultdict

        # reconstruct cumulative offset per node from bump/reset events;
        # strobes render as shaded oscillation windows
        series = defaultdict(lambda: [(0.0, 0.0)])
        strobes = []
        for t, f, v in points:
            ts = (t or 0) / 1e9
            if f == "bump-clock" and isinstance(v, dict):
                for node, delta_ms in v.items():
                    prev = series[node][-1][1]
                    series[node].append((ts, prev))
                    series[node].append((ts, prev + delta_ms))
            elif f == "reset-clock":
                for node in list(series) or list(v or []):
                    prev = series[node][-1][1]
                    series[node].append((ts, prev))
                    series[node].append((ts, 0.0))
            elif f == "strobe-clock" and isinstance(v, dict):
                # the op completes AFTER oscillating for duration-ms, so
                # the window it strobed is (completion - duration,
                # completion)
                dur = v.get("duration-ms", 0) / 1e3
                strobes.append((ts - dur, ts, v.get("delta-ms", 0)))
        fig, ax = plt.subplots(figsize=(10, 3))
        for lo, hi, delta in strobes:
            ax.axvspan(lo, hi, alpha=0.2, color="#FFDB9A")
        for node in sorted(series):
            xs = [x for x, _ in series[node]]
            ys = [y for _, y in series[node]]
            ax.plot(xs, ys, label=node, drawstyle="steps-post")
        ax.set_xlabel("time (s)")
        ax.set_ylabel("clock offset (ms)")
        ax.legend(fontsize=6, ncol=3)
        fig.savefig(os.path.join(store_dir, "clock.png"), dpi=100)
        plt.close(fig)


class Noop(Checker):
    def check(self, test, history, opts=None) -> dict:
        return {"valid?": True}
