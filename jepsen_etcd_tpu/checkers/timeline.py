"""Per-process op timeline HTML (jepsen.checker.timeline, used at
register.clj:112, lock.clj:245,259)."""

from __future__ import annotations

import html
import os

from ..core.history import History
from .core import Checker

SECOND = 1_000_000_000

COLORS = {"ok": "#B3F3B5", "info": "#F3EAB3", "fail": "#F3B3B3"}


class TimelineHtml(Checker):
    def check(self, test, history, opts=None) -> dict:
        store_dir = (opts or {}).get("store_dir")
        if not store_dir:
            return {"valid?": True}
        h = history if isinstance(history, History) else History(history)
        rows = []
        for op in h.client_ops():
            if not op.is_invoke:
                continue
            comp = h.completion(op)
            t0 = op["time"] / SECOND
            t1 = comp["time"] / SECOND if comp else None
            typ = comp["type"] if comp else "info"
            val = comp.get("value") if comp else op.get("value")
            rows.append(
                f"<div class='op' style='background:{COLORS.get(typ, '#ddd')}'>"
                f"<b>{op['process']}</b> {html.escape(str(op.f))} "
                f"{html.escape(repr(val))} "
                f"<span class='t'>[{t0:.3f}s → "
                f"{f'{t1:.3f}s' if t1 else '⋯'}] {typ}"
                f"{(' ' + html.escape(repr(comp.get('error')))) if comp is not None and comp.get('error') else ''}"
                f"</span></div>")
        doc = ("<html><head><style>"
               ".op{font:12px monospace;margin:1px;padding:2px}"
               ".t{color:#666}"
               "</style></head><body>" + "\n".join(rows) + "</body></html>")
        path = os.path.join(store_dir, "timeline.html")
        with open(path, "w") as f:
            f.write(doc)
        return {"valid?": True, "file": path}
