"""Per-process op timeline HTML (jepsen.checker.timeline, used at
register.clj:112, lock.clj:245,259).

Real positioned rendering (VERDICT #7): one column per process, each op
an absolutely positioned box spanning invoke→complete on a shared
vertical time axis — so overlapping ops sit side by side and a lock
run's blocked acquires are visibly long. Nemesis activity windows
(the same :perf metadata checkers/perf.py extracts) render as
full-width bands behind the columns; hover any box for the op's full
detail (values, completion, error, latency).
"""

from __future__ import annotations

import html
import math
import os

from ..core.history import History
from .core import Checker
from .perf import nemesis_bands

SECOND = 1_000_000_000

COLORS = {"ok": "#B3F3B5", "info": "#F3EAB3", "fail": "#F3B3B3"}

#: layout constants: vertical px per second picked to land near this
#: total height, a fixed column width, and a left gutter for the axis
TARGET_HEIGHT_PX = 3000
MIN_PX_PER_S = 2.0
MAX_PX_PER_S = 2000.0
COL_W = 130
AXIS_W = 70
HEAD_H = 22
MIN_BOX_PX = 3
#: render cap — a 50k-op history still loads in a browser; the page
#: says how many ops were cut
MAX_OPS = 20_000

_CSS = """
body{font:12px monospace;margin:8px;background:#fafafa}
.meta{color:#444;margin:4px 0 10px}
.legend span{padding:1px 6px;margin-right:6px;border:1px solid #999}
.tl{position:relative;background:#fff;border:1px solid #ccc;
    overflow:hidden}
.colhead{position:absolute;top:0;height:%(head)dpx;width:%(colw)dpx;
    text-align:center;font-weight:bold;background:#eee;
    border-left:1px solid #ddd;z-index:3;overflow:hidden}
.op{position:absolute;width:%(opw)dpx;box-sizing:border-box;
    border:1px solid rgba(0,0,0,.25);overflow:hidden;z-index:2;
    font-size:10px;line-height:11px;padding:0 2px}
.op.open{border-style:dashed;opacity:.8}
.grid{position:absolute;left:0;right:0;height:0;
    border-top:1px solid #eee;z-index:0}
.tick{position:absolute;left:2px;width:%(axis)dpx;color:#999;
    font-size:10px;z-index:1}
.band{position:absolute;left:0;right:0;opacity:.18;z-index:1}
.bandlabel{position:absolute;right:4px;font-size:10px;color:#a40;
    z-index:1}
""" % {"head": HEAD_H, "colw": COL_W, "opw": COL_W - 8, "axis": AXIS_W}


def _tick_step(duration_s: float) -> float:
    """A round gridline step giving ~8-15 ticks."""
    if duration_s <= 0:
        return 1.0
    step = 10.0 ** max(-3, round(math.log10(max(duration_s / 10,
                                               1e-9))))
    while duration_s / step > 15:
        step *= 2
    while duration_s / step < 4 and step > 1e-3:
        step /= 2
    return step


class TimelineHtml(Checker):
    def __init__(self, nemesis_perf=None):
        # nemesis packages contribute {name,color,fs} specs, same shape
        # perf.Perf consumes for its plot bands
        self.nemesis_perf = nemesis_perf or []

    def _band_color(self, f) -> str:
        for spec in self.nemesis_perf:
            if f in spec.get("fs", []):
                return spec.get("color", "#FFDB9A")
        return "#FFDB9A"

    def check(self, test, history, opts=None) -> dict:
        store_dir = (opts or {}).get("store_dir")
        if not store_dir:
            return {"valid?": True}
        h = history if isinstance(history, History) else History(history)
        doc = self.render(test, h)
        path = os.path.join(store_dir, "timeline.html")
        with open(path, "w") as f:
            f.write(doc)
        return {"valid?": True, "file": path}

    def _box_rows(self, h: History) -> list[tuple]:
        """One row per client invoke, in invoke order:
        ``(process, f, value, t0_ns, t1_ns|None, typ|None, error)`` —
        value/typ from the completion when one exists (the completion's
        view of the op is what the reference timeline shows). Columnar
        when the history carries SoA columns: pairing and every field
        come from the typed arrays, no per-op dict access."""
        cols = getattr(h, "columns", None)
        if cols is not None:
            from ..core.history import TYPE_NAMES
            tm = cols.time.tolist()
            tc = cols.type_code.tolist()
            fcl = cols.f_code.tolist()
            ft = cols.f_table
            ex = cols.extras
            rows = []
            for inv, comp in cols.client_pairs():
                f = ft[fcl[inv]]
                p = cols.process_at(inv)
                if comp >= 0:
                    err = (ex.get(comp) or {}).get("error")
                    rows.append((p, f, cols.value_at(comp), tm[inv],
                                 tm[comp], TYPE_NAMES[tc[comp]], err))
                else:
                    rows.append((p, f, cols.value_at(inv), tm[inv],
                                 None, None, None))
            return rows
        rows = []
        # graftlint: ignore[COL002] dict fallback for loaded/legacy histories
        for op in h.client_ops():
            if not op.is_invoke:
                continue
            # graftlint: ignore[COL002] dict fallback for loaded/legacy histories
            comp = h.completion(op)
            if comp is not None:
                rows.append((op["process"], op.f, comp.get("value"),
                             op["time"], comp["time"], comp["type"],
                             comp.get("error")))
            else:
                rows.append((op["process"], op.f, op.get("value"),
                             op["time"], None, None, None))
        return rows

    def render(self, test, h: History) -> str:
        boxes = self._box_rows(h)
        truncated = max(0, len(boxes) - MAX_OPS)
        boxes = boxes[:MAX_OPS]
        bands = nemesis_bands(h)

        cols = getattr(h, "columns", None)
        if cols is not None:
            t_min = (int(cols.time.min()) if len(cols) else 0) / SECOND
            t_max = (int(cols.time.max()) if len(cols) else 0) / SECOND
        else:
            times = [op["time"] for op in h
                     if op.get("time") is not None]
            t_min = (min(times) if times else 0) / SECOND
            t_max = (max(times) if times else 0) / SECOND
        duration = max(t_max - t_min, 1e-9)
        px_per_s = min(MAX_PX_PER_S,
                       max(MIN_PX_PER_S, TARGET_HEIGHT_PX / duration))
        height = int(duration * px_per_s) + HEAD_H + 20

        def y(ts: float) -> int:
            return HEAD_H + int((ts - t_min) * px_per_s)

        processes = sorted({b[0] for b in boxes}, key=str)
        col_x = {p: AXIS_W + i * COL_W for i, p in enumerate(processes)}
        width = AXIS_W + max(1, len(processes)) * COL_W

        parts = []
        # time gridlines + tick labels
        step = _tick_step(duration)
        t = t_min - (t_min % step)
        while t <= t_max + step:
            if t >= t_min:
                parts.append(
                    f"<div class='grid' style='top:{y(t)}px'></div>"
                    f"<div class='tick' style='top:{y(t)}px'>"
                    f"{t:.3g}s</div>")
            t += step
        # nemesis bands behind the columns
        for b in bands:
            top, bot = y(b["start"]), y(b["end"])
            parts.append(
                f"<div class='band' style='top:{top}px;"
                f"height:{max(bot - top, 2)}px;"
                f"background:{self._band_color(b['f'])}'></div>"
                f"<div class='bandlabel' style='top:{top}px'>"
                f"{html.escape(str(b['f']))}</div>")
        # column headers
        for p in processes:
            parts.append(
                f"<div class='colhead' style='left:{col_x[p]}px'>"
                f"{html.escape(str(p))}</div>")
        # op boxes
        for p, f, val, t0n, t1n, typc, err in boxes:
            done = t1n is not None
            t0 = t0n / SECOND
            t1 = t1n / SECOND if done else t_max
            typ = typc if done else "info"
            top = y(t0)
            hgt = max(MIN_BOX_PX, y(t1) - top)
            title = (f"process {p} · {f} "
                     f"{val!r}\n[{t0:.4f}s → "
                     + (f"{t1:.4f}s] {typ} "
                        f"({(t1 - t0) * 1e3:.1f} ms)" if done
                        else "⋯] never completed"))
            if done and err:
                title += f"\nerror: {err!r}"
            label = f"{f} {val!r}"
            parts.append(
                f"<div class='op{'' if done else ' open'}' "
                f"style='left:{col_x[p] + 4}px;"
                f"top:{top}px;height:{hgt}px;"
                f"background:{COLORS.get(typ, '#ddd')}' "
                f"title='{html.escape(title, quote=True)}'>"
                f"{html.escape(label)}</div>")

        name = html.escape(str((test or {}).get("name", "run"))
                           if isinstance(test, dict) else "run")
        legend = "".join(
            f"<span style='background:{c}'>{k}</span>"
            for k, c in COLORS.items())
        note = (f" · <b>{truncated} ops past the {MAX_OPS}-op render "
                f"cap not drawn</b>" if truncated else "")
        return (
            "<!doctype html><html><head><meta charset='utf-8'>"
            f"<title>timeline — {name}</title>"
            f"<style>{_CSS}</style></head><body>"
            f"<h2>timeline — {name}</h2>"
            f"<div class='meta'>{len(boxes)} ops · "
            f"{len(processes)} processes · {duration:.3f}s · "
            f"<span class='legend'>{legend}</span>"
            f"<span style='border:1px dashed #999;padding:1px 6px'>"
            f"open (never completed)</span>{note}</div>"
            f"<div class='tl' style='height:{height}px;"
            f"width:{width}px'>" + "".join(parts) +
            "</div></body></html>")
