"""Linearizability checking: the CPU reference oracle (WGL).

The reference delegates to Knossos (``checker/linearizable {:model ...}``,
register.clj:110-112, lock.clj:244). This module is our CPU
re-implementation of the Wing-Gong/Lowe search — it is the *oracle* the
TPU kernel (ops/wgl.py) is differentially tested against, and the
fallback when a history exceeds kernel capacity.

Semantics (matching Knossos):
- :ok ops must linearize, using the completion's value (reads learn their
  value at completion);
- :info ops (indefinite) may linearize at any point after their invoke, or
  never (the client may or may not have taken effect); their value is the
  invocation's;
- :fail ops definitely did not happen and are excluded.

Search: depth-first over configurations (linearized-mask, model-state)
with a visited-set memo — Lowe's "just-in-time linearization". The WGL
candidate rule: an op may be linearized next only if it was invoked before
the earliest return among unlinearized ops that must linearize (nothing
can be deferred past a completed op's return).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional

from ..core.op import Op
from ..core.history import History
from ..models.base import Model, Inconsistent
from .core import Checker

INF = float("inf")


@dataclass
class Entry:
    """One logical operation for the search."""

    i: int          # dense id (bit position)
    f: str
    value: Any
    invoke: int     # total order position of invocation
    ret: float      # total order position of return (INF for :info)
    required: bool  # must linearize (ok) vs may (info)
    op: Op          # original invoke op (for reporting)


def history_entries(history) -> Optional[list[Entry]]:
    """Extract completed client operations; None means malformed.

    Hot path: called once per key by every engine (native DFS, device
    kernels, Python oracle), so the loop reads each op's type/process
    exactly once through plain dict access rather than the Op
    predicate properties (measured ~2x on the batched key-DP axis)."""
    h = history if isinstance(history, History) else History(history)
    entries: list[Entry] = []
    open_by_process: dict[Any, tuple[int, Op]] = {}
    pos = 0
    append = entries.append
    for op in h:
        proc = op.get("process")
        if not isinstance(proc, int):
            continue
        pos += 1
        t = op.get("type")
        if t == "invoke":
            open_by_process[proc] = (pos, op)
            continue
        got = open_by_process.pop(proc, None)
        if got is None or t == "fail":
            continue  # unmatched, or definitely didn't happen
        inv_pos, inv = got
        if t == "ok":
            append(Entry(i=len(entries), f=inv["f"],
                         value=op.get("value"), invoke=inv_pos, ret=pos,
                         required=True, op=inv))
        elif t == "info":
            append(Entry(i=len(entries), f=inv["f"],
                         value=inv.get("value"), invoke=inv_pos, ret=INF,
                         required=False, op=inv))
        else:  # not a completion (ad-hoc type): leave the op open
            open_by_process[proc] = got
    # ops still open at history end: treat as :info (may or may not happen)
    for inv_pos, inv in open_by_process.values():
        append(Entry(
            i=len(entries), f=inv["f"], value=inv.get("value"),
            invoke=inv_pos, ret=INF, required=False, op=inv))
    return entries


def check_history(model: Model, history, max_configs: int = 5_000_000,
                  use_native: bool = True) -> dict:
    """WGL search. Returns {'valid?': bool|'unknown', ...}.

    Models expressible as (versioned) CAS registers run on the native
    C++ engine (native/wgl_oracle.cpp, ~100x this DFS); this Python
    search is the semantic reference the native engine is
    differentially tested against, and the path for other models."""
    entries = history_entries(history)
    n = len(entries)
    if n == 0:
        return {"valid?": True, "configs": 0, "ops": 0}
    if use_native:
        from ..native import oracle as native_oracle
        out = native_oracle.check_entries(model, entries,
                                          max_configs=max_configs)
        if out is not None:
            return out
    full_required = 0
    for e in entries:
        if e.required:
            full_required |= 1 << e.i
    visited: set[tuple[int, Model]] = set()
    configs = 0
    # stack of (mask, model); DFS
    stack: list[tuple[int, Model]] = [(0, model)]
    best_depth = 0
    best_blocked: Optional[list] = None
    while stack:
        mask, state = stack.pop()
        try:
            if (mask, state) in visited:
                continue
            visited.add((mask, state))
        except TypeError:
            # unhashable model state (e.g. a set-valued register):
            # proceed without memoizing — correct, just slower
            pass
        configs += 1
        if configs > max_configs:
            return {"valid?": "unknown", "error": "search budget exceeded",
                    "configs": configs, "ops": n}
        if mask & full_required == full_required:
            return {"valid?": True, "configs": configs, "ops": n,
                    "final-model": repr(state)}
        # candidate rule
        min_ret = INF
        for e in entries:
            if e.required and not (mask >> e.i) & 1 and e.ret < min_ret:
                min_ret = e.ret
        depth = bin(mask).count("1")
        blocked_here = []
        for e in entries:
            if (mask >> e.i) & 1:
                continue
            if e.invoke >= min_ret:
                continue
            nxt = state.step(e)
            if isinstance(nxt, Inconsistent):
                if e.required:
                    blocked_here.append((e, nxt.msg))
                # info ops may simply never linearize
                continue
            stack.append((mask | (1 << e.i), nxt))
        if depth >= best_depth and blocked_here:
            best_depth = depth
            best_blocked = blocked_here
    info = {"valid?": False, "configs": configs, "ops": n}
    if best_blocked:
        e, msg = best_blocked[0]
        info["op"] = dict(e.op)
        info["error"] = msg
        info["max-linearized"] = best_depth
    return info


class LinearizableChecker(Checker):
    """checker/linearizable: CPU oracle (use TPUlinearizable for scale)."""

    def __init__(self, model_fn, max_configs: int = 5_000_000):
        self.model_fn = model_fn
        self.max_configs = max_configs

    def check(self, test, history, opts=None) -> dict:
        return check_history(self.model_fn(), history,
                             max_configs=self.max_configs)


def linearizable(model_fn) -> LinearizableChecker:
    return LinearizableChecker(model_fn)
