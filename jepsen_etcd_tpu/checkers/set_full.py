"""The set-full checker: full lifecycle analysis of set elements.

Re-implements the jepsen library checker the reference binds at
``set.clj:46`` and ``lock.clj:258`` (``checker/set-full
{:linearizable? true}``). The history contains ``add`` ops (one element
each) and ``read`` ops (whole set). For every attempted element we track
its lifecycle against all reads:

- an element becomes **known** once its add completes :ok, or once any
  :ok read observes it (whichever is earliest);
- reads *invoked after* the known point must observe it; a read that
  misses it is an **absent observation**;
- outcome per element:
    * ``stable``     — known, and every read after the known point saw it;
    * ``lost``       — known, and the last read(s) no longer see it
                       (absent with no later present observation);
    * ``stale``      — known, temporarily absent, but visible again later
                       (legal only for non-linearizable sets);
    * ``never-read`` — possibly present (add :ok or :info) but no read
                       after it ever ran / observed it — proves nothing;
    * ``unknown``    — add :info and never observed (may simply not have
                       happened).

``valid?`` is false when any element is lost, or (with
``linearizable=True``) when any stale window exists; it is ``"unknown"``
when nothing was ever read (no information).

Timing: stale windows are measured in virtual nanoseconds between the
known time and the first subsequent present read, matching the spirit of
the reference checker's ``:worst-stale`` report.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional

import numpy as np

from ..core.history import History, TYPE_NAMES
from .core import Checker


@dataclass
class _Element:
    value: Any
    add_invoke: Optional[int] = None      # history index
    add_type: Optional[str] = None        # ok | fail | info
    known_index: Optional[int] = None     # index where presence is proven
    known_time: Optional[int] = None
    present_after_absent: bool = False
    stale_until: Optional[int] = None     # time first re-observed


class _NonColumnar(Exception):
    """Values outside the int fast path (floats, ad-hoc objects):
    lifecycle analysis needs Python == semantics, use the set sweep."""


def _increment_of(prev: list, vals: list) -> Optional[list]:
    """The elements inserted into ``prev`` to produce ``vals``, or None.

    A growing sorted set changes by insertion, so consecutive read
    views differ by a handful of elements; finding them costs
    O(d log n) probes (first-mismatch binary search, valid for
    strictly increasing lists) plus one O(n) slice-equality
    reconstruction check that keeps the answer exact for arbitrary
    lists — a wrong candidate from unsorted input just fails the
    check and the caller falls back to a full conversion."""
    lp, lv = len(prev), len(vals)
    d = lv - lp
    if d <= 0 or d > 64:
        return None
    ins = []
    cuts = []                # insert positions in vals
    po = vo = 0
    while len(ins) < d:
        m = lp - po          # remaining common span
        lo, hi = 0, m
        while lo < hi:       # first i with vals[vo+i] != prev[po+i]
            mid = (lo + hi) // 2
            if vals[vo + mid] == prev[po + mid]:
                lo = mid + 1
            else:
                hi = mid
        ins.append(vals[vo + lo])
        cuts.append(vo + lo)
        vo += lo + 1
        po += lo
    # exact reconstruction: vals minus the cut positions == prev
    a = b = 0
    for c in cuts:
        if vals[a:c] != prev[b:b + (c - a)]:
            return None
        b += c - a
        a = c + 1
    if vals[a:] != prev[b:]:
        return None
    return ins


def analyze(history, _scan=None) -> dict:
    """Element-lifecycle analysis; see module docstring for outcomes.

    Int-valued workloads (every real set workload) run the columnar
    numpy path: one element x read presence matrix, known points via
    first-true, lost/stale via suffix comparisons — the per-read set
    arithmetic of the sweep becomes a handful of matrix reductions.
    Anything else falls back to the reference sweep; both produce
    identical results (differentially tested in tests/test_set.py).

    ``_scan`` is a precomputed event-scan tuple (a finished
    :class:`ColumnScan` fed incrementally by the streaming runner); it
    replaces the scan pass only — the vectorized tail still runs here,
    so the result is bit-identical to the post-hoc path by
    construction."""
    h = history if isinstance(history, History) else History(history)
    try:
        return _analyze_columnar(h, _scan=_scan)
    except _NonColumnar:
        return _analyze_reference(h)


def _analyze_reference(h: History) -> dict:
    """Single forward sweep with set arithmetic.

    Every read covers every element already known when it was invoked,
    so per-read state is maintained with whole-set operations (C speed)
    instead of a per-element scan of all reads — the naive formulation
    is O(elements x reads), quadratic on set-workload histories.
    """
    elements: dict[Any, _Element] = {}
    # reads: (invoke_index, invoke_time, ok_index, value-as-set, dup-list)
    reads: list[tuple[int, int, int, frozenset, list]] = []
    duplicated: dict[Any, int] = {}

    for op in h:
        if not op.is_client_op:
            continue
        if op.f == "add":
            x = op.value
            el = elements.get(x)
            if el is None:
                el = elements[x] = _Element(value=x)
            if op.is_invoke:
                el.add_invoke = op.index
            else:
                el.add_type = op["type"]
        elif op.f == "read" and op.is_ok and op.value is not None:
            # graftlint: ignore[COL002] reference dict sweep: the guarded fallback behind _NonColumnar
            inv = h.invocation(op)
            vals = list(op.value)
            vset = frozenset(vals)
            if len(vals) != len(vset):
                seen: set = set()
                for v in vals:
                    if v in seen:
                        duplicated[v] = duplicated.get(v, 0) + 1
                    seen.add(v)
            reads.append((inv.index if inv is not None else op.index,
                          (inv or op).time or 0, op.index, vset, vals))

    # pass 1: establish known points (add :ok completion or first read
    # observation, whichever proves presence earliest in history order)
    for op in h:
        if op.f == "add" and op.is_ok:
            el = elements[op.value]
            if el.known_index is None:
                el.known_index = op.index
                el.known_time = op.time or 0
    observed: set = set()
    for ri, rt, ok_i, vset, _vals in sorted(reads, key=lambda r: r[2]):
        for x in vset - observed:   # first observation = min ok_i
            el = elements.get(x)
            if el is None:
                el = elements[x] = _Element(value=x)
            if el.known_index is None or ok_i < el.known_index:
                el.known_index = ok_i
                el.known_time = rt
        observed |= vset

    # pass 2: sweep reads in invoke order; each read covers exactly the
    # elements known before its invoke
    reads.sort()
    by_known = sorted((el for el in elements.values()
                       if el.known_index is not None),
                      key=lambda e: e.known_index)
    ptr = 0
    known_now: set = set()
    absent_last: set = set()       # missing in their latest covering read
    absent_count: dict[Any, int] = {}
    for ri, rt, ok_i, vset, _vals in reads:
        while ptr < len(by_known) and by_known[ptr].known_index < ri:
            known_now.add(by_known[ptr].value)
            ptr += 1
        if not known_now:
            continue
        for x in absent_last & vset:      # reappeared: stale transition
            el = elements[x]
            if not el.present_after_absent:
                el.present_after_absent = True
                el.stale_until = rt
        miss = known_now - vset
        for x in miss:
            absent_count[x] = absent_count.get(x, 0) + 1
        absent_last = miss
    # known_now only grows, so after the sweep it is exactly the set of
    # elements covered by at least one read
    covered = known_now

    stable, lost, never_read, stale, unknown = [], [], [], [], []
    attempts = 0
    for x, el in sorted(elements.items(), key=lambda kv: repr(kv[0])):
        if el.add_invoke is not None:
            attempts += 1
        if el.known_index is None:
            if el.add_type == "ok":
                never_read.append(x)     # confirmed added, never observed
            elif el.add_type in ("info", None):
                unknown.append(x)        # may never have happened
            # fail: definitely absent; ignore
            continue
        if x in absent_last:
            lost.append(x)               # still missing at the final read
        elif absent_count.get(x):
            stale.append(x)
        elif x not in covered:
            never_read.append(x)         # known but no read ever covered it
        else:
            stable.append(x)

    worst_stale = []
    for x in stale:
        el = elements[x]
        dur = (el.stale_until or 0) - (el.known_time or 0)
        worst_stale.append({"element": x, "stale-ns": dur,
                            "absent-reads": absent_count.get(x, 0)})
    worst_stale.sort(key=lambda d: -d["stale-ns"])

    return {
        "attempt-count": attempts,
        "stable-count": len(stable),
        "lost": lost, "lost-count": len(lost),
        "stale": stale, "stale-count": len(stale),
        "worst-stale": worst_stale[:8],
        "never-read": never_read[:64], "never-read-count": len(never_read),
        "unknown-count": len(unknown),
        "duplicated": dict(sorted(duplicated.items(),
                                  key=lambda kv: repr(kv[0]))[:16]),
        "duplicated-count": sum(duplicated.values()),
        "read-count": len(reads),
    }


def _scan_ops(h: History):
    """Event scan over dict ops: adds + read views with chain/increment
    compression (see _analyze_columnar's docstring). Returns
    (adds, r_ri, r_rt, r_ok, views, payloads, anchor, mono)."""
    adds: dict = {}    # x -> [add_invoke, add_type, first_ok_idx, ok_time]
    r_ri: list = []          # read invoke index
    r_rt: list = []          # read invoke time
    r_ok: list = []          # read ok index
    views: list = []         # raw per-read value lists
    payloads: list = []      # full list (anchor read) or new-element tail
    anchor: list = []        # True: payload is the read's full value set
    prev: list = []
    mono = True              # r_ok ascending in scan order
    last_ok = None
    for op in h:
        f = op.get("f")
        if f == "add":
            if not isinstance(op.get("process"), int):
                continue
            x = op.get("value")
            if type(x) is not int:
                raise _NonColumnar
            rec = adds.get(x)
            if rec is None:
                rec = adds[x] = [None, None, None, 0]
            t = op.get("type")
            if t == "invoke":
                rec[0] = op["index"]
            else:
                rec[1] = t
                if t == "ok" and rec[2] is None:
                    rec[2] = op["index"]   # first :ok completion
                    rec[3] = op.get("time") or 0
        elif f == "read" and op.get("type") == "ok":
            v = op.get("value")
            if v is None or not isinstance(op.get("process"), int):
                continue
            vals = v if type(v) is list else list(v)
            # chain detection: a growing set means consecutive reads
            # share their prefix, and list == compares shared int
            # objects by identity at C speed — only the tail of new
            # elements ever needs numpy conversion
            lp = len(prev)
            if views and len(vals) >= lp and vals[:lp] == prev:
                payloads.append(vals[lp:])
                anchor.append(False)
            else:
                inc = _increment_of(prev, vals) if views else None
                if inc is not None:
                    payloads.append(inc)
                    anchor.append(False)
                else:
                    payloads.append(vals)
                    anchor.append(True)
            prev = vals
            views.append(vals)
            # graftlint: ignore[COL002] reference dict sweep: the guarded fallback behind _NonColumnar
            inv = h.invocation(op)
            oki = op["index"]
            if last_ok is not None and oki < last_ok:
                mono = False
            last_ok = oki
            r_ri.append(inv["index"] if inv is not None else oki)
            r_rt.append((inv if inv is not None else op).get("time") or 0)
            r_ok.append(oki)
    return adds, r_ri, r_rt, r_ok, views, payloads, anchor, mono


class ColumnScan:
    """Resumable form of the columnar event scan: ``feed`` OpColumns
    chunks as generation proceeds (the streaming set path — the
    incremental half of the running-max presence pipeline); ``finish``
    returns the same (adds, r_ri, r_rt, r_ok, views, payloads, anchor,
    mono) tuple one pass over the complete columns produces. Open
    invocations carry their (index, time) across chunk boundaries, and
    chain detection's ``prev`` view spans chunks unchanged, so chunked
    feeding is bit-identical to the one-shot scan (``_scan_columns``
    is now just the one-shot wrapper). ``feed`` raises _NonColumnar
    exactly where the one-shot scan would; the streaming driver treats
    that as stream invalidation while post-hoc callers fall back to
    the reference sweep as before."""

    __slots__ = ("adds", "r_ri", "r_rt", "r_ok", "views", "payloads",
                 "anchor", "prev", "mono", "last_ok", "open_by",
                 "n_rows")

    def __init__(self):
        self.adds: dict = {}
        self.r_ri: list = []
        self.r_rt: list = []
        self.r_ok: list = []
        self.views: list = []
        self.payloads: list = []
        self.anchor: list = []
        self.prev: list = []
        self.mono = True
        self.last_ok = None
        self.open_by: dict = {}   # process code -> (invoke idx, time)
        self.n_rows = 0           # total column rows consumed

    def feed(self, cols) -> None:
        self.n_rows += len(cols)
        adds = self.adds
        r_ri, r_rt, r_ok = self.r_ri, self.r_rt, self.r_ok
        views, payloads, anchor = self.views, self.payloads, self.anchor
        prev = self.prev
        mono = self.mono
        last_ok = self.last_ok
        open_by = self.open_by
        tc = cols.type_code.tolist()
        pr = cols.proc.tolist()
        fcl = cols.f_code.tolist()
        ft = cols.f_table
        idx = cols.index.tolist()
        tm = cols.time.tolist()
        vals_col = cols.values
        pt = cols.proc_table
        try:
            for i, t in enumerate(tc):
                p = pr[i]
                if t == 0:
                    open_by[p] = (idx[i], tm[i])
                    inv = None
                else:
                    inv = open_by.pop(p, None)
                f = ft[fcl[i]]
                if f == "add":
                    if p < 0 and not isinstance(pt[-1 - p], int):
                        continue
                    x = vals_col[i]
                    if type(x) is not int:
                        raise _NonColumnar
                    rec = adds.get(x)
                    if rec is None:
                        rec = adds[x] = [None, None, None, 0]
                    if t == 0:
                        rec[0] = idx[i]
                    else:
                        rec[1] = TYPE_NAMES[t]
                        if t == 1 and rec[2] is None:
                            rec[2] = idx[i]    # first :ok completion
                            rec[3] = tm[i] or 0
                elif f == "read" and t == 1:
                    v = vals_col[i]
                    if v is None or (p < 0
                                     and not isinstance(pt[-1 - p], int)):
                        continue
                    vals = v if type(v) is list else list(v)
                    lp = len(prev)
                    if views and len(vals) >= lp and vals[:lp] == prev:
                        payloads.append(vals[lp:])
                        anchor.append(False)
                    else:
                        inc = _increment_of(prev, vals) if views else None
                        if inc is not None:
                            payloads.append(inc)
                            anchor.append(False)
                        else:
                            payloads.append(vals)
                            anchor.append(True)
                    prev = vals
                    views.append(vals)
                    oki = idx[i]
                    if last_ok is not None and oki < last_ok:
                        mono = False
                    last_ok = oki
                    r_ri.append(inv[0] if inv is not None else oki)
                    r_rt.append((inv[1] if inv is not None
                                 else tm[i]) or 0)
                    r_ok.append(oki)
        finally:
            self.prev = prev
            self.mono = mono
            self.last_ok = last_ok

    def finish(self):
        return (self.adds, self.r_ri, self.r_rt, self.r_ok, self.views,
                self.payloads, self.anchor, self.mono)


def _scan_columns(cols):
    """_scan_ops over SoA columns (core/history.py OpColumns): the same
    event scan fed from typed arrays and intern tables — no per-op dict
    access, and read invocations pair by an inline per-process walk
    instead of History.pairs (which would materialize dict ops on a
    column-only history). One-shot wrapper of :class:`ColumnScan`."""
    s = ColumnScan()
    s.feed(cols)
    return s.finish()


def _analyze_columnar(h: History, _scan=None) -> dict:
    """Vectorized analyze(): element x read presence matrix in numpy.

    The host floor for set histories is the read payload: ~24k ops
    carry ~15M observed values, and converting (or even type-checking)
    every one costs more than the whole analysis budget. The pipeline
    dodges the floor structurally: a growing set means consecutive
    views share their prefix (compared by C-level list ==, which
    short-circuits and compares shared int objects by identity) or
    differ by a few insertions (_increment_of), so only arrival events
    — new elements — are ever converted; runs of identical views
    collapse into one presence row. Known points come from a reversed
    first-arrival scatter, coverage from one broadcast compare of
    known indices against invoke indices, and presence from a single
    running-max fill over the row axis.

    Exactness contract with the sweep: element values must be plain
    ints (floats/Decimals/ad-hoc objects raise _NonColumnar and take
    the sweep; bools alias their int values exactly as Python == does
    in the sweep's set arithmetic). Histories the fast algebra cannot
    express exactly — duplicate observations, reads that miss covered
    elements, out-of-order ok indices — retry in full mode with one
    row per read, which is bit-identical to the sweep by the
    differential fuzz in tests/test_set.py."""
    if _scan is not None:
        scan = _scan
    else:
        cols = getattr(h, "columns", None)
        if cols is not None:
            scan = _scan_columns(cols)
        else:
            scan = _scan_ops(h)
    adds, r_ri, r_rt, r_ok, views, payloads, anchor, mono = scan
    nR = len(r_ok)

    def _to_i64(vals: list) -> np.ndarray:
        # sum() walks the list at C speed and its result type exposes
        # any float/Decimal/np-scalar contamination that np.asarray
        # with a fixed dtype would silently truncate; non-numerics
        # raise TypeError. (Bools alias their int values exactly as
        # Python == does in the sweep's set arithmetic.)
        if vals:
            try:
                if type(sum(vals)) not in (int, bool):
                    raise _NonColumnar
            except TypeError:
                raise _NonColumnar
        try:
            return np.asarray(vals, dtype=np.int64)
        except (OverflowError, ValueError, TypeError):
            raise _NonColumnar   # ints beyond int64 etc.: sweep handles

    try:
        add_arr = np.fromiter(adds.keys(), dtype=np.int64, count=len(adds))
    except OverflowError:
        raise _NonColumnar
    BIG = np.int64(2) ** 62
    r_ok_a = np.array(r_ok, dtype=np.int64)
    r_ri_a = np.array(r_ri, dtype=np.int64)
    r_rt_a = np.array(r_rt, dtype=np.int64)

    # ---- event pipeline -------------------------------------------------
    # Rows are distinct presence states, not reads: in chain mode a run
    # of consecutive reads with identical views (empty tails) shares one
    # row — the store only changes when an add commits, so reads
    # outnumber distinct views. Coverage per row uses the run's widest
    # invoke (miss detection is monotone in the invoke index), which is
    # exact for the miss/no-miss verdict; any actual miss — and any
    # duplicate, whose accounting is per read — retries in full mode
    # with one row per read. Out-of-order ok indices skip chain mode.
    use_chain = mono
    duplicated: dict = {}
    lens_read = np.fromiter(map(len, views), dtype=np.int64, count=nR)
    while True:
        if use_chain:
            plens_pay = np.fromiter(map(len, payloads), dtype=np.int64,
                                    count=nR)
            # graftlint: ignore[JAX002] host list -> array; retry loop runs at most twice (chain then full)
            anchor_np = np.asarray(anchor, dtype=bool)
            hf = anchor_np | (plens_pay > 0)     # run heads
            if nR:
                hf[0] = True
            heads = np.flatnonzero(hf)
            nrows = len(heads)
            row_of_read = (np.cumsum(hf) - 1) if nR else heads
            parrs = [_to_i64(payloads[r]) for r in heads.tolist()]
            anchor_rows = anchor_np[heads]
            row_ok = r_ok_a[heads]
            row_rt = r_rt_a[heads]
            row_ri = np.maximum.reduceat(r_ri_a, heads) if nrows \
                else r_ri_a
        else:
            nrows = nR
            row_of_read = np.arange(nR, dtype=np.int64)
            parrs = [_to_i64(vals) for vals in views]
            anchor_rows = np.ones(nR, dtype=bool)
            row_ok = r_ok_a
            row_rt = r_rt_a
            row_ri = r_ri_a
        plens = np.fromiter(map(len, parrs), dtype=np.int64, count=nrows)
        total = int(plens.sum()) if nrows else 0
        flat = np.concatenate(parrs) if total else np.zeros(
            0, dtype=np.int64)
        rid = np.repeat(np.arange(nrows, dtype=np.int64), plens)

        # element universe: everything added + everything ever
        # observed (chain prefixes are == earlier events, so events
        # alone span it). Small non-negative domains — every real
        # workload: elements are a dense counter — get an O(domain)
        # lookup table; anything else one global sort + searchsorted.
        if total or len(add_arr):
            allv = np.concatenate([flat, add_arr])
            lo = int(allv.min())
            hi = int(allv.max())
            if 0 <= lo and hi < max(4 * allv.size, 1 << 16):
                mask = np.zeros(hi + 1, dtype=bool)
                mask[flat] = True
                mask[add_arr] = True
                uniq = np.flatnonzero(mask).astype(np.int64)
                lut = np.zeros(hi + 1, dtype=np.int64)
                lut[uniq] = np.arange(len(uniq), dtype=np.int64)
                eid = lut[flat]
                add_e = lut[add_arr]
            else:
                uniq = np.unique(allv)
                eid = np.searchsorted(uniq, flat)
                add_e = np.searchsorted(uniq, add_arr)
        else:
            uniq = np.zeros(0, dtype=np.int64)
            eid = np.zeros(0, dtype=np.int64)
            add_e = np.zeros(0, dtype=np.int64)
        E = len(uniq)

        # presence matrix. Chain rows forward-fill from the previous
        # row (anchors reset presence to their own set): present at
        # row r = last arrival row >= r's segment start, one running
        # max over the whole matrix instead of a per-segment loop.
        if nrows and E and not anchor_rows.all():
            A = np.full((nrows, E), -1, dtype=np.int32)
            if total:
                A[rid, eid] = rid
            np.maximum.accumulate(A, axis=0, out=A)
            seg0 = np.where(anchor_rows,
                            np.arange(nrows, dtype=np.int32),
                            np.int32(-1))
            np.maximum.accumulate(seg0, out=seg0)
            P = A >= seg0[:, None]
        else:
            P = np.zeros((nrows, E), dtype=bool)
            if total:
                P[rid, eid] = True

        # duplicate observations: a read with more values than its row
        # has distinct elements repeats one
        rowsum = P.sum(axis=1)
        dup_reads = np.flatnonzero(lens_read != rowsum[row_of_read])
        if dup_reads.size and use_chain:
            use_chain = False    # dup accounting is per read
            continue
        if dup_reads.size:
            starts = np.zeros(nR + 1, dtype=np.int64)
            np.cumsum(plens, out=starts[1:])
            dsum = np.zeros(E, dtype=np.int64)
            for r in dup_reads.tolist():
                u, c = np.unique(eid[starts[r]:starts[r + 1]],
                                 return_counts=True)
                dupm = c > 1
                dsum[u[dupm]] += c[dupm] - 1
            duplicated = {int(uniq[e]): int(dsum[e])
                          for e in np.flatnonzero(dsum)}

        # known points: first :ok add completion vs first observation
        # (min). First observation = the element's first arrival event
        # in :ok order; with ascending rows a reversed scatter keeps
        # the earliest write per element — no [rows, E] argmax pass.
        known_idx = np.full(E, BIG, dtype=np.int64)
        known_time = np.zeros(E, dtype=np.int64)
        if total:
            firstr = np.full(E, -1, dtype=np.int64)
            if mono:
                firstr[eid[::-1]] = rid[::-1]
            else:
                rnk = np.empty(nrows, dtype=np.int64)
                rnk[np.argsort(row_ok, kind="stable")] = np.arange(
                    nrows, dtype=np.int64)
                order = np.argsort(rnk[rid], kind="stable")
                firstr[eid[order][::-1]] = rid[order][::-1]
            seen = firstr >= 0
            known_idx[seen] = row_ok[firstr[seen]]
            known_time[seen] = row_rt[firstr[seen]]
        if adds:
            big = int(BIG)
            ok_i = np.fromiter((big if rec[2] is None else rec[2]
                                for rec in adds.values()),
                               dtype=np.int64, count=len(adds))
            ok_t = np.fromiter((rec[3] for rec in adds.values()),
                               dtype=np.int64, count=len(adds))
            has_ok = ok_i < BIG
            e_ok = add_e[has_ok]
            better = ok_i[has_ok] < known_idx[e_ok]
            known_idx[e_ok[better]] = ok_i[has_ok][better]
            known_time[e_ok[better]] = ok_t[has_ok][better]

        # coverage: miss = covered (known before invoke) but not present
        if use_chain:
            # row-wise miss detection only — exact because a collapsed
            # run's widest invoke dominates; per-read absent counts are
            # all zero whenever no row misses
            if nrows:
                K = known_idx[None, :] < row_ri[:, None]
                if (K & ~P).any():
                    use_chain = False
                    continue     # real misses: redo with per-read rows
            absent_count = np.zeros(E, dtype=np.int64)
            absent_last = np.zeros(E, dtype=bool)
            covered = (known_idx < int(r_ri_a.max())) if nR \
                else np.zeros(E, dtype=bool)
            stale_until = np.zeros(E, dtype=np.int64)
        elif nR:
            if np.any(np.diff(r_ri_a) < 0):
                order_inv = np.argsort(r_ri_a, kind="stable")
                Pi = P[order_inv]
                ri_s = r_ri_a[order_inv]
                rt_s = r_rt_a[order_inv]
            else:
                Pi, ri_s, rt_s = P, r_ri_a, r_rt_a
            K = known_idx[None, :] < ri_s[:, None]      # [nR, E]
            miss = K & ~Pi
            absent_count = miss.sum(axis=0)
            absent_last = miss[-1]
            covered = K[-1]
            # stale transition: absent in the previous covering read,
            # back in this one; rows before anything is known have no
            # coverage, so their all-False miss rows make the shifted
            # AND exact. Only columns with absences can transition.
            stale_until = np.zeros(E, dtype=np.int64)
            if nR > 1:
                cols = np.flatnonzero(absent_count)
                if cols.size:
                    trans = miss[:-1][:, cols] & Pi[1:][:, cols]
                    ht = trans.any(axis=0)
                    ft = np.argmax(trans, axis=0) + 1
                    stale_until[cols[ht]] = rt_s[ft[ht]]
        else:
            absent_count = np.zeros(E, dtype=np.int64)
            absent_last = np.zeros(E, dtype=bool)
            covered = np.zeros(E, dtype=bool)
            stale_until = np.zeros(E, dtype=np.int64)
        break

    # classification, elements in repr order like the sweep's report
    uvals = uniq.tolist()
    ki_l = known_idx.tolist()
    kt_l = known_time.tolist()
    ac_l = absent_count.tolist()
    al_l = absent_last.tolist()
    cov_l = covered.tolist()
    su_l = stale_until.tolist()
    big = int(BIG)
    order_repr = sorted(range(E), key=lambda e: repr(uvals[e]))
    stable, lost, never_read, stale, unknown = [], [], [], [], []
    stale_rows = []
    attempts = 0
    for e in order_repr:
        x = uvals[e]
        rec = adds.get(x)
        if rec is not None and rec[0] is not None:
            attempts += 1
        if ki_l[e] == big:
            at = rec[1] if rec is not None else None
            if at == "ok":
                never_read.append(x)     # confirmed added, never observed
            elif at in ("info", None):
                unknown.append(x)        # may never have happened
            # fail: definitely absent; ignore
            continue
        if al_l[e]:
            lost.append(x)               # still missing at the final read
        elif ac_l[e]:
            stale.append(x)
            stale_rows.append(
                {"element": x,
                 "stale-ns": su_l[e] - kt_l[e],
                 "absent-reads": ac_l[e]})
        elif not cov_l[e]:
            never_read.append(x)         # known but no read covered it
        else:
            stable.append(x)
    stale_rows.sort(key=lambda d: -d["stale-ns"])

    return {
        "attempt-count": attempts,
        "stable-count": len(stable),
        "lost": lost, "lost-count": len(lost),
        "stale": stale, "stale-count": len(stale),
        "worst-stale": stale_rows[:8],
        "never-read": never_read[:64], "never-read-count": len(never_read),
        "unknown-count": len(unknown),
        "duplicated": dict(sorted(duplicated.items(),
                                  key=lambda kv: repr(kv[0]))[:16]),
        "duplicated-count": sum(duplicated.values()),
        "read-count": nR,
    }


class SetFull(Checker):
    """checker/set-full analog; linearizable=True makes staleness illegal
    (set.clj:46 passes {:linearizable? true})."""

    def __init__(self, linearizable: bool = False):
        self.linearizable = linearizable

    def check(self, test, history, opts=None) -> dict:
        # streaming reuse: the runner installs a finished incremental
        # event scan on the test when it consumed the WHOLE history the
        # checker is now handed (row-count guard re-checked here); the
        # vectorized tail still runs below, so verdicts stay
        # bit-identical to the post-hoc path by construction
        from .core import stream_hint
        res = analyze(history, _scan=stream_hint(test, history,
                                                 "set_scan"))
        if res["read-count"] == 0:
            valid: Any = "unknown"
        elif res["lost-count"] or res["duplicated-count"] or (
                self.linearizable and res["stale-count"]):
            valid = False
        elif res["stable-count"] == 0 and res["attempt-count"] > 0:
            valid = "unknown"   # nothing confirmed either way
        else:
            valid = True
        return {"valid?": valid, "linearizable?": self.linearizable, **res}


def set_full(linearizable: bool = False) -> SetFull:
    return SetFull(linearizable=linearizable)
