"""The set-full checker: full lifecycle analysis of set elements.

Re-implements the jepsen library checker the reference binds at
``set.clj:46`` and ``lock.clj:258`` (``checker/set-full
{:linearizable? true}``). The history contains ``add`` ops (one element
each) and ``read`` ops (whole set). For every attempted element we track
its lifecycle against all reads:

- an element becomes **known** once its add completes :ok, or once any
  :ok read observes it (whichever is earliest);
- reads *invoked after* the known point must observe it; a read that
  misses it is an **absent observation**;
- outcome per element:
    * ``stable``     — known, and every read after the known point saw it;
    * ``lost``       — known, and the last read(s) no longer see it
                       (absent with no later present observation);
    * ``stale``      — known, temporarily absent, but visible again later
                       (legal only for non-linearizable sets);
    * ``never-read`` — possibly present (add :ok or :info) but no read
                       after it ever ran / observed it — proves nothing;
    * ``unknown``    — add :info and never observed (may simply not have
                       happened).

``valid?`` is false when any element is lost, or (with
``linearizable=True``) when any stale window exists; it is ``"unknown"``
when nothing was ever read (no information).

Timing: stale windows are measured in virtual nanoseconds between the
known time and the first subsequent present read, matching the spirit of
the reference checker's ``:worst-stale`` report.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional

from ..core.history import History
from .core import Checker


@dataclass
class _Element:
    value: Any
    add_invoke: Optional[int] = None      # history index
    add_type: Optional[str] = None        # ok | fail | info
    known_index: Optional[int] = None     # index where presence is proven
    known_time: Optional[int] = None
    present_after_absent: bool = False
    stale_until: Optional[int] = None     # time first re-observed


def analyze(history) -> dict:
    """Single forward sweep with set arithmetic.

    Every read covers every element already known when it was invoked,
    so per-read state is maintained with whole-set operations (C speed)
    instead of a per-element scan of all reads — the naive formulation
    is O(elements x reads), quadratic on set-workload histories.
    """
    h = history if isinstance(history, History) else History(history)
    elements: dict[Any, _Element] = {}
    # reads: (invoke_index, invoke_time, ok_index, value-as-set, dup-list)
    reads: list[tuple[int, int, int, frozenset, list]] = []
    duplicated: dict[Any, int] = {}

    for op in h:
        if not op.is_client_op:
            continue
        if op.f == "add":
            x = op.value
            el = elements.get(x)
            if el is None:
                el = elements[x] = _Element(value=x)
            if op.is_invoke:
                el.add_invoke = op.index
            else:
                el.add_type = op["type"]
        elif op.f == "read" and op.is_ok and op.value is not None:
            inv = h.invocation(op)
            vals = list(op.value)
            vset = frozenset(vals)
            if len(vals) != len(vset):
                seen: set = set()
                for v in vals:
                    if v in seen:
                        duplicated[v] = duplicated.get(v, 0) + 1
                    seen.add(v)
            reads.append((inv.index if inv is not None else op.index,
                          (inv or op).time or 0, op.index, vset, vals))

    # pass 1: establish known points (add :ok completion or first read
    # observation, whichever proves presence earliest in history order)
    for op in h:
        if op.f == "add" and op.is_ok:
            el = elements[op.value]
            if el.known_index is None:
                el.known_index = op.index
                el.known_time = op.time or 0
    observed: set = set()
    for ri, rt, ok_i, vset, _vals in sorted(reads, key=lambda r: r[2]):
        for x in vset - observed:   # first observation = min ok_i
            el = elements.get(x)
            if el is None:
                el = elements[x] = _Element(value=x)
            if el.known_index is None or ok_i < el.known_index:
                el.known_index = ok_i
                el.known_time = rt
        observed |= vset

    # pass 2: sweep reads in invoke order; each read covers exactly the
    # elements known before its invoke
    reads.sort()
    by_known = sorted((el for el in elements.values()
                       if el.known_index is not None),
                      key=lambda e: e.known_index)
    ptr = 0
    known_now: set = set()
    absent_last: set = set()       # missing in their latest covering read
    absent_count: dict[Any, int] = {}
    for ri, rt, ok_i, vset, _vals in reads:
        while ptr < len(by_known) and by_known[ptr].known_index < ri:
            known_now.add(by_known[ptr].value)
            ptr += 1
        if not known_now:
            continue
        for x in absent_last & vset:      # reappeared: stale transition
            el = elements[x]
            if not el.present_after_absent:
                el.present_after_absent = True
                el.stale_until = rt
        miss = known_now - vset
        for x in miss:
            absent_count[x] = absent_count.get(x, 0) + 1
        absent_last = miss
    # known_now only grows, so after the sweep it is exactly the set of
    # elements covered by at least one read
    covered = known_now

    stable, lost, never_read, stale, unknown = [], [], [], [], []
    attempts = 0
    for x, el in sorted(elements.items(), key=lambda kv: repr(kv[0])):
        if el.add_invoke is not None:
            attempts += 1
        if el.known_index is None:
            if el.add_type == "ok":
                never_read.append(x)     # confirmed added, never observed
            elif el.add_type in ("info", None):
                unknown.append(x)        # may never have happened
            # fail: definitely absent; ignore
            continue
        if x in absent_last:
            lost.append(x)               # still missing at the final read
        elif absent_count.get(x):
            stale.append(x)
        elif x not in covered:
            never_read.append(x)         # known but no read ever covered it
        else:
            stable.append(x)

    worst_stale = []
    for x in stale:
        el = elements[x]
        dur = (el.stale_until or 0) - (el.known_time or 0)
        worst_stale.append({"element": x, "stale-ns": dur,
                            "absent-reads": absent_count.get(x, 0)})
    worst_stale.sort(key=lambda d: -d["stale-ns"])

    return {
        "attempt-count": attempts,
        "stable-count": len(stable),
        "lost": lost, "lost-count": len(lost),
        "stale": stale, "stale-count": len(stale),
        "worst-stale": worst_stale[:8],
        "never-read": never_read[:64], "never-read-count": len(never_read),
        "unknown-count": len(unknown),
        "duplicated": dict(sorted(duplicated.items(),
                                  key=lambda kv: repr(kv[0]))[:16]),
        "duplicated-count": sum(duplicated.values()),
        "read-count": len(reads),
    }


class SetFull(Checker):
    """checker/set-full analog; linearizable=True makes staleness illegal
    (set.clj:46 passes {:linearizable? true})."""

    def __init__(self, linearizable: bool = False):
        self.linearizable = linearizable

    def check(self, test, history, opts=None) -> dict:
        res = analyze(history)
        if res["read-count"] == 0:
            valid: Any = "unknown"
        elif res["lost-count"] or res["duplicated-count"] or (
                self.linearizable and res["stale-count"]):
            valid = False
        elif res["stable-count"] == 0 and res["attempt-count"] > 0:
            valid = "unknown"   # nothing confirmed either way
        else:
            valid = True
        return {"valid?": valid, "linearizable?": self.linearizable, **res}


def set_full(linearizable: bool = False) -> SetFull:
    return SetFull(linearizable=linearizable)
