"""Latency/throughput analysis + plots (checker/perf, etcd.clj:130).

Produces latency quantiles and rate series per op class, renders
latency-raw / rate PNGs into the store dir (when opts supply one), with
nemesis activity bands from the nemesis package's :perf metadata
(nemesis.clj:65-70,134-143,195-198)."""

from __future__ import annotations

import json
import os
from collections import defaultdict
from typing import Optional

from ..core.history import History, TYPE_NAMES
from ..core.op import Op
from .core import Checker

SECOND = 1_000_000_000


def latency_points(h: History) -> dict[str, list[tuple[float, float, str]]]:
    """f -> [(invoke_time_s, latency_ms, completion_type)].

    Recorded histories carry SoA columns (core/history.py OpColumns):
    invoke/completion pairing and the per-point fields come straight
    from the typed arrays, no per-op dict access."""
    cols = getattr(h, "columns", None)
    if cols is not None:
        out: dict = defaultdict(list)
        tm = cols.time.tolist()
        tc = cols.type_code.tolist()
        fcl = cols.f_code.tolist()
        ft = cols.f_table
        for inv, comp in cols.client_pairs():
            if comp < 0:
                continue
            out[ft[fcl[inv]]].append((tm[inv] / SECOND,
                                      (tm[comp] - tm[inv]) / 1e6,
                                      TYPE_NAMES[tc[comp]]))
        return dict(out)
    out = defaultdict(list)
    # graftlint: ignore[COL002] dict fallback for loaded/legacy histories
    for op in h.client_ops():
        if not op.is_invoke:
            continue
        # graftlint: ignore[COL002] dict fallback for loaded/legacy histories
        comp = h.completion(op)
        if comp is None:
            continue
        out[op.f].append((op["time"] / SECOND,
                          (comp["time"] - op["time"]) / 1e6,
                          comp["type"]))
    return dict(out)


def quantiles(xs: list[float], qs=(0.5, 0.95, 0.99, 1.0)) -> dict:
    if not xs:
        return {}
    s = sorted(xs)
    return {q: s[min(len(s) - 1, int(q * len(s)))] for q in qs}


def nemesis_bands(h: History) -> list[dict]:
    """[{f, start_s, end_s}] windows of nemesis activity.

    Columnar path: nemesis rows are the non-int processes (interned
    negative in ``cols.proc``); read f/time straight from the typed
    arrays instead of materializing per-op dicts via nemesis_ops()."""
    bands: list = []
    open_at: dict = {}
    cols = getattr(h, "columns", None)
    if cols is not None:
        tc = cols.type_code.tolist()
        pr = cols.proc.tolist()
        pt = cols.proc_table
        tm = cols.time.tolist()
        fcl = cols.f_code.tolist()
        ft = cols.f_table
        for i, p in enumerate(pr):
            if p >= 0 or isinstance(pt[-1 - p], int):
                continue  # client row
            f = ft[fcl[i]]
            if tc[i] == 0:  # invoke
                open_at[f] = tm[i]
            elif f in open_at:
                bands.append({"f": f, "start": open_at.pop(f) / SECOND,
                              "end": tm[i] / SECOND})
        return bands
    # graftlint: ignore[COL002] dict fallback for loaded/legacy histories
    for op in h.nemesis_ops():
        if op.is_invoke:
            open_at[op.f] = op["time"]
        elif op.f in open_at:
            bands.append({"f": op.f, "start": open_at.pop(op.f) / SECOND,
                          "end": op["time"] / SECOND})
    return bands


class Perf(Checker):
    def __init__(self, nemesis_perf: Optional[list] = None):
        # nemesis packages contribute {name,color,fs} specs
        self.nemesis_perf = nemesis_perf or []

    def check(self, test, history, opts=None) -> dict:
        h = history if isinstance(history, History) else History(history)
        pts = latency_points(h)
        stats = {}
        from ..runner import telemetry
        tel = telemetry.current()
        for f, rows in pts.items():
            oks = [lat for _, lat, t in rows if t == "ok"]
            stats[f] = {
                "count": len(rows),
                "ok-latency-ms": quantiles(oks),
            }
            if oks:
                # per-class latency distribution in SECONDS (virtual
                # time in sim mode); campaign rows merge these
                tel.hist_many(f"op.latency.{f}",
                              [lat / 1e3 for lat in oks])
        cols = getattr(h, "columns", None)
        if cols is not None and len(cols):
            duration = (int(cols.time.max()) or 1) / SECOND
        else:
            duration = (max((op["time"] for op in h),
                            default=0) or 1) / SECOND
        rate = sum(len(r) for r in pts.values()) / max(duration, 1e-9)
        bands = nemesis_bands(h)
        result = {"valid?": True, "latencies": stats,
                  "throughput-ops-per-s": rate,
                  "duration-s": duration,
                  "nemesis-bands": bands}
        store_dir = (opts or {}).get("store_dir")
        if store_dir:
            try:
                self._plot(pts, bands, store_dir)
                result["plots"] = ["latency-raw.png", "rate.png"]
            except Exception as e:  # plotting must never fail a test run
                result["plot-error"] = repr(e)
        return result

    def _plot(self, pts, bands, store_dir):
        import matplotlib
        matplotlib.use("Agg")
        import matplotlib.pyplot as plt

        band_colors = {}
        for spec in self.nemesis_perf:
            for f in spec.get("fs", []):
                band_colors[f] = spec.get("color", "#FFDB9A")

        def draw_bands(ax):
            for b in bands:
                ax.axvspan(b["start"], b["end"], alpha=0.15,
                           color=band_colors.get(b["f"], "#FFDB9A"))

        fig, ax = plt.subplots(figsize=(10, 4))
        draw_bands(ax)
        type_marker = {"ok": ".", "fail": "x", "info": "+"}
        for f, rows in pts.items():
            for t in ("ok", "fail", "info"):
                xs = [x for x, _, tt in rows if tt == t]
                ys = [y for _, y, tt in rows if tt == t]
                if xs:
                    ax.plot(xs, ys, type_marker[t], markersize=3,
                            label=f"{f} {t}")
        ax.set_yscale("log")
        ax.set_xlabel("time (s)")
        ax.set_ylabel("latency (ms)")
        ax.legend(fontsize=6, ncol=3)
        fig.savefig(os.path.join(store_dir, "latency-raw.png"), dpi=100)
        plt.close(fig)

        fig, ax = plt.subplots(figsize=(10, 3))
        draw_bands(ax)
        # 1-second rate buckets per f
        for f, rows in pts.items():
            buckets: dict = defaultdict(int)
            for x, _, t in rows:
                buckets[int(x)] += 1
            xs = sorted(buckets)
            ax.plot(xs, [buckets[x] for x in xs], label=f)
        ax.set_xlabel("time (s)")
        ax.set_ylabel("ops/s")
        ax.legend(fontsize=6)
        fig.savefig(os.path.join(store_dir, "rate.png"), dpi=100)
        plt.close(fig)
