"""Consistency-surface checkers over the MVCC model (core/mvcc.py).

Four weaker-than-linearizable surfaces, each with its own definite
verdict class and each regression-tested against a simbatch injection
that provably trips it (tests/test_mvcc.py):

- :class:`BoundedStaleness` — serializable reads must be *recent*:
  never from the future (``future-read``), monotone per session
  unless a fault window separates the two reads — a restarted or
  partitioned node legitimately serves its recovering snapshot
  (``nonmonotone-session``) — and within the staleness bound unless a
  fault window explains the lag (``stale-beyond-bound``; injection:
  ``inject_stale_snapshot``).
- :class:`SnapshotRanges` — a multi-key range must be a snapshot:
  the observed versions' possibly-current windows must share an
  instant (``torn-range``; injection: ``inject_torn_range``).
- :class:`LeaseChurn` — no two sessions certainly hold the lock at
  once: certain-hold windows are clipped by the lease TTL, so
  expired-lease re-grants (pause faults) are excused by construction
  (``double-grant``; injection: ``inject_double_grant``).
- :class:`CompactionWatch` — every event a watcher missed must be
  attributed to a recorded compaction gap or lie under the compaction
  horizon; anything else is definite (``lost-event``; injection:
  ``inject_compaction_swallow``).

Every rule leans on the model's widening convention (unknown commit
points stretch intervals), so a verdict of invalid is always definite
evidence — fault schedules can only ever *excuse*, never convict.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..core.mvcc import MvccModel, T_INF, history_columns
from ..runner import telemetry
from .core import Checker

#: violations reported per run (the rest are counted, not listed)
_MAX_REPORT = 8

#: default staleness bound (virtual seconds) when opts carry none
DEFAULT_STALENESS_BOUND_S = 8.0

#: default lease TTL (ms) when opts carry none — matches the
#: lock-lease workload's churn TTL
DEFAULT_LEASE_TTL_MS = 1500


def _model_of(history) -> Optional[MvccModel]:
    cols = history_columns(history)
    return None if cols is None else MvccModel.from_columns(cols)


def _result(violations: list, counted: dict) -> dict:
    telemetry.current().counter("mvcc.violations", len(violations))
    out = {"valid?": not violations}
    out.update(counted)
    if violations:
        out["violation-count"] = len(violations)
        out["violations"] = violations[:_MAX_REPORT]
    return out


class BoundedStaleness(Checker):
    """Reads carry ``[key, version, value]``; verify every ok read is
    plausible (not future), per-session monotone, and no staler than
    the bound unless a fault window overlaps the lag."""

    def __init__(self, bound_s: Optional[float] = None):
        self.bound_s = bound_s

    def check(self, test, history, opts: Optional[dict] = None) -> dict:
        m = _model_of(history)
        if m is None:
            return {"valid?": "unknown",
                    "error": "history has no columnar view"}
        bound_s = self.bound_s
        if bound_s is None:
            bound_s = (test or {}).get("staleness_bound_s") \
                or DEFAULT_STALENESS_BOUND_S
        bound_ns = int(float(bound_s) * 1e9)
        telemetry.current().counter("mvcc.reads", len(m.reads))
        telemetry.current().counter("mvcc.keys", len(m.chains))
        telemetry.current().counter("mvcc.writes", m.writes)
        violations: list = []
        excused = 0
        excused_nonmono = 0
        # (proc, key) -> (running max ver, its read's ok time)
        last_seen: dict = {}
        for idx, p, k, ver, inv_t, ok_t in m.reads:
            # future-read: version v needs >= v writes invoked by the
            # read's completion (info writes count — they may commit)
            if ver > m.writes_invoked_before(k, ok_t):
                violations.append({
                    "class": "future-read", "index": idx, "process": p,
                    "key": k, "version": ver,
                    "writes-invoked": m.writes_invoked_before(k, ok_t)})
                continue
            prior, prior_ok = last_seen.get((p, k), (-1, 0))
            if ver < prior:
                # a fault between the two reads excuses the regression:
                # a killed-and-restarted (or partitioned) node serves
                # its recovering snapshot until it catches up
                if m.window_overlaps(prior_ok, ok_t):
                    excused_nonmono += 1
                else:
                    violations.append({
                        "class": "nonmonotone-session", "index": idx,
                        "process": p, "key": k, "version": ver,
                        "prior-read-max": prior})
                continue
            last_seen[(p, k)] = (ver, ok_t)
            # stale-beyond-bound: the successor write completed more
            # than the bound before this read even started, and no
            # fault window can explain the replica lag
            nxt = m.chain_link(k, ver + 1)
            if nxt is not None and inv_t - nxt[1] > bound_ns:
                if m.window_overlaps(inv_t - bound_ns, inv_t):
                    excused += 1
                else:
                    violations.append({
                        "class": "stale-beyond-bound", "index": idx,
                        "process": p, "key": k, "version": ver,
                        "lag-ns": int(inv_t - nxt[1]),
                        "bound-ns": bound_ns})
        return _result(violations, {
            "reads": len(m.reads), "keys": len(m.chains),
            "writes": m.writes, "excused-stale": excused,
            "excused-nonmonotone": excused_nonmono,
            "bound-s": float(bound_s)})


class SnapshotRanges(Checker):
    """Ranges carry ``[[key, version], ...]``; verify each observed
    version vector admits a common instant (no torn ranges)."""

    def check(self, test, history, opts: Optional[dict] = None) -> dict:
        m = _model_of(history)
        if m is None:
            return {"valid?": "unknown",
                    "error": "history has no columnar view"}
        telemetry.current().counter("mvcc.ranges", len(m.ranges))
        violations: list = []
        for idx, p, inv_t, ok_t, pairs in m.ranges:
            lo, hi = 0, T_INF
            lo_k = hi_k = None
            for k, ver in pairs:
                w_lo, w_hi = m.version_window(k, ver)
                if w_lo > lo:
                    lo, lo_k = w_lo, (k, ver)
                if w_hi < hi:
                    hi, hi_k = w_hi, (k, ver)
            if lo > hi:
                violations.append({
                    "class": "torn-range", "index": idx, "process": p,
                    "newest": lo_k, "stalest": hi_k,
                    "window-ns": [int(lo), int(hi)]})
        return _result(violations, {
            "ranges": len(m.ranges), "keys": len(m.chains),
            "writes": m.writes})


class LeaseChurn(Checker):
    """No two sessions certainly hold the lock at once. A session
    certainly holds from its acquire-ok until ``min(release invoke,
    acquire invoke + TTL)`` — the lease countdown starts no earlier
    than the grant request, so the TTL clip never overshoots the real
    expiry, and an expired-lease re-grant is excused by construction."""

    def __init__(self, ttl_ms: Optional[float] = None):
        self.ttl_ms = ttl_ms

    def check(self, test, history, opts: Optional[dict] = None) -> dict:
        m = _model_of(history)
        if m is None:
            return {"valid?": "unknown",
                    "error": "history has no columnar view"}
        ttl_ms = self.ttl_ms
        if ttl_ms is None:
            ttl_ms = (test or {}).get("lease_ttl_ms") \
                or DEFAULT_LEASE_TTL_MS
        ttl_ns = int(float(ttl_ms) * 1e6)
        telemetry.current().counter("mvcc.grants", len(m.sessions))
        holds = []
        for idx, p, acq_inv, acq_ok, rel_inv in m.sessions:
            end = acq_inv + ttl_ns
            if rel_inv is not None:
                end = min(end, rel_inv)
            if end > acq_ok:
                holds.append((acq_ok, end, p, idx))
        holds.sort()
        violations: list = []
        prev_end, prev_p, prev_idx = -1, None, None
        for start, end, p, idx in holds:
            if start < prev_end:
                violations.append({
                    "class": "double-grant", "index": idx, "process": p,
                    "overlaps-process": prev_p,
                    "overlaps-index": prev_idx,
                    "overlap-ns": int(prev_end - start)})
            if end > prev_end:
                prev_end, prev_p, prev_idx = end, p, idx
        return _result(violations, {
            "grants": len(m.sessions), "holds": len(holds),
            "ttl-ms": float(ttl_ms)})


class CompactionWatch(Checker):
    """Watch ops carry ``{"from", "revs", "gaps"}``; every acked
    revision a watcher's span covers must be delivered, inside a
    recorded compaction gap, or under the compaction horizon
    (attributed) — anything else is a definite lost event."""

    def check(self, test, history, opts: Optional[dict] = None) -> dict:
        m = _model_of(history)
        if m is None:
            return {"valid?": "unknown",
                    "error": "history has no columnar view"}
        horizon = m.horizon()
        canonical = m.revisions
        telemetry.current().counter("mvcc.watches", len(m.watches))
        telemetry.current().counter("mvcc.compactions",
                                    len(m.compactions))
        violations: list = []
        delivered = 0
        gap_attributed = 0
        horizon_attributed = 0
        for idx, p, from_rev, revs, gaps in m.watches:
            delivered += len(revs)
            hi = max([from_rev] + revs + [g[1] for g in gaps])
            if hi <= from_rev:
                continue
            j0 = int(np.searchsorted(canonical, from_rev, side="right"))
            j1 = int(np.searchsorted(canonical, hi, side="right"))
            expected = canonical[j0:j1]
            seen = set(revs)
            for r in expected.tolist():
                if r in seen:
                    continue
                if any(g_lo < r <= g_hi for g_lo, g_hi in gaps):
                    gap_attributed += 1
                elif r <= horizon:
                    horizon_attributed += 1
                else:
                    violations.append({
                        "class": "lost-event", "index": idx,
                        "process": p, "revision": int(r),
                        "span": [int(from_rev), int(hi)],
                        "horizon": int(horizon)})
        telemetry.current().counter("mvcc.watch-events", delivered)
        return _result(violations, {
            "watches": len(m.watches), "events": delivered,
            "acked-revisions": int(len(canonical)),
            "compactions": len(m.compactions), "horizon": horizon,
            "gap-attributed": gap_attributed,
            "horizon-attributed": horizon_attributed})
