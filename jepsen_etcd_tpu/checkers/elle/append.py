"""Elle list-append checker (the ``append/test`` analog).

Semantics re-derived from Elle's list-append model as the reference uses
it (append.clj:183-185, ``{:key-count 3 :max-txn-length 4
:consistency-models [:strict-serializable]}``):

- every append value is unique per key, so each ok read of a key — a
  list — reveals that key's version order as a prefix chain;
- reads inside a txn see the txn's own earlier appends (etcd txns apply
  their ops in sequence), so a read's *external* prefix is the list minus
  the txn's own-append suffix;
- dependency edges over committed txns:
    wr  writer(last element of external prefix) -> reader
    ww  writer(v_i) -> writer(v_{i+1}) along each key's version order
    rw  reader of prefix P -> writer(P's successor version)
    rt  T1 completed before T2 invoked (strict-serializable only)
- non-cycle anomalies: duplicate-elements, incompatible-order (reads
  that are not a prefix chain), internal (read contradicts own appends),
  G1a (aborted read: observed a failed txn's append), G1b (intermediate
  read: external prefix ends at a txn's non-final append to that key);
- cycle anomalies G0/G1c/G-single/G2-item (+-realtime) via the batched
  TPU closure kernel (graph.py / ops/closure.py).

Info (indeterminate) txns count as committed iff one of their appends
was observed by an ok read.
"""

from __future__ import annotations

from collections import defaultdict
from typing import Any, Optional

import numpy as np

from ..core import Checker
from .graph import DepGraph, Txn, collect_txns, render_result


def _collect(history) -> list[Txn]:
    txns = collect_txns(history)
    for t in txns:
        for f, k, v in t.mops:
            if f == "append":
                t.appends[k].append(v)
    return txns


class ListAppendChecker(Checker):
    def __init__(self, consistency_models=("strict-serializable",),
                 use_tpu: Optional[bool] = None):
        self.models = list(consistency_models)
        self.realtime = "strict-serializable" in self.models
        self.use_tpu = use_tpu

    def check(self, test, history, opts=None) -> dict:
        anomalies: dict[str, list] = defaultdict(list)
        txns = _collect(history)
        writer: dict[tuple, Txn] = {}
        for t in txns:
            for k, vs in t.appends.items():
                for v in vs:
                    if (k, v) in writer:
                        anomalies["duplicate-appends"].append(
                            {"key": k, "value": v})
                    writer[(k, v)] = t

        # -- reads: internal checks + external prefixes ----------------------
        # (k, external-prefix-tuple, reader) triples
        ext_reads: list[tuple] = []
        observed: set = set()  # (k, v) seen in any ok read
        for t in txns:
            if t.status != "ok":
                continue
            own_so_far: dict = defaultdict(list)
            for f, k, v in t.mops:
                if f == "append":
                    own_so_far[k].append(v)
                    continue
                lst = list(v) if v is not None else []
                if len(set(lst)) != len(lst):
                    anomalies["duplicate-elements"].append(
                        {"op": dict(t.op), "mop": [f, k, v]})
                    continue
                own = own_so_far[k]
                if lst[len(lst) - len(own):] != own or len(lst) < len(own):
                    anomalies["internal"].append(
                        {"op": dict(t.op), "mop": [f, k, v],
                         "expected-suffix": list(own)})
                    continue
                ext = lst[:len(lst) - len(own)]
                if any(x in t.appends.get(k, []) for x in ext):
                    anomalies["internal"].append(
                        {"op": dict(t.op), "mop": [f, k, v],
                         "reason": "own append in external prefix"})
                    continue
                for x in ext:
                    observed.add((k, x))
                ext_reads.append((k, tuple(ext), t))

        # -- per-key version order from prefix chains ------------------------
        version_order: dict[Any, list] = {}
        bad_keys: set = set()
        by_key: dict[Any, set] = defaultdict(set)
        for k, ext, _ in ext_reads:
            by_key[k].add(ext)
        for k, prefixes in by_key.items():
            longest = max(prefixes, key=len)
            for p in prefixes:
                if longest[:len(p)] != p:
                    anomalies["incompatible-order"].append(
                        {"key": k, "values": [list(p), list(longest)]})
                    bad_keys.add(k)
            if k not in bad_keys:
                version_order[k] = list(longest)

        # -- aborted / intermediate reads ------------------------------------
        for (k, v) in sorted(observed, key=repr):
            w = writer.get((k, v))
            if w is None:
                anomalies["lost-write"].append(
                    {"key": k, "value": v,
                     "reason": "read a value no txn appended"})
            elif w.status == "fail":
                anomalies["G1a"].append(
                    {"key": k, "value": v, "writer": dict(w.op)})
        for k, ext, t in ext_reads:
            if not ext:
                continue
            last = ext[-1]
            w = writer.get((k, last))
            if w is not None and w.status != "fail" and \
                    w.appends[k] and w.appends[k][-1] != last:
                anomalies["G1b"].append(
                    {"op": dict(t.op), "key": k,
                     "read-prefix": list(ext),
                     "writer-appends": list(w.appends[k])})

        # -- committed node set ----------------------------------------------
        committed = [t for t in txns
                     if t.status == "ok" or
                     (t.status == "info" and
                      any((k, v) in observed for k, vs in t.appends.items()
                          for v in vs))]
        for i, t in enumerate(committed):
            t.node = i
        g = DepGraph(len(committed))

        # ww + rw along version orders
        for k, order in version_order.items():
            for a, b in zip(order, order[1:]):
                wa, wb = writer.get((k, a)), writer.get((k, b))
                if wa is not None and wb is not None and \
                        wa.node is not None and wb.node is not None:
                    g.add("ww", wa.node, wb.node)
        for k, ext, t in ext_reads:
            order = version_order.get(k)
            if t.node is None:
                continue
            if ext:
                w = writer.get((k, ext[-1]))
                if w is not None and w.node is not None:
                    g.add("wr", w.node, t.node)
            if order is not None and len(ext) < len(order):
                succ = writer.get((k, order[len(ext)]))
                if succ is not None and succ.node is not None:
                    g.add("rw", t.node, succ.node)

        if self.realtime and committed:
            g.set_realtime(
                np.array([t.invoke_index for t in committed], float),
                np.array([t.complete_index for t in committed], float))

        for rec in g.find_cycles(realtime=self.realtime,
                                 force_device=self.use_tpu):
            rec = dict(rec)
            rec["txns"] = [dict(committed[i].op) for i in rec["cycle"]]
            anomalies[rec.pop("type")].append(rec)

        out = render_result(dict(anomalies), self.models)
        out["txn-count"] = len(txns)
        out["committed-count"] = len(committed)
        return out
