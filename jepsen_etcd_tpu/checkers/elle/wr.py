"""Elle rw-register checker (the ``wr/test`` analog).

Semantics re-derived from Elle's rw-register model as the reference uses
it (wr.clj:87-92, ``{:key-count 3 :max-txn-length 4
:consistency-models [:strict-serializable] :wfr-keys true}``):

Registers carry opaque (unique per key) values, so version orders are not
directly observable like list prefixes; they are *inferred* from certain
sources only (keeping the checker sound — no false anomalies):

- the initial state ⊥ (a read of nil) precedes every written version;
- writes-follow-reads within one txn (wfr-keys): a txn that externally
  reads k=v1 and then writes k=v2 establishes v1 << v2;
- intra-txn write chains: writing v_a then v_b to the same key in one
  txn establishes v_a << v_b.

From the per-key partial order (transitively closed; a cycle in it is
itself the ``cyclic-version-order`` anomaly):

    wr  writer(v) -> txn that externally read k=v
    ww  writer(v1) -> writer(v2)           for every known v1 << v2
    rw  reader of k=v1 -> writer(v2)       for every known v1 << v2
        (a read of ⊥ precedes every writer of k)
    rt  realtime edges for strict-serializable

plus internal (a txn's read contradicts its own earlier ops), G1a
(reading a failed txn's write), G1b (reading a non-final write of a
committed txn). Cycles via the shared TPU closure kernel (graph.py).
"""

from __future__ import annotations

from collections import defaultdict
from typing import Any, Optional

import numpy as np

from ..core import Checker
from .graph import DepGraph, Txn, collect_txns, render_result


class RWRegisterChecker(Checker):
    def __init__(self, consistency_models=("strict-serializable",),
                 wfr_keys: bool = True, use_tpu: Optional[bool] = None):
        self.models = list(consistency_models)
        self.realtime = "strict-serializable" in self.models
        self.wfr = wfr_keys
        self.use_tpu = use_tpu

    def check(self, test, history, opts=None) -> dict:
        anomalies: dict[str, list] = defaultdict(list)
        txns = collect_txns(history)

        # -- writer index + per-txn analysis ---------------------------------
        writer: dict[tuple, Txn] = {}
        for t in txns:
            for f, k, v in t.mops:
                if f == "w":
                    if (k, v) in writer:
                        anomalies["duplicate-writes"].append(
                            {"key": k, "value": v})
                    writer[(k, v)] = t
                    t.writes[k].append(v)

        #: per-key version constraints v1 << v2 (certain sources only)
        vo_edges: dict[Any, set] = defaultdict(set)
        observed: set = set()   # (k, v) read by an ok txn (v may be None)
        for t in txns:
            if t.status != "ok":
                continue
            last_written: dict = {}
            last_read: dict = {}
            for f, k, v in t.mops:
                if f == "w":
                    if k in last_written:
                        vo_edges[k].add((last_written[k], v))
                    elif self.wfr and k in t.ext_reads and \
                            t.ext_reads[k] is not None:
                        vo_edges[k].add((t.ext_reads[k], v))
                    last_written[k] = v
                    continue
                # f == "r"
                if k in last_written:
                    if v != last_written[k]:
                        anomalies["internal"].append(
                            {"op": dict(t.op), "mop": [f, k, v],
                             "expected": last_written[k]})
                    continue
                if k in last_read and last_read[k] != v:
                    anomalies["internal"].append(
                        {"op": dict(t.op), "mop": [f, k, v],
                         "expected": last_read[k],
                         "reason": "non-repeatable read inside txn"})
                last_read[k] = v
                if k not in t.ext_reads:
                    t.ext_reads[k] = v
                    observed.add((k, v))

        # -- aborted / intermediate / phantom reads --------------------------
        for (k, v) in sorted(observed, key=repr):
            if v is None:
                continue
            w = writer.get((k, v))
            if w is None:
                anomalies["lost-write"].append(
                    {"key": k, "value": v,
                     "reason": "read a value no txn wrote"})
            elif w.status == "fail":
                anomalies["G1a"].append(
                    {"key": k, "value": v, "writer": dict(w.op)})
            elif w.writes[k] and w.writes[k][-1] != v:
                anomalies["G1b"].append(
                    {"key": k, "value": v,
                     "writer-writes": list(w.writes[k])})

        # -- per-key version-order closure -----------------------------------
        succ: dict[Any, dict] = {}
        for k, edges in vo_edges.items():
            adj: dict = defaultdict(set)
            for a, b in edges:
                adj[a].add(b)
            closure: dict = {}
            cyclic = False
            for start in list(adj):
                seen: set = set()
                stack = [start]
                while stack:
                    u = stack.pop()
                    for nxt in adj.get(u, ()):
                        if nxt == start:
                            cyclic = True
                        if nxt not in seen:
                            seen.add(nxt)
                            stack.append(nxt)
                closure[start] = seen
            if cyclic:
                anomalies["cyclic-version-order"].append(
                    {"key": k, "edges": sorted(edges)})
            else:
                succ[k] = closure

        # -- committed nodes + dependency edges ------------------------------
        committed = [t for t in txns
                     if t.status == "ok" or
                     (t.status == "info" and
                      any((k, v) in observed for k, vs in t.writes.items()
                          for v in vs))]
        for i, t in enumerate(committed):
            t.node = i
        g = DepGraph(len(committed))

        key_writers: dict[Any, list] = defaultdict(list)
        for (k, v), w in writer.items():
            if w.node is not None:
                key_writers[k].append((v, w))

        for k, closure in succ.items():
            for v1, v2s in closure.items():
                w1 = writer.get((k, v1))
                if w1 is None or w1.node is None:
                    continue
                for v2 in v2s:
                    w2 = writer.get((k, v2))
                    if w2 is not None and w2.node is not None:
                        g.add("ww", w1.node, w2.node)
        for t in committed:
            if t.status != "ok":
                continue
            for k, v in t.ext_reads.items():
                if v is not None:
                    w = writer.get((k, v))
                    if w is not None and w.node is not None:
                        g.add("wr", w.node, t.node)
                    for v2 in succ.get(k, {}).get(v, ()):
                        w2 = writer.get((k, v2))
                        if w2 is not None and w2.node is not None:
                            g.add("rw", t.node, w2.node)
                else:
                    # read of ⊥: every writer of k overwrote what t saw
                    for _, w2 in key_writers.get(k, ()):
                        g.add("rw", t.node, w2.node)

        if self.realtime and committed:
            g.set_realtime(
                np.array([t.invoke_index for t in committed], float),
                np.array([t.complete_index for t in committed], float))

        for rec in g.find_cycles(realtime=self.realtime,
                                 force_device=self.use_tpu):
            rec = dict(rec)
            rec["txns"] = [dict(committed[i].op) for i in rec["cycle"]]
            anomalies[rec.pop("type")].append(rec)

        out = render_result(dict(anomalies), self.models)
        out["txn-count"] = len(txns)
        out["committed-count"] = len(committed)
        return out
