"""Shared dependency-graph cycle analysis for the Elle-analog checkers.

Both Elle checkers (list-append, rw-register) reduce to the same core:
given per-edge-type adjacency over committed transactions (ww, wr, rw,
plus realtime for strict-serializable), find cycles in the *nested*
subgraphs Adya's anomaly hierarchy distinguishes:

    G0          cycle in ww alone           (write cycle)
    G1c         cycle in ww|wr              (circular information flow)
    G-single    cycle with exactly one rw   (read skew / non-repeatable)
    G2-item     cycle with >=2 rw           (anti-dependency cycle)
    *-realtime  same, but needing realtime edges (strict-serializability
                violations that serializability alone permits)

All six subgraph closures compute in ONE batched TPU kernel launch
(ops/closure.py); certificates (a concrete cycle to show the user) are
recovered host-side by BFS over the sparse edges, restricted to the
cycle-participating nodes the kernel identified.

G-single is separated from G2-item exactly: an rw edge (a, b) closes a
G-single cycle iff the *previous* level's closure already reaches b -> a
(one rw + a ww|wr path); otherwise the cycle needs a second rw.
"""

from __future__ import annotations

import logging
import math
from collections import defaultdict, deque
from typing import Any, Optional

import numpy as np

logger = logging.getLogger("jepsen_etcd_tpu.checkers")

from ...core.history import History
from ...ops.closure import EdgeAccumulator, closure_levels_lazy

WW, WR, RW, RT = "ww", "wr", "rw", "realtime"
_ET_INDEX = {WW: 0, WR: 1, RW: 2}

#: certificate-enumeration bounds per anomaly class: enough to show every
#: independent cycle in practice without letting one big SCC turn the
#: checker into an O(E) BFS storm with a thousand-entry result map
MAX_CERTS_PER_CLASS = 32
MAX_ANCHOR_SCANS = 512

#: anomaly -> weakest consistency models it rules out (Elle's `not` field)
ANOMALY_NOT = {
    "G0": ["read-uncommitted"],
    "G1a": ["read-committed"],
    "G1b": ["read-committed"],
    "G1c": ["read-committed"],
    "internal": ["read-committed"],
    "G-single": ["consistent-view", "snapshot-isolation"],
    "G2-item": ["serializable"],
    "G0-realtime": ["strict-serializable"],
    "G1c-realtime": ["strict-serializable"],
    "G-single-realtime": ["strict-serializable"],
    "G2-item-realtime": ["strict-serializable"],
    "incompatible-order": ["read-committed"],
    "duplicate-elements": ["read-committed"],
    "cyclic-version-order": ["read-committed"],
}


class Txn:
    """One transaction as both checkers see it: the completion op, its
    invoke/complete history indices (complete = +inf for indeterminate
    ops, which never gain outgoing realtime edges), and the micro-ops
    (from the invocation for non-ok ops, whose completion value may be
    missing)."""

    __slots__ = ("op", "invoke_index", "complete_index", "mops", "status",
                 "appends", "writes", "ext_reads", "node")

    def __init__(self, op, invoke_index, complete_index, mops, status):
        self.op = op
        self.invoke_index = invoke_index
        self.complete_index = complete_index
        self.mops = mops
        self.status = status  # "ok" | "info" | "fail"
        self.appends: dict = defaultdict(list)  # list-append: k -> [v...]
        self.writes: dict = defaultdict(list)   # rw-register: k -> [v...]
        self.ext_reads: dict = {}               # rw-register: k -> v
        self.node: Optional[int] = None


def collect_txns(history) -> list[Txn]:
    h = history if isinstance(history, History) else History(history)
    txns = []
    for op in h.client_ops():
        if not (op.is_completion and op.get("f") == "txn"):
            continue
        inv = h.invocation(op)
        inv_index = inv["index"] if inv is not None else op["index"]
        status = op["type"]
        mops = op.value if (status == "ok" and op.value) else \
            (inv.value if inv is not None else op.value) or []
        complete = op["index"] if status == "ok" else math.inf
        txns.append(Txn(op, inv_index, complete, mops, status))
    return txns


def _bfs_path(adj: dict[int, list], src: int, dst: int) -> Optional[list]:
    """Shortest node path src..dst over adjacency lists (None if none)."""
    if src == dst:
        return [src]
    prev: dict[int, int] = {src: src}
    q = deque([src])
    while q:
        u = q.popleft()
        for v in adj.get(u, ()):
            if v in prev:
                continue
            prev[v] = u
            if v == dst:
                path = [v]
                while path[-1] != src:
                    path.append(prev[path[-1]])
                return path[::-1]
            q.append(v)
    return None


class DepGraph:
    """Sparse per-type edges over n transaction nodes.

    Edges are held in an :class:`~...ops.closure.EdgeAccumulator` —
    chunked int32 buffers instead of a set of tuples — so the streaming
    path can accumulate edges incrementally without a per-edge Python
    object footprint. ``finalize()`` yields the sorted-unique per-type
    ``[E, 2]`` arrays, which are both the kernel input and (row order ==
    ``sorted(set)``) what every host-side consumer below iterates."""

    def __init__(self, n: int):
        self.n = n
        self._acc = EdgeAccumulator(len(_ET_INDEX))
        self._sets: Optional[dict] = None  # lazy, certificates only
        self.rt: Optional[np.ndarray] = None  # dense [n, n] bool

    def add(self, etype: str, i: int, j: int) -> None:
        self._acc.add(_ET_INDEX[etype], i, j)
        self._sets = None

    def _arrays(self) -> list[np.ndarray]:
        return self._acc.finalize()

    @property
    def edges(self) -> dict[str, set]:
        """Per-type edge sets, materialized on demand (certificate
        recovery and membership tests only — the hot paths use the
        finalized arrays directly)."""
        if self._sets is None:
            arrs = self._arrays()
            self._sets = {et: set(map(tuple, arrs[ti].tolist()))
                          for et, ti in _ET_INDEX.items()}
        return self._sets

    def set_realtime(self, invoke_idx: np.ndarray,
                     complete_idx: np.ndarray) -> None:
        """T1 -> T2 iff T1 completed before T2 invoked (history indices;
        ops that never completed carry +inf and get no outgoing edges)."""
        self.rt = complete_idx[:, None] < invoke_idx[None, :]
        np.fill_diagonal(self.rt, False)
        # kept for the compact device path: the dense rt matrix is
        # derivable from these two N-vectors on device, so the closure
        # launch ships ~KBs instead of the O(B*N^2) bool stack (80 MB
        # at the append bench's 3.7k txns — ~2 s of tunnel bandwidth)
        self._rt_vecs = (np.asarray(invoke_idx, dtype=np.float64),
                         np.asarray(complete_idx, dtype=np.float64))

    # -- analysis ------------------------------------------------------------

    def _dense(self, *etypes: str) -> np.ndarray:
        a = np.zeros((self.n, self.n), dtype=bool)
        arrs = self._arrays()
        for et in etypes:
            if et == RT:
                if self.rt is not None:
                    a |= self.rt
                continue
            idx = arrs[_ET_INDEX[et]]
            if len(idx):
                a[idx[:, 0], idx[:, 1]] = True
        return a

    def _adj_lists(self, *etypes: str) -> dict[int, list]:
        adj: dict[int, list] = {}
        seen = set()
        arrs = self._arrays()
        for et in etypes:
            if et == RT:
                if self.rt is not None:
                    for i, j in zip(*np.nonzero(self.rt)):
                        if (i, j) not in seen:
                            seen.add((i, j))
                            adj.setdefault(int(i), []).append(int(j))
                continue
            for i, j in arrs[_ET_INDEX[et]].tolist():
                if (i, j) not in seen:
                    seen.add((i, j))
                    adj.setdefault(i, []).append(j)
        return adj

    def edge_type(self, i: int, j: int) -> str:
        for et in (WW, WR, RW):
            if (i, j) in self.edges[et]:
                return et
        if self.rt is not None and self.rt[i, j]:
            return RT
        return "?"

    def find_cycles(self, realtime: bool = True,
                    force_device: Optional[bool] = None) -> list[dict]:
        """Run the batched closure kernel over the nested subgraphs and
        return anomaly records [{type, cycle, steps}], strongest first.

        Each anomaly level recovers its certificate *anchored on the edge
        type that distinguishes it* — G1c on a wr edge whose target reaches
        back, G-single/G2-item on an rw edge, the realtime variants on an
        edge whose back-path exists only once rt edges are added — so a
        weaker level's cycle can never be re-found and mislabeled at a
        stronger level, and each reported type is genuinely present.
        """
        if self.n == 0:
            return []
        levels = [(WW,), (WW, WR), (WW, WR, RW)]
        use_rt = realtime and self.rt is not None
        if use_rt:
            levels += [(WW, RT), (WW, WR, RT), (WW, WR, RW, RT)]
        # compact inputs: per-type edge lists + the rt vectors; the
        # device path builds the level stack on-chip (shipping the
        # dense bool stack cost ~2 s of tunnel bandwidth at 3.7k txns),
        # while host/sharded paths densify lazily as before. reach is
        # fetched lazily: only certificate recovery on invalid
        # histories touches it, so valid checks skip the O(B*N^2)
        # device->host transfer
        et_order = (WW, WR, RW)
        lvl_mask = np.array(
            [[et in ets for et in et_order] + [RT in ets]
             for ets in levels])
        et_edges = [self._arrays()[_ET_INDEX[et]] for et in et_order]
        rt_vecs = getattr(self, "_rt_vecs", None) if use_rt else None
        reach_fn, on_cycle = closure_levels_lazy(
            et_edges, lvl_mask, self.n, rt_vecs,
            densify=lambda: np.stack([self._dense(*ets)
                                      for ets in levels]),
            force_device=force_device)
        adjs: dict[int, dict] = {}

        def adj(li: int) -> dict:
            if li not in adjs:
                adjs[li] = self._adj_lists(*levels[li])
            return adjs[li]

        def anchored(name: str, anchor_edges, need: int,
                     forbid: tuple = ()) -> list[dict]:
            """ALL cycles of a class: one certificate per anchor edge
            (a, b) whose back-path b->a exists in level `need`; `forbid`
            lists weaker levels the back-path must NOT exist at (so the
            cycle genuinely needs the edges `need` adds, and a weaker
            anomaly is never re-labeled here). Distinct anchors that
            close over the same node cycle dedupe to one certificate —
            Elle likewise enumerates every cycle it finds, not just the
            first (elle's cycle search reports each anchored cycle)."""
            reach = reach_fn()
            found: list[dict] = []
            seen_cycles: set = set()
            scans = 0
            for (a, b) in sorted(anchor_edges):
                # bound the enumeration: a densely cyclic history can
                # have O(E) on-cycle anchors (one BFS each) — Elle
                # likewise bounds its cycle search rather than emit
                # thousands of certificates. Mark the truncation so a
                # dense history's report never reads as exhaustive
                # (the repo's no-silent-caps convention).
                if len(found) >= MAX_CERTS_PER_CLASS or \
                        scans >= MAX_ANCHOR_SCANS:
                    if found:
                        found[-1] = dict(found[-1],
                                         **{"certificates-truncated": True})
                    truncated_classes.append(name)
                    break
                if not reach[need][b, a]:
                    continue
                if any(reach[f][b, a] for f in forbid):
                    continue
                scans += 1
                back = _bfs_path(adj(need), b, a)
                if back is None:
                    continue
                cycle = [a] + back
                nodes = cycle[:-1]
                # canonical rotation: same cycle found from different
                # anchors collapses to one certificate
                pivot = nodes.index(min(nodes))
                key = tuple(nodes[pivot:] + nodes[:pivot])
                if key in seen_cycles:
                    continue
                seen_cycles.add(key)
                found.append(self._record(name, cycle))
            return found

        recs: list = []
        truncated_classes: list = []
        add = recs.extend

        # anchor lists come straight from the finalized arrays: already
        # lexicographically sorted, so anchored()'s sorted() is a no-op
        ww, wr, rw = (list(map(tuple, e.tolist())) for e in et_edges)
        if on_cycle[0].any():
            add(anchored("G0", ww, need=0))
        if on_cycle[1].any():
            add(anchored("G1c", wr, need=1))
        if on_cycle[2].any():
            # Scan both classes: a history can contain a G-single AND an
            # independent G2-item cycle. The forbid gate keeps G2-item
            # anchored only on rw edges whose back-path genuinely needs
            # a second rw, so one cycle is never labeled twice.
            add(anchored("G-single", rw, need=1))
            add(anchored("G2-item", rw, need=2, forbid=(1,)))
        if len(levels) > 3:
            if on_cycle[3].any():
                add(anchored("G0-realtime", ww, need=3, forbid=(0,)))
            if on_cycle[4].any():
                add(anchored("G1c-realtime", wr, need=4, forbid=(1,)))
            if on_cycle[5].any():
                add(anchored("G-single-realtime", rw, need=4, forbid=(1,)))
                add(anchored("G2-item-realtime", rw, need=5,
                             forbid=(2, 4)))
        if truncated_classes:
            logger.warning(
                "elle certificate enumeration truncated for %s "
                "(caps: %d certificates / %d anchor scans per class); "
                "the verdict is unaffected but the anomaly list is "
                "not exhaustive", truncated_classes,
                MAX_CERTS_PER_CLASS, MAX_ANCHOR_SCANS)
        return recs

    def _record(self, name: str, cycle: list) -> dict:
        """cycle is [n0, n1, ..., n0]; annotate each step's edge type."""
        steps = [{"from": cycle[i], "to": cycle[i + 1],
                  "type": self.edge_type(cycle[i], cycle[i + 1])}
                 for i in range(len(cycle) - 1)]
        return {"type": name, "cycle": cycle[:-1], "steps": steps}


def render_result(anomalies: dict[str, list],
                  consistency_models: list) -> dict:
    """Assemble the Elle-shaped result map: valid?, anomaly-types,
    anomalies, not (models ruled out)."""
    types = sorted(anomalies)
    not_models: list = []
    for t in types:
        for m in ANOMALY_NOT.get(t, []):
            if m not in not_models:
                not_models.append(m)
    valid = not types
    out = {"valid?": True if valid else False,
           "anomaly-types": types,
           "anomalies": anomalies,
           "not": not_models}
    return out
