"""Elle-analog transactional anomaly checkers (TPU cycle engine).

The reference consumes the Elle library through ``append/test``
(append.clj:183-185) and ``wr/test`` (wr.clj:87-92); these modules
re-derive the two checkers — list-append and rw-register — with the
dependency-graph cycle search running as a batched boolean-matmul
transitive closure on TPU (ops/closure.py).
"""

from .append import ListAppendChecker
from .wr import RWRegisterChecker

__all__ = ["ListAppendChecker", "RWRegisterChecker"]
