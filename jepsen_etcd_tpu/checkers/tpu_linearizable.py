"""checker/tpu-linearizable: the TPU fast path with a sound fallback.

Binding analog of ``checker/linearizable`` (register.clj:110-112) but
running the search on-device (ops/wgl.py). Soundness contract: the kernel
answers definitively only when its preconditions hold (window fits, no
info ops, no frontier overflow); anything else falls back to the CPU
oracle — the TPU path can be fast, it must never be wrong.
"""

from __future__ import annotations

import logging
from typing import Optional

from ..models import VersionedRegister
from ..runner import telemetry
from .core import Checker
from .linearizable import check_history

logger = logging.getLogger("jepsen_etcd_tpu.checkers")


def _tally_engine(out: dict) -> dict:
    """Count which engine produced this verdict (``engine.mxu-wave``,
    ``engine.jnp-ladder``, ``engine.cpu-oracle``) into the run's
    telemetry, so results.json shows the routing split per run."""
    telemetry.current().counter(
        "engine." + str(out.get("engine") or out.get("checker")
                        or "unknown"))
    return out

#: histories at or below this many entries (invoke + completion) route
#: to the native DFS before any device packing: TPU dispatch costs
#: ~0.4 s while the native engine answers small searches in single-digit
#: ms (BENCH_r02 register_100: 0.40 s TPU vs 2.4 ms native — ~166x).
#: The kernel remains the engine for deep histories and batched keys,
#: mirroring the CPU_CUTOFF routing in ops/closure.py:37. The split
#: plays the role of the reference's Knossos-vs-workload division at
#: register.clj:110-112 (one checker, engine picked by problem size).
CPU_CUTOFF = 512

#: mid-size band: up to here the DFS still gets first shot, with a
#: budget scaled to history size. MEASURED head-to-head (r4, single
#: v5e through axon, native DFS vs the MXU wave kernel on register
#: histories; entries = history length incl invokes ~= 2.6 x R):
#:
#:   R      entries   native DFS   mxu kernel
#:   511     1,350      0.005 s      0.079 s
#:   2,068   5,400      0.027 s      0.118 s
#:   5,157  13,500      0.101 s      0.102 s   <- crossover
#:   10,392 27,000      0.599 s      0.129 s
#:   26,045 67,500      3.004 s      0.223 s
#:   52,007 135,000     8.766 s      0.398 s
#:
#: adversarial (violation injected mid-history, DFS must linearize
#: half before discovering it): R=10,392 native 0.332 s vs mxu
#: 0.135 s — same crossover region, so one constant serves both.
#: The kernel's floor is the axon tunnel round trip (~0.1 s); the
#: DFS's curve is ~quadratic. They cross at ~13k entries.
#:
#: Search DIFFICULTY is measured not to need its own routing
#: dimension below this cutoff (r5, deep 4n/2000 cell: 5.2k entries,
#: BFS peak frontier 252): the memoized DFS walks a near-linear
#: witness on the valid history (3,044 configs, 0.035 s vs the
#: ladder's 1.22 s), and on corrupted-read / unreachable-version
#: adversarials the version-determinism of the register model
#: collapses the refutation to ~18.5k configs (0.05 s). Pathological
#: cases the prediction misses are bounded by the band's 4n+10k
#: config budget (~one kernel-run of waste) before the kernel takes
#: over — exhaustion-priced, not predicted.
DFS_FIRST_MAX = 13_000

#: (BATCH_DFS_MAX, r5's batched key-DP crossover at 1,000 entries/key,
#: is deleted: its limiting term was HOST-side per-key Python packing
#: — ~1.1 ms/key incl. history_entries, exceeding the native DFS's
#: entire per-key budget of ~0.7 ms — which the batched SoA packer
#: (wgl.pack_register_histories_batched) removed by vectorizing
#: extraction + interning + window geometry across the whole key
#: batch. With packing amortized, the batch band collapses to the
#: same CPU_CUTOFF the single-key path uses: keys the native DFS
#: answers in ms stay native, everything else amortizes one fused
#: launch. Measured packing cost model in PERF.md §2.)


class TPULinearizableChecker(Checker):
    def __init__(self, model_fn=None, fallback: bool = True,
                 f_max: Optional[int] = None,
                 cpu_cutoff: Optional[int] = CPU_CUTOFF,
                 dfs_first_max: Optional[int] = DFS_FIRST_MAX):
        self.model_fn = model_fn or (lambda: VersionedRegister(0, None))
        self.fallback = fallback
        self.f_max = f_max
        # fallback=False means "I want the kernel's answer" (the test
        # harness's way of pinning the TPU path), so the size cutoff
        # only applies when CPU routing is allowed at all
        self.cpu_cutoff = cpu_cutoff if fallback else None
        # the mid band rides on the cutoff: without CPU routing at all
        # (cpu_cutoff None pins the kernel) the band must be off too
        self.dfs_first_max = dfs_first_max if self.cpu_cutoff else None

    #: cutoff-DFS budget: the "cheap shot" size (same cap _fallback uses
    #: for blowup histories) — a small history that exhausts this gets
    #: the kernel's complete BFS instead of more DFS
    CUTOFF_MAX_CONFIGS = 1_000_000
    #: check_history's default budget: what _fallback spends when the
    #: kernel can't take a history at all
    FALLBACK_MAX_CONFIGS = 5_000_000

    def _small_history_check(
            self, history,
            band: Optional[int] = None
    ) -> tuple[Optional[dict], Optional[dict], int]:
        """Size-cutoff routing: below CPU_CUTOFF the native DFS wins by
        orders of magnitude over device dispatch; up to the mid-size
        band it still goes first with a size-scaled budget (measured
        crossover in DFS_FIRST_MAX's comment). Returns (result, unknown,
        budget): result is the definitive answer or None; unknown
        carries a budget-exhausted verdict with the budget it spent, so
        callers that later fail to reach the kernel can decide whether
        that search already covered what _fallback would spend."""
        n = len(history)
        if band is None:
            band = max(self.cpu_cutoff or 0, self.dfs_first_max or 0)
        if not self.cpu_cutoff or n > band:
            return None, None, 0
        if n <= self.cpu_cutoff:
            budget = self.CUTOFF_MAX_CONFIGS
        else:
            # mid-size band: a valid history's witness costs ~R = n/2
            # configs, so 4n + 10k is ~8x that with floor headroom,
            # while an exhausted budget wastes at most about one
            # kernel-run of time before the kernel gets the history
            budget = 4 * n + 10_000
        out = check_history(self.model_fn(), history, max_configs=budget)
        out["checker"] = "cpu-oracle"
        out["engine-route"] = "size-cutoff"
        if out["valid?"] == "unknown":
            return None, out, budget
        # report the indefinite-entry count like the kernel result does
        # (wgl.check_packed's "info-ops"): entries the search may decline
        # to linearize — :info completions AND still-open invokes
        from .linearizable import history_entries
        entries = history_entries(history) or []
        out.setdefault("info-ops",
                       sum(1 for e in entries if not e.required))
        return out, None, budget

    def _pack_fn(self):
        """The kernel packing for this model, or None for CPU-only
        models. VersionedRegister(0, None) packs natively; Mutex packs
        through the CAS-register adapter (a mutex IS a 2-value CAS
        register) — so the lock workloads' Knossos check (lock.clj:244)
        also runs on-device."""
        from ..ops import wgl
        from ..models import Mutex
        m = self.model_fn()
        if m == VersionedRegister(0, None):
            return wgl.pack_register_history
        if m == Mutex(False):
            return wgl.pack_mutex_history
        return None

    def _pack_batch_fn(self):
        """Batched form of _pack_fn: one SoA packing pass over a whole
        keyed dict of subhistories (wgl.pack_register_histories_batched)
        instead of a per-key Python loop. None for CPU-only models."""
        import functools
        from ..ops import wgl
        from ..models import Mutex
        m = self.model_fn()
        if m == VersionedRegister(0, None):
            return wgl.pack_register_histories_batched
        if m == Mutex(False):
            return functools.partial(wgl.pack_register_histories_batched,
                                     adapter=wgl.mutex_adapter)
        return None

    def _service_check(self, test, packs: list) -> Optional[list]:
        """Ship device-bound packs to the campaign checker service
        (runner/checker_service.py) when one is configured, returning
        verdicts aligned with packs — or None, meaning "check
        in-process". Only device-bound work ships: the size-cutoff
        routing ran before packing, and the CPU diagnostics / overflow
        DFS / fallback ladder all run locally on what comes back
        (_finalize), so verdicts are independent of WHERE the kernel
        ran. Any service failure degrades to the exact in-process path
        (counted as service.fallback) — a dead service costs latency,
        never verdicts."""
        from ..runner import checker_service as svc
        if svc.endpoint_for(test) is None:
            return None
        client = svc.client_for(test)
        tel = telemetry.current()
        # ship the run's trace id with the packs: the service stamps
        # it on the coalesced dispatch span, making the shipped ==
        # submitted ledger joinable per run
        outs = client.check(packs, trace=tel.trace) \
            if client is not None else None
        if outs is None:
            tel.counter("service.fallback")
        else:
            # producer-side ledger: what THIS run shipped. Summed over
            # a campaign's runs, service.shipped must equal the
            # service's own service.submitted (the e2e test pins it).
            tel.counter("service.checks")
            tel.counter("service.shipped", len(packs))
            wait = getattr(client, "last_queue_wait_s", None)
            if wait is not None:
                # this run's share of the service's total queue wait
                tel.counter("service.queue_wait_s", wait)
                tel.hist("service.queue_wait_s", wait)
        return outs

    def _finalize(self, history, out: dict, pack=None,
                  band=(None, None, 0)) -> dict:
        """Post-process one kernel verdict into a checker result,
        attaching CPU counterexample diagnostics / fallback as needed.
        band is the (result, unknown, budget) triple from a prior
        _small_history_check, so the fallback can skip a DFS that
        already ran with at least the budget it would spend."""
        if out["valid?"] is True:
            out["checker"] = "tpu-wgl"
            return out
        if out["valid?"] is False:
            # attach the counterexample diagnostics (offending op,
            # model error) the CPU oracle produces; violations are
            # rare so the extra search is cheap
            out["checker"] = "tpu-wgl"
            cpu = check_history(self.model_fn(), history)
            for k in ("op", "error", "max-linearized"):
                if k in cpu:
                    out[k] = cpu[k]
            return out
        if out.get("overflow") and pack is not None:
            return self._overflow(history, pack, out)
        return self._fallback_after_band(
            history, out.get("reason", "unknown"),
            bool(out.get("blowup")), band[1], band[2])

    def _overflow(self, history, pack, out: dict) -> dict:
        """Top-rung frontier overflow: a DFS needs only one witness
        path where the BFS carries the whole frontier, so the (native)
        CPU oracle goes first; the budgeted spill BFS remains the
        *complete* last resort when the DFS exhausts its budget."""
        from ..ops import wgl
        resume = out.pop("_resume", None)
        cpu = self._fallback(history, out.get("reason", "overflow"))
        if cpu["valid?"] != "unknown":
            return cpu
        if resume is not None:
            # resume the spill from the frozen frontier — the ladder
            # waves already run are never redone
            out2 = wgl.spill_packed(pack, *resume)
        else:
            out2 = wgl.check_packed(pack, f_max=self.f_max, spill=True)
        # no _finalize here: the DFS just exhausted its budget, so
        # re-running it for counterexample diagnostics would duplicate
        # that cost and stamp its budget error onto a sound verdict
        out2["checker"] = "tpu-wgl"
        if out2["valid?"] == "unknown":
            out2["dfs-also-unknown"] = True
        return out2

    def _fallback_budget(self, blowup: bool) -> int:
        """The ONE definition of what _fallback spends: blowup (the
        packer proved the space astronomical) gets the cheap shot, else
        the full budget. _fallback_after_band's verdict-reuse compare
        must use exactly this number or its dedupe silently diverges."""
        return self.CUTOFF_MAX_CONFIGS if blowup \
            else self.FALLBACK_MAX_CONFIGS

    def _fallback(self, history, reason: str,
                  blowup: bool = False) -> dict:
        if not self.fallback:
            return {"valid?": "unknown", "reason": reason,
                    "checker": "tpu-wgl"}
        logger.debug("TPU path unavailable (%s); CPU oracle", reason)
        # blowup: the DFS oracle almost certainly can't finish either —
        # give it a cheap shot (it can still find a witness for valid
        # histories fast) instead of burning the full budget per key
        out = check_history(self.model_fn(), history,
                            max_configs=self._fallback_budget(blowup))
        out["checker"] = "cpu-oracle"
        out["tpu-fallback-reason"] = reason
        return out

    def _fallback_after_band(self, history, reason: str, blowup: bool,
                             small_unknown: Optional[dict],
                             band_budget: int) -> dict:
        """The kernel can't take this history; fall back to the CPU —
        but skip the fallback DFS when the band search already spent at
        least what _fallback would (dedupe), and escalate to the full
        budget when the band's size-scaled budget was smaller (a tiny
        band budget must not replace the 5M-config fallback verdict)."""
        if small_unknown is not None and \
                band_budget >= self._fallback_budget(blowup):
            small_unknown["tpu-fallback-reason"] = reason
            return small_unknown
        return self._fallback(history, reason, blowup=blowup)

    def check(self, test, history, opts=None, _band=None) -> dict:
        return _tally_engine(self._check(test, history, opts, _band))

    def _check(self, test, history, opts=None, _band=None) -> dict:
        from ..ops import wgl
        small, small_unknown, band_budget = \
            self._small_history_check(history) if _band is None else _band
        if small is not None:
            return small
        pack = self._pack_fn()
        if pack is None:
            return self._fallback_after_band(
                history, "model has no kernel packing", False,
                small_unknown, band_budget)
        with telemetry.current().span("wgl.pack", ops=len(history)):
            p = pack(history)
        if not p.ok:
            return self._fallback_after_band(
                history, p.reason, bool(p.blowup),
                small_unknown, band_budget)
        if p.I > 0 and self.cpu_cutoff and small_unknown is None \
                and len(history) > (self.dfs_first_max or 0):
            # info-op histories can't run fused, and the jnp ladder is
            # MEASURED ~50x slower than the native DFS on them (r5,
            # R=3068 / I=26 faulted key: ladder 4.1 s warm — 187 s with
            # its per-(C, NI) compile — vs DFS 0.08 s), so the DFS-first
            # band extends to ANY size when infos are present; the
            # ladder stays the complete last resort
            cpu = check_history(self.model_fn(), history,
                                max_configs=self.FALLBACK_MAX_CONFIGS)
            if cpu["valid?"] != "unknown":
                cpu["checker"] = "cpu-oracle"
                cpu["engine-route"] = "info-dfs-first"
                return cpu
            small_unknown, band_budget = cpu, self.FALLBACK_MAX_CONFIGS
        # with a fallback available, defer the spill BFS until the DFS
        # has had its (cheaper) shot — see _overflow. The service path
        # rides the same deferral (its batch runs spill=False), so it
        # only engages when a fallback exists to match semantics.
        out = None
        svc_tried = self.f_max is None and self.fallback
        if svc_tried:
            svc_outs = self._service_check(test, [p])
            if svc_outs is not None:
                out = svc_outs[0]
        if out is None:
            device = None
            if svc_tried:
                # service-down fallback: land the dispatch on the chip
                # the service's sticky placement map would have picked
                # (fallback_device_for counts it per device) instead of
                # re-serializing onto device 0
                from ..runner import checker_service as svc
                if svc.endpoint_for(test) is not None:
                    dev_for = svc.fallback_device_for(
                        telemetry.current())
                    if dev_for is not None:
                        device = dev_for(wgl.group_key(p))
            out = wgl.check_packed(p, f_max=self.f_max,
                                   spill=not self.fallback,
                                   device=device)
        return self._finalize(history, out, pack=p,
                              band=(None, small_unknown, band_budget))

    def check_batch(self, test, subhistories: dict, opts=None) -> dict:
        """Check many per-key histories in one vmapped, mesh-sharded
        kernel launch (the production form of SURVEY §2.3's key-level
        DP axis). Called by checkers.Independent; falls back per key."""
        from ..ops import wgl
        results: dict = {}
        # size-cutoff first: keys whose histories the native DFS answers
        # in ms never pay packing or dispatch at all
        big_keys = []
        bands: dict = {}
        # the mid-size band only pays in a batch when FEW keys would
        # actually reach the kernel launch: the launch amortizes
        # dispatch across those keys, so a per-key serial DFS over many
        # mid-size keys costs O(keys) against the launch's O(1) — but
        # for a handful the DFS's near-linear witness search wins.
        # With the batched SoA packer the old ~1,000 entries/key batch
        # crossover (BATCH_DFS_MAX, r5) is gone — its limiting term was
        # the per-key Python packing floor, now amortized across the
        # batch — so the band collapses to CPU_CUTOFF: any key past the
        # single-key native-DFS cutoff joins the fused launch.
        mid_count = sum(1 for h in subhistories.values()
                        if len(h) > (self.cpu_cutoff or 0))
        batch_band = None if mid_count <= 8 else (self.cpu_cutoff or 0)
        for k in subhistories:
            band = self._small_history_check(subhistories[k],
                                             band=batch_band)
            if band[0] is not None:
                results[k] = _tally_engine(band[0])
            else:
                big_keys.append(k)
                bands[k] = band
        if not big_keys:
            return results
        pack_batch = self._pack_batch_fn()
        if pack_batch is None:
            results.update({k: self.check(test, subhistories[k], opts,
                                          _band=bands[k])
                            for k in big_keys})
            return results
        # pack ALL remaining keys in one batched SoA pass (vectorized
        # across keys — the per-key Python packing floor that used to
        # lose this cell to the native sweep is gone), launch all fused
        # (bucket, width) groups asynchronously, then collect with one
        # synchronization — the only batching that pays on the measured
        # cost model (each extra launch costs ~57 ms fixed, so fewer,
        # larger dispatches always win over finer overlapped chunks
        # through the tunnel). Launch and collect ride the shared
        # _run_fused guard: the TPU-backend check, the
        # JEPSEN_ETCD_TPU_NO_PALLAS_WGL kill switch, and
        # degrade-don't-crash on Mosaic failures all apply to this
        # production path exactly as inside check_packed_batch.
        from ..ops import wgl_mxu
        packs_hint = (opts or {}).get("_stream_packs")
        if packs_hint is not None and \
                all(k in packs_hint for k in big_keys):
            # streaming reuse: the feed already packed every key from
            # the same op stream (per-key pack independence is pinned by
            # tests/test_wgl_batch_pack.py, so selecting this subset is
            # exactly what pack_batch would have produced)
            telemetry.current().counter("stream.pack_reuse",
                                        len(big_keys))
            packed = {k: packs_hint[k] for k in big_keys}
        else:
            with telemetry.current().span("wgl.pack-batch",
                                          keys=len(big_keys)):
                packed = pack_batch({k: subhistories[k]
                                     for k in big_keys})
        packs = [packed[k] for k in big_keys]
        outs: list = [None] * len(big_keys)
        # campaign mode: the checker service owns the device and
        # coalesces these packs with every other run's pending work
        # into one dispatch per (bucket, width) per tick — the batch
        # axis extended ACROSS runs. Absent/dead service: None, and
        # the in-process path below runs unchanged.
        svc_outs = self._service_check(test, packs) \
            if self.f_max is None else None
        if svc_outs is not None:
            outs = svc_outs
        else:
            device_for = None
            if self.f_max is None:
                from ..runner import checker_service as svc
                if svc.endpoint_for(test) is not None:
                    # service-down fallback: honor the same sticky
                    # group→device placement the service dispatcher
                    # runs (counted per device as service.fallback.*)
                    # instead of re-serializing onto device 0
                    device_for = svc.fallback_device_for(
                        telemetry.current())
                launched = wgl._run_fused(
                    wgl._mxu_broken, "mxu batch",
                    lambda: wgl_mxu.launch_packed_batch_mxu(packs))
                if launched:
                    wgl._run_fused(
                        wgl._mxu_broken, "mxu batch",
                        lambda: wgl_mxu.collect_packed_batch_mxu(
                            launched, outs))
            # keys the fused path couldn't take (unsupported shapes,
            # frontier overflow) ride the jnp ladder batch as before
            rest = [i for i, out in enumerate(outs)
                    if out is None or out.get("overflow")]
            if rest:
                rest_outs = wgl.check_packed_batch(
                    [packs[i] for i in rest], f_max=self.f_max,
                    try_fused=False, device_for=device_for)
                for i, out in zip(rest, rest_outs):
                    outs[i] = out
        # unpackable keys come back "unknown" with the pack reason;
        # _finalize routes those through the CPU fallback (and top-rung
        # overflows through the DFS-then-spill ordering), skipping any
        # DFS the band already ran at sufficient budget
        results.update({k: _tally_engine(
                            self._finalize(subhistories[k], out, pack=p,
                                           band=bands[k]))
                        for (k, out, p) in zip(big_keys, outs, packs)})
        return results


def tpu_linearizable(model_fn=None) -> TPULinearizableChecker:
    return TPULinearizableChecker(model_fn)
