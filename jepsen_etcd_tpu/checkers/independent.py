"""Per-key checker decomposition (jepsen.independent/checker,
register.clj:108-113).

Splits a (key, value)-tuple history into per-key sub-histories and runs
the wrapped checker on each. Sub-histories preserve op indices, so
reports point back into the full history. This is the host-side half of
the key-level data parallelism; TPU checkers batch the same split into a
padded tensor and vmap over it (SURVEY §2.3).
"""

from __future__ import annotations

from ..core.history import History
from ..runner import telemetry
from .core import Checker, _merge_valid, stream_hint


class Independent(Checker):
    def __init__(self, inner: Checker):
        self.inner = inner

    def check(self, test, history, opts=None) -> dict:
        h = history if isinstance(history, History) else History(history)
        # streaming reuse: the feed's per-key register packs were
        # extracted from this exact op stream (row-count + columns
        # guard in stream_hint). Validated HERE — the only place the
        # parent history is visible — and handed down via opts so the
        # batch checker can skip its own pack pass key-for-key.
        packs = stream_hint(test, h, "register_packs")
        if packs is not None:
            opts = dict(opts or {})
            opts["_stream_packs"] = packs
        # one pass over the parent history builds every per-key
        # subhistory (the per-key subhistory() loop re-scans the full
        # history once per key — O(K * N) host time the batched packer
        # axis can't afford). Recorded histories carry SoA columns, so
        # the split is a grouped array slice and the per-key histories
        # stay column-backed all the way into the batched packer — no
        # per-op dict access on this path (guarded by the
        # dict_materializations test in tests/test_history.py).
        subs = h.split_by_key()
        # the run's key fanout: how many per-key checks this split
        # produced — the producer side of the batching axis (within a
        # run here; across runs when a campaign checker service
        # coalesces many runs' keys into shared ticks, PERF.md
        # §campaign)
        telemetry.current().counter("independent.keys", len(subs))
        if hasattr(self.inner, "check_batch"):
            # batch-aware inner checker (TPULinearizableChecker): one
            # vmapped kernel launch over the whole key batch, sharded
            # over the device mesh — not a serial per-key loop
            results = self.inner.check_batch(test, subs, opts)
        else:
            results = {k: self.inner.check(test, sub, opts)
                       for k, sub in subs.items()}
        return {
            "valid?": _merge_valid([r.get("valid?")
                                    for r in results.values()]),
            "key-count": len(results),
            "results": results,
        }


def independent_checker(inner: Checker) -> Independent:
    return Independent(inner)
