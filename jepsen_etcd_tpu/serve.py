"""``serve``: browse saved test runs over local HTTP.

Re-designs the reference's ``lein run serve`` (etcd.clj:250-252, jepsen's
built-in web server): ``/`` renders a run index (name, time, ops,
valid? badge); each run dir renders a report page — test parameters,
per-checker verdicts, inline perf/clock plots, artifact links — with
plain file serving below it (``?files`` forces the raw listing).
"""

from __future__ import annotations

import html
import json
import os
import time
from http.server import SimpleHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import quote

_CSS = """
body{font-family:sans-serif;margin:2em;max-width:70em}
table{border-collapse:collapse}
td,th{border:1px solid #ccc;padding:4px 8px;text-align:left}
.ok{color:#2a2;font-weight:bold}
.bad{color:#c22;font-weight:bold}
.unk{color:#b80;font-weight:bold}
img{max-width:100%;border:1px solid #ddd;margin:4px 0}
code{background:#f4f4f4;padding:1px 4px}
"""


def _badge(v) -> str:
    cls = {"True": "ok", True: "ok", False: "bad", "False": "bad"}.get(
        v, "unk")
    return f'<span class="{cls}">{html.escape(str(v))}</span>'


def _load_json(path: str):
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError, ValueError):
        return None


def _run_rows(store_base: str) -> list[dict]:
    from .forensics import all_runs
    rows = []
    for rdir in all_runs(store_base):
        rel = os.path.relpath(rdir, store_base)
        results = _load_json(os.path.join(rdir, "results.json")) or {}
        test = _load_json(os.path.join(rdir, "test.json")) or {}
        try:
            mtime = os.path.getmtime(rdir)
        except OSError:
            mtime = 0
        ops = (results.get("stats") or {}).get("count")
        rows.append({"dir": rel, "mtime": mtime,
                     "valid?": results.get("valid?", "?"),
                     "name": test.get("name", rel.split(os.sep)[0]),
                     "time_limit": test.get("time_limit"),
                     "ops": ops})
    rows.sort(key=lambda r: r["mtime"], reverse=True)
    return rows


def index_html(store_base: str) -> str:
    rows = []
    for r in _run_rows(store_base):
        when = time.strftime("%Y-%m-%d %H:%M:%S",
                             time.localtime(r["mtime"]))
        rows.append(
            f'<tr><td><a href="/{quote(r["dir"])}/">'
            f'{html.escape(r["dir"])}</a></td>'
            f"<td>{html.escape(when)}</td>"
            f"<td>{_badge(r['valid?'])}</td>"
            f"<td>{r['ops'] if r['ops'] is not None else ''}</td></tr>")
    return (f"<!doctype html><title>jepsen_etcd_tpu store</title>"
            f"<style>{_CSS}</style>"
            "<h1>Test runs</h1>"
            "<table><tr><th>run</th><th>time</th>"
            "<th>valid?</th><th>ops</th></tr>"
            + "".join(rows) + "</table>")


#: test.json keys shown in the run page's parameter table, in order
_PARAM_KEYS = ("workload", "nemesis_spec", "nemesis_interval",
               "time_limit", "rate", "ops_per_key", "concurrency",
               "serializable", "lazyfs", "client_type", "snapshot_count",
               "unsafe_no_fsync", "corrupt_check", "version", "seed",
               "nodes")


def run_html(store_base: str, rel: str) -> str:
    """The per-run report page (jepsen's run view: params, checker
    verdicts, plots, artifacts)."""
    rdir = os.path.join(store_base, rel)
    results = _load_json(os.path.join(rdir, "results.json")) or {}
    test = _load_json(os.path.join(rdir, "test.json")) or {}
    out = [f"<!doctype html><title>{html.escape(rel)}</title>",
           f"<style>{_CSS}</style>",
           f'<p><a href="/">&larr; all runs</a> &middot; '
           f'<a href="/{quote(rel)}/?files">raw files</a></p>',
           f"<h1>{html.escape(test.get('name', rel))} "
           f"{_badge(results.get('valid?', '?'))}</h1>"]
    # parameters
    params = [(k, test[k]) for k in _PARAM_KEYS if k in test]
    if params:
        out.append("<h2>Parameters</h2><table>")
        out.extend(
            f"<tr><th>{html.escape(k)}</th>"
            f"<td><code>{html.escape(json.dumps(v))}</code></td></tr>"
            for k, v in params)
        out.append("</table>")
    # checker verdicts
    checkers = [(k, v) for k, v in sorted(results.items())
                if isinstance(v, dict) and "valid?" in v]
    if checkers:
        out.append("<h2>Checkers</h2><table>"
                   "<tr><th>checker</th><th>valid?</th><th>detail</th></tr>")
        for k, v in checkers:
            detail = {dk: dv for dk, dv in v.items() if dk != "valid?"}
            blob = html.escape(json.dumps(detail, default=repr)[:2000])
            out.append(f"<tr><td>{html.escape(k)}</td>"
                       f"<td>{_badge(v.get('valid?'))}</td>"
                       f"<td><code>{blob}</code></td></tr>")
        out.append("</table>")
    # plots inline
    plots = [f for f in ("latency-raw.png", "rate.png", "clock.png")
             if os.path.exists(os.path.join(rdir, f))]
    if plots:
        out.append("<h2>Plots</h2>")
        out.extend(f'<img src="/{quote(rel)}/{quote(f)}" alt="{f}">'
                   for f in plots)
    # artifacts
    out.append("<h2>Artifacts</h2><ul>")
    for fn in sorted(os.listdir(rdir)):
        p = os.path.join(rdir, fn)
        label = fn + ("/" if os.path.isdir(p) else "")
        out.append(f'<li><a href="/{quote(rel)}/{quote(fn)}">'
                   f"{html.escape(label)}</a></li>")
    out.append("</ul>")
    return "".join(out)


class StoreHandler(SimpleHTTPRequestHandler):
    """Serves the store dir; '/' renders the run index, run dirs render
    report pages (?files for the raw listing)."""

    store_base = "store"

    def __init__(self, *args, **kw):
        super().__init__(*args, directory=self.store_base, **kw)

    def _html(self, body: str) -> None:
        data = body.encode()
        self.send_response(200)
        self.send_header("Content-Type", "text/html; charset=utf-8")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self):
        from urllib.parse import parse_qs
        path, _, query = self.path.partition("?")
        if path in ("/", "/index.html"):
            return self._html(index_html(self.store_base))
        want_files = "files" in parse_qs(query, keep_blank_values=True)
        if path.endswith("/") and not want_files:
            rel = os.path.normpath(path.strip("/"))
            rdir = os.path.join(self.store_base, rel)
            # only render report pages for real run dirs inside the store
            if not rel.startswith("..") and \
                    os.path.exists(os.path.join(rdir, "results.json")):
                return self._html(run_html(self.store_base, rel))
        super().do_GET()

    def log_message(self, fmt, *args):  # quiet by default
        pass


def make_server(store_base: str, port: int = 0,
                bind: str = "127.0.0.1") -> ThreadingHTTPServer:
    handler = type("Handler", (StoreHandler,), {"store_base": store_base})
    return ThreadingHTTPServer((bind, port), handler)


def serve_store(store_base: str, port: int = 8080,
                bind: str = "127.0.0.1") -> int:
    srv = make_server(store_base, port, bind)
    host, p = srv.server_address[:2]
    print(f"Serving {store_base} at http://{host}:{p}/ (ctrl-c to stop)")
    try:
        srv.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        srv.server_close()
    return 0
