"""``serve``: browse saved test runs over local HTTP.

Re-designs the reference's ``lein run serve`` (etcd.clj:250-252, jepsen's
built-in web server): ``/`` renders a run index (name, time, ops,
valid? badge); each run dir renders a report page — test parameters,
per-checker verdicts, telemetry phase/counter summary, inline
perf/clock plots, artifact links — with plain file serving below it
(``?files`` forces the raw listing, ``?trace`` a trace.jsonl event
viewer). ``/aggregate`` is the cross-run dashboard: a pass/fail matrix
over workload × nemesis × db, per-run phase-breakdown bars from
telemetry, and failure dedupe by checker verdict signature — the seed
of the campaign summary page (ROADMAP direction 2).
"""

from __future__ import annotations

import html
import json
import os
import time
from http.server import SimpleHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import quote

_CSS = """
body{font-family:sans-serif;margin:2em;max-width:75em}
table{border-collapse:collapse}
td,th{border:1px solid #ccc;padding:4px 8px;text-align:left}
.ok{color:#2a2;font-weight:bold}
.bad{color:#c22;font-weight:bold}
.unk{color:#b80;font-weight:bold}
img{max-width:100%;border:1px solid #ddd;margin:4px 0}
code{background:#f4f4f4;padding:1px 4px}
.bar{display:inline-block;height:12px;vertical-align:middle}
.barbox{display:inline-block;width:320px;background:#f4f4f4;
    border:1px solid #ddd;font-size:0;line-height:0}
.dim{color:#888}
"""

#: run-phase display order and bar colors (phases map keys come from
#: runner/telemetry.py's ``phase:<name>`` spans)
_PHASES = (("setup", "#9ab8d8"), ("generate", "#8fc98f"),
           ("stream-finalize", "#6fc4bc"), ("teardown", "#d8d8d8"),
           ("check", "#e0a848"), ("save", "#b8a0d0"))


def _badge(v) -> str:
    cls = {"True": "ok", True: "ok", False: "bad", "False": "bad"}.get(
        v, "unk")
    return f'<span class="{cls}">{html.escape(str(v))}</span>'


def _load_json(path: str):
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError, ValueError):
        return None


# Row derivation lives in runner/store_index.py now: the index writer
# and these walk fallbacks call the SAME builders, so index-backed
# pages replay bit-identically to a fresh tree walk. The old private
# names stay importable (tel_cli and shrink import _failure_signature;
# the canonical implementation is runner/store.failure_signature).
from .runner.store import failure_signature as _failure_signature  # noqa: E402
from .runner.store_index import (  # noqa: E402
    SURFACES as _SURFACES,
    chip_util as _chip_util,
    consistency_surface as _consistency_surface,
    host_ledger as _host_ledger,
    overlap_ratio as _overlap_ratio,
)


def _run_rows(store_base: str) -> list[dict]:
    from .runner import store_index
    fold = store_index.fold(store_base)
    if fold is not None:
        return store_index.serve_run_rows(fold)
    from .forensics import all_runs
    rows = []
    for rdir in all_runs(store_base):
        rel = os.path.relpath(rdir, store_base)
        results = _load_json(os.path.join(rdir, "results.json")) or {}
        test = _load_json(os.path.join(rdir, "test.json")) or {}
        try:
            mtime = os.path.getmtime(rdir)
        except OSError:
            mtime = 0
        rows.append(store_index.run_row(rel, results, test, mtime))
    rows.sort(key=lambda r: r["mtime"], reverse=True)
    return rows


def _campaign_rows(store_base: str) -> list[dict]:
    """Campaign summaries under the store: every
    ``<store>/<name>/<id>/campaign.json`` written by
    runner/campaign.run_campaign. (Campaign dirs carry no
    history.jsonl, so the run index never lists them — this is their
    only dashboard surface.) Sorted oldest-first: the table reads as a
    trend over successive campaigns."""
    from .runner import store_index
    fold = store_index.fold(store_base)
    if fold is not None:
        return store_index.serve_campaign_rows(fold)
    rows = []
    try:
        names = sorted(os.listdir(store_base))
    except OSError:
        return rows
    for name in names:
        ndir = os.path.join(store_base, name)
        if not os.path.isdir(ndir):
            continue
        try:
            ids = sorted(os.listdir(ndir))
        except OSError:
            continue
        for rid in ids:
            if os.path.islink(os.path.join(ndir, rid)):
                continue  # the `latest` convenience symlink
            cpath = os.path.join(ndir, rid, "campaign.json")
            summary = _load_json(cpath)
            if not isinstance(summary, dict) or "runs" not in summary:
                continue
            try:
                mtime = os.path.getmtime(cpath)
            except OSError:
                mtime = 0
            rows.append(store_index.campaign_row(
                os.path.join(name, rid), summary, mtime))
    rows.sort(key=lambda r: r["mtime"])
    return rows


def _guided_rows(store_base: str) -> list[dict]:
    """Guided-campaign summaries under the store: every
    ``<store>/<name>/<id>/guided.json`` written by
    runner/guided.run_guided. Same two-level walk as
    ``_campaign_rows`` (guided dirs carry no history.jsonl either).
    Sorted oldest-first."""
    from .runner import store_index
    fold = store_index.fold(store_base)
    if fold is not None:
        return store_index.serve_guided_rows(fold)
    rows = []
    try:
        names = sorted(os.listdir(store_base))
    except OSError:
        return rows
    for name in names:
        ndir = os.path.join(store_base, name)
        if not os.path.isdir(ndir):
            continue
        try:
            ids = sorted(os.listdir(ndir))
        except OSError:
            continue
        for rid in ids:
            if os.path.islink(os.path.join(ndir, rid)):
                continue  # the `latest` convenience symlink
            gpath = os.path.join(ndir, rid, "guided.json")
            summary = _load_json(gpath)
            if not isinstance(summary, dict) or \
                    summary.get("kind") != "guided":
                continue
            try:
                mtime = os.path.getmtime(gpath)
            except OSError:
                mtime = 0
            rows.append(store_index.guided_row(
                os.path.join(name, rid), summary, mtime))
    rows.sort(key=lambda r: r["mtime"])
    return rows


def _shrink_rows(store_base: str) -> list[dict]:
    """Minimized-repro artifacts: every run dir carrying a
    ``shrink.json`` written by runner/shrink.shrink_run. A full walk,
    not forensics.all_runs — guided campaigns nest their runs one
    level deeper (``<store>/<name>/<id>/gen<N>/<run>``) than the
    two-level run index. Newest first."""
    from .runner import store_index
    fold = store_index.fold(store_base)
    if fold is not None:
        return store_index.serve_shrink_rows(fold, store_base)
    rows = []
    for root, dirs, files in os.walk(store_base, followlinks=False):
        dirs[:] = [d for d in dirs
                   if not os.path.islink(os.path.join(root, d))]
        if "shrink.json" not in files:
            continue
        rdir = root
        art = _load_json(os.path.join(rdir, "shrink.json"))
        if not isinstance(art, dict) or "signature" not in art:
            continue
        try:
            mtime = os.path.getmtime(os.path.join(rdir, "shrink.json"))
        except OSError:
            mtime = 0
        rows.append(store_index.shrink_row(
            os.path.relpath(rdir, store_base), art, mtime))
    rows.sort(key=lambda r: r["mtime"], reverse=True)
    return rows


def _fmt_s(v) -> str:
    """Compact seconds: us/ms/s by magnitude."""
    if not isinstance(v, (int, float)):
        return "—"
    if v < 1e-3:
        return f"{v * 1e6:.0f}us"
    if v < 1.0:
        return f"{v * 1e3:.1f}ms"
    return f"{v:.2f}s"


def _percentile_cell(p: dict) -> str:
    """One table cell of p95 gen/check/queue-wait (p50 and p99 in the
    title), from a campaign summary's merged-histogram ``p`` map."""
    labels = ("gen", "check", "queue_wait")
    if not any(isinstance(p.get(k), list) and len(p[k]) == 3
               for k in labels):
        return "<td class='dim'>—</td>"
    shown, titled = [], []
    for k in labels:
        tri = p.get(k)
        if isinstance(tri, list) and len(tri) == 3:
            shown.append(_fmt_s(tri[1]))
            titled.append(f"{k}: p50 {_fmt_s(tri[0])}, "
                          f"p95 {_fmt_s(tri[1])}, p99 {_fmt_s(tri[2])}")
        else:
            shown.append("—")
    return (f"<td title='{html.escape('; '.join(titled))}'>"
            + "&thinsp;/&thinsp;".join(shown) + "</td>")


def _phase_bar(phases: dict) -> str:
    """A stacked horizontal bar of the run's phase wall times."""
    total = sum(v for v in phases.values()
                if isinstance(v, (int, float)))
    if not total:
        return "<span class='dim'>no telemetry</span>"
    segs = []
    for name, color in _PHASES:
        v = phases.get(name)
        if not v:
            continue
        pct = 100.0 * v / total
        segs.append(
            f"<span class='bar' style='width:{pct:.2f}%;"
            f"background:{color}' "
            f"title='{html.escape(name)}: {v:.3f}s "
            f"({pct:.0f}%)'></span>")
    return (f"<span class='barbox'>{''.join(segs)}</span> "
            f"<span class='dim'>{total:.2f}s</span>")


def index_html(store_base: str) -> str:
    rows = []
    for r in _run_rows(store_base):
        when = time.strftime("%Y-%m-%d %H:%M:%S",
                             time.localtime(r["mtime"]))
        rows.append(
            f'<tr><td><a href="/{quote(r["dir"])}/">'
            f'{html.escape(r["dir"])}</a></td>'
            f"<td>{html.escape(when)}</td>"
            f"<td>{_badge(r['valid?'])}</td>"
            f"<td>{r['ops'] if r['ops'] is not None else ''}</td></tr>")
    return (f"<!doctype html><title>jepsen_etcd_tpu store</title>"
            f"<style>{_CSS}</style>"
            "<h1>Test runs</h1>"
            '<p><a href="/aggregate">cross-run dashboard &rarr;</a></p>'
            "<table><tr><th>run</th><th>time</th>"
            "<th>valid?</th><th>ops</th></tr>"
            + "".join(rows) + "</table>")


#: /aggregate pagination: the pass/fail matrix always aggregates ALL
#: runs, but the per-run phase table and the failure tables window at
#: ``per`` rows (?page=/?per=) so a 10k-run store renders flat
_DEF_PER = 200
_MAX_PER = 1000

#: per-process render cache for index-backed /aggregate pages:
#: (base, page, per) -> (fold generation vector, html). Unindexed
#: stores are never cached — there is no cheap invalidation signal.
_AGG_CACHE: dict = {}


def _agg_gens(store_base: str):
    """Generation vector covering every fold /aggregate reads: the
    base index plus each guided sub-index feeding the shrink table.
    Any committed index write bumps a component. None when the store
    is unindexed."""
    from .runner import store_index
    fold = store_index.fold(store_base)
    if fold is None:
        return None
    gens = [fold.gen]
    for d in store_index.kind_dirs(fold, "guided"):
        sub = store_index.fold(os.path.join(store_base, d))
        gens.append(-1 if sub is None else sub.gen)
    return tuple(gens)


def _page_window(total: int, page, per):
    """Clamped (lo, hi, page, pages, per) for one table's window."""
    try:
        per = int(per) if per else _DEF_PER
    except (TypeError, ValueError):
        per = _DEF_PER
    per = max(1, min(per, _MAX_PER))
    try:
        page = int(page) if page else 1
    except (TypeError, ValueError):
        page = 1
    pages = max(1, -(-total // per))
    page = max(1, min(page, pages))
    lo = (page - 1) * per
    return lo, min(lo + per, total), page, pages, per


def _pager(lo: int, hi: int, page: int, pages: int, per: int,
           total: int) -> str:
    if pages <= 1:
        return ""
    bits = [f"<p class='dim'>rows {lo + 1}–{hi} of {total} · "]
    if page > 1:
        bits.append(f'<a href="/aggregate?page={page - 1}'
                    f'&amp;per={per}">&larr; prev</a> · ')
    bits.append(f"page {page}/{pages}")
    if page < pages:
        bits.append(f' · <a href="/aggregate?page={page + 1}'
                    f'&amp;per={per}">next &rarr;</a>')
    bits.append("</p>")
    return "".join(bits)


def aggregate_html(store_base: str, page=1, per=None) -> str:
    """The cross-run dashboard: pass/fail matrix over workload ×
    (nemesis, db), per-run telemetry phase bars, and failure dedupe by
    checker verdict signature. The per-run and failure tables window
    at ``per`` rows (?page=/?per=); index-backed renders are cached
    per (page, per) until the index generation moves."""
    gens = _agg_gens(store_base)
    cache_key = (os.path.abspath(store_base), page, per)
    if gens is not None:
        hit = _AGG_CACHE.get(cache_key)
        if hit is not None and hit[0] == gens:
            return hit[1]
    rows = _run_rows(store_base)
    out = [f"<!doctype html><title>aggregate — jepsen_etcd_tpu</title>",
           f"<style>{_CSS}</style>",
           '<p><a href="/">&larr; all runs</a></p>',
           f"<h1>Cross-run dashboard</h1>",
           f"<p>{len(rows)} runs</p>"]

    # -- pass/fail matrix: workload rows × (nemesis, db) columns -------------
    cols = sorted({(r["nemesis"], r["db"]) for r in rows})
    workloads = sorted({r["workload"] for r in rows}, key=str)
    cells: dict = {}
    for r in rows:
        cells.setdefault(
            (r["workload"], (r["nemesis"], r["db"])), []).append(r)
    out.append("<h2>Pass/fail matrix</h2><table><tr><th>workload</th>")
    out.extend(f"<th>{html.escape(str(n))}<br>"
               f"<span class='dim'>{html.escape(str(d))}</span></th>"
               for n, d in cols)
    out.append("</tr>")
    for w in workloads:
        out.append(f"<tr><th>{html.escape(str(w))}</th>")
        for c in cols:
            runs = cells.get((w, c), [])
            if not runs:
                out.append("<td class='dim'>—</td>")
                continue
            npass = sum(1 for r in runs if r["valid?"] is True)
            nfail = sum(1 for r in runs if r["valid?"] is False)
            nunk = len(runs) - npass - nfail
            bits = []
            if npass:
                bits.append(f"<span class='ok'>{npass}&nbsp;pass</span>")
            if nfail:
                bits.append(f"<span class='bad'>{nfail}&nbsp;fail</span>")
            if nunk:
                bits.append(f"<span class='unk'>{nunk}&nbsp;unk</span>")
            links = " ".join(
                f'<a href="/{quote(r["dir"])}/">'
                f'{html.escape(os.path.basename(r["dir"]))}</a>'
                for r in runs[:8])
            out.append(f"<td>{' '.join(bits)}<br>"
                       f"<span class='dim'>{links}</span></td>")
        out.append("</tr>")
    out.append("</table>")

    # -- per-run phase breakdown bars ----------------------------------------
    lo, hi, pg, pages, per_n = _page_window(len(rows), page, per)
    out.append("<h2>Phase breakdown (wall time per run)</h2>")
    out.append(_pager(lo, hi, pg, pages, per_n, len(rows)))
    out.append("<table><tr><th>run</th><th>valid?</th>"
               "<th>consistency</th>"
               "<th>gen ops/s</th><th>e2e/gen</th><th>phases</th></tr>")
    for r in rows[lo:hi]:
        rate = r.get("gen_rate")
        rate_td = (f"<td>{rate:,.0f}</td>"
                   if isinstance(rate, (int, float))
                   else "<td class='dim'>—</td>")
        ov = r.get("overlap")
        # streamed runs only: how much wall time verification added on
        # top of generation (1.00x = checking came free)
        ov_td = (f"<td title='(generate + stream-finalize + check) / "
                 f"generate'>{ov:.2f}&times;</td>"
                 if isinstance(ov, (int, float))
                 else "<td class='dim'>—</td>")
        surf = r.get("consistency") or {}
        if surf:
            # per-surface verdicts of the MVCC consistency checkers
            # (checkers/mvcc.py) composed into this run's workload
            surf_td = "<td>" + " ".join(
                f"{html.escape(label)}&nbsp;{_badge(s['valid'])}"
                + (f"<span class='bad'>({s['violations']})</span>"
                   if s["violations"] else "")
                for label, s in surf.items()) + "</td>"
        else:
            surf_td = "<td class='dim'>—</td>"
        out.append(
            f'<tr><td><a href="/{quote(r["dir"])}/">'
            f'{html.escape(r["dir"])}</a></td>'
            f"<td>{_badge(r['valid?'])}</td>"
            f"{surf_td}"
            f"{rate_td}{ov_td}"
            f"<td>{_phase_bar(r['phases'])}</td></tr>")
    out.append("</table><p class='dim'>"
               + " ".join(f"<span class='bar' style='width:12px;"
                          f"background:{c}'></span> {html.escape(n)}"
                          for n, c in _PHASES) + "</p>")

    # -- campaign perf trends across rounds ----------------------------------
    camps = _campaign_rows(store_base)
    if camps:
        out.append(
            "<h2>Campaign perf trends</h2>"
            "<p class='dim'>successive campaigns, oldest first — "
            "dispatch amortization is submitted packs vs batched "
            "device dispatches (1 per (bucket, width, tick); "
            "PERF.md §campaign)</p>"
            "<table><tr><th>campaign</th><th>time</th><th>runs</th>"
            "<th>pool</th><th>valid?</th><th>wall</th>"
            "<th>gen ops/s</th><th>batched gen ops/s</th>"
            "<th>check wall</th>"
            "<th>p95 gen/check/queue</th><th>net</th>"
            "<th>dispatches</th><th>amortization</th>"
            "<th>chips</th><th>hosts</th></tr>")
        for c in camps:
            when = time.strftime("%Y-%m-%d %H:%M",
                                 time.localtime(c["mtime"]))
            rate = c["gen_rate"]
            rate_td = (f"<td>{rate:,.0f}</td>"
                       if isinstance(rate, (int, float))
                       else "<td class='dim'>—</td>")
            gb = c.get("genbatch") or {}
            gb_rate = gb.get("ops_per_s")
            gb_td = (f"<td title='{gb.get('seeds')} seeds over "
                     f"{gb.get('cells')} cells, {gb.get('epoch')}'>"
                     f"{gb_rate:,.0f}</td>"
                     if isinstance(gb_rate, (int, float)) and gb_rate
                     else "<td class='dim'>—</td>")
            p_td = _percentile_cell(c.get("p") or {})
            net = c.get("net") or {}
            if any(net.values()):
                net_td = (
                    "<td title='dropped chunks / accept errors / "
                    "delayed bytes (net.* counters)'>"
                    f"{net.get('dropped_chunks', 0)}&thinsp;/&thinsp;"
                    f"{net.get('accept_errors', 0)}&thinsp;/&thinsp;"
                    f"{net.get('delayed_bytes', 0)}</td>")
            else:
                net_td = "<td class='dim'>—</td>"
            if c["submitted"]:
                amort = (f"{c['submitted']} packs &rarr; "
                         f"{c['group_ticks']} dispatches, "
                         f"occupancy&nbsp;{c['occupancy']}")
                if c["fallbacks"]:
                    amort += (f" <span class='bad'>"
                              f"({c['fallbacks']} fallbacks)</span>")
            else:
                amort = "<span class='dim'>per-run checking</span>"
            chips = c.get("chips")
            if chips:
                bal = chips.get("balance")
                bal_s = (f"{bal:.1f}&times;"
                         if isinstance(bal, (int, float)) else "&infin;")
                title = ", ".join(
                    f"{d}: {n} dispatches"
                    f" ({_fmt_s(chips['busy_s'].get(d))} busy)"
                    for d, n in sorted(chips["dispatches"].items()))
                occ = chips.get("occupancy")
                sh = chips.get("sharded_ticks")
                chips_td = (
                    f"<td title='{html.escape(title)}'>"
                    f"{chips['devices']} chips, "
                    f"occ&nbsp;{occ if occ is not None else '?'}, "
                    f"balance&nbsp;{bal_s}"
                    + (f", {sh} sharded" if sh else "") + "</td>")
            else:
                chips_td = "<td class='dim'>—</td>"
            hosts = c.get("hosts")
            if hosts:
                # the ledger join per host: rows' shipped (producer)
                # vs the service's host_submitted (consumer)
                balanced = all(
                    st.get("submitted") is None
                    or st.get("shipped") == st.get("submitted")
                    for st in hosts.values())
                title = ", ".join(
                    f"{h}: {st.get('runs', 0)} runs, "
                    f"shipped {st.get('shipped', 0)} vs "
                    f"submitted {st.get('submitted', '?')}"
                    for h, st in sorted(hosts.items()))
                rq = c.get("agent_requeues")
                hosts_td = (
                    f"<td title='{html.escape(title)}'>"
                    f"{len(hosts)} hosts, ledger "
                    + ("<span class='ok'>balanced</span>" if balanced
                       else "<span class='bad'>MISMATCH</span>")
                    + (f", {rq} requeues" if rq else "") + "</td>")
            else:
                hosts_td = "<td class='dim'>—</td>"
            out.append(
                f'<tr><td><a href="/{quote(c["dir"])}/?files">'
                f'{html.escape(c["dir"])}</a></td>'
                f"<td>{html.escape(when)}</td>"
                f"<td>{c['count']}</td><td>{c['pool']}</td>"
                f"<td>{_badge(c['valid?'])}</td>"
                f"<td>{c['wall_s']}s</td>{rate_td}{gb_td}"
                f"<td>{c['check_s']:.2f}s</td>{p_td}{net_td}"
                f"<td>{c['dispatches']}</td><td>{amort}</td>"
                f"{chips_td}{hosts_td}</tr>")
        out.append("</table>")

    # -- guided campaigns ----------------------------------------------------
    guided = _guided_rows(store_base)
    if guided:
        out.append(
            "<h2>Guided campaigns</h2>"
            "<p class='dim'>coverage-guided fault search "
            "(campaign --guided N) — novelty-scored corpus evolution; "
            "first failing run and distinct verdict signatures per "
            "budget</p>"
            "<table><tr><th>campaign</th><th>time</th><th>budget</th>"
            "<th>runs</th><th>gens</th><th>first failure</th>"
            "<th>signatures</th><th>corpus</th><th>minimized</th>"
            "<th>wall</th></tr>")
        for g in guided:
            when = time.strftime("%Y-%m-%d %H:%M",
                                 time.localtime(g["mtime"]))
            ff = g["first_failure_run"]
            ff_td = (f"<td>run {ff}</td>" if isinstance(ff, int)
                     else "<td class='dim'>none</td>")
            sigs = g["signatures"]
            sig_td = (
                "<td title='"
                + html.escape("; ".join(
                    f"{s} @ run {r}" for s, r in sorted(sigs.items())))
                + f"'>{len(sigs)}</td>" if sigs
                else "<td class='dim'>0</td>")
            mins = g["minimized"]
            min_td = (f"<td>{len(mins)}</td>" if mins
                      else "<td class='dim'>0</td>")
            out.append(
                f'<tr><td><a href="/{quote(g["dir"])}/?files">'
                f'{html.escape(g["dir"])}</a></td>'
                f"<td>{html.escape(when)}</td>"
                f"<td>{g['budget']}</td><td>{g['runs']}</td>"
                f"<td>{g['generations']}</td>{ff_td}{sig_td}"
                f"<td>{g['corpus']}</td>{min_td}"
                f"<td>{g['wall_s']}s</td></tr>")
        out.append("</table>")

    # -- minimized repros ----------------------------------------------------
    shrunk = _shrink_rows(store_base)
    if shrunk:
        out.append(
            "<h2>Minimized repros</h2>"
            "<p class='dim'>delta-debugged failing schedules "
            "(runner/shrink) — smallest nemesis schedule that still "
            "reproduces the verdict signature, with its replay "
            "command</p>"
            "<table><tr><th>run</th><th>signature</th>"
            "<th>windows</th><th>nemesis ops</th><th>executions</th>"
            "<th>repro</th></tr>")
        for s in shrunk:
            out.append(
                f'<tr><td><a href="/{quote(s["dir"])}/">'
                f'{html.escape(s["dir"])}</a></td>'
                f"<td><code>{html.escape(str(s['signature']))}</code>"
                f"</td><td>{s['original_windows']}&rarr;"
                f"{s['windows']}</td>"
                f"<td>{s['nemesis_ops']}</td>"
                f"<td>{s['executions']}</td>"
                f"<td><code>{html.escape(str(s['repro']))}</code>"
                f"</td></tr>")
        out.append("</table>")

    # -- failure dedupe by verdict signature ---------------------------------
    # Runs with a checker signature are real verdicts; runs that
    # failed with no signature at all (crashed harness, truncated
    # results.json, setup errors) are infrastructure noise and get
    # their own section so verdict groups — and anything consuming
    # them, like guided's novelty scoring — never mix the two.
    failing = [r for r in rows if r["valid?"] is not True]
    verdicts = [r for r in failing if r["signature"]]
    infra = [r for r in failing if not r["signature"]]
    out.append("<h2>Failure dedupe</h2>")
    if not verdicts:
        out.append("<p class='ok'>no failing checker verdicts</p>")
    else:
        groups: dict = {}
        for r in verdicts:
            groups.setdefault(r["signature"], []).append(r)
        grouped = sorted(groups.items(), key=lambda kv: -len(kv[1]))
        glo, ghi, gpg, gpages, gper = _page_window(len(grouped),
                                                   page, per)
        out.append(_pager(glo, ghi, gpg, gpages, gper, len(grouped)))
        out.append("<table><tr><th>verdict signature</th>"
                   "<th>runs</th><th>dirs</th></tr>")
        for sig, rs in grouped[glo:ghi]:
            links = " ".join(
                f'<a href="/{quote(r["dir"])}/">'
                f'{html.escape(r["dir"])}</a>' for r in rs[:12])
            out.append(f"<tr><td><code>{html.escape(sig)}</code></td>"
                       f"<td>{len(rs)}</td><td>{links}</td></tr>")
        out.append("</table>")
    if infra:
        ilo, ihi, ipg, ipages, iper = _page_window(len(infra),
                                                   page, per)
        out.append(
            "<h2>Infrastructure / harness errors</h2>"
            "<p class='dim'>failing runs with no checker verdict — "
            "harness noise, not consistency results; excluded from "
            "the verdict dedupe above</p>")
        out.append(_pager(ilo, ihi, ipg, ipages, iper, len(infra)))
        out.append("<table><tr><th>run</th><th>valid?</th></tr>")
        for r in infra[ilo:ihi]:
            out.append(
                f'<tr><td><a href="/{quote(r["dir"])}/">'
                f'{html.escape(r["dir"])}</a></td>'
                f"<td>{_badge(r['valid?'])}</td></tr>")
        out.append("</table>")
    body = "".join(out)
    if gens is not None:
        _AGG_CACHE[cache_key] = (gens, body)
        if len(_AGG_CACHE) > 64:  # a scraper walking ?page= must not
            _AGG_CACHE.clear()    # grow the cache unboundedly
    return body


#: test.json keys shown in the run page's parameter table, in order
_PARAM_KEYS = ("workload", "nemesis_spec", "nemesis_interval",
               "time_limit", "rate", "ops_per_key", "concurrency",
               "serializable", "lazyfs", "client_type", "snapshot_count",
               "unsafe_no_fsync", "corrupt_check", "version", "seed",
               "nodes")

#: trace-viewer row cap per page load
_TRACE_ROWS = 500


def trace_html(store_base: str, rel: str, kind: str = "") -> str:
    """The trace.jsonl event viewer: first ``_TRACE_ROWS`` events
    (optionally filtered to one kind), with per-kind totals from the
    run's results.json net-trace summary."""
    rdir = os.path.join(store_base, rel)
    results = _load_json(os.path.join(rdir, "results.json")) or {}
    nt = results.get("net-trace") or {}
    out = [f"<!doctype html><title>trace — {html.escape(rel)}</title>",
           f"<style>{_CSS}</style>",
           f'<p><a href="/{quote(rel)}/">&larr; run</a></p>',
           f"<h1>trace — {html.escape(rel)}</h1>"]
    counts = nt.get("counts") or {}
    if counts:
        out.append("<p>filter: "
                   + " ".join(
                       f'<a href="/{quote(rel)}/?trace={quote(k)}">'
                       f"{html.escape(k)}</a>"
                       f"&nbsp;<span class='dim'>({v})</span>"
                       for k, v in sorted(counts.items()))
                   + f' · <a href="/{quote(rel)}/?trace">all</a></p>')
    if nt.get("dropped"):
        out.append(f"<p class='bad'>{nt['dropped']} events dropped "
                   "past the recorder cap</p>")
    path = os.path.join(rdir, "trace.jsonl")
    rows, shown, total = [], 0, 0
    try:
        with open(path) as f:
            for line in f:
                try:
                    e = json.loads(line)
                except ValueError:
                    continue
                if "kind" not in e:
                    continue  # the trailing truncation marker
                total += 1
                if kind and e.get("kind") != kind:
                    continue
                if shown >= _TRACE_ROWS:
                    continue
                shown += 1
                info = {k: v for k, v in e.items()
                        if k not in ("t", "kind", "src", "dst")}
                rows.append(
                    f"<tr><td>{(e.get('t') or 0) / 1e9:.6f}</td>"
                    f"<td>{html.escape(str(e.get('kind')))}</td>"
                    f"<td>{html.escape(str(e.get('src')))}</td>"
                    f"<td>{html.escape(str(e.get('dst')))}</td>"
                    f"<td><code>{html.escape(json.dumps(info, default=repr)[:400])}"
                    f"</code></td></tr>")
    except OSError:
        out.append("<p class='unk'>no trace.jsonl in this run "
                   "(pass --tcpdump)</p>")
        return "".join(out)
    out.append(f"<p>{shown} of {total} events shown"
               + (f" (kind <code>{html.escape(kind)}</code>)" if kind
                  else "") + "</p>")
    out.append("<table><tr><th>t (s)</th><th>kind</th><th>src</th>"
               "<th>dst</th><th>info</th></tr>"
               + "".join(rows) + "</table>")
    return "".join(out)


def run_html(store_base: str, rel: str) -> str:
    """The per-run report page (jepsen's run view: params, checker
    verdicts, telemetry, plots, artifacts)."""
    rdir = os.path.join(store_base, rel)
    results = _load_json(os.path.join(rdir, "results.json")) or {}
    test = _load_json(os.path.join(rdir, "test.json")) or {}
    out = [f"<!doctype html><title>{html.escape(rel)}</title>",
           f"<style>{_CSS}</style>",
           f'<p><a href="/">&larr; all runs</a> &middot; '
           f'<a href="/aggregate">dashboard</a> &middot; '
           f'<a href="/{quote(rel)}/?files">raw files</a></p>',
           f"<h1>{html.escape(str(test.get('name', rel)))} "
           f"{_badge(results.get('valid?', '?'))}</h1>"]
    # parameters
    params = [(k, test[k]) for k in _PARAM_KEYS if k in test]
    if params:
        out.append("<h2>Parameters</h2><table>")
        out.extend(
            f"<tr><th>{html.escape(k)}</th>"
            f"<td><code>{html.escape(json.dumps(v))}</code></td></tr>"
            for k, v in params)
        out.append("</table>")
    # checker verdicts
    checkers = [(k, v) for k, v in sorted(results.items())
                if isinstance(v, dict) and "valid?" in v]
    if checkers:
        out.append("<h2>Checkers</h2><table>"
                   "<tr><th>checker</th><th>valid?</th><th>detail</th></tr>")
        for k, v in checkers:
            detail = {dk: dv for dk, dv in v.items() if dk != "valid?"}
            blob = html.escape(json.dumps(detail, default=repr)[:2000])
            out.append(f"<tr><td>{html.escape(k)}</td>"
                       f"<td>{_badge(v.get('valid?'))}</td>"
                       f"<td><code>{blob}</code></td></tr>")
        out.append("</table>")
    # telemetry summary (phase bar, checker span totals, counters)
    tel = results.get("telemetry") or {}
    if tel:
        out.append("<h2>Telemetry</h2>")
        out.append(f"<p>{_phase_bar(tel.get('phases') or {})}</p>")
        spans = tel.get("spans") or {}
        if spans:
            out.append("<table><tr><th>span</th><th>count</th>"
                       "<th>total (s)</th></tr>")
            for name, v in spans.items():
                out.append(
                    f"<tr><td><code>{html.escape(str(name))}</code></td>"
                    f"<td>{v.get('count')}</td>"
                    f"<td>{v.get('total_s', 0):.4f}</td></tr>")
            out.append("</table>")
        counters = tel.get("counters") or {}
        if counters:
            out.append("<p>"
                       + " · ".join(
                           f"<code>{html.escape(str(k))}</code>={v}"
                           for k, v in sorted(counters.items()))
                       + "</p>")
        if counters.get("net.links"):
            # the userspace proxy plane ran: call out its fault totals
            out.append("<p class='dim'>net proxy plane: "
                       f"{counters.get('net.links', 0)} links fronted, "
                       f"{counters.get('net.dropped_conns', 0)} conns "
                       "dropped/blackholed, "
                       f"{counters.get('net.delayed_bytes', 0)} bytes "
                       "delayed, peak "
                       f"{counters.get('net.active_rules', 0)} active "
                       "rules</p>")
        if tel.get("dropped"):
            out.append(f"<p class='bad'>{tel['dropped']} telemetry "
                       "records dropped past the cap</p>")
    # net-trace summary
    nt = results.get("net-trace") or {}
    if nt:
        out.append("<h2>Network trace</h2>"
                   f"<p>{nt.get('events', 0)} events"
                   + (f", <span class='bad'>{nt['dropped']} "
                      "dropped</span>" if nt.get("dropped") else "")
                   + (f' · <a href="/{quote(rel)}/?trace">'
                      "event viewer</a>"
                      if os.path.exists(os.path.join(rdir,
                                                     "trace.jsonl"))
                      else "") + "</p>")
        if nt.get("counts"):
            out.append("<p class='dim'>"
                       + " · ".join(
                           f"{html.escape(str(k))}: {v}"
                           for k, v in sorted(nt["counts"].items()))
                       + "</p>")
    # plots inline
    plots = [f for f in ("latency-raw.png", "rate.png", "clock.png")
             if os.path.exists(os.path.join(rdir, f))]
    if plots:
        out.append("<h2>Plots</h2>")
        out.extend(f'<img src="/{quote(rel)}/{quote(f)}" '
                   f'alt="{html.escape(f)}">'
                   for f in plots)
    # artifacts
    out.append("<h2>Artifacts</h2><ul>")
    for fn in sorted(os.listdir(rdir)):
        p = os.path.join(rdir, fn)
        label = fn + ("/" if os.path.isdir(p) else "")
        out.append(f'<li><a href="/{quote(rel)}/{quote(fn)}">'
                   f"{html.escape(label)}</a></li>")
    out.append("</ul>")
    return "".join(out)


# -- live campaign view ------------------------------------------------------

#: an SSE stream ends once the snapshot stops refreshing for this long
#: (campaign finished without a done marker, or died) — always after
#: serving at least one event
LIVE_STALE_S = 15.0

#: hard bound on events per SSE connection (a forgotten browser tab
#: must not pin a handler thread forever)
LIVE_MAX_EVENTS = 3600


def _live_snapshot(store_base: str):
    """``(snapshot, mtime, rel_dir)`` of the NEWEST ``live.json``
    under the store (the running — or most recent — campaign's
    collector output), or None when no campaign ever ran live.

    Indexed stores stat only the registered candidates (campaigns
    note themselves via store_index.note_live the moment their
    LiveCollector starts), so each SSE tick is O(campaigns) instead
    of a store-wide two-level listdir."""
    from .runner import store_index
    cands = store_index.live_candidates(store_base)
    if cands is not None:
        best = None
        for rel in cands:
            p = os.path.join(store_base, rel, "live.json")
            try:
                mtime = os.path.getmtime(p)
            except OSError:
                continue
            if best is None or mtime > best[1]:
                best = (p, mtime, rel)
        if best is None:
            return None
        snap = _load_json(best[0])
        if not isinstance(snap, dict):
            return None
        return snap, best[1], best[2]
    best = None
    try:
        names = os.listdir(store_base)
    except OSError:
        return None
    for name in names:
        ndir = os.path.join(store_base, name)
        if not os.path.isdir(ndir):
            continue
        try:
            ids = os.listdir(ndir)
        except OSError:
            continue
        for rid in ids:
            if os.path.islink(os.path.join(ndir, rid)):
                continue  # the `latest` convenience symlink
            p = os.path.join(ndir, rid, "live.json")
            try:
                mtime = os.path.getmtime(p)
            except OSError:
                continue
            if best is None or mtime > best[1]:
                best = (p, mtime, os.path.join(name, rid))
    if best is None:
        return None
    snap = _load_json(best[0])  # atomic rename: never torn, but a
    if not isinstance(snap, dict):  # vanished campaign dir reads None
        return None
    return snap, best[1], best[2]


def live_html() -> str:
    """The /live dashboard shell: an EventSource client that renders
    each SSE snapshot (run states, service occupancy, histogram
    sparklines). Static page — all data arrives over /live?sse=1."""
    return ("<!doctype html><title>live — jepsen_etcd_tpu</title>"
            f"<style>{_CSS}"
            ".spark{font-family:monospace;letter-spacing:1px}"
            "</style>"
            '<p><a href="/">&larr; all runs</a> &middot; '
            '<a href="/aggregate">dashboard</a></p>'
            "<h1>Live campaign</h1>"
            '<div id="s" class="dim">connecting…</div>'
            "<script>\n"
            "const BLOCKS='▁▂▃▄▅▆▇█';\n"
            "function spark(b){const ks=Object.keys(b||{})"
            ".map(Number);if(!ks.length)return'<span class=dim>"
            "(empty)</span>';const lo=Math.min(...ks),"
            "hi=Math.max(...ks);let m=0,out='';"
            "for(let i=lo;i<=hi;i++)m=Math.max(m,b[i]||0);"
            "for(let i=lo;i<=hi;i++){const c=b[i]||0;"
            "out+=BLOCKS[c?Math.min(7,1+Math.floor(6*c/m)):0];}"
            "return'<span class=spark>'+out+'</span>';}\n"
            "function fs(v){if(v==null)return'—';"
            "if(v<1e-3)return(v*1e6).toFixed(0)+'us';"
            "if(v<1)return(v*1e3).toFixed(1)+'ms';"
            "return v.toFixed(2)+'s';}\n"
            "function render(d){\n"
            " if(!d.active&&!d.campaign){document.getElementById('s')"
            ".innerHTML='<p class=unk>no live campaign</p>';return;}\n"
            " let h='<p><b>'+(d.campaign||'?')+'</b> — '+"
            "(d.done?'<span class=ok>finished</span>':"
            "(d.active?'<span class=ok>running</span>':"
            "'<span class=unk>stale</span>'))+"
            "' · '+d.records+' records'+"
            "(d.dropped?' · <span class=bad>'+d.dropped+"
            "' dropped</span>':'')+'</p>';\n"
            " const runs=Object.entries(d.runs||{});\n"
            " h+='<h2>Runs ('+runs.length+')</h2><table><tr>"
            "<th>trace</th><th>host</th><th>status</th><th>phase</th>"
            "<th>spans</th><th>valid</th></tr>';\n"
            " runs.sort();\n"
            " for(const[t,r]of runs){h+='<tr><td><code>'+t+"
            "'</code></td><td>'+(r.host||'—')+'</td><td>'+"
            "(r.status||'running')+'</td><td>'+"
            "(r.phase||'—')+'</td><td>'+(r.spans||0)+'</td><td>'+"
            "(r.valid===true?'<span class=ok>true</span>':"
            "(r.valid===false?'<span class=bad>false</span>':'—'))+"
            "'</td></tr>';}\n"
            " h+='</table>';\n"
            " const s=d.service||{};\n"
            " if(s.ticks)h+='<h2>Checker service</h2><p>'+s.ticks+"
            "' ticks · last: '+(s.packs||0)+' packs from '+"
            "(s.requests||0)+' requests in '+(s.groups||0)+"
            "' groups on <code>'+(s.device||'?')+'</code>'+"
            "(s.runs?' · runs '+s.runs.join(', '):'')+"
            "(s.placement?'<br>chips: '+Object.entries(s.placement)"
            ".sort().map(([d,n])=>'<code>'+d+'</code>&times;'+n)"
            ".join(' · ')+(s.sharded?' · <b>sharded</b>':''):'')+"
            "'</p>';\n"
            " const hists=Object.entries(d.hists||{});\n"
            " if(hists.length){h+='<h2>Distributions</h2><table>"
            "<tr><th>hist</th><th>n</th><th>p50</th><th>p95</th>"
            "<th>sparkline (log2 buckets)</th></tr>';\n"
            "  for(const[n,v]of hists){h+='<tr><td><code>'+n+"
            "'</code></td><td>'+v.count+'</td><td>'+fs(v.p50)+"
            "'</td><td>'+fs(v.p95)+'</td><td>'+spark(v.buckets)+"
            "'</td></tr>';}h+='</table>';}\n"
            " const ctr=Object.entries(d.counters||{});\n"
            " if(ctr.length){h+='<p class=dim>'+ctr.sort()"
            ".map(([k,v])=>k+'='+v).join(' · ')+'</p>';}\n"
            " document.getElementById('s').innerHTML=h;}\n"
            "const es=new EventSource('/live?sse=1');\n"
            "es.onmessage=e=>{const d=JSON.parse(e.data);render(d);"
            "if(d.done||!d.active)es.close();};\n"
            "es.onerror=()=>{es.close();};\n"
            "</script>")


class StoreHandler(SimpleHTTPRequestHandler):
    """Serves the store dir; '/' renders the run index, '/aggregate'
    the cross-run dashboard, run dirs render report pages (?files for
    the raw listing, ?trace for the trace.jsonl viewer)."""

    store_base = "store"

    def __init__(self, *args, **kw):
        super().__init__(*args, directory=self.store_base, **kw)

    def _html(self, body: str) -> None:
        data = body.encode()
        self.send_response(200)
        self.send_header("Content-Type", "text/html; charset=utf-8")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _sse_live(self) -> None:
        """``/live?sse=1``: push the live.json snapshot as SSE events
        (~1/s) until the campaign is done, the snapshot goes stale, or
        the client disconnects. Always serves at least one event —
        ``{"active": false}`` when no campaign ever ran live."""
        self.send_response(200)
        self.send_header("Content-Type", "text/event-stream")
        self.send_header("Cache-Control", "no-cache")
        self.end_headers()
        sent = 0
        try:
            while True:
                found = _live_snapshot(self.store_base)
                if found is None:
                    payload, last = {"active": False}, True
                else:
                    snap, mtime, rel = found
                    stale = time.time() - mtime > LIVE_STALE_S
                    done = bool(snap.get("done"))
                    payload = dict(snap, dir=rel,
                                   active=not (done or stale))
                    last = done or stale
                self.wfile.write(
                    b"data: "
                    + json.dumps(payload, default=repr).encode()
                    + b"\n\n")
                self.wfile.flush()
                sent += 1
                if last or sent >= LIVE_MAX_EVENTS:
                    return
                time.sleep(1.0)
        except (BrokenPipeError, ConnectionResetError, OSError):
            return  # client went away: normal for a live view

    def do_GET(self):
        from urllib.parse import parse_qs
        path, _, query = self.path.partition("?")
        if path in ("/", "/index.html"):
            return self._html(index_html(self.store_base))
        if path in ("/aggregate", "/aggregate/"):
            aq = parse_qs(query, keep_blank_values=True)
            return self._html(aggregate_html(
                self.store_base,
                page=(aq.get("page") or [1])[0],
                per=(aq.get("per") or [None])[0]))
        if path in ("/live", "/live/"):
            if "sse" in parse_qs(query, keep_blank_values=True):
                return self._sse_live()
            return self._html(live_html())
        qs = parse_qs(query, keep_blank_values=True)
        if path.endswith("/") and "files" not in qs:
            rel = os.path.normpath(path.strip("/"))
            rdir = os.path.join(self.store_base, rel)
            # only render report pages for real run dirs inside the store
            if not rel.startswith("..") and \
                    os.path.exists(os.path.join(rdir, "results.json")):
                if "trace" in qs:
                    kind = (qs["trace"][0] or "").strip()
                    return self._html(
                        trace_html(self.store_base, rel, kind))
                return self._html(run_html(self.store_base, rel))
        super().do_GET()

    def log_message(self, fmt, *args):  # quiet by default
        pass


def make_server(store_base: str, port: int = 0,
                bind: str = "127.0.0.1") -> ThreadingHTTPServer:
    handler = type("Handler", (StoreHandler,), {"store_base": store_base})
    return ThreadingHTTPServer((bind, port), handler)


def serve_store(store_base: str, port: int = 8080,
                bind: str = "127.0.0.1") -> int:
    srv = make_server(store_base, port, bind)
    host, p = srv.server_address[:2]
    print(f"Serving {store_base} at http://{host}:{p}/ (ctrl-c to stop)")
    try:
        srv.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        srv.server_close()
    return 0
