"""``serve``: browse saved test runs over local HTTP.

Re-designs the reference's ``lein run serve`` (etcd.clj:250-252, jepsen's
built-in web server): the store dir is served with a generated index of
runs at ``/`` — each linking its results.json, timeline.html, perf PNGs,
trace, and node logs — and plain file/directory serving below it.
"""

from __future__ import annotations

import html
import json
import os
from http.server import SimpleHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import quote


def _run_rows(store_base: str) -> list[dict]:
    from .forensics import all_runs
    rows = []
    for rdir in all_runs(store_base):
        rel = os.path.relpath(rdir, store_base)
        row = {"dir": rel, "valid?": "?", "files": []}
        results = os.path.join(rdir, "results.json")
        if os.path.exists(results):
            try:
                with open(results) as f:
                    row["valid?"] = json.load(f).get("valid?")
            except (OSError, json.JSONDecodeError):
                row["valid?"] = "unreadable"
        for fn in sorted(os.listdir(rdir)):
            row["files"].append(fn)
        rows.append(row)
    return rows


def index_html(store_base: str) -> str:
    rows = []
    # newest first by mtime — run ids are per-test sequence numbers, so
    # path order is not recency across test names
    ordered = sorted(
        _run_rows(store_base),
        key=lambda r: os.path.getmtime(os.path.join(store_base, r["dir"])),
        reverse=True)
    for r in ordered:
        color = {"True": "#2a2", True: "#2a2",
                 False: "#c22", "False": "#c22"}.get(r["valid?"], "#b80")
        files = " ".join(
            f'<a href="/{quote(r["dir"])}/{quote(fn)}">{html.escape(fn)}</a>'
            for fn in r["files"])
        rows.append(
            f'<tr><td><a href="/{quote(r["dir"])}/">'
            f'{html.escape(r["dir"])}</a></td>'
            f'<td style="color:{color}">{html.escape(str(r["valid?"]))}</td>'
            f"<td>{files}</td></tr>")
    return ("<!doctype html><title>jepsen_etcd_tpu store</title>"
            "<h1>Test runs</h1>"
            "<table border=1 cellpadding=4><tr><th>run</th>"
            "<th>valid?</th><th>artifacts</th></tr>"
            + "".join(rows) + "</table>")


class StoreHandler(SimpleHTTPRequestHandler):
    """Serves the store dir; '/' renders the generated run index."""

    store_base = "store"

    def __init__(self, *args, **kw):
        super().__init__(*args, directory=self.store_base, **kw)

    def do_GET(self):
        if self.path in ("/", "/index.html"):
            body = index_html(self.store_base).encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/html; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        super().do_GET()

    def log_message(self, fmt, *args):  # quiet by default
        pass


def make_server(store_base: str, port: int = 0,
                bind: str = "127.0.0.1") -> ThreadingHTTPServer:
    handler = type("Handler", (StoreHandler,), {"store_base": store_base})
    return ThreadingHTTPServer((bind, port), handler)


def serve_store(store_base: str, port: int = 8080,
                bind: str = "127.0.0.1") -> int:
    srv = make_server(store_base, port, bind)
    host, p = srv.server_address[:2]
    print(f"Serving {store_base} at http://{host}:{p}/ (ctrl-c to stop)")
    try:
        srv.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        srv.server_close()
    return 0
