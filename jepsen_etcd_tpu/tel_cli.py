"""Telemetry mining CLI (``python -m jepsen_etcd_tpu tel``).

Queries telemetry artifacts offline — plain jsonl/json reads, no jax
import, safe on any host. Four actions over one or many
``telemetry.jsonl`` / ``service.jsonl`` / ``campaign.json`` files:

  (default)    per-span percentile tables, merged hist records, and
               counter totals across every input
  --diff A B   side-by-side span comparison of exactly two inputs
  --ledger D   campaign ledger verification: Σ rows' shipped packs ==
               the service's submitted counter, per-run queue-wait
               attribution re-sums to the service total, and every
               shipping run's trace id appears in some service tick
               span (the cross-process join the trace plane exists
               to make checkable)
  --coverage P per-run + aggregate coverage vector (peak search
               frontier, rung escalations, host spills, verdict
               signatures) — the features ROADMAP #5's guided
               campaign scheduler will consume

All readers are torn-line tolerant (runner.telemetry.load_jsonl) and
report how many lines they skipped; a killed run must still be
minable.
"""

from __future__ import annotations

import json
import os
from collections import Counter

from .runner.telemetry import Hist, load_jsonl

#: |Σ per-run queue waits − service total| tolerance: the waits are
#: rounded to 1e-6 once at the service and reused verbatim on both
#: sides, so only float summation order can introduce drift
LEDGER_WAIT_TOL = 1e-3


def _fmt_s(v) -> str:
    """Human duration: 1.0e-6 -> '1.0us', 0.012 -> '12.0ms'."""
    if v is None:
        return "-"
    v = float(v)
    if v < 1e-3:
        return f"{v * 1e6:.1f}us"
    if v < 1.0:
        return f"{v * 1e3:.1f}ms"
    return f"{v:.3f}s"


def _resolve(path: str) -> list:
    """A CLI operand names either a jsonl file or a dir holding
    telemetry.jsonl / service.jsonl (run dirs and campaign dirs both
    qualify)."""
    if os.path.isfile(path):
        return [path]
    if os.path.isdir(path):
        found = [os.path.join(path, n)
                 for n in ("telemetry.jsonl", "service.jsonl")
                 if os.path.isfile(os.path.join(path, n))]
        if found:
            return found
    raise SystemExit(f"tel: no telemetry artifacts at {path!r}")


def scan(paths: list) -> dict:
    """Fold a set of jsonl files into one profile: per-span duration
    Hists, merged ``hist`` records, summed counters, trace ids seen,
    and the skipped-line count."""
    prof: dict = {"files": 0, "records": 0, "skipped": 0,
                  "spans": {}, "hists": {}, "counters": {},
                  "traces": set()}
    for p in paths:
        recs, skipped = load_jsonl(p)
        prof["files"] += 1
        prof["skipped"] += skipped
        for rec in recs:
            prof["records"] += 1
            trace = rec.get("trace")
            if trace is not None:
                prof["traces"].add(trace)
            kind = rec.get("kind")
            name = rec.get("name")
            if kind == "span" and isinstance(rec.get("dur_s"),
                                             (int, float)):
                prof["spans"].setdefault(name, Hist()).record(
                    rec["dur_s"])
            elif kind == "counter" and isinstance(rec.get("value"),
                                                  (int, float)):
                prof["counters"][name] = \
                    prof["counters"].get(name, 0) + rec["value"]
            elif kind == "hist":
                prof["hists"].setdefault(name, Hist()).merge(
                    Hist.from_dict(rec))
    return prof


def _span_rows(prof: dict) -> list:
    rows = []
    for name in sorted(prof["spans"]):
        h = prof["spans"][name]
        rows.append({"span": name, "count": h.count,
                     "total_s": round(h.sum, 6),
                     "p50": h.percentile(50), "p95": h.percentile(95),
                     "p99": h.percentile(99)})
    rows.sort(key=lambda r: -r["total_s"])
    return rows


def _print_span_table(rows: list) -> None:
    if not rows:
        print("  (no spans)")
        return
    w = max(len(r["span"]) for r in rows)
    print(f"  {'span':<{w}}  {'count':>7}  {'total':>9}  "
          f"{'p50':>9}  {'p95':>9}  {'p99':>9}")
    for r in rows:
        print(f"  {r['span']:<{w}}  {r['count']:>7}  "
              f"{_fmt_s(r['total_s']):>9}  {_fmt_s(r['p50']):>9}  "
              f"{_fmt_s(r['p95']):>9}  {_fmt_s(r['p99']):>9}")


def cmd_spans(paths: list, as_json: bool) -> int:
    files: list = []
    for p in paths:
        files.extend(_resolve(p))
    prof = scan(files)
    if as_json:
        print(json.dumps({
            "files": prof["files"], "records": prof["records"],
            "skipped": prof["skipped"],
            "traces": sorted(prof["traces"]),
            "spans": {n: dict(h.to_dict(), name=n)
                      for n, h in prof["spans"].items()},
            "hists": {n: h.to_dict()
                      for n, h in prof["hists"].items()},
            "counters": prof["counters"]}, indent=2, sort_keys=True))
        return 0
    print(f"{prof['files']} file(s), {prof['records']} records"
          f" ({prof['skipped']} torn/skipped lines),"
          f" {len(prof['traces'])} trace id(s)")
    print("spans:")
    _print_span_table(_span_rows(prof))
    if prof["hists"]:
        print("hist records:")
        for n in sorted(prof["hists"]):
            d = prof["hists"][n].to_dict()
            print(f"  {n}: count={d['count']} "
                  f"p50={_fmt_s(d['p50'])} p95={_fmt_s(d['p95'])} "
                  f"p99={_fmt_s(d['p99'])}")
    if prof["counters"]:
        print("counters:")
        for n in sorted(prof["counters"]):
            v = prof["counters"][n]
            v = round(v, 6) if isinstance(v, float) else v
            print(f"  {n} = {v}")
    return 0


def _merge_profiles(profs: list) -> dict:
    """Fold per-file profiles into one. Bit-identical to a single
    scan() over the same files for everything --diff prints: counts
    are ints and percentiles derive from bucket counts + exact
    min/max, none of which depend on float-summation order."""
    out: dict = {"files": 0, "records": 0, "skipped": 0,
                 "spans": {}, "hists": {}, "counters": {},
                 "traces": set()}
    for p in profs:
        out["files"] += p["files"]
        out["records"] += p["records"]
        out["skipped"] += p["skipped"]
        for n, h in p["spans"].items():
            out["spans"].setdefault(n, Hist()).merge(h)
        for n, h in p["hists"].items():
            out["hists"].setdefault(n, Hist()).merge(h)
        for n, v in p["counters"].items():
            out["counters"][n] = out["counters"].get(n, 0) + v
        out["traces"] |= p["traces"]
    return out


def _scan_cached(files: list, use_index: bool) -> dict:
    """scan(), but each file's profile is served from its store
    index's tel_cache when fresh (runner/store_index.tel_profile) —
    repeat diffs against a hot store re-read nothing."""
    if not use_index:
        return scan(files)
    from .runner import store_index
    return _merge_profiles(
        [store_index.tel_profile(f, scan) for f in files])


def cmd_diff(paths: list, as_json: bool,
             use_index: bool = True) -> int:
    if len(paths) != 2:
        raise SystemExit("tel --diff takes exactly two inputs")
    pa = _scan_cached(_resolve(paths[0]), use_index)
    pb = _scan_cached(_resolve(paths[1]), use_index)
    names = sorted(set(pa["spans"]) | set(pb["spans"]))
    delta = []
    for n in names:
        ha, hb = pa["spans"].get(n), pb["spans"].get(n)
        a95 = ha.percentile(95) if ha else None
        b95 = hb.percentile(95) if hb else None
        ratio = (b95 / a95) if a95 and b95 else None
        delta.append({"span": n,
                      "count_a": ha.count if ha else 0,
                      "count_b": hb.count if hb else 0,
                      "p95_a": a95, "p95_b": b95,
                      "p95_ratio": (round(ratio, 3)
                                    if ratio is not None else None)})
    if as_json:
        print(json.dumps({"a": paths[0], "b": paths[1],
                          "skipped": [pa["skipped"], pb["skipped"]],
                          "spans": delta}, indent=2, sort_keys=True))
        return 0
    print(f"A = {paths[0]}  ({pa['records']} records, "
          f"{pa['skipped']} skipped)")
    print(f"B = {paths[1]}  ({pb['records']} records, "
          f"{pb['skipped']} skipped)")
    if not delta:
        print("  (no spans on either side)")
        return 0
    w = max(len(d["span"]) for d in delta)
    print(f"  {'span':<{w}}  {'n(A)':>6}  {'n(B)':>6}  "
          f"{'p95(A)':>9}  {'p95(B)':>9}  {'B/A':>6}")
    for d in delta:
        r = "-" if d["p95_ratio"] is None else f"{d['p95_ratio']:.2f}x"
        print(f"  {d['span']:<{w}}  {d['count_a']:>6}  "
              f"{d['count_b']:>6}  {_fmt_s(d['p95_a']):>9}  "
              f"{_fmt_s(d['p95_b']):>9}  {r:>6}")
    return 0


def _load_campaign(path: str) -> tuple:
    """(campaign dir, summary dict) from a dir or campaign.json path."""
    if os.path.isdir(path):
        cpath = os.path.join(path, "campaign.json")
    else:
        cpath, path = path, os.path.dirname(path) or "."
    try:
        with open(cpath) as fh:
            summary = json.load(fh)
    except (OSError, json.JSONDecodeError, ValueError) as e:
        raise SystemExit(f"tel: cannot read {cpath!r}: {e}")
    if not isinstance(summary, dict) or "runs" not in summary:
        raise SystemExit(f"tel: {cpath!r} is not a campaign summary")
    return path, summary


def ledger(path: str, use_index: bool = True) -> dict:
    """Verify the campaign's cross-process accounting. Three checks:
    shipped-pack conservation, queue-wait attribution, and the
    trace join between runner rows and service tick spans."""
    cdir, summary = _load_campaign(path)
    rows = [r for r in (summary.get("runs") or [])
            if isinstance(r, dict)]
    done = [r for r in rows if r.get("status") == "done"]
    sctr = (summary.get("service") or {}).get("counters") or {}
    checks = []

    shipped = sum(int(r.get("service_shipped") or 0) for r in done)
    submitted = int(sctr.get("service.submitted", 0))
    checks.append({"check": "shipped==submitted",
                   "ok": shipped == submitted,
                   "detail": f"rows shipped {shipped}, "
                             f"service submitted {submitted}"})

    row_wait = sum(float(r.get("service_queue_wait_s") or 0.0)
                   for r in done)
    svc_wait = float(sctr.get("service.queue_wait_s", 0.0))
    checks.append({"check": "queue_wait attribution",
                   "ok": abs(row_wait - svc_wait) <= LEDGER_WAIT_TOL,
                   "detail": f"rows {round(row_wait, 6)}s, "
                             f"service {round(svc_wait, 6)}s"})

    svc_log = os.path.join(cdir, "service.jsonl")
    if os.path.isfile(svc_log):
        # the index row captured the tick-span trace join at campaign
        # fold time (service.jsonl is complete then); it is used only
        # while the file's fingerprint still matches
        cached = None
        if use_index:
            from .runner import store_index
            cached = store_index.ledger_ticks(cdir)
        if cached is not None:
            ticked, skipped = cached
        else:
            recs, skipped = load_jsonl(svc_log)
            ticked = set()
            for rec in recs:
                if rec.get("kind") == "span" and \
                        rec.get("name") == "service.tick":
                    ticked.update((rec.get("attrs") or {})
                                  .get("runs") or ())
        shippers = {r.get("trace") for r in done
                    if int(r.get("service_shipped") or 0) > 0
                    and r.get("trace") is not None}
        missing = sorted(shippers - ticked)
        checks.append({"check": "trace join (rows ⊆ tick spans)",
                       "ok": not missing,
                       "detail": f"{len(shippers)} shipping run(s), "
                                 f"{len(ticked)} trace(s) in tick "
                                 f"spans, {skipped} torn line(s)"
                                 + (f"; missing {missing}"
                                    if missing else "")})
    else:
        checks.append({"check": "trace join (rows ⊆ tick spans)",
                       "ok": None,
                       "detail": "no service.jsonl (service "
                                 "disabled or inline runs)"})
    return {"campaign": summary.get("trace") or summary.get("name"),
            "dir": cdir, "runs": len(rows), "done": len(done),
            "checks": checks,
            "ok": all(c["ok"] is not False for c in checks)}


def cmd_ledger(paths: list, as_json: bool,
               use_index: bool = True) -> int:
    out = ledger(paths[0], use_index=use_index)
    if as_json:
        print(json.dumps(out, indent=2, sort_keys=True))
        return 0 if out["ok"] else 1
    print(f"ledger: {out['campaign']}  "
          f"({out['done']}/{out['runs']} runs done)")
    for c in out["checks"]:
        mark = {True: "ok  ", False: "FAIL", None: "skip"}[c["ok"]]
        print(f"  [{mark}] {c['check']}: {c['detail']}")
    print("ledger verified" if out["ok"] else "LEDGER MISMATCH")
    return 0 if out["ok"] else 1


def _coverage_dirs(path: str) -> list:
    """Run dirs behind a coverage operand: a campaign dir (rows'
    dirs), a single run dir, or a store base (every run under it)."""
    if os.path.isfile(os.path.join(path, "campaign.json")) or \
            path.endswith("campaign.json"):
        cdir, summary = _load_campaign(path)
        out = []
        for r in summary.get("runs") or []:
            if isinstance(r, dict) and r.get("dir"):
                d = r["dir"]
                out.append(d if os.path.isabs(d)
                           else os.path.join(cdir, d))
        return out
    if os.path.isfile(os.path.join(path, "results.json")):
        return [path]
    out = []
    for root, dirs, files in os.walk(path, followlinks=False):
        dirs[:] = [d for d in dirs
                   if not os.path.islink(os.path.join(root, d))]
        if "results.json" in files:
            out.append(root)
            dirs[:] = []
    return sorted(out)


def _read_vector(rdir: str):
    """One run's coverage vector straight from its results.json;
    None when unreadable (the walk skips those)."""
    from .runner.store_index import coverage_fields
    try:
        with open(os.path.join(rdir, "results.json")) as fh:
            results = json.load(fh)
    except (OSError, json.JSONDecodeError, ValueError):
        return None
    return coverage_fields(results)


def coverage(path: str, use_index: bool = True) -> dict:
    """The guided-campaign feature vector: how hard the checker had
    to work (frontier/rungs/spills) and what verdicts the fleet
    produced (failure-signature histogram). Vector derivation lives
    in runner/store_index.coverage_fields — shared with the index
    writer, so the index path below is bit-identical to the walk.

    A multi-host campaign's rows are tolerated, not required, to have
    artifacts on this machine: error rows (agent deaths past the
    requeue cap, crashed epilogues) carry no ``dir``, and a re-queued
    or inline-stranded run may lack ``telemetry.jsonl``/``results.json``
    — those fold into ``aggregate.skipped`` instead of erroring, and
    the rows' per-host column folds into ``aggregate.hosts``."""
    from .runner import store_index
    rows_meta = None
    is_campaign = os.path.isfile(
        os.path.join(path, "campaign.json")) or \
        path.endswith("campaign.json")
    if is_campaign:
        _, summary = _load_campaign(path)
        rows_meta = [r for r in (summary.get("runs") or [])
                     if isinstance(r, dict)]
    runs = []
    if not is_campaign and use_index and \
            not os.path.isfile(os.path.join(path, "results.json")):
        # store-base operand: replay the index (recursing into guided
        # sub-indexes) instead of walking the tree
        pairs = store_index.coverage_run_vectors(path)
        if pairs is not None:
            runs = [dict(dir=d, **vec) for d, vec in pairs]
    if not runs:
        for rdir in _coverage_dirs(path):
            vec = store_index.run_vector(rdir) if use_index else None
            if vec is None:
                vec = _read_vector(rdir)
            if vec is None:
                continue
            runs.append(dict(dir=rdir, **vec))
    sigs = Counter(r["signature"] for r in runs if r["signature"])
    buckets: Counter = Counter()
    for r in runs:
        buckets.update(r["wave_hist"])
    agg = {"count": len(runs),
           "peak_frontier": max((r["frontier"] for r in runs),
                                default=0),
           "peak_waves": max((r["waves"] for r in runs), default=0),
           "rungs": sum(r["rungs"] for r in runs),
           "spills": sum(r["spills"] for r in runs),
           "invalid": sum(1 for r in runs
                          if r["valid"] is not True),
           "wave_hist": dict(sorted(buckets.items())),
           "signatures": dict(sorted(sigs.items()))}
    if rows_meta is not None:
        agg["rows"] = len(rows_meta)
        agg["skipped"] = max(0, len(rows_meta) - len(runs))
        hosts: dict = {}
        for r in rows_meta:
            st = hosts.setdefault(r.get("host") or "local",
                                  {"runs": 0, "invalid": 0,
                                   "errors": 0})
            st["runs"] += 1
            if r.get("status") != "done":
                st["errors"] += 1
            elif r.get("valid") is not True:
                st["invalid"] += 1
        agg["hosts"] = hosts
    return {"runs": runs, "aggregate": agg}


def cmd_coverage(paths: list, as_json: bool,
                 use_index: bool = True) -> int:
    out = coverage(paths[0], use_index=use_index)
    if as_json:
        print(json.dumps(out, indent=2, sort_keys=True))
        return 0
    agg = out["aggregate"]
    print(f"coverage over {agg['count']} run(s):")
    for r in out["runs"]:
        sig = f"  [{r['signature']}]" if r["signature"] else ""
        print(f"  {os.path.basename(r['dir'])}: "
              f"valid={r['valid']} frontier={r['frontier']} "
              f"waves={r['waves']} "
              f"rungs={r['rungs']} spills={r['spills']}{sig}")
    print(f"aggregate: peak_frontier={agg['peak_frontier']} "
          f"peak_waves={agg['peak_waves']} "
          f"rungs={agg['rungs']} spills={agg['spills']} "
          f"invalid={agg['invalid']}")
    if "rows" in agg:
        print(f"  campaign rows: {agg['rows']} "
              f"({agg['skipped']} without local artifacts)")
        for host, st in sorted(agg.get("hosts", {}).items()):
            print(f"  host {host}: runs={st['runs']} "
                  f"invalid={st['invalid']} errors={st['errors']}")
    for sig, n in agg["signatures"].items():
        print(f"  signature x{n}: {sig}")
    return 0


def _find_guided(path: str, use_index: bool = True) -> str:
    """Resolve a --corpus operand to a guided.json: the file itself, a
    guided dir containing one, or a store base (newest guided run,
    answered by the store index when one exists)."""
    if os.path.isfile(path) and path.endswith("guided.json"):
        return path
    direct = os.path.join(path, "guided.json")
    if os.path.isfile(direct):
        return direct
    if use_index:
        from .runner import store_index
        got = store_index.newest_guided(path)
        if got is not None:
            return got[1]
    cands = []
    for root, dirs, files in os.walk(path, followlinks=False):
        dirs[:] = [d for d in dirs
                   if not os.path.islink(os.path.join(root, d))]
        if "guided.json" in files:
            p = os.path.join(root, "guided.json")
            cands.append((os.path.getmtime(p), p))
            dirs[:] = []
    if not cands:
        raise SystemExit(f"tel: no guided.json under {path!r}")
    return max(cands)[1]


def corpus(path: str, use_index: bool = True) -> dict:
    """A guided campaign's search summary (guided.json)."""
    gpath = _find_guided(path, use_index=use_index)
    try:
        with open(gpath) as fh:
            out = json.load(fh)
    except (OSError, json.JSONDecodeError, ValueError) as e:
        raise SystemExit(f"tel: unreadable guided summary "
                         f"{gpath!r}: {e}")
    out["path"] = gpath
    return out


def cmd_corpus(paths: list, as_json: bool,
               use_index: bool = True) -> int:
    out = corpus(paths[0], use_index=use_index)
    if as_json:
        print(json.dumps(out, indent=2, sort_keys=True))
        return 0
    print(f"guided campaign {out.get('name')}: "
          f"{out.get('runs')}/{out.get('budget')} runs over "
          f"{out.get('generations')} generation(s), "
          f"master seed {out.get('master_seed')}")
    sigs = out.get("signatures") or {}
    for sig, run_no in sorted(sigs.items(), key=lambda kv: kv[1]):
        print(f"  signature @run {run_no}: {sig}")
    ff = out.get("first_failure_run")
    print(f"  first failure: "
          f"{'run %d' % ff if ff else '(none)'}  "
          f"envelope={out.get('envelope')}")
    for c in out.get("corpus") or []:
        print(f"  ancestor @run {c.get('run')}: "
              f"{c.get('opts', {}).get('workload')}/"
              f"{','.join(c.get('opts', {}).get('nemesis') or []) or '-'}"
              f" seed={c.get('seed')} score={c.get('score')}"
              + (f" [{c['signature']}]" if c.get("signature") else ""))
    for m in out.get("minimized") or []:
        print(f"  minimized @run {m.get('run')}: "
              f"{m.get('original_windows')}→{m.get('windows')} "
              f"window(s), {m.get('nemesis_ops')} nemesis op(s) "
              f"[{m.get('signature')}]")
        print(f"    repro: {m.get('repro')}")
    return 0


def run(args) -> int:
    """Entry point for the ``tel`` subcommand (cli.main dispatches
    here before any jax import)."""
    use_index = not getattr(args, "no_index", False)
    try:
        if args.ledger:
            return cmd_ledger(args.paths, args.as_json,
                              use_index=use_index)
        if getattr(args, "corpus", False):
            return cmd_corpus(args.paths, args.as_json,
                              use_index=use_index)
        if args.coverage:
            return cmd_coverage(args.paths, args.as_json,
                                use_index=use_index)
        if args.diff:
            return cmd_diff(args.paths, args.as_json,
                            use_index=use_index)
        return cmd_spans(args.paths, args.as_json)
    except BrokenPipeError:
        # `tel ... | head` closing stdout early is normal usage
        return 0
