"""ctypes driver for the native WGL oracle (wgl_oracle.cpp).

The C++ core is the CPU fallback engine for linearizability checks —
the role Knossos' JVM search plays in the reference
(register.clj:110-112, lock.clj:244, project.clj:21-23 gives it a 24 GB
heap). It speaks the same register language as the TPU kernel
(ops/wgl.py): models expressible as (versioned) CAS registers —
VersionedRegister natively, Mutex and CASRegister through adapters —
run native; anything else returns None and the caller uses the Python
DFS (checkers/linearizable.py), which stays the semantic reference.

Build: compiled on demand with g++ into ``_build/`` keyed by source
hash; any failure disables the native path for the process (the Python
oracle is always available). Set JEPSEN_ETCD_TPU_NO_NATIVE=1 to disable
explicitly.
"""

from __future__ import annotations

import ctypes
import hashlib
import logging
import os
import subprocess
import threading
from typing import Any, Optional

import numpy as np

from ..ops.common import UnsupportedValue, ValueIds, as_version
from ..ops.wgl import (CAS, NO_ASSERT, NONE_VAL, READ, WILDCARD,
                       WRITE)

logger = logging.getLogger("jepsen_etcd_tpu.native")

INF = float("inf")

_lock = threading.Lock()
_lib: Any = None
_lib_tried = False


def _build_lib() -> Optional[ctypes.CDLL]:
    here = os.path.dirname(os.path.abspath(__file__))
    src = os.path.join(here, "wgl_oracle.cpp")
    with open(src, "rb") as fh:
        digest = hashlib.sha256(fh.read()).hexdigest()[:16]
    build_dir = os.path.join(here, "_build")
    so = os.path.join(build_dir, f"wgl_oracle_{digest}.so")
    if not os.path.exists(so):
        os.makedirs(build_dir, exist_ok=True)
        tmp = so + f".tmp{os.getpid()}"
        cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", src,
               "-o", tmp]
        try:
            subprocess.run(cmd, check=True, capture_output=True,
                           timeout=120)
            os.replace(tmp, so)
        except Exception as e:
            logger.warning("native oracle build failed (%r); "
                           "using the Python oracle", e)
            return None
    lib = ctypes.CDLL(so)
    fn = lib.wgl_oracle_check
    fn.restype = ctypes.c_int32
    fn.argtypes = [
        ctypes.c_int32,
        np.ctypeslib.ndpointer(np.int8, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
        ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int32),
    ]
    return lib


def get_lib() -> Optional[ctypes.CDLL]:
    global _lib, _lib_tried
    if os.environ.get("JEPSEN_ETCD_TPU_NO_NATIVE"):
        return None
    with _lock:
        if not _lib_tried:
            _lib_tried = True
            _lib = _build_lib()
        return _lib


def _register_language(model) -> Optional[Any]:
    """An adapter mapping each entry's (f, value) into the register
    language ``(f, [version_assert, payload])``, or None when the model
    has no register expression (caller uses the Python DFS)."""
    from ..models import VersionedRegister, Mutex, CASRegister
    from ..ops.wgl import mutex_adapter

    if isinstance(model, VersionedRegister):
        if model.version != 0 or model.value is not None:
            return None
        return lambda f, v: (f, v) if f in ("read", "write", "cas") else None
    if isinstance(model, Mutex):
        return None if model.locked else mutex_adapter
    if isinstance(model, CASRegister):
        if model.value is not None:
            return None

        def adapt(f, v):
            if f == "read":
                return "read", [None, v]
            if f == "write":
                return "write", [None, v]
            if f == "cas":
                return "cas", [None, tuple(v)]
            return None

        return adapt
    return None


def check_entries(model, entries, max_configs: int = 5_000_000
                  ) -> Optional[dict]:
    """Run the native search over history entries. Returns the checker
    result dict, or None when the native path is unavailable or the
    history doesn't fit the register language."""
    lib = get_lib()
    if lib is None:
        return None
    adapter = _register_language(model)
    if adapter is None:
        return None

    n_all = len(entries)
    vids = ValueIds()
    val_id = vids.id

    required_rets = sorted(e.ret for e in entries if e.required)
    R = len(required_rets)
    if R == 0:
        return {"valid?": True, "configs": 0, "ops": n_all,
                "checker-impl": "native"}

    kept = []       # (entry, f_code, a1, a2, ver)
    for e in entries:
        try:
            m = adapter(e.f, e.value)
        except (TypeError, ValueError):
            return None
        if m is None:
            return None
        ef, ev = m
        if not e.required:
            if ef == "read":
                continue  # info reads can never change a verdict
            # info ops invoked after every required return can only
            # linearize after acceptance — droppable
            lo = 0
            hi = R
            while lo < hi:
                mid = (lo + hi) // 2
                if required_rets[mid] < e.invoke:
                    lo = mid + 1
                else:
                    hi = mid
            if lo >= R:
                continue
        ev = ev if ev is not None else (None, None)
        try:
            vassert, payload = ev
            ver_c = NO_ASSERT if vassert is None else as_version(vassert)
            if ef == "read":
                a1 = WILDCARD if payload is None else val_id(payload)
                kept.append((e, READ, a1, 0, ver_c))
            elif ef == "write":
                kept.append((e, WRITE, val_id(payload), 0, ver_c))
            elif ef == "cas":
                if not isinstance(payload, (list, tuple)) \
                        or len(payload) != 2:
                    return None
                old, new = payload
                kept.append((e, CAS, val_id(old), val_id(new), ver_c))
            else:
                return None
        except (TypeError, ValueError, UnsupportedValue):
            # malformed or semantically un-encodable value: the Python
            # DFS (the semantic reference) handles it
            return None

    # value-space reductions (ops/common.register_value_sets): merge
    # dead values into one id; drop info cas with unproducible olds —
    # the same collapse the kernel pack applies, equally sound here
    # (the C++ search honors the full semantics, this just shrinks the
    # reachable state space from 2^I to per-class counts)
    from ..ops.common import register_value_sets
    asserted, producible = register_value_sets(
        (kf, ka1, ka2) for (_e, kf, ka1, ka2, _v) in kept)
    dead = producible - asserted - {NONE_VAL}
    if len(dead) > 1:
        dead_id = min(dead)

        def remap(kf, ka1, ka2):
            if kf == WRITE and ka1 in dead:
                return kf, dead_id, ka2
            if kf == CAS and ka2 in dead:
                return kf, ka1, dead_id
            return kf, ka1, ka2

        kept = [(e, *remap(kf, ka1, ka2), kv)
                for (e, kf, ka1, ka2, kv) in kept]
    kept = [(e, kf, ka1, ka2, kv) for (e, kf, ka1, ka2, kv) in kept
            if e.required or not (kf == CAS and ka1 != NONE_VAL
                                  and ka1 not in producible)]

    n = len(kept)
    f = np.array([k[1] for k in kept], dtype=np.int8)
    a1 = np.array([k[2] for k in kept], dtype=np.int32)
    a2 = np.array([k[3] for k in kept], dtype=np.int32)
    ver = np.array([k[4] for k in kept], dtype=np.int32)
    inv = np.array([k[0].invoke for k in kept], dtype=np.int64)
    ret = np.array([np.iinfo(np.int64).max if k[0].ret == INF
                    else int(k[0].ret) for k in kept], dtype=np.int64)
    req = np.array([1 if k[0].required else 0 for k in kept],
                   dtype=np.uint8)
    # canonical firing order for interchangeable info ops: identical
    # (f, a1, a2, ver) info updates chained by (invoke, index) — a
    # lower-invoke member is enabled whenever a higher one is, so any
    # linearization rewrites to fire the chain in order.
    sym_pred = np.full(n, -1, dtype=np.int32)
    chains: dict = {}
    order = sorted(range(n), key=lambda j: (int(inv[j]), j))
    for j in order:
        if req[j]:
            continue
        key = (int(f[j]), int(a1[j]), int(a2[j]), int(ver[j]))
        if key in chains:
            sym_pred[j] = chains[key]
        chains[key] = j

    configs = ctypes.c_int64(0)
    blocked_op = ctypes.c_int32(-1)
    best_depth = ctypes.c_int32(-1)
    b_version = ctypes.c_int32(0)
    b_value = ctypes.c_int32(0)
    rc = lib.wgl_oracle_check(
        np.int32(n), f, a1, a2, ver, inv, ret, req, sym_pred,
        np.int64(max_configs), ctypes.byref(configs),
        ctypes.byref(blocked_op), ctypes.byref(best_depth),
        ctypes.byref(b_version), ctypes.byref(b_value))

    out = {"configs": int(configs.value), "ops": n_all,
           "checker-impl": "native"}
    if rc == 2:
        out["valid?"] = "unknown"
        out["error"] = "search budget exceeded"
        return out
    if rc == 1:
        out["valid?"] = True
        out["final-model"] = repr(_model_at(model, int(b_version.value),
                                            vids.rev.get(int(b_value.value))))
        return out
    out["valid?"] = False
    if blocked_op.value >= 0:
        e = kept[int(blocked_op.value)][0]
        out["op"] = dict(e.op)
        out["max-linearized"] = int(best_depth.value)
        state = _model_at(model, int(b_version.value),
                          vids.rev.get(int(b_value.value)))
        from ..models.base import Inconsistent
        nxt = state.step(e)
        out["error"] = (nxt.msg if isinstance(nxt, Inconsistent)
                        else "blocked")
    return out


def _model_at(model, version: int, value):
    """Reconstruct a model instance from the register-language state."""
    from ..models import VersionedRegister, Mutex, CASRegister
    from ..ops.wgl import MUTEX_LOCKED
    if isinstance(model, VersionedRegister):
        return VersionedRegister(version, value)
    if isinstance(model, Mutex):
        return Mutex(value == MUTEX_LOCKED)
    return CASRegister(value)
