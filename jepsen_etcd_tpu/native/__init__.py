"""Native (C++) components of the framework runtime.

The reference's performance-critical non-JVM surface lives in native
dependencies (etcd/Go, lazyfs/C++, netty epoll — SURVEY §2.2); here the
native citizen is the checker fallback engine: a C++ WGL search
(wgl_oracle.cpp) driven through ctypes (oracle.py).
"""

from .oracle import check_entries, get_lib

__all__ = ["check_entries", "get_lib"]
