// Native WGL linearizability oracle (C++ core of checkers/linearizable).
//
// The reference's Knossos search runs on the JVM with a 24 GB heap
// (project.clj:21-23); our CPU fallback path is this C++ depth-first
// search over (linearized-mask, register-value) configurations with a
// memoizing visited set — the same semantics as the Python oracle
// (checkers/linearizable.py, differential-tested against it), roughly
// two orders of magnitude faster. It handles histories the TPU kernel
// cannot pack (window > 64, info ops > 32) before any "unknown" verdict
// is accepted.
//
// Register language (matches ops/wgl.py packing):
//   f: 0 read / 1 write / 2 cas
//   a1: read expected value (or WILDCARD) / write value / cas old
//   a2: cas new
//   ver: version assertion (NO_ASSERT when absent). Version semantics are
//        VersionedRegister's (models/versioned_register.py): updates
//        assert version+1, reads assert version; version is DERIVED —
//        the count of linearized updates, a function of the mask — it
//        rides in the frame word beside the value for cheap access and
//        adds no distinct states to the visited set.
//   inv/ret: total-order positions; ret = INT64_MAX for :info ops.
//   req: 1 for :ok ops (must linearize), 0 for :info (may, or never).
//   sym_pred: canonical-order predecessor for interchangeable info ops
//        (identical f/a1/a2); -1 when none. Restricting the search to
//        fire each class in order collapses 2^I symmetric subsets.
//
// Returns 1 valid, 0 invalid, 2 search budget exceeded.

#include <cstdint>
#include <cstring>
#include <algorithm>
#include <utility>
#include <vector>

namespace {

constexpr int32_t NO_ASSERT = -(1 << 30);
constexpr int32_t WILDCARD = -1;
constexpr int8_t F_READ = 0, F_WRITE = 1, F_CAS = 2;

inline uint64_t splitmix64(uint64_t x) {
  x += 0x9e3779b97f4a7c15ULL;
  x = (x ^ (x >> 30)) * 0xbf58476d1ce4e5b9ULL;
  x = (x ^ (x >> 27)) * 0x94d049bb133111ebULL;
  return x ^ (x >> 31);
}

// Open-addressing hash set over fixed-width uint64 keys.
struct KeySet {
  size_t kw = 0, cap = 0, cnt = 0, mask = 0;
  std::vector<uint64_t> slots;
  std::vector<uint8_t> used;

  void init(size_t key_words, size_t cap0) {
    kw = key_words;
    cap = 64;
    while (cap < cap0) cap <<= 1;
    mask = cap - 1;
    slots.assign(cap * kw, 0);
    used.assign(cap, 0);
    cnt = 0;
  }

  uint64_t hash(const uint64_t* key) const {
    uint64_t h = 0x2545f4914f6cdd1dULL;
    for (size_t i = 0; i < kw; i++) h = splitmix64(h ^ key[i]);
    return h;
  }

  void grow() {
    std::vector<uint64_t> old_slots;
    std::vector<uint8_t> old_used;
    old_slots.swap(slots);
    old_used.swap(used);
    size_t old_cap = cap;
    cap <<= 1;
    mask = cap - 1;
    slots.assign(cap * kw, 0);
    used.assign(cap, 0);
    for (size_t i = 0; i < old_cap; i++) {
      if (!old_used[i]) continue;
      const uint64_t* key = &old_slots[i * kw];
      size_t j = hash(key) & mask;
      while (used[j]) j = (j + 1) & mask;
      std::memcpy(&slots[j * kw], key, kw * 8);
      used[j] = 1;
    }
  }

  // true iff the key was newly inserted.
  bool insert(const uint64_t* key) {
    size_t i = hash(key) & mask;
    while (used[i]) {
      if (!std::memcmp(&slots[i * kw], key, kw * 8)) return false;
      i = (i + 1) & mask;
    }
    std::memcpy(&slots[i * kw], key, kw * 8);
    used[i] = 1;
    cnt++;
    if (cnt * 10 > cap * 7) grow();
    return true;
  }
};

inline bool get_bit(const uint64_t* m, int32_t i) {
  return (m[i >> 6] >> (i & 63)) & 1ULL;
}

}  // namespace

extern "C" int32_t wgl_oracle_check(
    int32_t n, const int8_t* f, const int32_t* a1, const int32_t* a2,
    const int32_t* ver, const int64_t* inv, const int64_t* ret,
    const uint8_t* req, const int32_t* sym_pred, int64_t max_configs,
    int64_t* configs_out, int32_t* blocked_op_out, int32_t* best_depth_out,
    int32_t* blocked_version_out, int32_t* blocked_value_out) {
  const size_t nw = (static_cast<size_t>(n) + 63) / 64;
  const size_t fw = nw + 1;  // frame: mask words + (value<<32 | version)

  // required ops ordered by return position (for the min-ret scan)
  std::vector<int32_t> req_order;
  req_order.reserve(n);
  for (int32_t i = 0; i < n; i++)
    if (req[i]) req_order.push_back(i);
  for (size_t i = 1; i < req_order.size(); i++) {  // insertion sort by ret
    int32_t v = req_order[i];
    size_t j = i;
    while (j > 0 && ret[req_order[j - 1]] > ret[v]) {
      req_order[j] = req_order[j - 1];
      j--;
    }
    req_order[j] = v;
  }

  // version ceilings: a required op asserting a version can only fire
  // while the register version is below/at its ceiling (read: ver,
  // update: ver-1). Version never decreases, so any state whose
  // version exceeds the MINIMUM ceiling among unlinearized required
  // ops is dead — the prune that stops info-heavy searches from
  // wandering through count combinations no future assertion can
  // match (the dominant blowup for faulted register histories).
  std::vector<std::pair<int32_t, int32_t>> ceil_order;  // (ceiling, e)
  for (int32_t e = 0; e < n; e++) {
    if (req[e] && ver[e] != NO_ASSERT)
      ceil_order.emplace_back(f[e] == F_READ ? ver[e] : ver[e] - 1, e);
  }
  std::sort(ceil_order.begin(), ceil_order.end());
  std::vector<size_t> ceil_rank(n, 0);  // entry -> index in ceil_order
  for (size_t r = 0; r < ceil_order.size(); r++)
    ceil_rank[ceil_order[r].second] = r;

  // enabled-candidate prefix masks: pre[r] = entries with inv < the
  // r-th-by-ret required op's ret. The per-config candidate walk then
  // iterates only (pre[min_ret_op] & ~mask) set bits — O(n/64 + #cand)
  // instead of an O(n) scan, the difference between 0.2M and 2M
  // configs/s on 2000-entry histories.
  std::vector<size_t> rank_of(n, 0);  // entry -> index in req_order
  for (size_t r = 0; r < req_order.size(); r++) rank_of[req_order[r]] = r;
  std::vector<uint64_t> pre(req_order.size() * nw, 0);
  for (size_t r = 0; r < req_order.size(); r++) {
    const int64_t bound = ret[req_order[r]];
    uint64_t* row = &pre[r * nw];
    for (int32_t e = 0; e < n; e++)
      if (inv[e] < bound) row[e >> 6] |= 1ULL << (e & 63);
  }

  // frame layout:
  //   [mask: nw][value<<32|version: 1][pmask: nw_req][cmask: nw_ceil]
  // pmask/cmask mirror the mask permuted into ret-rank / ceiling-rank
  // order over required ops (bits past the rank count pre-set), so the
  // min-ret and min-ceiling scans are word-wise first-zero searches
  // instead of O(depth) bit walks. The visited key is the fw-word
  // prefix only — both permuted masks are functions of the mask.
  const size_t n_req = req_order.size();
  const size_t nw_req = (n_req + 63) / 64;
  const size_t n_ceil = ceil_order.size();
  const size_t nw_ceil = (n_ceil + 63) / 64;
  const size_t fs = fw + nw_req + nw_ceil;  // full stack-frame width

  KeySet visited;
  visited.init(fw, 1 << 16);
  std::vector<uint64_t> stack;  // frames, popped from the back
  stack.assign(fs, 0);          // initial: empty mask, value 0, version 0
  for (size_t b = n_req; b < nw_req * 64; b++)
    stack[fw + (b >> 6)] |= 1ULL << (b & 63);
  for (size_t b = n_ceil; b < nw_ceil * 64; b++)
    stack[fw + nw_req + (b >> 6)] |= 1ULL << (b & 63);

  int64_t configs = 0;
  int32_t best_depth = -1, blocked_op = -1;
  int32_t blocked_version = 0, blocked_value = 0;
  std::vector<uint64_t> frame(fs), child(fs);

  visited.insert(stack.data());  // dedup happens at PUSH time: a state
  // reachable through many parents is stacked (and its frame copied)
  // only once, instead of being pushed repeatedly and discarded on pop
  while (!stack.empty()) {
    std::memcpy(frame.data(), stack.data() + stack.size() - fs, fs * 8);
    stack.resize(stack.size() - fs);
    if (++configs > max_configs) {
      *configs_out = configs;
      return 2;
    }
    const uint64_t* m = frame.data();
    const uint64_t* pm = frame.data() + fw;
    const int32_t value = static_cast<int32_t>(frame[nw] >> 32);
    const int32_t version =
        static_cast<int32_t>(frame[nw] & 0xffffffffULL);

    size_t r_min = n_req;  // rank of the first unlinearized required op
    for (size_t w = 0; w < nw_req; w++) {
      if (pm[w] != ~0ULL) {
        r_min = (w << 6) + __builtin_ctzll(~pm[w]);
        break;
      }
    }
    if (r_min >= n_req) {  // every required op linearized
      *configs_out = configs;
      *blocked_version_out = version;
      *blocked_value_out = value;
      return 1;
    }

    int32_t min_ceil = INT32_MAX;
    int32_t min_ceil_op = -1;
    const uint64_t* cm = frame.data() + fw + nw_req;
    for (size_t w = 0; w < nw_ceil; w++) {
      if (cm[w] != ~0ULL) {
        const size_t r = (w << 6) + __builtin_ctzll(~cm[w]);
        min_ceil = ceil_order[r].first;
        min_ceil_op = ceil_order[r].second;
        break;
      }
    }
    if (version > min_ceil) {
      // dead: that op can never fire. Keep the counterexample
      // diagnostics the candidate walk would have produced.
      int32_t d = 0;
      for (size_t ww = 0; ww < nw; ww++)
        d += __builtin_popcountll(m[ww]);
      if (d >= best_depth) {
        best_depth = d;
        blocked_op = min_ceil_op;
        blocked_version = version;
        blocked_value = value;
      }
      continue;
    }

    // Two passes: info candidates pushed first, required last, so the
    // LIFO pop explores required ops first — greedy progress on the
    // forced schedule, with crashed ops interleaved only when a
    // required op is blocked. With id-order pushes an info-heavy
    // history makes the DFS burrow through 2^I crashed-op subsets
    // before advancing the schedule at all; witness search on valid
    // histories goes from budget-exhausting to near-linear.
    const uint64_t* enabled = &pre[r_min * nw];
    for (int pass = 0; pass < 2; pass++) {
    for (size_t w = 0; w < nw; w++) {
      uint64_t cand = enabled[w] & ~m[w];
      while (cand) {
        const int32_t e =
            static_cast<int32_t>((w << 6) + __builtin_ctzll(cand));
        cand &= cand - 1;
        if ((pass == 0) == static_cast<bool>(req[e])) continue;
        if (sym_pred[e] >= 0 && !get_bit(m, sym_pred[e])) continue;
        bool ok;
        int32_t nval;
        if (f[e] == F_READ) {
          ok = (ver[e] == NO_ASSERT || ver[e] == version) &&
               (a1[e] == WILDCARD || a1[e] == value);
          nval = value;
        } else if (f[e] == F_WRITE) {
          ok = (ver[e] == NO_ASSERT || ver[e] == version + 1);
          nval = a1[e];
        } else {
          ok = (ver[e] == NO_ASSERT || ver[e] == version + 1) &&
               a1[e] == value;
          nval = a2[e];
        }
        if (!ok) {
          if (req[e]) {
            int32_t d = 0;
            for (size_t ww = 0; ww < nw; ww++)
              d += __builtin_popcountll(m[ww]);
            if (d >= best_depth) {
              best_depth = d;
              blocked_op = e;
              blocked_version = version;
              blocked_value = value;
            }
          }
          continue;
        }
        const int32_t nver = (f[e] == F_READ) ? version : version + 1;
        std::memcpy(child.data(), frame.data(), fs * 8);
        child[e >> 6] |= 1ULL << (e & 63);
        child[nw] =
            (static_cast<uint64_t>(static_cast<uint32_t>(nval)) << 32) |
            static_cast<uint32_t>(nver);
        if (req[e]) {
          const size_t r = rank_of[e];
          child[fw + (r >> 6)] |= 1ULL << (r & 63);
          if (ver[e] != NO_ASSERT) {
            const size_t cr = ceil_rank[e];
            child[fw + nw_req + (cr >> 6)] |= 1ULL << (cr & 63);
          }
        }
        if (visited.insert(child.data()))
          stack.insert(stack.end(), child.begin(), child.end());
      }
    }
    }
  }

  *configs_out = configs;
  *blocked_op_out = blocked_op;
  *best_depth_out = best_depth;
  *blocked_version_out = blocked_version;
  *blocked_value_out = blocked_value;
  return 0;
}
