"""Lock workloads: demonstrations that etcd locks are unsafe.

Re-design of ``lock.clj``. etcd lock acquisition grants a short lease
(TTL 2 s, lock.clj:18-20), keeps it alive from a background task, and
acquires the named lock under that lease (lock.clj:22-56). Because the
lease is timed at the *leader* and reset on leader change, two clients
can genuinely hold the "lock" at once under faults — so ``lock`` and
``lock-set`` are expected to FAIL under nemeses. ``lock-etcd-set`` is
the exception: its txn-level ``version(lock_key) > 0`` guard holds up,
and the reference expects it to pass (WORKLOADS_EXPECTED_TO_PASS
removes only :lock and :lock-set, etcd.clj:51-53).

Three clients:

- LinearizableLockClient (lock.clj:91-134): bare acquire/release ops
  checked against a Knossos-style mutex model;
- LockingSetClient (lock.clj:139-179): an *in-memory* list guarded by
  the etcd lock; the critical section sleeps ~latency, so an expired
  lease lets two holders interleave read-modify-write and lose adds;
- LockingEtcdSetClient (lock.clj:185-228): the list lives in etcd and
  updates are guarded by ``version(lock_key) > 0`` inside the txn
  (lock.clj:214-216) — stronger, but still unsafe: the lock key can
  outlive the holder's critical section entry.

Failed lock *releases* with known errors coerce to :ok — the critical
section is over either way — except :not-held, which must stay a
failure or we'd double-release (lock.clj:66-86).
"""

from __future__ import annotations

from ..core.op import Op
from ..client import with_errors
from ..client import txn as t
from ..checkers import compose
from ..checkers.tpu_linearizable import TPULinearizableChecker
from ..checkers.set_full import SetFull
from ..generators import mix
from ..models import Mutex
from ..runner.sim import current_loop, sleep, SECOND
from ..sut.errors import SimError
from .base import WorkloadClient

LEASE_TTL = 2 * SECOND  # lock.clj:18-20
MS = 1_000_000


async def acquire(conn, lock_name: str, process) -> dict:
    """Grant lease -> spawn keepalive -> acquire lock (lock.clj:22-56).
    On failure, close the keepalive AND revoke the lease: a timed-out
    lock request may still be outstanding server-side and would otherwise
    hold the lock until the lease naturally expires."""
    lease_id = await conn.lease_grant(LEASE_TTL)
    listener = conn.spawn_keepalive(lease_id, LEASE_TTL // 3)
    try:
        lock_key = await conn.acquire_lock(lock_name, lease_id)
        return {"lease-id": lease_id, "listener": listener,
                "lock-key": lock_key, "process": process}
    except BaseException:
        listener.cancel()
        try:
            await conn.lease_revoke(lease_id)
        except (SimError, TimeoutError):
            pass
        raise


async def release(conn, lease_lock: dict) -> None:
    """Stop the keepalive, release the lock, revoke the lease
    (lock.clj:58-64)."""
    lease_lock["listener"].cancel()
    await conn.release_lock(lease_lock["lock-key"])
    await conn.lease_revoke(lease_lock["lease-id"])


def _is_not_held(err) -> bool:
    return (err == "not-held" or
            (isinstance(err, (list, tuple)) and err
             and err[0] == "not-held"))


async def lock_with_errors(op: Op, thunk) -> Op:
    """The lock-specific with-errors (lock.clj:66-86): failed releases
    with known errors still mean the critical section is over -> :ok,
    except :not-held (a double release must stay a failure)."""
    res = await with_errors(op, {"acquire", "release"}, thunk)
    if (op.f == "release" and res["type"] == "fail"
            and not _is_not_held(res.get("error"))):
        return res.evolve(type="ok")
    return res


class LinearizableLockClient(WorkloadClient):
    LOCK = "foo"

    def open(self, test: dict, node: str) -> "LinearizableLockClient":
        new = super().open(test, node)
        new.lease_lock = None  # per-process holding state
        return new

    async def invoke(self, test: dict, op: Op) -> Op:
        async def go():
            if op.f == "acquire":
                if self.lease_lock:
                    return op.evolve(type="fail", error="already-held")
                self.lease_lock = await acquire(self.conn, self.LOCK,
                                                op["process"])
                return op.evolve(type="ok")
            if op.f == "release":
                if not self.lease_lock:
                    return op.evolve(type="fail", error="not-held")
                try:
                    await release(self.conn, self.lease_lock)
                    return op.evolve(type="ok")
                finally:
                    # even if release failed, we stopped renewing; we
                    # will not try again (lock.clj:117-122)
                    self.lease_lock = None
            raise ValueError(f"unknown f {op.f}")

        return await lock_with_errors(op, go)


class LockingSetClient(WorkloadClient):
    """In-memory list guarded by an etcd lock (lock.clj:139-179)."""

    LOCK = "foo"

    def __init__(self, latency_ms: int = 1000):
        super().__init__()
        self.latency_ms = latency_ms
        self.shared = []        # the in-memory set, shared by all opens

    async def invoke(self, test: dict, op: Op) -> Op:
        loop = current_loop()
        added = [False]

        async def go():
            if op.f == "read":
                return op.evolve(type="ok", value=list(self.shared))
            if op.f == "add":
                lease_lock = await acquire(self.conn, self.LOCK,
                                           op["process"])
                v = list(self.shared)
                await sleep(loop.rng.randint(0, 2 * self.latency_ms) * MS)
                self.shared[:] = v + [op.value]
                added[0] = True
                await release(self.conn, lease_lock)
                return op.evolve(type="ok")
            raise ValueError(f"unknown f {op.f}")

        res = await with_errors(op, {"read"}, go)
        if op.f == "add":
            # the add's *effect* is purely the in-memory write: whatever
            # the locking path did, ok iff the write happened
            # (lock.clj:167-177)
            return res.evolve(type="ok" if added[0] else "fail")
        return res


class LockingEtcdSetClient(WorkloadClient):
    """etcd-resident list guarded by lock + txn (lock.clj:185-228)."""

    LOCK = "foo"
    KEY = "a-set"

    def __init__(self, latency_ms: int = 1000):
        super().__init__()
        self.latency_ms = latency_ms

    async def invoke(self, test: dict, op: Op) -> Op:
        loop = current_loop()

        if op.f == "read":
            async def read():
                kv = await self.conn.get(
                    self.KEY, serializable=test.get("serializable", False))
                return op.evolve(type="ok",
                                 value=list(kv["value"]) if kv else None)
            return await with_errors(op, {"read"}, read)

        if op.f == "add":
            async def add():
                lease_lock = await acquire(self.conn, self.LOCK,
                                           op["process"])
                try:
                    async def mutate():
                        kv = await self.conn.get(self.KEY)
                        v = list(kv["value"]) if kv else []
                        await sleep(loop.rng.randint(
                            0, 2 * self.latency_ms) * MS)
                        # guard: the lock key still exists
                        # (lock.clj:214-216 — still unsafe!)
                        r = await self.conn.txn(
                            [t.gt(lease_lock["lock-key"], t.version(0))],
                            [t.put(self.KEY, v + [op.value])])
                        return op.evolve(
                            type="ok" if r["succeeded"] else "fail")
                    return await with_errors(op, set(), mutate)
                finally:
                    try:
                        await release(self.conn, lease_lock)
                    except (SimError, TimeoutError):
                        pass
            return await with_errors(op, {"add"}, add)

        raise ValueError(f"unknown f {op.f}")


def workload(opts: dict) -> dict:
    """Linearizable acquire/release on one lock (lock.clj:238-246)."""
    def acquires(test, ctx):
        return {"f": "acquire", "value": None}

    def releases(test, ctx):
        return {"f": "release", "value": None}

    return {
        "client": LinearizableLockClient(),
        "checker": compose({
            # mutex packs onto the TPU WGL kernel via the CAS-register
            # adapter (ops/wgl.py mutex_adapter); CPU oracle on fallback
            # (the positioned timeline renders at the top of the stack,
            # compose.py — full history, nemesis bands)
            "linear": TPULinearizableChecker(Mutex),
        }),
        "generator": mix([acquires, releases]),
    }


def _set_like_workload(client) -> dict:
    counter = iter(range(10 ** 12))

    def adds(test, ctx):
        return {"f": "add", "value": next(counter)}

    def reads(test, ctx):
        return {"f": "read", "value": None}

    return {
        "client": client,
        "checker": compose({
            "set": SetFull(linearizable=True),
        }),
        "generator": mix([adds, reads]),
    }


class LeaseChurnClient(WorkloadClient):
    """Lease-churn locking: short TTLs and NO keepalive, so leases
    expire constantly and the lock server re-grants after every
    expiry. Checked by checkers/mvcc.py LeaseChurn: no two sessions'
    *certain-hold* windows (clipped at acquire-invoke + TTL) may
    overlap — expired-lease re-grants are excused by the clip, so
    this workload is expected to PASS even under pause faults, unlike
    ``lock``/``lock-set``."""

    LOCK = "churn"

    def open(self, test: dict, node: str) -> "LeaseChurnClient":
        new = super().open(test, node)
        new.lease_lock = None
        return new

    async def invoke(self, test: dict, op: Op) -> Op:
        from ..checkers.mvcc import DEFAULT_LEASE_TTL_MS
        ttl_ns = int(test.get("lease_ttl_ms")
                     or DEFAULT_LEASE_TTL_MS) * MS

        async def go():
            if op.f == "acquire":
                if self.lease_lock:
                    return op.evolve(type="fail", error="already-held")
                lease_id = await self.conn.lease_grant(ttl_ns)
                try:
                    lock_key = await self.conn.acquire_lock(
                        self.LOCK, lease_id)
                except BaseException:
                    try:
                        await self.conn.lease_revoke(lease_id)
                    except (SimError, TimeoutError):
                        pass
                    raise
                self.lease_lock = {"lease-id": lease_id,
                                   "lock-key": lock_key}
                return op.evolve(type="ok")
            if op.f == "release":
                if not self.lease_lock:
                    return op.evolve(type="fail", error="not-held")
                ll, self.lease_lock = self.lease_lock, None
                await self.conn.release_lock(ll["lock-key"])
                await self.conn.lease_revoke(ll["lease-id"])
                return op.evolve(type="ok")
            raise ValueError(f"unknown f {op.f}")

        return await lock_with_errors(op, go)


def lease_workload(opts: dict) -> dict:
    """Acquire/release churn under short, never-renewed leases
    (checkers/mvcc.py LeaseChurn: overlapping certain-hold windows)."""
    from ..checkers.mvcc import LeaseChurn

    def acquires(test, ctx):
        return {"f": "acquire", "value": None}

    def releases(test, ctx):
        return {"f": "release", "value": None}

    return {
        "client": LeaseChurnClient(),
        "checker": compose({"lease": LeaseChurn()}),
        "generator": mix([acquires, releases]),
    }


def set_workload(opts: dict) -> dict:
    """In-memory set under an etcd lock (lock.clj:248-259)."""
    return _set_like_workload(LockingSetClient())


def etcd_set_workload(opts: dict) -> dict:
    """etcd-resident set under an etcd lock (lock.clj:261-268)."""
    return _set_like_workload(LockingEtcdSetClient())
