"""Placeholder: the lock workload lands with the full workload suite."""


def workload(opts):
    raise NotImplementedError("lock workload not yet implemented")
def set_workload(opts):
    raise NotImplementedError("lock-set workload not yet implemented")


def etcd_set_workload(opts):
    raise NotImplementedError("lock-etcd-set workload not yet implemented")
