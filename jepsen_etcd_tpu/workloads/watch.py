"""Placeholder: the watch workload lands with the full workload suite."""


def workload(opts):
    raise NotImplementedError("watch workload not yet implemented")
