"""Watch workload: watchers must observe identical, ordered value streams.

Re-design of ``watch.clj``: the first node-count threads bump one key
``"w"`` with increasing ints; the remaining threads watch it and log the
value sequences they observe. The checker verifies every watcher saw the
same values in the same order (edit distance vs a canonical log,
watch.clj:328-357) and that no watch stream ever delivered a
non-monotonic revision (watch.clj:161-177 throws a *definite*
``:nonmonotonic-watch`` so the op lands in history as an error).

The final phase converges: every watcher repeatedly re-watches until all
watchers reach the same revision (custom converger barrier,
watch.clj:20-137), with a 60 s cap (watch.clj:245-246).
"""

from __future__ import annotations

import itertools
from typing import Any, Callable, Optional

from ..core.op import Op
from ..client import with_errors, client as make_client
from ..checkers.watch import WatchChecker
from ..generators import reserve, each_thread
from ..runner.sim import current_loop, sleep, Event, SECOND
from ..sut.errors import SimError
from .base import WorkloadClient

KEY = "w"
MS = 1_000_000

_INIT = ("init",)
_EVOLVING = ("evolving",)


class ConvergeTimeout(Exception):
    """Deadline passed; carries the thread's partial value."""

    def __init__(self, value):
        super().__init__("converge timeout")
        self.value = value


class ConvergeBroken(Exception):
    """Another participant crashed (the BrokenBarrierException analog)."""


class Converger:
    """N tasks evolve values until (converged? values) holds for all
    non-evolving values and none are initial (watch.clj:20-137)."""

    def __init__(self, n: int, converged: Callable[[list], bool]):
        self.n = n
        self.converged_fn = converged
        self.values: list = [_INIT] * n
        self.crashed = False
        self._next_index = 0
        self._change: Optional[Event] = None

    def _signal(self) -> None:
        if self._change is not None:
            self._change.set()
            self._change = None

    def _stable(self) -> bool:
        return not any(v is _INIT or v is _EVOLVING for v in self.values)

    def _divergent(self) -> bool:
        if any(v is _INIT for v in self.values):
            return True
        vs = [v for v in self.values if v is not _EVOLVING]
        return bool(vs) and not self.converged_fn(vs)

    def _converged(self) -> bool:
        return self._stable() and not self._divergent()

    async def converge(self, timeout_ns: int, init: Any,
                       evolve: Callable) -> Any:
        """Register this task (index = arrival order) and evolve until
        all participants converge. Raises ConvergeTimeout (with the
        partial value) or ConvergeBroken."""
        loop = current_loop()
        deadline = loop.now + timeout_ns
        i = self._next_index
        self._next_index += 1
        while True:
            if self.crashed:
                raise ConvergeBroken("convergence failed")
            if self._converged():
                return self.values[i]
            if loop.now >= deadline:
                raise ConvergeTimeout(self.values[i])
            if self._divergent():
                v = self.values[i]
                v = init if v is _INIT else v
                self.values[i] = _EVOLVING
                try:
                    self.values[i] = await evolve(v)
                except BaseException:
                    self.crashed = True
                    raise
                finally:
                    self._signal()
            else:
                # create the Event synchronously BEFORE yielding: a signal
                # fired between here and the await would otherwise be lost
                if self._change is None:
                    self._change = Event(loop)
                ev = self._change
                timer = loop.call_later(max(0, deadline - loop.now), ev.set)
                try:
                    await ev.wait()
                finally:
                    timer.cancel()


class WatchClient(WorkloadClient):
    def __init__(self):
        super().__init__()
        self.max_revision = [0]      # shared across all opens (an atom)
        self.converger: Optional[Converger] = None

    def open(self, test: dict, node: str) -> "WatchClient":
        new = super().open(test, node)
        new.revision = [0]           # per-client (per-process) revision
        return new

    # -- watch plumbing ------------------------------------------------------

    async def watch_for(self, revision: int, ms: int) -> dict:
        """Watch KEY from revision (exclusive) for ms; returns
        {revision, log} or raises the stream's error
        (watch.clj:139-212)."""
        state = {"revision": revision, "log": [], "revs": []}
        errors: list = []

        def on_events(events):
            if errors:
                return
            # Per-EVENT monotonicity, as the reference checks each event
            # against the last seen revision (watch.clj:161-177) — an
            # intra-batch out-of-order or stale event is an error even if
            # the batch max advances.
            for e in events:
                if not state["revision"] < e.revision:
                    errors.append(SimError(
                        "nonmonotonic-watch",
                        f"got event with revision {e.revision} but we "
                        f"last saw {state['revision']}", definite=True))
                    w.cancel()
                    return
                state["revision"] = e.revision
                state["log"].append(e.kv["value"] if e.kv else None)
                # parallel revision log: lets the checker attribute a
                # missing value to a recorded compaction gap precisely
                state["revs"].append(e.revision)

        def on_error(e):
            errors.append(e)

        # revision is inclusive in the API, so start just past what we
        # have (and never pass 0, which means "from now")
        w = self.conn.watch(KEY, revision + 1, on_events, on_error)
        await sleep(ms * MS)
        w.cancel()
        if errors:
            raise errors[0]
        return state

    def _track(self, res: dict) -> None:
        self.revision[0] = res["revision"]
        self.max_revision[0] = max(self.max_revision[0], res["revision"])

    def _failover(self, test: dict) -> None:
        """Re-pin the connection to a current member. jetcd is built
        with EVERY endpoint and its channel fails over internally when
        a member dies or is removed (client.clj's connect takes the
        full node list); the sim client pins one node, so a watcher
        whose node was shrunk away would otherwise retry connect-failed
        until the converger times out (-> unknown)."""
        db = test.get("db")
        members = sorted(getattr(db, "members", None) or test["nodes"])
        others = [m for m in members if m != self.node] or members
        if not others:
            return
        loop = current_loop()
        new = others[loop.rng.randrange(len(others))]
        try:
            self.conn.close()
        except Exception:
            pass
        self.conn = make_client(test, new)
        self.node = new

    # -- ops -----------------------------------------------------------------

    async def invoke(self, test: dict, op: Op) -> Op:
        loop = current_loop()

        async def go():
            if op.f == "write":
                res = await self.conn.put(KEY, op.value)
                self.max_revision[0] = max(self.max_revision[0],
                                           res["header"]["revision"])
                return op.evolve(type="ok")

            if op.f == "watch":
                res = await self.watch_for(self.revision[0],
                                           loop.rng.randint(0, 5000))
                self._track(res)
                return op.evolve(type="ok", value=res)

            if op.f == "final-watch":
                violations: list = []

                async def evolve(v):
                    try:
                        w = await self.watch_for(
                            v["revision"], loop.rng.randint(0, 5000))
                        self._track(w)
                        return {"revision": w["revision"],
                                "log": v["log"] + w["log"],
                                "revs": v.get("revs", []) + w["revs"],
                                "gaps": v.get("gaps", [])}
                    except (SimError, TimeoutError) as e:
                        # the reference retries EVERY client error here
                        # (watch.clj:258-261 catches client-error?) — a
                        # raise would crash the whole converger; a stuck
                        # watcher surfaces as converge-timeout instead.
                        # A monotonicity violation is retried too, but
                        # the evidence is preserved on the op (the
                        # reference silently drops it here).
                        if isinstance(e, SimError) and \
                                e.type == "nonmonotonic-watch":
                            violations.append(str(e))
                        if isinstance(e, SimError) and \
                                e.type == "connect-failed":
                            # node dead or shrunk away: fail over like
                            # jetcd's multi-endpoint channel would
                            self._failover(test)
                        if isinstance(e, SimError) and \
                                e.type == "compacted":
                            # a watch below the compact horizon can NEVER
                            # proceed: retrying the same revision stalls
                            # the converger until timeout (-> unknown).
                            # Restart past the horizon and record the
                            # unobservable window so the checker can
                            # attribute the missing entries
                            # (watch.clj:243-267 semantics; etcd's
                            # WatchResponse.compact_revision restart).
                            new_rev = getattr(e, "compact_revision", None)
                            if new_rev is None:
                                new_rev = self.max_revision[0]
                            if new_rev > v["revision"]:
                                self.revision[0] = new_rev
                                return {
                                    "revision": new_rev,
                                    "log": v["log"],
                                    "revs": v.get("revs", []),
                                    "gaps": v.get("gaps", []) +
                                            [[v["revision"], new_rev]]}
                        await sleep(1 * SECOND)
                        return v

                def done(type_, value, extra_error=None):
                    err = None
                    if violations:
                        err = ["nonmonotonic-watch"] + violations[:4]
                    elif extra_error:
                        err = extra_error
                    return op.evolve(type=type_, value=value,
                                     **({"error": err} if err else {}))

                try:
                    v = await self.converger.converge(
                        60 * SECOND,
                        {"revision": self.revision[0], "log": []}, evolve)
                    return done("ok", v)
                except ConvergeTimeout as e:
                    val = None if e.value in (_INIT, _EVOLVING) else e.value
                    return done("ok", val,
                                extra_error=["converge-timeout"])
            raise ValueError(f"unknown f {op.f}")

        async def go_with_failover():
            # every op re-pins on connect-failed (jetcd's channel fails
            # over for ALL calls, not just final-watch retries); the op
            # itself still fails honestly — the NEXT op uses the new
            # member
            try:
                return await go()
            except SimError as e:
                if e.type == "connect-failed":
                    self._failover(test)
                raise

        # watch ops must fail definitely: an indefinite error would spin
        # up a fresh client whose re-watch duplicates log entries
        return await with_errors(op, {"watch", "final-watch"},
                                 go_with_failover)


def workload(opts: dict) -> dict:
    node_count = len(opts["nodes"])
    concurrency = opts.get("concurrency") or 2 * node_count
    watch_count = max(1, concurrency - node_count)
    client = WatchClient()

    def converged(ms: list) -> bool:
        # all watchers agree AND have reached the highest revision any
        # writer observed — equality alone would let every watcher
        # converge at the same stale revision, masking a common-tail loss
        revs = {m["revision"] for m in ms}
        return len(revs) == 1 and min(revs) >= client.max_revision[0]

    client.converger = Converger(watch_count, converged)
    counter = itertools.count()

    def write(test, ctx):
        return {"f": "write", "value": next(counter)}

    def watch(test, ctx):
        return {"f": "watch", "value": None}

    return {
        "client": client,
        "checker": WatchChecker(),
        "generator": reserve(node_count, write, watch),
        "final_generator": reserve(
            node_count, None, each_thread({"f": "final-watch"})),
    }
