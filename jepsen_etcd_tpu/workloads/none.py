"""The no-op workload (tests/noop-test analog, etcd.clj:41): a null
client/generator for smoke-testing DB automation and nemeses alone."""

from __future__ import annotations

from ..checkers.core import Noop
from .base import WorkloadClient


class NoopClient(WorkloadClient):
    async def invoke(self, test, op):
        return op.evolve(type="ok")


def workload(opts: dict) -> dict:
    return {"client": NoopClient(), "checker": Noop(), "generator": None}
