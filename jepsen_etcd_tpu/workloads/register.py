"""Register workload: linearizable read/write/CAS on independent keys.

Re-design of ``register.clj``: ops carry ``[version, value]`` pairs; the
client derives the resulting version from etcd's prev-kv (write: prev
version + 1, register.clj:30-34; cas: prev version + 1 from the put's
prev-kv, register.clj:36-44), feeding the VersionedRegister model.

Checked per-key (independent keys, ``2 * node-count`` concurrent keys,
with a reserved read pool of node-count threads, register.clj:102-119).
"""

from __future__ import annotations

from ..core.op import Op
from ..client import with_errors
from ..generators import independent, mix, reserve, limit
from ..models import VersionedRegister
from ..checkers import compose, independent_checker
from ..checkers.session import SessionGuarantees
from ..checkers.tpu_linearizable import CPU_CUTOFF, TPULinearizableChecker
from .base import WorkloadClient


class RegisterClient(WorkloadClient):
    async def invoke(self, test: dict, op: Op) -> Op:
        k, (version, value) = op.value
        key = f"r{k}"

        async def go():
            if op.f == "read":
                kv = await self.conn.get(
                    key, serializable=test.get("serializable", False))
                v = [kv["version"], kv["value"]] if kv else [0, None]
                return op.evolve(type="ok", value=(k, v))
            if op.f == "write":
                r = await self.conn.put(key, value)
                prev = r.get("prev-kv")
                ver = (prev["version"] if prev else 0) + 1
                return op.evolve(type="ok", value=(k, [ver, value]))
            if op.f == "cas":
                old, new = value
                r = await self.conn.cas(key, old, new)
                if r["succeeded"]:
                    prev = r["puts"][0].get("prev-kv")
                    ver = (prev["version"] if prev else 0) + 1
                    return op.evolve(type="ok", value=(k, [ver, value]))
                return op.evolve(type="fail", error="did-not-succeed")
            raise ValueError(f"unknown f {op.f}")

        return await with_errors(op, {"read"}, go)


def r(test, ctx):
    return {"f": "read", "value": [None, None]}


def w(test, ctx):
    return {"f": "write", "value": [None, ctx.rng.randint(0, 4)]}


def cas(test, ctx):
    return {"f": "cas",
            "value": [None, [ctx.rng.randint(0, 4), ctx.rng.randint(0, 4)]]}


def workload(opts: dict) -> dict:
    """Groups of 2n threads work keys one at a time; within a group, n
    threads are a reserved read pool and the rest mix writes and CASes
    (register.clj:113-119: concurrent-generator (* 2 n) keys, reserve n r,
    limit ops-per-key)."""
    n = len(opts["nodes"])
    conc = opts.get("concurrency", 2 * n)
    group = max(1, min(2 * n, conc))
    readers = max(1, group // 2)
    # soak windows rotate key_offset so a retained live cluster never
    # re-serves a key an earlier window already wrote and checked
    k0 = int(opts.get("key_offset") or 0)
    return {
        "client": RegisterClient(),
        "checker": independent_checker(compose({
            # TPU frontier-BFS kernel with sound CPU-oracle fallback
            # (the positioned timeline renders at the top of the stack,
            # compose.py — a per-key subhistory would lose the nemesis
            # bands and clobber timeline.html once per key)
            # force_kernel pins the kernel path (no native-DFS size
            # cutoff): campaign coalescing tests/bench need tiny sim
            # histories to be device-bound even on CPU CI
            "linear": TPULinearizableChecker(
                lambda: VersionedRegister(0, None),
                cpu_cutoff=None if opts.get("force_kernel")
                else CPU_CUTOFF),
            # session guarantees (monotone reads, writes-follow-reads)
            # over the version payloads: strictly weaker than "linear"
            # but localizes WHICH session saw an anomaly, and cheap
            # enough (one vectorized pass) to run on every history
            "session": SessionGuarantees(),
        })),
        "generator": independent.concurrent_generator(
            group,
            range(k0, 10 ** 12),
            lambda k: limit(opts.get("ops_per_key", 200),
                            reserve(readers, r, mix([w, cas])))),
    }
