"""The workload client lifecycle protocol (jepsen.client).

open -> setup -> invoke* -> teardown -> close, driven by the runner;
a worker whose op crashes (:info) gets a fresh client on a fresh process
(jepsen semantics).
"""

from __future__ import annotations

from typing import Any

from ..core.op import Op
from ..client import client as make_client


class WorkloadClient:
    """Subclass and override; self.conn is the connected client."""

    def __init__(self):
        self.conn = None
        self.node = None

    def open(self, test: dict, node: str) -> "WorkloadClient":
        new = self.__class__.__new__(self.__class__)
        new.__dict__.update(self.__dict__)
        new.conn = make_client(test, node)
        new.node = node
        return new

    async def setup(self, test: dict) -> None:
        pass

    async def invoke(self, test: dict, op: Op) -> Op:
        raise NotImplementedError

    async def teardown(self, test: dict) -> None:
        pass

    def close(self, test: dict) -> None:
        if self.conn is not None:
            self.conn.close()
