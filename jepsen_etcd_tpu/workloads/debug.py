"""Debug-mode value provenance (``--debug``).

Re-designs ``append.clj:34-54`` / ``wr.clj:18-35``: when the test map has
``debug`` set, every value written to the SUT is wrapped as

    {"time": <virtual seconds>, "dir": <store run dir name>,
     "txn": <the generating op's txn>, "process": <op.process>,
     "value": <the real value>}

so histories are self-describing — a value read back identifies exactly
which run, txn, and process produced it (the reference used this to
track down an etcdctl state-leak across runs, etcd.clj:259-346).
``decode_get`` strips the wrapper on read so checkers see clean values;
the raw responses land on the op's ``debug`` field for the forensics
helpers (jepsen_etcd_tpu.forensics).
"""

from __future__ import annotations

import os
from typing import Any

from ..core.op import Op
from ..runner.sim import current_loop, SECOND


def encode_put(test: dict, op: Op, value: Any) -> Any:
    """Wrap a to-be-written value with provenance in debug mode
    (append.clj:34-45, wr.clj:18-27)."""
    if not test.get("debug"):
        return value
    store_dir = test.get("store_dir", "")
    return {
        "time": current_loop().now / SECOND,
        "dir": os.path.basename(os.path.dirname(store_dir)) + "/"
               + os.path.basename(store_dir) if store_dir else "",
        "txn": list(op.value) if isinstance(op.value, (list, tuple))
               else op.value,
        "process": op.get("process"),
        "value": value,
    }


def decode_get(test: dict, value: Any) -> Any:
    """Strip the provenance wrapper from a read value
    (append.clj:47-54, wr.clj:29-35)."""
    if test.get("debug") and isinstance(value, dict) and "value" in value:
        return value["value"]
    return value


def attach_debug(test: dict, op: Op, **responses) -> Op:
    """In debug mode, record raw phase responses on the op's ``debug``
    field (the reference keeps :debug {:read-res ... :txn-res ...} on
    append/wr ops; forensics reads them back, etcd.clj:302-336)."""
    if not test.get("debug"):
        return op
    return op.evolve(debug={k.replace("_", "-"): v
                            for k, v in responses.items()})
