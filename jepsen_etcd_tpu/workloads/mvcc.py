"""MVCC consistency-surface workloads: bounded-staleness reads,
snapshot ranges, compaction-vs-watch stress.

Three of the four consumers of the MVCC model (core/mvcc.py) — the
fourth, lease churn, extends workloads/lock.py. Each workload trades
the linearizable register's strong claim for a *weaker, still
falsifiable* one (ROADMAP direction 1: the judged raft bug lives in
exactly these surfaces), checked by checkers/mvcc.py:

- ``register-stale``: reads are **serializable** (node-local, so
  legitimately stale under partition) over a small fixed key set; ops
  carry ``[key, version, value]`` so the checker can bound the
  staleness instead of demanding linearizability.
- ``ranges``: writers bump per-key versions while readers fetch ALL
  keys in one txn (the pagination analog); a range must observe a
  version vector that was current at some instant.
- ``compact-watch``: writers bump one key, a dedicated thread
  compacts aggressively behind the head, and watchers log the
  revision streams they observe — recording an explicit gap whenever
  a compaction forces a restart past the horizon, so every missing
  event is attributable.
"""

from __future__ import annotations

from ..core.op import Op
from ..client import with_errors
from ..client import txn as t
from ..checkers import compose
from ..checkers.mvcc import (BoundedStaleness, CompactionWatch,
                             SnapshotRanges)
from ..generators import reserve
from ..runner.sim import current_loop, sleep
from ..sut.errors import SimError
from .base import WorkloadClient

MS = 1_000_000

#: revisions retained behind the head on each compaction (aggressive:
#: watchers that lag by more than this cross the horizon)
DEFAULT_COMPACT_KEEP = 8


def _key_count(opts: dict) -> int:
    conc = opts.get("concurrency") or 2 * len(opts["nodes"])
    return max(2, int(conc) // 4)


# -- register-stale ----------------------------------------------------------

class RegisterStaleClient(WorkloadClient):
    """Serializable reads + writes on fixed keys ``s0..s{K-1}``; ops
    carry flat ``[key, version, value]`` payloads."""

    async def invoke(self, test: dict, op: Op) -> Op:
        k = op.value[0]
        key = f"s{k}"

        async def go():
            if op.f == "read":
                kv = await self.conn.get(key, serializable=True)
                if kv:
                    return op.evolve(type="ok",
                                     value=[k, kv["version"], kv["value"]])
                return op.evolve(type="ok", value=[k, 0, None])
            if op.f == "write":
                v = op.value[2]
                r = await self.conn.put(key, v)
                prev = r.get("prev-kv")
                ver = (prev["version"] if prev else 0) + 1
                return op.evolve(type="ok", value=[k, ver, v])
            raise ValueError(f"unknown f {op.f}")

        return await with_errors(op, {"read"}, go)


def workload(opts: dict) -> dict:
    """Bounded-staleness register: half the threads are a reserved
    serializable-read pool, the rest write; the checker verifies the
    staleness surface instead of linearizability."""
    n = len(opts["nodes"])
    conc = opts.get("concurrency") or 2 * n
    readers = max(1, conc // 2)
    keys = _key_count(opts)

    def r(test, ctx):
        return {"f": "read", "value": [ctx.rng.randrange(keys), None, None]}

    def w(test, ctx):
        return {"f": "write", "value": [ctx.rng.randrange(keys), None,
                                        ctx.rng.randint(0, 4)]}

    return {
        "client": RegisterStaleClient(),
        "checker": compose({"staleness": BoundedStaleness()}),
        "generator": reserve(readers, r, w),
    }


# -- ranges ------------------------------------------------------------------

class RangesClient(WorkloadClient):
    """Writers bump ``g0..g{K-1}``; a range reads ALL keys in one txn
    (leader-atomic), acking ``[[key, version], ...]``."""

    def __init__(self, keys: int):
        super().__init__()
        self.keys = keys

    async def invoke(self, test: dict, op: Op) -> Op:
        async def go():
            if op.f == "range":
                gets = [t.get(f"g{i}") for i in range(self.keys)]
                res = await self.conn.txn([], gets)
                vec = [[i, kv["version"] if kv else 0]
                       for i, kv in enumerate(res["gets"])]
                return op.evolve(type="ok", value=vec)
            if op.f == "write":
                k, _, v = op.value
                r = await self.conn.put(f"g{k}", v)
                prev = r.get("prev-kv")
                ver = (prev["version"] if prev else 0) + 1
                return op.evolve(type="ok", value=[k, ver, v])
            raise ValueError(f"unknown f {op.f}")

        return await with_errors(op, {"range"}, go)


def ranges_workload(opts: dict) -> dict:
    """Snapshot-consistency ranges: multi-key reads must not tear
    across a revision boundary."""
    n = len(opts["nodes"])
    conc = opts.get("concurrency") or 2 * n
    readers = max(1, conc // 2)
    keys = _key_count(opts)

    def rng_gen(test, ctx):
        return {"f": "range", "value": None}

    def w(test, ctx):
        return {"f": "write", "value": [ctx.rng.randrange(keys), None,
                                        ctx.rng.randint(0, 4)]}

    return {
        "client": RangesClient(keys),
        "checker": compose({"ranges": SnapshotRanges()}),
        "generator": reserve(readers, rng_gen, w),
    }


# -- compact-watch -----------------------------------------------------------

KEY = "cw"


class CompactWatchClient(WorkloadClient):
    """Writers bump KEY acking ``[revision, value]``; a compactor
    trails the head by ``compact_keep`` revisions; watchers log the
    revision streams they observe, recording explicit gaps whenever a
    compaction forces a restart past the horizon."""

    def open(self, test: dict, node: str) -> "CompactWatchClient":
        new = super().open(test, node)
        new.last_seen = [0]          # per-process watch cursor
        return new

    async def _watch_once(self, ms: int) -> dict:
        from_rev = self.last_seen[0]
        state = {"rev": from_rev, "revs": [], "log": []}
        gaps: list = []
        errors: list = []

        def on_events(events):
            if errors:
                return
            for e in events:
                state["rev"] = max(state["rev"], e.revision)
                state["revs"].append(e.revision)
                state["log"].append(e.kv["value"] if e.kv else None)

        def on_error(e):
            errors.append(e)

        w = self.conn.watch(KEY, state["rev"] + 1, on_events, on_error)
        await sleep(ms * MS)
        w.cancel()
        if errors:
            e = errors[0]
            if isinstance(e, SimError) and e.type == "compacted":
                # unobservable window: record it so the checker can
                # attribute the missing revisions, restart past it
                new_rev = getattr(e, "compact_revision", None)
                if new_rev and new_rev > state["rev"]:
                    gaps.append([state["rev"], new_rev])
                    state["rev"] = new_rev
            else:
                raise e
        self.last_seen[0] = state["rev"]
        return {"from": from_rev, "revs": state["revs"], "gaps": gaps,
                "log": state["log"]}

    async def invoke(self, test: dict, op: Op) -> Op:
        loop = current_loop()
        keep = int(test.get("compact_keep") or DEFAULT_COMPACT_KEEP)

        async def go():
            if op.f == "write":
                res = await self.conn.put(KEY, op.value)
                return op.evolve(
                    type="ok",
                    value=[res["header"]["revision"], op.value])
            if op.f == "compact":
                rev = await self.conn.revision()
                target = rev - keep
                if target >= 1:
                    await self.conn.compact(target, physical=True)
                    return op.evolve(type="ok", value=target)
                return op.evolve(type="ok", value=0)
            if op.f == "watch":
                res = await self._watch_once(loop.rng.randint(0, 3000))
                return op.evolve(type="ok", value=res)
            raise ValueError(f"unknown f {op.f}")

        # watch/compact must fail definitely: an indefinite watch
        # would re-deliver its window through a fresh process
        return await with_errors(op, {"watch", "compact"}, go)


def compact_watch_workload(opts: dict) -> dict:
    """Compaction-vs-watch stress: one thread compacts aggressively
    behind the head while watchers lag; every lost event must be
    attributable to a compaction."""
    import itertools
    n = len(opts["nodes"])
    conc = opts.get("concurrency") or 2 * n
    writers = max(1, min(n, conc - 2))
    counter = itertools.count()

    def write(test, ctx):
        return {"f": "write", "value": next(counter)}

    def compact(test, ctx):
        return {"f": "compact", "value": None}

    def watch(test, ctx):
        return {"f": "watch", "value": None}

    return {
        "client": CompactWatchClient(),
        "checker": compose({"watch-mvcc": CompactionWatch()}),
        "generator": reserve(1, compact, writers, write, watch),
    }
