"""Workload registry (the analog of the workloads map, etcd.clj:33-45)."""

from __future__ import annotations


def workloads() -> dict:
    from . import (register, set as set_wl, append, wr, watch, lock,
                   mvcc, none)
    return {
        "append": append.workload,
        "compact-watch": mvcc.compact_watch_workload,
        "lock": lock.workload,
        "lock-lease": lock.lease_workload,
        "lock-set": lock.set_workload,
        "lock-etcd-set": lock.etcd_set_workload,
        "none": none.workload,
        "ranges": mvcc.ranges_workload,
        "register": register.workload,
        "register-stale": mvcc.workload,
        "set": set_wl.workload,
        "watch": watch.workload,
        "wr": wr.workload,
    }


#: workloads run by test-all's default sweep (all-workloads,
#: etcd.clj:47-49: everything but :none)
ALL_WORKLOADS = [
    "append", "compact-watch", "lock", "lock-etcd-set", "lock-lease",
    "lock-set", "ranges", "register", "register-stale", "set",
    "watch", "wr"]

#: workloads expected to pass (etcd.clj:51-53): removes only :lock and
#: :lock-set — lock-etcd-set's txn guard (version(lock_key) > 0) makes it
#: safe enough to pass, and empirically it does in the sim too. The MVCC
#: consistency surfaces (register-stale, ranges, lock-lease,
#: compact-watch) check claims weak enough to survive the fault matrix:
#: bounded staleness excuses fault-window lag, lease holds are clipped
#: at the TTL, and watch losses are attributable to recorded
#: compactions — so all four are expected to pass.
WORKLOADS_EXPECTED_TO_PASS = [
    "append", "compact-watch", "lock-etcd-set", "lock-lease", "ranges",
    "register", "register-stale", "set", "watch", "wr"]
