"""Workload registry (the analog of the workloads map, etcd.clj:33-45)."""

from __future__ import annotations


def workloads() -> dict:
    from . import register, set as set_wl, append, wr, watch, lock, none
    return {
        "append": append.workload,
        "lock": lock.workload,
        "lock-set": lock.set_workload,
        "lock-etcd-set": lock.etcd_set_workload,
        "none": none.workload,
        "register": register.workload,
        "set": set_wl.workload,
        "watch": watch.workload,
        "wr": wr.workload,
    }


#: workloads expected to pass (etcd.clj:47-53): everything but the lock
#: family, which demonstrates that etcd locks are unsafe.
WORKLOADS_EXPECTED_TO_PASS = [
    "append", "none", "register", "set", "watch", "wr"]
