"""Workload registry (the analog of the workloads map, etcd.clj:33-45)."""

from __future__ import annotations


def workloads() -> dict:
    from . import register, set as set_wl, append, wr, watch, lock, none
    return {
        "append": append.workload,
        "lock": lock.workload,
        "lock-set": lock.set_workload,
        "lock-etcd-set": lock.etcd_set_workload,
        "none": none.workload,
        "register": register.workload,
        "set": set_wl.workload,
        "watch": watch.workload,
        "wr": wr.workload,
    }


#: workloads run by test-all's default sweep (all-workloads,
#: etcd.clj:47-49: everything but :none)
ALL_WORKLOADS = [
    "append", "lock", "lock-etcd-set", "lock-set",
    "register", "set", "watch", "wr"]

#: workloads expected to pass (etcd.clj:51-53): removes only :lock and
#: :lock-set — lock-etcd-set's txn guard (version(lock_key) > 0) makes it
#: safe enough to pass, and empirically it does in the sim too
WORKLOADS_EXPECTED_TO_PASS = [
    "append", "lock-etcd-set", "register", "set", "watch", "wr"]
