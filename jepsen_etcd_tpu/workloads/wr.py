"""Placeholder: the wr workload lands with the full workload suite."""


def workload(opts):
    raise NotImplementedError("wr workload not yet implemented")
