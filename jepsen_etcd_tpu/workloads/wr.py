"""WR workload: transactional register reads/writes, checked by Elle.

Re-design of ``wr.clj``: each op's value is a list of micro-ops
``["r", k, None]`` / ``["w", k, v]``; the whole txn executes as a *single*
etcd transaction (no guards — a batch of gets/puts commits atomically,
wr.clj:37-45), and read results are stitched back into the txn
(wr.clj:63-69). Checked by the Elle rw-register analog with
strict-serializable + wfr-keys (wr.clj:87-92).
"""

from __future__ import annotations

from ..core.op import Op
from ..client import with_errors
from ..client import txn as t
from ..checkers.elle.wr import RWRegisterChecker
from ..generators.elle import rw_register_gen
from .base import WorkloadClient
from .debug import encode_put, decode_get, attach_debug


def ekey(k) -> str:
    return f"w{k}"


class WrTxnClient(WorkloadClient):
    async def invoke(self, test: dict, op: Op) -> Op:
        async def go():
            mops = op.value
            ast = [t.get(ekey(k)) if f == "r"
                   else t.put(ekey(k), encode_put(test, op, v))
                   for f, k, v in mops]
            res = await self.conn.txn([], ast)
            if not res["succeeded"]:
                return attach_debug(test, op.evolve(
                    type="fail", error="didnt-succeed"), txn_res=res)
            txn_out = []
            for (f, k, v), (_, payload) in zip(mops, res["results"]):
                if f == "w":
                    txn_out.append([f, k, v])
                else:
                    txn_out.append(
                        [f, k, decode_get(test, payload["value"])
                         if payload else None])
            return attach_debug(test, op.evolve(type="ok", value=txn_out),
                                txn_res=res)

        return await with_errors(op, set(), go)


def workload(opts: dict) -> dict:
    return {
        "client": WrTxnClient(),
        "checker": RWRegisterChecker(
            consistency_models=["strict-serializable"], wfr_keys=True),
        "generator": rw_register_gen(key_count=3, max_txn_length=4),
    }
