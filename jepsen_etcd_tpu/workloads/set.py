"""Placeholder: the set workload lands with the full workload suite."""


def workload(opts):
    raise NotImplementedError("set workload not yet implemented")
