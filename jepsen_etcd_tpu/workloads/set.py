"""Set workload: unique integers added to one key via retried CAS.

Re-design of ``set.clj``: a single key ``"a-set"`` holds the whole set;
``add`` ops append their element through the client's CAS-retry ``swap``
(set.clj:25-26 → client.clj:511-527), ``read`` ops fetch the full set
(serializable reads when the test says so, set.clj:21-23). Checked with
set-full in linearizable mode (set.clj:46); generator reserves 5 reader
threads, the rest add increasing ints (set.clj:47).
"""

from __future__ import annotations

import itertools

from ..core.op import Op
from ..client import with_errors
from ..generators import reserve
from ..checkers.set_full import SetFull
from .base import WorkloadClient

KEY = "a-set"


class SetClient(WorkloadClient):
    async def invoke(self, test: dict, op: Op) -> Op:
        async def go():
            if op.f == "read":
                kv = await self.conn.get(
                    KEY, serializable=test.get("serializable", False))
                return op.evolve(type="ok",
                                 value=list(kv["value"]) if kv else [])
            if op.f == "add":
                # conj on a set: append-if-absent, kept sorted for
                # deterministic read values
                def conj(s):
                    cur = list(s or [])
                    if op.value not in cur:
                        cur = sorted(cur + [op.value])
                    return cur
                await self.conn.swap(KEY, conj)
                return op.evolve(type="ok")
            raise ValueError(f"unknown f {op.f}")

        return await with_errors(op, {"read"}, go)

    async def setup(self, test: dict) -> None:
        await self.conn.put(KEY, [])


def workload(opts: dict) -> dict:
    counter = itertools.count()

    def r(test, ctx):
        return {"f": "read", "value": None}

    def w(test, ctx):
        return {"f": "add", "value": next(counter)}

    return {
        "client": SetClient(),
        "checker": SetFull(linearizable=True),
        "generator": reserve(5, r, w),
    }
