"""Append workload: transactional list-appends, checked by Elle.

Re-design of ``append.clj``: ops are txns of ``["r", k, None]`` /
``["append", k, v]`` micro-ops, executed as an *optimistic two-phase*
etcd transaction:

1. read phase (append.clj:64-83): one txn of gets over the written keys,
   recording each key's value + mod-revision and the header revision;
2. write phase (append.clj:121-158): a guarded If/Then/Else txn —
   per written key present at read time, guard
   ``mod_revision(k) == seen`` ; per absent key, guard
   ``mod_revision(k) < read_revision`` (append.clj:85-97) — whose then
   branch replays the txn, turning each append into a put of the full
   list with the new element conj'd on, playing multi-append state
   forward (append.clj:99-119), and each read into a get.

If the guards fail the op is a definite :fail (:didnt-succeed,
append.clj:156-158). Read results from the write txn are stitched back
into the micro-ops.
"""

from __future__ import annotations

from ..core.op import Op
from ..client import with_errors
from ..client import txn as t
from ..checkers.elle.append import ListAppendChecker
from ..generators.elle import list_append_gen
from .base import WorkloadClient
from .debug import encode_put, decode_get, attach_debug


def ekey(k) -> str:
    return f"a{k}"


class AppendTxnClient(WorkloadClient):
    async def invoke(self, test: dict, op: Op) -> Op:
        async def go():
            mops = op.value
            written = sorted({k for f, k, _ in mops if f == "append"})

            # phase 1: read current state of all written keys
            reads: dict = {}
            read_revision = 0
            if written:
                res = await self.conn.txn([], [t.get(ekey(k))
                                               for k in written])
                read_revision = res["header"]["revision"]
                for k, (_, kv) in zip(written, res["results"]):
                    reads[k] = kv  # kv map or None

            # phase 2: guards + replayed write txn
            guards = []
            for k in written:
                kv = reads[k]
                if kv is not None:
                    guards.append(t.eq(ekey(k),
                                       t.mod_revision(kv["mod-revision"])))
                else:
                    guards.append(t.lt(ekey(k),
                                       t.mod_revision(read_revision)))
            state = {k: list(decode_get(test, kv["value"]))
                     for k, kv in reads.items() if kv is not None}
            ast = []
            for f, k, v in mops:
                if f == "r":
                    ast.append(t.get(ekey(k)))
                else:
                    state[k] = state.get(k, []) + [v]
                    ast.append(t.put(ekey(k),
                                     encode_put(test, op, list(state[k]))))
            res = await self.conn.txn(guards, ast)
            if not res["succeeded"]:
                return attach_debug(test, op.evolve(
                    type="fail", error="didnt-succeed"),
                    read_res={"reads": reads,
                              "read-revision": read_revision},
                    txn_res=res)
            txn_out = []
            for (f, k, v), (_, payload) in zip(mops, res["results"]):
                if f == "append":
                    txn_out.append([f, k, v])
                else:
                    val = decode_get(test, payload["value"]) \
                        if payload else None
                    txn_out.append([f, k, list(val)
                                    if val is not None else None])
            return attach_debug(
                test, op.evolve(type="ok", value=txn_out),
                read_res={"reads": reads, "read-revision": read_revision},
                txn_res=res)

        return await with_errors(op, set(), go)


def workload(opts: dict) -> dict:
    return {
        "client": AppendTxnClient(),
        "checker": ListAppendChecker(
            consistency_models=["strict-serializable"]),
        "generator": list_append_gen(key_count=3, max_txn_length=4),
    }
