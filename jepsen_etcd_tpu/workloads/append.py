"""Placeholder: the append workload lands with the full workload suite."""


def workload(opts):
    raise NotImplementedError("append workload not yet implemented")
