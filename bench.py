#!/usr/bin/env python
"""The benchmark suite (BASELINE.md configs 1-5).

Headline (north star): a 10k-op single-key register history verified
linearizable on TPU; the reference's CPU Knossos cannot verify it within
60 s (BASELINE.md "North star"), so vs_baseline = 60s / wall-clock.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline",
"matrix": {...}} — the matrix carries BASELINE.md's other configs
(register-100 CPU-vs-TPU, deep WGL at 4n/2000, set-full, Elle append at
device-closure scale, watch edit-distance), each with wall-clock and
search stats (peak frontier, spill, device usage).
"""

import json
import sys
import time

sys.path.insert(0, ".")

N_OPS = 13_500  # ~10k :ok ops after failed-CAS exclusion
CONCURRENCY = 8
BASELINE_SECONDS = 60.0  # CPU Knossos budget it cannot meet


def sim_register_history(n_ops, concurrency, seed=2026, name="bench",
                         nodes=None):
    """n_ops on ONE key via the simulated cluster (fast: virtual time)."""
    from jepsen_etcd_tpu.compose import etcd_test
    from jepsen_etcd_tpu.runner.test_runner import run_test
    from jepsen_etcd_tpu.generators import limit, mix, reserve, independent
    from jepsen_etcd_tpu.workloads.register import (RegisterClient, r, w,
                                                    cas)
    from jepsen_etcd_tpu.checkers.core import Noop

    test = etcd_test({
        "workload": "none",
        "time_limit": 3600, "rate": 0, "seed": seed,
        "concurrency": concurrency, "store_base": "store",
        **({"nodes": nodes} if nodes else {}),
        # generation is checker-input prep, not the thing benchmarked:
        # frequent snapshots make the sim O(ops * store-size) (every
        # count applies re-encodes the whole store and triggers
        # follower installs)
        "snapshot_count": 100_000,
    })
    test["name"] = name
    test["client"] = RegisterClient()
    test["checker"] = Noop()
    test["generator"] = independent.concurrent_generator(
        concurrency, [0],
        lambda k: limit(n_ops, reserve(concurrency // 2, r, mix([w, cas]))))
    out = run_test(test)
    from jepsen_etcd_tpu.generators.independent import subhistory
    from jepsen_etcd_tpu.core.history import History
    return History(subhistory(out["history"], 0))


def run_workload(workload, seed=7, time_limit=40, rate=200, **opts):
    from jepsen_etcd_tpu.compose import etcd_test
    from jepsen_etcd_tpu.runner.test_runner import run_test
    o = {"workload": workload, "time_limit": time_limit, "rate": rate,
         "seed": seed, "store_base": "store"}
    o.update(opts)
    test = etcd_test(o)
    return test, run_test(test)


def note(msg):
    print(f"# {msg}", file=sys.stderr)


def bench_register_10k():
    """North star: 10k-op single-key check (config #1's big sibling)."""
    from jepsen_etcd_tpu.ops import wgl
    t0 = time.time()
    h = sim_register_history(N_OPS, CONCURRENCY, name="bench-register-10k")
    note(f"10k: generated {len(h)} ops in {time.time()-t0:.1f}s")
    p = wgl.pack_register_history(h)
    assert p.ok, p.reason
    wgl.check_packed(p)  # warmup: compile + first search
    t1 = time.time()
    out = wgl.check_packed(p)
    dt = time.time() - t1
    note(f"10k: verdict={out['valid?']} waves={out.get('waves')} "
         f"peak={out.get('peak-frontier')} w={p.w} in {dt:.3f}s")
    assert out["valid?"] is True, out
    return dt, out, p


def bench_register_100():
    """Config #1: 1 key, ops-per-key 100 — the regime the reference's
    CPU Knossos competes in; report CPU oracle vs TPU kernel."""
    from jepsen_etcd_tpu.ops import wgl
    from jepsen_etcd_tpu.checkers.linearizable import check_history
    from jepsen_etcd_tpu.models import VersionedRegister
    h = sim_register_history(135, CONCURRENCY, seed=11,
                             name="bench-register-100")
    p = wgl.pack_register_history(h)
    assert p.ok, p.reason
    t0 = time.time()
    cpu = check_history(VersionedRegister(), h, use_native=False)
    cpu_s = time.time() - t0
    from jepsen_etcd_tpu.native import get_lib
    get_lib()  # warmup: one-time g++ build must not land in the timer
    t0 = time.time()
    nat = check_history(VersionedRegister(), h)
    native_s = time.time() - t0
    wgl.check_packed(p)
    t1 = time.time()
    tpu = wgl.check_packed(p)
    tpu_s = time.time() - t1
    # the production checker routes this size to the native DFS via the
    # size cutoff (checkers/tpu_linearizable.py CPU_CUTOFF)
    from jepsen_etcd_tpu.checkers.tpu_linearizable import (
        TPULinearizableChecker)
    prod = TPULinearizableChecker()
    t1 = time.time()
    pres = prod.check({}, h)
    prod_s = time.time() - t1
    assert tpu["valid?"] is True and cpu["valid?"] is True
    assert nat["valid?"] is True and pres["valid?"] is True
    note(f"100-op: cpu={cpu_s:.4f}s native={native_s:.4f}s "
         f"tpu={tpu_s:.4f}s production={prod_s:.4f}s "
         f"({pres['checker']})")
    return {"value": round(prod_s, 4), "unit": "s",
            "cpu_oracle_s": round(cpu_s, 4),
            "native_oracle_s": round(native_s, 4),
            "tpu_kernel_s": round(tpu_s, 4),
            "production_engine": pres["checker"],
            "ops": p.R, "vs_baseline": round(BASELINE_SECONDS / max(
                prod_s, 1e-9), 1)}


def bench_deep_wgl():
    """Config #2: concurrency 4n (=20), ops-per-key 2000 — deep
    permutation search; records peak frontier + spill stats."""
    from jepsen_etcd_tpu.ops import wgl
    h = sim_register_history(2600, 20, seed=5, name="bench-register-deep")
    p = wgl.pack_register_history(h)
    assert p.ok, p.reason
    # deep searches overflow the 128 rung immediately; start at 512 to
    # skip one heavy w=64 compile in the warmup
    wgl.check_packed(p, f_max=wgl.F_MAX)
    t0 = time.time()
    out = wgl.check_packed(p, f_max=wgl.F_MAX)
    dt = time.time() - t0
    note(f"deep 4n/2000: verdict={out['valid?']} w={p.w} "
         f"peak={out.get('peak-frontier')} spilled={out.get('spilled')} "
         f"in {dt:.3f}s")
    assert out["valid?"] is True, out
    return {"value": round(dt, 4), "unit": "s", "ops": p.R, "w": p.w,
            "peak_frontier": out.get("peak-frontier"),
            "spilled": bool(out.get("spilled")),
            "vs_baseline": round(BASELINE_SECONDS / max(dt, 1e-9), 1)}


def bench_batched_keys():
    """The production key-DP axis (SURVEY §2.3): 64 independent keys
    packed into vmapped kernel launches, key axis sharded over the
    device mesh. One sim run generates all keys' histories; the timed
    region is the whole batched check."""
    from jepsen_etcd_tpu.compose import etcd_test
    from jepsen_etcd_tpu.runner.test_runner import run_test
    from jepsen_etcd_tpu.generators import limit, mix, reserve, independent
    from jepsen_etcd_tpu.generators.independent import subhistory
    from jepsen_etcd_tpu.core.history import History
    from jepsen_etcd_tpu.workloads.register import RegisterClient, r, w, cas
    from jepsen_etcd_tpu.checkers.core import Noop
    from jepsen_etcd_tpu.ops import wgl

    K = 64
    test = etcd_test({"workload": "none", "time_limit": 3600, "rate": 0,
                      "seed": 3, "concurrency": 8, "store_base": "store",
                      "snapshot_count": 100_000})
    test["name"] = "bench-batched-keys"
    test["client"] = RegisterClient()
    test["checker"] = Noop()
    test["generator"] = independent.concurrent_generator(
        8, list(range(K)),
        lambda k: limit(200, reserve(4, r, mix([w, cas]))))
    out = run_test(test)
    subs = {k: History(subhistory(out["history"], k)) for k in range(K)}
    packs = [wgl.pack_register_history(subs[k]) for k in range(K)]
    ok_packs = [p for p in packs if p.ok]
    wgl.check_packed_batch(packs)  # warmup compiles
    t0 = time.time()
    results = wgl.check_packed_batch(packs)
    dt = time.time() - t0
    valid = sum(1 for res in results if res.get("valid?") is True)
    note(f"batched {K} keys (kernel): {valid} valid, {len(ok_packs)} "
         f"packed, in {dt:.3f}s ({K/max(dt,1e-9):.0f} keys/s)")
    assert valid == K, results
    # production path: check_batch's size cutoff answers keys this small
    # from the native DFS without any device dispatch
    from jepsen_etcd_tpu.checkers.tpu_linearizable import (
        TPULinearizableChecker)
    prod = TPULinearizableChecker()
    t0 = time.time()
    pres = prod.check_batch({}, subs)
    prod_s = time.time() - t0
    engines = {}
    for r in pres.values():
        engines[r.get("checker")] = engines.get(r.get("checker"), 0) + 1
    assert all(r["valid?"] is True for r in pres.values())
    note(f"batched {K} keys (production): engines={engines} "
         f"in {prod_s:.3f}s")
    # headline value pins the PRODUCTION engine (matching
    # bench_register_100); kernel_s tracks the device path separately
    # so a regression in either series stays visible
    return {"value": round(prod_s, 4), "unit": "s", "keys": K,
            "kernel_s": round(dt, 4), "production_s": round(prod_s, 4),
            "engines": engines,
            "keys_per_s": round(K / max(prod_s, 1e-9), 1),
            "vs_baseline": round(BASELINE_SECONDS / max(prod_s, 1e-9), 1)}


def bench_register_50k():
    """Scale cell (VERDICT r3 #7): >=50k-op single-key history — 5x the
    north star — recording where the ladder/spill boundaries land."""
    from jepsen_etcd_tpu.ops import wgl
    t0 = time.time()
    h = sim_register_history(67_500, CONCURRENCY, seed=17,
                             name="bench-register-50k",
                             nodes=["n1", "n2", "n3"])
    note(f"50k: generated {len(h)} ops in {time.time()-t0:.1f}s")
    p = wgl.pack_register_history(h)
    assert p.ok, p.reason
    wgl.check_packed(p)  # warmup: compile + first search
    t1 = time.time()
    out = wgl.check_packed(p)
    dt = time.time() - t1
    note(f"50k: verdict={out['valid?']} waves={out.get('waves')} "
         f"peak={out.get('peak-frontier')} w={p.w} "
         f"spilled={out.get('spilled')} in {dt:.3f}s")
    assert out["valid?"] is True, out
    return {"value": round(dt, 4), "unit": "s", "ops": p.R, "w": p.w,
            "waves": out.get("waves"),
            "peak_frontier": out.get("peak-frontier"),
            "spilled": bool(out.get("spilled")),
            "vs_baseline": round(BASELINE_SECONDS / max(dt, 1e-9), 1)}


def bench_batched_512_keys():
    """Scale cell (VERDICT r3 #7): 512 independent keys in vmapped
    kernel launches, key axis sharded over the device mesh — the key-DP
    axis at 8x the round-2 batch."""
    from jepsen_etcd_tpu.compose import etcd_test
    from jepsen_etcd_tpu.runner.test_runner import run_test
    from jepsen_etcd_tpu.generators import limit, mix, reserve, independent
    from jepsen_etcd_tpu.generators.independent import subhistory
    from jepsen_etcd_tpu.core.history import History
    from jepsen_etcd_tpu.workloads.register import RegisterClient, r, w, cas
    from jepsen_etcd_tpu.checkers.core import Noop
    from jepsen_etcd_tpu.ops import wgl

    K = 512
    t0 = time.time()
    # 3 nodes: replication fan-out dominates generation wall-clock and
    # the checker input doesn't care about cluster size
    test = etcd_test({"workload": "none", "time_limit": 36_000, "rate": 0,
                      "seed": 29, "concurrency": 16, "store_base": "store",
                      "nodes": ["n1", "n2", "n3"],
                      "snapshot_count": 100_000})
    test["name"] = "bench-batched-512"
    test["client"] = RegisterClient()
    test["checker"] = Noop()
    test["generator"] = independent.concurrent_generator(
        16, list(range(K)),
        lambda k: limit(100, reserve(8, r, mix([w, cas]))))
    out = run_test(test)
    subs = {k: History(subhistory(out["history"], k)) for k in range(K)}
    note(f"512-key: generated {len(out['history'])} ops "
         f"in {time.time()-t0:.1f}s")
    packs = [wgl.pack_register_history(subs[k]) for k in range(K)]
    assert all(p.ok for p in packs), [p.reason for p in packs if not p.ok]
    wgl.check_packed_batch(packs)  # warmup compiles
    t1 = time.time()
    results = wgl.check_packed_batch(packs)
    kernel_s = time.time() - t1
    valid = sum(1 for res in results if res.get("valid?") is True)
    assert valid == K, f"only {valid}/{K} valid"
    # production path (size cutoff routes these to the native engine)
    from jepsen_etcd_tpu.checkers.tpu_linearizable import (
        TPULinearizableChecker)
    t1 = time.time()
    pres = TPULinearizableChecker().check_batch({}, subs)
    prod_s = time.time() - t1
    assert all(res["valid?"] is True for res in pres.values())
    note(f"512-key: kernel={kernel_s:.3f}s production={prod_s:.3f}s "
         f"({K/max(prod_s,1e-9):.0f} keys/s)")
    return {"value": round(prod_s, 4), "unit": "s", "keys": K,
            "kernel_s": round(kernel_s, 4),
            "production_s": round(prod_s, 4),
            "keys_per_s": round(K / max(prod_s, 1e-9), 1),
            "vs_baseline": round(BASELINE_SECONDS / max(prod_s, 1e-9), 1)}


def bench_faulted_register():
    """Register under kill+partition faults: histories carry :info
    (crashed) ops — the regime the info-op packing, symmetry classes,
    and version-ceiling prune exist for. Times the full independent-key
    checker pass and reports how many keys stayed on the TPU path."""
    from jepsen_etcd_tpu.workloads.register import workload as reg_wl
    test, out = run_workload("register", time_limit=40, rate=200,
                             nemesis=["kill", "partition"],
                             nemesis_interval=5.0)
    h = out["history"]
    infos = len([o for o in h.client_ops() if o.is_info])
    checker = reg_wl({"nodes": test["nodes"]})["checker"]
    checker.check(test, h)  # warmup compiles
    t0 = time.time()
    res = checker.check(test, h)
    dt = time.time() - t0
    keys = res.get("results", {})
    engines = {}
    for r in keys.values():
        for sub in r.values() if isinstance(r, dict) else []:
            if isinstance(sub, dict) and "checker" in sub:
                engines[sub["checker"]] = engines.get(
                    sub["checker"], 0) + 1
    note(f"faulted register: valid?={res['valid?']} infos={infos} "
         f"engines={engines} in {dt:.3f}s")
    assert res["valid?"] is True, res
    return {"value": round(dt, 4), "unit": "s", "history_ops": len(h),
            "info_ops": infos, "engines": engines,
            "vs_baseline": round(BASELINE_SECONDS / max(dt, 1e-9), 1)}


def bench_set():
    """Config #3: set workload — CAS-retry adds + set-full analysis."""
    from jepsen_etcd_tpu.checkers.set_full import SetFull
    test, out = run_workload("set", time_limit=60, rate=200)
    h = out["history"]
    t0 = time.time()
    res = SetFull(linearizable=True).check(test, h)
    dt = time.time() - t0
    note(f"set-full: valid?={res['valid?']} over {len(h)} ops in {dt:.3f}s")
    assert res["valid?"] is True, res
    return {"value": round(dt, 4), "unit": "s", "history_ops": len(h),
            "vs_baseline": round(BASELINE_SECONDS / max(dt, 1e-9), 1)}


def bench_elle_append():
    """Config #4: Elle list-append dep-graph + closure at device scale
    (>=256 committed txns forces the device closure path)."""
    from jepsen_etcd_tpu.workloads.append import workload as append_wl
    test, out = run_workload("append", time_limit=25, rate=200)
    h = out["history"].client_ops()
    committed = len([o for o in h if o.is_ok])
    checker = append_wl({"nodes": test["nodes"]})["checker"]
    checker.use_tpu = True  # force the device closure regardless of N
    checker.check(test, h)  # warmup: closure compile
    t0 = time.time()
    res = checker.check(test, h)
    dt = time.time() - t0
    note(f"elle append: valid?={res['valid?']} txns={committed} "
         f"in {dt:.3f}s (device closure forced)")
    assert res["valid?"] is True, res
    return {"value": round(dt, 4), "unit": "s", "committed_txns": committed,
            "device_closure": True,
            "vs_baseline": round(BASELINE_SECONDS / max(dt, 1e-9), 1)}


def bench_watch():
    """Config #5: watch per-thread log order vs canonical (TPU
    edit-distance)."""
    from jepsen_etcd_tpu.checkers.watch import WatchChecker
    test, out = run_workload("watch", time_limit=60, rate=200)
    h = out["history"]
    checker = WatchChecker(use_tpu=True)
    checker.check(test, h)  # warmup: wavefront-DP compile
    t0 = time.time()
    res = checker.check(test, h)
    dt = time.time() - t0
    note(f"watch: valid?={res['valid?']} in {dt:.3f}s")
    assert res["valid?"] in (True, "unknown"), res
    return {"value": round(dt, 4), "unit": "s", "history_ops": len(h),
            "vs_baseline": round(BASELINE_SECONDS / max(dt, 1e-9), 1)}


def main() -> int:
    from jepsen_etcd_tpu.ops.common import enable_compile_cache
    enable_compile_cache()
    matrix = {}
    for name, fn in [("register_100", bench_register_100),
                     ("deep_wgl_4n_2000", bench_deep_wgl),
                     ("faulted_register", bench_faulted_register),
                     ("batched_64_keys", bench_batched_keys),
                     ("register_50k", bench_register_50k),
                     ("batched_512_keys", bench_batched_512_keys),
                     ("set_full", bench_set),
                     ("elle_append_device", bench_elle_append),
                     ("watch_edit_distance", bench_watch)]:
        try:
            matrix[name] = fn()
        except Exception as e:  # record, don't abort the headline bench
            note(f"{name} FAILED: {e!r}")
            matrix[name] = {"error": repr(e)}

    check_s, out, p = bench_register_10k()
    print(json.dumps({
        "metric": "register_linearizability_10k_ops_check_wallclock",
        "value": round(check_s, 4),
        "unit": "s",
        "vs_baseline": round(BASELINE_SECONDS / max(check_s, 1e-9), 1),
        "matrix": matrix,
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
