#!/usr/bin/env python
"""The benchmark suite (BASELINE.md configs 1-5).

Headline (north star): a 10k-op single-key register history verified
linearizable on TPU; the reference's CPU Knossos cannot verify it within
60 s (BASELINE.md "North star"), so vs_baseline = 60s / wall-clock.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline",
"matrix": {...}}. Every cell that simulates a history records its
generation time separately (``gen_s``) — generation is checker-input
prep, not the thing benchmarked. Device cells carry a cost split
(``host_prep_ms`` / ``device_ms`` / end-to-end) because on this
environment (v5e through the axon tunnel) a synchronized device call
pays ~100 ms round-trip latency regardless of work — see PERF.md.
The engine-crossover cell measures the native DFS against the MXU wave
kernel head-to-head on shared histories; the routing constants in
checkers/tpu_linearizable.py cite its numbers.
"""

import json
import sys
import time

sys.path.insert(0, ".")

N_OPS = 13_500  # ~10k :ok ops after failed-CAS exclusion
CONCURRENCY = 8
BASELINE_SECONDS = 60.0  # CPU Knossos budget it cannot meet


def _sim_keys(keys, ops_per_key, concurrency, seed, name, nodes=None,
              extra=None):
    """Simulated register histories for a key list (virtual time).
    Returns ({key: History}, gen_s, total_ops) — the ONE scaffolding
    both the single-key and batched cells build on."""
    from jepsen_etcd_tpu.compose import etcd_test
    from jepsen_etcd_tpu.runner.test_runner import run_test
    from jepsen_etcd_tpu.generators import limit, mix, reserve, independent
    from jepsen_etcd_tpu.generators.independent import subhistory
    from jepsen_etcd_tpu.core.history import History
    from jepsen_etcd_tpu.workloads.register import (RegisterClient, r, w,
                                                    cas)
    from jepsen_etcd_tpu.checkers.core import Noop

    test = etcd_test({
        "workload": "none",
        "time_limit": 36_000, "rate": 0, "seed": seed,
        "concurrency": concurrency, "store_base": "store",
        **({"nodes": nodes} if nodes else {}),
        # generation is checker-input prep, not the thing benchmarked:
        # frequent snapshots make the sim O(ops * store-size) (every
        # count applies re-encodes the whole store and triggers
        # follower installs)
        "snapshot_count": 100_000,
        **(extra or {}),
    })
    test["name"] = name
    test["client"] = RegisterClient()
    test["checker"] = Noop()
    test["generator"] = independent.concurrent_generator(
        concurrency, list(keys),
        lambda k: limit(ops_per_key, reserve(concurrency // 2, r,
                                             mix([w, cas]))))
    t0 = time.time()
    out = run_test(test)
    gen_s = time.time() - t0
    subs = {k: History(subhistory(out["history"], k)) for k in keys}
    return subs, gen_s, len(out["history"])


def sim_register_history(n_ops, concurrency, seed=2026, name="bench",
                         nodes=None, extra=None):
    """n_ops on ONE key via the simulated cluster (fast: virtual time)."""
    subs, _, _ = _sim_keys([0], n_ops, concurrency, seed, name, nodes,
                           extra)
    return subs[0]


def run_workload(workload, seed=7, time_limit=40, rate=200, **opts):
    from jepsen_etcd_tpu.compose import etcd_test
    from jepsen_etcd_tpu.runner.test_runner import run_test
    o = {"workload": workload, "time_limit": time_limit, "rate": rate,
         "seed": seed, "store_base": "store"}
    o.update(opts)
    test = etcd_test(o)
    t0 = time.time()
    out = run_test(test)
    return test, out, time.time() - t0


def note(msg):
    print(f"# {msg}", file=sys.stderr)


def gen_batched_keys(K, concurrency, per_key, seed):
    return _sim_keys(range(K), per_key, concurrency, seed,
                     f"bench-batched-{K}", nodes=["n1", "n2", "n3"])


def bench_register_10k():
    """North star: 10k-op single-key check with the full device cost
    split (host table prep / one-dispatch end-to-end / device-resident
    re-run)."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    from jepsen_etcd_tpu.ops import wgl, wgl_mxu
    t0 = time.time()
    h = sim_register_history(N_OPS, CONCURRENCY, name="bench-register-10k")
    gen_s = time.time() - t0
    note(f"10k: generated {len(h)} ops in {gen_s:.1f}s")
    t1 = time.time()
    p = wgl.pack_register_history(h)
    pack_s = time.time() - t1
    assert p.ok, p.reason
    wgl.check_packed(p)  # warmup: compile + first search
    # best of 3: a synchronized tunnel round trip carries tens of ms
    # of jitter (PERF.md), which is material at this cell's scale
    dt = 1e9
    for _ in range(3):
        t1 = time.time()
        out = wgl.check_packed(p)
        dt = min(dt, time.time() - t1)
    # cost split: host per-op packing; device-resident exec (tables
    # already shipped) isolates tunnel transfer+latency from compute
    r_pad = max(wgl.bucket(p.R), wgl_mxu.TSUB)
    t1 = time.time()
    i32, u16 = wgl_mxu.pack_perop(p, r_pad)
    prep_ms = (time.time() - t1) * 1e3
    dev = [jax.device_put(jnp.asarray(x)) for x in (i32, u16)]
    jax.block_until_ready(dev)
    call = wgl_mxu._call_single(r_pad, p.w,
                                jax.default_backend() != "tpu")
    np.asarray(call(*dev))
    best = 1e9
    for _ in range(3):
        t1 = time.time()
        np.asarray(call(*dev))
        best = min(best, time.time() - t1)
    note(f"10k: verdict={out['valid?']} waves={out.get('waves')} "
         f"engine={out.get('engine')} peak={out.get('peak-frontier')} "
         f"w={p.w} in {dt:.3f}s (prep {prep_ms:.0f}ms, device-resident "
         f"{best*1e3:.0f}ms)")
    assert out["valid?"] is True, out
    return dt, out, p, gen_s, prep_ms, best * 1e3, pack_s


def bench_register_100():
    """Config #1: 1 key, ops-per-key 100 — the regime the reference's
    CPU Knossos competes in; report CPU oracle vs TPU kernel."""
    from jepsen_etcd_tpu.ops import wgl
    from jepsen_etcd_tpu.checkers.linearizable import check_history
    from jepsen_etcd_tpu.models import VersionedRegister
    t0 = time.time()
    h = sim_register_history(135, CONCURRENCY, seed=11,
                             name="bench-register-100")
    gen_s = time.time() - t0
    p = wgl.pack_register_history(h)
    assert p.ok, p.reason
    t0 = time.time()
    cpu = check_history(VersionedRegister(), h, use_native=False)
    cpu_s = time.time() - t0
    from jepsen_etcd_tpu.native import get_lib
    get_lib()  # warmup: one-time g++ build must not land in the timer
    t0 = time.time()
    nat = check_history(VersionedRegister(), h)
    native_s = time.time() - t0
    wgl.check_packed(p)
    t1 = time.time()
    tpu = wgl.check_packed(p)
    tpu_s = time.time() - t1
    # the production checker routes this size to the native DFS via the
    # size cutoff (checkers/tpu_linearizable.py CPU_CUTOFF)
    from jepsen_etcd_tpu.checkers.tpu_linearizable import (
        TPULinearizableChecker)
    prod = TPULinearizableChecker()
    t1 = time.time()
    pres = prod.check({}, h)
    prod_s = time.time() - t1
    assert tpu["valid?"] is True and cpu["valid?"] is True
    assert nat["valid?"] is True and pres["valid?"] is True
    note(f"100-op: cpu={cpu_s:.4f}s native={native_s:.4f}s "
         f"tpu={tpu_s:.4f}s production={prod_s:.4f}s "
         f"({pres['checker']})")
    return {"value": round(prod_s, 4), "unit": "s", "gen_s": round(gen_s, 2),
            "cpu_oracle_s": round(cpu_s, 4),
            "native_oracle_s": round(native_s, 4),
            "tpu_kernel_s": round(tpu_s, 4),
            "production_engine": pres["checker"],
            "ops": p.R, "vs_baseline": round(BASELINE_SECONDS / max(
                prod_s, 1e-9), 1)}


def bench_engine_crossover():
    """VERDICT r3 #3: the DFS<->kernel crossover MEASURED, not modeled.
    One 50k generation; prefixes at completion boundaries give the
    sweep sizes. The adversarial row injects a violation mid-history,
    where a backtracking DFS must linearize half the history before
    discovering it. DFS_FIRST_MAX in checkers/tpu_linearizable.py is
    calibrated from this table."""
    from jepsen_etcd_tpu.core.op import Op
    from jepsen_etcd_tpu.core.history import History
    from jepsen_etcd_tpu.ops import wgl, wgl_mxu
    from jepsen_etcd_tpu.checkers.linearizable import check_history
    from jepsen_etcd_tpu.models import VersionedRegister
    from jepsen_etcd_tpu.native import get_lib
    get_lib()
    gen_s = 0.0
    rows = []
    h = None
    for n_req in (3_375, 13_500, 33_750):
        t0 = time.time()
        hh = sim_register_history(n_req, CONCURRENCY, seed=17,
                                  name=f"bench-crossover-{n_req}",
                                  nodes=["n1", "n2", "n3"])
        gen_s += time.time() - t0
        h = hh  # largest kept for the adversarial row
        p = wgl.pack_register_history(hh)
        if not (p.ok and wgl_mxu.supported(p)):
            continue
        t1 = time.time()
        nat = check_history(VersionedRegister(), hh)
        nat_s = time.time() - t1
        wgl_mxu.check_packed_mxu(p)  # warmup this bucket
        t1 = time.time()
        mxu = wgl_mxu.check_packed_mxu(p)
        mxu_s = time.time() - t1
        rows.append({"entries": len(hh), "R": p.R,
                     "native_s": round(nat_s, 4),
                     "mxu_s": round(mxu_s, 4),
                     "native_valid": nat["valid?"],
                     "mxu_valid": mxu["valid?"]})
        note(f"crossover entries={len(hh)}: native={nat_s:.3f}s "
             f"mxu={mxu_s:.3f}s")
    # adversarial: violation at the midpoint of the largest history
    ops = list(h)
    mid = len(ops) // 2
    adv = [Op(dict(o)) for o in ops]
    injected = False
    for i in range(mid, len(adv)):
        o = adv[i]
        if o.get("type") == "ok" and o.get("f") == "read" \
                and o.get("value") and o["value"][1] is not None:
            v = list(o["value"])
            v[1] = 424242
            adv[i]["value"] = v
            injected = True
            break
    ha = History(adv)
    pa = wgl.pack_register_history(ha)
    if injected and pa.ok and wgl_mxu.supported(pa):
        t1 = time.time()
        nat = check_history(VersionedRegister(), ha,
                            max_configs=5_000_000)
        nat_s = time.time() - t1
        t1 = time.time()
        mxu = wgl_mxu.check_packed_mxu(pa)
        mxu_s = time.time() - t1
        note(f"crossover adversarial: native={nat_s:.3f}s "
             f"({nat['valid?']}) mxu={mxu_s:.3f}s ({mxu['valid?']})")
        adv_row = {"entries": len(ha), "native_s": round(nat_s, 4),
                   "mxu_s": round(mxu_s, 4), "both_false":
                   nat["valid?"] is False and mxu["valid?"] is False}
    else:
        adv_row = {"skipped": ("no injectable read" if not injected
                               else "pack unsupported")}
    # value = the largest measured speedup row (kernel vs native)
    if rows:
        full = max(rows, key=lambda r: r["entries"])
        val = round(full["native_s"] / max(full["mxu_s"], 1e-9), 1)
        unit = f"x_native_at_{full['entries']}_entries"
    else:
        val, unit = 0.0, "no_supported_rows"
    return {"value": val, "unit": unit,
            "gen_s": round(gen_s, 2), "table": rows,
            "adversarial": adv_row,
            "vs_baseline": val}


def bench_deep_wgl():
    """Config #2: concurrency 4n (=20), ops-per-key 2000 — deep
    permutation search (BFS peak frontier ~252). r5 routes this cell
    through PRODUCTION and reports every engine head-to-head: the
    native DFS walks a near-linear witness (~3k configs) where the
    pinned r4 ladder paid 1.2 s of per-wave dispatch, so the router's
    size-cutoff (entries 5.2k < DFS_FIRST_MAX) is the measured winner.
    An exhaustion adversarial (read asserting an unreachable version
    appended at the end) checks the invalid polarity stays routed."""
    from jepsen_etcd_tpu.core.op import Op
    from jepsen_etcd_tpu.core.history import History
    from jepsen_etcd_tpu.ops import wgl
    from jepsen_etcd_tpu.checkers.linearizable import check_history
    from jepsen_etcd_tpu.checkers.tpu_linearizable import (
        TPULinearizableChecker)
    from jepsen_etcd_tpu.models import VersionedRegister
    t0 = time.time()
    h = sim_register_history(2600, 20, seed=5, name="bench-register-deep")
    gen_s = time.time() - t0
    t0 = time.time()
    p = wgl.pack_register_history(h)
    pack_s = time.time() - t0
    assert p.ok, p.reason

    t0 = time.time()
    nat = check_history(VersionedRegister(), h)
    native_s = time.time() - t0
    assert nat["valid?"] is True, nat
    # the ladder needs the 256 rung (peak 252); warm the compile
    wgl.check_packed(p, f_max=256)
    t0 = time.time()
    lad = wgl.check_packed(p, f_max=256)
    ladder_s = time.time() - t0
    assert lad["valid?"] is True, lad
    prod = TPULinearizableChecker()
    prod.check({}, h)
    t0 = time.time()
    out = prod.check({}, h)
    prod_s = time.time() - t0
    assert out["valid?"] is True, out

    # adversarial: an end-appended read asserting an unreachable
    # version — every engine must answer False, routed production too
    ops = list(h)
    vmax = max((o["value"][0] or 0) for o in ops
               if o.get("type") == "ok"
               and isinstance(o.get("value"), (list, tuple))
               and o["value"] and isinstance(o["value"][0], int))
    ops.append(Op(type="invoke", process=19, f="read",
                  value=[None, None], index=len(ops), time=10 ** 15))
    ops.append(Op(type="ok", process=19, f="read",
                  value=[vmax + 7, None], index=len(ops),
                  time=10 ** 15 + 1))
    hb = History(ops)
    t0 = time.time()
    adv = prod.check({}, hb)
    adv_s = time.time() - t0
    assert adv["valid?"] is False, adv

    note(f"deep 4n/2000: native={native_s:.3f}s ladder={ladder_s:.3f}s "
         f"production={prod_s:.3f}s ({out.get('checker')}) "
         f"adversarial={adv_s:.3f}s peak={lad.get('peak-frontier')}")
    return {"value": round(prod_s, 4), "unit": "s",
            "gen_s": round(gen_s, 2),
            "ops": p.R, "w": p.w,
            "pack_s": round(pack_s, 4),
            "native_s": round(native_s, 4),
            "ladder_s": round(ladder_s, 4),
            "production_s": round(prod_s, 4),
            "production_engine": out.get("checker"),
            "adversarial_s": round(adv_s, 4),
            "peak_frontier": lad.get("peak-frontier"),
            "vs_baseline": round(BASELINE_SECONDS / max(prod_s, 1e-9), 1)}


def bench_batched_keys():
    """The key-DP axis (SURVEY §2.3) at 64 keys. kernel_s is the MXU
    batch — ONE pallas dispatch for the whole batch; production_s is
    the checker's routed path. The router keeps the native sweep in
    production here BY MEASUREMENT: the tunnel's ~0.1 s round trip
    alone exceeds the native sweep for keys this small (PERF.md)."""
    from jepsen_etcd_tpu.ops import wgl, wgl_mxu
    from jepsen_etcd_tpu.checkers.tpu_linearizable import (
        TPULinearizableChecker)
    K = 64
    subs, gen_s, total_ops = gen_batched_keys(K, 8, 200, seed=3)
    note(f"batched {K}: generated {total_ops} ops in {gen_s:.1f}s")
    t0 = time.time()
    packs_by_key = wgl.pack_register_histories_batched(subs)
    pack_s = time.time() - t0
    packs = [packs_by_key[k] for k in range(K)]
    wgl_mxu.check_packed_batch_mxu(packs)  # warmup compiles
    t0 = time.time()
    results = wgl_mxu.check_packed_batch_mxu(packs)
    kernel_s = time.time() - t0
    valid = sum(1 for res in results
                if res is not None and res.get("valid?") is True)
    note(f"batched {K} (mxu one-dispatch): {valid} valid in "
         f"{kernel_s:.3f}s ({K/max(kernel_s,1e-9):.0f} keys/s)")
    assert valid == K, results
    prod = TPULinearizableChecker()
    t0 = time.time()
    pres = prod.check_batch({}, subs)
    prod_s = time.time() - t0
    engines = {}
    for r in pres.values():
        engines[r.get("checker")] = engines.get(r.get("checker"), 0) + 1
    assert all(r["valid?"] is True for r in pres.values())
    note(f"batched {K} (production): engines={engines} in {prod_s:.3f}s")
    return {"value": round(prod_s, 4), "unit": "s",
            "gen_s": round(gen_s, 2), "keys": K,
            "pack_s": round(pack_s, 4),
            "pack_ms_per_key": round(1e3 * pack_s / K, 3),
            "kernel_s": round(kernel_s, 4),
            "production_s": round(prod_s, 4), "engines": engines,
            "keys_per_s": round(K / max(prod_s, 1e-9), 1),
            "vs_baseline": round(BASELINE_SECONDS / max(prod_s, 1e-9), 1)}


def bench_register_50k():
    """Scale cell: >=50k-op single-key history — 5x the north star."""
    from jepsen_etcd_tpu.ops import wgl
    t0 = time.time()
    h = sim_register_history(67_500, CONCURRENCY, seed=17,
                             name="bench-register-50k",
                             nodes=["n1", "n2", "n3"])
    gen_s = time.time() - t0
    note(f"50k: generated {len(h)} ops in {gen_s:.1f}s")
    t0 = time.time()
    p = wgl.pack_register_history(h)
    pack_s = time.time() - t0
    assert p.ok, p.reason
    wgl.check_packed(p)  # warmup: compile + first search
    t1 = time.time()
    out = wgl.check_packed(p)
    dt = time.time() - t1
    note(f"50k: verdict={out['valid?']} waves={out.get('waves')} "
         f"engine={out.get('engine')} peak={out.get('peak-frontier')} "
         f"w={p.w} in {dt:.3f}s")
    assert out["valid?"] is True, out
    return {"value": round(dt, 4), "unit": "s", "gen_s": round(gen_s, 2),
            "pack_s": round(pack_s, 4),
            "ops": p.R, "w": p.w, "waves": out.get("waves"),
            "engine": out.get("engine"),
            "peak_frontier": out.get("peak-frontier"),
            "vs_baseline": round(BASELINE_SECONDS / max(dt, 1e-9), 1)}


def bench_batched_512_keys():
    """Scale cell: 512 independent keys (concurrency 16 -> w=64
    windows for most keys, exercising the two-word kernel). kernel_s =
    one MXU dispatch per (bucket, width) group — r5 cut it ~4x (one-hot
    matmul table gather, matmul wave reductions, 8 KB readback), under
    the 0.45 s r4-production bar. pack_s is the batched SoA packer
    (ops/wgl.py pack_register_histories_batched): ONE numpy pass over
    all K subhistories instead of a per-key Python loop — the r5
    per-key packing floor it replaced was large enough to decide
    routing by itself (the deleted BATCH_DFS_MAX); routing now keys on
    the measured engine times with packing reported separately."""
    from jepsen_etcd_tpu.ops import wgl, wgl_mxu
    from jepsen_etcd_tpu.checkers.tpu_linearizable import (
        TPULinearizableChecker)
    K = 512
    subs, gen_s, total_ops = gen_batched_keys(K, 16, 100, seed=29)
    note(f"512-key: generated {total_ops} ops in {gen_s:.1f}s")
    t1 = time.time()
    packs_by_key = wgl.pack_register_histories_batched(subs)
    pack_s = time.time() - t1
    packs = [packs_by_key[k] for k in range(K)]
    note(f"512-key: packed in {pack_s:.3f}s "
         f"({1e3 * pack_s / K:.2f} ms/key)")
    widths = {}
    for p in packs:
        widths[p.w] = widths.get(p.w, 0) + 1
    wgl_mxu.check_packed_batch_mxu(packs)  # warmup compiles
    t1 = time.time()
    results = wgl_mxu.check_packed_batch_mxu(packs)
    kernel_s = time.time() - t1
    valid = sum(1 for res in results
                if res is not None and res.get("valid?") is True)
    assert valid == K, f"only {valid}/{K} valid"
    t1 = time.time()
    pres = TPULinearizableChecker().check_batch({}, subs)
    prod_s = time.time() - t1
    assert all(res["valid?"] is True for res in pres.values())
    note(f"512-key: kernel={kernel_s:.3f}s production={prod_s:.3f}s "
         f"widths={widths} ({K/max(prod_s,1e-9):.0f} keys/s)")
    return {"value": round(prod_s, 4), "unit": "s",
            "gen_s": round(gen_s, 2), "keys": K, "widths": widths,
            "pack_s": round(pack_s, 4),
            "pack_ms_per_key": round(1e3 * pack_s / K, 3),
            "kernel_s": round(kernel_s, 4),
            "production_s": round(prod_s, 4),
            "keys_per_s": round(K / max(prod_s, 1e-9), 1),
            "vs_baseline": round(BASELINE_SECONDS / max(prod_s, 1e-9), 1)}


def bench_w128_deep():
    """Four-word windows (w=128): concurrency 40 pushes the undecided
    window past 64, the regime lock-style long-blocked ops create
    (VERDICT r4 #6). Above the DFS crossover, so the production router
    sends it to the fused kernel; the jnp ladder cannot answer this
    shape at all (peak frontier ~3.4k blows through every rung) and is
    reported as such."""
    from jepsen_etcd_tpu.ops import wgl, wgl_mxu
    from jepsen_etcd_tpu.checkers.linearizable import check_history
    from jepsen_etcd_tpu.checkers.tpu_linearizable import (
        TPULinearizableChecker)
    from jepsen_etcd_tpu.models import VersionedRegister
    t0 = time.time()
    h = sim_register_history(13000, 40, seed=13, name="bench-w128-deep")
    gen_s = time.time() - t0
    t0 = time.time()
    p = wgl.pack_register_history(h)
    pack_s = time.time() - t0
    assert p.ok and p.w == 128, (p.reason, p.w)
    wgl_mxu.check_packed_mxu(p)  # warmup compile
    t0 = time.time()
    out = wgl_mxu.check_packed_mxu(p)
    mxu_s = time.time() - t0
    assert out["valid?"] is True, out
    t0 = time.time()
    nat = check_history(VersionedRegister(), h)
    native_s = time.time() - t0
    assert nat["valid?"] is True, nat
    prod = TPULinearizableChecker()
    prod.check({}, h)
    t0 = time.time()
    pr = prod.check({}, h)
    prod_s = time.time() - t0
    assert pr["valid?"] is True, pr
    note(f"w128 deep: mxu={mxu_s:.3f}s native={native_s:.3f}s "
         f"production={prod_s:.3f}s engine={pr.get('engine')} "
         f"entries={len(h)} R={p.R}")
    return {"value": round(prod_s, 4), "unit": "s",
            "gen_s": round(gen_s, 2), "ops": p.R, "w": p.w,
            "pack_s": round(pack_s, 4),
            "mxu_s": round(mxu_s, 4), "native_s": round(native_s, 4),
            "production_s": round(prod_s, 4),
            "production_engine": pr.get("engine"),
            "ladder": "unknown (peak ~3.4k exceeds every rung)",
            "vs_baseline": round(BASELINE_SECONDS / max(prod_s, 1e-9), 1)}


def bench_faulted_register():
    """Register under kill+partition faults: histories carry :info
    (crashed) ops — the regime the info-op packing, symmetry classes,
    and version-ceiling prune exist for."""
    from jepsen_etcd_tpu.workloads.register import workload as reg_wl
    test, out, gen_s = run_workload("register", time_limit=40, rate=200,
                                    nemesis=["kill", "partition"],
                                    nemesis_interval=5.0)
    h = out["history"]
    infos = len([o for o in h.client_ops() if o.is_info])
    checker = reg_wl({"nodes": test["nodes"]})["checker"]
    checker.check(test, h)  # warmup compiles
    t0 = time.time()
    res = checker.check(test, h)
    dt = time.time() - t0
    keys = res.get("results", {})
    engines = {}
    for r in keys.values():
        for sub in r.values() if isinstance(r, dict) else []:
            if isinstance(sub, dict) and "checker" in sub:
                engines[sub["checker"]] = engines.get(
                    sub["checker"], 0) + 1
    note(f"faulted register: valid?={res['valid?']} infos={infos} "
         f"engines={engines} in {dt:.3f}s")
    assert res["valid?"] is True, res
    return {"value": round(dt, 4), "unit": "s", "gen_s": round(gen_s, 2),
            "history_ops": len(h), "info_ops": infos, "engines": engines,
            "vs_baseline": round(BASELINE_SECONDS / max(dt, 1e-9), 1)}


def bench_set():
    """Config #3: set workload — CAS-retry adds + set-full analysis."""
    from jepsen_etcd_tpu.checkers.set_full import SetFull
    test, out, gen_s = run_workload("set", time_limit=60, rate=200)
    h = out["history"]
    t0 = time.time()
    res = SetFull(linearizable=True).check(test, h)
    dt = time.time() - t0
    note(f"set-full: valid?={res['valid?']} over {len(h)} ops in {dt:.3f}s")
    assert res["valid?"] is True, res
    return {"value": round(dt, 4), "unit": "s", "gen_s": round(gen_s, 2),
            "history_ops": len(h),
            "vs_baseline": round(BASELINE_SECONDS / max(dt, 1e-9), 1)}


def bench_elle_append():
    """Config #4: Elle list-append dep-graph + closure, HOST vs DEVICE
    closure timed head-to-head at the workload's real txn count (the
    ops/closure.py CPU_CUTOFF=768 crossover cites these numbers)."""
    from jepsen_etcd_tpu.workloads.append import workload as append_wl
    test, out, gen_s = run_workload("append", time_limit=25, rate=200)
    h = out["history"].client_ops()
    committed = len([o for o in h if o.is_ok])
    checker = append_wl({"nodes": test["nodes"]})["checker"]
    # device path (the size-router picks it anyway at this txn count —
    # ops/closure.py CPU_CUTOFF=768)
    checker.use_tpu = True
    checker.check(test, h)  # warmup: closure compile
    t0 = time.time()
    res = checker.check(test, h)
    dev_s = time.time() - t0
    # host leg only at sizes where numpy finishes in bench time; the
    # closure_scale_2048 cell carries the head-to-head at scale
    host_s = None
    if committed <= 2048:
        checker.use_tpu = False
        t0 = time.time()
        res_h = checker.check(test, h)
        host_s = time.time() - t0
        assert res_h["valid?"] is True
    note(f"elle append: valid?={res['valid?']} txns={committed} "
         f"device={dev_s:.3f}s host={host_s}")
    assert res["valid?"] is True
    return {"value": round(dev_s, 4), "unit": "s", "gen_s": round(gen_s, 2),
            "committed_txns": committed,
            "device_closure_s": round(dev_s, 4),
            **({"host_closure_s": round(host_s, 4)}
               if host_s is not None else
               {"host_closure": "skipped (txns > 2048; see "
                                "closure_scale_2048)"}),
            "vs_baseline": round(BASELINE_SECONDS / max(dev_s, 1e-9), 1)}


def bench_closure_scale():
    """VERDICT r3 #5 / ROADMAP #5: a closure size where the MXU path
    decisively beats numpy. Six 2048-node subgraphs (the append
    checker's shape at ~30 min of workload), measured host vs device —
    the device leg DECOMPOSED into {transfer_s, compute_s}: the old
    single number folded an O(B*N^2)-byte host->device copy plus the
    O(B*N^2) reach readback into "kernel time". TFLOPS is computed from
    the squarings the fixpoint early-exit actually executes (the
    batched while_loop in ops/closure.py runs until NO plane grows,
    i.e. max over planes of the per-plane fixpoint count), not the
    worst-case ceil(log2 N) bound."""
    import numpy as np
    import jax
    from jepsen_etcd_tpu.ops import closure
    rng = np.random.RandomState(0)
    B, N = 6, 2048
    a = rng.rand(B, N, N) < (2.0 / N)
    iters = int(np.ceil(np.log2(N))) + 1
    plane_sq = []   # per-plane squarings to fixpoint
    t0 = time.time()
    for b in range(B):
        r = a[b] | np.eye(N, dtype=bool)
        prev, sq = int(r.sum()), 0
        for _ in range(iters):
            r = (r.astype(np.float32) @ r.astype(np.float32)) > 0
            sq += 1
            cur = int(r.sum())
            if cur == prev:
                break
            prev = cur
        plane_sq.append(sq)
    host_s = time.time() - t0
    f = closure._closure_device
    # transfer leg: the [B, N, N] bool stack over the host->device link
    t0 = time.time()
    a_dev = jax.block_until_ready(jax.device_put(a))
    transfer_s = time.time() - t0
    jax.block_until_ready(f(a_dev, iters))  # warmup: compile
    compute_s = 1e9
    for _ in range(2):
        t0 = time.time()
        jax.block_until_ready(f(a_dev, iters))
        compute_s = min(compute_s, time.time() - t0)
    dev_s = transfer_s + compute_s
    # device executes max(plane_sq) squarings for ALL B planes (one
    # batched while_loop); 2*N^3 flops per N x N squaring
    sq_dev = max(plane_sq)
    tflops = (B * sq_dev * 2 * N ** 3) / max(compute_s, 1e-9) / 1e12
    extra = {}
    if jax.default_backend() == "tpu":
        # v5e peak: 197 bf16 TFLOPS/chip
        peak = 197.0 * len(jax.devices())
        extra["mfu_pct"] = round(100 * tflops / peak, 1)
    note(f"closure scale N={N}: host={host_s:.2f}s "
         f"device={compute_s:.2f}s compute + {transfer_s:.2f}s transfer "
         f"({host_s/max(dev_s,1e-9):.1f}x, {tflops:.1f} TFLOPS, "
         f"{sq_dev}/{iters} squarings)")
    return {"value": round(dev_s, 4), "unit": "s", "nodes": N,
            "subgraphs": B, "host_s": round(host_s, 4),
            "transfer_s": round(transfer_s, 4),
            "compute_s": round(compute_s, 4),
            "squarings_run": sq_dev, "squarings_bound": iters,
            "tflops": round(tflops, 2), **extra,
            "speedup_x": round(host_s / max(dev_s, 1e-9), 1),
            "vs_baseline": round(host_s / max(dev_s, 1e-9), 1)}


def bench_watch():
    """Config #5: watch per-thread log order vs canonical (TPU
    edit-distance)."""
    from jepsen_etcd_tpu.checkers.watch import WatchChecker
    test, out, gen_s = run_workload("watch", time_limit=60, rate=200)
    h = out["history"]
    checker = WatchChecker(use_tpu=True)
    checker.check(test, h)  # warmup: wavefront-DP compile
    t0 = time.time()
    res = checker.check(test, h)
    dt = time.time() - t0
    note(f"watch: valid?={res['valid?']} in {dt:.3f}s")
    assert res["valid?"] in (True, "unknown"), res
    return {"value": round(dt, 4), "unit": "s", "gen_s": round(gen_s, 2),
            "history_ops": len(h),
            "vs_baseline": round(BASELINE_SECONDS / max(dt, 1e-9), 1)}


def _bucket_gen_profile(prof, n_events):
    """Bucket a cProfile of a generation run into the hot-loop cost
    centers the r6 overhaul targets: timer churn (SimLoop heap ops),
    queue hops (Queue/Future/Task scheduling + interpreter dispatch),
    generator poll, record (history append + SoA column emission), sut
    (raft/client work — off-limits to optimisation, it defines history
    timing), other. Returns {bucket: {s, us_per_op}}."""
    import pstats
    TIMER_FNS = {"call_later", "call_at", "cancel", "_compact",
                 "sleep", "run", "time"}
    buckets = dict.fromkeys(
        ("timer_churn", "queue_hops", "generator_poll", "record",
         "sut", "other"), 0.0)
    for (fname, _ln, func), (_cc, _nc, tt, _ct, _callers) in \
            pstats.Stats(prof).stats.items():
        f = fname.replace("\\", "/")
        if "/generators/" in f:
            b = "generator_poll"
        elif f.endswith("core/history.py") or (
                f.endswith("runner/interpreter.py")
                and func in ("record", "ctx")):
            b = "record"
        elif f.endswith(("runner/sim.py", "runner/wall.py")):
            b = "timer_churn" if func in TIMER_FNS else "queue_hops"
        elif f.endswith("runner/interpreter.py"):
            b = "queue_hops"
        elif "/sut/" in f or "/client/" in f or "/nemesis/" in f:
            b = "sut"
        else:
            b = "other"
        buckets[b] += tt
    return {k: {"s": round(v, 3),
                "us_per_op": round(1e6 * v / max(n_events, 1), 2)}
            for k, v in buckets.items()}


#: seed generation rate (events/s) this cell's vs_baseline divides by —
#: the pre-overhaul hot loop measured ~6.8k events/s on this host (the
#: register_50k cell generated ~135k events in 19.8 s; PERF.md §gen)
SEED_GEN_OPS_PER_S = 6_800.0


def bench_gen_throughput():
    """Generation-throughput cell (r6): raw simulated history
    production in events/s, plus a per-op µs cost breakdown from a
    second, smaller profiled run (cProfile inflates wall time ~2x, so
    the headline rate comes from the unprofiled leg)."""
    import cProfile
    _, gen_s, total = _sim_keys([0], 27_000, CONCURRENCY, 23,
                                "bench-gen-throughput",
                                nodes=["n1", "n2", "n3"])
    rate = total / max(gen_s, 1e-9)
    note(f"gen-throughput: {total} events in {gen_s:.2f}s "
         f"({rate:,.0f} events/s)")
    prof = cProfile.Profile()
    prof.enable()
    _, prof_s, prof_total = _sim_keys([0], 6_750, CONCURRENCY, 23,
                                      "bench-gen-prof",
                                      nodes=["n1", "n2", "n3"])
    prof.disable()
    breakdown = _bucket_gen_profile(prof, prof_total)
    top = sorted(breakdown.items(), key=lambda kv: -kv[1]["s"])[:3]
    note("gen-throughput profile: " + " ".join(
        f"{k}={v['us_per_op']}us/op" for k, v in top))
    # batched leg (ISSUE 13): the SAME register generation shape, but
    # 16 seeds in one lockstep columnar pass (simbatch/, epoch-v2).
    # Headline is AGGREGATE events/s across the batch — per-seed cost
    # amortizes over the seed axis, which is the escape from the
    # single-stream ~8-9k wall PR 6 hit (PERF.md §gen batched).
    from jepsen_etcd_tpu.simbatch import generate_for_opts
    bopts = {"workload": "register", "nodes": ["n1", "n2", "n3"],
             "concurrency": 16, "rate": 1000.0, "time_limit": 7.52}
    seeds = list(range(16))
    generate_for_opts(bopts, seeds)  # warm numpy/import costs
    bt0 = time.time()
    bgen = generate_for_opts(bopts, seeds)
    b_s = time.time() - bt0
    b_rate = bgen["events"] / max(b_s, 1e-9)
    note(f"gen-throughput batched: {bgen['events']} events across "
         f"{len(seeds)} seeds in {b_s:.2f}s ({b_rate:,.0f} aggregate "
         f"events/s, {bgen['epoch']})")
    # jitted leg (ISSUE 19): the SAME batch shape through the epoch-v3
    # device engine (simbatch/engine_jax.py). The warm-up MUST run at
    # the real (config, S): batch size and per-lane op count are shape
    # dims of both jits, so warming at a toy shape recompiles inside
    # the timed region and the leg reads ~0.5x instead of ~4x. Bar:
    # >= 2x the numpy-batched leg (PERF.md §gen-jitted).
    jopts = dict(bopts, gen_epoch="epoch-v3")
    generate_for_opts(jopts, seeds)  # warm: compile at the real shape
    jt0 = time.time()
    jgen = generate_for_opts(jopts, seeds)
    j_s = time.time() - jt0
    j_rate = jgen["events"] / max(j_s, 1e-9)
    note(f"gen-throughput jitted: {jgen['events']} events across "
         f"{len(seeds)} seeds in {j_s:.2f}s ({j_rate:,.0f} aggregate "
         f"events/s, {jgen['epoch']}, "
         f"{j_rate / max(b_rate, 1e-9):.1f}x batched)")
    # seed-axis scaling: the vmapped lanes amortize over S, so a short
    # config at S=256 must not cost more per seed than at S=16 (both
    # legs warmed at their own shape first — S is a shape dim).
    sopts = dict(jopts, time_limit=1.0)
    scaling = {}
    for n in (16, 256):
        ss = list(range(n))
        generate_for_opts(sopts, ss)
        st0 = time.time()
        sgen = generate_for_opts(sopts, ss)
        scaling[n] = {"wall_s": round(time.time() - st0, 3),
                      "events": sgen["events"],
                      "per_seed_ms": round(
                          1e3 * (time.time() - st0) / n, 2)}
    note(f"gen-throughput jitted scaling: per-seed "
         f"{scaling[16]['per_seed_ms']}ms at S=16 vs "
         f"{scaling[256]['per_seed_ms']}ms at S=256")
    return {"value": round(rate, 1), "unit": "events/s",
            "gen_s": round(gen_s, 2), "events": total,
            "per_op_us": round(1e6 * gen_s / max(total, 1), 2),
            "profiled": {"events": prof_total,
                         "wall_s": round(prof_s, 2),
                         "breakdown": breakdown},
            "batched": {"value": round(b_rate, 1),
                        "unit": "aggregate events/s",
                        "seeds": len(seeds), "events": bgen["events"],
                        "steps": bgen["steps"],
                        "gen_s": round(b_s, 3),
                        "epoch": bgen["epoch"],
                        "per_seed_ops_per_s": round(
                            b_rate / len(seeds), 1),
                        "vs_single_stream": round(
                            b_rate / max(rate, 1e-9), 2)},
            "jitted": {"value": round(j_rate, 1),
                       "unit": "aggregate events/s",
                       "seeds": len(seeds), "events": jgen["events"],
                       "gen_s": round(j_s, 3),
                       "epoch": jgen["epoch"],
                       "vs_batched": round(
                           j_rate / max(b_rate, 1e-9), 2),
                       "scaling": scaling},
            "vs_baseline": round(rate / SEED_GEN_OPS_PER_S, 2)}


def bench_streaming_overlap():
    """Streaming-overlap cell (ISSUE 8): a ~50k-op register run with
    online chunked checking (--stream) against the identical post-hoc
    run. Reports the end-to-end-over-generation ratio — how close
    verification came to free — from the run's own phase telemetry.
    Honesty (PERF.md §streaming): sim generation is CPU-bound Python,
    so under the GIL the streamed consumers mostly interleave rather
    than overlap; the ratio is REPORTED, never asserted. The durable
    wins are the artifacts being ready at generation end (check
    collapses to the vectorized finalize) and bounded-memory soak."""
    opts = dict(rate=0, ops_per_key=2000, seed=29, time_limit=20,
                snapshot_count=100_000, nodes=["n1", "n2", "n3"])
    t0 = time.time()
    _, s_out, _ = run_workload("register", stream=True, **opts)
    stream_e2e = time.time() - t0
    s_tel = s_out["results"].get("telemetry") or {}
    s_ph = s_tel.get("phases") or {}
    ctr = s_tel.get("counters") or {}
    gen_s = s_ph.get("generate") or 0.0
    overlap_s = (s_ph.get("stream-finalize") or 0.0) + \
        (s_ph.get("check") or 0.0)
    ratio = (gen_s + overlap_s) / max(gen_s, 1e-9)
    t0 = time.time()
    _, p_out, _ = run_workload("register", **opts)
    posthoc_e2e = time.time() - t0
    p_ph = (p_out["results"].get("telemetry") or {}).get("phases") or {}
    s_verdict = json.dumps(s_out["results"]["workload"], sort_keys=True,
                           default=repr)
    p_verdict = json.dumps(p_out["results"]["workload"], sort_keys=True,
                           default=repr)
    assert s_verdict == p_verdict, "streamed verdict diverged"
    note(f"streaming-overlap: {len(s_out['history'])} ops, "
         f"gen {gen_s:.2f}s + residual check {overlap_s:.2f}s "
         f"(e2e/gen {ratio:.2f}x) vs post-hoc check "
         f"{p_ph.get('check', 0):.2f}s; chunks="
         f"{ctr.get('stream.chunks')} "
         f"pack_reuse={ctr.get('stream.pack_reuse')}")
    return {"value": round(ratio, 3), "unit": "e2e/gen",
            "ops": len(s_out["history"]),
            "gen_s": round(gen_s, 2),
            "check_overlap_s": round(overlap_s, 3),
            "posthoc_check_s": round(p_ph.get("check") or 0.0, 3),
            "chunks": ctr.get("stream.chunks"),
            "pack_reuse": ctr.get("stream.pack_reuse"),
            "backlog_peak": ctr.get("stream.backlog_peak"),
            "stream_e2e_s": round(stream_e2e, 2),
            "posthoc_e2e_s": round(posthoc_e2e, 2),
            "verdicts_identical": True,
            "vs_baseline": round(posthoc_e2e / max(stream_e2e, 1e-9),
                                 2)}


def bench_fused_pipeline():
    """Fused gen->check cell (ISSUE 19): epoch-v3 jitted generation
    feeding ``check_prefix`` via PackStream chunk slices while later
    sub-batches are still generating, vs the SAME seeds run strictly
    sequentially (generate everything, then check everything). Both
    hot legs release the GIL inside jitted dispatches, so unlike
    §streaming's Python-bound producer the overlap is real. Bar:
    fused e2e <= ~1.2x max(gen_s, check_s) — the cheaper phase rides
    inside the dominant one (PERF.md §gen-jitted). Verdicts must be
    IDENTICAL between fused and sequential runs (asserted, not
    reported — a divergence is a soundness bug, not a slow cell)."""
    from jepsen_etcd_tpu.runner.stream import FusedPipeline
    from jepsen_etcd_tpu.simbatch import generate_for_opts
    opts = {"workload": "register", "nodes": ["n1", "n2", "n3"],
            "concurrency": 16, "rate": 1000.0, "time_limit": 7.52}
    seeds = list(range(16))
    # warm at the real shapes: generation jits compile at the
    # sub-batch size (a shape dim), check kernels at the pack widths
    warm = FusedPipeline(opts)
    warm.run(seeds[:warm.sub_batch])
    fused = FusedPipeline(opts).run(seeds)
    # sequential twin: one full batch generate, then the identical
    # per-history pack+prefix walk (same PackStream, same ladder)
    t0 = time.time()
    gen = generate_for_opts(dict(opts, gen_epoch="epoch-v3"), seeds)
    seq_gen_s = time.time() - t0
    twin = FusedPipeline(opts)
    t0 = time.time()
    seq_verdicts = {sd: twin._check_history(sd, h)[0]
                    for sd, h in zip(seeds, gen["histories"])}
    seq_check_s = time.time() - t0
    seq_e2e = seq_gen_s + seq_check_s
    assert seq_verdicts == fused["verdicts"], \
        "fused verdicts diverged from sequential"
    note(f"fused-pipeline: {len(seeds)} seeds, gen {fused['gen_s']:.2f}s"
         f" || check {fused['check_s']:.2f}s -> e2e {fused['e2e_s']:.2f}s"
         f" ({fused['ratio']:.3f}x max leg) vs sequential "
         f"{seq_e2e:.2f}s; packs={fused['packs']} "
         f"waves={fused['waves']}")
    return {"value": round(fused["ratio"], 3),
            "unit": "e2e/max(gen,check)",
            "seeds": len(seeds),
            "gen_s": round(fused["gen_s"], 3),
            "check_s": round(fused["check_s"], 3),
            "e2e_s": round(fused["e2e_s"], 3),
            "seq_e2e_s": round(seq_e2e, 3),
            "packs": fused["packs"], "waves": fused["waves"],
            "verdicts_identical": True,
            "vs_baseline": round(seq_e2e / max(fused["e2e_s"], 1e-9),
                                 2)}


def bench_campaign_amortization():
    """Campaign cell (PERF.md §campaign): the SAME 12-run seed matrix
    three ways — serial in-process test-all (the baseline every prior
    round paid), pooled campaign without the service (spawn
    parallelism only; every worker still owns a jax runtime and pays
    its own dispatches + compiles), and pooled campaign + shared
    checker service (ONE device owner; workers ship packed histories
    over the socket and never import jax). ``force_kernel`` pins every
    history to the device path so the dispatch ledger is visible on
    CPU CI too.

    The durable number is the dispatch ledger, not wall clock: per-run
    checking pays >= 1 dispatch per run per (bucket, width); the
    service pays 1 per (bucket, width, tick) however many runs' keys
    share it. On this box the device is jax-cpu and wall clocks are
    compile-dominated, so wall is REPORTED, never asserted."""
    from jepsen_etcd_tpu.runner.campaign import (campaign_specs,
                                                 run_campaign)
    base = {"time_limit": 3, "rate": 100.0, "force_kernel": True,
            "nodes": ["n1", "n2", "n3"], "snapshot_count": 100_000}

    def specs():
        return campaign_specs(base, ["register"], [[], ["kill"]],
                              runs_per_cell=6, seed0=31)

    def run_dispatches(summary):
        return sum(r.get("dispatches", 0) for r in summary["runs"]
                   if r and r.get("status") == "done")

    serial = run_campaign(specs(), pool=0, service=False,
                          name="bench-campaign-serial")
    pooled = run_campaign(specs(), pool=4, service=False,
                          name="bench-campaign-pooled")
    svc = run_campaign(specs(), pool=4, service=True,
                       name="bench-campaign-service")
    for arm in (serial, pooled, svc):
        assert arm["valid?"], arm["failures"]
    # same seeds => same verdicts, whichever arm checked them
    valids = [[r["valid"] for r in arm["runs"]]
              for arm in (serial, pooled, svc)]
    assert valids[0] == valids[1] == valids[2], valids
    sctr = (svc["service"] or {}).get("counters") or {}
    svc_dispatches = int(sctr.get("wgl.dispatches", 0)
                         + sctr.get("mxu.dispatches", 0))
    amort = run_dispatches(serial) / max(svc_dispatches
                                         + run_dispatches(svc), 1)
    note(f"campaign-amortization: {serial['count']} runs; dispatches "
         f"serial={run_dispatches(serial)} pooled={run_dispatches(pooled)} "
         f"service={svc_dispatches} (+{run_dispatches(svc)} local, "
         f"ticks={sctr.get('service.ticks')}, "
         f"group_ticks={sctr.get('service.group_ticks')}, "
         f"occupancy<={sctr.get('service.batch_occupancy')}); wall "
         f"serial={serial['wall_s']}s pooled={pooled['wall_s']}s "
         f"service={svc['wall_s']}s")
    return {"value": round(amort, 2), "unit": "dispatch-amortization",
            "runs": serial["count"],
            "serial": {"wall_s": serial["wall_s"],
                       "dispatches": run_dispatches(serial)},
            "pooled": {"wall_s": pooled["wall_s"],
                       "dispatches": run_dispatches(pooled)},
            "service": {"wall_s": svc["wall_s"],
                        "dispatches": svc_dispatches,
                        "local_dispatches": run_dispatches(svc),
                        "submitted": sctr.get("service.submitted"),
                        "coalesced": sctr.get("service.coalesced"),
                        "ticks": sctr.get("service.ticks"),
                        "group_ticks": sctr.get("service.group_ticks"),
                        "batch_occupancy":
                            sctr.get("service.batch_occupancy"),
                        "fallbacks": sum(
                            r.get("service_fallbacks", 0)
                            for r in svc["runs"] if r)},
            "vs_baseline": round(serial["wall_s"]
                                 / max(svc["wall_s"], 1e-9), 2)}


def _scaling_packs(tag: str):
    """Mixed-shape pack fleet for the service_scaling arms: three sim
    sizes so several (bucket, width) groups exist — the placement map
    has something to spread."""
    from jepsen_etcd_tpu.ops import wgl
    packs = []
    for kk, (keys, ops, conc) in enumerate([(4, 30, 4), (4, 120, 4),
                                            (2, 260, 6)]):
        subs, _, _ = _sim_keys(range(keys), ops, conc, 31 + kk,
                               f"svc-scaling-{tag}-{kk}",
                               nodes=["n1", "n2", "n3"])
        packs += [wgl.pack_register_history(subs[k]) for k in subs]
    return [p for p in packs if p.ok and p.R > 0]


def _service_scaling_arm(n_dev: int) -> dict:
    """Child half of the service_scaling cell, spawned with
    ``XLA_FLAGS=--xla_force_host_platform_device_count=<n>`` and
    JAX_PLATFORMS=cpu: the mixed-shape pack fleet through a live
    CheckerService, one warm round (compiles land on their sticky
    chips) then timed rounds. Returns the check wall plus the
    per-device dispatch ledger."""
    import jax
    from jepsen_etcd_tpu.runner import checker_service as svc_mod

    assert len(jax.devices()) == n_dev, (jax.devices(), n_dev)
    packs = _scaling_packs(str(n_dev))
    svc = svc_mod.CheckerService(tick_s=0.01).start()
    try:
        client = svc_mod.CheckerClient(svc.path)
        warm = client.check(packs)
        assert warm is not None and len(warm) == len(packs)
        t0 = time.time()
        for _ in range(3):
            assert client.check(packs) is not None
        check_s = time.time() - t0
        ctr = (svc.stats().get("counters") or {})
        client.close()
    finally:
        svc.close()
    disp = {k[len("service.device_dispatches."):]: v
            for k, v in ctr.items()
            if k.startswith("service.device_dispatches.")}
    return {"devices": n_dev, "check_s": round(check_s, 4),
            "packs": len(packs),
            "group_ticks": ctr.get("service.group_ticks"),
            "shard_fanout": ctr.get("service.shard_fanout", 0),
            "device_dispatches": disp,
            "occupancy": ctr.get("service.device_occupancy"),
            "sharded_ticks": ctr.get("service.sharded_ticks", 0)}


def _spawn_scaling_arm(n_dev: int) -> dict:
    """Run _service_scaling_arm in a fresh process: the host device
    count is process-global (XLA reads XLA_FLAGS once), so the 1- and
    8-device arms cannot share this interpreter."""
    import os
    import subprocess
    env = dict(os.environ)
    env["XLA_FLAGS"] = \
        f"--xla_force_host_platform_device_count={n_dev}"
    env["JAX_PLATFORMS"] = "cpu"
    out = subprocess.run(
        [sys.executable, os.path.abspath(__file__),
         "--service-scaling-arm", str(n_dev)],
        capture_output=True, text=True, env=env, timeout=900)
    assert out.returncode == 0, (out.returncode, out.stderr[-2000:])
    return json.loads(out.stdout.strip().splitlines()[-1])


def bench_service_scaling():
    """Service sharding cell (PERF.md §6): the SAME mixed-shape pack
    fleet through the checker service with 1 vs 8 host devices
    (subprocess arms), reporting the warm check-wall ratio. The 8 fake
    CPU devices share the same cores, so the ratio is REPORTED, never
    asserted — the cell's durable payload is the per-device dispatch
    ledger (every device dispatching, Σ == group_ticks + shard_fanout),
    the structure real-hardware scaling rides on."""
    a1 = _spawn_scaling_arm(1)
    a8 = _spawn_scaling_arm(8)
    for arm in (a1, a8):
        disp = arm["device_dispatches"]
        assert sum(disp.values()) == ((arm["group_ticks"] or 0)
                                      + (arm["shard_fanout"] or 0)), arm
    ratio = a1["check_s"] / max(a8["check_s"], 1e-9)
    note(f"service-scaling: 1-dev {a1['check_s']}s vs 8-dev "
         f"{a8['check_s']}s (ratio {ratio:.2f}x, 8-dev used "
         f"{len(a8['device_dispatches'])} chips, occupancy "
         f"{a8['occupancy']}, {a8['sharded_ticks']} sharded ticks)")
    return {"value": round(ratio, 3), "unit": "check_wall_ratio_1v8",
            "one_device": a1, "eight_device": a8,
            "chips_used": len(a8["device_dispatches"])}


def _mean_op_latency_ms(h):
    """Mean invoke->ok wall latency over client ops (ms), paired by
    process. Returns (mean_ms, n_ok)."""
    pend, lat = {}, []
    for o in h.client_ops():
        if o.get("type") == "invoke":
            pend[o.get("process")] = o.get("time")
        elif o.get("type") == "ok":
            t0 = pend.pop(o.get("process"), None)
            if t0 is not None and o.get("time") is not None:
                lat.append((o["time"] - t0) / 1e6)
    if not lat:
        return None, 0
    return sum(lat) / len(lat), len(lat)


def _verdict_skeleton(results):
    """The recursive valid?-only projection of a results tree: the
    VERDICT with every timing/detail field stripped, so two runs can
    be compared bit-for-bit on what they decided."""
    if not isinstance(results, dict):
        return None
    out = {}
    for k in sorted(results):
        v = results[k]
        if k == "valid?":
            out[k] = v
        elif isinstance(v, dict):
            sub = _verdict_skeleton(v)
            if sub:
                out[k] = sub
    return out


def _net_runs(time_limit, rate, seed):
    """The SAME single-node fake-etcd register run twice: direct, then
    through the userspace proxy plane (net/). Single node keeps the
    fake stub a linearizable register; the client hop is the proxied
    path being measured either way."""
    base = dict(client_type="http", db_mode="local", etcd_binary="fake",
                nodes=["n1"], time_limit=time_limit, rate=rate,
                seed=seed, snapshot_count=100_000)
    d_test, d_out, d_s = run_workload("register", **base)
    p_test, p_out, p_s = run_workload("register", net_proxy=True, **base)
    assert d_test["db"].plane is None
    assert p_test["db"].plane is not None
    return (d_test, d_out, d_s), (p_test, p_out, p_s)


def bench_net_overhead():
    """Proxy-plane overhead cell (PR 11): a no-fault `--db local` run
    direct vs proxied (--net-proxy), mean client op latency
    head-to-head. Wall numbers are REPORTED, never asserted (userspace
    splice cost rides host load); the asserted guarantee is
    structural — the proxied run's verdict skeleton is bit-identical
    to the direct run's, i.e. fronting every URL changes nothing a
    checker can see."""
    (d_test, d_out, _), (p_test, p_out, _) = _net_runs(
        time_limit=8, rate=100, seed=41)
    d_ms, d_n = _mean_op_latency_ms(d_out["history"])
    p_ms, p_n = _mean_op_latency_ms(p_out["history"])
    dsk = _verdict_skeleton(d_out["results"].get("workload"))
    psk = _verdict_skeleton(p_out["results"].get("workload"))
    assert dsk == psk, (dsk, psk)
    stats = p_test["db"].plane.stats()
    added = (p_ms - d_ms) if (p_ms is not None and d_ms is not None) \
        else None
    # a run with no ok ops yields None latencies — report the
    # degenerate cell instead of crashing on the format spec
    def fmt(v, spec=".2f"):
        return format(v, spec) if v is not None else "n/a"
    note(f"net-overhead: direct {fmt(d_ms)}ms/{d_n} ops, proxied "
         f"{fmt(p_ms)}ms/{p_n} ops (added {fmt(added, '+.2f')}ms); "
         f"plane={stats}")
    return {"value": round(added, 3) if added is not None else None,
            "unit": "added_ms_per_op",
            "direct_ms": round(d_ms, 3) if d_ms is not None else None,
            "proxied_ms": round(p_ms, 3) if p_ms is not None else None,
            "direct_ok_ops": d_n, "proxied_ok_ops": p_n,
            "plane": stats, "verdicts_identical": True,
            # overhead cell: vs_baseline is direct/proxied throughput
            # ratio, ~1.0 when the plane is invisible
            "vs_baseline": round(d_ms / max(p_ms, 1e-9), 2)
            if d_ms is not None and p_ms is not None else None}


def _telemetry_arms(n_ops, seed):
    """(off_s, on_s, summary, records): the SAME gen + pack + check +
    bulk-latency-hist work, once with every recorder off (run_test's
    via ``no_telemetry``, the check half under NULL) and once fully
    recorded (run_test's default file recorder for gen, a live
    file-backed one with a trace id for the check half). Same seed,
    warmup check first so neither timed arm pays compilation."""
    import os
    import tempfile
    from jepsen_etcd_tpu.ops import wgl
    from jepsen_etcd_tpu.runner import telemetry

    def check_half(h, tel):
        # run_test resets the process-current recorder on exit, so
        # (re)install the arm's before the device half
        telemetry.set_current(tel)
        p = wgl.pack_register_history(h)
        assert p.ok, p.reason
        out = wgl.check_packed(p)
        assert out["valid?"] is True, out
        # the campaign's per-row distribution cost: one bulk fold of
        # R synthetic per-op latencies through the log2 hist
        telemetry.current().hist_many(
            "op.latency.write",
            [1e-4 + (i % 97) * 1e-6 for i in range(p.R)])
        return p

    prev = telemetry.current()
    try:
        # --- off arm (also warms the compile cache for this shape) --
        t0 = time.time()
        h = sim_register_history(n_ops, CONCURRENCY, seed=seed,
                                 name="bench-tel-overhead",
                                 nodes=["n1", "n2", "n3"],
                                 extra={"no_telemetry": True})
        gen_off_s = time.time() - t0
        p = wgl.pack_register_history(h)
        assert p.ok, p.reason
        wgl.check_packed(p)  # warmup: compile + first search
        t0 = time.time()
        check_half(h, telemetry.NULL)
        off_s = gen_off_s + (time.time() - t0)
        # --- on arm: everything recorded -----------------------------
        t0 = time.time()
        h = sim_register_history(n_ops, CONCURRENCY, seed=seed,
                                 name="bench-tel-overhead",
                                 nodes=["n1", "n2", "n3"])
        gen_on_s = time.time() - t0
        with tempfile.TemporaryDirectory() as td:
            tel = telemetry.Telemetry(os.path.join(td, "tel.jsonl"),
                                      trace="bench-tel")
            t0 = time.time()
            check_half(h, tel)
            on_s = gen_on_s + (time.time() - t0)
            telemetry.set_current(telemetry.NULL)
            tel.close()
            summary = tel.summary()
            records = tel.records
    finally:
        telemetry.set_current(prev)
    return off_s, on_s, summary, records


def bench_telemetry_overhead():
    """Observability cell: what the trace plane costs on the
    register_50k path — recorder on (file-backed, trace id, hists)
    vs off (NULL), same seed, same work. The percentage is REPORTED,
    never asserted: the cell keeps the telemetry plane honest about
    its own overhead, it is not a gate."""
    off_s, on_s, summary, records = _telemetry_arms(67_500, seed=23)
    pct = 100.0 * (on_s - off_s) / max(off_s, 1e-9)
    note(f"telemetry-overhead: off={off_s:.3f}s on={on_s:.3f}s "
         f"({pct:+.1f}%, {records} records)")
    return {"value": round(pct, 2), "unit": "overhead_pct",
            "off_s": round(off_s, 4), "on_s": round(on_s, 4),
            "records": records,
            "hists": sorted((summary.get("hists") or {}).keys())}


#: the guided-search quarry: base opts seeding the stale-read bug that
#: only fires inside open partition windows, over a cell list that
#: EXCLUDES the bare [] cell (which fails unconditionally under the
#: legacy always-on injection and would trivialize the uniform arm)
_GUIDED_BASE = {"workload": "register", "nodes": ["n1", "n2", "n3"],
                "concurrency": 6, "rate": 100.0, "time_limit": 1.0,
                "inject_stale_reads": True, "gen_epoch": "epoch-v2"}
_GUIDED_CELLS = [["kill"], ["pause"], ["latency"], ["member"],
                 ["partition"]]


def _uniform_first_failure(specs) -> int | None:
    """1-based index of the first failing spec in matrix order, each
    evaluated as one single-seed batched generation + checker pass
    (the cheap stand-in for a full uniform campaign run)."""
    from jepsen_etcd_tpu.runner.shrink import checker_opts_from
    from jepsen_etcd_tpu.simbatch import BatchConfig, generate
    from jepsen_etcd_tpu.workloads import workloads
    for i, s in enumerate(specs):
        opts = s["opts"]
        cfg = BatchConfig.from_opts(opts)
        copts = checker_opts_from(opts)
        checker = workloads()[cfg.workload](dict(copts))["checker"]
        g = generate(cfg, [int(opts.get("seed", 0))])
        res = checker.check(dict(copts), g["histories"][0])
        if res.get("valid?") is not True:
            return i + 1
    return None


def bench_guided_search():
    """Robustness cell: coverage-guided search vs the uniform matrix,
    same seeded stale-read bug, same master seed. Reports runs-to-
    first-failure for both arms (the guided arm must not be slower
    than HALF the uniform arm — the acceptance bar tests/test_guided.py
    pins) plus the guided wall time and the minimized repro size."""
    import tempfile
    from jepsen_etcd_tpu.runner.campaign import campaign_specs
    from jepsen_etcd_tpu.runner.guided import run_guided

    specs = campaign_specs(_GUIDED_BASE, ["register"], _GUIDED_CELLS,
                           6, 7)
    t0 = time.time()
    uniform_first = _uniform_first_failure(specs)
    uniform_s = time.time() - t0
    assert uniform_first is not None, "uniform matrix never failed"
    t0 = time.time()
    with tempfile.TemporaryDirectory() as td:
        summary = run_guided(_GUIDED_BASE, ["register"], _GUIDED_CELLS,
                             budget=12, seed0=7, pool=0, service=False,
                             live=False, store_base=td)
        guided_s = time.time() - t0
        guided_first = summary["first_failure_run"]
        mins = summary["minimized"]
    assert guided_first is not None, "guided search never failed"
    note(f"guided-search: uniform first failure at run {uniform_first} "
         f"({uniform_s:.2f}s), guided at run {guided_first} "
         f"({guided_s:.2f}s), {len(mins)} minimized repro(s)")
    return {"value": round(uniform_first / max(guided_first, 1), 2),
            "unit": "x_fewer_runs",
            "uniform_first": uniform_first,
            "guided_first": guided_first,
            "guided_s": round(guided_s, 2),
            "uniform_s": round(uniform_s, 2),
            "minimized": [{"windows": m["windows"],
                           "nemesis_ops": m["nemesis_ops"]}
                          for m in mins]}


#: the four MVCC consistency-surface workloads (ISSUE 18): batched
#: generation + model build + surface checking, end to end
_MVCC_WORKLOADS = ("register-stale", "ranges", "lock-lease",
                   "compact-watch")


def _mvcc_check_all(opts_base: dict, seeds: list) -> dict:
    """Generate + check every surface workload; returns per-workload
    event counts, asserting every verdict is clean."""
    from jepsen_etcd_tpu.runner.shrink import checker_opts_from
    from jepsen_etcd_tpu.simbatch import BatchConfig, generate
    from jepsen_etcd_tpu.workloads import workloads as _workloads
    per = {}
    for wl in _MVCC_WORKLOADS:
        opts = dict(opts_base, workload=wl)
        cfg = BatchConfig.from_opts(opts)
        copts = checker_opts_from(opts)
        checker = _workloads()[wl](dict(copts))["checker"]
        g = generate(cfg, list(seeds))
        for h in g["histories"]:
            res = checker.check(dict(copts), h)
            assert res.get("valid?") is True, (wl, res)
        per[wl] = g["events"]
    return per


def bench_mvcc_surfaces():
    """Consistency-surface cell: end-to-end throughput of the MVCC
    subsystem — batched generation of all four surface workloads, the
    columnar model build (core/mvcc.py), and the surface checkers
    (checkers/mvcc.py), 16 seeds each, every verdict clean."""
    base = {"nodes": ["n1", "n2", "n3"], "concurrency": 8,
            "rate": 200.0, "time_limit": 5.0, "gen_epoch": "epoch-v2"}
    t0 = time.time()
    per = _mvcc_check_all(base, list(range(16)))
    wall = time.time() - t0
    events = sum(per.values())
    rate = events / max(wall, 1e-9)
    note(f"mvcc-surfaces: {events} events over "
         f"{len(per)} workloads x 16 seeds in {wall:.2f}s "
         f"({rate:,.0f} ev/s, generate+model+check)")
    return {"value": round(rate, 1), "unit": "events_per_s",
            "events": events, "wall_s": round(wall, 2),
            "per_workload": per}


def _synth_store(base, n, start=0, fail_every=7):
    """Write ``n`` tiny synthetic runs under ``base`` (the two-level
    ``<store>/<test>/<run>`` layout save_run produces): results.json +
    test.json + a one-line history.jsonl each, a failing verdict every
    ``fail_every``-th run so the aggregate's failure table and the
    coverage signatures are non-trivial."""
    import json as _json
    import os
    os.makedirs(base, exist_ok=True)
    for i in range(start, start + n):
        tname = f"synth-{i % 5}"
        rdir = os.path.join(base, tname, f"{i:05d}")
        os.makedirs(rdir)
        failed = bool(fail_every) and i % fail_every == 0
        results = {
            "valid?": not failed,
            "stats": {"count": 100 + i},
            "workload": {"valid?": not failed},
            "telemetry": {
                "phases": {"generate": 0.5, "check": 0.25},
                "counters": {"generate.ops_per_s": 1000.0 + i,
                             "wgl.max-frontier": 4 + i % 3,
                             "wgl.rungs": 2, "wgl.waves": 3,
                             "wgl.host-spill": i % 2},
            },
        }
        test = {"name": tname, "workload": "register",
                "nemesis": ["kill"] if i % 2 else ["partition"],
                "db_mode": "sim", "time_limit": 5, "seed": i}
        with open(os.path.join(rdir, "results.json"), "w") as f:
            _json.dump(results, f)
        with open(os.path.join(rdir, "test.json"), "w") as f:
            _json.dump(test, f)
        with open(os.path.join(rdir, "history.jsonl"), "w") as f:
            f.write('{"type": "invoke", "f": "write", "value": 1}\n')


def bench_store_index():
    """Indexed-store serving cell: warm ``/aggregate`` latency must
    stay flat (within the ±2x acceptance bar) from 100 to 10k runs —
    the fold replays only rows past its high-water mark and the render
    cache keys off the index generation, so a warm request pays two
    stats and a dict lookup regardless of store size."""
    import os
    import shutil
    import tempfile
    from jepsen_etcd_tpu import serve
    from jepsen_etcd_tpu.runner import store_index

    sizes = (100, 10_000)
    walls = {}
    calls = 200  # amortize the sub-ms warm path over a batch
    for n in sizes:
        tmp = tempfile.mkdtemp(prefix=f"bench-idx-{n}-")
        try:
            t0 = time.time()
            _synth_store(tmp, n)
            synth_s = time.time() - t0
            t0 = time.time()
            store_index.rebuild(tmp)
            rebuild_s = time.time() - t0
            t0 = time.time()
            page = serve.aggregate_html(tmp, page=1, per=50)
            cold_s = time.time() - t0  # fold + full render, once
            assert f"{n} runs" in page, "aggregate lost runs"
            batches = []
            for _ in range(5):
                t0 = time.time()
                for _ in range(calls):
                    serve.aggregate_html(tmp, page=1, per=50)
                batches.append((time.time() - t0) / calls)
            walls[n] = {"warm_s": sorted(batches)[len(batches) // 2],
                        "cold_s": cold_s, "rebuild_s": rebuild_s,
                        "synth_s": synth_s}
        finally:
            shutil.rmtree(tmp, ignore_errors=True)
            serve._AGG_CACHE.clear()
            store_index._FOLDS.clear()
    ratio = walls[10_000]["warm_s"] / max(walls[100]["warm_s"], 1e-9)
    note(f"store-index: warm /aggregate "
         f"{walls[100]['warm_s'] * 1e6:.0f}us @100 vs "
         f"{walls[10_000]['warm_s'] * 1e6:.0f}us @10k "
         f"({ratio:.2f}x; cold render {walls[10_000]['cold_s']:.2f}s, "
         f"rebuild {walls[10_000]['rebuild_s']:.2f}s)")
    assert ratio <= 2.0, \
        f"warm /aggregate not flat 100 -> 10k: {ratio:.2f}x"
    return {"value": round(ratio, 3), "unit": "x_100_to_10k",
            "warm_us_100": round(walls[100]["warm_s"] * 1e6, 1),
            "warm_us_10k": round(walls[10_000]["warm_s"] * 1e6, 1),
            "cold_s_10k": round(walls[10_000]["cold_s"], 3),
            "rebuild_s_10k": round(walls[10_000]["rebuild_s"], 3)}


CELLS = [("register_100", bench_register_100),
         ("engine_crossover", bench_engine_crossover),
         ("deep_wgl_4n_2000", bench_deep_wgl),
         ("w128_deep", bench_w128_deep),
         ("faulted_register", bench_faulted_register),
         ("batched_64_keys", bench_batched_keys),
         ("gen_throughput", bench_gen_throughput),
         ("register_50k", bench_register_50k),
         ("batched_512_keys", bench_batched_512_keys),
         ("set_full", bench_set),
         ("elle_append_device", bench_elle_append),
         ("closure_scale_2048", bench_closure_scale),
         ("watch_edit_distance", bench_watch),
         ("streaming_overlap", bench_streaming_overlap),
         ("fused_pipeline", bench_fused_pipeline),
         ("net_overhead", bench_net_overhead),
         ("telemetry_overhead", bench_telemetry_overhead),
         ("campaign_amortization", bench_campaign_amortization),
         ("service_scaling", bench_service_scaling),
         ("guided_search", bench_guided_search),
         ("mvcc_surfaces", bench_mvcc_surfaces),
         ("store_index", bench_store_index)]


# ---------------------------------------------------------------------
# --dry smoke mode: each check exercises the SAME code path as its
# bench cell at tiny sizes and asserts STRUCTURE — engine routing and
# packer equivalence — never timings, so it runs under tier-1 pytest
# with JAX_PLATFORMS=cpu in seconds.
# ---------------------------------------------------------------------

_DRY_SEED = 99


def _assert_packs_equal(a, b):
    import dataclasses
    import numpy as np
    from jepsen_etcd_tpu.ops import wgl
    wgl.ensure_frames(a)
    wgl.ensure_frames(b)
    for fld in dataclasses.fields(type(a)):
        x, y = getattr(a, fld.name), getattr(b, fld.name)
        if isinstance(x, np.ndarray) or isinstance(y, np.ndarray):
            assert np.array_equal(x, y), fld.name
        else:
            assert x == y, (fld.name, x, y)


def _dry_register():
    """Tiny single key: batched packer == per-key reference,
    production routes below CPU_CUTOFF to the host engine, verdict
    True."""
    from jepsen_etcd_tpu.ops import wgl
    from jepsen_etcd_tpu.checkers.tpu_linearizable import (
        TPULinearizableChecker)
    h = sim_register_history(40, 4, seed=_DRY_SEED, name="dry-register")
    p = wgl.pack_register_history(h)
    assert p.ok, p.reason
    _assert_packs_equal(p, wgl._pack_reference(h))
    res = TPULinearizableChecker().check({}, h)
    assert res["valid?"] is True, res
    assert res["checker"] == "cpu-oracle", res   # size-cutoff routing
    return {"ops": p.R, "engine": res["checker"]}


def _dry_batched():
    """Tiny key batch: batched SoA packer bit-identical to the
    reference per key, pack_perop_batch bit-identical to the per-key
    loop, forced MXU batch verdicts agree with production routing."""
    import numpy as np
    from jepsen_etcd_tpu.ops import wgl, wgl_mxu
    from jepsen_etcd_tpu.checkers.tpu_linearizable import (
        TPULinearizableChecker)
    K = 8
    subs, _, _ = _sim_keys(range(K), 30, 4, _DRY_SEED, "dry-batched",
                           nodes=["n1", "n2", "n3"])
    packs_by_key = wgl.pack_register_histories_batched(subs)
    for k in range(K):
        _assert_packs_equal(packs_by_key[k],
                            wgl._pack_reference(subs[k]))
    packs = [packs_by_key[k] for k in range(K)]
    sup = [p for p in packs if wgl_mxu.supported(p)]
    assert sup, "no MXU-supported pack in the dry batch"
    r_pad = max(max(wgl_mxu.bucket(p.R) for p in sup), wgl_mxu.TSUB)
    bi, bu = wgl_mxu.pack_perop_batch(sup, r_pad, len(sup) + 2)
    for j, p in enumerate(sup):
        a, b = wgl_mxu.pack_perop(p, r_pad)
        assert np.array_equal(bi[j], a) and np.array_equal(bu[j], b), j
    mxu = wgl_mxu.check_packed_batch_mxu(packs)
    pres = TPULinearizableChecker().check_batch({}, subs)
    for i, k in enumerate(range(K)):
        assert pres[k]["valid?"] is True, pres[k]
        if mxu[i] is not None:
            assert mxu[i]["valid?"] == pres[k]["valid?"], (k, mxu[i])
            assert mxu[i]["engine"] == "mxu-wave", mxu[i]
    engines = {r["checker"] for r in pres.values()}
    assert engines == {"cpu-oracle"}, engines   # tiny keys: host route
    return {"keys": K, "mxu_supported": len(sup),
            "engines": sorted(engines)}


def _dry_set():
    """Tiny set workload: columnar analysis == reference sweep,
    checker verdict True."""
    import importlib
    # the set_full() factory shadows the module name on package import
    sf = importlib.import_module("jepsen_etcd_tpu.checkers.set_full")
    test, out, _ = run_workload("set", time_limit=3, rate=100)
    h = out["history"]
    hh = h if isinstance(h, sf.History) else sf.History(h)
    col = sf._analyze_columnar(hh)
    ref = sf._analyze_reference(hh)
    assert col == ref, "columnar set analysis diverges from reference"
    res = sf.SetFull(linearizable=True).check(test, h)
    assert res["valid?"] is True, res
    return {"ops": len(h), "attempts": res["attempt-count"]}


def _dry_closure():
    """Tiny closure: fixpoint-early-exit device kernel bit-identical
    to the numpy reference, cycle polarity both ways."""
    import numpy as np
    import jax.numpy as jnp
    from jepsen_etcd_tpu.ops import closure
    rng = np.random.RandomState(_DRY_SEED)
    a = rng.rand(3, 48, 48) < 0.04
    r_np, oc_np = closure._closure_numpy(a)
    r_dev, oc_dev = closure._closure_device(jnp.asarray(a), 7)
    assert np.array_equal(r_np, np.asarray(r_dev))
    assert np.array_equal(oc_np, np.asarray(oc_dev))
    acyc = np.triu(np.ones((2, 16, 16), bool), 1)  # DAG: no cycles
    _, oc = closure._closure_device(jnp.asarray(acyc), 5)
    assert not np.asarray(oc).any()
    return {"subgraphs": 3, "nodes": 48,
            "cycles": int(oc_np.any(axis=-1).sum())}


def _dry_gen_throughput():
    """Tiny sim through the full run path: the recorded history carries
    SoA columns matching the dict stream event-for-event, and the
    profile bucketing covers every cost center (structure only — no
    timing asserts, CPU-safe)."""
    import cProfile
    from jepsen_etcd_tpu.core.history import History
    prof = cProfile.Profile()
    prof.enable()
    test, out, _ = run_workload("register", time_limit=3, rate=100,
                                seed=_DRY_SEED)
    prof.disable()
    h = out["history"]
    cols = getattr(h, "columns", None)
    assert cols is not None, "recorded history lost its columns"
    assert len(cols) == len(h), (len(cols), len(h))
    assert [dict(o) for o in History.from_columns(cols).ops] == \
        [dict(o) for o in h.ops], "columns diverge from dict stream"
    bk = _bucket_gen_profile(prof, len(h))
    assert set(bk) == {"timer_churn", "queue_hops", "generator_poll",
                       "record", "sut", "other"}, bk
    assert bk["generator_poll"]["s"] > 0 and bk["sut"]["s"] > 0, bk
    batched = _dry_gen_batched()
    jitted = _dry_gen_jitted()
    return {"ops": len(h), "events": len(cols), "batched": batched,
            "jitted": jitted}


def _dry_gen_batched():
    """Structural check of the batched leg (no timing asserts): a tiny
    16-seed batch generates deterministically, histories are BORN
    columnar (never materialized to dicts by generation itself), and
    the genbatch stats the leg reports are self-consistent."""
    from jepsen_etcd_tpu.simbatch import (GEN_EPOCH_V2,
                                          generate_for_opts,
                                          history_sha)
    bopts = {"workload": "register", "nodes": ["n1", "n2", "n3"],
             "concurrency": 8, "rate": 200.0, "time_limit": 2.0,
             "seed": _DRY_SEED}
    seeds = list(range(16))
    g1 = generate_for_opts(bopts, seeds)
    assert g1["epoch"] == GEN_EPOCH_V2, g1["epoch"]
    assert len(g1["histories"]) == 16
    assert g1["events"] == sum(len(h) for h in g1["histories"])
    assert g1["steps"] > 0
    for h in g1["histories"]:
        assert h._ops is None, "batched history materialized dicts"
        assert len(h.columns) == len(h)
    g2 = generate_for_opts(bopts, seeds)
    sh1 = [history_sha(h) for h in g1["histories"]]
    sh2 = [history_sha(h) for h in g2["histories"]]
    assert sh1 == sh2, "batched generation not deterministic"
    return {"seeds": len(seeds), "events": g1["events"],
            "steps": g1["steps"]}


def _dry_gen_jitted():
    """Structural twin of the jitted leg (no timing asserts): the
    epoch-v3 route through generate_for_opts produces columnar,
    deterministic histories stamped with the v3 ledger epoch, at the
    same tiny shape as the batched dry check."""
    from jepsen_etcd_tpu.simbatch import (GEN_EPOCH_V3,
                                          generate_for_opts,
                                          history_sha)
    jopts = {"workload": "register", "nodes": ["n1", "n2", "n3"],
             "concurrency": 8, "rate": 200.0, "time_limit": 2.0,
             "seed": _DRY_SEED, "gen_epoch": "epoch-v3"}
    seeds = list(range(16))
    g1 = generate_for_opts(jopts, seeds)
    assert g1["epoch"] == GEN_EPOCH_V3, g1["epoch"]
    assert len(g1["histories"]) == 16
    assert g1["events"] == sum(len(h) for h in g1["histories"])
    for h in g1["histories"]:
        assert h._ops is None, "jitted history materialized dicts"
        assert len(h.columns) == len(h)
    g2 = generate_for_opts(jopts, seeds)
    sh1 = [history_sha(h) for h in g1["histories"]]
    sh2 = [history_sha(h) for h in g2["histories"]]
    assert sh1 == sh2, "jitted generation not deterministic"
    assert len(set(sh1)) == 16, "jitted seeds not distinct"
    return {"seeds": len(seeds), "events": g1["events"]}


def _dry_fused_pipeline():
    """Structural twin of the fused cell: a tiny seed set through
    FusedPipeline with a small chunk size (forcing multi-chunk packing
    per history) must produce the IDENTICAL verdict map as the
    sequential generate-then-check twin, and the overlap accounting
    fields must be present and self-consistent."""
    from jepsen_etcd_tpu.runner.stream import FusedPipeline
    from jepsen_etcd_tpu.simbatch import generate_for_opts
    opts = {"workload": "register", "nodes": ["n1", "n2", "n3"],
            "concurrency": 8, "rate": 200.0, "time_limit": 2.0}
    seeds = list(range(4))
    fused = FusedPipeline(opts, sub_batch=2,
                          chunk_rows=64).run(seeds)
    assert sorted(fused["verdicts"]) == seeds, fused["verdicts"]
    assert fused["packs"] >= len(seeds), fused
    assert fused["waves"] > 0, fused
    assert fused["e2e_s"] >= max(fused["gen_s"], fused["check_s"]), \
        fused
    gen = generate_for_opts(dict(opts, gen_epoch="epoch-v3"), seeds)
    twin = FusedPipeline(opts, chunk_rows=64)
    seq_verdicts = {sd: twin._check_history(sd, h)[0]
                    for sd, h in zip(seeds, gen["histories"])}
    assert seq_verdicts == fused["verdicts"], \
        (seq_verdicts, fused["verdicts"])
    try:
        FusedPipeline(dict(opts, workload="set"))
    except ValueError:
        pass
    else:
        raise AssertionError("non-register workload accepted")
    return {"seeds": len(seeds), "packs": fused["packs"],
            "waves": fused["waves"],
            "verdicts": fused["verdicts"]}


def _dry_watch():
    """Tiny watch workload through the real checker."""
    from jepsen_etcd_tpu.checkers.watch import WatchChecker
    test, out, _ = run_workload("watch", time_limit=3, rate=100)
    res = WatchChecker(use_tpu=True).check(test, out["history"])
    assert res["valid?"] in (True, "unknown"), res
    return {"ops": len(out["history"]), "valid": res["valid?"]}


def _dry_streaming():
    """Tiny streamed run vs its post-hoc twin: chunked feeding actually
    happened (>= 2 chunks at a small chunk size), the worker consumed
    every recorded row, the stream counters landed in the run summary,
    and the workload verdict is BIT-identical (same serialized dict)."""
    opts = dict(rate=100, time_limit=3, seed=_DRY_SEED,
                stream_chunk_ops=64)
    s_test, s_out, _ = run_workload("register", stream=True, **opts)
    p_test, p_out, _ = run_workload("register", **opts)
    hints = s_test.get("_stream") or {}
    stats = hints.get("stats") or {}
    assert stats.get("chunks", 0) >= 2, stats
    assert stats.get("rows") == len(s_out["history"]), stats
    assert "register_packs" in hints, sorted(hints)
    assert not p_test.get("_stream"), "post-hoc run grew stream hints"
    ctr = (s_out["results"].get("telemetry") or {}).get("counters") or {}
    assert ctr.get("stream.chunks") == stats["chunks"], ctr
    assert ctr.get("stream.flushed_events") == stats["rows"], ctr
    assert ctr.get("stream.register_packs_reuse"), ctr
    sv = json.dumps(s_out["results"]["workload"], sort_keys=True,
                    default=repr)
    pv = json.dumps(p_out["results"]["workload"], sort_keys=True,
                    default=repr)
    assert sv == pv, "streamed verdict diverged from post-hoc"
    return {"ops": len(s_out["history"]), "chunks": stats["chunks"]}


def _dry_campaign():
    """Campaign structure at tiny size: the Packed wire format
    round-trips bit-identically, a live checker service returns the
    SAME verdict projection as local ``check_packed`` for the same
    packs (singleton ladder AND cross-history batch), its coalescing
    counters account for every submitted pack, and a dead socket
    degrades to local checking (client_for -> None), never an error."""
    import numpy as np
    from jepsen_etcd_tpu.ops import wgl
    from jepsen_etcd_tpu.runner import checker_service as svc_mod

    subs, _, _ = _sim_keys(range(2), 30, 4, _DRY_SEED, "dry-campaign",
                           nodes=["n1", "n2", "n3"])
    packs = [wgl.pack_register_history(subs[k]) for k in range(2)]
    for p in packs:
        assert p.ok, p.reason
        q = wgl.deserialize_packed(wgl.serialize_packed(p))
        _assert_packs_equal(p, q)

    proj = ("valid?", "waves", "peak-frontier", "ops", "info-ops",
            "op", "error", "stuck-at-depth")

    def view(out):
        return {k: out.get(k) for k in proj}

    local = [wgl.check_packed(p) for p in packs]
    svc = svc_mod.CheckerService(tick_s=0.01).start()
    try:
        client = svc_mod.CheckerClient(svc.path)
        # one pack per request: singleton-ladder route in the service
        one = client.check(packs[:1])
        assert one is not None and view(one[0]) == view(local[0]), one
        # both packs in one request: cross-history batched route
        both = client.check(packs)
        assert both is not None, "service unreachable"
        for got, want in zip(both, local):
            assert view(got) == view(want), (view(got), view(want))
        ctr = (svc.stats().get("counters") or {})
        assert ctr.get("service.submitted") == 3, ctr
        assert ctr.get("service.requests") == 2, ctr
        assert ctr.get("service.ticks", 0) >= 1, ctr
        client.close()
    finally:
        svc.close()
    # degradation: dead socket -> no client -> caller checks locally
    svc_mod.reset_clients()
    dead = svc_mod.client_for({"checker_service": svc.path})
    assert dead is None, "client_for returned a client for a dead socket"
    svc_mod.reset_clients()
    return {"packs": len(packs), "ops": int(sum(p.R for p in packs)),
            "verdicts_identical": True}


def _dry_service_scaling():
    """Tiny structural pass of the sharded service (no timing, no
    subprocess arms): distinct groups land on distinct sticky devices
    when a mesh is visible, the per-device dispatch counters balance
    the group ledger, stats carries the device roster + placement map,
    and every service verdict projection matches local
    ``check_packed`` on the same pack."""
    import jax
    from jepsen_etcd_tpu.ops import wgl
    from jepsen_etcd_tpu.runner import checker_service as svc_mod

    packs = []
    for kk, (keys, ops) in enumerate([(2, 30), (2, 120)]):
        subs, _, _ = _sim_keys(range(keys), ops, 4, _DRY_SEED + kk,
                               f"dry-svc-scaling-{kk}",
                               nodes=["n1", "n2", "n3"])
        packs += [wgl.pack_register_history(subs[k]) for k in subs]
    assert all(p.ok and p.R > 0 for p in packs), \
        [(p.ok, p.R) for p in packs]
    local = [wgl.check_packed(p) for p in packs]
    proj = ("valid?", "waves", "peak-frontier", "ops", "info-ops",
            "op", "error", "stuck-at-depth")

    def view(out):
        return {k: out.get(k) for k in proj}

    svc = svc_mod.CheckerService(tick_s=0.01).start()
    try:
        client = svc_mod.CheckerClient(svc.path)
        outs = client.check(packs)
        assert outs is not None, "service unreachable"
        for got, want in zip(outs, local):
            assert view(got) == view(want), (view(got), view(want))
        st = svc.stats()
        ctr = st.get("counters") or {}
        disp = {k: v for k, v in ctr.items()
                if k.startswith("service.device_dispatches.")}
        assert disp, sorted(ctr)
        assert sum(disp.values()) == \
            (ctr.get("service.group_ticks", 0)
             + ctr.get("service.shard_fanout", 0)), ctr
        assert st.get("devices"), st.get("devices")
        placement = st.get("placement") or {}
        assert placement, st
        if len(jax.devices()) > 1 and len(placement) > 1:
            # sticky round-robin: distinct group shapes spread out
            assert len(set(placement.values())) > 1, placement
        client.close()
    finally:
        svc.close()
    return {"packs": len(packs), "devices": len(jax.devices()),
            "chips_used": len(disp), "verdicts_identical": True}


def _dry_net_overhead():
    """Tiny proxied run vs its direct twin: the plane actually fronted
    the node's URLs (links counted, ports split listen-vs-advertise),
    and the no-fault proxied verdict skeleton is BIT-identical to the
    direct run's — the tier-1 guard that the proxy is invisible to
    checkers."""
    (d_test, d_out, _), (p_test, p_out, _) = _net_runs(
        time_limit=3, rate=50, seed=_DRY_SEED)
    plane = p_test["db"].plane
    stats = plane.stats()
    assert stats["links"] == 2, stats          # client + peer for n1
    assert p_test["db"].proxy_ports["n1"] != p_test["db"].ports["n1"]
    ctr = (p_out["results"].get("telemetry") or {}).get("counters") or {}
    assert ctr.get("net.links") == 2, ctr
    dsk = _verdict_skeleton(d_out["results"].get("workload"))
    psk = _verdict_skeleton(p_out["results"].get("workload"))
    assert dsk and dsk == psk, (dsk, psk)
    assert psk.get("valid?") is True, psk
    return {"ops": len(p_out["history"]), "links": stats["links"],
            "verdicts_identical": True}


def _dry_telemetry_overhead():
    """Tiny two-arm run: both arms complete, the on-arm summary
    carries the trace id, the op-latency hist (count == ops), and the
    wgl spans — structure only, the overhead number is never
    asserted."""
    off_s, on_s, summary, records = _telemetry_arms(600,
                                                    seed=_DRY_SEED)
    assert summary.get("trace") == "bench-tel", summary.get("trace")
    hists = summary.get("hists") or {}
    assert "op.latency.write" in hists, sorted(hists)
    assert hists["op.latency.write"]["count"] > 0, hists
    assert any(n.startswith("wgl.") for n in summary.get("spans")
               or {}), sorted(summary.get("spans") or {})
    assert records > 0, records
    assert off_s > 0 and on_s > 0, (off_s, on_s)
    return {"records": records,
            "hist_count": hists["op.latency.write"]["count"]}


def _dry_guided_search():
    """Guided-search structure at tiny size, no timing: (a) two
    schedulers with the same master seed emit byte-identical candidate
    generations (the search is a pure function of the seed), and (b) a
    drawn fault plan, its materialized explicit schedule, and a batched
    same-seed population all generate BIT-identical histories — the
    determinism contract shrink's candidate re-execution rests on."""
    import json as _json
    from jepsen_etcd_tpu.runner.guided import GuidedScheduler
    from jepsen_etcd_tpu.simbatch import (BatchConfig, default_schedule,
                                          generate, history_sha)

    base = dict(_GUIDED_BASE, time_limit=0.5)
    cells = [["partition"], ["kill"]]
    ancestor = dict(base, workload="register", nemesis=["partition"],
                    seed=_DRY_SEED)
    gens = []
    for _ in range(2):
        s = GuidedScheduler(base, ["register"], cells,
                            seed0=_DRY_SEED, master_seed=_DRY_SEED)
        s.corpus.append({"opts": ancestor, "seed": _DRY_SEED, "run": 1,
                         "score": 4, "signature": "workload=False",
                         "vector": {"frontier": 1, "rungs": 0,
                                    "spills": 0}})
        s.corpus.append({"opts": dict(ancestor, nemesis=["kill"],
                                      seed=_DRY_SEED + 1),
                         "seed": _DRY_SEED + 1, "run": 2, "score": 1,
                         "signature": "",
                         "vector": {"frontier": 1, "rungs": 0,
                                    "spills": 0}})
        gens.append([s.next_generation(6) for _ in range(3)])
    assert _json.dumps(gens[0], sort_keys=True) == \
        _json.dumps(gens[1], sort_keys=True), "mutation nondeterminism"
    mutated = sum(1 for g in gens[0] for o in g
                  if o.get("nem_schedule") or o.get("nem_drop_prob")
                  or o.get("nem_partition_shape")
                  or o.get("nem_latency_ms"))
    assert mutated, "no schedule/knob mutations in 18 candidates"

    cfg = BatchConfig.from_opts(ancestor)
    drawn = generate(cfg, [_DRY_SEED])["histories"][0]
    sched = default_schedule(cfg, _DRY_SEED)
    explicit = generate(cfg, [_DRY_SEED],
                        nem_schedules=[sched])["histories"][0]
    pop = generate(cfg, [_DRY_SEED] * 3,
                   nem_schedules=[sched] * 3)["histories"]
    sha = history_sha(drawn)
    assert history_sha(explicit) == sha, \
        "materialized schedule diverges from the drawn plan"
    assert all(history_sha(h) == sha for h in pop), \
        "batched same-seed population diverges"
    return {"candidates": sum(len(g) for g in gens[0]),
            "mutated": mutated, "windows": len(sched),
            "replay_identical": True}


def _dry_mvcc_surfaces():
    """MVCC surface structure at tiny size, no timing: every surface
    workload generates and checks clean, and each engine injection
    flag trips EXACTLY its pinned verdict class (the same pins
    tests/test_mvcc.py regression-tests in depth)."""
    from jepsen_etcd_tpu.runner.shrink import checker_opts_from
    from jepsen_etcd_tpu.simbatch import BatchConfig, generate
    from jepsen_etcd_tpu.workloads import workloads as _workloads

    base = {"nodes": ["n1", "n2", "n3"], "concurrency": 8,
            "rate": 200.0, "time_limit": 2.0, "gen_epoch": "epoch-v2",
            "staleness_bound_s": 0.5}
    per = _mvcc_check_all(base, [_DRY_SEED])
    assert all(v > 0 for v in per.values()), per
    pins = {"register-stale": ("inject_stale_snapshot", "staleness",
                               "stale-beyond-bound"),
            "ranges": ("inject_torn_range", "ranges", "torn-range"),
            "lock-lease": ("inject_double_grant", "lease",
                           "double-grant"),
            "compact-watch": ("inject_compaction_swallow", "watch-mvcc",
                              "lost-event")}
    tripped = {}
    for wl, (flag, key, klass) in pins.items():
        opts = dict(base, workload=wl, **{flag: True})
        cfg = BatchConfig.from_opts(opts)
        copts = checker_opts_from(opts)
        checker = _workloads()[wl](dict(copts))["checker"]
        h = generate(cfg, [_DRY_SEED])["histories"][0]
        res = checker.check(dict(copts), h)
        assert res.get("valid?") is False, (wl, res)
        classes = {v["class"] for v in res[key]["violations"]}
        assert classes == {klass}, (wl, classes)
        tripped[wl] = klass
    return {"events": sum(per.values()), "workloads": len(per),
            "pins": tripped}


def _dry_store_index():
    """Index structure at tiny size, no timing: a rebuilt index must
    replay the exact rows a tree walk derives, survive the
    row-count/fingerprint verify, match an incrementally-written index
    row-for-row, and window /aggregate tables with clamped bounds."""
    import os
    import shutil
    import tempfile
    from jepsen_etcd_tpu import serve
    from jepsen_etcd_tpu.runner import store_index

    tmp = tempfile.mkdtemp(prefix="dry-idx-")
    try:
        _synth_store(tmp, 12)
        walk = serve._run_rows(tmp)  # no index yet: the tree walk
        store_index.rebuild(tmp)
        fold = store_index.fold(tmp)
        assert fold is not None, "rebuild produced no readable index"
        indexed = store_index.serve_run_rows(fold)
        assert indexed == walk, "index rows != walk rows"
        v = store_index.verify(tmp)
        assert v["ok"], v

        # incremental writes land the same rows a full rebuild derives
        inc = os.path.join(tmp, "inc-store")
        _synth_store(inc, 3)
        store_index.rebuild(inc)
        _synth_store(inc, 3, start=3)
        for i in range(3, 6):
            store_index.record_run(
                os.path.join(inc, f"synth-{i % 5}", f"{i:05d}"))
        f_inc = store_index.fold(inc)
        rows_inc = store_index.serve_run_rows(f_inc)
        store_index.rebuild(inc)
        rows_reb = store_index.serve_run_rows(store_index.fold(inc))
        assert rows_inc == rows_reb, "incremental != rebuild rows"

        # pagination bounds: interior window, and out-of-range clamps
        page2 = serve.aggregate_html(tmp, page=2, per=5)
        assert "rows 6–10 of 12" in page2, "page window off"
        clamped = serve.aggregate_html(tmp, page=99, per=5)
        assert "rows 11–12 of 12" in clamped, "page clamp off"
        assert serve._page_window(12, "junk", "junk") == \
            (0, 12, 1, 1, serve._DEF_PER), "bad query args must clamp"
        return {"runs": 12, "rows": len(indexed),
                "fingerprint": v["fingerprint"], "incremental": 3}
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
        serve._AGG_CACHE.clear()
        store_index._FOLDS.clear()


DRY_CHECKS = {"register_100": _dry_register,
              "engine_crossover": _dry_register,
              "deep_wgl_4n_2000": _dry_register,
              "w128_deep": _dry_register,
              "faulted_register": _dry_register,
              "register_50k": _dry_register,
              "gen_throughput": _dry_gen_throughput,
              "batched_64_keys": _dry_batched,
              "batched_512_keys": _dry_batched,
              "set_full": _dry_set,
              "elle_append_device": _dry_closure,
              "closure_scale_2048": _dry_closure,
              "watch_edit_distance": _dry_watch,
              "streaming_overlap": _dry_streaming,
              "fused_pipeline": _dry_fused_pipeline,
              "net_overhead": _dry_net_overhead,
              "telemetry_overhead": _dry_telemetry_overhead,
              "campaign_amortization": _dry_campaign,
              "service_scaling": _dry_service_scaling,
              "guided_search": _dry_guided_search,
              "mvcc_surfaces": _dry_mvcc_surfaces,
              "store_index": _dry_store_index,
              "register_10k": _dry_register}


#: modules whose lint cleanliness gates a bench round: the register
#: kernel driver and the set checker are exactly the code BENCH rounds
#: time, and a determinism/columnar/dispatch regression there makes
#: the numbers wrong before they're slow
LINT_GATED = ("jepsen_etcd_tpu/ops/wgl.py",
              "jepsen_etcd_tpu/checkers/set_full.py",
              # the campaign cell times these two: a thread-safety or
              # determinism slip there corrupts the dispatch ledger
              "jepsen_etcd_tpu/runner/campaign.py",
              "jepsen_etcd_tpu/runner/checker_service.py",
              # the mvcc_surfaces cell times the columnar model build
              # and the surface checkers: a dict materialization there
              # IS the regression the cell exists to catch
              "jepsen_etcd_tpu/core/mvcc.py",
              "jepsen_etcd_tpu/checkers/mvcc.py",
              # the store_index cell times the fold/render path over
              # index rows derived by these two: a determinism or
              # registry slip there skews every dashboard they feed
              "jepsen_etcd_tpu/runner/store.py",
              "jepsen_etcd_tpu/runner/store_index.py")


def _lint_gate() -> None:
    """Run graftlint over the bench-critical modules; raises on any
    non-suppressed finding. Pure-AST, a few ms — cheap insurance that
    a cell isn't about to time a dispatch storm or a dict round-trip."""
    import os
    from jepsen_etcd_tpu.lint import run_lint
    report = run_lint(paths=[os.path.join(os.path.dirname(
        os.path.abspath(__file__)), p) for p in LINT_GATED])
    if report.errors:
        lines = "\n".join(f"  {f.location()}: {f.rule}: {f.message}"
                          for f in report.errors)
        raise SystemExit(f"bench lint gate failed "
                         f"({len(report.errors)} finding(s)):\n{lines}")
    note(f"lint gate: {report.files} modules clean")


def run_dry(cell: str | None) -> int:
    _lint_gate()
    names = [cell] if cell else sorted(set(DRY_CHECKS))
    out = {}
    for name in names:
        fn = DRY_CHECKS[name]
        t0 = time.time()
        info = fn()
        note(f"dry {name}: OK ({fn.__name__}, {time.time()-t0:.1f}s)")
        out[name] = {"ok": True, "check": fn.__name__, **info}
    print(json.dumps({"dry": out}))
    return 0


def _bench_telemetry():
    """The bench run's telemetry recorder: the SAME span schema as a
    test run's store/<run>/telemetry.jsonl (runner/telemetry.py pins
    the field sets), so BENCH rounds and live runs are comparable with
    one reader. One ``cell:<name>`` span per cell, scalar results
    attached as attrs; deep-path spans/counters (wgl.*, mxu.*,
    closure.*) land in the same stream because the recorder installs
    as the process-current one. File path from
    JEPSEN_ETCD_TPU_BENCH_TELEMETRY (unset: aggregate in memory only,
    summary still printed)."""
    import os
    from jepsen_etcd_tpu.runner import telemetry
    from jepsen_etcd_tpu.runner.telemetry import Telemetry
    tel = Telemetry(os.environ.get("JEPSEN_ETCD_TPU_BENCH_TELEMETRY"))
    telemetry.set_current(tel)
    return tel


def _run_cell(tel, name: str, fn):
    with tel.span("cell:" + name) as sp:
        out = fn()
        sp.set(**{k: v for k, v in out.items()
                  if isinstance(v, (int, float, str, bool))})
    return out


def main() -> int:
    import argparse
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--cell", choices=[n for n, _ in CELLS]
                    + ["register_10k"],
                    help="run a single matrix cell")
    ap.add_argument("--dry", action="store_true",
                    help="smoke mode: tiny sizes, structural asserts "
                         "(engine routing, packer equivalence), no "
                         "timing asserts")
    ap.add_argument("--service-scaling-arm", type=int,
                    help=argparse.SUPPRESS)  # subprocess child entry
    args = ap.parse_args()
    from jepsen_etcd_tpu.ops.common import enable_compile_cache
    enable_compile_cache()
    if args.service_scaling_arm:
        print(json.dumps(_service_scaling_arm(args.service_scaling_arm)))
        return 0
    if args.dry:
        return run_dry(args.cell)
    _lint_gate()
    tel = _bench_telemetry()
    if args.cell and args.cell != "register_10k":
        fn = dict(CELLS)[args.cell]
        out = _run_cell(tel, args.cell, fn)
        tel.close()
        print(json.dumps({args.cell: out,
                          "telemetry": tel.summary()}))
        return 0
    matrix = {}
    if not args.cell:
        for name, fn in CELLS:
            try:
                matrix[name] = _run_cell(tel, name, fn)
            except Exception as e:  # record, don't abort the headline
                note(f"{name} FAILED: {e!r}")
                matrix[name] = {"error": repr(e)}

    with tel.span("cell:register_10k") as sp:
        check_s, out, p, gen_s, prep_ms, device_ms, pack_s = \
            bench_register_10k()
        sp.set(check_s=check_s, gen_s=gen_s, pack_s=pack_s,
               engine=out.get("engine"))
    tel.close()
    print(json.dumps({
        "metric": "register_linearizability_10k_ops_check_wallclock",
        "value": round(check_s, 4),
        "unit": "s",
        "gen_s": round(gen_s, 2),
        "host_pack_s": round(pack_s, 4),
        "host_prep_ms": round(prep_ms, 1),
        "device_ms": round(device_ms, 1),
        "engine": out.get("engine"),
        "vs_baseline": round(BASELINE_SECONDS / max(check_s, 1e-9), 1),
        "matrix": matrix,
        "telemetry": tel.summary(),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
