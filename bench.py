#!/usr/bin/env python
"""The headline benchmark (BASELINE.md north star).

Generates a 10k-op single-key register history with the hermetic
simulator (seeded, concurrency 8), then times the TPU linearizability
kernel verifying it. Baseline: the reference's CPU Knossos checker cannot
verify a 10k-op single-key history within 60 s (it times out; BASELINE.md
"North star"), so vs_baseline = 60s / our wall-clock.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

import json
import sys
import time

sys.path.insert(0, ".")

N_OPS = 13_500  # ~10k :ok ops after failed-CAS exclusion
CONCURRENCY = 8
BASELINE_SECONDS = 60.0  # CPU Knossos budget it cannot meet


def generate_history(n_ops: int = N_OPS, seed: int = 2026):
    """10k ops on ONE key via the simulated cluster (fast: virtual time)."""
    from jepsen_etcd_tpu.compose import etcd_test
    from jepsen_etcd_tpu.runner.test_runner import run_test
    from jepsen_etcd_tpu.generators import limit, mix, reserve, independent
    from jepsen_etcd_tpu.workloads.register import (RegisterClient, r, w,
                                                    cas)
    from jepsen_etcd_tpu.checkers.core import Noop

    test = etcd_test({
        "workload": "none",
        "time_limit": 3600, "rate": 0, "seed": seed,
        "concurrency": CONCURRENCY, "store_base": "store",
    })
    test["name"] = "bench-register-10k"
    test["client"] = RegisterClient()
    test["checker"] = Noop()
    test["generator"] = independent.concurrent_generator(
        CONCURRENCY, [0],
        lambda k: limit(n_ops, reserve(CONCURRENCY // 2, r, mix([w, cas]))))
    out = run_test(test)
    from jepsen_etcd_tpu.generators.independent import subhistory
    from jepsen_etcd_tpu.core.history import History
    return History(subhistory(out["history"], 0))


def main() -> int:
    t0 = time.time()
    h = generate_history()
    gen_s = time.time() - t0
    n_ok = len([o for o in h if o.is_ok])
    print(f"# generated {len(h)} ops ({n_ok} ok) in {gen_s:.1f}s",
          file=sys.stderr)

    from jepsen_etcd_tpu.ops import wgl
    p = wgl.pack_register_history(h)
    if not p.ok:
        print(f"# pack failed: {p.reason}", file=sys.stderr)
        return 1
    print(f"# packed R={p.R}", file=sys.stderr)

    # warmup: first call compiles and runs the full search; the timed
    # second call measures steady-state search wall-clock
    wgl.check_packed(p)
    t1 = time.time()
    out = wgl.check_packed(p)
    check_s = time.time() - t1
    print(f"# kernel verdict={out['valid?']} waves={out.get('waves')} "
          f"peak-frontier={out.get('peak-frontier')} in {check_s:.3f}s "
          f"(first call incl. compile: {t1 - t0 - gen_s:.1f}s)",
          file=sys.stderr)
    if out["valid?"] is not True:
        print(f"# UNEXPECTED verdict: {out}", file=sys.stderr)
        return 1

    print(json.dumps({
        "metric": "register_linearizability_10k_ops_check_wallclock",
        "value": round(check_s, 4),
        "unit": "s",
        "vs_baseline": round(BASELINE_SECONDS / max(check_s, 1e-9), 1),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
