"""The jitted device-side generator (simbatch/engine_jax.py, epoch-v3):
the 16-seed golden-hash pin freezing the v3 ledger entry, determinism
and batch-composition independence, the drawn-vs-explicit schedule
replay contract, MVCC delegation to the epoch-v2 per-seed sweep, the
stale-read injection surviving the port, and the cross-epoch
verdict-equality fuzz against BOTH epoch-v1 (live interpreter) and
epoch-v2 (numpy lockstep engine).

The golden hashes pin the epoch-v3 draw contract (threefry
``PRNGKey(seed mod 2**32)`` split 12 ways, the int/float scaling rules
in engine_jax's module docstring) AND the shared ``BatchConfig.
from_opts`` sizing: an intentional change to either must bump the
generator epoch (the ledger in runner/sim.py) and re-pin here in the
same commit — never re-pin under epoch-v3.
"""

import hashlib

import pytest

from jepsen_etcd_tpu.simbatch import (GEN_EPOCH_V2, GEN_EPOCH_V3,
                                      BatchConfig, default_schedule_jax,
                                      generate, generate_for_opts,
                                      generate_jax, history_sha)

# ---- the 16-seed golden pin ------------------------------------------------

#: same shape as test_simbatch.GOLDEN_OPTS / bench _dry_gen_jitted,
#: routed through the v3 engine
GOLDEN_OPTS = {"workload": "register", "nodes": ["n1", "n2", "n3"],
               "concurrency": 8, "rate": 200.0, "time_limit": 2.0,
               "gen_epoch": "epoch-v3"}

GOLDEN_SEED0 = \
    "c82fabd17a19636bd2aa710d219ff7da169d8919b4528566d55fd41e63853fb8"
GOLDEN_JOINED = \
    "d93dbf74fe3c0a4180282278c2c223293b7779e33edbdd4bb5a2798c43f9693c"


def test_golden_hash_16_seed_pin_v3():
    """Epoch-v3 is pinned: these 16 histories must serialize to these
    exact bytes on every platform (threefry is platform-stable by
    design; the host-side scaling arithmetic is pure int64). A failure
    here means either an engine bug or a contract change that REQUIRES
    a new generator epoch."""
    g = generate_for_opts(dict(GOLDEN_OPTS), range(16))
    assert g["epoch"] == GEN_EPOCH_V3
    shas = [history_sha(h) for h in g["histories"]]
    assert shas[0] == GOLDEN_SEED0
    joined = hashlib.sha256("".join(shas).encode()).hexdigest()
    assert joined == GOLDEN_JOINED
    assert len(set(shas)) == 16, "distinct seeds collapsed"


# ---- determinism + composition independence --------------------------------


def test_jitted_deterministic_and_composition_independent():
    cfg = BatchConfig(workload="register", lanes=4, ops_per_lane=30,
                      rate=500.0)
    g1 = generate_jax(cfg, [3, 5, 7])
    # born-columnar must be asserted BEFORE hashing: history_sha's
    # to_jsonl is the declared dict-materializing exception
    for h in g1["histories"]:
        assert h._ops is None, "jitted generation materialized dicts"
        assert len(h.columns) == len(h) > 0
    g2 = generate_jax(cfg, [3, 5, 7])
    s1 = [history_sha(h) for h in g1["histories"]]
    assert s1 == [history_sha(h) for h in g2["histories"]]
    # a seed's history is a pure function of (seed, config): the
    # per-seed key split means batch membership must not matter
    solo = generate_jax(cfg, [5])
    assert history_sha(solo["histories"][0]) == s1[1]
    assert g1["epoch"] == GEN_EPOCH_V3


# ---- drawn-vs-explicit schedule replay -------------------------------------


@pytest.mark.parametrize("nemesis", [["kill"], ["partition"],
                                     ["kill", "partition"]],
                         ids=lambda n: "+".join(n))
def test_explicit_schedule_replays_drawn_plan_v3(nemesis):
    """The shrink determinism contract holds for the jitted engine:
    materializing a run's drawn fault plan (``default_schedule_jax``)
    as an explicit window list — singly or as a batched same-seed
    population — changes NOTHING about the history."""
    opts = {"workload": "register", "nodes": ["n1", "n2", "n3"],
            "concurrency": 6, "rate": 100.0, "time_limit": 1.0,
            "nemesis": nemesis}
    cfg = BatchConfig.from_opts(opts)
    for seed in (7, 12):
        drawn = generate_jax(cfg, [seed])["histories"][0]
        sched = default_schedule_jax(cfg, seed)
        assert len(sched) >= 1
        explicit = generate_jax(cfg, [seed],
                                nem_schedules=[sched])["histories"][0]
        pop = generate_jax(cfg, [seed] * 3,
                           nem_schedules=[sched] * 3)["histories"]
        sha = history_sha(drawn)
        assert history_sha(explicit) == sha
        assert all(history_sha(h) == sha for h in pop)


# ---- MVCC delegation -------------------------------------------------------


def test_mvcc_workloads_delegate_bit_identically_to_v2():
    """The v3 ledger entry declares MVCC workloads delegate to the
    epoch-v2 per-seed sweep: rows bit-identical, only the epoch label
    differs (so MVCC injections keep working untouched)."""
    opts = {"workload": "ranges", "nodes": ["n1", "n2", "n3"],
            "concurrency": 6, "rate": 100.0, "time_limit": 1.0}
    cfg = BatchConfig.from_opts(opts)
    v2 = generate(cfg, [4, 9])
    v3 = generate_jax(cfg, [4, 9])
    assert v2["epoch"] == GEN_EPOCH_V2
    assert v3["epoch"] == GEN_EPOCH_V3
    assert [history_sha(h) for h in v2["histories"]] == \
        [history_sha(h) for h in v3["histories"]]


# ---- stale-read injection survives the port --------------------------------


def test_stale_injection_caught_by_session_checker_v3():
    """The seeded stale-read bug flips the session-guarantee verdict
    through the jitted path too; clean v3 generation stays green."""
    from jepsen_etcd_tpu.workloads.register import workload as reg_wl

    wopts = {"nodes": ["n1", "n2", "n3"], "concurrency": 6}
    chk = reg_wl(wopts)["checker"]
    mk = dict(workload="register", lanes=6, ops_per_lane=60, rate=500.0)
    clean = generate_jax(BatchConfig(**mk), range(3))
    stale = generate_jax(BatchConfig(inject_stale_reads=True, **mk),
                         range(3))
    for h in clean["histories"]:
        assert chk.check(dict(wopts), h)["valid?"] is True
    flipped = [chk.check(dict(wopts), h)["valid?"] is False
               for h in stale["histories"]]
    assert all(flipped), flipped


# ---- verdict-equality fuzz: epoch-v3 vs BOTH v1 and v2 ---------------------

#: histories differ across epochs by design (different draw streams);
#: the contract is verdict equality — register/set x none/kill/
#: partition, each cell checked through all three generators
FUZZ_CELLS = [("register", []), ("register", ["kill"]),
              ("register", ["partition"]),
              ("set", []), ("set", ["kill"]), ("set", ["partition"])]


@pytest.mark.parametrize("workload,nemesis", FUZZ_CELLS,
                         ids=[f"{w}-{'+'.join(n) or 'none'}"
                              for w, n in FUZZ_CELLS])
def test_verdict_equality_v3_vs_v1_and_v2(tmp_path, workload, nemesis):
    from jepsen_etcd_tpu.compose import etcd_test
    from jepsen_etcd_tpu.runner.test_runner import run_test

    seed = 11
    opts = {"workload": workload, "nemesis": list(nemesis),
            "nodes": ["n1", "n2", "n3"], "concurrency": 8,
            "rate": 200.0, "time_limit": 2, "seed": seed,
            "store_base": str(tmp_path), "no_telemetry": True}
    v1 = run_test(etcd_test(dict(opts)))["valid?"]
    verdicts = {"v1": v1}
    for label, epoch in (("v2", "epoch-v2"), ("v3", "epoch-v3")):
        g = generate_for_opts(dict(opts, gen_epoch=epoch), [seed])
        test = etcd_test(dict(opts))
        d = tmp_path / f"{label}-{workload}-{seed}"
        d.mkdir(exist_ok=True)
        verdicts[label] = test["checker"].check(
            test, g["histories"][0], {"store_dir": str(d)})["valid?"]
    assert verdicts["v1"] == verdicts["v2"] == verdicts["v3"] == True, \
        (workload, nemesis, seed, verdicts)  # noqa: E712
