"""Lock workload tests: healthy-cluster runs pass, the mutex model
rejects double-holds, and the error-coercion rules match lock.clj."""

import pytest

from jepsen_etcd_tpu.core.op import Op
from jepsen_etcd_tpu.core.history import History
from jepsen_etcd_tpu.checkers.linearizable import check_history
from jepsen_etcd_tpu.models import Mutex
from jepsen_etcd_tpu.workloads.lock import _is_not_held


def H(*ops):
    return History([Op(o) for o in ops])


def test_mutex_model_rejects_double_acquire():
    h = H({"type": "invoke", "process": 0, "f": "acquire", "value": None},
          {"type": "ok", "process": 0, "f": "acquire", "value": None},
          {"type": "invoke", "process": 1, "f": "acquire", "value": None},
          {"type": "ok", "process": 1, "f": "acquire", "value": None})
    assert check_history(Mutex(), h)["valid?"] is False


def test_mutex_model_accepts_handoff():
    h = H({"type": "invoke", "process": 0, "f": "acquire", "value": None},
          {"type": "ok", "process": 0, "f": "acquire", "value": None},
          {"type": "invoke", "process": 0, "f": "release", "value": None},
          {"type": "ok", "process": 0, "f": "release", "value": None},
          {"type": "invoke", "process": 1, "f": "acquire", "value": None},
          {"type": "ok", "process": 1, "f": "acquire", "value": None})
    assert check_history(Mutex(), h)["valid?"] is True


def test_is_not_held_shapes():
    assert _is_not_held("not-held")
    assert _is_not_held(["not-held", "not-held: k"])
    assert not _is_not_held(["timeout", "x"])
    assert not _is_not_held(None)


def run(tmp_path, **opts):
    from jepsen_etcd_tpu.compose import etcd_test
    from jepsen_etcd_tpu.runner.test_runner import run_test
    base = {"time_limit": 10, "rate": 5, "store_base": str(tmp_path),
            "seed": 17}
    base.update(opts)
    return run_test(etcd_test(base))


def test_lock_workload_healthy_passes(tmp_path):
    # without faults, etcd locks do exclude; acquire/release linearizes
    out = run(tmp_path, workload="lock")
    wl = out["results"]["workload"]
    assert wl["linear"]["valid?"] is True, wl["linear"]
    stats = out["results"]["stats"]["by-f"]
    assert stats.get("acquire", {}).get("ok", 0) > 0
    assert stats.get("release", {}).get("ok", 0) > 0


def test_lock_set_workload_healthy_passes(tmp_path):
    out = run(tmp_path, workload="lock-set", time_limit=12)
    wl = out["results"]["workload"]
    assert wl["set"]["valid?"] is True, wl["set"]


def test_lock_etcd_set_workload_healthy_passes(tmp_path):
    out = run(tmp_path, workload="lock-etcd-set", time_limit=12)
    wl = out["results"]["workload"]
    assert wl["set"]["valid?"] is True, wl["set"]
