"""Checker service (runner/checker_service.py): wire format, verdict
identity, coalescing accounting, and degradation.

The service's soundness contract is that shipping a packed history over
the socket changes NOTHING about its verdict: the service runs the same
``wgl.check_packed_batch`` the runner would, so the verdict projection
(validity, failure site, wave/frontier accounting) must be bit-identical
to in-process checking — including invalid and info-heavy histories.
Degradation must be silent and sound: a dead socket means the caller
checks locally, never an error, never a changed verdict.
"""

import dataclasses
import json
import random
import threading

import numpy as np
import pytest

from jepsen_etcd_tpu.core.history import History
from jepsen_etcd_tpu.checkers.tpu_linearizable import TPULinearizableChecker
from jepsen_etcd_tpu.ops import wgl
from jepsen_etcd_tpu.runner import checker_service as svc_mod
from jepsen_etcd_tpu.runner import telemetry
from jepsen_etcd_tpu.runner.telemetry import Telemetry

from test_wgl import gen_history

#: the verdict projection the service must reproduce bit-identically;
#: metadata ("rungs", "engine", "batched") legitimately differs with
#: group composition (a pack alone rides the ladder; grouped, the
#: vmapped kernel) — exactly as it already does between check_packed
#: and check_packed_batch in-process
PROJECTION = ("valid?", "waves", "peak-frontier", "ops", "info-ops",
              "op", "error", "stuck-at-depth")


def view(out: dict) -> dict:
    return {k: out.get(k) for k in PROJECTION}


def make_packs(seed, n, info_rate=0.1, corrupt=False):
    rng = random.Random(seed)
    packs = []
    while len(packs) < n:
        h = History(gen_history(rng, n_procs=rng.randint(2, 4),
                                n_ops=rng.randint(8, 40),
                                info_rate=info_rate, corrupt=corrupt))
        p = wgl.pack_register_history(h)
        if p.ok and p.R > 0:
            packs.append(p)
    return packs


@pytest.fixture
def service():
    svc = svc_mod.CheckerService(tick_s=0.01).start()
    yield svc
    svc.close()
    svc_mod.reset_clients()


# -- wire format -------------------------------------------------------------

def test_serialize_roundtrip_bit_identical():
    for p in make_packs(3, 6, info_rate=0.2):
        q = wgl.deserialize_packed(wgl.serialize_packed(p))
        wgl.ensure_frames(p)
        wgl.ensure_frames(q)
        for fld in dataclasses.fields(type(p)):
            x, y = getattr(p, fld.name), getattr(q, fld.name)
            if isinstance(x, np.ndarray) or isinstance(y, np.ndarray):
                assert np.array_equal(x, y), fld.name
                assert x.dtype == y.dtype, fld.name
            else:
                assert x == y, (fld.name, x, y)


def test_deserialize_rejects_unknown_version():
    buf = wgl.serialize_packed(make_packs(4, 1)[0])
    head, _, blobs = buf.partition(b"\n")
    h = json.loads(head)
    h["v"] = 99
    with pytest.raises(ValueError):
        wgl.deserialize_packed(json.dumps(h).encode() + b"\n" + blobs)


# -- verdict identity --------------------------------------------------------

def test_service_verdicts_match_local_fuzz(service):
    """Mixed valid/corrupt/info-heavy packs through the socket: every
    verdict projection identical to in-process check_packed, singleton
    and cross-history-batched requests alike."""
    packs = (make_packs(11, 6, info_rate=0.15)
             + make_packs(12, 4, corrupt=True)
             + make_packs(13, 2, info_rate=0.5))
    local = [wgl.check_packed(p) for p in packs]
    assert any(out["valid?"] is False for out in local), \
        "fuzz lost its invalid histories"
    client = svc_mod.CheckerClient(service.path)
    # one big request: the service batches across histories
    outs = client.check(packs)
    assert outs is not None
    for got, want in zip(outs, local):
        assert view(got) == view(want)
    # singleton requests: the service's lone-pack ladder route
    for p, want in zip(packs[:3], local[:3]):
        got = client.check([p])
        assert got is not None and view(got[0]) == view(want)
    client.close()


def test_service_coalesces_concurrent_clients(service):
    """Requests from concurrent clients land in shared ticks: the
    dispatch ledger shows every pack accounted for and device
    launches bounded by (bucket, width) groups per tick, not by
    request count."""
    packs = make_packs(21, 8, info_rate=0.1)
    local = [wgl.check_packed(p) for p in packs]
    results = [None] * 4
    # warm the dispatcher before timing-sensitive concurrency: the
    # first tick pays jit compiles that would smear arrival windows
    warm = svc_mod.CheckerClient(service.path)
    assert warm.check(packs[:1]) is not None
    warm.close()

    def go(i):
        c = svc_mod.CheckerClient(service.path)
        results[i] = c.check(packs[2 * i: 2 * i + 2])
        c.close()

    threads = [threading.Thread(target=go, args=(i,)) for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for i in range(4):
        assert results[i] is not None
        for got, want in zip(results[i], local[2 * i: 2 * i + 2]):
            assert view(got) == view(want)
    ctr = (service.stats().get("counters") or {})
    assert ctr.get("service.requests") == 5, ctr
    assert ctr.get("service.submitted") == 9, ctr
    # every tick launches at most one dispatch per (bucket, width)
    # group — the amortization bar (rung escalation could add more,
    # but these shallow histories resolve on the first rung)
    assert ctr.get("wgl.dispatches", 0) <= ctr.get("service.group_ticks"), ctr
    assert ctr.get("service.ticks", 0) >= 1, ctr


def test_resume_state_never_crosses_the_wire(service, monkeypatch):
    """Device-array resume state (the spill=False overflow handshake)
    must be stripped before the verdict is serialized — a client must
    receive clean JSON it can re-run the spill from locally."""
    pack = make_packs(31, 1)[0]

    real = wgl.check_packed_batch

    def overflowing(packs, **kw):
        outs = real(packs, **kw)
        for o in outs:
            o["_resume"] = (object(), object(), 3)  # unserializable
        return outs

    monkeypatch.setattr(wgl, "check_packed_batch", overflowing)
    client = svc_mod.CheckerClient(service.path)
    outs = client.check([pack])
    assert outs is not None
    assert "_resume" not in outs[0]
    assert view(outs[0]) == view(wgl.check_packed(pack))
    client.close()


# -- degradation -------------------------------------------------------------

def test_checker_falls_back_when_service_down(tmp_path):
    """A configured-but-dead endpoint degrades to in-process checking:
    same verdict, one service.fallback counter, no error."""
    rng = random.Random(41)
    h = History(gen_history(rng, n_procs=3, n_ops=24, info_rate=0.1))
    checker = TPULinearizableChecker(cpu_cutoff=None)
    want = checker.check({}, h)
    svc_mod.reset_clients()
    tel = Telemetry()
    prev = telemetry.current()
    telemetry.set_current(tel)
    try:
        got = checker.check(
            {"checker_service": str(tmp_path / "nope.sock")}, h)
    finally:
        telemetry.set_current(
            prev if prev is not telemetry.NULL else None)
        svc_mod.reset_clients()
    assert view(got) == view(want)
    ctr = (tel.summary().get("counters") or {})
    assert ctr.get("service.fallback") == 1, ctr


def test_client_cache_latches_broken(tmp_path):
    svc_mod.reset_clients()
    test = {"checker_service": str(tmp_path / "gone.sock")}
    assert svc_mod.client_for(test) is None
    # second lookup hits the latched None, no second connect attempt
    assert svc_mod.client_for(test) is None
    svc_mod.reset_clients()


def test_service_survives_checker_exception(service, monkeypatch):
    """A tick that raises must degrade (error reply -> client returns
    None -> caller checks locally), and the NEXT request must succeed
    — the service never wedges."""
    pack = make_packs(51, 1)[0]

    def boom(packs, **kw):
        raise RuntimeError("injected tick failure")

    real = wgl.check_packed_batch
    monkeypatch.setattr(wgl, "check_packed_batch", boom)
    client = svc_mod.CheckerClient(service.path)
    assert client.check([pack]) is None
    monkeypatch.setattr(wgl, "check_packed_batch", real)
    outs = client.check([pack])
    assert outs is not None
    assert view(outs[0]) == view(wgl.check_packed(pack))
    client.close()
