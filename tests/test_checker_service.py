"""Checker service (runner/checker_service.py): wire format, verdict
identity, coalescing accounting, and degradation.

The service's soundness contract is that shipping a packed history over
the socket changes NOTHING about its verdict: the service runs the same
``wgl.check_packed_batch`` the runner would, so the verdict projection
(validity, failure site, wave/frontier accounting) must be bit-identical
to in-process checking — including invalid and info-heavy histories.
Degradation must be silent and sound: a dead socket means the caller
checks locally, never an error, never a changed verdict.
"""

import dataclasses
import json
import random
import socket
import threading
import time

import numpy as np
import pytest

from jepsen_etcd_tpu.core.history import History
from jepsen_etcd_tpu.checkers.tpu_linearizable import TPULinearizableChecker
from jepsen_etcd_tpu.ops import wgl
from jepsen_etcd_tpu.runner import checker_service as svc_mod
from jepsen_etcd_tpu.runner import transport
from jepsen_etcd_tpu.runner import telemetry
from jepsen_etcd_tpu.runner.telemetry import Telemetry

from test_wgl import gen_history

#: the verdict projection the service must reproduce bit-identically;
#: metadata ("rungs", "engine", "batched") legitimately differs with
#: group composition (a pack alone rides the ladder; grouped, the
#: vmapped kernel) — exactly as it already does between check_packed
#: and check_packed_batch in-process
PROJECTION = ("valid?", "waves", "peak-frontier", "ops", "info-ops",
              "op", "error", "stuck-at-depth")


def view(out: dict) -> dict:
    return {k: out.get(k) for k in PROJECTION}


def make_packs(seed, n, info_rate=0.1, corrupt=False):
    rng = random.Random(seed)
    packs = []
    while len(packs) < n:
        h = History(gen_history(rng, n_procs=rng.randint(2, 4),
                                n_ops=rng.randint(8, 40),
                                info_rate=info_rate, corrupt=corrupt))
        p = wgl.pack_register_history(h)
        if p.ok and p.R > 0:
            packs.append(p)
    return packs


@pytest.fixture
def service():
    svc = svc_mod.CheckerService(tick_s=0.01).start()
    yield svc
    svc.close()
    svc_mod.reset_clients()


# -- wire format -------------------------------------------------------------

def test_serialize_roundtrip_bit_identical():
    for p in make_packs(3, 6, info_rate=0.2):
        q = wgl.deserialize_packed(wgl.serialize_packed(p))
        wgl.ensure_frames(p)
        wgl.ensure_frames(q)
        for fld in dataclasses.fields(type(p)):
            x, y = getattr(p, fld.name), getattr(q, fld.name)
            if isinstance(x, np.ndarray) or isinstance(y, np.ndarray):
                assert np.array_equal(x, y), fld.name
                assert x.dtype == y.dtype, fld.name
            else:
                assert x == y, (fld.name, x, y)


def test_deserialize_rejects_unknown_version():
    buf = wgl.serialize_packed(make_packs(4, 1)[0])
    head, _, blobs = buf.partition(b"\n")
    h = json.loads(head)
    h["v"] = 99
    with pytest.raises(ValueError):
        wgl.deserialize_packed(json.dumps(h).encode() + b"\n" + blobs)


# -- verdict identity --------------------------------------------------------

def test_service_verdicts_match_local_fuzz(service):
    """Mixed valid/corrupt/info-heavy packs through the socket: every
    verdict projection identical to in-process check_packed, singleton
    and cross-history-batched requests alike."""
    packs = (make_packs(11, 6, info_rate=0.15)
             + make_packs(12, 4, corrupt=True)
             + make_packs(13, 2, info_rate=0.5))
    local = [wgl.check_packed(p) for p in packs]
    assert any(out["valid?"] is False for out in local), \
        "fuzz lost its invalid histories"
    client = svc_mod.CheckerClient(service.path)
    # one big request: the service batches across histories
    outs = client.check(packs)
    assert outs is not None
    for got, want in zip(outs, local):
        assert view(got) == view(want)
    # singleton requests: the service's lone-pack ladder route
    for p, want in zip(packs[:3], local[:3]):
        got = client.check([p])
        assert got is not None and view(got[0]) == view(want)
    client.close()


def test_service_coalesces_concurrent_clients(service):
    """Requests from concurrent clients land in shared ticks: the
    dispatch ledger shows every pack accounted for and device
    launches bounded by (bucket, width) groups per tick, not by
    request count."""
    packs = make_packs(21, 8, info_rate=0.1)
    local = [wgl.check_packed(p) for p in packs]
    results = [None] * 4
    # warm the dispatcher before timing-sensitive concurrency: the
    # first tick pays jit compiles that would smear arrival windows
    warm = svc_mod.CheckerClient(service.path)
    assert warm.check(packs[:1]) is not None
    warm.close()

    def go(i):
        c = svc_mod.CheckerClient(service.path)
        results[i] = c.check(packs[2 * i: 2 * i + 2])
        c.close()

    threads = [threading.Thread(target=go, args=(i,)) for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for i in range(4):
        assert results[i] is not None
        for got, want in zip(results[i], local[2 * i: 2 * i + 2]):
            assert view(got) == view(want)
    ctr = (service.stats().get("counters") or {})
    assert ctr.get("service.requests") == 5, ctr
    assert ctr.get("service.submitted") == 9, ctr
    # every tick launches at most one dispatch per (bucket, width)
    # group — the amortization bar (rung escalation could add more,
    # but these shallow histories resolve on the first rung)
    assert ctr.get("wgl.dispatches", 0) <= ctr.get("service.group_ticks"), ctr
    assert ctr.get("service.ticks", 0) >= 1, ctr


def test_resume_state_never_crosses_the_wire(service, monkeypatch):
    """Device-array resume state (the spill=False overflow handshake)
    must be stripped before the verdict is serialized — a client must
    receive clean JSON it can re-run the spill from locally."""
    pack = make_packs(31, 1)[0]

    real = wgl.check_packed_batch

    def overflowing(packs, **kw):
        outs = real(packs, **kw)
        for o in outs:
            o["_resume"] = (object(), object(), 3)  # unserializable
        return outs

    monkeypatch.setattr(wgl, "check_packed_batch", overflowing)
    client = svc_mod.CheckerClient(service.path)
    outs = client.check([pack])
    assert outs is not None
    assert "_resume" not in outs[0]
    assert view(outs[0]) == view(wgl.check_packed(pack))
    client.close()


# -- degradation -------------------------------------------------------------

def test_checker_falls_back_when_service_down(tmp_path):
    """A configured-but-dead endpoint degrades to in-process checking:
    same verdict, one service.fallback counter, no error."""
    rng = random.Random(41)
    h = History(gen_history(rng, n_procs=3, n_ops=24, info_rate=0.1))
    checker = TPULinearizableChecker(cpu_cutoff=None)
    want = checker.check({}, h)
    svc_mod.reset_clients()
    tel = Telemetry()
    prev = telemetry.current()
    telemetry.set_current(tel)
    try:
        got = checker.check(
            {"checker_service": str(tmp_path / "nope.sock")}, h)
    finally:
        telemetry.set_current(
            prev if prev is not telemetry.NULL else None)
        svc_mod.reset_clients()
    assert view(got) == view(want)
    ctr = (tel.summary().get("counters") or {})
    assert ctr.get("service.fallback") == 1, ctr


def test_client_for_negative_cache_expires_and_repromotes(tmp_path,
                                                          monkeypatch):
    """The old permanent latch, fixed: a dead endpoint is a cooldown
    entry (no connect storm while it lasts), and once it expires the
    endpoint is re-probed — a service that comes up mid-campaign is
    adopted without any reset."""
    monkeypatch.setattr(svc_mod, "RETRY_BASE_S", 0.05)
    monkeypatch.setattr(svc_mod, "RETRY_CAP_S", 0.1)
    svc_mod.reset_clients()
    path = str(tmp_path / "late.sock")
    test = {"checker_service": path}
    try:
        assert svc_mod.client_for(test) is None
        cached = svc_mod._clients[path]
        assert cached.fails == 1 and cached.broken
        # during the cooldown: negative-cached, no second dial
        assert svc_mod.client_for(test) is None
        assert cached.fails == 1
        # the service comes up late, the cooldown expires: re-promoted
        svc = svc_mod.CheckerService(path=path, tick_s=0.01).start()
        try:
            deadline = time.monotonic() + 5.0
            client = None
            while client is None and time.monotonic() < deadline:
                time.sleep(0.02)
                client = svc_mod.client_for(test)
            assert client is cached, "healed endpoint not re-promoted"
            assert not client.broken and client.fails == 0
            pack = make_packs(61, 1)[0]
            outs = client.check([pack])
            assert outs is not None
            assert view(outs[0]) == view(wgl.check_packed(pack))
        finally:
            svc.close()
    finally:
        svc_mod.reset_clients()


# -- TCP transport, auth, admission, reconnect -------------------------------

def test_tcp_transport_auth_and_host_attribution():
    """The TCP listener speaks the same framed protocol as the unix
    socket, rejects a wrong shared secret at hello, and attributes
    submitted packs to the connecting host's ledger entry."""
    svc = svc_mod.CheckerService(tick_s=0.01, tcp=True,
                                 auth_token="sekrit").start()
    try:
        assert svc.tcp_endpoint and svc.tcp_endpoint.startswith("tcp://")
        bad = svc_mod.CheckerClient(svc.tcp_endpoint, token="wrong",
                                    connect_timeout=2.0)
        assert bad.ping() is False
        bad.close()
        good = svc_mod.CheckerClient(svc.tcp_endpoint, token="sekrit",
                                     host="hostB")
        packs = make_packs(91, 3)
        outs = good.check(packs)
        assert outs is not None
        for got, p in zip(outs, packs):
            assert view(got) == view(wgl.check_packed(p))
        good.close()
        ctr = (svc.stats().get("counters") or {})
        assert ctr.get("service.auth_rejects", 0) >= 1, ctr
        assert ctr.get("service.host_submitted.hostB") == 3, ctr
    finally:
        svc.close()
        svc_mod.reset_clients()


def test_admission_control_busy_is_bounded(monkeypatch):
    """A saturated service answers BUSY immediately (never a blind
    in-queue wait), the client's retry budget is bounded, and a BUSY
    verdict does NOT arm the reconnect cooldown — the transport is
    healthy, the very next smaller request may be admitted."""
    svc = svc_mod.CheckerService(tick_s=0.01, max_pending_packs=2).start()
    release = threading.Event()
    real = wgl.check_packed_batch

    def stalled(packs, **kw):
        assert release.wait(timeout=30.0), "test deadlocked"
        return real(packs, **kw)

    monkeypatch.setattr(wgl, "check_packed_batch", stalled)
    hold_result = [None]

    def hold():
        c = svc_mod.CheckerClient(svc.path)
        hold_result[0] = c.check(make_packs(81, 2))
        c.close()

    t = threading.Thread(target=hold)
    try:
        t.start()
        # wait until both packs occupy the admission ledger
        deadline = time.monotonic() + 10.0
        while svc._pending_packs < 2 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert svc._pending_packs == 2
        probe = svc_mod.CheckerClient(svc.path, max_busy_retries=1)
        assert probe.check(make_packs(82, 1)) is None  # saturated
        # BUSY is not a transport failure: no cooldown, no fail count
        assert probe.available() and probe.fails == 0
        ctr = (svc.stats().get("counters") or {})
        assert ctr.get("service.admission_rejects", 0) >= 2, ctr
        release.set()
        t.join(timeout=30.0)
        assert hold_result[0] is not None  # held request completed
        # drained: the same client is admitted now
        outs = probe.check(make_packs(83, 1))
        assert outs is not None
        probe.close()
    finally:
        release.set()
        svc.close()
        svc_mod.reset_clients()


def test_reconnect_after_service_restart(monkeypatch, tmp_path):
    """A client that watched its service die degrades (None -> caller
    falls back), arms a capped-backoff cooldown instead of latching,
    and re-promotes automatically once the service is back — counting
    service.reconnects."""
    monkeypatch.setattr(svc_mod, "RETRY_BASE_S", 0.05)
    monkeypatch.setattr(svc_mod, "RETRY_CAP_S", 0.1)
    path = str(tmp_path / "svc.sock")
    pack = make_packs(101, 1)[0]
    want = view(wgl.check_packed(pack))
    svc = svc_mod.CheckerService(path=path, tick_s=0.01).start()
    client = svc_mod.CheckerClient(path)
    tel = Telemetry()
    prev = telemetry.current()
    telemetry.set_current(tel)
    try:
        outs = client.check([pack])
        assert outs is not None and view(outs[0]) == want
        svc.close()
        assert client.check([pack]) is None  # dead: degrade, arm cooldown
        assert client.broken and client.fails >= 1
        svc2 = svc_mod.CheckerService(path=path, tick_s=0.01).start()
        try:
            deadline = time.monotonic() + 5.0
            outs = None
            while outs is None and time.monotonic() < deadline:
                time.sleep(0.02)
                outs = client.check([pack])
            assert outs is not None and view(outs[0]) == want
            assert client.fails == 0 and not client.broken
        finally:
            svc2.close()
    finally:
        telemetry.set_current(prev if prev is not telemetry.NULL else None)
        client.close()
        svc_mod.reset_clients()
    ctr = (tel.summary().get("counters") or {})
    assert ctr.get("service.reconnects", 0) >= 1, ctr


def test_version_mismatch_mid_stream_keeps_connection(service):
    """A frame whose pack blob claims an unknown wire version is
    answered with a structured error — and the SAME connection then
    serves a good check: per-request degradation, not a poisoned
    stream."""
    pack = make_packs(111, 1)[0]
    good = wgl.serialize_packed(pack)
    head, _, blobs = good.partition(b"\n")
    h = json.loads(head)
    h["v"] = 99
    bad = json.dumps(h).encode() + b"\n" + blobs
    s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    s.connect(service.path)
    s.settimeout(10.0)
    r = transport.FrameReader(s)

    def rpc(head_obj, body=b""):
        transport.send_frame(
            s, json.dumps(head_obj).encode() + b"\n" + body)
        while True:
            fr = r.recv_frame()
            assert fr is not None, "service closed the connection"
            resp = json.loads(fr.decode())
            if "heartbeat" in resp:
                continue
            return resp

    try:
        resp = rpc({"op": "check", "sizes": [len(bad)], "id": 1}, bad)
        assert resp["id"] == 1 and resp.get("error"), resp
        resp = rpc({"op": "check", "sizes": [len(good)], "id": 2}, good)
        assert resp["id"] == 2 and resp.get("results"), resp
        assert view(resp["results"][0]) == view(wgl.check_packed(pack))
    finally:
        s.close()
    ctr = (service.stats().get("counters") or {})
    assert ctr.get("service.bad_requests") == 1, ctr


def test_shutdown_counts_leaked_threads():
    """A thread that outlives the join grace is a ledger entry
    (service.shutdown_leaked_threads, stats field), not a silently
    discarded join result."""
    svc = svc_mod.CheckerService(tick_s=0.01, shutdown_join_s=0.1).start()
    release = threading.Event()
    hung = threading.Thread(target=release.wait, name="wedged-worker",
                            daemon=True)
    hung.start()
    with svc._cv:
        svc._threads.append(hung)
    try:
        svc.close()
        assert svc.shutdown_leaked_threads >= 1
        st = svc.stats()
        assert st["shutdown_leaked_threads"] >= 1
        ctr = (st.get("counters") or {})
        assert ctr.get("service.shutdown_leaked_threads", 0) >= 1, ctr
    finally:
        release.set()
        hung.join(timeout=5.0)
        svc_mod.reset_clients()


def test_service_survives_checker_exception(service, monkeypatch):
    """A tick that raises must degrade (error reply -> client returns
    None -> caller checks locally), and the NEXT request must succeed
    — the service never wedges."""
    pack = make_packs(51, 1)[0]

    def boom(packs, **kw):
        raise RuntimeError("injected tick failure")

    real = wgl.check_packed_batch
    monkeypatch.setattr(wgl, "check_packed_batch", boom)
    client = svc_mod.CheckerClient(service.path)
    assert client.check([pack]) is None
    monkeypatch.setattr(wgl, "check_packed_batch", real)
    outs = client.check([pack])
    assert outs is not None
    assert view(outs[0]) == view(wgl.check_packed(pack))
    client.close()
