"""Frame robustness (runner/transport.py): the reader must tell a
peer that finished (clean EOF -> None) from a link that died mid-frame
(TornFrame), reject absurd length prefixes BEFORE allocating, survive
socket timeouts mid-frame (re-entrancy), and parse — or decline — the
JET-HOST preamble without eating frame bytes.
"""

import socket
import struct

import pytest

from jepsen_etcd_tpu.runner import transport
from jepsen_etcd_tpu.runner.transport import (FrameReader, TornFrame,
                                              send_frame, send_preamble)


def pair():
    a, b = socket.socketpair()
    return a, b


def test_roundtrip_frames():
    a, b = pair()
    try:
        send_frame(a, b"hello")
        send_frame(a, b"")
        send_frame(a, b"x" * 70000)  # > one recv chunk
        r = FrameReader(b)
        assert r.recv_frame() == b"hello"
        assert r.recv_frame() == b""
        assert r.recv_frame() == b"x" * 70000
    finally:
        a.close()
        b.close()


def test_clean_eof_is_none():
    a, b = pair()
    try:
        send_frame(a, b"last")
        a.close()
        r = FrameReader(b)
        assert r.recv_frame() == b"last"
        assert r.recv_frame() is None  # EOF exactly on a boundary
    finally:
        b.close()


def test_torn_mid_header():
    """EOF after 3 of the 8 length bytes: the peer died mid-message,
    not finished — TornFrame, never a silent None."""
    a, b = pair()
    try:
        a.sendall(b"\x05\x00\x00")
        a.close()
        with pytest.raises(TornFrame):
            FrameReader(b).recv_frame()
    finally:
        b.close()


def test_truncated_payload():
    a, b = pair()
    try:
        a.sendall(struct.pack("<Q", 100) + b"only-ten-b")
        a.close()
        with pytest.raises(TornFrame):
            FrameReader(b).recv_frame()
    finally:
        b.close()


def test_absurd_length_rejected_before_allocating():
    """A corrupt/adversarial 8-byte prefix claiming an exabyte frame
    must raise from the 8 header bytes alone — the reader never tries
    to buffer (or allocate) the claimed payload."""
    a, b = pair()
    try:
        a.sendall(struct.pack("<Q", 1 << 60))  # no payload follows
        r = FrameReader(b)
        b.settimeout(5.0)  # if it tried to read the payload, it hangs
        with pytest.raises(ValueError, match="exceeds max_frame"):
            r.recv_frame()
    finally:
        a.close()
        b.close()


def test_custom_max_frame_cap():
    a, b = pair()
    try:
        send_frame(a, b"y" * 2048)
        with pytest.raises(ValueError, match="exceeds max_frame"):
            FrameReader(b, max_frame=1024).recv_frame()
    finally:
        a.close()
        b.close()


def test_reader_reentrant_across_timeouts():
    """A socket timeout mid-frame (header parsed, payload partial)
    must leave the reader resumable: the next recv_frame call picks up
    exactly where it stopped — the client heartbeat loop depends on
    this."""
    a, b = pair()
    try:
        b.settimeout(0.05)
        r = FrameReader(b)
        a.sendall(struct.pack("<Q", 6) + b"abc")  # half the payload
        with pytest.raises(socket.timeout):
            r.recv_frame()
        with pytest.raises(socket.timeout):  # still parked, still sane
            r.recv_frame()
        a.sendall(b"def")
        assert r.recv_frame() == b"abcdef"
        # and the stream keeps working after the stall
        send_frame(a, b"next")
        assert r.recv_frame() == b"next"
    finally:
        a.close()
        b.close()


def test_preamble_roundtrip_then_frames():
    a, b = pair()
    try:
        send_preamble(a, "hostB")
        send_frame(a, b"frame1")
        r = FrameReader(b)
        assert r.read_preamble() == "hostB"
        assert r.recv_frame() == b"frame1"
    finally:
        a.close()
        b.close()


def test_preamble_absent_leaves_frames_untouched():
    """A stream that opens with a frame (unix-socket clients skip the
    preamble) must not lose a single byte to the preamble probe."""
    a, b = pair()
    try:
        send_frame(a, b"no-preamble-here")
        r = FrameReader(b)
        assert r.read_preamble() is None
        assert r.recv_frame() == b"no-preamble-here"
    finally:
        a.close()
        b.close()


def test_preamble_diverging_prefix_returns_early():
    """First bytes sharing a prefix with JET-HOST but diverging must
    return None the moment they diverge, without waiting for more
    bytes (a frame length header would stall it forever otherwise)."""
    a, b = pair()
    try:
        a.sendall(b"JE")        # prefix of the preamble...
        a.sendall(b"X-rest")    # ...then divergence, no newline ever
        b.settimeout(5.0)
        r = FrameReader(b)
        assert r.read_preamble() is None
        assert bytes(r._buf) == b"JEX-rest"  # nothing consumed
    finally:
        a.close()
        b.close()


def test_preamble_unterminated_is_rejected():
    a, b = pair()
    try:
        a.sendall(transport.PREAMBLE + b"x" * 600)  # no \n, too long
        with pytest.raises(ValueError, match="unterminated"):
            FrameReader(b).read_preamble()
    finally:
        a.close()
        b.close()


def test_parse_tcp():
    assert transport.is_tcp("tcp://127.0.0.1:8000")
    assert not transport.is_tcp("/tmp/x.sock")
    assert transport.parse_tcp("tcp://10.0.0.1:99") == ("10.0.0.1", 99)
    for bad in ("tcp://", "tcp://host", "tcp://:80x", "tcp://:"):
        with pytest.raises(ValueError):
            transport.parse_tcp(bad)


def test_listen_tcp_specs():
    ls, ep = transport.listen_tcp(True)
    try:
        assert ep.startswith("tcp://127.0.0.1:")
        host, port = transport.parse_tcp(ep)
        assert port > 0
        c = transport.connect(ep, timeout=5.0)
        c.close()
    finally:
        ls.close()
