"""Coverage-guided fault campaigns + schedule shrinking (runner/
guided.py, runner/shrink.py).

The headline test is the acceptance bar: against the seeded stale-read
bug (which only fires inside open partition windows), the guided
scheduler must find a failing run in no more than HALF the runs a
uniform matrix sweep needs under the same budget and master seed — and
the failure must land as an auto-shrunk, replayable store artifact of
fewer than 10 nemesis ops, surfaced on the aggregate dashboard and
``tel --corpus``.

Everything here is deterministic: sim histories are pure functions of
(seed, config), and the guided search is a pure function of its master
seed, so the exact runs-to-failure numbers are stable across hosts.
"""

import json
import os

from jepsen_etcd_tpu.runner.guided import GuidedScheduler, run_guided
from jepsen_etcd_tpu.runner.shrink import (checker_opts_from,
                                           replay_artifact, shrink_run)
from jepsen_etcd_tpu.simbatch import (BatchConfig, default_schedule,
                                      generate, history_sha)

#: the quarry: epoch-v2 sim runs with the seeded stale-read bug. The
#: bare [] cell is EXCLUDED from the cell list below — with no
#: nemeses the injection is unconditional (the legacy semantics
#: tests/test_simbatch.py pins), which would hand the uniform arm a
#: failure at run 1 and prove nothing.
BASE = {"workload": "register", "nodes": ["n1", "n2", "n3"],
        "concurrency": 6, "rate": 100.0, "time_limit": 1.0,
        "inject_stale_reads": True, "gen_epoch": "epoch-v2"}
CELLS = [["kill"], ["pause"], ["latency"], ["member"], ["partition"]]


def _check(opts: dict, seed: int, nem_schedules=None) -> dict:
    """One cheap single-seed evaluation: batched generation + the
    workload checker, no store, no test runner."""
    from jepsen_etcd_tpu.workloads import workloads
    cfg = BatchConfig.from_opts(opts)
    copts = checker_opts_from(opts)
    checker = workloads()[cfg.workload](dict(copts))["checker"]
    g = generate(cfg, [seed], nem_schedules=nem_schedules)
    return checker.check(dict(copts), g["histories"][0])


def test_explicit_schedule_replays_drawn_plan_bit_identically():
    """The shrink determinism contract: materializing a run's drawn
    fault plan as an explicit window list — singly or as a batched
    same-seed population — changes NOTHING about the history."""
    opts = dict(BASE, nemesis=["partition"], seed=12)
    cfg = BatchConfig.from_opts(opts)
    for seed in (7, 12, 31):
        drawn = generate(cfg, [seed])["histories"][0]
        sched = default_schedule(cfg, seed)
        assert len(sched) >= 1
        explicit = generate(cfg, [seed],
                            nem_schedules=[sched])["histories"][0]
        pop = generate(cfg, [seed] * 4,
                       nem_schedules=[sched] * 4)["histories"]
        sha = history_sha(drawn)
        assert history_sha(explicit) == sha
        assert all(history_sha(h) == sha for h in pop)


def test_scheduler_is_deterministic_in_master_seed():
    """Two schedulers with the same master seed emit byte-identical
    candidate streams, including window/knob mutations of a shared
    corpus ancestor."""
    ancestor = dict(BASE, nemesis=["partition"], seed=99)
    streams = []
    for _ in range(2):
        s = GuidedScheduler(BASE, ["register"], CELLS, seed0=7,
                            master_seed=7)
        s.corpus.append({"opts": ancestor, "seed": 99, "run": 1,
                         "score": 4, "signature": "workload=False",
                         "vector": {"frontier": 1, "rungs": 0,
                                    "spills": 0}})
        streams.append([s.next_generation(4) for _ in range(4)])
    assert json.dumps(streams[0], sort_keys=True) == \
        json.dumps(streams[1], sort_keys=True)
    # the stratified gen 0 covers every cell exactly once first
    gen0 = streams[0][0] + streams[0][1]
    cells0 = [tuple(o["nemesis"]) for o in gen0[:len(CELLS)]]
    assert cells0 == [tuple(c) for c in CELLS]


def test_scoring_ignores_harness_noise():
    """Rows without a real checker verdict never enter the corpus or
    steer the envelope — guided must not chase infrastructure errors."""
    s = GuidedScheduler(BASE, ["register"], CELLS, seed0=0)
    err_row = {"status": "error", "workload": "register",
               "nemesis": ["kill"], "seed": 1}
    assert s.observe(dict(BASE), err_row, None) == 0
    assert s.observe(dict(BASE), err_row,
                     {"frontier": 99, "signature": "x=False"}) == 0
    assert not s.corpus and not s.seen_signatures
    ok_row = {"status": "done", "valid": True, "workload": "register",
              "nemesis": ["kill"], "seed": 2}
    score = s.observe(dict(BASE), ok_row,
                      {"frontier": 3, "rungs": 0, "spills": 0,
                       "signature": ""})
    assert score > 0 and len(s.corpus) == 1


def test_corpus_export_import_roundtrip():
    """A corpus exported from one search warm-starts another: the
    ancestor joins the pool, the envelope widens to the imported
    peaks, and already-seen signatures/cells stop scoring as novel."""
    s = GuidedScheduler(BASE, ["register"], CELLS, seed0=7,
                        master_seed=7)
    row = {"status": "done", "valid": False, "workload": "register",
           "nemesis": ["kill"], "seed": 2}
    vec = {"frontier": 3, "waves": 2, "rungs": 1, "spills": 0,
           "signature": "workload=False"}
    assert s.observe(dict(BASE, nemesis=["kill"]), row, vec) > 0
    data = json.loads(json.dumps(s.export_corpus()))
    assert data["kind"] == "guided-corpus"

    s2 = GuidedScheduler(BASE, ["register"], CELLS, seed0=7,
                         master_seed=11)
    assert s2.import_corpus(data) == 1
    assert s2.envelope["frontier"] == 3 and s2.envelope["waves"] == 2
    assert len(s2.corpus) == 1 and s2.corpus[0]["imported"]
    # seeds minted after import never collide with exported ones
    assert s2.next_seed >= s.next_seed
    # nothing in the imported payload is novel to the warmed search
    row2 = dict(row, seed=3)
    assert s2.observe(dict(BASE, nemesis=["kill"]), row2, dict(vec)) == 0
    # garbage payloads are rejected, not absorbed
    import pytest
    with pytest.raises(ValueError):
        s2.import_corpus({"kind": "something-else"})


def _corpus_entry(run, score, **extra):
    return dict({"opts": dict(BASE, nemesis=["kill"], seed=run),
                 "seed": run, "run": run, "score": score,
                 "signature": "", "vector": {}}, **extra)


def test_imported_ancestors_age_out_of_mutation_draws():
    """Generation-stamped decay (ISSUE 19 satellite): an imported
    ancestor's effective score halves every IMPORT_HALF_LIFE_GENS
    generations, so a stale import stops feeding _pick; natives never
    decay, and an all-stale corpus still draws uniformly."""
    import pytest as _pytest
    from jepsen_etcd_tpu.runner.guided import IMPORT_HALF_LIFE_GENS

    s = GuidedScheduler(BASE, ["register"], CELLS, seed0=0,
                        master_seed=5)
    imp = _corpus_entry(1, 1.5, imported=True, born=0)
    nat = _corpus_entry(2, 1.0)
    s.corpus[:] = [imp, nat]
    assert s._eff_score(imp) == 1.5, "no decay before a half-life"
    for _ in range(IMPORT_HALF_LIFE_GENS):
        s.next_generation(1)
    assert s._eff_score(imp) == _pytest.approx(0.75)
    assert s._eff_score(nat) == 1.0, "natives must never decay"
    # effective score < 1 drops the import from the draw pool
    assert {id(s._pick()) for _ in range(32)} == {id(nat)}
    s.corpus[:] = [imp]
    assert s._pick() is imp, "all-stale corpus must not starve"


def test_stale_imports_retire_after_one_full_generation():
    """The aging residual (ISSUE 20 satellite): an imported ancestor
    whose effective score sits below 1 gets ONE grace generation (its
    decay step may land mid-wave) and is then evicted from the corpus
    entirely, counted as ``corpus_retired``; natives never retire."""
    from jepsen_etcd_tpu.runner.guided import IMPORT_HALF_LIFE_GENS

    s = GuidedScheduler(BASE, ["register"], CELLS, seed0=0,
                        master_seed=5)
    imp = _corpus_entry(1, 1.5, imported=True, born=0)
    nat = _corpus_entry(2, 0.5)  # low score, but native: immune
    s.corpus[:] = [imp, nat]
    for _ in range(IMPORT_HALF_LIFE_GENS):
        s.next_generation(1)
    # the decay step landed THIS wave (eff 0.75): marked, still drawn
    assert imp in s.corpus and imp["stale_since"] == s.wave
    assert s.corpus_retired == 0
    s.next_generation(1)
    assert imp not in s.corpus, "stale import must retire"
    assert s.corpus_retired == 1
    assert nat in s.corpus and "stale_since" not in nat


def test_recovered_imports_clear_their_stale_marker():
    """An import marked stale whose effective score recovers (e.g. a
    mutant descendant re-earns it score) sheds the marker instead of
    retiring on the next wave."""
    from jepsen_etcd_tpu.runner.guided import IMPORT_HALF_LIFE_GENS

    s = GuidedScheduler(BASE, ["register"], CELLS, seed0=0,
                        master_seed=5)
    imp = _corpus_entry(1, 1.5, imported=True, born=0)
    s.corpus[:] = [imp]
    for _ in range(IMPORT_HALF_LIFE_GENS):
        s.next_generation(1)
    assert imp["stale_since"] == s.wave
    imp["score"] = 8.0  # recovers: eff back over 1
    s.next_generation(1)
    assert imp in s.corpus and "stale_since" not in imp
    assert s.corpus_retired == 0


def test_eviction_prefers_live_natives_over_stale_imports():
    """The cap sorts by effective (decayed) score: a once-dominant
    import with the highest RAW score is evicted once fresher native
    entries out-score its decayed weight."""
    from jepsen_etcd_tpu.runner.guided import IMPORT_HALF_LIFE_GENS

    s = GuidedScheduler(BASE, ["register"], CELLS, seed0=0,
                        master_seed=5, corpus_cap=2)
    imp = _corpus_entry(1, 8.0, imported=True, born=0)
    s.corpus[:] = [imp]
    for _ in range(2 * IMPORT_HALF_LIFE_GENS):
        s.next_generation(1)
    assert s._eff_score(imp) == 2.0
    s.corpus.extend([_corpus_entry(2, 4.0), _corpus_entry(3, 3.0)])
    s._evict()
    assert imp not in s.corpus and len(s.corpus) == 2


def test_import_stamps_born_and_roundtrips_wave_buckets():
    """Imports start their decay clock at the CURRENT wave (age 0 on
    arrival, whatever the exporter's history), and the exporter's
    occupied wave-histogram buckets stop scoring as novel."""
    s = GuidedScheduler(BASE, ["register"], CELLS, seed0=7,
                        master_seed=7)
    row = {"status": "done", "valid": False, "workload": "register",
           "nemesis": ["kill"], "seed": 2}
    vec = {"frontier": 3, "waves": 2, "rungs": 1, "spills": 0,
           "signature": "workload=False", "wave_hist": {24: 9, 26: 1}}
    assert s.observe(dict(BASE, nemesis=["kill"]), row, vec) > 0
    assert s.corpus[0]["born"] == s.wave
    data = json.loads(json.dumps(s.export_corpus()))
    assert data["wave_buckets"] == [24, 26]

    s2 = GuidedScheduler(BASE, ["register"], CELLS, seed0=7,
                         master_seed=11)
    for _ in range(3):
        s2.next_generation(1)
    assert s2.import_corpus(data) == 1
    assert s2.corpus[0]["imported"]
    assert s2.corpus[0]["born"] == s2.wave == 3
    assert s2._eff_score(s2.corpus[0]) == s2.corpus[0]["score"]
    assert s2.seen_wave_buckets == {24, 26}
    # the imported buckets are no longer novel to the warmed search
    row2 = dict(row, seed=3)
    assert s2.observe(dict(BASE, nemesis=["kill"]), row2,
                      dict(vec)) == 0


def test_wave_hist_buckets_score_search_depth_shape():
    """Each newly-occupied wgl.rung_waves bucket scores +1 — depth
    SHAPE novelty, independent of the envelope peaks — and an
    already-seen bucket scores nothing (string keys tolerated: the
    vector arrives through JSON)."""
    s = GuidedScheduler(BASE, ["register"], CELLS, seed0=0,
                        master_seed=3)
    ok = {"status": "done", "valid": True, "workload": "register",
          "nemesis": ["kill"], "seed": 2}
    base_vec = {"frontier": 1, "rungs": 0, "spills": 0}
    first = s.observe(dict(BASE), ok, dict(base_vec,
                                           wave_hist={24: 9}))
    assert first > 0 and 24 in s.seen_wave_buckets
    # same cell, same bucket, nothing else novel: zero
    assert s.observe(dict(BASE), dict(ok, seed=3),
                     dict(base_vec, wave_hist={"24": 2})) == 0
    # one fresh bucket alone is worth exactly one point
    assert s.observe(dict(BASE), dict(ok, seed=4),
                     dict(base_vec, wave_hist={26: 1})) == 1
    assert s.seen_wave_buckets == {24, 26}


def test_coverage_surfaces_wave_histogram(tmp_path):
    """tel --coverage lifts each run's wgl.rung_waves buckets into its
    row and sums them into the aggregate (int keys, sorted)."""
    from jepsen_etcd_tpu.tel_cli import coverage

    def fake_run(name, hists):
        rdir = tmp_path / name
        rdir.mkdir(parents=True)
        (rdir / "results.json").write_text(json.dumps(
            {"valid?": True,
             "telemetry": {"counters": {"wgl.max-frontier": 2},
                           "hists": hists}}))

    fake_run("0001", {"wgl.rung_waves": {"buckets": {"24": 5,
                                                     "26": 1}}})
    fake_run("0002", {"wgl.rung_waves": {"buckets": {"24": 2}}})
    fake_run("0003", {})  # no histogram recorded: empty, not an error
    out = coverage(str(tmp_path))
    by_dir = {r["dir"]: r for r in out["runs"]}
    assert by_dir[str(tmp_path / "0001")]["wave_hist"] == {24: 5, 26: 1}
    assert by_dir[str(tmp_path / "0003")]["wave_hist"] == {}
    assert out["aggregate"]["wave_hist"] == {24: 7, 26: 1}


def test_param_mutation_hops_within_pools():
    """The param dimension only hops along its declared pools — one
    parameter per mutation, always to a pool value."""
    from jepsen_etcd_tpu.runner.guided import PARAM_POOLS
    s = GuidedScheduler(BASE, ["register"], CELLS, seed0=0,
                        master_seed=3)
    touched = set()
    for _ in range(64):
        o = dict(BASE, nemesis=["kill"], seed=1)
        before = {k: o.get(k) for k in PARAM_POOLS}
        s._hop_param(o)
        changed = [k for k in PARAM_POOLS if o.get(k) != before[k]]
        assert len(changed) == 1, changed
        assert o[changed[0]] in PARAM_POOLS[changed[0]], changed
        touched.update(changed)
    assert len(touched) >= 2, touched


def test_guided_finds_seeded_bug_in_half_the_uniform_runs(tmp_path):
    """The acceptance bar, end to end: uniform matrix vs guided search
    on the same budget class and master seed, then the novel failure
    auto-shrinks to a < 10-op schedule that replays to the same
    verdict signature and surfaces on /aggregate."""
    from jepsen_etcd_tpu.runner.campaign import campaign_specs
    from jepsen_etcd_tpu.serve import aggregate_html
    from jepsen_etcd_tpu.tel_cli import corpus

    # uniform arm: the test-all matrix in its own order, evaluated
    # cheaply (same histories the full runner would generate)
    specs = campaign_specs(BASE, ["register"], CELLS,
                           runs_per_cell=6, seed0=7)
    assert len(specs) == 30
    uniform_first = None
    for i, s in enumerate(specs):
        res = _check(s["opts"], s["opts"]["seed"])
        if res.get("valid?") is not True:
            uniform_first = i + 1
            break
    assert uniform_first == 25  # partition cell is last in the matrix

    # guided arm: less than half the uniform budget, same seed base
    summary = run_guided(BASE, ["register"], CELLS, budget=12,
                         seed0=7, pool=0, service=False, live=False,
                         store_base=str(tmp_path), name="hunt")
    assert summary["runs"] == 12
    ff = summary["first_failure_run"]
    assert ff is not None and ff <= uniform_first // 2, \
        (ff, uniform_first)
    assert summary["signatures"], "failure produced no signature"
    ctr = (summary["telemetry"].get("counters") or {})
    assert ctr.get("guided.runs") == 12
    assert ctr.get("guided.failures", 0) >= 1
    assert not ctr.get("guided.errors")

    # the novel failure shrank into a replayable store artifact
    assert summary["minimized"], "no minimized repro was produced"
    m = summary["minimized"][0]
    assert m["nemesis_ops"] < 10
    art_path = os.path.join(m["dir"], "shrink.json")
    assert os.path.isfile(art_path)
    assert art_path in m["repro"]
    rep = replay_artifact(art_path)
    assert rep["match"] is True, rep
    assert rep["signature"] == m["signature"]

    # surfacing: aggregate dashboard + tel --corpus
    page = aggregate_html(str(tmp_path))
    assert "Guided campaigns" in page and "hunt/" in page
    assert "Minimized repros" in page
    assert "jepsen_etcd_tpu replay" in page
    out = corpus(str(tmp_path))
    assert out["first_failure_run"] == ff
    assert out["minimized"][0]["nemesis_ops"] == m["nemesis_ops"]


def test_shrink_minimizes_schedule_and_replays(tmp_path):
    """Direct shrinker run on a known-failing (config, seed): the
    four-window drawn plan minimizes to fewer windows, under 10
    nemesis ops, and the artifact re-executes to the same signature."""
    opts = dict(BASE, nemesis=["partition"], seed=12)
    res = _check(opts, 12)
    assert res.get("valid?") is False  # the quarry really fails here
    art = shrink_run(opts, 12, store_dir=str(tmp_path))
    assert art is not None
    assert art["original_windows"] == 4
    assert art["windows"] < art["original_windows"]
    assert art["nemesis_ops"] < 10
    assert art["executions"] <= 40
    rep = replay_artifact(os.path.join(str(tmp_path), "shrink.json"))
    assert rep["match"] is True and rep["signature"] == art["signature"]
    # nothing to shrink without faults; no artifact is written
    assert shrink_run(dict(BASE, nemesis=[]), 12,
                      store_dir=str(tmp_path / "none")) is None


def test_aggregate_separates_infrastructure_errors(tmp_path):
    """Failure dedupe splits real checker verdicts from no-verdict
    harness noise instead of lumping both under one group."""
    from jepsen_etcd_tpu.serve import aggregate_html

    def fake_run(name, results):
        rdir = tmp_path / name / "0001"
        rdir.mkdir(parents=True)
        (rdir / "history.jsonl").write_text("")
        (rdir / "results.json").write_text(json.dumps(results))

    fake_run("verdict", {"valid?": False,
                         "workload": {"valid?": False}})
    fake_run("infra", {"valid?": False})
    page = aggregate_html(str(tmp_path))
    assert "workload=False" in page
    assert "Infrastructure / harness errors" in page
    assert "(no checker verdict)" not in page
    assert "infra/0001" in page.split(
        "Infrastructure / harness errors")[1]


def test_coverage_tolerates_stranded_campaign_rows(tmp_path):
    """tel --coverage on a multi-host campaign dir: error rows with no
    dir and re-queued/inline-stranded rows without local artifacts fold
    into skipped + the per-host column instead of erroring."""
    from jepsen_etcd_tpu.tel_cli import coverage

    cdir = tmp_path / "camp" / "0001"
    done_dir = cdir / "run0"
    done_dir.mkdir(parents=True)
    (done_dir / "results.json").write_text(json.dumps(
        {"valid?": True,
         "telemetry": {"counters": {"wgl.max-frontier": 3}}}))
    rows = [
        {"index": 0, "status": "done", "valid": True,
         "dir": str(done_dir), "host": "hostA"},
        # agent death past the requeue cap: no dir at all
        {"index": 1, "status": "error", "host": "hostB"},
        # re-queued/inline-stranded: dir recorded, artifacts elsewhere
        {"index": 2, "status": "done", "valid": False,
         "dir": str(cdir / "gone"), "host": "hostB"},
    ]
    (cdir / "campaign.json").write_text(json.dumps(
        {"name": "camp", "runs": rows}))
    out = coverage(str(cdir))
    agg = out["aggregate"]
    assert agg["count"] == 1 and agg["peak_frontier"] == 3
    assert agg["rows"] == 3 and agg["skipped"] == 2
    assert agg["hosts"]["hostA"] == {"runs": 1, "invalid": 0,
                                     "errors": 0}
    assert agg["hosts"]["hostB"] == {"runs": 2, "invalid": 1,
                                     "errors": 1}
