"""checkers/perf.py unit coverage: quantile edge cases, latency-point
completion pairing, and nemesis band extraction."""

import pytest

from jepsen_etcd_tpu.checkers.perf import (latency_points, nemesis_bands,
                                           quantiles)
from jepsen_etcd_tpu.core.history import History
from jepsen_etcd_tpu.core.op import Op

SECOND = 1_000_000_000


def H(*ops):
    return History([Op(o) for o in ops])


def ev(typ, p, f, v, t_s):
    return {"type": typ, "process": p, "f": f, "value": v,
            "time": int(t_s * SECOND)}


# ---- quantiles --------------------------------------------------------------

def test_quantiles_empty():
    assert quantiles([]) == {}


def test_quantiles_single_sample():
    # every quantile of one sample is that sample (1.0 must not
    # index past the end)
    assert quantiles([7.0]) == {0.5: 7.0, 0.95: 7.0, 0.99: 7.0,
                                1.0: 7.0}


def test_quantiles_orders_input():
    q = quantiles([30.0, 10.0, 20.0, 40.0], qs=(0.5, 1.0))
    assert q[0.5] == 30.0
    assert q[1.0] == 40.0


# ---- latency_points ---------------------------------------------------------

def test_latency_points_pairs_completions():
    h = H(ev("invoke", 0, "read", None, 1.0),
          ev("ok", 0, "read", 3, 1.5),
          ev("invoke", 1, "write", 9, 2.0),
          ev("fail", 1, "write", 9, 2.25))
    pts = latency_points(h)
    assert set(pts) == {"read", "write"}
    (t, lat, typ), = pts["read"]
    assert t == pytest.approx(1.0)
    assert lat == pytest.approx(500.0)  # ms
    assert typ == "ok"
    (t, lat, typ), = pts["write"]
    assert lat == pytest.approx(250.0)
    assert typ == "fail"


def test_latency_points_skips_unpaired_and_nemesis():
    h = H(ev("invoke", 0, "read", None, 1.0),       # never completes
          ev("invoke", "nemesis", "kill", None, 1.5),
          ev("info", "nemesis", "kill", None, 2.0),
          ev("invoke", 1, "write", 4, 3.0),
          ev("ok", 1, "write", 4, 3.5))
    pts = latency_points(h)
    assert set(pts) == {"write"}        # no open read, no nemesis ops
    assert len(pts["write"]) == 1


# ---- nemesis_bands ----------------------------------------------------------

def test_nemesis_bands_extraction():
    h = H(ev("invoke", 0, "read", None, 0.0),       # clients don't band
          ev("invoke", "nemesis", "kill", None, 1.0),
          ev("info", "nemesis", "kill", None, 3.0),
          ev("invoke", "nemesis", "partition", None, 3.5),
          ev("info", "nemesis", "partition", None, 5.0),
          ev("ok", 0, "read", 1, 6.0))
    bands = nemesis_bands(h)
    assert bands == [
        {"f": "kill", "start": pytest.approx(1.0),
         "end": pytest.approx(3.0)},
        {"f": "partition", "start": pytest.approx(3.5),
         "end": pytest.approx(5.0)},
    ]


def test_nemesis_bands_unclosed_window_is_dropped():
    h = H(ev("invoke", "nemesis", "kill", None, 1.0))
    assert nemesis_bands(h) == []
