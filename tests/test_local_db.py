"""The local control plane (db/local.py) end-to-end against the
fake-etcd stub (db/fake_etcd.py).

Every process-management path — spawn, readiness, SIGKILL, SIGSTOP/
SIGCONT, wipe, member grow/shrink, crash-loop detection, log capture,
teardown — runs against REAL child processes here, with zero etcd
installed: the stub is a Python binary speaking the v3 JSON gateway
wire format with a synchronously-persisted store, so kill/restart
durability is real too. Real-binary coverage lives in
test_live_etcd.py behind @pytest.mark.live.

Every fixture asserts zero leaked processes after teardown (the
reference's thread-leak scan, support.clj:57-72, applied to PIDs).
"""

import json
import os
import signal
import sys

import pytest

from jepsen_etcd_tpu.core.op import Op
from jepsen_etcd_tpu.db.local import LocalDb, FAKE_ETCD, resolve_binary
from jepsen_etcd_tpu.nemesis.packages import nemesis_package
from jepsen_etcd_tpu.runner.sim import set_current_loop
from jepsen_etcd_tpu.runner.wall import WallLoop
from jepsen_etcd_tpu.sut.errors import SimError


NODES = ["n1", "n2", "n3"]


def proc_state(pid: int) -> str:
    """Process state letter from /proc/<pid>/stat (field 3): R/S/T/Z."""
    with open(f"/proc/{pid}/stat") as f:
        # comm may contain spaces; state follows the closing paren
        return f.read().rsplit(")", 1)[1].split()[0]


def await_proc_state(pid: int, want: str, invert: bool = False,
                     timeout: float = 5.0) -> str:
    """Signal delivery is asynchronous: poll /proc until the state
    (dis)appears, returning the final state either way."""
    import time
    deadline = time.time() + timeout
    while time.time() < deadline:
        s = proc_state(pid)
        if (s != want) if invert else (s == want):
            return s
        time.sleep(0.01)
    return proc_state(pid)


@pytest.fixture()
def wall_loop():
    loop = WallLoop()
    set_current_loop(loop)
    yield loop
    set_current_loop(None)
    loop.shutdown()


def build_db(tmp_path, nodes=NODES, **extra):
    opts = {"etcd_binary": "fake",
            "etcd_data_dir": str(tmp_path / "data"),
            "client_type": "http",
            "nodes": list(nodes)}
    opts.update(extra)
    db = LocalDb(opts)
    test = {"nodes": list(nodes), "client_type": "http",
            "db_mode": "local", "db": db}
    return db, test


@pytest.fixture()
def cluster(wall_loop, tmp_path):
    """A running 3-node fake cluster; teardown asserts zero leaks."""
    db, test = build_db(tmp_path)
    wall_loop.run_coro(db.setup(test))
    try:
        yield wall_loop, db, test
    finally:
        db.stop_all()
        assert db.leaked_pids() == []


def client_for(db, test, node):
    c = db._client(test, node)
    return c


# ---- binary resolution -----------------------------------------------------

def test_resolve_binary():
    assert resolve_binary("fake") == [sys.executable, FAKE_ETCD]
    assert resolve_binary([sys.executable, FAKE_ETCD]) == \
        [sys.executable, FAKE_ETCD]
    assert resolve_binary("/usr/bin/etcd --foo") == \
        ["/usr/bin/etcd", "--foo"]


# ---- lifecycle -------------------------------------------------------------

def test_setup_readiness_and_logs(cluster):
    loop, db, test = cluster
    assert db.members == set(NODES)
    # every node answers the client wire with a leader
    for node in NODES:
        c = client_for(db, test, node)
        try:
            st = loop.run_coro(c.status())
            assert st["leader"]
        finally:
            c.close()
        assert os.path.isdir(db.data_dir(node))
    # per-node log capture (db.clj:234-242)
    logs = db.log_files(test)
    assert set(logs) == set(NODES)
    for node in NODES:
        assert any("ready to serve client requests" in ln
                   for ln in logs[node]), node


def test_kill_restart_preserves_acked_writes(cluster):
    loop, db, test = cluster

    async def story():
        c = client_for(db, test, "n1")
        try:
            await c.put("durable", 42)
        finally:
            c.close()
        assert db.kill(test, "n1") == "killed"
        assert db.procs["n1"].poll() is not None
        assert db.start(test, "n1") == "started"
        await db._await_node_ready(test, "n1")
        c = client_for(db, test, "n1")
        try:
            got = await c.get("durable")
        finally:
            c.close()
        return got

    got = loop.run_coro(story())
    assert got is not None and got["value"] == 42


def test_kill_with_wipe_loses_data(cluster):
    loop, db, test = cluster

    async def story():
        c = client_for(db, test, "n2")
        try:
            await c.put("doomed", 1)
        finally:
            c.close()
        db.kill_node(test, "n2", wipe=True)
        db.start(test, "n2")
        await db._await_node_ready(test, "n2")
        c = client_for(db, test, "n2")
        try:
            return await c.get("doomed")
        finally:
            c.close()

    assert loop.run_coro(story()) is None


def test_start_is_idempotent(cluster):
    loop, db, test = cluster
    assert db.start(test, "n1") == "already-running"


def test_pause_resume(cluster):
    loop, db, test = cluster
    pid = db.procs["n3"].pid
    assert db.pause(test, "n3") == "paused"
    assert await_proc_state(pid, "T") == "T"
    assert db.resume(test, "n3") == "resumed"
    assert await_proc_state(pid, "T", invert=True) != "T"
    # the node serves again after resume
    c = client_for(db, test, "n3")
    try:
        assert loop.run_coro(c.status())["leader"]
    finally:
        c.close()
    # signalling a dead node reports, not raises
    db.kill(test, "n3")
    assert db.pause(test, "n3") == "not-running"
    assert db.resume(test, "n3") == "not-running"


def test_grow_and_shrink_via_member_api(cluster):
    loop, db, test = cluster
    new = loop.run_coro(db.grow(test))
    assert new == "n4"
    assert db.members == {"n1", "n2", "n3", "n4"}
    # the new node's roster (from --initial-cluster) carries all four
    c = client_for(db, test, "n4")
    try:
        members = loop.run_coro(c.member_list())
    finally:
        c.close()
    assert {m["name"] for m in members} == {"n1", "n2", "n3", "n4"}
    victim = loop.run_coro(db.shrink(test))
    assert victim not in db.members
    assert len(db.members) == 3
    proc = db.procs.get(victim)
    assert proc is None or proc.poll() is not None


def test_crash_loop_detection(wall_loop, tmp_path):
    """A binary that dies at boot is respawned a bounded number of
    times, then setup fails with a crash-loop error carrying the log
    tail — not a hang, not an infinite respawn."""
    db, test = build_db(tmp_path,
                        etcd_env={"FAKE_ETCD_CRASH": "1"})
    with pytest.raises(SimError) as ei:
        wall_loop.run_coro(db.setup(test))
    assert ei.value.type == "crash-loop"
    assert "injected crash" in str(ei.value)
    db.stop_all()
    assert db.leaked_pids() == []


def test_teardown_kills_paused_nodes(wall_loop, tmp_path):
    """SIGKILL lands on SIGSTOP'd processes: a paused node cannot
    outlive the run."""
    db, test = build_db(tmp_path, nodes=["n1"])
    wall_loop.run_coro(db.setup(test))
    db.pause(test, "n1")
    pid = db.procs["n1"].pid
    assert await_proc_state(pid, "T") == "T"
    wall_loop.run_coro(db.teardown(test))
    assert db.leaked_pids() == []


def test_reference_flag_set(tmp_path):
    """The spawn argv mirrors db.clj:79-100: URLs, snapshot-count,
    fsync and corrupt-check knobs."""
    db, _ = build_db(tmp_path, unsafe_no_fsync=True, corrupt_check=True,
                     snapshot_count=77)
    argv = db._argv("n1", "new", NODES)
    s = " ".join(argv)
    assert "--name n1" in s
    assert "--initial-cluster-state new" in s
    assert "--snapshot-count 77" in s
    assert "--unsafe-no-fsync" in s
    assert "--experimental-initial-corrupt-check=true" in s
    assert "--experimental-corrupt-check-time 1m" in s
    assert f"n1={db.peer_url('n1')}" in s


# ---- nemesis packages against the local control plane ----------------------

def test_nemesis_packages_drive_local_db(cluster):
    """kill / pause / member / admin packages route their ops to the
    local control plane unchanged — the same dispatch the sim path
    uses (etcd.clj:105-112)."""
    loop, db, test = cluster
    nem = nemesis_package({"nemesis": ["kill", "pause", "member",
                                       "admin"],
                           "nodes": NODES, "nemesis_interval": 1})
    n = nem["nemesis"]
    assert {"kill", "start", "pause", "resume", "grow", "shrink",
            "compact", "defrag"} <= n.fs

    async def story():
        out = []
        out.append(await n.invoke(test, Op(type="invoke", f="kill",
                                           value="one")))
        out.append(await n.invoke(test, Op(type="invoke", f="start",
                                           value="all")))
        for node in sorted(db.members):
            await db._await_node_ready(test, node)
        out.append(await n.invoke(test, Op(type="invoke", f="pause",
                                           value="minority")))
        out.append(await n.invoke(test, Op(type="invoke", f="resume",
                                           value="all")))
        out.append(await n.invoke(test, Op(type="invoke", f="grow",
                                           value=None)))
        out.append(await n.invoke(test, Op(type="invoke", f="shrink",
                                           value=None)))
        out.append(await n.invoke(test, Op(type="invoke", f="compact",
                                           value=None)))
        out.append(await n.invoke(test, Op(type="invoke", f="defrag",
                                           value=None)))
        return out

    kill, start, pause, resume, grow, shrink, compact, defrag = \
        loop.run_coro(story())
    assert "killed" in kill.value.values()
    assert set(start.value.values()) <= {"started", "already-running"}
    assert "paused" in pause.value.values()
    assert "resumed" in resume.value.values()
    assert str(grow.value).startswith("n") or \
        "grow-failed" in str(grow.value)
    assert shrink.value is not None
    assert str(compact.value).startswith("compacted to") or \
        compact.value == "compact-failed"
    assert all(v == "defragged" for v in defrag.value.values())
    assert len(db.members) == 3


def test_primaries_maps_leader_to_node(cluster):
    loop, db, test = cluster
    prim = loop.run_coro(db.primaries(test))
    # fake nodes don't replicate: each reports itself leader of its own
    # roster view, leader = min member id, so exactly one node wins
    assert len(prim) == 1 and prim[0] in NODES


# ---- full run through compose + runner -------------------------------------

def test_cli_local_register_run_with_kill_nemesis(tmp_path):
    """The headline e2e: `--db local` + kill nemesis, from the CLI down
    to real child processes and back up through the checker stack.
    Single node so the fake stub's non-replicated store is still a
    linearizable register through kill/restart (acked writes persist
    synchronously)."""
    from jepsen_etcd_tpu.cli import main
    data_dir = tmp_path / "cluster"
    rc = main(["test", "-w", "register", "--client-type", "http",
               "--db", "local", "--etcd-binary", "fake",
               "--etcd-data-dir", str(data_dir),
               "--nodes", "n1", "--nemesis", "kill",
               "--nemesis-interval", "2", "--time-limit", "8",
               "-r", "10", "-c", "2", "--store", str(tmp_path / "store")])
    run_dirs = []
    for root, dirs, files in os.walk(tmp_path / "store"):
        if "results.json" in files:
            run_dirs.append(root)
    assert len(run_dirs) == 1
    results = json.load(open(os.path.join(run_dirs[0], "results.json")))
    history = open(os.path.join(run_dirs[0], "history.jsonl")).read()
    assert history.count('"type": "ok"') > 10
    # the nemesis actually fired and was recorded. The kill package's
    # generator is a seeded 50/50 mix of kill/start ops, and how many
    # land inside the wall-clock window varies run to run — so assert
    # a kill-package op was recorded, not which side of the mix came
    # up (kill/restart mechanics have deterministic coverage in
    # test_kill_restart_preserves_acked_writes and
    # test_nemesis_packages_drive_local_db)
    assert '"process": "nemesis"' in history
    assert '"kill"' in history or '"start"' in history
    test_json = json.load(open(os.path.join(run_dirs[0], "test.json")))
    assert test_json["db_mode"] == "local"
    assert test_json["nodes"] == ["n1"]
    # node logs were collected into the run store
    assert results is not None
    assert rc == 0, f"run invalid: {json.dumps(results)[:2000]}"
    # zero leaked processes: nothing carrying this run's data-dir path
    token = str(data_dir)
    leaked = []
    for pid in os.listdir("/proc"):
        if not pid.isdigit() or int(pid) == os.getpid():
            continue
        try:
            with open(f"/proc/{pid}/cmdline", "rb") as f:
                if token in f.read().decode("utf-8", "replace"):
                    leaked.append(int(pid))
        except OSError:
            continue
    assert leaked == []


def test_compose_refuses_unsupported_local_faults(tmp_path):
    """clock/corruption are refused with specific reasons, not
    attempted and half-broken (compose.py fault matrix); partition and
    latency — refused before PR 11 — now compose through the userspace
    proxy plane, raising net_proxy automatically."""
    from jepsen_etcd_tpu.compose import etcd_test
    base = {"client_type": "http", "db_mode": "local",
            "nodes": ["n1"], "etcd_binary": "fake",
            "etcd_data_dir": str(tmp_path)}
    with pytest.raises(ValueError, match="CAP_SYS_TIME"):
        etcd_test(dict(base, nemesis=["clock"]))
    with pytest.raises(ValueError, match="corruption"):
        etcd_test(dict(base, nemesis=["bitflip-wal"]))
    # a mixed request names ONLY the remaining unsupported faults
    with pytest.raises(ValueError) as ei:
        etcd_test(dict(base, nemesis=["partition", "clock"]))
    assert "clock" in str(ei.value)
    assert "partition" not in str(ei.value).split("Supported")[0]
    # supported combos compose fine
    t = etcd_test(dict(base, nemesis=["kill", "pause", "member",
                                      "admin"]))
    assert t["db_mode"] == "local"
    assert t["net_proxy"] is False
    t["db"].stop_all()
    # network faults compose and auto-raise the proxy plane
    t = etcd_test(dict(base, nemesis=["partition", "latency"]))
    assert t["net_proxy"] is True
    assert t["db"].plane is not None
    t["db"].stop_all()
