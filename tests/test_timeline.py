"""Positioned timeline rendering (VERDICT #7): per-process columns,
ops as absolutely positioned boxes spanning invoke→complete, nemesis
bands, hover detail, escaping — plus the acceptance run: a sim lock
test under kill faults renders overlapping boxes and fault bands."""

import os
import re

from jepsen_etcd_tpu.checkers.timeline import TimelineHtml
from jepsen_etcd_tpu.core.history import History
from jepsen_etcd_tpu.core.op import Op

SECOND = 1_000_000_000


def H(*ops):
    return History([Op(o) for o in ops])


def ev(typ, p, f, v, t_s):
    return {"type": typ, "process": p, "f": f, "value": v,
            "time": int(t_s * SECOND)}


def overlapping_history():
    return H(
        ev("invoke", 0, "write", 1, 0.0),
        ev("invoke", "nemesis", "kill", None, 0.5),
        ev("invoke", 1, "read", None, 1.0),
        ev("invoke", 2, "write", "<x>", 1.5),   # never completes
        ev("ok", 0, "write", 1, 2.0),
        ev("info", "nemesis", "kill", None, 2.5),
        ev("ok", 1, "read", 1, 3.0),
    )


def boxes(doc):
    """[(left_px, top_px, height_px, is_open)] for every op box."""
    out = []
    for m in re.finditer(
            r"class='op( open)?' style='left:(\d+)px;top:(\d+)px;"
            r"height:(\d+)px", doc):
        out.append((int(m.group(2)), int(m.group(3)), int(m.group(4)),
                    bool(m.group(1))))
    return out


def test_positioned_boxes_and_overlap():
    doc = TimelineHtml().render({"name": "t"}, overlapping_history())
    bs = boxes(doc)
    assert len(bs) == 3
    # three per-process columns, distinct x positions
    assert doc.count("class='colhead'") == 3
    lefts = {b[0] for b in bs}
    assert len(lefts) == 3
    # ops on p0 [0,2] and p1 [1,3] overlap in time: their vertical
    # extents must intersect while sitting in different columns
    (l0, t0, h0, _), (l1, t1, h1, _) = bs[0], bs[1]
    assert l0 != l1
    assert t0 < t1 + h1 and t1 < t0 + h0
    # duration maps to height: the 2 s ops are visibly long
    assert h0 > 10 and h1 > 10


def test_open_op_rendered_dashed_to_end():
    doc = TimelineHtml().render({"name": "t"}, overlapping_history())
    bs = boxes(doc)
    open_boxes = [b for b in bs if b[3]]
    assert len(open_boxes) == 1
    # the open op extends from its invoke (1.5 s) to t_max (3 s):
    # at least as tall as half of a completed 2 s op
    assert open_boxes[0][2] >= bs[0][2] // 2
    assert "never completed" in doc


def test_nemesis_band_and_hover_detail():
    doc = TimelineHtml(nemesis_perf=[
        {"name": "kills", "color": "#E9A4A4", "fs": ["kill"]},
    ]).render({"name": "t"}, overlapping_history())
    band = re.search(r"class='band' style='top:(\d+)px;"
                     r"height:(\d+)px;background:(#\w+)'", doc)
    assert band, "nemesis band missing"
    assert band.group(3) == "#E9A4A4"  # the package's perf color
    assert int(band.group(2)) > 10     # the 2 s window has real height
    assert "class='bandlabel'" in doc and ">kill</div>" in doc
    # hover titles carry the op detail
    assert "process 0" in doc
    assert re.search(r"title='[^']*2\.0000s\] ok \(2000\.0 ms\)", doc)


def test_axis_ticks_and_meta():
    doc = TimelineHtml().render({"name": "t"}, overlapping_history())
    assert doc.count("class='tick'") >= 4
    assert doc.count("class='grid'") >= 4
    assert "3 ops" in doc and "3 processes" in doc


def test_html_escaping():
    h = H(ev("invoke", 0, "write", "<x>", 0.0),
          ev("ok", 0, "write", "<x>", 1.0))
    doc = TimelineHtml().render(
        {"name": "<script>alert(1)</script>"}, h)
    assert "<script>" not in doc
    assert "&lt;script&gt;" in doc
    assert "<x>" not in doc          # op value escaped in label+title
    assert "&lt;x&gt;" in doc


def test_check_writes_file(tmp_path):
    res = TimelineHtml().check({"name": "t"}, overlapping_history(),
                               {"store_dir": str(tmp_path)})
    assert res["valid?"] is True
    assert os.path.exists(res["file"])
    with open(res["file"]) as f:
        assert "class='op'" in f.read()
    # no store dir -> valid, no file
    assert TimelineHtml().check({}, overlapping_history()) == \
        {"valid?": True}


def test_sim_lock_run_timeline(tmp_path):
    """Acceptance: a lock run under kill faults produces a timeline
    whose blocked acquires are positioned boxes and whose fault
    windows render as bands."""
    from jepsen_etcd_tpu.compose import etcd_test
    from jepsen_etcd_tpu.runner.test_runner import run_test
    out = run_test(etcd_test({
        "workload": "lock", "nemesis": ["kill"], "nemesis_interval": 2.0,
        "time_limit": 6, "rate": 30, "store_base": str(tmp_path),
        "seed": 3}))
    path = os.path.join(out["dir"], "timeline.html")
    assert os.path.exists(path)
    with open(path) as f:
        doc = f.read()
    bs = boxes(doc)
    assert len(bs) >= 4
    assert len({b[0] for b in bs}) >= 2      # multiple process columns
    assert len({b[1] for b in bs}) >= 2      # spread over the time axis
    assert "class='band'" in doc             # kill windows
    assert "acquire" in doc
