from jepsen_etcd_tpu.core import History, Op, invoke_op, ok, fail, info
import pytest


def test_op_attribute_access():
    op = invoke_op(0, "read")
    assert op.type == "invoke"
    assert op.f == "read"
    assert op.value is None
    assert op.error is None  # nil-punning for missing keys
    assert op.is_invoke and not op.is_ok


def test_op_completions():
    op = invoke_op(3, "write", 5)
    done = ok(op, value=5)
    assert done.is_ok and done.process == 3 and done.value == 5
    assert op.is_invoke  # original untouched
    f = fail(op, error="cas-failed")
    assert f.is_fail and f.error == "cas-failed"
    i = info(op, error="timeout")
    assert i.is_info
    assert i.is_client_op  # process 3 is a client


def test_pairing():
    h = History([
        invoke_op(0, "read"),
        invoke_op(1, "write", 1),
        Op(type="ok", f="write", value=1, process=1),
        Op(type="ok", f="read", value=1, process=0),
    ])
    assert h.pairs == {0: 3, 1: 2, 2: 1, 3: 0}
    assert h.completion(h[0])["index"] == 3
    assert h.invocation(h[2])["index"] == 1


def test_pairing_unmatched_invoke():
    h = History([
        invoke_op(0, "read"),
        Op(type="info", f="write", value=None, process=1),  # spontaneous
    ])
    assert h.pairs[0] is None
    assert h.pairs[1] is None


def test_double_invoke_raises():
    h = History([invoke_op(0, "read"), invoke_op(0, "read")])
    with pytest.raises(ValueError):
        _ = h.pairs


def test_filters_and_roundtrip():
    h = History([
        invoke_op("nemesis", "kill", ["n1"]),
        invoke_op(0, "read"),
        Op(type="ok", f="read", value=7, process=0),
        Op(type="info", f="kill", value=["n1"], process="nemesis"),
    ])
    assert len(h.client_ops()) == 2
    assert len(h.nemesis_ops()) == 2
    assert len(h.oks()) == 1
    h2 = History.from_jsonl(h.to_jsonl())
    assert len(h2) == len(h)
    assert h2[2].value == 7
    assert h2.pairs  # pairing survives round-trip


def test_filtered_history_pairing():
    # Regression: pairing must survive filtering (indices, not positions).
    h = History([
        invoke_op("nemesis", "kill"),
        Op(type="info", f="kill", process="nemesis"),
        invoke_op(0, "read"),
        Op(type="ok", f="read", value=3, process=0),
    ])
    sub = h.client_ops()
    inv = sub[0]
    assert inv["index"] == 2
    comp = sub.completion(inv)
    assert comp is not None and comp.value == 3


def test_tuple_value_roundtrip():
    # Regression: (key, value) tuples must survive JSONL round-trip.
    h = History([
        invoke_op(0, "txn", [("r", 5, None), ("append", 5, 1)]),
        Op(type="ok", f="read", value=("k", 1), process=0),
    ])
    h2 = History.from_jsonl(h.to_jsonl())
    assert h2[1].value == ("k", 1)
    assert h2[0].value == [("r", 5, None), ("append", 5, 1)]


def test_dict_key_and_index_collision_fixes():
    # Regression: non-string dict keys survive round-trip.
    h = History([Op(type="ok", f="read", process=0,
                    value={5: "a", ("k", 1): 2})])
    h2 = History.from_jsonl(h.to_jsonl())
    assert h2[0].value == {5: "a", ("k", 1): 2}

    # Regression: appending unindexed ops to indexed history can't collide.
    h3 = History([Op(type="invoke", f="r", process=0, index=1),
                  Op(type="ok", f="r", process=0)])
    assert h3[1]["index"] == 2
    assert h3.pairs == {1: 2, 2: 1}

    # Duplicate explicit indices are an error, not silent corruption.
    with pytest.raises(ValueError):
        History([Op(type="invoke", f="r", process=0, index=1),
                 Op(type="ok", f="r", process=0, index=1)])
