"""Differential tests: TPU WGL kernel vs the CPU oracle.

The kernel's verdict must match the oracle on every history where it
claims a definitive answer (SURVEY §7 step 6: validate on thousands of
small random histories; known-bad fixtures must stay invalid).
"""

import random

import pytest

from jepsen_etcd_tpu.core.op import Op
from jepsen_etcd_tpu.core.history import History
from jepsen_etcd_tpu.checkers import check_history
from jepsen_etcd_tpu.checkers.tpu_linearizable import TPULinearizableChecker
from jepsen_etcd_tpu.models import VersionedRegister
from jepsen_etcd_tpu.ops import wgl


def gen_history(rng: random.Random, n_procs=4, n_ops=20, values=3,
                corrupt=False, info_rate=0.0, dur_scale=1.0):
    """Random concurrent register history via linearization-point
    simulation: ops apply atomically at a random instant inside their
    [invoke, complete] span, so the generated history is linearizable by
    construction — unless `corrupt` flips some observations. With
    info_rate > 0, some ops complete :info (timeout/crash): the client
    doesn't learn the outcome — the op took effect with probability 1/2
    (at its linearization point) or not at all."""
    events = []  # (time, kind, proc, ...)
    t = 0.0
    state_v = 0   # version
    state_val = None
    # build per-process schedules: (start, end) spans
    spans = []
    for p in range(n_procs):
        at = rng.random()
        for _ in range(n_ops // n_procs):
            dur = (0.1 + rng.random()) * dur_scale
            spans.append((at, at + dur, p))
            at += dur + rng.random() * 0.3
    is_info = [rng.random() < info_rate for _ in spans]
    took_effect = [rng.random() < 0.5 for _ in spans]
    # linearization points decide outcomes
    pts = sorted((rng.uniform(s, e), i) for i, (s, e, p) in enumerate(spans))
    outcomes = {}
    for _, i in pts:
        s, e, p = spans[i]
        f = rng.choice(["read", "write", "cas"])
        if is_info[i] and not took_effect[i]:
            # crashed before reaching the server: no state change
            if f == "read":
                outcomes[i] = ("read", [None, None])
            elif f == "write":
                outcomes[i] = ("write", [None, rng.randrange(values)])
            else:
                outcomes[i] = ("cas", [None, [rng.randrange(values),
                                              rng.randrange(values)]])
            continue
        if f == "read":
            outcomes[i] = ("read", [state_v, state_val])
        elif f == "write":
            v = rng.randrange(values)
            state_v += 1
            state_val = v
            outcomes[i] = ("write", [state_v, v])
        else:
            old = rng.randrange(values)
            new = rng.randrange(values)
            if state_val == old:
                state_v += 1
                state_val = new
                outcomes[i] = ("cas", [state_v, [old, new]])
            elif is_info[i]:
                # would not have matched; still indefinite to the client
                outcomes[i] = ("cas", [None, [old, new]])
            else:
                outcomes[i] = ("cas-fail", [None, [old, new]])
    ops = []
    evs = []
    for i, (s, e, p) in enumerate(spans):
        evs.append((s, "inv", i, p))
        evs.append((e, "ret", i, p))
    evs.sort()
    for _, kind, i, p in evs:
        f, val = outcomes[i]
        if kind == "inv":
            fv = f if f != "cas-fail" else "cas"
            ops.append(Op(type="invoke", process=p, f=fv,
                          value=[None, val[1]] if fv != "read"
                          else [None, None]))
        else:
            if is_info[i]:
                ops.append(Op(type="info", process=p, f=f,
                              value=[None, val[1]] if f != "read"
                              else [None, None], error="timeout"))
            elif f == "cas-fail":
                ops.append(Op(type="fail", process=p, f="cas",
                              value=[None, val[1]], error="did-not-succeed"))
            else:
                v = list(val)
                if corrupt and rng.random() < 0.15:
                    if rng.random() < 0.5 and v[0] is not None:
                        v[0] = v[0] + rng.choice([-1, 1])
                    else:
                        v[1] = (v[1] + 1) % values if isinstance(v[1], int) \
                            else v[1]
                ops.append(Op(type="ok", process=p, f=f, value=v))
    return History(ops)


#: scale the differential fuzz via env (2500 trials ran clean in ~70 s
#: on the CPU mesh; default stays CI-sized); floor of 15 keeps the
#: definitive-coverage assertion meaningful
def _fuzz_trials() -> int:
    import os
    try:
        return max(15, int(os.environ.get("WGL_FUZZ_TRIALS", "150")))
    except ValueError:
        return 150


FUZZ_TRIALS = _fuzz_trials()


@pytest.mark.parametrize("corrupt", [False, True])
def test_differential_random_histories(corrupt):
    rng = random.Random(1234 if corrupt else 99)
    checker = TPULinearizableChecker(fallback=False)
    agree = 0
    definitive = 0
    for trial in range(FUZZ_TRIALS):
        h = gen_history(rng, n_procs=rng.randint(2, 5),
                        n_ops=rng.randint(8, 32), corrupt=corrupt)
        cpu = check_history(VersionedRegister(), h, use_native=False)
        tpu = checker.check({}, h)
        if tpu["valid?"] == "unknown":
            continue
        definitive += 1
        assert tpu["valid?"] == cpu["valid?"], (
            f"trial {trial}: kernel={tpu} oracle={cpu['valid?']}\n"
            + h.to_jsonl())
        agree += 1
    # the kernel must actually cover the vast majority of histories
    assert definitive >= FUZZ_TRIALS * 13 // 15, \
        f"only {definitive}/{FUZZ_TRIALS} definitive"


def test_clean_histories_all_valid():
    # uncorrupted histories are linearizable by construction
    rng = random.Random(7)
    checker = TPULinearizableChecker(fallback=False)
    for _ in range(50):
        h = gen_history(rng, n_procs=3, n_ops=18, corrupt=False)
        out = checker.check({}, h)
        if out["valid?"] != "unknown":
            assert out["valid?"] is True, h.to_jsonl()


def test_kernel_packing_feasibility():
    rng = random.Random(5)
    h = gen_history(rng, n_procs=4, n_ops=24)
    p = wgl.pack_register_history(h)
    assert p.ok
    assert p.R > 0
    # every op is forced by depth R: total slide equals R
    assert p.shift.sum() == p.R


def test_info_only_history_is_trivially_valid():
    h = History([
        Op(type="invoke", process=0, f="write", value=[None, 1]),
        Op(type="info", process=0, f="write", value=[None, 1]),
    ])
    p = wgl.pack_register_history(h)
    assert p.ok and p.R == 0
    out = TPULinearizableChecker(fallback=True).check({}, h)
    assert out["valid?"] is True


def test_info_write_may_have_happened():
    # crashed write of 7; later read sees 7 at version 1 — only legal if
    # the info write linearized. The kernel must find it.
    h = History([
        Op(type="invoke", process=0, f="write", value=[None, 7]),
        Op(type="info", process=0, f="write", value=[None, 7],
           error="timeout"),
        Op(type="invoke", process=1, f="read", value=[None, None]),
        Op(type="ok", process=1, f="read", value=[1, 7]),
    ])
    p = wgl.pack_register_history(h)
    assert p.ok and p.R == 1 and p.I == 1
    out = TPULinearizableChecker(fallback=False).check({}, h)
    assert out["valid?"] is True and out["checker"] == "tpu-wgl"


def test_info_write_may_not_have_happened():
    # crashed write of 7; later read sees version 0 / unset — only legal
    # if the info write never linearized.
    h = History([
        Op(type="invoke", process=0, f="write", value=[None, 7]),
        Op(type="info", process=0, f="write", value=[None, 7],
           error="timeout"),
        Op(type="invoke", process=1, f="read", value=[None, None]),
        Op(type="ok", process=1, f="read", value=[0, None]),
    ])
    out = TPULinearizableChecker(fallback=False).check({}, h)
    assert out["valid?"] is True and out["checker"] == "tpu-wgl"


def test_info_write_cannot_rescue_impossible_read():
    # read sees version 2 but only one (crashed) write exists: version
    # can reach at most 1 — invalid, and the kernel must say so.
    h = History([
        Op(type="invoke", process=0, f="write", value=[None, 7]),
        Op(type="info", process=0, f="write", value=[None, 7],
           error="timeout"),
        Op(type="invoke", process=1, f="read", value=[None, None]),
        Op(type="ok", process=1, f="read", value=[2, 7]),
    ])
    out = TPULinearizableChecker(fallback=False).check({}, h)
    assert out["valid?"] is False


def test_info_pred_ordering():
    # an info op invoked AFTER an ok op returns cannot linearize before
    # it: w=1 completes (version 1), THEN a write of 2 crashes, then a
    # read sees [1, 2] — impossible: the crashed write could only
    # linearize at version 2.
    h = History([
        Op(type="invoke", process=0, f="write", value=[None, 1]),
        Op(type="ok", process=0, f="write", value=[1, 1]),
        Op(type="invoke", process=1, f="write", value=[None, 2]),
        Op(type="info", process=1, f="write", value=[None, 2],
           error="timeout"),
        Op(type="invoke", process=2, f="read", value=[None, None]),
        Op(type="ok", process=2, f="read", value=[1, 2]),
    ])
    cpu = check_history(VersionedRegister(), h, use_native=False)
    out = TPULinearizableChecker(fallback=False).check({}, h)
    assert cpu["valid?"] is False
    assert out["valid?"] is False


def test_info_cas_requires_matching_value():
    # crashed cas(1->9) can only linearize when value is 1; value history
    # is 2 only, so a read of [2, 9] is impossible...
    h = History([
        Op(type="invoke", process=0, f="write", value=[None, 2]),
        Op(type="ok", process=0, f="write", value=[1, 2]),
        Op(type="invoke", process=1, f="cas", value=[None, [1, 9]]),
        Op(type="info", process=1, f="cas", value=[None, [1, 9]],
           error="timeout"),
        Op(type="invoke", process=2, f="read", value=[None, None]),
        Op(type="ok", process=2, f="read", value=[2, 9]),
    ])
    out = TPULinearizableChecker(fallback=False).check({}, h)
    assert out["valid?"] is False
    # ...but cas(2->9) CAN: value 2 at version 1, cas makes version 2.
    h2 = History([
        Op(type="invoke", process=0, f="write", value=[None, 2]),
        Op(type="ok", process=0, f="write", value=[1, 2]),
        Op(type="invoke", process=1, f="cas", value=[None, [2, 9]]),
        Op(type="info", process=1, f="cas", value=[None, [2, 9]],
           error="timeout"),
        Op(type="invoke", process=2, f="read", value=[None, None]),
        Op(type="ok", process=2, f="read", value=[2, 9]),
    ])
    out2 = TPULinearizableChecker(fallback=False).check({}, h2)
    assert out2["valid?"] is True


@pytest.mark.parametrize("corrupt", [False, True])
def test_differential_info_histories(corrupt):
    # crashed-op histories: the kernel's info path vs the CPU oracle
    rng = random.Random(4242 if corrupt else 777)
    checker = TPULinearizableChecker(fallback=False)
    definitive = 0
    for trial in range(120):
        h = gen_history(rng, n_procs=rng.randint(2, 5),
                        n_ops=rng.randint(8, 28), corrupt=corrupt,
                        info_rate=0.3)
        cpu = check_history(VersionedRegister(), h, use_native=False)
        tpu = checker.check({}, h)
        if tpu["valid?"] == "unknown" or cpu["valid?"] == "unknown":
            continue
        definitive += 1
        assert tpu["valid?"] == cpu["valid?"], (
            f"trial {trial}: kernel={tpu} oracle={cpu['valid?']}\n"
            + h.to_jsonl())
    assert definitive >= 100, f"only {definitive}/120 definitive"


def test_kernel_on_real_run_history(tmp_path):
    # end-to-end: swap the register workload's checker to the TPU kernel
    from jepsen_etcd_tpu.compose import etcd_test
    from jepsen_etcd_tpu.runner.test_runner import run_test
    from jepsen_etcd_tpu.generators.independent import history_keys, subhistory

    out = run_test(etcd_test({
        "workload": "register", "time_limit": 6, "rate": 60,
        "ops_per_key": 40, "store_base": str(tmp_path), "seed": 17}))
    h = out["history"]
    checker = TPULinearizableChecker(fallback=False)
    n_checked = 0
    for k in history_keys(h):
        sub = History(subhistory(h, k))
        r = checker.check({}, sub)
        cpu = check_history(VersionedRegister(), sub)
        if r["valid?"] != "unknown":
            assert r["valid?"] == cpu["valid?"]
            n_checked += 1
    assert n_checked >= 1


def test_read_none_value_is_wildcard():
    # Regression: a read [v>0, None] asserts only the version, like the
    # CPU model (nil op-value is unchecked).
    h = History([
        Op(type="invoke", process=0, f="write", value=[None, 3]),
        Op(type="ok", process=0, f="write", value=[1, 3]),
        Op(type="invoke", process=0, f="read", value=[None, None]),
        Op(type="ok", process=0, f="read", value=[1, None]),
    ])
    cpu = check_history(VersionedRegister(), h, use_native=False)
    tpu = TPULinearizableChecker(fallback=False).check({}, h)
    assert cpu["valid?"] is True
    assert tpu["valid?"] is True


def test_full_window_slide():
    # 32 mutually-concurrent ops force a whole-window slide (shift == W,
    # the uint32<<32 hazard) AND a combinatorial frontier: the kernel must
    # never answer wrongly — overflow -> unknown -> CPU fallback.
    ops = []
    for p in range(32):
        ops.append(Op(type="invoke", process=p, f="write", value=[None, 1]))
    for p in range(32):
        ops.append(Op(type="ok", process=p, f="write", value=[None, 1]))
    h = History(ops)
    pk = wgl.pack_register_history(h)
    assert pk.ok and int(pk.shift.max()) == 32
    raw = TPULinearizableChecker(fallback=False).check({}, h)
    assert raw["valid?"] in (True, "unknown")  # never a wrong False
    out = TPULinearizableChecker(fallback=True).check({}, h)
    assert out["valid?"] is True


def _concurrent_writes_history(n=16, read_val=1, read_ver=None):
    # n mutually-concurrent unversioned writes of the same value, then a
    # sequential read. Peak frontier = C(n, n/2) — far past F_MAX=512 for
    # n=16 (12870), exercising the spill path end to end.
    ops = []
    for p in range(n):
        ops.append(Op(type="invoke", process=p, f="write", value=[None, 1]))
    for p in range(n):
        ops.append(Op(type="ok", process=p, f="write", value=[None, 1]))
    ops.append(Op(type="invoke", process=n, f="read", value=[None, None]))
    ops.append(Op(type="ok", process=n, f="read",
                  value=[n if read_ver is None else read_ver, read_val]))
    return History(ops)


def test_spill_valid_verdict_past_fmax():
    h = _concurrent_writes_history(16, read_val=1)
    out = TPULinearizableChecker(fallback=False).check({}, h)
    assert out["valid?"] is True, out
    assert out.get("spilled"), out
    assert out["peak-frontier"] > wgl.F_MAX


def test_spill_invalid_verdict_past_fmax():
    # read observes a value nobody wrote: invalid, proven by exhausting
    # the spilled search (complete, not just sound)
    h = _concurrent_writes_history(16, read_val=9)
    out = TPULinearizableChecker(fallback=False).check({}, h)
    assert out["valid?"] is False, out
    assert out.get("spilled"), out


def _crashed_writes_history(n_info: int, read=(1, 1)):
    ops = [
        Op(type="invoke", process=0, f="write", value=[None, 1]),
        Op(type="ok", process=0, f="write", value=[1, 1]),
    ]
    for j in range(n_info):  # concurrent crashed writes, distinct values
        ops.append(Op(type="invoke", process=100 + j, f="write",
                      value=[None, 1000 + j]))
    ops += [
        Op(type="invoke", process=1, f="read", value=[None, None]),
        Op(type="ok", process=1, f="read", value=list(read)),
    ]
    for j in range(n_info):
        ops.append(Op(type="info", process=100 + j, f="write",
                      value=[None, 1000 + j], error="timeout"))
    return History(ops)


def test_dead_value_merge_collapses_info_classes():
    """Crashed writes of distinct never-observed values merge into ONE
    symmetry class: within imask capacity the kernel search collapses
    to per-class prefix counts, and PAST capacity the (sound) fallback
    DFS now answers definitively instead of exceeding its budget —
    2^40 subsets become 41 counts."""
    # dead-value merge folds all crashed writes into ONE class, so the
    # info state is a single prefix count — a handful of bits
    h24 = _crashed_writes_history(24)
    p = wgl.pack_register_history(h24)
    assert p.ok and p.I == 24, (p.ok, p.reason, p.I)
    assert p.C == 1 and p.ni == 1, (p.C, p.ni)
    out = TPULinearizableChecker(fallback=False).check({}, h24)
    assert out["valid?"] is True and out["checker"] == "tpu-wgl", out
    # 40 crashed writes — past the old one-bit-per-op limit (32) —
    # still pack: counts, not bits
    h40 = _crashed_writes_history(40)
    p40 = wgl.pack_register_history(h40)
    assert p40.ok and p40.I == 40 and p40.C == 1, \
        (p40.ok, p40.reason, p40.C)
    out = TPULinearizableChecker(fallback=False).check({}, h40)
    assert out["valid?"] is True and out["checker"] == "tpu-wgl", out
    # a read observing a crashed value keeps it asserted (alive): the
    # kernel proves the version contradiction (1007's write and the ok
    # write can't both be version 1). The unreduced Python DFS can
    # only answer 'unknown' here (2^24 subsets exceed its budget) —
    # compatible; on a small instance it must agree exactly
    bad = _crashed_writes_history(24, read=(1, 1007))
    tpu = TPULinearizableChecker(fallback=False).check({}, bad)
    assert tpu["valid?"] is False, tpu
    cpu = check_history(VersionedRegister(), bad, use_native=False)
    assert cpu["valid?"] in (False, "unknown"), cpu
    small_bad = _crashed_writes_history(8, read=(1, 1007))
    cpu = check_history(VersionedRegister(), small_bad, use_native=False)
    tpu = TPULinearizableChecker(fallback=False).check({}, small_bad)
    assert tpu["valid?"] == cpu["valid?"] is False, (tpu, cpu)


@pytest.mark.parametrize("corrupt", [False, True])
def test_differential_high_info(corrupt):
    """Histories with MANY crashed ops (often I > 32 — past the old
    one-bit-per-op limit) pack as per-class counts and must agree with
    the native engine."""
    from jepsen_etcd_tpu.native import oracle as native_oracle
    from jepsen_etcd_tpu.checkers.linearizable import history_entries
    rng = random.Random(909 + corrupt)
    checker = TPULinearizableChecker(fallback=False)
    definitive = 0
    seen_high_i = 0
    for trial in range(12):
        h = gen_history(rng, n_procs=rng.randint(4, 8),
                        n_ops=rng.randint(80, 130), values=3,
                        corrupt=corrupt, info_rate=0.6)
        p = wgl.pack_register_history(h)
        if not p.ok:
            continue
        if p.I > 32:
            seen_high_i += 1
        nat = native_oracle.check_entries(VersionedRegister(),
                                          history_entries(h))
        tpu = checker.check({}, h)
        if "unknown" in (tpu["valid?"], nat["valid?"]):
            continue
        definitive += 1
        assert tpu["valid?"] == nat["valid?"], (
            f"trial {trial} (I={p.I}, C={p.C}): kernel={tpu['valid?']} "
            f"native={nat['valid?']}\n" + h.to_jsonl())
    assert definitive >= 7, f"only {definitive}/12 definitive"
    assert seen_high_i >= 2, f"only {seen_high_i} high-I packs"


def test_multiword_count_state():
    """Many DISTINCT classes (crashed cas ops with distinct asserted
    olds) overflow one count word: the ni=2 layout must agree with the
    native engine on both verdicts."""
    from jepsen_etcd_tpu.native import oracle as native_oracle
    from jepsen_etcd_tpu.checkers.linearizable import history_entries
    n = 40
    ops = []
    for j in range(n):
        ops.append(Op(type="invoke", process=100 + j, f="cas",
                      value=[None, [j, 500 + j]]))
    cur = None
    ver = 0
    for j in range(n):  # sequential required writes produce each old
        ops += [Op(type="invoke", process=0, f="write", value=[None, j]),
                Op(type="ok", process=0, f="write", value=[ver + 1, j])]
        ver += 1
        cur = j
    ops += [Op(type="invoke", process=1, f="read", value=[None, None]),
            Op(type="ok", process=1, f="read", value=[ver, cur])]
    for j in range(n):
        ops.append(Op(type="info", process=100 + j, f="cas",
                      value=[None, [j, 500 + j]], error="timeout"))
    h = History(ops)
    p = wgl.pack_register_history(h)
    assert p.ok and p.C == n and p.ni >= 2, (p.ok, p.reason, p.C, p.ni)
    tpu = TPULinearizableChecker(fallback=False).check({}, h)
    nat = native_oracle.check_entries(VersionedRegister(),
                                      history_entries(h))
    assert tpu["valid?"] == nat["valid?"] is True, (tpu, nat)
    # and an impossible final read stays jointly invalid
    bad = History(ops[:-1 - n] + [
        Op(type="ok", process=1, f="read", value=[ver, 12345])] + ops[-n:])
    tpu = TPULinearizableChecker(fallback=False).check({}, bad)
    nat = native_oracle.check_entries(VersionedRegister(),
                                      history_entries(bad))
    assert tpu["valid?"] == nat["valid?"] is False, (tpu, nat)


def test_version_ceiling_prune_info_heavy():
    """A tightly version-asserted required schedule plus 30 concurrent
    crashed writes (of ASSERTED values — no dead-value merge applies):
    the ceiling prune kills every state that fires a crashed update
    the next assertion can't absorb. Without it this search wanders
    millions of count combinations; with it, thousands at most —
    the regime behind test-all's faulted-register unknowns."""
    ops = []
    for j in range(30):
        ops.append(Op(type="invoke", process=100 + j, f="write",
                      value=[None, j % 5]))
    for i in range(1, 11):
        ops += [
            Op(type="invoke", process=0, f="write", value=[None, i % 5]),
            Op(type="ok", process=0, f="write", value=[i, i % 5]),
            Op(type="invoke", process=1, f="read", value=[None, None]),
            Op(type="ok", process=1, f="read", value=[i, i % 5]),
        ]
    for j in range(30):
        ops.append(Op(type="info", process=100 + j, f="write",
                      value=[None, j % 5], error="timeout"))
    h = History(ops)
    nat = check_history(VersionedRegister(), h)
    assert nat["valid?"] is True, nat
    assert nat.get("checker-impl") == "native"
    assert nat["configs"] < 5_000, nat["configs"]
    tpu = TPULinearizableChecker(fallback=False).check({}, h)
    assert tpu["valid?"] is True and tpu["checker"] == "tpu-wgl", tpu
    assert tpu["peak-frontier"] < 64, tpu


def test_unproducible_info_cas_dropped():
    """A crashed cas whose old value nothing can produce can never
    fire; it must not count against imask capacity or change verdicts."""
    ops = [
        Op(type="invoke", process=0, f="write", value=[None, 1]),
        Op(type="ok", process=0, f="write", value=[1, 1]),
    ]
    for j in range(40):
        ops.append(Op(type="invoke", process=100 + j, f="cas",
                      value=[None, [5000 + j, 6000 + j]]))
    ops += [
        Op(type="invoke", process=1, f="read", value=[None, None]),
        Op(type="ok", process=1, f="read", value=[1, 1]),
    ]
    for j in range(40):
        ops.append(Op(type="info", process=100 + j, f="cas",
                      value=[None, [5000 + j, 6000 + j]], error="timeout"))
    h = History(ops)
    p = wgl.pack_register_history(h)
    assert p.ok, p.reason
    assert p.I == 0, p.I  # all dropped: olds have no producer
    out = TPULinearizableChecker(fallback=False).check({}, h)
    assert out["valid?"] is True, out


@pytest.mark.parametrize("corrupt", [False, True])
def test_differential_wide_value_domain(corrupt):
    """Random info-heavy histories over a LARGE value domain (most
    values dead) must agree across kernel, native, and Python engines."""
    from jepsen_etcd_tpu.native import oracle as native_oracle
    from jepsen_etcd_tpu.checkers.linearizable import history_entries
    rng = random.Random(2024 + corrupt)
    checker = TPULinearizableChecker(fallback=False)
    definitive = 0
    for trial in range(60):
        h = gen_history(rng, n_procs=rng.randint(2, 5),
                        n_ops=rng.randint(8, 28), values=10_000,
                        corrupt=corrupt, info_rate=0.3)
        cpu = check_history(VersionedRegister(), h, use_native=False)
        nat = native_oracle.check_entries(VersionedRegister(),
                                          history_entries(h))
        tpu = checker.check({}, h)
        assert nat is not None
        if "unknown" in (tpu["valid?"], cpu["valid?"], nat["valid?"]):
            continue
        definitive += 1
        assert tpu["valid?"] == cpu["valid?"] == nat["valid?"], (
            f"trial {trial}: kernel={tpu['valid?']} "
            f"python={cpu['valid?']} native={nat['valid?']}\n"
            + h.to_jsonl())
    assert definitive >= 45, f"only {definitive}/60 definitive"


def test_spill_resumes_from_frozen_frontier():
    """check_packed(spill=False) hands back the frozen frontier; spilling
    from it must reach the same verdicts as the integrated spill, without
    re-climbing the ladder."""
    for read_val, expect in ((1, True), (9, False)):
        h = _concurrent_writes_history(16, read_val=read_val)
        p = wgl.pack_register_history(h)
        out = wgl.check_packed(p, spill=False)
        assert out["valid?"] == "unknown" and out.get("overflow"), out
        resumed = wgl.spill_packed(p, *out["_resume"])
        assert resumed["valid?"] is expect, (read_val, resumed)
        assert resumed.get("spilled"), resumed


def test_overflow_prefers_dfs_before_spill():
    """With a fallback available, top-rung overflow routes to the DFS
    (one witness path) before the exhaustive spill BFS: a hopelessly
    wide valid history answers fast via cpu-oracle instead of grinding
    through a multi-million-state frontier."""
    h = _concurrent_writes_history(24, read_val=1)  # C(24,12) ~ 2.7M
    # cutoff disabled: this pins the kernel-overflow -> DFS ordering,
    # which only triggers when the history actually reaches the device
    out = TPULinearizableChecker(cpu_cutoff=None).check({}, h)
    assert out["valid?"] is True, out
    assert out["checker"] == "cpu-oracle", out
    assert "overflow" in out.get("tpu-fallback-reason", ""), out


def test_unsupported_model_goes_to_cpu():
    # a model state the kernel has no packing for (non-default initial
    # register) must take the sound CPU path; Mutex itself now packs
    # onto the kernel (see test_differential_mutex)
    h = History([
        Op(type="invoke", process=0, f="read", value=[3, "x"]),
        Op(type="ok", process=0, f="read", value=[3, "x"]),
    ])
    out = TPULinearizableChecker(
        lambda: VersionedRegister(3, "x")).check({}, h)
    assert out["checker"] == "cpu-oracle"
    assert out["valid?"] is True


def gen_mutex_history(rng, n_procs=3, n_ops=24, corrupt=False,
                      info_rate=0.0):
    """Random mutex history by linearization-point simulation (legal by
    construction unless corrupt flips an outcome into a double-acquire /
    free-release)."""
    spans = []
    for p in range(n_procs):
        at = rng.random()
        for _ in range(n_ops // n_procs):
            dur = 0.1 + rng.random()
            spans.append((at, at + dur, p))
            at += dur + rng.random() * 0.3
    is_info = [rng.random() < info_rate for _ in spans]
    took_effect = [rng.random() < 0.5 for _ in spans]
    pts = sorted((rng.uniform(s, e), i) for i, (s, e, p) in enumerate(spans))
    locked = False
    outcomes = {}
    for _, i in pts:
        if is_info[i] and not took_effect[i]:
            outcomes[i] = (rng.choice(["acquire", "release"]), None)
            continue
        if not locked:
            locked = True
            outcomes[i] = ("acquire", "ok")
        else:
            locked = False
            outcomes[i] = ("release", "ok")
    if corrupt:
        # flip some outcomes BEFORE events are emitted: checkers take f
        # from the invoke op, so a flip must land there to produce a
        # genuinely illegal schedule (double-acquire / free-release)
        for i in list(outcomes):
            if outcomes[i][1] == "ok" and rng.random() < 0.2:
                f0, res = outcomes[i]
                outcomes[i] = ("release" if f0 == "acquire"
                               else "acquire", res)
    evs = []
    for i, (s, e, p) in enumerate(spans):
        evs.append((s, "inv", i, p))
        evs.append((e, "ret", i, p))
    evs.sort()
    ops = []
    for _, kind, i, p in evs:
        f, res = outcomes[i]
        if kind == "inv":
            ops.append(Op(type="invoke", process=p, f=f, value=None))
        elif is_info[i]:
            ops.append(Op(type="info", process=p, f=f, value=None,
                          error="timeout"))
        else:
            ops.append(Op(type="ok", process=p, f=f, value=None))
    return History(ops)


@pytest.mark.parametrize("corrupt,info_rate",
                         [(False, 0.0), (True, 0.0), (False, 0.25)])
def test_differential_mutex(corrupt, info_rate):
    """Mutex histories run on the SAME kernel via the CAS-register
    adapter; verdicts must match the CPU mutex oracle (VERDICT r1
    weak #6)."""
    from jepsen_etcd_tpu.models import Mutex
    rng = random.Random(hash((corrupt, info_rate)) & 0xFFFF)
    checker = TPULinearizableChecker(Mutex, fallback=False)
    definitive = 0
    for trial in range(100):
        h = gen_mutex_history(rng, n_procs=rng.randint(2, 4),
                              n_ops=rng.randint(6, 24),
                              corrupt=corrupt, info_rate=info_rate)
        cpu = check_history(Mutex(), h, use_native=False)
        tpu = checker.check({}, h)
        if tpu["valid?"] == "unknown":
            continue
        definitive += 1
        assert tpu["valid?"] == cpu["valid?"], (
            f"trial {trial}: kernel={tpu} oracle={cpu['valid?']}\n"
            + h.to_jsonl())
    assert definitive >= 85, f"only {definitive}/100 definitive"


def test_mutex_known_bad():
    from jepsen_etcd_tpu.models import Mutex
    # double acquire with no release between: must be invalid
    ops = [
        Op(type="invoke", process=0, f="acquire", value=None),
        Op(type="ok", process=0, f="acquire", value=None),
        Op(type="invoke", process=1, f="acquire", value=None),
        Op(type="ok", process=1, f="acquire", value=None),
    ]
    out = TPULinearizableChecker(Mutex, fallback=False).check(
        {}, History(ops))
    assert out["valid?"] is False
    assert out["checker"] == "tpu-wgl"


def _wide_window_history(n=45, bad=False):
    """One write spans n sequential versioned writes: the undecided
    window reaches n+1 > 32, exercising the two-word (W=64) kernel."""
    ops = [Op(type="invoke", process=0, f="write", value=[None, 7])]
    for i in range(1, n + 1):
        ops.append(Op(type="invoke", process=i, f="write",
                      value=[None, i]))
        ver = i if not (bad and i == n) else i + 3
        ops.append(Op(type="ok", process=i, f="write", value=[ver, i]))
    ops.append(Op(type="ok", process=0, f="write", value=[n + 1, 7]))
    return History(ops)


def test_wide_window_uses_w64():
    p = wgl.pack_register_history(_wide_window_history(45))
    assert p.ok and p.w == 64, (p.ok, p.reason, p.w)
    out = TPULinearizableChecker(fallback=False).check(
        {}, _wide_window_history(45))
    assert out["valid?"] is True, out
    assert out["checker"] == "tpu-wgl"


def test_wide_window_invalid():
    out = TPULinearizableChecker(fallback=False).check(
        {}, _wide_window_history(45, bad=True))
    assert out["valid?"] is False, out


def test_window_past_64_uses_w128():
    h = _wide_window_history(70)
    p = wgl.pack_register_history(h)
    assert p.ok and p.w == 128, (p.ok, p.reason, p.w)
    out = TPULinearizableChecker(fallback=False).check({}, h)
    assert out["valid?"] is True, out
    assert out["checker"] == "tpu-wgl"
    bad = TPULinearizableChecker(fallback=False).check(
        {}, _wide_window_history(70, bad=True))
    assert bad["valid?"] is False, bad


def test_wide_window_with_info_ops():
    """The W=128 x info-count intersection on one fixed shape: a
    70-wide window plus crashed writes must agree with the native
    engine on valid and invalid variants."""
    from jepsen_etcd_tpu.native import oracle as native_oracle
    from jepsen_etcd_tpu.checkers.linearizable import history_entries
    for bad in (False, True):
        ops = list(_wide_window_history(70))
        if bad:
            # a value nothing (required or crashed) ever writes:
            # unrescuable, unlike a small version skew which the
            # crashed writes below could legally absorb
            ops += [Op(type="invoke", process=300, f="read",
                       value=[None, None]),
                    Op(type="ok", process=300, f="read",
                       value=[None, 424242])]
        for j in range(6):
            ops.insert(1, Op(type="invoke", process=200 + j, f="write",
                             value=[None, 900 + j]))
        for j in range(6):
            ops.append(Op(type="info", process=200 + j, f="write",
                          value=[None, 900 + j], error="timeout"))
        h = History([o.evolve(index=None) for o in ops])
        p = wgl.pack_register_history(h)
        assert p.ok and p.w == 128 and p.I == 6, \
            (p.ok, p.reason, p.w, p.I)
        tpu = TPULinearizableChecker(fallback=False).check({}, h)
        nat = native_oracle.check_entries(VersionedRegister(),
                                          history_entries(h))
        assert tpu["valid?"] == nat["valid?"] == (not bad), \
            (bad, tpu, nat["valid?"])


def test_window_past_128_rejected():
    p = wgl.pack_register_history(_wide_window_history(140))
    assert not p.ok and "window" in p.reason


def test_differential_w128():
    """Histories stretched past window 64 run the four-word kernel and
    agree with the Python oracle."""
    rng = random.Random(777)
    checker = TPULinearizableChecker(fallback=False)
    definitive = 0
    for trial in range(15):
        base = gen_history(rng, n_procs=4, n_ops=rng.randint(68, 100),
                           corrupt=(trial % 2 == 1))
        long_op = Op(type="invoke", process=99, f="write",
                     value=[None, 3])
        ops = [long_op] + list(base) + [
            Op(type="ok", process=99, f="write", value=[None, 3])]
        h = History([o.evolve(index=None) for o in ops])
        p = wgl.pack_register_history(h)
        if not p.ok or p.w != 128:
            continue
        cpu = check_history(VersionedRegister(), h, use_native=False)
        tpu = checker.check({}, h)
        if tpu["valid?"] == "unknown" or cpu["valid?"] == "unknown":
            continue
        definitive += 1
        assert tpu["valid?"] == cpu["valid?"], (
            f"trial {trial} (w={p.w}): kernel={tpu} "
            f"oracle={cpu['valid?']}\n" + h.to_jsonl())
    assert definitive >= 8, f"only {definitive}/15 definitive"


def test_differential_wide_histories():
    """Random histories stretched by a history-spanning op (window > 32)
    agree with the CPU oracle on the W=64 kernel."""
    rng = random.Random(321)
    checker = TPULinearizableChecker(fallback=False)
    definitive = 0
    for trial in range(30):
        base = gen_history(rng, n_procs=3, n_ops=rng.randint(34, 50),
                           corrupt=(trial % 2 == 1))
        long_op = Op(type="invoke", process=99, f="write",
                     value=[None, 3])
        ops = [long_op] + list(base) + [
            Op(type="ok", process=99, f="write", value=[None, 3])]
        h = History([o.evolve(index=None) for o in ops])
        p = wgl.pack_register_history(h)
        if not p.ok:
            continue
        cpu = check_history(VersionedRegister(), h, use_native=False)
        tpu = checker.check({}, h)
        if tpu["valid?"] == "unknown" or cpu["valid?"] == "unknown":
            continue
        definitive += 1
        assert tpu["valid?"] == cpu["valid?"], (
            f"trial {trial} (w={p.w}): kernel={tpu} "
            f"oracle={cpu['valid?']}\n" + h.to_jsonl())
    assert definitive >= 20, f"only {definitive}/30 definitive"


# ---- engine-size cutoff (one checker, engine picked by problem size) ------

def test_size_cutoff_routes_small_histories_to_native():
    """Small histories must answer from the native DFS in milliseconds,
    never paying device dispatch (BENCH_r02: 0.40 s TPU vs 2.4 ms
    native on register_100)."""
    import time
    rng2 = random.Random(5)
    h = History([o.evolve(index=None)
                 for o in gen_history(rng2, n_procs=4, n_ops=100)])
    assert len(h) <= TPULinearizableChecker().cpu_cutoff
    checker = TPULinearizableChecker(fallback=True)
    t0 = time.perf_counter()
    out = checker.check({}, h)
    dt = time.perf_counter() - t0
    assert out["valid?"] is True
    assert out["checker"] == "cpu-oracle"
    assert out["engine-route"] == "size-cutoff"
    assert dt < 0.25, f"cutoff path took {dt:.3f}s"


def test_size_cutoff_disabled_when_kernel_pinned():
    """fallback=False pins the kernel path (the differential harness
    relies on it), so the cutoff must not apply there."""
    assert TPULinearizableChecker(fallback=False).cpu_cutoff is None


def test_size_cutoff_differential_verdicts():
    """Cutoff routing must be verdict-preserving: same answers as the
    kernel on both valid and corrupted histories."""
    rng2 = random.Random(11)
    for trial in range(8):
        h = History([o.evolve(index=None)
                     for o in gen_history(rng2, n_procs=3, n_ops=24,
                                          corrupt=(trial % 2 == 1))])
        via_cutoff = TPULinearizableChecker(fallback=True).check({}, h)
        via_kernel = TPULinearizableChecker(fallback=False).check({}, h)
        if via_kernel["valid?"] == "unknown":
            continue
        assert via_cutoff["valid?"] == via_kernel["valid?"], (
            f"trial {trial}: cutoff={via_cutoff['valid?']} "
            f"kernel={via_kernel['valid?']}")


def test_check_batch_splits_small_and_large():
    """check_batch must answer small keys natively and keep big keys on
    the batched kernel launch."""
    rng2 = random.Random(23)
    small = History([o.evolve(index=None)
                     for o in gen_history(rng2, n_procs=3, n_ops=20)])
    big = History([o.evolve(index=None)
                   for o in gen_history(random.Random(101),
                                        n_procs=4, n_ops=120)])
    checker = TPULinearizableChecker(fallback=True, cpu_cutoff=100,
                                     dfs_first_max=None)
    assert len(small) <= 100 < len(big)
    outs = checker.check_batch({}, {"s": small, "b": big})
    assert outs["s"]["checker"] == "cpu-oracle"
    assert outs["s"]["engine-route"] == "size-cutoff"
    assert outs["s"]["valid?"] is True
    assert outs["b"]["valid?"] is True
    assert outs["b"]["checker"] == "tpu-wgl"


def test_dfs_first_band_routes_midsize_histories():
    """Histories between CPU_CUTOFF and DFS_FIRST_MAX get a scaled-
    budget DFS first shot (measured crossover: the DFS's near-linear
    witness search beats kernel dispatch well past 512 entries). The
    routing assertions prove the band was taken; wall-clock is a bench
    concern, not a unit-test one."""
    from jepsen_etcd_tpu.checkers.tpu_linearizable import (
        CPU_CUTOFF, DFS_FIRST_MAX)
    rng2 = random.Random(71)
    h = History([o.evolve(index=None)
                 for o in gen_history(rng2, n_procs=4, n_ops=600)])
    assert CPU_CUTOFF < len(h) <= DFS_FIRST_MAX
    out = TPULinearizableChecker(fallback=True).check({}, h)
    assert out["valid?"] is True
    assert out["checker"] == "cpu-oracle"
    assert out["engine-route"] == "size-cutoff"


def test_dfs_first_band_invalid_stays_correct():
    """An invalid mid-size history must produce a definitive, correct
    verdict — a corrupted observation is provably non-linearizable and
    neither engine may answer unknown on it."""
    from jepsen_etcd_tpu.checkers.tpu_linearizable import (
        CPU_CUTOFF, DFS_FIRST_MAX)
    rng2 = random.Random(73)
    h = History([o.evolve(index=None)
                 for o in gen_history(rng2, n_procs=3, n_ops=400,
                                      corrupt=True)])
    assert CPU_CUTOFF < len(h) <= DFS_FIRST_MAX
    ref = check_history(VersionedRegister(), h, use_native=False)
    assert ref["valid?"] is False, "seed 73 must stay a known-bad fixture"
    out = TPULinearizableChecker(fallback=True).check({}, h)
    assert out["valid?"] is False


def test_band_budget_never_replaces_full_fallback():
    """A mid-size history the kernel can't pack must get the FULL
    5M-config fallback search, not a tiny band-budget unknown (the
    band budget is sized for witness-finding, not exhaustion)."""
    checker = TPULinearizableChecker(fallback=True)
    h = History([o.evolve(index=None)
                 for o in gen_history(random.Random(71), n_procs=4,
                                      n_ops=600)])
    small, unknown, budget = checker._small_history_check(h)
    assert small is not None and unknown is None
    assert budget < checker.FALLBACK_MAX_CONFIGS
    # simulate a band-budget unknown on a pack-less path: it must
    # escalate to _fallback rather than return the band unknown
    fake_unknown = {"valid?": "unknown", "error": "search budget exceeded"}
    out = checker._fallback_after_band(h, "no packing", False,
                                       fake_unknown, budget)
    assert out["valid?"] is True          # full budget finds the witness
    assert out["tpu-fallback-reason"] == "no packing"
