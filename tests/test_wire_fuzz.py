"""Seeded malformed-frame fuzz over the live wire surfaces.

Four surfaces, one invariant: a malformed frame is CLASSIFIED (an error
response from a server, a taxonomy SimError from a client), never a
crashed serving loop or an unclassified exception escaping into the
worker. Corpus is seeded (random.Random(SEED)) so failures reproduce.

Frame classes (per surface as applicable): truncated varints, unknown
fields (proto3 must ACCEPT these), oversized / lying-length frames,
wrong-type fields, plain byte garbage.
"""

import http.client
import json
import random
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from jepsen_etcd_tpu.sut.errors import SimError, ERROR_TYPES
from jepsen_etcd_tpu.sut.http_gateway import serve
from jepsen_etcd_tpu.runner.sim import set_current_loop
from jepsen_etcd_tpu.runner.wall import WallLoop

SEED = 0xE7CD


@pytest.fixture()
def wall_loop():
    loop = WallLoop()
    set_current_loop(loop)
    yield loop
    set_current_loop(None)
    loop.shutdown()


@pytest.fixture()
def gateway_port():
    srv, state = serve()
    thread = threading.Thread(target=srv.serve_forever, daemon=True)
    thread.start()
    yield srv.server_address[1]
    srv.shutdown()
    srv.server_close()


def _post_raw(port: int, path: str, body: bytes):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    try:
        conn.request("POST", path, body=body,
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        return resp.status, resp.read()
    finally:
        conn.close()


def _b64key(k: str = "fuzz") -> str:
    import base64
    return base64.b64encode(k.encode()).decode()


def http_corpus(rng: random.Random) -> list[bytes]:
    valid = json.dumps({"key": _b64key(), "limit": 1}).encode()
    frames = []
    # truncated frames: valid JSON cut at random byte offsets
    for _ in range(8):
        frames.append(valid[:rng.randrange(1, len(valid))])
    # plain byte garbage
    for _ in range(8):
        frames.append(bytes(rng.randrange(256)
                            for _ in range(rng.randrange(1, 64))))
    # wrong-type fields: schema-shaped JSON with the wrong leaf types
    frames += [
        json.dumps({"key": 5}).encode(),
        json.dumps({"key": {"nested": 1}}).encode(),
        json.dumps({"key": "!!!not-base64!!!"}).encode(),
        json.dumps({"key": _b64key(), "limit": "many"}).encode(),
        json.dumps({"key": _b64key(), "range_end": 9}).encode(),
        json.dumps([1, 2, 3]).encode(),
        b"null",
    ]
    # oversized frame: a megabyte of zeros where an object belongs
    frames.append(b"0" * (1 << 20))
    return frames


def test_http_gateway_survives_malformed_frames(gateway_port):
    rng = random.Random(SEED)
    paths = ["/v3/kv/range", "/v3/kv/put", "/v3/kv/txn",
             "/v3/lease/grant", "/v3/cluster/member/add",
             "/v3/maintenance/status"]
    for frame in http_corpus(rng):
        path = rng.choice(paths)
        status, body = _post_raw(gateway_port, path, frame)
        # classified: an HTTP status with a JSON error body, never a
        # dropped connection (a handler crash would reset it)
        assert 200 <= status < 600, (path, frame[:40])
        if status >= 400:
            err = json.loads(body)
            assert "code" in err and "message" in err, (path, frame[:40])
    # unknown fields in otherwise-valid requests are accepted
    status, _ = _post_raw(
        gateway_port, "/v3/kv/range",
        json.dumps({"key": _b64key(), "bogus_field": 1,
                    "another": {"deep": True}}).encode())
    assert status == 200
    # the serving loop is still healthy: a well-formed request succeeds
    status, body = _post_raw(gateway_port, "/v3/kv/range",
                             json.dumps({"key": _b64key()}).encode())
    assert status == 200
    assert "header" in json.loads(body)


def test_http_gateway_fuzz_through_net_proxy(gateway_port):
    """The same malformed-frame corpus routed THROUGH the userspace
    proxy plane (net/), on both leg kinds: the client-kind leg must be
    a transparent splice, and the peer-kind leg's attribution sniffer
    must classify-or-pass garbage first bytes — never wedge a
    connection or crash a pump thread."""
    from jepsen_etcd_tpu.net.plane import NetPlane
    plane = NetPlane(seed=SEED)
    ports = [plane.front("n1", "client", gateway_port),
             plane.front("n1", "peer", gateway_port)]
    try:
        rng = random.Random(SEED)
        paths = ["/v3/kv/range", "/v3/kv/put", "/v3/kv/txn",
                 "/v3/lease/grant", "/v3/maintenance/status"]
        for frame in http_corpus(rng):
            for port in ports:
                status, body = _post_raw(port, rng.choice(paths), frame)
                assert 200 <= status < 600, (port, frame[:40])
                if status >= 400:
                    err = json.loads(body)
                    assert "code" in err and "message" in err
        # both proxied legs still healthy for a well-formed request
        for port in ports:
            status, body = _post_raw(
                port, "/v3/kv/range",
                json.dumps({"key": _b64key()}).encode())
            assert status == 200
            assert "header" in json.loads(body)
    finally:
        plane.close()


# ---- native-gRPC gateway ---------------------------------------------------

def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        out.append(b | (0x80 if n else 0))
        if not n:
            return bytes(out)


def grpc_corpus(rng: random.Random, valid: bytes) -> list[bytes]:
    frames = []
    # truncated varints / truncated messages
    for _ in range(6):
        frames.append(valid[:rng.randrange(1, max(2, len(valid)))])
    frames.append(b"\x0a\xff")              # length varint cut short
    frames.append(b"\xff\xff\xff\xff")      # tag garbage
    # lying length prefix: field 1 claims 1 GiB of bytes follow
    frames.append(b"\x0a" + _varint(1 << 30))
    # wrong wire type: field 1 (bytes, wiretype 2) sent as varint
    frames.append(b"\x08\x05")
    # byte garbage
    for _ in range(6):
        frames.append(bytes(rng.randrange(256)
                            for _ in range(rng.randrange(1, 48))))
    return frames


def test_grpc_gateway_survives_malformed_frames():
    grpc = pytest.importorskip("grpc")
    from jepsen_etcd_tpu.sut.grpc_gateway import serve_grpc
    from jepsen_etcd_tpu.client.proto import etcd_rpc_pb2 as pb
    from jepsen_etcd_tpu.client.etcd_grpc import classify_grpc_error

    srv, _state, port = serve_grpc()
    try:
        chan = grpc.insecure_channel(f"127.0.0.1:{port}")
        raw_range = chan.unary_unary(
            "/etcdserverpb.KV/Range",
            request_serializer=lambda b: b,
            response_deserializer=lambda b: b)
        rng = random.Random(SEED)
        valid = pb.RangeRequest(key=b"fuzz", limit=1).SerializeToString()
        for frame in grpc_corpus(rng, valid):
            try:
                raw_range(frame, timeout=10)
            except grpc.RpcError as e:
                # classified into the taxonomy, like any live-client
                # error path would see it
                err = classify_grpc_error(e)
                assert err.type in ERROR_TYPES, frame
        # unknown fields are proto3-legal: parsed, ignored, served
        with_unknown = valid + b"\xf8\x07\x01"  # field 127, varint 1
        resp = pb.RangeResponse.FromString(
            raw_range(with_unknown, timeout=10))
        assert resp.header.revision >= 0
        # serving loop still healthy for a well-formed frame
        resp = pb.RangeResponse.FromString(raw_range(valid, timeout=10))
        assert resp.header.revision >= 0
        chan.close()
    finally:
        srv.stop(0)


# ---- HTTP client against a garbage server ----------------------------------

class _GarbageHandler(BaseHTTPRequestHandler):
    """Replays a scripted wire response per request."""
    script: list = []  # (mode, status, body) tuples, popped per request
    lock = threading.Lock()

    def log_message(self, *a):
        pass

    def do_POST(self):  # noqa: N802
        self.rfile.read(int(self.headers.get("Content-Length", 0)))
        with self.lock:
            mode, status, body = (self.script.pop(0) if self.script
                                  else ("ok", 200, b"{}"))
        if mode == "close":
            # connection dropped before any status line
            self.connection.close()
            return
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


def test_http_client_classifies_garbage_responses(wall_loop):
    from jepsen_etcd_tpu.client.etcd_http import HttpEtcdClient

    rng = random.Random(SEED)
    garbage = [
        ("body", 200, b"this is not json"),
        ("body", 200, b'{"kvs": '),                   # truncated JSON
        ("body", 200, bytes(rng.randrange(256) for _ in range(40))),
        ("body", 500, b"<html>Internal Server Error</html>"),
        ("body", 503, b'{"error": "overloaded", "code": 8, '
                      b'"message": "etcdserver: too many requests"}'),
        ("body", 400, b'{"code": 11, "message": "etcdserver: mvcc: '
                      b'required revision has been compacted"}'),
        ("close", 0, b""),                            # mid-stream EOF
    ]
    srv = ThreadingHTTPServer(("127.0.0.1", 0), _GarbageHandler)
    thread = threading.Thread(target=srv.serve_forever, daemon=True)
    thread.start()
    try:
        for mode, status, body in garbage:
            _GarbageHandler.script = [(mode, status, body)]
            c = HttpEtcdClient(
                f"http://127.0.0.1:{srv.server_address[1]}")
            try:
                with pytest.raises(SimError) as ei:
                    wall_loop.run_coro(c.revision())
                # classified, in-taxonomy — never a raw urllib/json
                # exception escaping into the worker
                assert ei.value.type in ERROR_TYPES, (mode, status, body)
            finally:
                c.close()
        # specific classifications survive the wrapping
        _GarbageHandler.script = [garbage[4]]
        c = HttpEtcdClient(f"http://127.0.0.1:{srv.server_address[1]}")
        with pytest.raises(SimError) as ei:
            wall_loop.run_coro(c.revision())
        assert ei.value.type == "too-many-requests"
        c.close()
        _GarbageHandler.script = [garbage[5]]
        c = HttpEtcdClient(f"http://127.0.0.1:{srv.server_address[1]}")
        with pytest.raises(SimError) as ei:
            wall_loop.run_coro(c.revision())
        assert ei.value.type == "compacted"
        c.close()
    finally:
        srv.shutdown()
        srv.server_close()


# ---- gRPC client against a garbage server ----------------------------------

def test_grpc_client_classifies_garbage_responses(wall_loop):
    grpc = pytest.importorskip("grpc")
    from concurrent import futures
    from jepsen_etcd_tpu.client.etcd_grpc import GrpcEtcdClient

    responses = [b"\xff\xff\xff\xff", b"\x0a" + _varint(1 << 30),
                 b"not a protobuf message at all"]
    state = {"i": 0}

    def handler(request, context):
        r = responses[state["i"] % len(responses)]
        state["i"] += 1
        return r

    method = grpc.unary_unary_rpc_method_handler(
        handler,
        request_deserializer=lambda b: b,
        response_serializer=lambda b: b)
    generic = grpc.method_handlers_generic_handler(
        "etcdserverpb.KV", {"Range": method})
    srv = grpc.server(futures.ThreadPoolExecutor(max_workers=2))
    srv.add_generic_rpc_handlers((generic,))
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    try:
        for _ in responses:
            c = GrpcEtcdClient(f"http://127.0.0.1:{port}")
            try:
                with pytest.raises(SimError) as ei:
                    wall_loop.run_coro(c.get("k"))
                assert ei.value.type in ERROR_TYPES
            finally:
                c.close()
    finally:
        srv.stop(0)
