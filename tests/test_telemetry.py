"""Run telemetry: recorder unit tests, file/summary reconciliation,
end-to-end wiring through a sim run, the forced-kernel counter path,
the --no-telemetry opt-out, and bench-cell schema equality."""

import json
import os

import pytest

from jepsen_etcd_tpu.runner import telemetry
from jepsen_etcd_tpu.runner.telemetry import (
    Telemetry, NullTelemetry, NULL, SPAN_FIELDS, COUNTER_FIELDS,
    EVENT_FIELDS, HIST_FIELDS)


def read_jsonl(path):
    with open(path) as f:
        return [json.loads(line) for line in f]


class FakeClock:
    """Deterministic monotonic clock: +0.25 s per reading."""

    def __init__(self):
        self.t = 0.0

    def __call__(self):
        self.t += 0.25
        return self.t


@pytest.fixture(autouse=True)
def _isolate_current():
    """No test may leak a process-current recorder."""
    yield
    telemetry.set_current(None)


# ---- recorder unit tests ----------------------------------------------------

def test_span_records_reconcile_with_summary(tmp_path):
    path = str(tmp_path / "t.jsonl")
    tel = Telemetry(path, clock=FakeClock())
    with tel.span("phase:check", ops=7):
        with tel.span("wgl.check_packed", w=3) as sp:
            sp.set(engine="jnp-ladder", rungs=2)
        with tel.span("wgl.check_packed"):
            pass
    tel.counter("wgl.dispatches", 2)
    tel.close()

    recs = read_jsonl(path)
    spans = [r for r in recs if r["kind"] == "span"]
    counters = [r for r in recs if r["kind"] == "counter"]
    assert all(tuple(r.keys()) == SPAN_FIELDS for r in spans)
    assert all(tuple(r.keys()) == COUNTER_FIELDS for r in counters)

    s = tel.summary()
    assert s["schema"] == telemetry.SCHEMA_VERSION
    assert s["file"] == "t.jsonl"
    # every summary total is exactly the sum of the file's records
    for name, agg in s["spans"].items():
        mine = [r for r in spans if r["name"] == name]
        assert len(mine) == agg["count"]
        assert sum(r["dur_s"] for r in mine) == \
            pytest.approx(agg["total_s"])
    assert s["spans"]["wgl.check_packed"]["count"] == 2
    assert s["phases"] == {"check": s["spans"]["phase:check"]["total_s"]}
    # attrs set mid-span land in the file record
    attrs = [r["attrs"] for r in spans if r["name"] == "wgl.check_packed"]
    assert {"w": 3, "engine": "jnp-ladder", "rungs": 2} in attrs
    # counters flush as records at close and match the summary
    assert {r["name"]: r["value"] for r in counters} == s["counters"] \
        == {"wgl.dispatches": 2}


def test_counter_sum_and_max_modes():
    tel = Telemetry()
    tel.counter("n")
    tel.counter("n", 4)
    tel.counter("peak", 7, mode="max")
    tel.counter("peak", 3, mode="max")
    tel.counter("peak", 9, mode="max")
    assert tel.summary()["counters"] == {"n": 5, "peak": 9}


def test_null_outside_run():
    assert isinstance(telemetry.current(), NullTelemetry)
    assert telemetry.current() is NULL
    assert NULL.enabled is False
    with NULL.span("x", a=1) as sp:
        sp.set(b=2)  # all no-ops
    NULL.counter("c")
    NULL.event("e")
    assert NULL.summary() == {}


def test_set_current_roundtrip():
    tel = Telemetry()
    telemetry.set_current(tel)
    assert telemetry.current() is tel
    telemetry.set_current(None)
    assert telemetry.current() is NULL


def test_max_records_drops_past_cap(tmp_path):
    path = str(tmp_path / "t.jsonl")
    tel = Telemetry(path, clock=FakeClock(), max_records=3)
    for _ in range(5):
        with tel.span("s"):
            pass
    tel.close()
    s = tel.summary()
    assert s["dropped"] == 2
    # aggregation still sees every span; only the file is capped
    assert s["spans"]["s"]["count"] == 5
    recs = read_jsonl(path)
    assert sum(1 for r in recs if r["kind"] == "span") == 3
    drop = [r for r in recs if r["kind"] == "event"
            and r["name"] == "telemetry.dropped"]
    assert drop and drop[0]["attrs"]["dropped"] == 2
    assert tuple(drop[0].keys()) == EVENT_FIELDS


def test_close_idempotent(tmp_path):
    tel = Telemetry(str(tmp_path / "t.jsonl"))
    with tel.span("s"):
        pass
    tel.close()
    tel.close()  # must not raise or re-flush


# ---- end-to-end: a sim run writes and reconciles telemetry ------------------

def run(tmp_path, **opts):
    from jepsen_etcd_tpu.compose import etcd_test
    from jepsen_etcd_tpu.runner.test_runner import run_test
    base = {"time_limit": 4, "rate": 50, "ops_per_key": 30,
            "store_base": str(tmp_path), "seed": 11}
    base.update(opts)
    return run_test(etcd_test(base))


def test_run_writes_telemetry_and_reconciles(tmp_path):
    out = run(tmp_path, workload="register")
    assert out["valid?"] is True
    path = os.path.join(out["dir"], "telemetry.jsonl")
    assert os.path.exists(path)

    tel = out["results"]["telemetry"]
    # ...and the summary persists into results.json on disk
    with open(os.path.join(out["dir"], "results.json")) as f:
        assert json.load(f)["telemetry"] == tel

    # run phases (save closes after the summary snapshot, so it lives
    # in the file only)
    assert {"setup", "generate", "teardown", "check"} <= \
        set(tel["phases"])
    assert tel["phases"]["check"] > 0
    # each composed checker contributed a span
    assert {"perf", "stats", "workload", "crash"} <= set(tel["checkers"])

    # the file's span records sum to exactly the summary totals
    recs = read_jsonl(path)
    for r in recs:
        want = {"span": SPAN_FIELDS, "counter": COUNTER_FIELDS,
                "event": EVENT_FIELDS, "hist": HIST_FIELDS}[r["kind"]]
        assert tuple(r.keys()) == want
    # perf's op-latency distributions flush as hist records at close
    assert any(r["kind"] == "hist" and r["name"].startswith("op.latency.")
               for r in recs)
    by_name = {}
    for r in recs:
        if r["kind"] == "span":
            agg = by_name.setdefault(r["name"], [0, 0.0])
            agg[0] += 1
            agg[1] += r["dur_s"]
    for name, v in tel["spans"].items():
        assert by_name[name][0] == v["count"], name
        assert by_name[name][1] == pytest.approx(v["total_s"]), name
    assert "phase:save" in by_name  # file-only, see above

    # register's small per-key subhistories route to the CPU oracle
    assert tel["counters"].get("engine.cpu-oracle", 0) >= 1
    file_counters = {r["name"]: r["value"] for r in recs
                     if r["kind"] == "counter"}
    # the save phase runs AFTER the summary snapshot (like phase:save
    # above): its run-index write counters live in the file only
    assert set(file_counters) - set(tel["counters"]) <= \
        {"store.index_rows", "store.index_writes"}
    for name, v in tel["counters"].items():
        assert file_counters[name] == v, name

    # the recorder is uninstalled after the run
    assert telemetry.current() is NULL


def test_no_telemetry_opt_out(tmp_path):
    out = run(tmp_path, workload="register", no_telemetry=True)
    assert out["valid?"] is True
    assert not os.path.exists(os.path.join(out["dir"], "telemetry.jsonl"))
    assert "telemetry" not in out["results"]
    with open(os.path.join(out["dir"], "results.json")) as f:
        assert "telemetry" not in json.load(f)


# ---- forced kernel path: TPU counters under JAX_PLATFORMS=cpu ---------------

def test_forced_kernel_emits_tpu_counters(tmp_path):
    """cpu_cutoff=None pins the wave-kernel path (the jnp ladder on
    this CPU host), which must emit the ISSUE's TPU-path telemetry:
    engine counter, dispatch count, rung count, max frontier width,
    pack + dispatch spans with wall times."""
    from jepsen_etcd_tpu.checkers.tpu_linearizable import \
        TPULinearizableChecker
    from jepsen_etcd_tpu.core.history import History
    from jepsen_etcd_tpu.core.op import Op
    from jepsen_etcd_tpu.models import VersionedRegister

    ops, t = [], 0
    for i in range(20):
        ops.append(Op({"type": "invoke", "process": 0, "f": "write",
                       "value": [None, i], "time": t}))
        ops.append(Op({"type": "ok", "process": 0, "f": "write",
                       "value": [i + 1, i], "time": t + 1}))
        t += 2
    h = History(ops)

    tel = Telemetry(str(tmp_path / "t.jsonl"))
    telemetry.set_current(tel)
    try:
        checker = TPULinearizableChecker(
            lambda: VersionedRegister(0, None), cpu_cutoff=None)
        res = checker.check({}, h)
    finally:
        telemetry.set_current(None)
        tel.close()

    assert res["valid?"] is True
    s = tel.summary()
    assert s["counters"].get("engine.jnp-ladder") == 1
    assert s["counters"].get("wgl.dispatches") == 1
    assert s["counters"].get("wgl.rungs", 0) >= 1
    assert s["counters"].get("wgl.max-frontier", 0) >= 1
    assert s["spans"]["wgl.pack"]["count"] == 1
    assert s["spans"]["wgl.check_packed"]["count"] == 1
    assert s["spans"]["wgl.check_packed"]["total_s"] > 0
    # the dispatch span carries the engine + rung attrs in the file
    recs = read_jsonl(str(tmp_path / "t.jsonl"))
    disp = [r for r in recs if r["kind"] == "span"
            and r["name"] == "wgl.check_packed"]
    assert disp[0]["attrs"]["engine"] == "jnp-ladder"
    assert disp[0]["attrs"]["valid"] is True


# ---- bench cells share the run span schema ----------------------------------

def test_bench_cell_schema_equals_run_schema(tmp_path):
    import bench

    # a bench cell span, recorded exactly as bench.py main() does
    bench_path = str(tmp_path / "bench.jsonl")
    tel = Telemetry(bench_path, clock=FakeClock())
    out = bench._run_cell(tel, "demo", lambda: {"ok": True, "n": 3,
                                                "skip": [1, 2]})
    tel.close()
    assert out["ok"] is True
    cell = read_jsonl(bench_path)[0]
    assert cell["kind"] == "span" and cell["name"] == "cell:demo"
    # scalar result fields become span attrs; non-scalars are dropped
    assert cell["attrs"] == {"ok": True, "n": 3}

    # a run-style span from the same recorder class
    run_path = str(tmp_path / "run.jsonl")
    tel2 = Telemetry(run_path, clock=FakeClock())
    with tel2.span("phase:check", ops=1):
        pass
    tel2.close()
    run_rec = read_jsonl(run_path)[0]

    # schema equality: identical field sets, identical order, both
    # matching the pinned schema
    assert tuple(cell.keys()) == tuple(run_rec.keys()) == SPAN_FIELDS
    assert bench._bench_telemetry is not None  # bench wires a recorder
