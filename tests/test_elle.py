"""Elle checker tests: known-good and known-bad txn histories (the golden
fixtures SURVEY §4 calls for), plus closure-kernel equivalence."""

import numpy as np
import pytest

from jepsen_etcd_tpu.core.op import Op
from jepsen_etcd_tpu.core.history import History
from jepsen_etcd_tpu.checkers.elle.append import ListAppendChecker
from jepsen_etcd_tpu.checkers.elle.wr import RWRegisterChecker
from jepsen_etcd_tpu.ops.closure import closure_batch, _closure_numpy


def H(*ops):
    return History([Op(o) for o in ops])


def inv(p, txn):
    return {"type": "invoke", "process": p, "f": "txn", "value": txn}


def ok(p, txn):
    return {"type": "ok", "process": p, "f": "txn", "value": txn}


def fail(p, txn):
    return {"type": "fail", "process": p, "f": "txn", "value": txn,
            "error": "didnt-succeed"}


def info(p, txn):
    return {"type": "info", "process": p, "f": "txn", "value": txn}


def check_append(h, models=("strict-serializable",)):
    return ListAppendChecker(consistency_models=models).check({}, h)


def check_wr(h, models=("strict-serializable",)):
    return RWRegisterChecker(consistency_models=models).check({}, h)


# ---- list-append ----------------------------------------------------------

def test_append_sequential_valid():
    h = H(inv(0, [["append", "x", 1]]), ok(0, [["append", "x", 1]]),
          inv(0, [["r", "x", None]]), ok(0, [["r", "x", [1]]]),
          inv(1, [["append", "x", 2]]), ok(1, [["append", "x", 2]]),
          inv(1, [["r", "x", None]]), ok(1, [["r", "x", [1, 2]]]))
    r = check_append(h)
    assert r["valid?"] is True, r
    assert r["anomaly-types"] == []


def test_append_g1c_circular_information_flow():
    # T1 and T2 each read the other's append: wr cycle
    h = H(inv(0, [["append", "x", 1], ["r", "y", None]]),
          inv(1, [["append", "y", 1], ["r", "x", None]]),
          ok(0, [["append", "x", 1], ["r", "y", [1]]]),
          ok(1, [["append", "y", 1], ["r", "x", [1]]]))
    r = check_append(h)
    assert r["valid?"] is False
    assert "G1c" in r["anomaly-types"], r["anomaly-types"]
    cyc = r["anomalies"]["G1c"][0]
    assert {s["type"] for s in cyc["steps"]} == {"wr"}


def test_append_g_single_read_skew():
    # T2 appends x and y; T1 sees y's new state but x's old state
    h = H(inv(0, [["r", "x", None], ["r", "y", None]]),
          inv(1, [["append", "x", 1], ["append", "y", 1]]),
          ok(1, [["append", "x", 1], ["append", "y", 1]]),
          ok(0, [["r", "x", []], ["r", "y", [1]]]),
          inv(2, [["r", "x", None]]), ok(2, [["r", "x", [1]]]))
    r = check_append(h)
    assert r["valid?"] is False
    assert "G-single" in r["anomaly-types"], r["anomaly-types"]


def test_append_g0_write_cycle():
    # interleaved append order between two keys; order fixed by reader
    h = H(inv(0, [["append", "x", 1], ["append", "y", 2]]),
          inv(1, [["append", "x", 2], ["append", "y", 1]]),
          ok(0, [["append", "x", 1], ["append", "y", 2]]),
          ok(1, [["append", "x", 2], ["append", "y", 1]]),
          inv(2, [["r", "x", None], ["r", "y", None]]),
          ok(2, [["r", "x", [1, 2]], ["r", "y", [1, 2]]]))
    r = check_append(h)
    assert r["valid?"] is False
    assert "G0" in r["anomaly-types"], r["anomaly-types"]


def test_append_stale_read_realtime_only():
    # T2 invokes after T1 completed but misses T1's committed append:
    # fine under serializable, a cycle only with realtime edges.
    h = H(inv(0, [["append", "x", 1]]), ok(0, [["append", "x", 1]]),
          inv(1, [["r", "x", None]]), ok(1, [["r", "x", []]]),
          inv(2, [["r", "x", None]]), ok(2, [["r", "x", [1]]]))
    r = check_append(h)
    assert r["valid?"] is False
    assert "G-single-realtime" in r["anomaly-types"], r["anomaly-types"]
    # ...and valid under plain serializability
    r2 = check_append(h, models=("serializable",))
    assert r2["valid?"] is True, r2


def test_append_g1a_aborted_read():
    h = H(inv(0, [["append", "x", 1]]), fail(0, [["append", "x", 1]]),
          inv(1, [["r", "x", None]]), ok(1, [["r", "x", [1]]]))
    r = check_append(h)
    assert "G1a" in r["anomaly-types"]


def test_append_g1b_intermediate_read():
    h = H(inv(0, [["append", "x", 1], ["append", "x", 2]]),
          ok(0, [["append", "x", 1], ["append", "x", 2]]),
          inv(1, [["r", "x", None]]), ok(1, [["r", "x", [1]]]))
    r = check_append(h)
    assert "G1b" in r["anomaly-types"]


def test_append_internal():
    # a read must reflect the txn's own earlier appends
    h = H(inv(0, [["append", "x", 1], ["r", "x", None]]),
          ok(0, [["append", "x", 1], ["r", "x", []]]))
    r = check_append(h)
    assert "internal" in r["anomaly-types"]


def test_append_own_reads_ok():
    h = H(inv(0, [["append", "x", 1], ["r", "x", None]]),
          ok(0, [["append", "x", 1], ["r", "x", [1]]]))
    assert check_append(h)["valid?"] is True


def test_append_incompatible_order():
    h = H(inv(0, [["r", "x", None]]), ok(0, [["r", "x", [1, 2]]]),
          inv(1, [["r", "x", None]]), ok(1, [["r", "x", [2, 1]]]))
    r = check_append(h)
    assert "incompatible-order" in r["anomaly-types"]


def test_append_duplicate_elements():
    h = H(inv(0, [["r", "x", None]]), ok(0, [["r", "x", [1, 1]]]))
    r = check_append(h)
    assert "duplicate-elements" in r["anomaly-types"]


def test_append_info_txn_observed_is_committed():
    # an indeterminate append later observed joins the graph
    h = H(inv(0, [["append", "x", 1]]), info(0, [["append", "x", 1]]),
          inv(1, [["r", "x", None]]), ok(1, [["r", "x", [1]]]))
    r = check_append(h)
    assert r["valid?"] is True
    assert r["committed-count"] == 2


def test_append_coexisting_g0_and_g1c_both_reported():
    # a ww cycle on keys x/y (txns 0,1) AND a separate wr cycle on keys
    # p/q (txns 3,4): both anomaly types must surface, correctly labeled
    h = H(inv(0, [["append", "x", 1], ["append", "y", 2]]),
          inv(1, [["append", "x", 2], ["append", "y", 1]]),
          ok(0, [["append", "x", 1], ["append", "y", 2]]),
          ok(1, [["append", "x", 2], ["append", "y", 1]]),
          inv(2, [["r", "x", None], ["r", "y", None]]),
          ok(2, [["r", "x", [1, 2]], ["r", "y", [1, 2]]]),
          inv(3, [["append", "p", 1], ["r", "q", None]]),
          inv(4, [["append", "q", 1], ["r", "p", None]]),
          ok(3, [["append", "p", 1], ["r", "q", [1]]]),
          ok(4, [["append", "q", 1], ["r", "p", [1]]]))
    r = check_append(h)
    assert "G0" in r["anomaly-types"], r["anomaly-types"]
    assert "G1c" in r["anomaly-types"], r["anomaly-types"]
    # the G1c certificate must actually contain a wr edge
    g1c = r["anomalies"]["G1c"][0]
    assert any(s["type"] == "wr" for s in g1c["steps"])


# ---- rw-register ----------------------------------------------------------

def test_wr_sequential_valid():
    h = H(inv(0, [["w", "x", 1]]), ok(0, [["w", "x", 1]]),
          inv(1, [["r", "x", None]]), ok(1, [["r", "x", 1]]))
    r = check_wr(h)
    assert r["valid?"] is True, r


def test_wr_internal():
    h = H(inv(0, [["w", "x", 1], ["r", "x", None]]),
          ok(0, [["w", "x", 1], ["r", "x", 2]]))
    r = check_wr(h)
    assert "internal" in r["anomaly-types"]


def test_wr_g1a():
    h = H(inv(0, [["w", "x", 1]]), fail(0, [["w", "x", 1]]),
          inv(1, [["r", "x", None]]), ok(1, [["r", "x", 1]]))
    r = check_wr(h)
    assert "G1a" in r["anomaly-types"]


def test_wr_g1c():
    h = H(inv(0, [["w", "x", 1], ["r", "y", None]]),
          inv(1, [["w", "y", 2], ["r", "x", None]]),
          ok(0, [["w", "x", 1], ["r", "y", 2]]),
          ok(1, [["w", "y", 2], ["r", "x", 1]]))
    r = check_wr(h)
    assert "G1c" in r["anomaly-types"], r["anomaly-types"]


def test_wr_stale_read_realtime():
    # committed write, then a later txn still reads nil
    h = H(inv(0, [["w", "x", 1]]), ok(0, [["w", "x", 1]]),
          inv(1, [["r", "x", None]]), ok(1, [["r", "x", None]]))
    r = check_wr(h)
    assert r["valid?"] is False
    assert "G-single-realtime" in r["anomaly-types"], r["anomaly-types"]
    assert check_wr(h, models=("serializable",))["valid?"] is True


def test_wr_cyclic_version_order():
    h = H(inv(0, [["r", "x", None], ["w", "x", 2]]),
          ok(0, [["r", "x", 1], ["w", "x", 2]]),
          inv(1, [["r", "x", None], ["w", "x", 1]]),
          ok(1, [["r", "x", 2], ["w", "x", 1]]))
    r = check_wr(h)
    assert "cyclic-version-order" in r["anomaly-types"]


def test_wr_wfr_inference():
    # wfr: T0 reads x=1 then writes x=2 => 1 << 2; T1 read x=2 then
    # x=1 again would be a non-repeatable read inside one txn
    h = H(inv(0, [["w", "x", 1]]), ok(0, [["w", "x", 1]]),
          inv(1, [["r", "x", None], ["w", "x", 2]]),
          ok(1, [["r", "x", 1], ["w", "x", 2]]),
          inv(2, [["r", "x", None], ["r", "x", None]]),
          ok(2, [["r", "x", 2], ["r", "x", 1]]))
    r = check_wr(h)
    assert "internal" in r["anomaly-types"]


# ---- closure kernel -------------------------------------------------------

@pytest.mark.parametrize("n", [5, 40, 300])
def test_closure_matches_numpy(n):
    rng = np.random.default_rng(n)
    a = rng.random((3, n, n)) < (2.0 / n)
    ref_reach, ref_cyc = _closure_numpy(a)
    reach, cyc = closure_batch(a, force_device=True)
    assert np.array_equal(reach, ref_reach)
    assert np.array_equal(cyc, ref_cyc)


def test_closure_numpy_no_overflow_at_256_paths():
    # 0 -> {1..256} -> 257: exactly 256 distinct paths; a uint8
    # accumulator would wrap to 0 and lose the reachability
    n = 258
    a = np.zeros((1, n, n), bool)
    a[0, 0, 1:257] = True
    a[0, 1:257, 257] = True
    reach, _ = _closure_numpy(a)
    assert reach[0, 0, 257]


def test_closure_empty():
    reach, cyc = closure_batch(np.zeros((2, 0, 0), bool))
    assert reach.shape == (2, 0, 0)


def test_closure_simple_cycle():
    a = np.zeros((1, 4, 4), bool)
    a[0, 0, 1] = a[0, 1, 2] = a[0, 2, 0] = True  # 0->1->2->0; 3 isolated
    reach, cyc = closure_batch(a)
    assert cyc[0].tolist() == [True, True, True, False]
    assert reach[0, 0, 2] and reach[0, 2, 1] and not reach[0, 3, 0]


# ---- end-to-end against the simulated cluster -----------------------------

def run(tmp_path, **opts):
    from jepsen_etcd_tpu.compose import etcd_test
    from jepsen_etcd_tpu.runner.test_runner import run_test
    base = {"time_limit": 6, "rate": 50, "store_base": str(tmp_path),
            "seed": 11}
    base.update(opts)
    return run_test(etcd_test(base))


def test_wr_workload_e2e(tmp_path):
    out = run(tmp_path, workload="wr")
    assert out["valid?"] is True, out["results"]["workload"]["anomaly-types"]
    assert out["results"]["workload"]["txn-count"] > 50


def test_append_workload_e2e(tmp_path):
    out = run(tmp_path, workload="append")
    assert out["valid?"] is True, out["results"]["workload"]["anomaly-types"]
    assert out["results"]["workload"]["txn-count"] > 50


def test_gsingle_and_g2item_both_reported():
    """A history with a G-single cycle AND an independent G2-item cycle
    reports both (find_cycles must not short-circuit after G-single)."""
    from jepsen_etcd_tpu.checkers.elle.graph import DepGraph

    g = DepGraph(4)
    # G-single: 0 -rw-> 1 -wr-> 0  (exactly one rw)
    g.add("rw", 0, 1)
    g.add("wr", 1, 0)
    # independent G2-item: 2 -rw-> 3 -rw-> 2  (two rw)
    g.add("rw", 2, 3)
    g.add("rw", 3, 2)
    recs = g.find_cycles(realtime=False)
    types = {r["type"] for r in recs}
    assert "G-single" in types
    assert "G2-item" in types
    # and the G2-item certificate is the 2<->3 cycle, not a relabel of
    # the G-single one
    g2 = next(r for r in recs if r["type"] == "G2-item")
    assert set(g2["cycle"]) == {2, 3}


def test_sharded_closure_matches_numpy():
    """Row-sharded mesh closure (>1 device, N >= SHARD_CUTOFF) agrees
    with the numpy oracle (VERDICT r1 item 9)."""
    import jax
    from jepsen_etcd_tpu.ops import closure as cl
    assert len(jax.devices()) > 1, "conftest should provide 8 CPU devices"
    rng = np.random.default_rng(17)
    n = cl.SHARD_CUTOFF
    # sparse random digraph + a planted long cycle
    a = rng.random((2, n, n)) < (2.0 / n)
    ring = np.arange(n)
    a[1, ring, (ring + 1) % n] = True
    reach, cyc = closure_batch(a, force_device=True)
    reach_np, cyc_np = _closure_numpy(a)
    assert (reach == reach_np).all()
    assert (cyc == cyc_np).all()
    assert cyc[1].all()  # the planted ring puts every node on a cycle


def test_append_two_independent_g1c_cycles_both_listed():
    """Certificate completeness (Elle enumerates EVERY cycle found): two
    disjoint wr cycles — T0/T1 on x/y and T2/T3 on p/q — must both
    appear under G1c, not just the first."""
    h = H(inv(0, [["append", "x", 1], ["r", "y", None]]),
          inv(1, [["append", "y", 1], ["r", "x", None]]),
          ok(0, [["append", "x", 1], ["r", "y", [1]]]),
          ok(1, [["append", "y", 1], ["r", "x", [1]]]),
          inv(2, [["append", "p", 1], ["r", "q", None]]),
          inv(3, [["append", "q", 1], ["r", "p", None]]),
          ok(2, [["append", "p", 1], ["r", "q", [1]]]),
          ok(3, [["append", "q", 1], ["r", "p", [1]]]))
    r = check_append(h)
    assert r["valid?"] is False
    certs = r["anomalies"]["G1c"]
    assert len(certs) == 2, certs
    node_sets = {frozenset(c["cycle"]) for c in certs}
    assert len(node_sets) == 2, "the two cycles must be distinct"
    for c in certs:
        assert {s["type"] for s in c["steps"]} == {"wr"}


def test_append_same_cycle_not_duplicated():
    """One cycle reachable from two anchors (both wr edges of the same
    2-cycle) must yield exactly one certificate."""
    h = H(inv(0, [["append", "x", 1], ["r", "y", None]]),
          inv(1, [["append", "y", 1], ["r", "x", None]]),
          ok(0, [["append", "x", 1], ["r", "y", [1]]]),
          ok(1, [["append", "y", 1], ["r", "x", [1]]]))
    r = check_append(h)
    assert r["valid?"] is False
    assert len(r["anomalies"]["G1c"]) == 1, r["anomalies"]["G1c"]
