"""Nemesis suite tests: every fault package runs end-to-end, safety
holds for linearizable workloads under faults, and the lock workloads
demonstrably break under pause faults (the reference's raison d'être:
etcd locks are unsafe, lock.clj)."""

import pytest

from jepsen_etcd_tpu.compose import etcd_test
from jepsen_etcd_tpu.runner.test_runner import run_test
from jepsen_etcd_tpu.cli import parse_nemesis_spec


def run(tmp_path, **opts):
    base = {"time_limit": 20, "rate": 25, "ops_per_key": 50,
            "store_base": str(tmp_path), "seed": 3,
            "nemesis_interval": 3}
    base.update(opts)
    return run_test(etcd_test(base))


def test_parse_nemesis_spec():
    assert parse_nemesis_spec("kill,pause") == ["kill", "pause"]
    assert parse_nemesis_spec("none") == []
    assert "bitflip-wal" in parse_nemesis_spec("corrupt")
    assert "member" in parse_nemesis_spec("all")


def nemesis_fs(history):
    return {op.f for op in history if op.get("process") == "nemesis"
            and op.get("type") == "info"}


def test_register_under_kill(tmp_path):
    out = run(tmp_path, workload="register", nemesis=["kill"])
    assert out["results"]["workload"]["valid?"] is True, \
        "kill faults must not break linearizability"
    assert {"kill", "start"} & nemesis_fs(out["history"])
    # per-key histories here are small, so the size cutoff routes them
    # to the native DFS; either engine is a sound verdict (the kernel's
    # info-op support is pinned separately in test_wgl with
    # fallback=False, which disables the cutoff)
    per_key = out["results"]["workload"]["results"]
    checkers = [r["linear"].get("checker") for r in per_key.values()]
    assert checkers and all(c in ("tpu-wgl", "cpu-oracle")
                            for c in checkers), checkers
    assert any(r["linear"].get("info-ops", 0) > 0
               for r in per_key.values()), \
        "kill run should produce at least one indefinite op"


def test_register_under_partition(tmp_path):
    out = run(tmp_path, workload="register", nemesis=["partition"])
    assert out["results"]["workload"]["valid?"] is True, \
        "partitions must not break linearizability"
    assert "start-partition" in nemesis_fs(out["history"])


def test_register_under_latency(tmp_path):
    """Injected link latency slows the sim's message legs but must
    never break linearizability — and the extra delay draws rng ONLY
    while the fault is active (fault-free histories stay
    bit-identical; test_sim pins that)."""
    out = run(tmp_path, workload="register", nemesis=["latency"])
    assert out["results"]["workload"]["valid?"] is True, \
        "latency must not break linearizability"
    assert "start-latency" in nemesis_fs(out["history"])


def test_sim_directed_partition_blocks_one_direction():
    """Ordered (src, dst) pairs block exactly one direction in the sim;
    frozensets block both (the shared encoding with net/plane.py)."""
    from jepsen_etcd_tpu.runner.sim import (SimLoop, set_current_loop,
                                            sleep, SECOND)
    from jepsen_etcd_tpu.sut.cluster import Cluster, ClusterConfig
    loop = SimLoop(seed=1)
    set_current_loop(loop)
    try:
        cluster = Cluster(loop, ["n1", "n2", "n3"], ClusterConfig())
        cluster.launch()  # reachable() is False for unlaunched nodes
        loop.run_coro(sleep(SECOND // 1000))  # start launch coroutines
        cluster.partition_pairs({("n1", "n2")})
        assert cluster.reachable("n1", "n2") is False
        assert cluster.reachable("n2", "n1") is True
        assert cluster.reachable("n1", "n3") is True
        cluster.partition_pairs({frozenset(("n1", "n2"))})
        assert cluster.reachable("n1", "n2") is False
        assert cluster.reachable("n2", "n1") is False
        cluster.heal_partition()
        assert cluster.reachable("n1", "n2") is True
        # latency knob: extra delay only while the fault is active
        base = (10, 20)
        cluster.set_latency(50, jitter_ms=10)
        assert cluster.net_latency is not None
        # 50 ms of injected delay dominates the 10-20 tick base range
        assert cluster.msg_delay(base) > base[1]
        cluster.clear_latency()
        assert cluster.net_latency is None
        assert base[0] <= cluster.msg_delay(base) <= base[1]
        cluster.shutdown()
    finally:
        set_current_loop(None)


def test_partition_spec_shapes():
    """The new partition specs produce the documented shapes: one-way
    is a single source's outbound tuples, bridge splits the non-bridge
    rest into two halves blocked pairwise."""
    from jepsen_etcd_tpu.runner.sim import SimLoop, set_current_loop
    from jepsen_etcd_tpu.sut.cluster import Cluster, ClusterConfig
    from jepsen_etcd_tpu.nemesis.faults import _partition_groups
    loop = SimLoop(seed=2)
    set_current_loop(loop)
    try:
        nodes = ["n1", "n2", "n3", "n4", "n5"]
        cluster = Cluster(loop, nodes, ClusterConfig())
        test = {"cluster": cluster}
        ow = _partition_groups(test, "one-way", [])
        assert isinstance(ow, set) and len(ow) == 4
        srcs = {p[0] for p in ow}
        assert len(srcs) == 1
        assert all(isinstance(p, tuple) and not isinstance(p, frozenset)
                   for p in ow)
        br = _partition_groups(test, "bridge", [])
        assert isinstance(br, set) and br
        assert all(isinstance(p, frozenset) for p in br)
        # 5 nodes: bridge + halves of 2 -> 2x2 blocked cross pairs,
        # and the bridge node appears in none of them
        assert len(br) == 4
        blocked_nodes = set().union(*br)
        assert len(blocked_nodes) == 4
        bridge = (set(nodes) - blocked_nodes).pop()
        assert all(bridge not in pair for pair in br)
    finally:
        set_current_loop(None)


def test_register_under_pause_clock(tmp_path):
    # longer window: enough nemesis cycles that both fault classes fire
    # regardless of where the seed lands the pause/clock mix
    out = run(tmp_path, workload="register", nemesis=["pause", "clock"],
              time_limit=40)
    assert out["results"]["workload"]["valid?"] is True
    # pause log markers must not trip the crash-pattern checker
    # (SIG[A-Z]+ false positive found by the test-all sweep)
    assert out["results"]["crash"]["valid?"] is True, \
        out["results"]["crash"]["matches"][:3]
    assert out["valid?"] is True
    fs = nemesis_fs(out["history"])
    assert "pause" in fs
    assert fs & {"bump-clock", "strobe-clock", "reset-clock"}


def test_register_under_member(tmp_path):
    out = run(tmp_path, workload="register", nemesis=["member"],
              time_limit=25)
    assert out["results"]["workload"]["valid?"] is True
    assert {"grow", "shrink"} & nemesis_fs(out["history"])
    # the healing phase grew the cluster back to full strength
    test = out["results"]
    db_members = out["history"]  # via run's test map
    # (membership is checked through the cluster state below)


def test_member_heals_to_full(tmp_path):
    test = etcd_test({"workload": "register", "nemesis": ["member"],
                      "time_limit": 25, "rate": 25, "ops_per_key": 50,
                      "store_base": str(tmp_path), "seed": 5,
                      "nemesis_interval": 3})
    out = run_test(test)
    assert len(test["db"].members) >= len(test["nodes"])


def test_set_under_admin_compact(tmp_path):
    out = run(tmp_path, workload="set", nemesis=["admin"])
    assert out["results"]["workload"]["valid?"] is True
    assert {"compact", "defrag"} & nemesis_fs(out["history"])


def test_append_under_kill_bitflip(tmp_path):
    out = run(tmp_path, workload="append",
              nemesis=["kill", "bitflip-wal", "bitflip-snap"],
              time_limit=25)
    wl = out["results"]["workload"]
    assert wl["valid?"] is True, wl.get("anomaly-types")


def test_watch_under_kill(tmp_path):
    out = run(tmp_path, workload="watch", nemesis=["kill"])
    wl = out["results"]["workload"]
    # kills can prevent convergence (unknown) but must never produce
    # divergent ordered logs or nonmonotonic revisions
    assert wl["valid?"] in (True, "unknown"), wl


def test_lock_set_breaks_under_clock_faults(tmp_path):
    # The headline demonstration (lock.clj): skewing the leader's clock
    # expires the holder's lease mid-critical-section; a second holder
    # acquires; read-modify-write interleaves; adds are lost.
    failures = 0
    for seed in range(2):
        out = run(tmp_path, workload="lock-set", nemesis=["clock"],
                  time_limit=60, rate=10, seed=seed,
                  nemesis_interval=2)
        wl = out["results"]["workload"]["set"]
        if wl["valid?"] is not True and wl.get("lost"):
            failures += 1
    assert failures > 0, \
        "etcd locks should demonstrably fail under clock faults"
