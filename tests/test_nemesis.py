"""Nemesis suite tests: every fault package runs end-to-end, safety
holds for linearizable workloads under faults, and the lock workloads
demonstrably break under pause faults (the reference's raison d'être:
etcd locks are unsafe, lock.clj)."""

import pytest

from jepsen_etcd_tpu.compose import etcd_test
from jepsen_etcd_tpu.runner.test_runner import run_test
from jepsen_etcd_tpu.cli import parse_nemesis_spec


def run(tmp_path, **opts):
    base = {"time_limit": 20, "rate": 25, "ops_per_key": 50,
            "store_base": str(tmp_path), "seed": 3,
            "nemesis_interval": 3}
    base.update(opts)
    return run_test(etcd_test(base))


def test_parse_nemesis_spec():
    assert parse_nemesis_spec("kill,pause") == ["kill", "pause"]
    assert parse_nemesis_spec("none") == []
    assert "bitflip-wal" in parse_nemesis_spec("corrupt")
    assert "member" in parse_nemesis_spec("all")


def nemesis_fs(history):
    return {op.f for op in history if op.get("process") == "nemesis"
            and op.get("type") == "info"}


def test_register_under_kill(tmp_path):
    out = run(tmp_path, workload="register", nemesis=["kill"])
    assert out["results"]["workload"]["valid?"] is True, \
        "kill faults must not break linearizability"
    assert {"kill", "start"} & nemesis_fs(out["history"])
    # per-key histories here are small, so the size cutoff routes them
    # to the native DFS; either engine is a sound verdict (the kernel's
    # info-op support is pinned separately in test_wgl with
    # fallback=False, which disables the cutoff)
    per_key = out["results"]["workload"]["results"]
    checkers = [r["linear"].get("checker") for r in per_key.values()]
    assert checkers and all(c in ("tpu-wgl", "cpu-oracle")
                            for c in checkers), checkers
    assert any(r["linear"].get("info-ops", 0) > 0
               for r in per_key.values()), \
        "kill run should produce at least one indefinite op"


def test_register_under_partition(tmp_path):
    out = run(tmp_path, workload="register", nemesis=["partition"])
    assert out["results"]["workload"]["valid?"] is True, \
        "partitions must not break linearizability"
    assert "start-partition" in nemesis_fs(out["history"])


def test_register_under_pause_clock(tmp_path):
    # longer window: enough nemesis cycles that both fault classes fire
    # regardless of where the seed lands the pause/clock mix
    out = run(tmp_path, workload="register", nemesis=["pause", "clock"],
              time_limit=40)
    assert out["results"]["workload"]["valid?"] is True
    # pause log markers must not trip the crash-pattern checker
    # (SIG[A-Z]+ false positive found by the test-all sweep)
    assert out["results"]["crash"]["valid?"] is True, \
        out["results"]["crash"]["matches"][:3]
    assert out["valid?"] is True
    fs = nemesis_fs(out["history"])
    assert "pause" in fs
    assert fs & {"bump-clock", "strobe-clock", "reset-clock"}


def test_register_under_member(tmp_path):
    out = run(tmp_path, workload="register", nemesis=["member"],
              time_limit=25)
    assert out["results"]["workload"]["valid?"] is True
    assert {"grow", "shrink"} & nemesis_fs(out["history"])
    # the healing phase grew the cluster back to full strength
    test = out["results"]
    db_members = out["history"]  # via run's test map
    # (membership is checked through the cluster state below)


def test_member_heals_to_full(tmp_path):
    test = etcd_test({"workload": "register", "nemesis": ["member"],
                      "time_limit": 25, "rate": 25, "ops_per_key": 50,
                      "store_base": str(tmp_path), "seed": 5,
                      "nemesis_interval": 3})
    out = run_test(test)
    assert len(test["db"].members) >= len(test["nodes"])


def test_set_under_admin_compact(tmp_path):
    out = run(tmp_path, workload="set", nemesis=["admin"])
    assert out["results"]["workload"]["valid?"] is True
    assert {"compact", "defrag"} & nemesis_fs(out["history"])


def test_append_under_kill_bitflip(tmp_path):
    out = run(tmp_path, workload="append",
              nemesis=["kill", "bitflip-wal", "bitflip-snap"],
              time_limit=25)
    wl = out["results"]["workload"]
    assert wl["valid?"] is True, wl.get("anomaly-types")


def test_watch_under_kill(tmp_path):
    out = run(tmp_path, workload="watch", nemesis=["kill"])
    wl = out["results"]["workload"]
    # kills can prevent convergence (unknown) but must never produce
    # divergent ordered logs or nonmonotonic revisions
    assert wl["valid?"] in (True, "unknown"), wl


def test_lock_set_breaks_under_clock_faults(tmp_path):
    # The headline demonstration (lock.clj): skewing the leader's clock
    # expires the holder's lease mid-critical-section; a second holder
    # acquires; read-modify-write interleaves; adds are lost.
    failures = 0
    for seed in range(2):
        out = run(tmp_path, workload="lock-set", nemesis=["clock"],
                  time_limit=60, rate=10, seed=seed,
                  nemesis_interval=2)
        wl = out["results"]["workload"]["set"]
        if wl["valid?"] is not True and wl.get("lost"):
            failures += 1
    assert failures > 0, \
        "etcd locks should demonstrably fail under clock faults"
